"""Axon-relay health probe, shared by ``bench.py`` and
``__graft_entry__.py``.

This box attaches its single TPU through the axon loopback relay
(``PALLAS_AXON_POOL_IPS``). A dead relay refuses TCP; a *wedged* relay
accepts TCP but hangs the first backend-initialising jax call forever.
Hence two stages: a 1s port scan over the relay's fixed port list, then
a throwaway subprocess that must enumerate devices within a timeout
(``DEAP_TPU_SKIP_PROBE=1`` trusts the port scan and skips the slow
stage). Deliberately jax-free so callers can probe before deciding
which backend to let jax initialise.
"""

import os
import socket
import subprocess
import sys

RELAY_PORTS = (8082, 8083, 8087, 8092, 8093, 8097,
               8102, 8103, 8107, 8112, 8113, 8117)


def axon_tunnel_reachable(probe_timeout: int = 180) -> bool:
    """True when TPU work is safe: not tunnel-attached, or the relay
    answers and a throwaway subprocess can enumerate devices."""
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return True  # not tunnel-attached; nothing to probe
    port_open = False
    for port in RELAY_PORTS:
        s = socket.socket()
        s.settimeout(1)
        try:
            s.connect(("127.0.0.1", port))
            port_open = True
            break
        except OSError:
            pass
        finally:
            s.close()
    if not port_open:
        return False
    if os.environ.get("DEAP_TPU_SKIP_PROBE"):
        return True  # trust the port check; skip the slow device probe
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(len(jax.devices()))"],
            capture_output=True, timeout=probe_timeout)
        return out.returncode == 0
    except subprocess.TimeoutExpired:
        return False
