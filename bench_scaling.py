"""Multi-chip weak-scaling curve on a virtual CPU mesh.

The single-step multichip dryrun (``__graft_entry__.dryrun_multichip``)
proves the sharded programs compile and execute at n=8; what it cannot
catch is a *collective-placement* regression — a change that silently
turns a per-device-local step into one that moves the global
population every generation still passes a correctness dryrun. A
weak-scaling shape does catch it: with per-device work held constant,
total-work throughput should stay roughly flat as devices double, and
a superlinear fall-off flags collectives (or host transfers) that
scale with the global population. That is the best multi-chip evidence
this environment allows (SURVEY §2.3 P4/P6; one real chip, no
multi-chip hardware).

Two paths — the framework's prescribed multi-device layouts:

- ``island``: per-device demes, ``freq`` local generations per epoch +
  one ``ppermute`` ring migration (reference analog:
  onemax_island_scoop.py). Per-device deme size fixed → total
  population grows with n. The only cross-device traffic is the
  ``mig_k``-row ring hop, so throughput-per-device should be flat.
- ``pop``: row-sharded population with shard-local evaluation — the
  reference's P2 axis (``pool.map`` distributing EVALUATION, SURVEY
  §2.3), here ``shard_population`` + a compute-heavy fitness that XLA
  keeps entirely shard-local. Per-device shard size fixed → total
  population grows with n. There should be NO steady-state
  cross-device traffic at all.
- ``sp``: genome-axis sharding (SURVEY §5.7) — each device holds a
  genome *slice* of every individual and evaluation reduces partial
  fitness with ``psum`` (parallel/genome_shard.py). Per-device slice
  fixed → genome length grows with n. Cross-device traffic is one
  ``f32[n_pop]`` psum per evaluation.

Deliberately NOT on the curve: a *global* tournament over a
population sharded by rows. Selecting with global random aspirant
indices forces XLA to materialise cross-shard gathers of the whole
population every generation — measured at n=8 on this mesh it is
~30x below the contention-ideal line. That anti-pattern is why
``make_island_step`` exists; it is recorded in SCALING.json's
``antipattern_note`` for the record, not tracked as a regression
gate.

Each device count runs in a sanitized subprocess (CPU backend forced,
axon env stripped, ``--xla_force_host_platform_device_count`` set
before backend init — same recipe as the dryrun) so the curve reflects
the compiled programs, never the TPU tunnel's health.

Virtual devices contend for the SAME physical cores (this box: one),
so raw gens/sec falls with n by construction; the tracked metric is
**work-normalised efficiency** — ``(gens/sec x n) / (gens/sec at
n=1)``, the total-row throughput relative to single-device — which is
flat when no collective scales with global size. Results land in
``SCALING.json`` and one JSON line per device count on stdout.
Run: ``python bench_scaling.py``.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
DEVICE_COUNTS = (1, 2, 4, 8)
_SMOKE = bool(os.environ.get("DEAP_TPU_SCALING_SMOKE"))
ISLAND_SIZE = 64 if _SMOKE else 1024   # per-device deme rows
POP_SHARD = 64 if _SMOKE else 4096     # per-device rows, pop path
SP_POP = 64 if _SMOKE else 2048        # individuals on the SP path
SP_SLICE = 64 if _SMOKE else 2048      # per-device genome slice length
LENGTH = 100
FREQ = 5                # local generations per island epoch
EPOCHS = 2 if _SMOKE else 6            # timed epochs per measurement
OUT = os.path.join(HERE, "SCALING.json")

ANTIPATTERN_NOTE = (
    "global tournament over a row-sharded population (random global "
    "aspirant indices -> cross-shard row gathers every generation) "
    "measured ~30x below the contention-ideal line at n=8; use "
    "make_island_step (per-device demes + ring migration) or keep "
    "selection per-shard instead")


def _child(n_devices: int) -> None:
    """Measure both paths on ``n_devices`` virtual devices; print one
    JSON dict. Runs in the sanitized subprocess only."""
    import jax
    import jax.numpy as jnp

    from deap_tpu import ops
    from deap_tpu.algorithms import evaluate_invalid
    from deap_tpu.core.fitness import FitnessSpec
    from deap_tpu.core.toolbox import Toolbox
    from deap_tpu.parallel import (
        genome_mesh,
        island_init,
        make_island_step,
        make_sharded_evaluator,
        population_mesh,
        shard_genomes,
        shard_population,
    )

    assert len(jax.devices()) == n_devices, jax.devices()

    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)

    def timed(fn, *args):
        out = fn(*args)          # compile + warm
        jax.block_until_ready(out)
        best = float("inf")
        for _ in range(3):       # best-of-3 blunts shared-box noise
            t0 = time.perf_counter()
            for _ in range(EPOCHS):
                out = fn(*args)
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / EPOCHS)
        return best

    res = {"n_devices": n_devices}

    # ---- island path: fixed deme per device, ring migration ----
    mesh = population_mesh(n_devices, ("island",))
    pops = island_init(jax.random.key(0), n_devices, ISLAND_SIZE,
                       ops.bernoulli_genome(LENGTH), FitnessSpec((1.0,)))
    pops = jax.vmap(lambda p: evaluate_invalid(p, tb.evaluate))(pops)
    pops = shard_population(pops, mesh, "island")
    step = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=FREQ,
                            mig_k=32, mesh=mesh)
    dt = timed(step, jax.random.key(1), pops)
    res["island_gens_per_sec"] = FREQ / dt

    # ---- pop path: row-sharded population, shard-local heavy eval ----
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec

    mesh_p = population_mesh(n_devices, ("pop",))
    genomes_p = jax.device_put(
        jax.random.uniform(jax.random.key(5), (POP_SHARD * n_devices, 32)),
        NamedSharding(mesh_p, PartitionSpec("pop")))

    @jax.jit
    def heavy_eval(g):
        # a compute-heavy, purely row-local fitness (rastrigin iterated
        # to dominate dispatch): XLA must keep it shard-local — any
        # cross-device traffic here is a placement regression
        def body(i, acc):
            x = g * (1.0 + 1e-6 * acc[:, None])
            r = jnp.sum(x * x - 10.0 * jnp.cos(2 * jnp.pi * x) + 10.0,
                        axis=-1)
            return acc + r
        return lax.fori_loop(0, 8, body, jnp.zeros(g.shape[0]))

    dt = timed(heavy_eval, genomes_p)
    # PER-DEVICE rate (like island's per-deme gens/sec), so main()'s
    # uniform `rate * n / base` work-normalisation holds — a total-rows
    # rate here would double-count n and inflate the efficiency n-fold
    res["pop_evals_per_sec"] = POP_SHARD / dt

    # ---- SP path: genome-axis sharding, psum-reduced evaluation ----
    gmesh = genome_mesh(n_pop_shards=1, n_genome_shards=n_devices)
    genomes = jax.random.bernoulli(
        jax.random.key(2), 0.5,
        (SP_POP, SP_SLICE * n_devices)).astype(jnp.float32)
    evaluate = make_sharded_evaluator(
        lambda g: g.sum(-1), gmesh, combine="sum")
    sharded = shard_genomes(genomes, gmesh)
    dt = timed(evaluate, sharded)
    res["sp_evals_per_sec"] = SP_POP / dt

    print(json.dumps(res))


def measure(n_devices: int) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    out = subprocess.run(
        [sys.executable, "-c",
         f"import bench_scaling as b; b._child({int(n_devices)})"],
        cwd=HERE, env=env, capture_output=True, text=True, timeout=1200)
    if out.returncode != 0:
        raise RuntimeError(f"scaling child n={n_devices} failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


# ------------------------------------------------ sp cliff attribution ----

#: the attribution sweep's variants (VERDICT r5 weak #5 / next #4): the
#: n=8 sp efficiency cliff (0.87 -> 0.34) could be (a) psum cost
#: scaling with global size, (b) XLA layout effects tied to the slice
#: width, or (c) pure 1-core virtual-mesh contention. Each variant
#: isolates one axis; the conclusion is computed by differencing the
#: measured curves, not asserted.
ATTR_SLICE = 2048
ATTR_POP = 2048          # the SP config that measured the r5 cliff
ATTR_VARIANTS = (
    # (name, slice_len, combine, compute_reps)
    ("base", ATTR_SLICE, "sum", 1),
    ("no_collective", ATTR_SLICE, "none", 1),   # same compute, no psum
    ("heavy_compute", ATTR_SLICE, "sum", 8),    # 8x compute per psum
    ("narrow_slice", 512, "sum", 1),
    ("wide_slice", 8192, "sum", 1),
)
ATTR_DEVICES = (1, 4, 8)


def _attr_child(n_devices: int) -> None:
    """Measure every attribution variant on ``n_devices`` virtual
    devices; one JSON dict on stdout. Sanitized subprocess only."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from deap_tpu.parallel import genome_mesh, shard_genomes
    from deap_tpu.parallel.genome_shard import make_sharded_evaluator
    from deap_tpu.parallel.mesh import shard_map
    from deap_tpu.support.profiling import SpanRecorder

    assert len(jax.devices()) == n_devices, jax.devices()
    res = {"n_devices": n_devices, "variants": {}}

    for name, slice_len, combine, reps in ATTR_VARIANTS:
        gmesh = genome_mesh(n_pop_shards=1, n_genome_shards=n_devices)
        genomes = jax.random.uniform(
            jax.random.key(2), (ATTR_POP, slice_len * n_devices))

        def partial_eval(g, reps=reps):
            # rastrigin-flavoured local reduction, iterated ``reps``
            # times: varies compute per collective (the psum
            # "frequency" relative to useful work) without touching
            # the communication volume
            def body(i, acc):
                x = g * (1.0 + 1e-6 * acc[:, None])
                return acc + jnp.sum(
                    x * x - 10.0 * jnp.cos(2 * jnp.pi * x) + 10.0,
                    axis=-1)
            return lax.fori_loop(0, reps, body,
                                 jnp.zeros(g.shape[0]))

        if combine == "none":
            # identical local compute, NO cross-shard reduction: the
            # partials stay sharded — any residual inefficiency vs
            # n=1 is contention/layout, not the collective
            fn = jax.jit(shard_map(
                lambda g: partial_eval(g)[:, None], mesh=gmesh,
                in_specs=P("pop", "genome"),
                out_specs=P("pop", "genome")))
        else:
            fn = make_sharded_evaluator(partial_eval, gmesh,
                                        combine=combine)
        sharded = shard_genomes(genomes, gmesh)

        with SpanRecorder() as rec:
            out = fn(sharded)                 # compile + warm
            jax.block_until_ready(out)
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(EPOCHS):
                    out = fn(sharded)
                jax.block_until_ready(out)
                best = min(best, (time.perf_counter() - t0) / EPOCHS)
        spans = {k: {"count": v["count"],
                     "total_s": round(v["total_s"], 6)}
                 for k, v in rec.aggregates().items()}
        res["variants"][name] = {
            "slice": slice_len, "combine": combine,
            "compute_reps": reps,
            "evals_per_sec": ATTR_POP / best,
            # trace-time per-collective spans (SpanRecorder fires once
            # per trace under jit) — compile-phase attribution; the
            # execution attribution is the differenced timings
            "spans_trace_time": spans,
        }
    print(json.dumps(res))


def _attr_measure(n_devices: int) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    out = subprocess.run(
        [sys.executable, "-c",
         f"import bench_scaling as b; b._attr_child({int(n_devices)})"],
        cwd=HERE, env=env, capture_output=True, text=True, timeout=1800)
    if out.returncode != 0:
        raise RuntimeError(f"attr child n={n_devices} failed "
                           f"(rc={out.returncode}):\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def attribute_sp() -> None:
    """Run the attribution sweep and fold the result (rows + computed
    conclusion) into SCALING.json's ``sp_attribution`` section."""
    rows = [_attr_measure(n) for n in ATTR_DEVICES]
    base = rows[0]["variants"]
    eff = {}
    for row in rows:
        n = row["n_devices"]
        for name, v in row["variants"].items():
            e = v["evals_per_sec"] * n / base[name]["evals_per_sec"]
            v["work_efficiency"] = round(e, 3)
            eff[(name, n)] = e
        print(json.dumps(row))

    n_hi = ATTR_DEVICES[-1]
    e_base = eff[("base", n_hi)]
    e_none = eff[("no_collective", n_hi)]
    e_heavy = eff[("heavy_compute", n_hi)]
    e_narrow = eff[("narrow_slice", n_hi)]
    e_wide = eff[("wide_slice", n_hi)]
    parts = [
        f"at n={n_hi}: base eff {e_base:.2f}, no-collective "
        f"{e_none:.2f}, 8x-compute-per-psum {e_heavy:.2f}, "
        f"narrow(512) {e_narrow:.2f}, wide(8192) {e_wide:.2f}."
    ]
    if e_base >= 0.7:
        parts.append(
            "The r5 cliff (0.34) did NOT reproduce at the same "
            "pop/slice config in this sweep — consistent with the "
            "r5 capture riding transient shared-box load rather than "
            "a property of the sharded program; the variants below "
            "bound where a real cliff could come from.")
    elif e_none < 0.7:
        parts.append(
            "The cliff persists with the psum REMOVED entirely, so it "
            "is predominantly 1-core virtual-mesh contention "
            "(n XLA programs time-slicing one physical core), not "
            "collective cost — expect it not to reproduce on real "
            "multi-chip ICI.")
    else:
        parts.append(
            f"Removing the psum recovers efficiency to {e_none:.2f}: "
            "the collective itself is the dominant cost at n=8.")
    if e_heavy > e_base + 0.1:
        parts.append(
            f"Raising compute per psum 8x lifts efficiency to "
            f"{e_heavy:.2f}: the psum frequency (per-evaluation "
            "reduction) is a real secondary term — batching "
            "evaluations per collective would recover it.")
    if abs(e_narrow - e_wide) > 0.15:
        parts.append(
            f"Slice width moves efficiency ({e_narrow:.2f} at 512 vs "
            f"{e_wide:.2f} at 8192): per-slice compute granularity / "
            "XLA layout contributes.")
    else:
        parts.append("Slice width barely moves the curve: no "
                     "layout/granularity effect.")
    conclusion = " ".join(parts)
    print(json.dumps({"sp_attribution_conclusion": conclusion}))

    report = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            report = json.load(f)
    report["sp_attribution"] = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "config": {"pop": ATTR_POP, "epochs": EPOCHS,
                   "variants": [list(v) for v in ATTR_VARIANTS],
                   "device_counts": list(ATTR_DEVICES)},
        "rows": rows,
        "conclusion": conclusion,
    }
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)


def main() -> None:
    rows = [measure(n) for n in DEVICE_COUNTS]
    base = rows[0]
    for row in rows:
        n = row["n_devices"]
        for path, key in (("island", "island_gens_per_sec"),
                          ("pop", "pop_evals_per_sec"),
                          ("sp", "sp_evals_per_sec")):
            # work-normalised: per-device work is constant, devices
            # share the same cores, so ideal total-work throughput is
            # flat vs n=1 (see module docstring)
            row[f"{path}_work_efficiency"] = row[key] * n / base[key]
        print(json.dumps(row))
    report = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "backend": "cpu-virtual-mesh",
        "config": {"island_size": ISLAND_SIZE, "pop_shard": POP_SHARD,
                   "sp_pop": SP_POP, "sp_slice": SP_SLICE,
                   "length": LENGTH, "freq": FREQ, "epochs": EPOCHS},
        "antipattern_note": ANTIPATTERN_NOTE,
        "rows": rows,
    }
    with open(OUT, "w") as f:
        json.dump(report, f, indent=1)
    # regression tripwire, not a perf claim: allow generous room for
    # scheduling overhead of n virtual device programs on one core —
    # a collective that moves the global population every generation
    # lands far below this floor
    worst = min(min(r["island_work_efficiency"],
                    r["pop_work_efficiency"],
                    r["sp_work_efficiency"]) for r in rows)
    print(json.dumps({"metric": "weak_scaling_work_efficiency_min",
                      "value": round(worst, 3), "unit": "ratio",
                      "ok": worst >= 0.25}))


if __name__ == "__main__":
    if "--attribute-sp" in sys.argv:
        attribute_sp()
    else:
        main()
