"""Secondary benchmark suite (bench.py measures the onemax headline).

Measures generations/sec on three configurations spanning the
framework's main engines beyond the north-star GA, each against the
reference CPU throughput measured on this machine (BASELINE.md
"Secondary configs"):

1. ``cmaes_n100_lam4096`` — full Hansen CMA-ES ask-tell on sphere
   (reference deap/cma.py:84-171 driven by eaGenerateUpdate): generate,
   batched evaluate, covariance/eigh update all in one scanned step.
2. ``nsga2_zdt1_pop2000`` — the canonical NSGA-II generation
   (examples/ga/nsga2.py shape: selTournamentDCD → SBX-bounded +
   polynomial mutation → zdt1 → selNSGA2 over pop+offspring).
3. ``rastrigin_n30_pop100k`` — real-valued eaSimple GA (cxBlend α=0.5 +
   mutGaussian σ=0.3, selTournament 3) on rastrigin.
4. ``gp_symbreg_pop4096_pts256`` — GP symbolic regression of the
   quartic (examples/gp/symbreg.py scaled up): the batched stack
   interpreter + tensor tree ops versus the reference's per-individual
   string-codegen ``eval`` (deap/gp.py:462-487). The reference number
   is generous to the reference — measured at generation ~4, before
   bloat grows the trees.
5. ``nsga2_zdt1_pop50k`` — the BASELINE.json pop=50k NSGA-II config:
   100k-candidate non-dominated selection per generation through the
   tiled streaming kernels (mo.emo past ND_TILED_THRESHOLD). The
   reference denominator is EXTRAPOLATED (its O(MN²) Python sort makes
   pop=50k infeasible to sample — BASELINE.md): 0.1662 gens/s at 4k
   candidates × (4k/100k)² on the dominating sort term.
6. ``cartpole_neuro_pop10k`` — BASELINE.json config #5: GA over flat
   MLP(4,16,2) weight vectors, fitness = 3-episode mean CartPole
   rollout (500 steps, lax.scan), population sharded over the mesh.
   Reference denominator measured with the same GA + a pure-Python
   rollout on the 2to3-converted reference (BASELINE.md): 0.2398
   gens/s with the *initial* population, where random policies fail in
   ~20 steps — deliberately generous to the reference, since our scan
   always pays full 500-step episodes; with converged (full-length)
   policies the reference drops to 0.0121 gens/s.

Prints one JSON line per config:
  {"metric": ..., "value": N, "unit": "gens/sec", "vs_baseline": N}

Reference numbers were produced by the 2to3-converted reference run
from /tmp scratch, timed generations after warmup — mean of 3 (mean of
2 for the pop=100k GA), matching BASELINE.md's recipe.
"""

import json
import os
import sys

# reuse bench.py's axon-tunnel probe + platform forcing side effects
import bench  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu import benchmarks, ops
from deap_tpu.algorithms import evaluate_invalid, var_and
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import concat, gather, init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.mo.emo import sel_nsga2, sel_tournament_dcd
from deap_tpu.strategies.cma import Strategy

# CPU reference gens/sec, measured 2026-07-30 (BASELINE.md).
# nsga2_zdt1_pop50k is EXTRAPOLATED (quadratic sort term from the
# measured 4k-candidate run; direct measurement infeasible — see
# BASELINE.md); cartpole is measured with a pure-Python rollout.
# Values live in tpu_capture (the import-light canonical home shared
# with bench_report.py).
from tpu_capture import (  # noqa: E402
    SUITE_EXTRAPOLATED,
    SUITE_REF,
    SUITE_REF_CONVERGED,
)

REF = SUITE_REF
EXTRAPOLATED = SUITE_EXTRAPOLATED
REF_CONVERGED = SUITE_REF_CONVERGED

NGEN = 50
REPS = 3


def _time(run, *args, ngen=None):
    """gens/sec, mean of REPS after a warmup/compile run.

    Deliberately mean-of-REPS rather than bench.py's best-of-REPS: the
    reference CPU numbers in REF are means (BASELINE.md recipe), so the
    vs_baseline ratio must be like-for-like.
    """
    import time

    ngen = ngen or NGEN
    bench.sync(run(jax.random.key(100), *args))  # compile + warm
    t0 = time.perf_counter()
    for r in range(REPS):
        bench.sync(run(jax.random.key(101 + r), *args))
    return ngen / ((time.perf_counter() - t0) / REPS)


def bench_cmaes():
    strat = Strategy(jnp.full(100, 5.0), sigma=0.5, lambda_=4096)
    state = strat.initial_state()
    ev = jax.vmap(benchmarks.sphere)

    @jax.jit
    def run(key, state):
        def step(st, k):
            pop = strat.generate(k, st)
            return strat.update(st, pop, ev(pop)), 0

        st, _ = lax.scan(step, state, jax.random.split(key, NGEN))
        return st.centroid

    return _time(run, state)


def bench_nsga2():
    NDIM, MU = 30, 2000
    spec = FitnessSpec((-1.0, -1.0))
    tb = Toolbox()
    tb.register("evaluate", jax.vmap(benchmarks.zdt1))
    tb.register("mate", ops.cx_simulated_binary_bounded,
                eta=20.0, low=0.0, up=1.0)
    tb.register("mutate", ops.mut_polynomial_bounded,
                eta=20.0, low=0.0, up=1.0, indpb=1.0 / NDIM)
    pop = init_population(jax.random.key(1), MU,
                          ops.uniform_genome(NDIM, 0.0, 1.0), spec)
    pop = evaluate_invalid(pop, tb.evaluate)

    @jax.jit
    def run(key, pop):
        def step(p, k):
            k1, k2 = jax.random.split(k)
            idx = sel_tournament_dcd(k1, p.wvalues, MU)
            off = var_and(k2, gather(p, idx), tb, 0.9, 1.0)
            off = evaluate_invalid(off, tb.evaluate)
            comb = concat([p, off])
            return gather(comb, sel_nsga2(None, comb.wvalues, MU)), 0

        p, _ = lax.scan(step, pop, jax.random.split(key, NGEN))
        return p.wvalues

    return _time(run, pop)


def bench_rastrigin():
    N, POP = 30, 100_000
    tb = Toolbox()
    tb.register("evaluate", jax.vmap(benchmarks.rastrigin))
    tb.register("mate", ops.cx_blend, alpha=0.5)
    tb.register("mutate", ops.mut_gaussian, mu=0.0, sigma=0.3, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    pop = init_population(jax.random.key(1), POP,
                          ops.uniform_genome(N, -5.12, 5.12),
                          FitnessSpec((-1.0,)))
    pop = evaluate_invalid(pop, tb.evaluate)

    if jax.default_backend() == "tpu":
        # fused Pallas path: blend + gaussian + rastrigin in one HBM
        # pass, per-gene randomness from the hardware PRNG
        genomes = pop.genomes
        fit = pop.fitness[:, 0]

        @jax.jit
        def run_fused(key, genomes, fit):
            def step(carry, k):
                g, f = carry
                k1, k2 = jax.random.split(k)
                idx = ops.sel_tournament_sorted(k1, -f[:, None], POP,
                                                tournsize=3)
                g, f = ops.fused_variation_eval_real(
                    k2, g[idx], cxpb=0.5, mutpb=0.2, indpb=0.1,
                    alpha=0.5, sigma=0.3, evaluate="rastrigin",
                    prng="hw", block_i=1024, interpret=False)
                return (g, f), 0

            (g, f), _ = lax.scan(step, (genomes, fit),
                                 jax.random.split(key, NGEN))
            return f

        return _time(run_fused, genomes, fit)

    @jax.jit
    def run(key, pop):
        def step(p, k):
            k1, k2 = jax.random.split(k)
            idx = tb.select(k1, p.wvalues, POP)
            off = var_and(k2, gather(p, idx), tb, 0.5, 0.2)
            return evaluate_invalid(off, tb.evaluate), 0

        p, _ = lax.scan(step, pop, jax.random.split(key, NGEN))
        return p.wvalues

    return _time(run, pop)


def bench_nsga2_50k():
    """The pop=50k promise: selection over 100k candidates per
    generation. Two exact nd-sort routes race (same pattern as the GP
    scan/sweep race): the bi-objective O(n log n) staircase
    (``nd='staircase'``, r5 — the path that also runs end-to-end on a
    CPU host) and, on TPU, the tiled streaming Pallas kernel
    (``nd='tiled'``, the general >2-objective scale path) — the row
    records the faster, and the race itself is the tiled kernel's
    first at-scale on-chip execution."""
    NDIM, MU, ngen = 30, 50_000, 10
    spec = FitnessSpec((-1.0, -1.0))
    tb = Toolbox()
    tb.register("evaluate", jax.vmap(benchmarks.zdt1))
    tb.register("mate", ops.cx_simulated_binary_bounded,
                eta=20.0, low=0.0, up=1.0)
    tb.register("mutate", ops.mut_polynomial_bounded,
                eta=20.0, low=0.0, up=1.0, indpb=1.0 / NDIM)
    pop = init_population(jax.random.key(1), MU,
                          ops.uniform_genome(NDIM, 0.0, 1.0), spec)
    pop = evaluate_invalid(pop, tb.evaluate)

    def build(nd):
        @jax.jit
        def run(key, pop):
            def step(p, k):
                k1, k2 = jax.random.split(k)
                idx = sel_tournament_dcd(k1, p.wvalues, MU)
                off = var_and(k2, gather(p, idx), tb, 0.9, 1.0)
                off = evaluate_invalid(off, tb.evaluate)
                comb = concat([p, off])
                return gather(comb,
                              sel_nsga2(None, comb.wvalues, MU, nd=nd)), 0

            p, _ = lax.scan(step, pop, jax.random.split(key, ngen))
            return p.wvalues

        return run, pop

    gps = _time(*build("staircase"), ngen=ngen)
    if jax.default_backend() == "tpu":
        gps = max(gps, _time(*build("tiled"), ngen=ngen))
    return gps


def bench_cartpole():
    """BASELINE.json config #5: pop=10k MLP policies, 3-episode mean
    CartPole rollout fitness, population sharded over the mesh."""
    from deap_tpu.benchmarks.cartpole import (mlp_policy,
                                              rollout_population)
    from deap_tpu.parallel import population_mesh, shard_population

    POP, ngen, episodes, max_steps = 10_000, 20, 3, 500
    policy, n_params = mlp_policy((4, 16, 2))
    ep_keys = jax.random.split(jax.random.key(123), episodes)

    def evaluate(genomes):
        # compaction cascade (rollout_population): cost tracks the
        # survivor-curve integral (alive episodes get compacted into
        # halving buffers) instead of always paying max_steps per
        # episode — the reference's per-individual while-loop
        # advantage, recovered in batch form
        return rollout_population(policy, genomes, ep_keys,
                                  max_steps).mean(axis=1)

    tb = Toolbox()
    tb.register("evaluate", evaluate)
    tb.register("mate", ops.cx_blend, alpha=0.1)
    tb.register("mutate", ops.mut_gaussian, mu=0.0, sigma=0.3, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(90), POP,
                          ops.normal_genome(n_params, sigma=0.5),
                          FitnessSpec((1.0,)))
    pop = evaluate_invalid(pop, tb.evaluate)
    pop = shard_population(pop, population_mesh())

    @jax.jit
    def run(key, pop):
        def step(p, k):
            k1, k2 = jax.random.split(k)
            idx = tb.select(k1, p.wvalues, POP)
            off = var_and(k2, gather(p, idx), tb, 0.5, 0.5)
            return evaluate_invalid(off, tb.evaluate), 0

        p, _ = lax.scan(step, pop, jax.random.split(key, ngen))
        return p.wvalues

    return _time(run, pop, ngen=ngen)


def bench_gp_symbreg():
    """Races the interpreter schedules with a SHORT probe — the jit'd
    scan loop, the level-synchronous sweep loop (TPU only), and the
    host-dispatch grouped+dedup loop (gp/loop.py, the bench.py
    --gp-race winner on CPU) — then measures the winner alone at full
    length (bench_gp.suite_gps). Probing first keeps the staged
    scan-vs-sweep-vs-grouped TPU race inside a few minutes of relay
    window, where the old full-length-per-mode race needed tens."""
    from bench_gp import suite_gps

    return suite_gps()


# cmaes runs LAST: its scan-of-eigh is the largest compile shipped
# through the axon tunnel and the prime suspect for the 2026-07-31
# relay wedge (the suite froze inside bench_cmaes with the relay ports
# still accepting TCP) — everything cheaper must land first.
CONFIGS = [
    ("nsga2_zdt1_pop2000", bench_nsga2),
    ("rastrigin_n30_pop100k", bench_rastrigin),
    ("gp_symbreg_pop4096_pts256", bench_gp_symbreg),
    ("nsga2_zdt1_pop50k", bench_nsga2_50k),
    ("cartpole_neuro_pop10k", bench_cartpole),
    ("cmaes_n100_lam4096", bench_cmaes),
]

# tpu_capture.queue_complete() keeps its own copy of this list (it
# cannot import us — our `import bench` side effect probes the relay);
# fail loudly here if the two ever drift
from tpu_capture import SUITE_CONFIG_NAMES  # noqa: E402

if tuple(n for n, _ in CONFIGS) != SUITE_CONFIG_NAMES:
    raise SystemExit("CONFIGS drifted from "
                     "tpu_capture.SUITE_CONFIG_NAMES")


def run_one(name: str) -> dict:
    fn = dict(CONFIGS)[name]
    gps = fn()
    ref = REF[name]
    line = {
        "metric": f"{name}_generations_per_sec",
        "value": round(gps, 2),
        "unit": "gens/sec",
        "vs_baseline": round(gps / ref, 1) if ref else None,
        "backend": jax.default_backend(),
    }
    if name in EXTRAPOLATED:
        line["ref_extrapolated"] = True
    if name in REF_CONVERGED:
        # our lax.scan rollout pays the same cost at any skill level;
        # the reference collapses as policies improve — report the
        # converged-pop ratio alongside the generous initial-pop one
        line["vs_baseline_converged"] = round(gps / REF_CONVERGED[name], 1)
    return line


def main_isolated(out_path, timeout_s):
    """Each config in its own subprocess with a hard timeout, results
    appended to ``out_path`` as they land — a wedged relay (or one
    poison compile) costs that config only, not the suite. The relay is
    re-probed between configs; a dead probe stops the sweep early with
    an explanatory line rather than a hang."""
    import subprocess

    from _axon_probe import axon_tunnel_reachable

    def emit(line):
        print(json.dumps(line), flush=True)
        with open(out_path, "a") as f:
            f.write(json.dumps(line) + "\n")

    me = os.path.abspath(__file__)
    env = dict(os.environ, DEAP_TPU_SKIP_PROBE="1")  # supervisor probes
    # resume support: a config whose TPU value already landed in
    # out_path (from an earlier uptime window) is not re-run — windows
    # are scarce and a captured row is a captured row
    from tpu_capture import _jsonl_rows
    done = {d["metric"] for d in _jsonl_rows(out_path)
            if "value" in d and d.get("backend") == "tpu"}
    for i, (name, _) in enumerate(CONFIGS):
        metric = f"{name}_generations_per_sec"
        if metric in done:
            print(f"{metric}: already captured, skipping", flush=True)
            continue
        if not axon_tunnel_reachable():
            emit({"metric": metric, "skipped": "relay unreachable"})
            for later, _ in CONFIGS[i + 1:]:
                emit({"metric": f"{later}_generations_per_sec",
                      "skipped": "relay unreachable"})
            break
        try:
            r = subprocess.run(
                [sys.executable, me, "--config", name], env=env,
                capture_output=True, text=True, timeout=timeout_s)
            out = [ln for ln in r.stdout.splitlines()
                   if ln.startswith("{")]
            try:
                line = json.loads(out[-1]) if out else {
                    "metric": metric, "error": (r.stderr or "")[-400:]}
            except json.JSONDecodeError:
                line = {"metric": metric,
                        "error": f"unparseable child output: {out[-1][-200:]}"}
        except subprocess.TimeoutExpired:
            line = {"metric": metric, "error": f"timeout after {timeout_s}s"}
        emit(line)


def main():
    for name, _ in CONFIGS:
        print(json.dumps(run_one(name)), flush=True)


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--config", choices=[n for n, _ in CONFIGS],
                    help="run exactly one configuration")
    ap.add_argument("--isolated", action="store_true",
                    help="run every config in its own subprocess")
    ap.add_argument("--out", default="BENCH_SUITE_PARTIAL.jsonl",
                    help="append-as-they-land artifact (with --isolated)")
    ap.add_argument("--timeout", type=int, default=1500,
                    help="per-config subprocess timeout (with --isolated)")
    a = ap.parse_args()
    if a.config:
        print(json.dumps(run_one(a.config)), flush=True)
    elif a.isolated:
        main_isolated(a.out, a.timeout)
    else:
        main()
