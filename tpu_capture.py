"""Unattended TPU evidence capture for relay uptime windows.

The axon relay has been up for ~15 minutes total across rounds 2-3;
when it answers, every driver-parseable artifact must be captured
before it wedges again. This orchestrator runs the whole measurement
queue with per-step subprocess isolation (a wedge costs one step, not
the window), appends each result to the round's evidence file
(``TPU_EVIDENCE_{ROUND}.jsonl`` — see the ROUND constant) the moment
it lands, and git-commits after every step so evidence survives
anything.

Queue order is cheapest-first / highest-value-first:

1. ``bench.py`` — the headline three-candidate race (north star).
2. ``bench_profile.py`` — component attribution incl. the two
   counting-sort modes (the roofline evidence VERDICT r1/r2 asked for).
3. ``bench_suite.py --isolated`` — the five secondary configs, each in
   its own subprocess, cmaes (the wedge suspect) last.
4. ``bench_profile.py --trace`` into the round's trace dir — xplane
   capture, last: it adds nothing numeric and profiling has its own
   wedge risk.

Usage: ``python tpu_capture.py`` (checks the relay first, exits 0 with
a message if it is down; safe to re-run — steps append, never clobber).
"""

import datetime
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
from _axon_probe import axon_tunnel_reachable  # noqa: E402

# single source for every round-stamped artifact name — STEPS and the
# _have_* predicates both derive from these, so a round bump cannot
# leave queue_complete() reading stale files
ROUND = "r04"
ZOO_OUT = f"TPU_ZOO_{ROUND}.json"

# persistent XLA compilation cache shared across window attempts: the
# 03:18 r3 window lost ~40 of its 44 minutes to tunnel compiles that a
# prior attempt had already paid for. Threaded into every captured
# subprocess via CACHE_ENV.
CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".jax_cache")
CACHE_ENV = {
    "JAX_COMPILATION_CACHE_DIR": CACHE_DIR,
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "1",
}
EVIDENCE = os.path.join(HERE, f"TPU_EVIDENCE_{ROUND}.jsonl")
SUITE_OUT = f"TPU_SUITE_{ROUND}.jsonl"
PROFILE_OUT = f"TPU_PROFILE_{ROUND}.jsonl"
TRACE_DIR = os.path.join("traces", ROUND)

STEPS = [
    # hw-kernel semantics validated on-chip BEFORE any throughput
    # number is recorded (the pytest suite pins CPU and cannot).
    # Ordering lesson from the 2026-07-31 03:18-04:02 window: the
    # five-config suite must precede the profile — the profile's eight
    # tunnel compiles ate the whole window and its timeout lost them
    # all (profile is now incremental via --out, but the suite rows
    # are the higher-value artifact).
    ("_tpu_hw_check.py", [sys.executable, "_tpu_hw_check.py"], 1200),
    ("bench.py", [sys.executable, "bench.py"], 6600),
    ("bench_suite.py", [sys.executable, "bench_suite.py", "--isolated",
                        "--out", SUITE_OUT], 9000),
    ("bench_profile.py", [sys.executable, "bench_profile.py",
                          "--out", PROFILE_OUT], 3600),
    # --out here too: resume skips the already-captured component
    # timings so a short window spends its minutes on the trace itself
    ("bench_profile.py --trace", [sys.executable, "bench_profile.py",
                                  "--trace", TRACE_DIR,
                                  "--out", PROFILE_OUT], 2400),
    # the examples are the de-facto integration suite and have never
    # touched the hardware they're named for (VERDICT r3 #9): one
    # TPU-salient program per family, full configs, process-isolated
    ("speed.py#flagship", [sys.executable,
                           os.path.join("examples", "speed.py"),
                           "--flagship", "--full", "--isolate",
                           "--resume", "--report", ZOO_OUT], 5400),
    # LAST: re-race the headline once everything else is captured —
    # candidates added after the first capture (block-size variants)
    # are otherwise only measured at the driver's round-end run
    ("bench.py#rerace", [sys.executable, "bench.py"], 6600),
]

# canonical artifact inventories for queue_complete(). Kept HERE (not
# imported from bench_suite/bench_profile) because importing either
# triggers `import bench` → a relay probe + jax initialisation — far
# too heavy for the watcher's 2-minute loop. The bench scripts assert
# against these at runtime so the lists cannot drift silently.
SUITE_CONFIG_NAMES = (
    "nsga2_zdt1_pop2000", "rastrigin_n30_pop100k",
    "gp_symbreg_pop4096_pts256", "nsga2_zdt1_pop50k",
    "cartpole_neuro_pop10k", "cmaes_n100_lam4096",
)
COMPONENT_NAMES = (
    "full_binned", "full_evolve", "kernel_fused_packed",
    "select_binned", "gather_random", "gather_coherent", "full_sorted",
    "select_sorted", "counting_mxu", "counting_scan",
)
# bench.py cross-checks its CANDIDATES length against this (same
# cannot-import-the-bench-script reason as the lists above).
# 7 = + packed_evolve, the r4 whole-GA-in-VMEM mega-kernel
N_CANDIDATES = 7

# bump when _tpu_hw_check gains checks: an ok verdict from an older
# version must not skip the step, or kernels added since (e.g. the
# selgather dynamic_gather path) get raced without on-chip validation.
# v3: tiled dominance kernels (nd_rank_tiled/strengths_tiled vs the
# matrix oracle at n=16k) — their first execution on a real TPU core.
# v4: the evolve_packed whole-GA mega-kernel's on-chip checks.
HW_CHECK_VERSION = 4

# reference CPU gens/sec per suite config, and which references are
# extrapolated rather than measured (BASELINE.md records the recipes).
# Canonical HERE for the same import-weight reason; bench_suite
# imports and uses these directly so values cannot drift.
SUITE_REF = {
    "cmaes_n100_lam4096": 6.6318,
    "nsga2_zdt1_pop2000": 0.1662,
    "rastrigin_n30_pop100k": 0.2693,
    "gp_symbreg_pop4096_pts256": 3.0766,
    "nsga2_zdt1_pop50k": 0.1662 * (4_000 / 100_000) ** 2,
    "cartpole_neuro_pop10k": 0.2398,  # initial-pop (generous); 0.0121 converged
}
SUITE_EXTRAPOLATED = {"nsga2_zdt1_pop50k"}
# the reference pays per-step Python only while episodes survive, so
# its gens/sec collapses 20x as policies learn to balance — the
# CONVERGED denominator (hand-built balancer completing full 500-step
# episodes, BASELINE.md CartPole section). Suite rows report both
# ratios: vs_baseline against the generous initial-pop number above,
# vs_baseline_converged against this one.
SUITE_REF_CONVERGED = {"cartpole_neuro_pop10k": 0.0121}

# canonical flagship list (examples/speed.py asserts against this —
# same cannot-import-the-heavy-module reason as the lists above)
ZOO_FLAGSHIP = (
    "examples.ga.onemax_fused",
    "examples.ga.nsga2_large",
    "examples.gp.symbreg",
    "examples.es.cma_minfct",
    "examples.ga.onemax_island_sharded",
    "examples.neuroevolution.cartpole",
)


def _jsonl_rows(path):
    rows = []
    if os.path.exists(path):
        for line in open(path):
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                pass
    return rows


def _evidence_results(step):
    """All result rows the evidence file records for ``step``."""
    return [r for d in _jsonl_rows(EVIDENCE) if d.get("script") == step
            for r in d.get("results", [])]


BENCH_SCRIPTS = ("bench.py", "bench.py#rerace")


def headline_rows(path=None):
    """Every VALID TPU headline row, any bench script, with the
    envelope timestamp attached as ``measured_at``. The single source
    of what counts as a headline measurement — the capture predicates
    and bench.py's cached replay (which passes prior rounds' evidence
    files as ``path``) must never disagree on this: "error" rows (the
    all-candidates-failed sentinel carries value=0.0) and "cached" rows
    (replays of earlier captures) don't count."""
    return [dict(r, measured_at=d.get("ts"))
            for d in _jsonl_rows(EVIDENCE if path is None else path)
            if d.get("script") in BENCH_SCRIPTS
            for r in d.get("results", [])
            if r.get("backend") == "tpu" and r.get("value")
            and "error" not in r and not r.get("cached")]


def _have_hw_check():
    """A *passing* core on-chip validation at the CURRENT check
    version — a failed, CPU-fallback, or outdated row must not
    suppress re-validation in a later window — AND a tiled-dominance
    row at the current version. The tiled row needs only to be
    RESOLVED (ok true or false): a deterministic Mosaic failure there
    is recorded evidence that must not re-run the step every window
    (the suite's nsga2 configs surface it independently)."""
    rows = _evidence_results("_tpu_hw_check.py")
    core_ok = any(r.get("check") == "hw_kernels" and r.get("ok") is True
                  and r.get("version", 1) >= HW_CHECK_VERSION
                  for r in rows)
    tiled_resolved = any(r.get("check") == "tiled_dominance"
                         and r.get("version", 1) >= HW_CHECK_VERSION
                         for r in rows)
    if core_ok and not tiled_resolved:
        # a PROCESS-level abort in the tiled block (fatal Mosaic error,
        # not a Python exception) flushes the core row but never prints
        # a tiled one. Two attempts ending that way WITH THE RELAY
        # STILL UP afterwards is a deterministic abort on record —
        # treat as resolved rather than burning 1200 s of every future
        # window re-proving it (the suite's nsga2 configs surface the
        # breakage independently). Attempts where the relay was down
        # after the step (or envelopes predating the liveness stamp)
        # don't count: the death was plausibly the relay's.
        aborted = sum(
            1 for d in _jsonl_rows(EVIDENCE)
            if d.get("script") == "_tpu_hw_check.py"
            and d.get("relay_up_after") is True
            and any(r.get("check") == "hw_kernels"
                    and r.get("ok") is True
                    and r.get("version", 1) >= HW_CHECK_VERSION
                    for r in d.get("results", []))
            and not any(r.get("check") == "tiled_dominance"
                        for r in d.get("results", [])))
        tiled_resolved = aborted >= 2
    return core_ok and tiled_resolved


def _have_headline():
    return bool(headline_rows())


def suite_rows():
    """Valid TPU suite rows, keyed by metric — shared by the capture
    predicate and bench_report so they can never disagree."""
    return {r["metric"]: r for r in
            _jsonl_rows(os.path.join(HERE, SUITE_OUT))
            if r.get("backend") == "tpu" and "value" in r}


def profile_rows():
    """Valid TPU profile timing rows, keyed by component — shared by
    the capture predicate and bench_report."""
    return {r["component"]: r for r in
            _jsonl_rows(os.path.join(HERE, PROFILE_OUT))
            if r.get("backend") == "tpu" and "ms_per_gen" in r}


def profile_resolved():
    """Every component RESOLVED with TPU backing, keyed by component:
    a timing row, or an error row (a deterministic on-chip failure is
    a resolution — e.g. a Mosaic lowering gap — and its text is worth
    surfacing). Superset of :func:`profile_rows`; the single source
    for both the capture predicate and bench_report, so the watcher
    and the report can never disagree on capture status."""
    return {r["component"]: r for r in
            _jsonl_rows(os.path.join(HERE, PROFILE_OUT))
            if r.get("backend") == "tpu" and r.get("component")
            and ("ms_per_gen" in r or "error" in r)}


def _have_suite():
    suite = suite_rows()
    return all(f"{n}_generations_per_sec" in suite
               for n in SUITE_CONFIG_NAMES)


def _have_profile():
    """Every component RESOLVED with TPU backing — a timing row, or an
    error row (a deterministic failure, e.g. a Mosaic lowering gap, is
    a resolution; re-paying the component's tunnel compile every
    window is not). bench_profile itself aborts rather than writing an
    error row when the relay died under a component, so transient
    failures never masquerade as resolutions here."""
    return set(profile_resolved()).issuperset(COMPONENT_NAMES)


def _have_zoo():
    """Every flagship example RESOLVED on TPU in the zoo report: a row
    with backend "tpu", passing or not (a recorded on-chip failure is
    evidence; a timeout/no-backend row is not — the window died and a
    later one must retry)."""
    path = os.path.join(HERE, ZOO_OUT)
    if not os.path.exists(path):
        return False
    try:
        report = json.load(open(path))
    except (json.JSONDecodeError, OSError):
        return False
    # full-config TPU rows only: a smoke run on-chip must not satisfy
    # the full-config step (same stance as _have_trace's CPU guard)
    resolved = {r.get("example") for r in report.get("results", [])
                if r.get("backend") == "tpu"
                and r.get("config") == "full"}
    return resolved.issuperset(ZOO_FLAGSHIP)


def _have_trace():
    """A *finalised* xplane file, not just a non-empty directory — a
    trace run killed mid-write leaves plugins/... scaffolding that
    must not satisfy the watcher's stop condition."""
    import glob
    return bool(glob.glob(os.path.join(HERE, TRACE_DIR, "**",
                                       "*.xplane.pb"), recursive=True))


def _have_full_race():
    """A headline row produced by a race in which every candidate on
    the current roster RESOLVED — timed, or deterministically failed
    (e.g. the selgather semantic gate raising on an unsupported Mosaic
    lowering). A deterministic failure must count as resolution, or a
    roster with one unsupported kernel would make this predicate
    permanently false and _relay_watch would re-run the full race every
    uptime window forever (advisor r3). Partial races ("timeout",
    "unreached": relay died mid-window) still don't satisfy it.

    The all-candidates-FAILED sentinel (value=0.0, "error" key) is
    excluded from headline_rows() by design, but when every candidate
    resolved as a deterministic failure it is still a terminal race
    outcome — without accepting it here the watcher would re-run the
    race every window in that corner (advisor r4). So scan the raw
    evidence rows for resolution counts, not just the valid headlines."""
    def _resolved(r):
        return r.get("n_resolved", r.get("n_candidates", 0)) >= N_CANDIDATES
    if any(_resolved(r) for r in headline_rows()):
        return True
    return any(
        _resolved(r)
        for step in BENCH_SCRIPTS for r in _evidence_results(step)
        if r.get("backend") == "tpu" and not r.get("cached")
        and "error" in r)


# step → "this artifact is already captured with TPU backing". Applied
# on queue entry so a later window spends its scarce minutes only on
# what is still missing (the 03:18 window burned 40 of its 44 minutes
# re-proving things it already had).
CAPTURED = {
    "_tpu_hw_check.py": _have_hw_check,
    "bench.py": _have_headline,
    "bench_suite.py": _have_suite,
    "bench_profile.py": _have_profile,
    "bench_profile.py --trace": _have_trace,
    "speed.py#flagship": _have_zoo,
    "bench.py#rerace": _have_full_race,
}


if {s for s, _, _ in STEPS} != set(CAPTURED):
    raise SystemExit("STEPS and CAPTURED drifted — every queue step "
                     "needs a captured-predicate and vice versa")


def already_captured(step):
    return CAPTURED[step]()


def queue_complete():
    """True when every artifact the queue exists to produce is on disk
    with TPU backing — the watcher's stop condition (without it, an
    uptime window with everything captured would re-run the whole
    queue every probe interval forever)."""
    return all(have() for have in CAPTURED.values())


def log(step, payload):
    line = {"ts": datetime.datetime.now(datetime.timezone.utc).isoformat(),
            "script": step, **payload}
    with open(EVIDENCE, "a") as f:
        f.write(json.dumps(line) + "\n")
    print(json.dumps(line), flush=True)


def commit(step):
    paths = [p for p in (os.path.basename(EVIDENCE), SUITE_OUT,
                         PROFILE_OUT, ZOO_OUT,
                         "TPU_PROBE_LOG.jsonl", "traces")
             if os.path.exists(os.path.join(HERE, p))]
    subprocess.run(["git", "add", "-A"] + paths,
                   cwd=HERE, capture_output=True)
    subprocess.run(["git", "commit", "-q", "-m",
                    f"TPU evidence: {step} captured\n\n"
                    "No-Verification-Needed: measurement artifacts only"],
                   cwd=HERE, capture_output=True)


def _run_step(cmd, timeout_s):
    """Run one queue step in its OWN process group and, on timeout,
    kill the whole group. ``subprocess.run``'s timeout kills only the
    direct child: a step like speed.py --isolate (or bench.py's
    candidate race) spawns grandchildren that would survive, keep
    holding the single-client TPU, and wedge every later step in the
    window."""
    import signal

    proc = subprocess.Popen(
        cmd, cwd=HERE, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env={**os.environ, **CACHE_ENV},
        start_new_session=True)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.communicate()
        raise
    return subprocess.CompletedProcess(cmd, proc.returncode, out, err)


def main():
    if not axon_tunnel_reachable():
        print("relay unreachable; nothing captured")
        return
    for step, cmd, timeout_s in STEPS:
        if already_captured(step):
            print(f"{step}: already captured this round, skipping",
                  flush=True)
            continue
        if not axon_tunnel_reachable():
            log(step, {"skipped": "relay died mid-window"})
            commit(step)
            break
        try:
            r = _run_step(cmd, timeout_s)
            results = []
            for ln in r.stdout.splitlines():
                if ln.startswith("{"):
                    try:
                        results.append(json.loads(ln))
                    except json.JSONDecodeError:
                        results.append({"unparseable": ln[-200:]})
            if results:
                # relay liveness right after the step: lets the
                # predicates tell "step genuinely resolved" from "step
                # died with the relay" (_have_hw_check's abort counter)
                log(step, {"results": results,
                           "relay_up_after": axon_tunnel_reachable()})
            else:
                log(step, {"error": f"rc={r.returncode}, no JSON; "
                                    f"stderr tail: {(r.stderr or '')[-300:]}"})
        except subprocess.TimeoutExpired:
            log(step, {"error": f"timeout after {timeout_s}s"})
        commit(step)


if __name__ == "__main__":
    main()
