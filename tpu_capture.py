"""Unattended TPU evidence capture for relay uptime windows.

The axon relay has been up for ~15 minutes total across rounds 2-3;
when it answers, every driver-parseable artifact must be captured
before it wedges again. This orchestrator runs the whole measurement
queue with per-step subprocess isolation (a wedge costs one step, not
the window), appends each result to ``TPU_EVIDENCE_r03.jsonl`` the
moment it lands, and git-commits after every step so evidence survives
anything.

Queue order is cheapest-first / highest-value-first:

1. ``bench.py`` — the headline three-candidate race (north star).
2. ``bench_profile.py`` — component attribution incl. the two
   counting-sort modes (the roofline evidence VERDICT r1/r2 asked for).
3. ``bench_suite.py --isolated`` — the five secondary configs, each in
   its own subprocess, cmaes (the wedge suspect) last.
4. ``bench_profile.py --trace traces/r03`` — xplane capture, last:
   it adds nothing numeric and profiling has its own wedge risk.

Usage: ``python tpu_capture.py`` (checks the relay first, exits 0 with
a message if it is down; safe to re-run — steps append, never clobber).
"""

import datetime
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
from _axon_probe import axon_tunnel_reachable  # noqa: E402

EVIDENCE = os.path.join(HERE, "TPU_EVIDENCE_r03.jsonl")

STEPS = [
    # hw-kernel semantics validated on-chip BEFORE any throughput
    # number is recorded (the pytest suite pins CPU and cannot).
    # Ordering lesson from the 2026-07-31 03:18-04:02 window: the
    # five-config suite must precede the profile — the profile's eight
    # tunnel compiles ate the whole window and its timeout lost them
    # all (profile is now incremental via --out, but the suite rows
    # are the higher-value artifact).
    ("_tpu_hw_check.py", [sys.executable, "_tpu_hw_check.py"], 1200),
    ("bench.py", [sys.executable, "bench.py"], 2400),
    ("bench_suite.py", [sys.executable, "bench_suite.py", "--isolated",
                        "--out", "TPU_SUITE_r03.jsonl"], 9000),
    ("bench_profile.py", [sys.executable, "bench_profile.py",
                          "--out", "TPU_PROFILE_r03.jsonl"], 3600),
    ("bench_profile.py --trace", [sys.executable, "bench_profile.py",
                                  "--trace", "traces/r03"], 2400),
]

# steps whose single successful capture this round makes a re-run
# pointless (validation, not measurement) — skipped when the evidence
# file already records them ok
ONE_SHOT = {"_tpu_hw_check.py"}


def already_captured(step):
    if step not in ONE_SHOT or not os.path.exists(EVIDENCE):
        return False
    for line in open(EVIDENCE):
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if d.get("script") == step and "results" in d:
            return True
    return False


def log(step, payload):
    line = {"ts": datetime.datetime.now(datetime.timezone.utc).isoformat(),
            "script": step, **payload}
    with open(EVIDENCE, "a") as f:
        f.write(json.dumps(line) + "\n")
    print(json.dumps(line), flush=True)


def commit(step):
    paths = [p for p in ("TPU_EVIDENCE_r03.jsonl", "TPU_SUITE_r03.jsonl",
                         "TPU_PROBE_LOG.jsonl", "traces")
             if os.path.exists(os.path.join(HERE, p))]
    subprocess.run(["git", "add", "-A"] + paths,
                   cwd=HERE, capture_output=True)
    subprocess.run(["git", "commit", "-q", "-m",
                    f"TPU evidence: {step} captured\n\n"
                    "No-Verification-Needed: measurement artifacts only"],
                   cwd=HERE, capture_output=True)


def main():
    if not axon_tunnel_reachable():
        print("relay unreachable; nothing captured")
        return
    for step, cmd, timeout_s in STEPS:
        if already_captured(step):
            print(f"{step}: already captured this round, skipping",
                  flush=True)
            continue
        if not axon_tunnel_reachable():
            log(step, {"skipped": "relay died mid-window"})
            commit(step)
            break
        try:
            r = subprocess.run(cmd, cwd=HERE, capture_output=True,
                               text=True, timeout=timeout_s)
            results = []
            for ln in r.stdout.splitlines():
                if ln.startswith("{"):
                    try:
                        results.append(json.loads(ln))
                    except json.JSONDecodeError:
                        results.append({"unparseable": ln[-200:]})
            if results:
                log(step, {"results": results})
            else:
                log(step, {"error": f"rc={r.returncode}, no JSON; "
                                    f"stderr tail: {(r.stderr or '')[-300:]}"})
        except subprocess.TimeoutExpired:
            log(step, {"error": f"timeout after {timeout_s}s"})
        commit(step)


if __name__ == "__main__":
    main()
