"""GP interpreter race — ``bench.py --gp-race``.

The GP margin was the framework's weak flank (VERDICT r5 weak #4): a
1.7× CPU ratio swinging ±40% with box load, measured in different
sessions from its denominator. This harness makes the number mean
something on a shared box by racing everything BACK-TO-BACK in one
session (VERDICT weak #8):

1. **reference proxy** — the symbreg config through the compat layer's
   list-based GP (per-individual stack evaluation, the reference's
   architecture; the reference tree itself is not vendored, and the
   compat path's explicit stack is if anything faster than the
   reference's string-codegen ``eval``). The committed r1 reference
   measurement (3.08 gens/s, BASELINE.md) is reported alongside as the
   cross-round denominator.
2. **ours/old** — the committed formulation: jit'd ``lax.scan``
   generation loop over the full-vocab scan interpreter.
3. **ours/new** — the host-dispatch loop (gp/loop.py) with the
   specialized interpreter: live-vocab masks + unique-genome dedup +
   opcode-major grouped dispatch + algebraic height limits.
4. **component deltas** on the same evolved population: mask vs
   full-vocab, grouped vs scan, dedup on/off, points-tiled vs untiled
   at large point counts — so the headline decomposes into its
   mechanisms instead of being one opaque ratio.

A quality gate (best MSE on the quartic) runs before any timing is
reported: a fast-but-wrong interpreter must not win a race. Output is
one JSON line per row; ``main()`` commits them to BENCH_GP.json in the
BENCH_r*.json shape (``tail`` of JSON lines) so ``bench_report.py
--tripwire`` can diff rounds live-vs-live.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import bench  # noqa: F401  (platform forcing side effects)
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu import gp, ops
from deap_tpu.algorithms import evaluate_invalid, var_and
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import gather, init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.gp.loop import make_symbreg_loop
from deap_tpu.support.profiling import sync

#: CPU reference DEAP, measured 2026-07-29 on the round-1 box
#: (BASELINE.md "GP symbreg pop=4096 pts=256") — the cross-round
#: denominator; the in-session proxy row is the same-box one.
REFERENCE_GPS = 3.08

POP, ML, P = 4096, 64, 256
NGEN = 50
REPS = 3
MSE_GATE = 0.05


def _X_y():
    X = jnp.linspace(-1.0, 1.0, P, endpoint=False)[:, None]
    y = X[:, 0] ** 4 + X[:, 0] ** 3 + X[:, 0] ** 2 + X[:, 0]
    return X, y


def _init_genomes(pset, key=1):
    gen = gp.gen_half_and_half(pset, ML, 1, 2)
    return jax.vmap(gen)(jax.random.split(jax.random.key(key), POP))


def _median(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2]


# ------------------------------------------------------ reference proxy ----

def ref_proxy_gps(ngen: int = 4) -> dict:
    """The same config through compat's list-based GP — one fitness
    call per individual (numpy-vectorised over the 256 points, which is
    GENEROUS: the reference example evaluates point-by-point)."""
    import operator
    import random

    from deap_tpu.compat import base, creator, tools
    from deap_tpu.compat import gp as cgp

    pset = cgp.PrimitiveSet("MAIN", 1)
    pset.addPrimitive(np.add, 2, name="add")
    pset.addPrimitive(np.subtract, 2, name="sub")
    pset.addPrimitive(np.multiply, 2, name="mul")
    pset.addPrimitive(
        lambda a, b: np.where(b == 0.0, 1.0,
                              a / np.where(b == 0.0, 1.0, b)),
        2, name="protectedDiv")
    pset.addPrimitive(np.negative, 1, name="neg")
    pset.addPrimitive(np.cos, 1, name="cos")
    pset.addPrimitive(np.sin, 1, name="sin")
    pset.addEphemeralConstant("rand101",
                              lambda: random.uniform(-1.0, 1.0))

    creator.create("FitnessMin", base.Fitness, weights=(-1.0,))
    creator.create("IndividualGP", cgp.PrimitiveTree,
                   fitness=creator.FitnessMin)
    xs = np.linspace(-1.0, 1.0, P, endpoint=False)
    ys = xs ** 4 + xs ** 3 + xs ** 2 + xs

    def evaluate(ind):
        f = cgp.compile(ind, pset)
        pred = f(xs)
        return (float(np.mean((pred - ys) ** 2)),)

    tb = base.Toolbox()
    tb.register("expr", cgp.genHalfAndHalf, pset=pset, min_=1, max_=2)
    tb.register("individual", tools.initIterate, creator.IndividualGP,
                tb.expr)
    tb.register("population", tools.initRepeat, list, tb.individual)
    tb.register("evaluate", evaluate)
    tb.register("select", tools.selTournament, tournsize=3)
    tb.register("mate", cgp.cxOnePoint)
    tb.register("expr_mut", cgp.genFull, min_=0, max_=2)
    tb.register("mutate", cgp.mutUniform, expr=tb.expr_mut, pset=pset)
    limit = cgp.staticLimit(key=operator.attrgetter("height"),
                            max_value=17)
    tb.decorate("mate", limit)
    tb.decorate("mutate", limit)

    random.seed(318)
    pop = tb.population(n=POP)
    for ind in pop:
        ind.fitness.values = tb.evaluate(ind)
    from deap_tpu.compat.algorithms import varAnd

    t0 = time.perf_counter()
    for _ in range(ngen):
        off = tb.select(pop, POP)
        off = varAnd(off, tb, 0.5, 0.1)
        for ind in off:
            if not ind.fitness.valid:
                ind.fitness.values = tb.evaluate(ind)
        pop = off
    dt = time.perf_counter() - t0
    return {"metric": "gp_ref_proxy_generations_per_sec",
            "value": round(ngen / dt, 3), "unit": "gens/sec",
            "ngen": ngen,
            "note": ("compat list-GP, per-individual stack eval, "
                     "numpy-vectorised points (generous to the "
                     "reference, whose example evaluates per point); "
                     "reference tree not vendored — committed r1 "
                     "measurement is the 3.08 denominator")}


# --------------------------------------------------- ours, old and new ----

def _scan_loop_runner(pset, X, y, mode="scan", specialize="none"):
    """The committed formulation: whole run as one jit'd lax.scan."""
    evaluate = gp.make_population_evaluator(
        pset, ML, lambda pred, y_: jnp.mean((pred - y_) ** 2),
        mode=mode, specialize=specialize)
    expr_mut = gp.make_generator(pset, 32, 0, 2, "full")
    limit = gp.static_limit(lambda g: gp.tree_height(g, pset), 17)
    tb = Toolbox()
    tb.register("evaluate", lambda gs: -evaluate(gs, X, y))
    tb.register("mate", limit(gp.make_cx_one_point(pset)))
    tb.register("mutate", limit(gp.make_mut_uniform(pset, expr_mut)))
    tb.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(1), POP,
                          gp.gen_half_and_half(pset, ML, 1, 2),
                          FitnessSpec((1.0,)))
    pop = evaluate_invalid(pop, tb.evaluate)

    @jax.jit
    def run(key, pop):
        def step(p, k):
            k1, k2 = jax.random.split(k)
            idx = tb.select(k1, p.wvalues, POP)
            off = var_and(k2, gather(p, idx), tb, 0.5, 0.1)
            return evaluate_invalid(off, tb.evaluate), 0

        p, _ = lax.scan(step, pop, jax.random.split(key, NGEN))
        return p

    return run, pop


def old_loop_row(pset, X, y) -> dict:
    run, pop = _scan_loop_runner(pset, X, y)
    sync(run(jax.random.key(100), pop).wvalues)      # compile + warm
    times = []
    for r in range(REPS):
        t0 = time.perf_counter()
        endpop = run(jax.random.key(101 + r), pop)
        sync(endpop.wvalues)
        times.append(time.perf_counter() - t0)
    mse = float(-jnp.max(endpop.wvalues[:, 0]))
    return {"metric": "gp_symbreg_scan_loop_generations_per_sec",
            "value": round(NGEN / _median(times), 3), "unit": "gens/sec",
            "impl": "scan_loop_full_vocab", "ngen": NGEN,
            "n_samples": REPS,
            "spread_pct": round(100 * (max(times) - min(times))
                                / _median(times), 1),
            "best_mse": round(mse, 6)}


def new_loop_row(pset, X, y, mode="grouped") -> dict:
    run = make_symbreg_loop(pset, ML, X, y)
    genomes = _init_genomes(pset)
    # two warm runs with distinct seeds: different growth trajectories
    # hit different lattice classes, and a class first seen inside a
    # timed rep would charge its compile to that sample
    run(jax.random.key(100), genomes, NGEN)
    run(jax.random.key(1100), genomes, NGEN)
    times, last = [], None
    for rep in range(REPS):
        t0 = time.perf_counter()
        last = run(jax.random.key(101 + rep), genomes, NGEN)
        times.append(time.perf_counter() - t0)
    mse = -last["best_fitness"]
    if mse > MSE_GATE:
        raise AssertionError(
            f"gp-race quality gate: best MSE {mse:.4f} > {MSE_GATE}")
    gps = NGEN / _median(times)
    return {"metric": "gp_symbreg_pop4096_pts256_generations_per_sec",
            "value": round(gps, 3), "unit": "gens/sec",
            "impl": "host_loop_grouped_dedup",
            "vs_baseline": round(gps / REFERENCE_GPS, 1),
            "ngen": NGEN, "n_samples": REPS,
            "spread_pct": round(100 * (max(times) - min(times))
                                / _median(times), 1),
            "best_mse": round(mse, 6),
            "nevals_per_gen": round(float(np.mean(last["nevals"][1:])),
                                    1)}


# ----------------------------------------------------- component deltas ----

def _evolved_population(pset, X, y):
    run = make_symbreg_loop(pset, ML, X, y)
    r = run(jax.random.key(55), _init_genomes(pset), 40)
    return r["genomes"]


def _time_eval(fn, *args, reps=5):
    jax.block_until_ready(fn(*args))
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return _median(times) * 1000


def component_rows(pset, X, y) -> list:
    """Eval-only deltas on one evolved (bloated, converged) population:
    every variant verified bit-identical to the full-vocab scan BEFORE
    it is timed."""
    genomes = _evolved_population(pset, X, y)
    rows = []
    ref = gp.make_batch_interpreter(pset, ML, specialize="none")
    jref = jax.jit(ref)
    want = np.asarray(jref(genomes, X))
    variants = [
        ("scan_full_vocab", jref, True),
        ("scan_masked",
         gp.make_batch_interpreter(pset, ML, mode="scan", dedup=False),
         False),
        ("scan_masked_dedup",
         gp.make_batch_interpreter(pset, ML, mode="scan"), False),
        ("grouped",
         gp.make_batch_interpreter(pset, ML, mode="grouped",
                                   dedup=False), False),
        ("grouped_dedup",
         gp.make_batch_interpreter(pset, ML, mode="grouped"), False),
    ]
    for name, fn, _ in variants:
        got = np.asarray(fn(genomes, X))
        if not (got == want).all():
            raise AssertionError(f"gp-race parity gate: {name} != scan")
        rows.append({"metric": "gp_interp_eval_ms", "impl": name,
                     "value": round(_time_eval(fn, genomes, X), 2),
                     "unit": "ms", "pop": POP, "points": P})
    lens = np.asarray(genomes["length"])
    rows[-1]["n_unique"] = int(len(set(
        np.asarray(genomes["nodes"])[i, :lens[i]].tobytes()
        + np.asarray(genomes["consts"])[i, :lens[i]].tobytes()
        for i in range(POP))))

    # points-axis tiling at large P, on the SCAN path — the per-tree
    # out[T, P] buffer leaves cache untiled (36·32768·4 ≈ 4.7 MB/tree
    # here). Grouped needs no points tiling on CPU: its chunk loop is
    # already [chunk, P]-blocked, and measured tiles only add per-tile
    # dispatch (272 → 407 ms at pop=512/P=8192) — tile grouped only to
    # bound buffer MEMORY, not for speed.
    bigP = 32768
    Xb = jnp.linspace(-1.0, 1.0, bigP, endpoint=False)[:, None]
    sub = jax.tree_util.tree_map(lambda a: a[:128], genomes)
    untiled = gp.make_batch_interpreter(pset, ML, mode="scan",
                                        dedup=False)
    tiled = gp.make_batch_interpreter(pset, ML, mode="scan",
                                      dedup=False, points_tile=4096)
    wu = np.asarray(untiled(sub, Xb))
    wt = np.asarray(tiled(sub, Xb))
    if not (wu == wt).all():
        raise AssertionError("gp-race parity gate: tiled != untiled")
    for name, fn in (("scan_untiled", untiled),
                     ("scan_tiled_4096", tiled)):
        rows.append({"metric": "gp_interp_eval_bigP_ms", "impl": name,
                     "value": round(_time_eval(fn, sub, Xb, reps=3), 2),
                     "unit": "ms", "pop": 128, "points": bigP})
    return rows


# --------------------------------------------------------- suite entry ----

def suite_gps() -> float:
    """bench_suite's gp_symbreg config: a SHORT probe races the
    interpreter schedules on the current backend — scan loop, sweep
    loop (accelerator schedule), host-dispatch grouped loop — then the
    winner alone is measured at full length with the suite's
    mean-of-REPS protocol. The probe keeps the staged TPU race inside
    minutes (it used to measure every mode at full length)."""
    pset = gp.math_set(n_args=1)
    pset.arity_table()
    X, y = _X_y()
    probe_ngen = 6
    cands = {}

    run_scan, pop = _scan_loop_runner(pset, X, y)
    sync(run_scan(jax.random.key(9), pop).wvalues)
    t0 = time.perf_counter()
    sync(run_scan(jax.random.key(10), pop).wvalues)
    cands["scan"] = NGEN / (time.perf_counter() - t0)

    if jax.default_backend() == "tpu":
        run_sw, pop_sw = _scan_loop_runner(pset, X, y, mode="sweep")
        sync(run_sw(jax.random.key(9), pop_sw).wvalues)
        t0 = time.perf_counter()
        sync(run_sw(jax.random.key(10), pop_sw).wvalues)
        cands["sweep"] = NGEN / (time.perf_counter() - t0)

    hrun = make_symbreg_loop(pset, ML, X, y)
    genomes = _init_genomes(pset)
    hrun(jax.random.key(9), genomes, probe_ngen)
    t0 = time.perf_counter()
    hrun(jax.random.key(10), genomes, probe_ngen)
    cands["grouped_host"] = probe_ngen / (time.perf_counter() - t0)

    winner = max(cands, key=cands.get)
    reps = []
    for rep in range(3):
        if winner == "grouped_host":
            t0 = time.perf_counter()
            hrun(jax.random.key(20 + rep), genomes, NGEN)
            reps.append(NGEN / (time.perf_counter() - t0))
        else:
            run = run_scan if winner == "scan" else run_sw
            t0 = time.perf_counter()
            sync(run(jax.random.key(20 + rep), pop).wvalues)
            reps.append(NGEN / (time.perf_counter() - t0))
    return float(np.mean(reps))


# ----------------------------------------------------------------- main ----

def race_rows() -> list:
    pset = gp.math_set(n_args=1)
    pset.arity_table()
    X, y = _X_y()
    rows = [ref_proxy_gps()]
    rows.append(old_loop_row(pset, X, y))
    rows.append(new_loop_row(pset, X, y))
    new, old = rows[2]["value"], rows[1]["value"]
    rows.append({
        "metric": "gp_race_new_vs_old", "value": round(new / old, 2),
        "unit": "x", "note": "same-session live-vs-live"})
    rows.append({
        "metric": "gp_race_new_vs_ref_proxy",
        "value": round(new / rows[0]["value"], 2), "unit": "x"})
    rows.extend(component_rows(pset, X, y))
    return rows


def main(out_path="BENCH_GP.json"):
    backend = jax.default_backend()
    t0 = time.perf_counter()
    rows = race_rows()
    env = {"jax": jax.__version__, "backend": backend,
           "device_kind": jax.devices()[0].device_kind,
           "n_cores": os.cpu_count()}
    for row in rows:
        row.setdefault("backend", backend)
        print(json.dumps(row), flush=True)
    report = {
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
        "env": env,
        "config": {"pop": POP, "max_len": ML, "points": P,
                   "ngen": NGEN, "reps": REPS,
                   "reference_gps_r1": REFERENCE_GPS},
        "race_seconds": round(time.perf_counter() - t0, 1),
        "tail": "\n".join(json.dumps(r) for r in rows),
    }
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1)
    print(f"wrote {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "BENCH_GP.json")
