"""Append a timestamped axon-relay probe result to TPU_PROBE_LOG.jsonl.

VERDICT r2 item 1 asks for a committed probe log when the relay stays
dead, so the driver can distinguish "unproven" from "unprovable this
round". One JSON line per probe: {ts, port_open, reachable}.
"""

import datetime
import json
import os
import socket
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _axon_probe import RELAY_PORTS, axon_tunnel_reachable

LOG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "TPU_PROBE_LOG.jsonl")


def probe_once() -> dict:
    port_open = False
    for port in RELAY_PORTS:
        s = socket.socket()
        s.settimeout(1)
        try:
            s.connect(("127.0.0.1", port))
            port_open = True
            break
        except OSError:
            pass
        finally:
            s.close()
    reachable = axon_tunnel_reachable() if port_open else False
    rec = {
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(),
        "port_open": port_open,
        "reachable": reachable,
    }
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


if __name__ == "__main__":
    print(json.dumps(probe_once()))
