"""Render the round's captured TPU evidence as markdown tables.

Reads the artifacts the capture queue produces (headline rows in
``TPU_EVIDENCE_{ROUND}.jsonl``, suite rows in ``TPU_SUITE_{ROUND}.jsonl``,
profile rows in ``TPU_PROFILE_{ROUND}.jsonl``) and prints BASELINE.md-
ready tables, so summarising a relay window costs seconds, not window
minutes. Pure file reading — no jax, safe to run any time.

Usage: ``python bench_report.py``
"""

import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
from tpu_capture import (  # noqa: E402
    COMPONENT_NAMES,
    SUITE_CONFIG_NAMES,
    SUITE_EXTRAPOLATED,
    SUITE_REF,
    headline_rows,
    profile_resolved,
    profile_rows,
    suite_rows,
)


def main() -> None:
    rows = headline_rows()
    print("## Headline (OneMax pop=100k)\n")
    if rows:
        print("| measured at | gens/sec | vs CPU reference | candidates |")
        print("|---|---|---|---|")
        for r in sorted(rows, key=lambda r: r["measured_at"] or ""):
            print(f"| {r['measured_at']} | **{r['value']}** | "
                  f"{r.get('vs_baseline', '?')}× | "
                  f"{r.get('n_candidates', '?')} |")
    else:
        print("*(no TPU headline captured yet)*")

    print("\n## Suite configs\n")
    suite = suite_rows()
    print("| config | TPU gens/sec | reference CPU | speedup |")
    print("|---|---|---|---|")
    for name in SUITE_CONFIG_NAMES:
        r = suite.get(f"{name}_generations_per_sec")
        ref = SUITE_REF[name]
        # extrapolation is a static property of the reference number,
        # not of the captured row — mark it on pending rows too
        extra = " (ref extrapolated)" if name in SUITE_EXTRAPOLATED else ""
        if r:
            print(f"| {name} | **{r['value']}** | {ref:.4g}{extra} | "
                  f"{r.get('vs_baseline', '?')}× |")
        else:
            print(f"| {name} | *(pending)* | {ref:.4g}{extra} | |")

    print("\n## Generation-step profile (ms/gen, pop=100k)\n")
    prof = {c: r["ms_per_gen"] for c, r in profile_rows().items()}
    resolved = profile_resolved()
    print("| component | ms/gen |")
    print("|---|---|")
    for name in COMPONENT_NAMES:
        v = prof.get(name)
        if v is None and name in resolved:
            # errored on-chip: surface the verdict, don't show pending
            # (sanitised — Mosaic errors carry newlines and pipes that
            # would break the markdown row)
            err = resolved[name]["error"].replace("\n", " ")
            v = "failed: " + err.replace("|", "\\|")[:80]
        print(f"| {name} | {v if v is not None else '*(pending)*'} |")
    if prof.get("full_binned"):
        parts = {k: v for k, v in prof.items()
                 if k in ("select_binned", "gather_random",
                          "kernel_fused_packed")}
        if len(parts) == 3:
            gap = prof["full_binned"] - sum(parts.values())
            print(f"\nfull_binned − (select + gather + kernel) = "
                  f"{gap:.4f} ms/gen of fusion/overhead delta.")


if __name__ == "__main__":
    main()
