"""Render the round's captured TPU evidence as markdown tables.

Reads the artifacts the capture queue produces (headline rows in
``TPU_EVIDENCE_{ROUND}.jsonl``, suite rows in ``TPU_SUITE_{ROUND}.jsonl``,
profile rows in ``TPU_PROFILE_{ROUND}.jsonl``) and prints BASELINE.md-
ready tables, so summarising a relay window costs seconds, not window
minutes. Pure file reading — no jax, safe to run any time.

Usage:
    python bench_report.py               # evidence tables (default)
    python bench_report.py --tripwire    # regression diff of the two
                                         # most recent BENCH_r*.json;
                                         # exit 1 if a live-vs-live
                                         # metric regressed > 10%
    python bench_report.py --journal F   # summarise a run journal
                                         # (telemetry JSONL): compiles/
                                         # retraces, span aggregates,
                                         # meter first/last rows
"""

import glob
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
from tpu_capture import (  # noqa: E402
    COMPONENT_NAMES,
    SUITE_CONFIG_NAMES,
    SUITE_EXTRAPOLATED,
    SUITE_REF,
    headline_rows,
    profile_resolved,
    profile_rows,
    suite_rows,
)


# ------------------------------------------------------------ tripwire ----

#: fractional worsening beyond which a live-vs-live row trips
TRIPWIRE_THRESHOLD = 0.10

#: per-unit direction: is a larger value better?
_HIGHER_IS_BETTER = {"gens/sec": True, "x": True, "seconds": False}


def _bench_rows(path: str) -> dict:
    """metric -> row dicts parsed out of a committed BENCH_*.json's
    ``tail`` (one JSON line per metric; non-JSON lines skipped)."""
    with open(path) as fh:
        data = json.load(fh)
    rows = {}
    for ln in data.get("tail", "").splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            d = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if "metric" in d:
            # --nd3 style files repeat a metric per impl — key on both
            key = d["metric"] + (":" + d["impl"] if "impl" in d else "")
            rows[key] = d
    return rows


def tripwire(threshold: float = TRIPWIRE_THRESHOLD) -> int:
    """Diff the two most recent committed ``BENCH_r*.json`` files and
    flag regressions. Cached-replay rows (``cached: true`` /
    ``tpu-cached`` backend) never trip — a replay of an old capture
    carries no new information about the current code; the env
    fingerprint bench.py now stamps makes the distinction visible in
    the table. Returns the number of tripped metrics (the process exit
    code)."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_r*.json")))
    if len(files) < 2:
        print("tripwire: need >= 2 committed BENCH_r*.json files, "
              f"found {len(files)}")
        return 0
    prev_path, cur_path = files[-2], files[-1]
    prev, cur = _bench_rows(prev_path), _bench_rows(cur_path)
    print(f"## Bench tripwire: {os.path.basename(prev_path)} → "
          f"{os.path.basename(cur_path)}\n")
    print("| metric | prev | cur | Δ% | status |")
    print("|---|---|---|---|---|")
    tripped = 0
    for key in sorted(set(prev) & set(cur)):
        p, c = prev[key], cur[key]
        pv, cv = p.get("value"), c.get("value")
        if not isinstance(pv, (int, float)) or not isinstance(cv, (int, float)):
            continue
        delta_pct = 100.0 * (cv - pv) / pv if pv else float("inf")
        replay = (p.get("cached") or c.get("cached")
                  or "cached" in str(p.get("backend", ""))
                  or "cached" in str(c.get("backend", "")))
        higher_better = _HIGHER_IS_BETTER.get(c.get("unit"), True)
        worsened = (cv < pv * (1 - threshold)) if higher_better else (
            cv > pv * (1 + threshold))
        if replay:
            status = "replay (not comparable)"
        elif worsened:
            status = "**REGRESSION**"
            tripped += 1
        else:
            status = "ok"
        print(f"| {key} | {pv} | {cv} | {delta_pct:+.1f}% | {status} |")
    missing = sorted(set(prev) - set(cur))
    if missing:
        print(f"\nmetrics dropped since {os.path.basename(prev_path)}: "
              + ", ".join(missing))
    if tripped:
        print(f"\n{tripped} metric(s) regressed beyond "
              f"{threshold:.0%} — failing.")
    return tripped


# ------------------------------------------------------- journal reader ----

def _read_jsonl(path: str) -> list:
    out = []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                continue
    return out


def journal_report(path: str) -> None:
    """Summarise a telemetry run journal (the JSONL RunJournal format;
    local parser — this tool must stay importable without jax)."""
    events = _read_jsonl(path)
    kinds = {}
    for e in events:
        kinds[e.get("kind", "?")] = kinds.get(e.get("kind", "?"), 0) + 1
    print(f"## Run journal: {os.path.basename(path)}\n")
    header = next((e for e in events if e.get("kind") == "header"), None)
    if header:
        env = header.get("env", {})
        print("- env: " + ", ".join(f"{k}={v}" for k, v in env.items()))
        if "toolbox" in header:
            print(f"- toolbox digest: {header['toolbox'].get('digest')}")
    print("- events: " + ", ".join(
        f"{k}×{v}" for k, v in sorted(kinds.items())))
    retraces = [e for e in events if e.get("kind") == "retrace"]
    if retraces:
        total = sum(e.get("dur_s", 0.0) for e in retraces)
        print(f"- **{len(retraces)} retrace(s)** after steady, "
              f"{total:.3f}s recompiling — investigate shape/closure "
              "churn")
    meters = [e for e in events if e.get("kind") == "meter"]
    if meters:
        drop = ("t", "kind")
        fmt = lambda e: ", ".join(f"{k}={v}" for k, v in e.items()
                                  if k not in drop and not isinstance(v, list))
        print(f"- meter rows: {len(meters)} (first: {fmt(meters[0])}; "
              f"last: {fmt(meters[-1])})")
    spans = [e for e in events if e.get("kind") == "span"]
    if spans:
        print("\n| span | count | total s | p50 s | p99 s |")
        print("|---|---|---|---|---|")
        for s in sorted(spans, key=lambda s: -s.get("total_s", 0)):
            print(f"| {s.get('name')} | {s.get('count')} | "
                  f"{s.get('total_s', 0):.6f} | {s.get('p50_s', 0):.6f} | "
                  f"{s.get('p99_s', 0):.6f} |")


def main() -> None:
    rows = headline_rows()
    print("## Headline (OneMax pop=100k)\n")
    if rows:
        print("| measured at | gens/sec | vs CPU reference | candidates |")
        print("|---|---|---|---|")
        for r in sorted(rows, key=lambda r: r["measured_at"] or ""):
            print(f"| {r['measured_at']} | **{r['value']}** | "
                  f"{r.get('vs_baseline', '?')}× | "
                  f"{r.get('n_candidates', '?')} |")
    else:
        print("*(no TPU headline captured yet)*")

    print("\n## Suite configs\n")
    suite = suite_rows()
    print("| config | TPU gens/sec | reference CPU | speedup |")
    print("|---|---|---|---|")
    for name in SUITE_CONFIG_NAMES:
        r = suite.get(f"{name}_generations_per_sec")
        ref = SUITE_REF[name]
        # extrapolation is a static property of the reference number,
        # not of the captured row — mark it on pending rows too
        extra = " (ref extrapolated)" if name in SUITE_EXTRAPOLATED else ""
        if r:
            print(f"| {name} | **{r['value']}** | {ref:.4g}{extra} | "
                  f"{r.get('vs_baseline', '?')}× |")
        else:
            print(f"| {name} | *(pending)* | {ref:.4g}{extra} | |")

    print("\n## Generation-step profile (ms/gen, pop=100k)\n")
    prof = {c: r["ms_per_gen"] for c, r in profile_rows().items()}
    resolved = profile_resolved()
    print("| component | ms/gen |")
    print("|---|---|")
    for name in COMPONENT_NAMES:
        v = prof.get(name)
        if v is None and name in resolved:
            # errored on-chip: surface the verdict, don't show pending
            # (sanitised — Mosaic errors carry newlines and pipes that
            # would break the markdown row)
            err = resolved[name]["error"].replace("\n", " ")
            v = "failed: " + err.replace("|", "\\|")[:80]
        print(f"| {name} | {v if v is not None else '*(pending)*'} |")
    if prof.get("full_binned"):
        parts = {k: v for k, v in prof.items()
                 if k in ("select_binned", "gather_random",
                          "kernel_fused_packed")}
        if len(parts) == 3:
            gap = prof["full_binned"] - sum(parts.values())
            print(f"\nfull_binned − (select + gather + kernel) = "
                  f"{gap:.4f} ms/gen of fusion/overhead delta.")


if __name__ == "__main__":
    if "--tripwire" in sys.argv:
        sys.exit(1 if tripwire() else 0)
    elif "--journal" in sys.argv:
        journal_report(sys.argv[sys.argv.index("--journal") + 1])
    else:
        main()
