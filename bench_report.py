"""Render the round's captured TPU evidence as markdown tables.

Reads the artifacts the capture queue produces (headline rows in
``TPU_EVIDENCE_{ROUND}.jsonl``, suite rows in ``TPU_SUITE_{ROUND}.jsonl``,
profile rows in ``TPU_PROFILE_{ROUND}.jsonl``) and prints BASELINE.md-
ready tables, so summarising a relay window costs seconds, not window
minutes. Pure file reading — no jax, safe to run any time.

Usage:
    python bench_report.py               # evidence tables (default)
    python bench_report.py --tripwire    # regression diff of the two
                                         # most recent BENCH_r*.json;
                                         # exit 1 if a live-vs-live
                                         # metric regressed > 10%, or
                                         # if probe overhead in the
                                         # latest BENCH_PROBES*.json
                                         # exceeds 3% (paired rows,
                                         # same session)
    python bench_report.py --journal F   # summarise a run journal
                                         # (telemetry JSONL): compiles/
                                         # retraces, span aggregates,
                                         # meter first/last rows
    python bench_report.py --health F    # full run-health report for a
                                         # journal: per-probe
                                         # sparklines, alarm timeline,
                                         # span p50/p99 table
                                         # (deap_tpu/telemetry/
                                         # report.py, loaded standalone
                                         # — still no jax import)
"""

import glob
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)
from tpu_capture import (  # noqa: E402
    COMPONENT_NAMES,
    SUITE_CONFIG_NAMES,
    SUITE_EXTRAPOLATED,
    SUITE_REF,
    headline_rows,
    profile_resolved,
    profile_rows,
    suite_rows,
)


# ------------------------------------------------------------ tripwire ----

#: fractional worsening beyond which a live-vs-live row trips
TRIPWIRE_THRESHOLD = 0.10

#: per-unit direction: is a larger value better?
_HIGHER_IS_BETTER = {"gens/sec": True, "x": True, "seconds": False,
                     "ms": False}


def _bench_rows(path: str) -> dict:
    """metric -> row dicts parsed out of a committed BENCH_*.json's
    ``tail`` (one JSON line per metric; non-JSON lines skipped)."""
    with open(path) as fh:
        data = json.load(fh)
    rows = {}
    for ln in data.get("tail", "").splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            d = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if "metric" in d:
            # --nd3 style files repeat a metric per impl — key on both
            key = d["metric"] + (":" + d["impl"] if "impl" in d else "")
            rows[key] = d
    return rows


def _gp_race_files() -> list:
    """Committed --gp-race files. BENCH_GP_SERVING*.json (the batched
    serving pair, :func:`gp_serving_tripwire`) shares the BENCH_GP
    prefix and must not shadow the race history."""
    return sorted(
        f for f in glob.glob(os.path.join(HERE, "BENCH_GP*.json"))
        if not os.path.basename(f).startswith("BENCH_GP_SERVING"))


def gp_tripwire(threshold: float = TRIPWIRE_THRESHOLD) -> int:
    """The gp_symbreg paired-row check. BENCH_GP.json carries the old
    scan-loop and the new specialized-loop throughputs measured
    back-to-back in the SAME session (bench.py --gp-race) — the only
    pairing that means anything on a box whose load swings ±40%
    (VERDICT weak #8). Trips when the specialized interpreter falls
    more than ``threshold`` below the scan loop it replaced
    (live-vs-live, same session), and diffs consecutive committed
    BENCH_GP*.json files with the same rules as the headline
    tripwire. Returns the number of tripped rows."""
    files = _gp_race_files()
    if not files:
        print("gp tripwire: no committed BENCH_GP*.json yet")
        return 0
    tripped = 0
    cur = _bench_rows(files[-1])

    def find(metric):
        # rows carry an impl tag, so keys are "metric:impl"
        return next((cur[k] for k in cur
                     if k == metric or k.startswith(metric + ":")), None)

    new = find("gp_symbreg_pop4096_pts256_generations_per_sec")
    old = find("gp_symbreg_scan_loop_generations_per_sec")
    print(f"\n## GP paired rows ({os.path.basename(files[-1])})\n")
    if new and old and isinstance(new.get("value"), (int, float)):
        ratio = new["value"] / old["value"]
        ok = ratio >= (1 - threshold)
        print(f"- specialized loop {new['value']} vs scan loop "
              f"{old['value']} gens/s, same session: {ratio:.2f}× "
              + ("ok" if ok else "**REGRESSION** (specialized "
                 "interpreter slower than the scan loop it replaced)"))
        tripped += 0 if ok else 1
    else:
        print("- paired rows missing from latest BENCH_GP file")
    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1], threshold)
    return tripped


def _diff_rows(prev_path: str, cur_path: str, threshold: float) -> int:
    prev, cur = _bench_rows(prev_path), _bench_rows(cur_path)
    print(f"\n## Bench tripwire: {os.path.basename(prev_path)} → "
          f"{os.path.basename(cur_path)}\n")
    print("| metric | prev | cur | Δ% | status |")
    print("|---|---|---|---|---|")
    tripped = 0
    for key in sorted(set(prev) & set(cur)):
        p, c = prev[key], cur[key]
        pv, cv = p.get("value"), c.get("value")
        if not isinstance(pv, (int, float)) or not isinstance(cv, (int, float)):
            continue
        delta_pct = 100.0 * (cv - pv) / pv if pv else float("inf")
        replay = (p.get("cached") or c.get("cached")
                  or "cached" in str(p.get("backend", ""))
                  or "cached" in str(c.get("backend", "")))
        higher_better = _HIGHER_IS_BETTER.get(c.get("unit"), True)
        worsened = (cv < pv * (1 - threshold)) if higher_better else (
            cv > pv * (1 + threshold))
        if replay:
            status = "replay (not comparable)"
        elif worsened:
            status = "**REGRESSION**"
            tripped += 1
        else:
            status = "ok"
        print(f"| {key} | {pv} | {cv} | {delta_pct:+.1f}% | {status} |")
    missing = sorted(set(prev) - set(cur))
    if missing:
        print(f"\nmetrics dropped since {os.path.basename(prev_path)}: "
              + ", ".join(missing))
    if tripped:
        print(f"\n{tripped} metric(s) regressed beyond "
              f"{threshold:.0%} — failing.")
    return tripped


#: fractional telemetry-probe overhead beyond which the probe pair trips
PROBE_OVERHEAD_THRESHOLD = 0.03


def probe_tripwire(threshold: float = PROBE_OVERHEAD_THRESHOLD) -> int:
    """The telemetry-probe overhead gate. BENCH_PROBES.json carries a
    probe-off and a probe-on headline-config row (pop=100k) measured
    back-to-back in the SAME session (bench.py --probes) — in-scan
    probes promise near-zero cost, and this is where that promise is
    enforced: trips when the probe-on run falls more than ``threshold``
    below its probe-off pair. Returns the number of tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_PROBES*.json")))
    if not files:
        print("probe tripwire: no committed BENCH_PROBES*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    off = rows.get("onemax_pop100k_probe_off_generations_per_sec")
    on = rows.get("onemax_pop100k_probe_on_generations_per_sec")
    ov = rows.get("onemax_pop100k_probe_overhead_pct")
    print(f"\n## Probe overhead ({os.path.basename(files[-1])})\n")
    if ov is not None and isinstance(ov.get("value"), (int, float)):
        # the committed row's estimator (min-of-interleaved-reps —
        # contention noise is one-sided) is the gate
        overhead = ov["value"] / 100.0
    elif (off and on and isinstance(off.get("value"), (int, float))
            and isinstance(on.get("value"), (int, float))):
        overhead = 1.0 - on["value"] / off["value"]
    else:
        print("- paired probe rows missing from latest BENCH_PROBES "
              "file")
        return 0
    ok = overhead <= threshold
    pair = ""
    if off and on:
        pair = (f"probes on {on['value']} vs off {off['value']} gens/s "
                f"(n_probe_metrics={on.get('n_probe_metrics', '?')}), ")
    print(f"- {pair}same session: {100 * overhead:+.2f}% overhead "
          + ("ok" if ok else f"**REGRESSION** (> {threshold:.0%} — "
             "an in-scan probe got expensive)"))
    if len(files) >= 2:
        return (0 if ok else 1) + _diff_rows(files[-2], files[-1],
                                             TRIPWIRE_THRESHOLD)
    return 0 if ok else 1


#: fractional speedup shortfall beyond which a fusion pair trips: the
#: SHIPPED side of a committed pair (the fused default / the auto-
#: resolved compaction) must not fall >10% below the same-session
#: alternative — the gate that lets fused/auto stay the default
FUSION_PAIR_THRESHOLD = 0.10


def fusion_tripwire(threshold: float = FUSION_PAIR_THRESHOLD) -> int:
    """The fused-variation-plane gate. BENCH_FUSION.json carries the
    unfused-vs-fused variation plane (bit-identity asserted before
    timing; the row's ``rng_bound_pct`` records how much of the step
    is shared threefry that no fusion can touch) and the
    host-vs-device GP compaction pipelines plus the ``auto``
    resolution, all same-session (bench.py --fusion). Trips when the
    fused default falls more than ``threshold`` below the unfused
    composition, or when ``compaction='auto'`` resolves more than
    ``threshold`` below the measured winner. Also diffs consecutive
    committed BENCH_FUSION files. Returns the number of tripped
    rows."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_FUSION*.json")))
    if not files:
        print("fusion tripwire: no committed BENCH_FUSION*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    tripped = 0
    print(f"\n## Fusion pairs ({os.path.basename(files[-1])})\n")
    f = rows.get("onemax_pop100k_varplane_fused_generations_per_sec")
    u = rows.get("onemax_pop100k_varplane_unfused_generations_per_sec")
    s = rows.get("onemax_pop100k_varplane_fused_speedup_x")
    if (f and u and isinstance(f.get("value"), (int, float))
            and isinstance(u.get("value"), (int, float))):
        ratio = f["value"] / u["value"]
        ok = ratio >= (1 - threshold)
        rng = (s or {}).get("rng_bound_pct")
        print(f"- fused variation plane: fused {f['value']} vs unfused "
              f"{u['value']} gens/s, same session: {ratio:.2f}×"
              + (f" (rng-bound {rng}% of the step — the bit-parity "
                 "ceiling on this backend)" if rng is not None else "")
              + (" ok" if ok else " **REGRESSION** (fused default "
                 "slower than the composition it replaced)"))
        tripped += 0 if ok else 1
    else:
        print("- fused variation plane: paired rows missing")
    auto = rows.get("gp_compaction_pop100k_auto_vs_best_x")
    if auto and isinstance(auto.get("value"), (int, float)):
        ok = auto["value"] >= (1 - threshold)
        print(f"- GP compaction auto-dispatch: {auto['value']:.2f}× of "
              f"the measured winner (resolved "
              f"{auto.get('resolved', '?')!r}) "
              + ("ok" if ok else "**REGRESSION** (auto picked a path "
                 ">10% below the same-session winner)"))
        tripped += 0 if ok else 1
    else:
        print("- GP compaction auto row missing")
    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1], TRIPWIRE_THRESHOLD)
    return tripped


#: fractional segmented-run overhead beyond which the resilience pair
#: trips — tightened from 3% to 1.5% once checkpoint double-buffering
#: (async boundary writes overlapped with the next segment's compute)
#: landed
RESILIENCE_OVERHEAD_THRESHOLD = 0.015


def resilience_tripwire(
        threshold: float = RESILIENCE_OVERHEAD_THRESHOLD) -> int:
    """The segmented-run overhead gate. BENCH_RESILIENCE.json carries a
    monolithic-scan and a ResilientRun-segmented headline-config row
    (pop=100k, per-segment fsync'd CRC checkpoints) measured
    back-to-back in the SAME session (bench.py --resilience): trips
    when the segmented run falls more than ``threshold`` below its
    monolithic pair. Returns the number of tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE,
                                          "BENCH_RESILIENCE*.json")))
    if not files:
        print("resilience tripwire: no committed BENCH_RESILIENCE*.json "
              "yet")
        return 0
    rows = _bench_rows(files[-1])
    mono = rows.get(
        "onemax_pop100k_resilience_monolithic_generations_per_sec")
    seg = rows.get(
        "onemax_pop100k_resilience_segmented_generations_per_sec")
    ov = rows.get("onemax_pop100k_resilience_overhead_pct")
    print(f"\n## Resilience overhead ({os.path.basename(files[-1])})\n")
    if ov is not None and isinstance(ov.get("value"), (int, float)):
        overhead = ov["value"] / 100.0
    elif (mono and seg and isinstance(mono.get("value"), (int, float))
            and isinstance(seg.get("value"), (int, float))):
        overhead = 1.0 - seg["value"] / mono["value"]
    else:
        print("- paired resilience rows missing from latest "
              "BENCH_RESILIENCE file")
        return 0
    ok = overhead <= threshold
    pair = ""
    if mono and seg:
        pair = (f"segmented {seg['value']} vs monolithic "
                f"{mono['value']} gens/s (segment_len="
                f"{seg.get('segment_len', '?')}, "
                f"{seg.get('n_checkpoints', '?')} checkpoints), ")
    print(f"- {pair}same session: {100 * overhead:+.2f}% overhead "
          + ("ok" if ok else f"**REGRESSION** (> {threshold:.0%} — "
             "segmented execution got expensive)"))
    if len(files) >= 2:
        return (0 if ok else 1) + _diff_rows(files[-2], files[-1],
                                             TRIPWIRE_THRESHOLD)
    return 0 if ok else 1


#: minimum batched-over-sequential aggregate-gens/sec ratios the
#: serving pairs must hold (bench.py --serving, BENCH_SERVING.json).
#: The sequential side is the steelman pre-jitted solo runner; the
#: OneMax GA bucket must clear the acceptance 5x, the CMA bucket (whose
#: batched path is bound by the 1024-lane batched eigh) its measured
#: 2.9x less noise margin.
SERVING_RATIO_GATES = {
    "serving_onemax_1k_batched_vs_sequential_x": 5.0,
    "serving_cma_1k_batched_vs_sequential_x": 2.0,
}


def serving_tripwire(gates=None) -> int:
    """The multi-tenant serving gate: each committed batched-vs-
    sequential ratio row in the latest BENCH_SERVING*.json must stay
    at or above its floor (same-session pairs — a live-vs-live
    comparison, never cached). Returns the number of tripped rows."""
    gates = dict(SERVING_RATIO_GATES if gates is None else gates)
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_SERVING*.json")))
    if not files:
        print("serving tripwire: no committed BENCH_SERVING*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    print(f"\n## Serving throughput ({os.path.basename(files[-1])})\n")
    tripped = 0
    for metric, floor in gates.items():
        row = rows.get(metric)
        if row is None or not isinstance(row.get("value"), (int, float)):
            print(f"- {metric}: **missing** from latest file")
            tripped += 1
            continue
        ok = row["value"] >= floor
        print(f"- {metric}: {row['value']}x (floor {floor}x) "
              + ("ok" if ok else
                 "**REGRESSION** (batched serving lost its edge "
                 "over sequential)"))
        tripped += 0 if ok else 1
    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1], TRIPWIRE_THRESHOLD)
    return tripped


#: the batched-GP serving gates (bench.py --gp-serving,
#: BENCH_GP_SERVING.json): the run-axis engine must hold >= 2x over
#: the steelman sequential solo loop at N=64, and the same-session
#: solo headline must stay within 10% of the committed --gp-race
#: number — the batched mode may not tax the solo path it shares
#: interpreters with
GP_SERVING_RATIO_FLOOR = 2.0
GP_SERVING_SOLO_FLOOR = 0.9

_GP_HEADLINE = "gp_symbreg_pop4096_pts256_generations_per_sec"


def gp_serving_tripwire(ratio_floor: float = GP_SERVING_RATIO_FLOOR,
                        solo_floor: float = GP_SERVING_SOLO_FLOOR
                        ) -> int:
    """The batched-GP serving gate (ISSUE 14). The latest
    BENCH_GP_SERVING*.json must show (1) the 64-tenant symbreg batch
    at or above ``ratio_floor``x the steelman sequential solo loop —
    a same-session live-vs-live pair, (2) every batched lane
    **bit-identical** to its solo run (the committed bool row — a
    throughput win that changes numerics is a bug, not a win), and
    (3) the same-session solo headline at or above ``solo_floor``x
    the committed BENCH_GP.json number. Returns the number of
    tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE,
                                          "BENCH_GP_SERVING*.json")))
    if not files:
        print("gp-serving tripwire: no committed "
              "BENCH_GP_SERVING*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    print(f"\n## GP serving ({os.path.basename(files[-1])})\n")
    tripped = 0

    ratio = rows.get("gp_serving_symbreg_64_batched_vs_sequential_x")
    if ratio is not None and isinstance(ratio.get("value"),
                                        (int, float)):
        ok = ratio["value"] >= ratio_floor
        print(f"- symbreg batched-vs-sequential: {ratio['value']}x "
              f"(floor {ratio_floor}x) "
              + ("ok" if ok else "**REGRESSION** (the run axis lost "
                 "its edge over per-tenant host dispatch)"))
        tripped += 0 if ok else 1
    else:
        print("- symbreg batched-vs-sequential row missing")
        tripped += 1

    bit = rows.get("gp_serving_bit_identical")
    if bit is not None and bit.get("value") is True:
        print(f"- batched lanes vs solo: bit-identical over "
              f"{bit.get('lanes_checked', '?')} lanes ok")
    else:
        print("- **REGRESSION**: batched GP lanes are NOT "
              "bit-identical to the solo loop (or the row is "
              "missing) — the run axis is changing numerics")
        tripped += 1

    isl = rows.get("gp_serving_island_16_batched_vs_sequential_x")
    if isl is not None and isinstance(isl.get("value"), (int, float)):
        print(f"- island batched-vs-sequential: {isl['value']}x "
              "(context row, ungated — the sequential side is "
              "already one fused scan per tenant)")

    def _find(rowmap, metric):
        # rows carry an impl tag, so keys may be "metric:impl"
        return next((rowmap[k] for k in rowmap
                     if k == metric or k.startswith(metric + ":")),
                    None)

    solo = _find(rows, _GP_HEADLINE)
    race = _gp_race_files()
    committed = _find(_bench_rows(race[-1]), _GP_HEADLINE) \
        if race else None
    if (solo and committed
            and isinstance(solo.get("value"), (int, float))
            and isinstance(committed.get("value"), (int, float))
            and committed["value"]):
        r = solo["value"] / committed["value"]
        ok = r >= solo_floor
        print(f"- same-session solo headline: {solo['value']} vs "
              f"committed {committed['value']} gens/s = {r:.2f}x "
              f"(floor {solo_floor}x) "
              + ("ok" if ok else "**REGRESSION** (the solo loop "
                 "slowed down in the build that carries the batched "
                 "mode)"))
        tripped += 0 if ok else 1
    else:
        print("- solo headline pair missing (need a committed "
              "BENCH_GP.json and the same-session row)")
        tripped += 1

    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1],
                              TRIPWIRE_THRESHOLD)
    return tripped


#: max service-vs-in-process wall overhead (percent) for the 1k-tenant
#: socket run (bench.py --service, BENCH_SERVICE.json)
SERVICE_OVERHEAD_PCT = 10.0


def service_tripwire(max_overhead_pct: float = SERVICE_OVERHEAD_PCT
                     ) -> int:
    """The network-service gate (ISSUE 11). The latest
    BENCH_SERVICE*.json must show (1) the 1k-tenant real-socket run
    within ``max_overhead_pct`` of the same jobs through the Scheduler
    in-process, (2) per-tenant results **bit-identical** across the
    socket (equal wire digests for every tenant), and (3) the bursty
    autoscaler-on run both *acting* (lane-changing
    ``autoscale_decision`` events in its journal) and *helping*
    (queue-wait p99 at or better than the autoscaler-off run).
    Returns the number of tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_SERVICE*.json")))
    if not files:
        print("service tripwire: no committed BENCH_SERVICE*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    print(f"\n## Network service ({os.path.basename(files[-1])})\n")
    tripped = 0

    ov = rows.get("service_vs_inprocess_overhead_pct")
    if ov is not None and isinstance(ov.get("value"), (int, float)):
        ok = ov["value"] <= max_overhead_pct
        print(f"- socket-vs-inprocess overhead: {ov['value']:+.2f}% "
              + ("ok" if ok else f"**REGRESSION** (> "
                 f"{max_overhead_pct:.0f}% — the front end got "
                 "expensive)"))
        tripped += 0 if ok else 1
    else:
        print("- service overhead row missing")
        tripped += 1

    bit = rows.get("service_bit_identical")
    if bit is not None and bit.get("value") is True:
        print(f"- per-tenant wire digests: bit-identical over "
              f"{bit.get('tenants_compared', '?')} tenants ok")
    else:
        print("- **REGRESSION**: service results are NOT bit-identical "
              "to in-process (or the row is missing) — the transport "
              "is changing numerics")
        tripped += 1

    imp = rows.get("service_autoscale_queue_wait_p99_improvement_x")
    on = rows.get("service_autoscale_on_queue_wait_p99_s")
    n_lane_moves = len((on or {}).get("lane_decisions") or [])
    if imp is None or not isinstance(imp.get("value"), (int, float)):
        print("- autoscale p99-improvement row missing")
        tripped += 1
    else:
        ok = imp["value"] >= 1.0 and n_lane_moves >= 1
        print(f"- autoscaler: p99 improvement {imp['value']}x with "
              f"{n_lane_moves} lane decisions "
              f"({imp.get('autoscale_decisions', '?')} total) "
              + ("ok" if ok else "**REGRESSION** (the control loop "
                 "stopped acting or stopped helping)"))
        tripped += 0 if ok else 1
    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1], TRIPWIRE_THRESHOLD)
    return tripped


#: recovery-wall budget (seconds) for the chaos gate: kill → last
#: tenant converged on the restarted service — matches
#: bench.CHAOS_RECOVERY_BUDGET_S. Tightened 120 → 30 by ISSUE 18:
#: the restarted child now takes the startup fast path (executable
#: artifact store, warm-handoff prewarm, batched WAL replay,
#: pipelined checkpoint restore)
CHAOS_RECOVERY_BUDGET_S = 30.0


def chaos_tripwire(budget_s: float = CHAOS_RECOVERY_BUDGET_S) -> int:
    """The fault-tolerance gate (ISSUE 12). The latest
    BENCH_CHAOS*.json — a mid-run ``kill -9`` of the service under 200
    live retrying tenants, supervisor restart over the same root —
    must show (1) the kill actually delivered, (2) **zero lost jobs**,
    (3) **100% wire-digest identity** against the uninterrupted
    in-process run, and (4) recovery wall time within ``budget_s``.
    Returns the number of tripped rows. (No cross-file wall-clock
    diff: recovery time is box-load noisy; the fixed budget is the
    contract.)"""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_CHAOS*.json")))
    if not files:
        print("chaos tripwire: no committed BENCH_CHAOS*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    print(f"\n## Service chaos ({os.path.basename(files[-1])})\n")
    tripped = 0

    kill = rows.get("chaos_kill_delivered")
    if kill is None or kill.get("value") is not True:
        print("- **REGRESSION**: the kill never fired (rc="
              f"{(kill or {}).get('kill_rc')}) — the run proved "
              "nothing")
        tripped += 1
    else:
        print(f"- kill -9 delivered at driver step "
              f"{kill.get('kill_at_step', '?')} ok")

    lost = rows.get("chaos_lost_jobs")
    if lost is None or lost.get("value") != 0:
        print(f"- **REGRESSION**: {(lost or {}).get('value', '?')} "
              "job(s) lost across the kill/restart (gate: 0) — the "
              "WAL/idempotency/resume chain is leaking work")
        tripped += 1
    else:
        print(f"- lost jobs: 0 of {lost.get('tenants', '?')} ok")

    ident = rows.get("chaos_digest_identity_frac")
    if ident is None or ident.get("value") != 1.0:
        print(f"- **REGRESSION**: digest identity "
              f"{(ident or {}).get('value', '?')} (gate: 1.0) — "
              "recovery is changing numerics")
        tripped += 1
    else:
        print(f"- wire digests: {ident.get('identical', '?')}/"
              f"{ident.get('compared', '?')} bit-identical to the "
              "uninterrupted run ok")

    rec = rows.get("chaos_recovery_seconds")
    if rec is None or not isinstance(rec.get("value"), (int, float)):
        print("- recovery-seconds row missing")
        tripped += 1
    else:
        ok = rec["value"] <= budget_s
        print(f"- recovery wall: {rec['value']}s (budget "
              f"{budget_s:.0f}s) " + ("ok" if ok else
              "**REGRESSION** (restart recovery got slow)"))
        tripped += 0 if ok else 1
    return tripped


#: artifact-warm first generation must land within this multiple of a
#: fully-warm (populated XLA cache) fresh process — matches the gate
#: stamped into BENCH_COLDSTART.json's coldstart_artifact_vs_warm_x row
COLDSTART_ARTIFACT_VS_WARM_X = 1.5


def coldstart_tripwire(max_ratio: float = COLDSTART_ARTIFACT_VS_WARM_X
                       ) -> int:
    """The cold-start gate (ISSUE 18). The latest
    BENCH_COLDSTART*.json — per-phase time_to_first_generation for a
    fresh process under empty / warm-XLA / artifact-store cache
    regimes — must show (1) the artifact run actually loading from the
    store, (2) artifact-warm within ``max_ratio``× the fully-warm
    baseline, and (3) the first generation's fitness digest
    bit-identical across all three regimes (the deserialized
    executable IS the compiled one). Returns tripped row count."""
    files = sorted(glob.glob(os.path.join(HERE,
                                          "BENCH_COLDSTART*.json")))
    if not files:
        print("coldstart tripwire: no committed BENCH_COLDSTART*.json "
              "yet")
        return 0
    rows = _bench_rows(files[-1])
    print(f"\n## Cold start ({os.path.basename(files[-1])})\n")
    tripped = 0

    ratio = rows.get("coldstart_artifact_vs_warm_x")
    if ratio is None or not isinstance(ratio.get("value"),
                                       (int, float)):
        print("- artifact-vs-warm ratio row missing")
        tripped += 1
    else:
        loaded = ratio.get("artifact_loaded") is True
        ok = ratio["value"] <= max_ratio and loaded
        print(f"- artifact-warm first generation: {ratio['value']}x "
              f"fully-warm (gate <= {max_ratio}x, "
              f"loaded_from_store={loaded}) "
              + ("ok" if ok else "**REGRESSION** (the executable "
                 "artifact path stopped paying for itself)"))
        tripped += 0 if ok else 1

    bit = rows.get("coldstart_artifact_digest_identical")
    if bit is None or bit.get("value") is not True:
        print("- **REGRESSION**: first-generation digests are NOT "
              "bit-identical across cold/warm/artifact regimes (or "
              "the row is missing) — the artifact path is changing "
              "numerics")
        tripped += 1
    else:
        print(f"- first-generation digest identical across all three "
              f"regimes ({bit.get('digest', '?')}…) ok")
    return tripped


#: fractional full-observability overhead beyond which the costs pair
#: trips (observatory + metrics + flight recorder vs bare segmented
#: run, same session, pop=100k)
COSTS_OVERHEAD_THRESHOLD = 0.03


def costs_tripwire(threshold: float = COSTS_OVERHEAD_THRESHOLD) -> int:
    """The observability-layer gate (ISSUE 9). The latest
    BENCH_COSTS*.json must show (1) the full third layer (program
    observatory + serving metrics + flight recorder) within
    ``threshold`` of its observability-off pair — same session,
    bit-identity asserted before timing — and (2) **every** donating
    generation-step program's ``memory_analysis`` reporting nonzero
    aliased (donated) bytes: the PR 8 donation contract audited per
    program on every committed run, not once by the mesh bench.
    Returns the number of tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_COSTS*.json")))
    if not files:
        print("costs tripwire: no committed BENCH_COSTS*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    tripped = 0
    print(f"\n## Observability costs ({os.path.basename(files[-1])})\n")
    ov = rows.get("onemax_pop100k_observability_overhead_pct")
    off = rows.get("onemax_pop100k_observability_off_generations_per_sec")
    on = rows.get("onemax_pop100k_observability_on_generations_per_sec")
    if ov is not None and isinstance(ov.get("value"), (int, float)):
        overhead = ov["value"] / 100.0
        ok = overhead <= threshold
        pair = ""
        if off and on:
            pair = (f"on {on['value']} vs off {off['value']} gens/s "
                    f"({on.get('n_programs', '?')} programs profiled, "
                    f"trace_every={on.get('trace_every', '?')}), ")
        print(f"- {pair}same session: {100 * overhead:+.2f}% overhead "
              + ("ok" if ok else f"**REGRESSION** (> {threshold:.0%} — "
                 "the observability layer got expensive)"))
        tripped += 0 if ok else 1
    else:
        print("- observability overhead row missing")
        tripped += 1
    programs = {k: r for k, r in rows.items()
                if k.startswith("program_cost_")}
    if not programs:
        print("- no program_cost_* rows committed — the per-program "
              "attribution is part of the acceptance")
        tripped += 1
    donating = {k: r for k, r in programs.items() if r.get("donating")}
    if programs and not donating:
        print("- no donating program rows — the donation-contract "
              "audit has nothing to check")
        tripped += 1
    for k, r in sorted(donating.items()):
        aliased = r.get("aliased_bytes")
        ok = isinstance(aliased, (int, float)) and aliased > 0
        print(f"- {k}: flops={r.get('value')} "
              f"bytes={r.get('bytes_accessed')} "
              f"compile={r.get('compile_s')}s aliased={aliased} "
              + ("ok" if ok else "**REGRESSION** (donating program "
                 "shows ZERO aliased bytes — the generation-step copy "
                 "is back)"))
        tripped += 0 if ok else 1
    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1], TRIPWIRE_THRESHOLD)
    return tripped


#: the pjit path must hold at least this fraction of the shard_map
#: path's throughput (same-session island pair, bench.py --mesh)
MESH_PJIT_FLOOR = 0.95


def mesh_tripwire(floor: float = MESH_PJIT_FLOOR) -> int:
    """The sharding-plan gate (ISSUE 8): the latest BENCH_MESH*.json
    must show (1) the plan-compiled (pjit) island epoch at or above
    ``floor`` × its shard_map pair — same session, live-vs-live — and
    (2) the ``donate_argnums`` row present with the generation-step
    copy actually eliminated (donated bytes > 0). Returns the number
    of tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_MESH*.json")))
    if not files:
        print("mesh tripwire: no committed BENCH_MESH*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    print(f"\n## Mesh plan ({os.path.basename(files[-1])})\n")
    tripped = 0
    ratio = rows.get("mesh_pjit_vs_shardmap_ratio")
    if ratio is None or not isinstance(ratio.get("value"), (int, float)):
        print("- mesh_pjit_vs_shardmap_ratio: **missing**")
        tripped += 1
    else:
        ok = ratio["value"] >= floor
        print(f"- pjit vs shard_map island epochs: {ratio['value']}x "
              f"(floor {floor}x) "
              + ("ok" if ok else "**REGRESSION** (the plan path is "
                 "slower than the pmap-era path it replaces)"))
        tripped += 0 if ok else 1
    don = rows.get("mesh_donation")
    if don is None:
        print("- mesh_donation: **missing** (the donate_argnums row "
              "is part of the acceptance)")
        tripped += 1
    else:
        ok = bool(don.get("copy_eliminated")) and \
            don.get("donated_mb", 0) > 0
        print(f"- donation: {don.get('donated_mb', 0)} MB of "
              f"generation-step carry aliased in place, "
              f"{don.get('value')}x vs no-donation "
              + ("ok" if ok else "**REGRESSION** (donation no longer "
                 "eliminates the generation-step copy)"))
        tripped += 0 if ok else 1
    eigh = rows.get("cma_serving_batched_eigh_speedup_x")
    if eigh is not None and isinstance(eigh.get("value"), (int, float)):
        print(f"- CMA serving batched eigh (jacobi vs lapack, "
              f"{eigh.get('lanes')} lanes, dim {eigh.get('dim')}): "
              f"{eigh['value']}x (context row, ungated)")
    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1], TRIPWIRE_THRESHOLD)
    return tripped


#: fractional overhead beyond which the sampled-tracing pair trips
#: (trace_sample=0.1 vs tracing off, same session, 1k-tenant socket
#: config, interleaved min-of-reps)
TRACING_OVERHEAD_THRESHOLD = 0.03


def tracing_tripwire(threshold: float = TRACING_OVERHEAD_THRESHOLD) -> int:
    """The tracing-plane gate (ISSUE 15). The latest
    BENCH_TRACING*.json must show (1) the sampled arm
    (``trace_sample=0.1``) within ``threshold`` of the tracing-off arm
    — same session, interleaved min-of-reps at the 1k-tenant socket
    config — and (2) all three arms (off / sampled / always-on)
    producing bit-identical per-tenant wire digests: spans observe the
    run, they never steer it. The always-on overhead row is printed
    for context but ungated. Returns the number of tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_TRACING*.json")))
    if not files:
        print("tracing tripwire: no committed BENCH_TRACING*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    tripped = 0
    print(f"\n## Tracing overhead ({os.path.basename(files[-1])})\n")
    ov = rows.get("tracing_sampled_overhead_pct")
    off = rows.get("tracing_off_seconds")
    sam = rows.get("tracing_sampled_seconds")
    if ov is not None and isinstance(ov.get("value"), (int, float)):
        overhead = ov["value"] / 100.0
        ok = overhead <= threshold
        pair = ""
        if off and sam:
            pair = (f"sampled {sam['value']}s vs off {off['value']}s "
                    f"({off.get('tenants', '?')} tenants, "
                    f"{off.get('clients', '?')} clients), ")
        print(f"- {pair}same session: {100 * overhead:+.2f}% overhead "
              + ("ok" if ok else f"**REGRESSION** (> {threshold:.0%} — "
                 "sampled tracing got expensive)"))
        tripped += 0 if ok else 1
    else:
        print("- tracing_sampled_overhead_pct row missing")
        tripped += 1
    alw = rows.get("tracing_always_overhead_pct")
    if alw is not None and isinstance(alw.get("value"), (int, float)):
        print(f"- always-on arm: {alw['value']:+.2f}% overhead "
              "(context row, ungated)")
    bit = rows.get("tracing_bit_identical")
    if bit is None:
        print("- tracing_bit_identical: **missing** (the bit-identity "
              "row is part of the acceptance)")
        tripped += 1
    else:
        ok = bool(bit.get("value"))
        print(f"- bit identity across off/sampled/always: "
              f"{bit.get('value')} "
              f"({bit.get('tenants_compared', '?')} tenants) "
              + ("ok" if ok else "**REGRESSION** (a traced run "
                 "diverged — spans are steering the evolution)"))
        tripped += 0 if ok else 1
    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1], TRIPWIRE_THRESHOLD)
    return tripped


#: canary steady-state cost ceiling — the known-answer probe rides
#: the production scheduler, so its overhead at the 1k-tenant socket
#: config must stay within noise of the canary-off arm
CANARY_OVERHEAD_THRESHOLD = 0.03
#: the injected corruption must produce a FIRING canary_failure
#: alert within this many segment boundaries of the canary completing
CANARY_DETECT_BOUNDARIES = 2


def canary_tripwire(threshold: float = CANARY_OVERHEAD_THRESHOLD) -> int:
    """The canary/alerting gate (ISSUE 19), over the latest committed
    BENCH_CANARY*.json: (1) ZERO false alarms across every clean rep
    — no ``alert`` transitions and no ``canary_failed`` rows when
    nothing is wrong (a paging signal that cries wolf is worse than
    none); (2) the injected-corruption run detected end to end
    (``canary_failed`` row + ``canary`` alarm + firing
    ``canary_failure`` alert) within ``CANARY_DETECT_BOUNDARIES``
    segment boundaries; (3) the canary-on arm within ``threshold`` of
    canary-off at the 1k-tenant socket config, interleaved
    min-of-reps. Returns the number of tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_CANARY*.json")))
    if not files:
        print("canary tripwire: no committed BENCH_CANARY*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    tripped = 0
    print(f"\n## Canary observability ({os.path.basename(files[-1])})\n")
    fa = rows.get("canary_false_alarms")
    if fa is not None and isinstance(fa.get("value"), int):
        ok = fa["value"] == 0
        print(f"- clean-run false alarms: {fa['value']} "
              f"({fa.get('alert_rows', '?')} alert rows, "
              f"{fa.get('canary_failed_rows', '?')} canary_failed, "
              f"{fa.get('clean_canary_ok_rows', '?')} canary_ok over "
              f"{fa.get('reps', '?')} reps) "
              + ("ok" if ok else "**REGRESSION** (the alert plane "
                 "pages on a healthy run)"))
        tripped += 0 if ok else 1
    else:
        print("- canary_false_alarms row missing")
        tripped += 1
    det = rows.get("canary_detection_boundaries")
    flag = rows.get("canary_detected")
    detected = bool(flag and flag.get("value"))
    if (det is not None and isinstance(det.get("value"), int)
            and detected):
        ok = det["value"] <= CANARY_DETECT_BOUNDARIES
        print(f"- injected corruption → firing alert in "
              f"{det['value']} boundary(ies) "
              f"({det.get('detect_wall_s', '?')}s wall) "
              + ("ok" if ok else "**REGRESSION** (> "
                 f"{CANARY_DETECT_BOUNDARIES} boundaries — detection "
                 "got slow)"))
        tripped += 0 if ok else 1
    else:
        print("- corruption detection: **REGRESSION** (the injected "
              "wrong answer was not detected end to end)")
        tripped += 1
    ov = rows.get("canary_overhead_pct")
    off = rows.get("canary_off_seconds")
    on = rows.get("canary_on_seconds")
    if ov is not None and isinstance(ov.get("value"), (int, float)):
        overhead = ov["value"] / 100.0
        ok = overhead <= threshold
        pair = ""
        if off and on:
            pair = (f"on {on['value']}s vs off {off['value']}s "
                    f"({off.get('tenants', '?')} tenants), ")
        print(f"- {pair}same session: {100 * overhead:+.2f}% overhead "
              + ("ok" if ok else f"**REGRESSION** (> {threshold:.0%} "
                 "— the canary got expensive)"))
        tripped += 0 if ok else 1
    else:
        print("- canary_overhead_pct row missing")
        tripped += 1
    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1], TRIPWIRE_THRESHOLD)
    return tripped


TUNING_WINNER_THRESHOLD_X = 0.95
TUNING_WARM_THRESHOLD_PCT = 1.0


def tuning_tripwire() -> int:
    """The dispatch-tuner gate (ISSUE 16), over the latest committed
    BENCH_TUNING*.json: (1) per probed knob, the tuned winner must be
    within 5% of the fastest static candidate (``value`` =
    fastest/winner >= 0.95 — 1.0 on a fresh probe by construction;
    the gate guards replayed or hand-edited caches) AND the probe's
    identity check must have passed (``bitwise``, or ``tolerance``
    for the eigh pair) — a fast-but-wrong winner is a correctness
    bug, not a perf win; (2) the warm-cache amortisation row: a fresh
    session's resolves of every probed key must cost <= 1% of one
    headline GP run. Knob rows without timings (cache/env-only knobs
    that did not probe) are exempt from (1). Returns the number of
    tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_TUNING*.json")))
    if not files:
        print("tuning tripwire: no committed BENCH_TUNING*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    tripped = 0
    print(f"\n## Dispatch tuning ({os.path.basename(files[-1])})\n")
    probe_rows = {m: r for m, r in rows.items()
                  if m.startswith("tuning_") and m.endswith("_probe")}
    if not probe_rows:
        print("- no tuning_*_probe rows (the probe sweep is part of "
              "the acceptance)")
        tripped += 1
    for metric, row in sorted(probe_rows.items()):
        knob = metric[len("tuning_"):-len("_probe")]
        val = row.get("value")
        winner = row.get("winner")
        identity = row.get("identity")
        if not row.get("timings"):
            print(f"- {knob}: winner {winner!r} (no probe timings — "
                  "cache/env rung, exempt)")
            continue
        ok_speed = (isinstance(val, (int, float))
                    and val >= TUNING_WINNER_THRESHOLD_X)
        ok_ident = identity in ("bitwise", "tolerance")
        note = ""
        spd = row.get("speedup_vs_default_x")
        if isinstance(spd, (int, float)) and spd > 1.0:
            note = f", {spd}x over the static default"
        print(f"- {knob}: winner {winner!r} at {val}x of fastest "
              f"static, identity {identity!r}{note} "
              + ("ok" if ok_speed and ok_ident else
                 "**REGRESSION** ("
                 + ("slower than a static candidate it had measured"
                    if not ok_speed else
                    "identity check did not pass — the winner is "
                    "not trusted") + ")"))
        tripped += 0 if (ok_speed and ok_ident) else 1
    warm = rows.get("tuning_warm_overhead_pct")
    if warm is None:
        print("- tuning_warm_overhead_pct row missing (the "
              "amortisation half is part of the acceptance)")
        tripped += 1
    elif isinstance(warm.get("value"), (int, float)):
        ok = warm["value"] <= TUNING_WARM_THRESHOLD_PCT
        print(f"- warm-cache resolves: {warm.get('warm_resolve_s')}s "
              f"for {warm.get('n_keys', '?')} keys = "
              f"{warm['value']}% of one {warm.get('headline', '?')} "
              "run " + ("ok" if ok else
                        f"**REGRESSION** (> "
                        f"{TUNING_WARM_THRESHOLD_PCT}% — the cache "
                        "read stopped amortising)"))
        tripped += 0 if ok else 1
    if len(files) >= 2:
        tripped += _diff_rows(files[-2], files[-1], TRIPWIRE_THRESHOLD)
    return tripped


#: pacing-fidelity budget for the replay gate — matches
#: bench.LOADGEN_FIDELITY_BUDGET_S
LOADGEN_FIDELITY_BUDGET_S = 0.5


def loadgen_tripwire(budget_s: float = LOADGEN_FIDELITY_BUDGET_S
                     ) -> int:
    """The load-observatory gate (ISSUE 17), over the latest committed
    BENCH_LOADGEN*.json: (1) every gated traffic model's windowed SLO
    curve green (``loadgen_*_slo_green`` rows), (2) the journal
    record→replay row within the pacing-fidelity budget AND every
    replayed digest bit-identical to the in-process reference, (3)
    the loadgen transport path bit-identical to direct Scheduler
    submission over the non-abandoned overlap set, and (4) the
    regression-attribution demo naming the ``segment`` phase — the
    whole point of the per-phase decomposition is a *named* culprit.
    Returns the number of tripped rows."""
    files = sorted(glob.glob(os.path.join(HERE,
                                          "BENCH_LOADGEN*.json")))
    if not files:
        print("loadgen tripwire: no committed BENCH_LOADGEN*.json yet")
        return 0
    rows = _bench_rows(files[-1])
    tripped = 0
    print(f"\n## Load observatory ({os.path.basename(files[-1])})\n")

    slo_rows = {m: r for m, r in rows.items()
                if m.startswith("loadgen_") and m.endswith("_slo_green")}
    if len(slo_rows) < 2:
        print(f"- only {len(slo_rows)} gated traffic model(s) "
              "committed (acceptance: >= 2)")
        tripped += 1
    for metric, row in sorted(slo_rows.items()):
        model = metric[len("loadgen_"):-len("_slo_green")]
        ok = row.get("value") is True
        bad = [g for g in row.get("gates", []) if not g.get("ok")]
        print(f"- {model}: {row.get('arrivals', '?')} arrival(s), "
              f"counts {row.get('counts')} "
              + ("— all SLO gates green ok" if ok else
                 "**REGRESSION** (breached: "
                 + ", ".join(f"{g['slo']}={g.get('worst')}"
                             for g in bad) + ")"))
        tripped += 0 if ok else 1

    rep = rows.get("loadgen_replay_fidelity_s")
    if rep is None or not isinstance(rep.get("value"), (int, float)):
        print("- replay-fidelity row missing (journal record→replay "
              "is part of the acceptance)")
        tripped += 1
    else:
        ok_pace = rep["value"] <= budget_s
        n_dig = rep.get("replay_digests_compared", 0)
        ok_dig = (n_dig > 0
                  and rep.get("replay_digest_identical") == n_dig)
        print(f"- replay at {rep.get('speed', '?')}x: "
              f"{rep.get('reconstructed', '?')} arrival(s) "
              f"reconstructed, max pacing error {rep['value']}s "
              f"(budget {budget_s}s), digests "
              f"{rep.get('replay_digest_identical', '?')}/{n_dig} "
              "identical to reference "
              + ("ok" if ok_pace and ok_dig else
                 "**REGRESSION** ("
                 + ("replay pacing drifted" if not ok_pace else
                    "replayed jobs diverged from the recorded run")
                 + ")"))
        tripped += 0 if (ok_pace and ok_dig) else 1

    bit = rows.get("loadgen_bit_identical_frac")
    if bit is None or bit.get("value") != 1.0:
        print(f"- **REGRESSION**: loadgen-path digest identity "
              f"{(bit or {}).get('value', '?')} (gate: 1.0) — the "
              "load generator is changing results")
        tripped += 1
    else:
        print(f"- transport: {bit.get('compared', '?')} non-abandoned "
              "tenant(s) bit-identical to in-process ok")

    att = rows.get("loadgen_attribution_top_phase")
    if att is None or att.get("value") != "segment":
        print(f"- **REGRESSION**: attribution named "
              f"{(att or {}).get('value')!r} (expected 'segment') — "
              "the injected segment stall was mis-attributed")
        tripped += 1
    else:
        print(f"- attribution: injected "
              f"{att.get('injected_delay_s', '?')}s segment stall → "
              f"'segment' +{att.get('top_delta_s', '?')}s at p99 ok")
    return tripped


def migration_tripwire() -> int:
    """The zero-downtime gate (ISSUE 20). The latest
    BENCH_MIGRATION*.json — a rolling upgrade under live load, the
    new-version child adopting the old-version child's tenants through
    fsync'd WAL ownership-transfer records — must show (1) zero lost
    jobs and (2) 100% wire-digest identity in the drill, (3) canaries
    green on both sides, (4) the compat gate actually exercised, (5)
    migration pause p99 within its budget AND under BENCH_CHAOS's
    whole-service recovery wall (live migration must beat
    kill/restart, or it has no reason to exist), and (6) the
    upgrade-under-load arm losing nothing, bit-identical to its
    baseline, with at least one arrival re-offered across the roll."""
    files = sorted(glob.glob(os.path.join(HERE,
                                          "BENCH_MIGRATION*.json")))
    if not files:
        print("migration tripwire: no committed BENCH_MIGRATION*.json "
              "yet")
        return 0
    rows = _bench_rows(files[-1])
    print(f"\n## Zero-downtime operations "
          f"({os.path.basename(files[-1])})\n")
    tripped = 0

    lost = rows.get("upgrade_lost_jobs")
    if lost is None or lost.get("value") != 0:
        print(f"- **REGRESSION**: {(lost or {}).get('value', '?')} "
              "job(s) lost across the rolling upgrade (gate: 0) — "
              "the ownership-transfer chain is leaking work")
        tripped += 1
    else:
        print(f"- upgrade drill: 0 of {lost.get('tenants', '?')} "
              f"job(s) lost (old child exit rc="
              f"{lost.get('old_rc', '?')}) ok")

    ident = rows.get("upgrade_digest_identity_frac")
    if ident is None or ident.get("value") != 1.0:
        print(f"- **REGRESSION**: drill digest identity "
              f"{(ident or {}).get('value', '?')} (gate: 1.0) — "
              "migration is changing numerics")
        tripped += 1
    else:
        print(f"- wire digests: {ident.get('identical', '?')}/"
              f"{ident.get('compared', '?')} bit-identical through "
              "the handoff ok")

    can = rows.get("upgrade_canary_failed")
    if can is None or can.get("value") != 0:
        print(f"- **REGRESSION**: {(can or {}).get('value', '?')} "
              "canary_failed row(s) during the roll (gate: 0)")
        tripped += 1
    else:
        print(f"- canaries: 0 failures "
              f"({can.get('canary_ok', '?')} green run(s)) across "
              "both versions ok")

    compat = rows.get("upgrade_compat_restores")
    if compat is None or not compat.get("value"):
        print("- **REGRESSION**: no compat_restore rows — the drill "
              "never exercised the version-skew gate, the run proved "
              "nothing about upgrades")
        tripped += 1
    else:
        print(f"- compat gate: {compat['value']} cross-version "
              "restore(s) journaled under the explicit gate ok")

    pause = rows.get("migration_pause_p99_s")
    if pause is None or not isinstance(pause.get("value"),
                                       (int, float)):
        print("- migration-pause row missing")
        tripped += 1
    else:
        budget = float(str(pause.get("gate", "<= 30")
                           ).split("<=")[-1])
        ok = pause["value"] <= budget
        # the cross-file teeth: a live migration that pauses a tenant
        # longer than a whole-service kill/restart recovery is a
        # regression even inside its static budget
        chaos_files = sorted(glob.glob(os.path.join(
            HERE, "BENCH_CHAOS*.json")))
        rec = None
        if chaos_files:
            rec_row = _bench_rows(chaos_files[-1]).get(
                "chaos_recovery_seconds")
            if rec_row and isinstance(rec_row.get("value"),
                                      (int, float)):
                rec = float(rec_row["value"])
        ok_rec = rec is None or pause["value"] <= rec
        print(f"- migration pause p99: {pause['value']}s over "
              f"{pause.get('migrations', '?')} migration(s) (budget "
              f"{budget:.0f}s"
              + (f", kill/restart recovery {rec}s" if rec is not None
                 else "") + ") "
              + ("ok" if ok and ok_rec else
                 "**REGRESSION** ("
                 + ("pause blew its budget" if not ok else
                    "pausing longer than a full kill/restart — live "
                    "migration lost its reason to exist") + ")"))
        tripped += 0 if (ok and ok_rec) else 1

    lg_lost = rows.get("upgrade_loadgen_lost_jobs")
    lg_ident = rows.get("upgrade_loadgen_digest_identity_frac")
    lg_cross = rows.get("upgrade_loadgen_migrated_reoffers")
    lg_ok = (lg_lost is not None and lg_lost.get("value") == 0
             and lg_ident is not None and lg_ident.get("value") == 1.0
             and lg_cross is not None and (lg_cross.get("value") or 0)
             >= 1)
    if not lg_ok:
        print("- **REGRESSION**: upgrade-under-load arm — lost="
              f"{(lg_lost or {}).get('value', '?')} (gate 0), "
              f"identity={(lg_ident or {}).get('value', '?')} "
              "(gate 1.0), migrated re-offers="
              f"{(lg_cross or {}).get('value', '?')} (gate >= 1)")
        tripped += 1
    else:
        delta = rows.get("upgrade_loadgen_p99_delta_s") or {}
        print(f"- under load: {lg_ident.get('compared', '?')} "
              "arrival(s) bit-identical to the no-upgrade arm, "
              f"{lg_cross['value']} re-offered across the roll, "
              f"completion p99 delta {delta.get('value', '?')}s ok")
    return tripped


def tripwire(threshold: float = TRIPWIRE_THRESHOLD) -> int:
    """Diff the two most recent committed ``BENCH_r*.json`` files and
    flag regressions; then the gp_symbreg paired rows
    (:func:`gp_tripwire`). Cached-replay rows (``cached: true`` /
    ``tpu-cached`` backend) never trip — a replay of an old capture
    carries no new information about the current code; the env
    fingerprint bench.py stamps makes the distinction visible in the
    table. Returns the number of tripped metrics (the process exit
    code)."""
    files = sorted(glob.glob(os.path.join(HERE, "BENCH_r*.json")))
    tripped = 0
    if len(files) < 2:
        print("tripwire: need >= 2 committed BENCH_r*.json files, "
              f"found {len(files)}")
    else:
        tripped += _diff_rows(files[-2], files[-1], threshold)
    tripped += gp_tripwire(threshold)
    tripped += probe_tripwire()
    tripped += resilience_tripwire()
    tripped += fusion_tripwire()
    tripped += serving_tripwire()
    tripped += gp_serving_tripwire()
    tripped += service_tripwire()
    tripped += chaos_tripwire()
    tripped += coldstart_tripwire()
    tripped += mesh_tripwire()
    tripped += costs_tripwire()
    tripped += tracing_tripwire()
    tripped += tuning_tripwire()
    tripped += loadgen_tripwire()
    tripped += canary_tripwire()
    tripped += migration_tripwire()
    return tripped


# ------------------------------------------------------- journal reader ----

def _read_jsonl(path: str) -> list:
    out = []
    with open(path) as fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                continue
    return out


def journal_report(path: str) -> None:
    """Summarise a telemetry run journal (the JSONL RunJournal format;
    local parser — this tool must stay importable without jax)."""
    events = _read_jsonl(path)
    kinds = {}
    for e in events:
        kinds[e.get("kind", "?")] = kinds.get(e.get("kind", "?"), 0) + 1
    print(f"## Run journal: {os.path.basename(path)}\n")
    header = next((e for e in events if e.get("kind") == "header"), None)
    if header:
        env = header.get("env", {})
        print("- env: " + ", ".join(f"{k}={v}" for k, v in env.items()))
        if "toolbox" in header:
            print(f"- toolbox digest: {header['toolbox'].get('digest')}")
    print("- events: " + ", ".join(
        f"{k}×{v}" for k, v in sorted(kinds.items())))
    retraces = [e for e in events if e.get("kind") == "retrace"]
    if retraces:
        total = sum(e.get("dur_s", 0.0) for e in retraces)
        print(f"- **{len(retraces)} retrace(s)** after steady, "
              f"{total:.3f}s recompiling — investigate shape/closure "
              "churn")
    meters = [e for e in events if e.get("kind") == "meter"]
    if meters:
        drop = ("t", "kind")
        fmt = lambda e: ", ".join(f"{k}={v}" for k, v in e.items()
                                  if k not in drop and not isinstance(v, list))
        print(f"- meter rows: {len(meters)} (first: {fmt(meters[0])}; "
              f"last: {fmt(meters[-1])})")
    spans = [e for e in events if e.get("kind") == "span"]
    if spans:
        print("\n| span | count | total s | p50 s | p99 s |")
        print("|---|---|---|---|---|")
        for s in sorted(spans, key=lambda s: -s.get("total_s", 0)):
            print(f"| {s.get('name')} | {s.get('count')} | "
                  f"{s.get('total_s', 0):.6f} | {s.get('p50_s', 0):.6f} | "
                  f"{s.get('p99_s', 0):.6f} |")


def main() -> None:
    rows = headline_rows()
    print("## Headline (OneMax pop=100k)\n")
    if rows:
        print("| measured at | gens/sec | vs CPU reference | candidates |")
        print("|---|---|---|---|")
        for r in sorted(rows, key=lambda r: r["measured_at"] or ""):
            print(f"| {r['measured_at']} | **{r['value']}** | "
                  f"{r.get('vs_baseline', '?')}× | "
                  f"{r.get('n_candidates', '?')} |")
    else:
        print("*(no TPU headline captured yet)*")

    print("\n## Suite configs\n")
    suite = suite_rows()
    print("| config | TPU gens/sec | reference CPU | speedup |")
    print("|---|---|---|---|")
    for name in SUITE_CONFIG_NAMES:
        r = suite.get(f"{name}_generations_per_sec")
        ref = SUITE_REF[name]
        # extrapolation is a static property of the reference number,
        # not of the captured row — mark it on pending rows too
        extra = " (ref extrapolated)" if name in SUITE_EXTRAPOLATED else ""
        if r:
            print(f"| {name} | **{r['value']}** | {ref:.4g}{extra} | "
                  f"{r.get('vs_baseline', '?')}× |")
        else:
            print(f"| {name} | *(pending)* | {ref:.4g}{extra} | |")

    print("\n## Generation-step profile (ms/gen, pop=100k)\n")
    prof = {c: r["ms_per_gen"] for c, r in profile_rows().items()}
    resolved = profile_resolved()
    print("| component | ms/gen |")
    print("|---|---|")
    for name in COMPONENT_NAMES:
        v = prof.get(name)
        if v is None and name in resolved:
            # errored on-chip: surface the verdict, don't show pending
            # (sanitised — Mosaic errors carry newlines and pipes that
            # would break the markdown row)
            err = resolved[name]["error"].replace("\n", " ")
            v = "failed: " + err.replace("|", "\\|")[:80]
        print(f"| {name} | {v if v is not None else '*(pending)*'} |")
    if prof.get("full_binned"):
        parts = {k: v for k, v in prof.items()
                 if k in ("select_binned", "gather_random",
                          "kernel_fused_packed")}
        if len(parts) == 3:
            gap = prof["full_binned"] - sum(parts.values())
            print(f"\nfull_binned − (select + gather + kernel) = "
                  f"{gap:.4f} ms/gen of fusion/overhead delta.")


def health_report(path: str) -> None:
    """Full run-health report (sparklines, alarms, spans) via
    deap_tpu/telemetry/report.py — loaded by FILE PATH, because
    importing the package would initialise jax and this tool's contract
    is to run anywhere (tests/test_probes.py pins the no-jax
    guarantee)."""
    import importlib.util

    rp = os.path.join(HERE, "deap_tpu", "telemetry", "report.py")
    spec = importlib.util.spec_from_file_location("_telemetry_report", rp)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    print(mod.render_report(path))


if __name__ == "__main__":
    if "--tripwire" in sys.argv:
        sys.exit(1 if tripwire() else 0)
    elif "--health" in sys.argv:
        health_report(sys.argv[sys.argv.index("--health") + 1])
    elif "--journal" in sys.argv:
        journal_report(sys.argv[sys.argv.index("--journal") + 1])
    else:
        main()
