"""Per-component attribution of the headline generation step.

VERDICT r1 asked where the ~2.7 ms/gen of the packed OneMax path goes
(selection sort vs parent gather vs fused kernel). This script times
each component in isolation (scanned NGEN times inside one jit, honest
`sync` barrier — same methodology as bench.py) and the full step, then
prints a JSON breakdown. Optionally captures an xplane trace of the
full step with ``--trace DIR`` (view in TensorBoard/Perfetto).

Run on TPU (falls back to CPU with a tunnel_down marker like bench.py).
"""

import json
import os
import sys
import time

# reuse bench.py's axon-tunnel probe + platform forcing side effects
# (and its packed_selector, so we profile exactly the measured config)
import bench  # noqa: F401  (must precede jax import)
import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu import ops
from deap_tpu.support.profiling import sync, trace

_TUNNEL_OK = bench._TUNNEL_OK

POP = 100_000
LENGTH = 100
NGEN = 200

# canonical component order (most-valuable-first) lives in
# tpu_capture.py (whose queue-completion check must not import this
# module — our `import bench` side effect probes the relay); main()
# asserts its component list against it so the two cannot drift
from tpu_capture import COMPONENT_NAMES


def timed(run, *args):
    sync(run(jax.random.key(0), *args))  # compile + warm
    best = float("inf")
    for r in range(3):
        t0 = time.perf_counter()
        sync(run(jax.random.key(1 + r), *args))
        best = min(best, time.perf_counter() - t0)
    return best / NGEN


def scanned(step):
    """jit(scan(step)) over NGEN keys; step: (carry, key) -> carry."""
    @jax.jit
    def run(key, *carry):
        out, _ = lax.scan(lambda c, k: (step(c, k), None), carry,
                          jax.random.split(key, NGEN))
        return out
    return run


def main():
    tdir = None
    if "--trace" in sys.argv:
        i = sys.argv.index("--trace")
        if i + 1 >= len(sys.argv):
            sys.exit("usage: bench_profile.py [--trace TRACE_DIR]")
        tdir = sys.argv[i + 1]

    interpret = jax.default_backend() != "tpu"
    kw = dict(cxpb=0.5, mutpb=0.2, indpb=0.05,
              prng="hw" if not interpret else "input",
              block_i=1024, interpret=interpret)

    genomes = jax.random.bernoulli(jax.random.key(9), 0.5, (POP, LENGTH))
    packed = ops.pack_genomes(genomes)
    fit = ops.packed_fitness(packed)

    # 1. selection alone (sorted vs binned), fitness fed back unchanged
    sel_sorted = scanned(lambda c, k: (
        c[0], c[1] + 0 * bench.packed_selector("sorted")(
            k, c[1][:, None], POP).astype(jnp.float32)))
    sel_binned = scanned(lambda c, k: (
        c[0], c[1] + 0 * bench.packed_selector("binned")(
            k, c[1][:, None], POP).astype(jnp.float32)))

    # 1b. the two counting-sort prefix formulations in isolation (the
    # scan mode's log-pass cumsum was the diagnosed dominant term; the
    # mxu mode replaces it with a tiled tril-matmul — same bit-exact
    # permutation, see ops.selection.counting_order_desc)
    from deap_tpu.ops.selection import counting_order_desc

    def sel_mode(mode):
        def step(c, k):
            order = counting_order_desc(c[1], 0, LENGTH, mode=mode)
            m = jnp.min(jax.random.randint(k, (3, POP), 0, POP), axis=0)
            return (c[0], c[1] + 0 * jnp.take(order, m).astype(jnp.float32))
        return scanned(step)

    # 2. gather alone: random idx (uniform — same access pattern class)
    def gather_step(c, k):
        packed, fit = c
        idx = jax.random.randint(k, (POP,), 0, POP)
        return (packed[idx], fit)
    gather_only = scanned(gather_step)

    # 2b. the same row gather with NEAR-COHERENT indices (monotone ramp
    # + bounded jitter, so duplicates and small back-steps occur but
    # accesses stay block-local) — built WITHOUT a sort so the row
    # isolates the pure access-pattern effect. If this beats
    # gather_random decisively, a counting-sort-the-parent-ranks
    # restructuring of the generation step becomes the next roofline
    # move (its counting sort costs extra, but that trade can then be
    # sized from the select_binned row); a tie means XLA's gather is
    # index-order-insensitive and the attack should aim elsewhere.
    def gather_coherent_step(c, k):
        packed, fit = c
        idx = jnp.clip(jnp.arange(POP) +
                       jax.random.randint(k, (POP,), -512, 512),
                       0, POP - 1)
        return (packed[idx], fit)
    gather_coherent = scanned(gather_coherent_step)

    # 3. kernel alone: variation+eval on the unshuffled rows
    def kernel_step(c, k):
        packed, fit = c
        children, newfit = ops.fused_variation_eval_packed(
            k, packed, LENGTH, **kw)
        return (children, newfit)
    kernel_only = scanned(kernel_step)

    # 4. full steps
    def full(select):
        sel = bench.packed_selector(select)

        def step(c, k):
            packed, fit = c
            ks, kv = jax.random.split(k)
            idx = sel(ks, fit[:, None], POP)
            return ops.fused_variation_eval_packed(
                kv, packed[idx], LENGTH, **kw)
        return scanned(step)

    # Most-valuable-first: each component is timed, printed, and
    # appended to --out the moment it lands. The 2026-07-31 relay
    # window taught the lesson — the old all-components-then-print
    # shape lost 40 minutes of tunnel compiles to a single timeout.
    def evolve_run():
        if jax.default_backend() != "tpu":
            # the interpreter at pop=100k x NGEN=200 would take hours;
            # the error row below records the resolution
            raise RuntimeError("full_evolve profiles on TPU only")

        @jax.jit
        def run(key, packed, fit):
            _, f = ops.evolve_packed(
                key, packed, fit, LENGTH, NGEN, tournsize=3, cxpb=0.5,
                mutpb=0.2, indpb=0.05, prng="hw", interpret=False)
            return f
        return run

    components = [
        ("full_binned", lambda: full("binned")),
        ("full_evolve", evolve_run),
        ("kernel_fused_packed", lambda: kernel_only),
        ("select_binned", lambda: sel_binned),
        ("gather_random", lambda: gather_only),
        ("gather_coherent", lambda: gather_coherent),
        ("full_sorted", lambda: full("sorted")),
        ("select_sorted", lambda: sel_sorted),
        ("counting_mxu", lambda: sel_mode("mxu")),
        ("counting_scan", lambda: sel_mode("scan")),
    ]
    if [n for n, _ in components] != list(COMPONENT_NAMES):
        raise SystemExit("component list drifted from "
                         "tpu_capture.COMPONENT_NAMES")
    out = {
        "backend": jax.default_backend(),
        "pop": POP, "length": LENGTH, "ngen": NGEN,
        "ms_per_gen": {},
    }
    if not _TUNNEL_OK:
        out["tunnel_down"] = True
    out_path = None
    if "--out" in sys.argv:
        i = sys.argv.index("--out")
        if i + 1 >= len(sys.argv):
            sys.exit("usage: bench_profile.py [--out OUT_JSONL]")
        out_path = sys.argv[i + 1]
    # resume: rows already captured for this backend in an earlier
    # window are not re-paid (each costs a multi-minute tunnel compile)
    done = set()
    if out_path:
        from tpu_capture import _jsonl_rows
        for d in _jsonl_rows(out_path):
            if d.get("backend") != out["backend"]:
                continue
            # error rows are resolutions too: a deterministically
            # failing component must not re-pay its tunnel compile on
            # every later run (incl. the --trace queue step)
            if "ms_per_gen" in d or "error" in d:
                done.add(d.get("component"))
            if "ms_per_gen" in d:
                out["ms_per_gen"][d["component"]] = d["ms_per_gen"]
    for name, build in components:
        if name in done:
            print(f'{{"component": "{name}", "skipped": "captured"}}',
                  flush=True)
            continue
        try:
            ms = round(timed(build(), packed, fit) * 1e3, 4)
            line = {"component": name, "ms_per_gen": ms,
                    "backend": out["backend"]}
            out["ms_per_gen"][name] = ms
        except Exception as e:
            from _axon_probe import axon_tunnel_reachable
            if (out["backend"] == "tpu"
                    and not axon_tunnel_reachable()):
                # the exception arrived WITH the relay dying: transient,
                # not a component verdict — abort with NO error row so
                # a later window re-attempts (mirrors _tpu_hw_check's
                # relay-liveness guard)
                print(f"bench_profile: {name} failed with the relay "
                      f"down ({type(e).__name__}); aborting sweep",
                      file=sys.stderr)
                sys.exit(1)
            # a deterministically failing component (e.g. a Mosaic
            # lowering gap in the mega-kernel) must resolve with an
            # error row, not block the remaining components or make
            # the capture predicate re-run this script every window
            line = {"component": name, "backend": out["backend"],
                    "error": f"{type(e).__name__}: {str(e)[:200]}"}
        print(json.dumps(line), flush=True)
        if out_path:
            with open(out_path, "a") as f:
                f.write(json.dumps(line) + "\n")
    print(json.dumps(out), flush=True)

    if tdir is not None:
        if out["backend"] != "tpu":
            # a CPU xplane under the TPU trace dir would satisfy
            # tpu_capture's _have_trace forever and stop the watcher
            # with the wrong artifact
            print(f"backend is {out['backend']}, not tpu — "
                  f"skipping trace capture")
            return
        run = full("binned")
        sync(run(jax.random.key(0), packed, fit))
        with trace(tdir):
            sync(run(jax.random.key(1), packed, fit))
        print(f"xplane trace written to {tdir}")


if __name__ == "__main__":
    main()
