"""Relay uptime watcher: probe every ~2 minutes, launch the evidence
harvester the moment the axon relay answers.

The relay's observed uptime this round is two windows totalling ~45
minutes against ~10 hours of downtime (TPU_PROBE_LOG.jsonl); a human-
in-the-loop poll wastes most of a window before capture even starts.
This daemon closes that latency: each probe is appended to the probe
log (driver-visible downtime evidence), and a reachable probe
immediately runs ``tpu_capture.py`` in the foreground — the harvester
owns the queue, per-step isolation, and per-step commits; this loop
only decides *when*. When the queue finishes or the relay dies the
loop resumes probing, so later windows resume the remaining steps
(tpu_capture skips one-shot steps, bench_suite skips captured
configs).

Usage: ``nohup python _relay_watch.py > relay_watch.log 2>&1 &``
"""

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, HERE)

from _probe_log import probe_once  # noqa: E402
from tpu_capture import queue_complete  # noqa: E402

INTERVAL_S = 120


def main() -> None:
    while True:
        if queue_complete():
            print("every queue artifact captured with TPU backing — "
                  "watcher done", flush=True)
            return
        rec = probe_once()
        print(json.dumps(rec), flush=True)
        if rec["reachable"]:
            print("relay up — launching tpu_capture.py", flush=True)
            r = subprocess.run(
                [sys.executable, os.path.join(HERE, "tpu_capture.py")],
                cwd=HERE)
            print(f"tpu_capture.py returned rc={r.returncode} — "
                  "resuming probe loop", flush=True)
        time.sleep(INTERVAL_S)


if __name__ == "__main__":
    main()
