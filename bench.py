"""Headline benchmark: OneMax GA, pop=100k, 100-bit genomes, eaSimple
config (cxTwoPoint cxpb=.5, mutFlipBit(0.05) mutpb=.2, selTournament(3))
— the BASELINE.json north-star configuration.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "gens/sec", "vs_baseline": N}

``vs_baseline`` is measured against the reference CPU implementation run
on this machine: examples/ga/onemax.py scaled to pop=100k = 0.1681
generations/sec (5.947 s/gen, see BASELINE.md). Target is >=100x.

On TPU the generation step runs the fused Pallas kernel
(deap_tpu.ops.kernels.fused_variation_eval): two-point crossover +
flip-bit mutation + popcount fitness in one HBM pass, with per-gene
random bits from the core's hardware PRNG. Off-TPU it falls back to the
portable XLA path (var_and + masked re-evaluation).

Timing note: device completion is forced by fetching a scalar reduction
of the result — on remote-attached TPU runtimes ``jax.block_until_ready``
can return before execution finishes, silently inflating throughput.
The scalar fetch's fixed round-trip latency is amortised over NGEN.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _axon_probe import axon_tunnel_reachable

_TUNNEL_OK = axon_tunnel_reachable()
if not _TUNNEL_OK:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax

if not _TUNNEL_OK:
    # the axon sitecustomize pins jax_platforms at import; re-force cpu
    jax.config.update("jax_platforms", "cpu")

# opt-in persistent compilation cache (ROADMAP item 5 first slice):
# DEAP_TPU_COMPILE_CACHE=<dir> makes every bench invocation reuse the
# previous one's XLA executables — bench.py --coldstart measures the
# cold-vs-warm time_to_first_generation delta it buys
from deap_tpu.support import compilecache as _compilecache  # noqa: E402

_COMPILE_CACHE = _compilecache.enable_from_env()
import jax.numpy as jnp
from jax import lax

from deap_tpu import ops
from deap_tpu.algorithms import evaluate_invalid, var_and
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import gather, init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.ops.kernels import fused_variation_eval
from deap_tpu.support.profiling import sync

REFERENCE_GENS_PER_SEC = 0.1681  # CPU DEAP, measured 2026-07-29 (BASELINE.md)

POP = 100_000
LENGTH = 100
NGEN = 200
REPS = 5

# v5e peak HBM bandwidth (GB/s) — the denominator for the honest "MFU"
# of a popcount workload (FLOPs are negligible; bandwidth is the roof).
PEAK_HBM_GBPS = 819.0


def _hbm_bytes_per_gen(candidate: str = "packed"):
    """Analytic HBM traffic of one generation for the given winning
    candidate, the numerator of the utilization line: selection reads
    the fitness vector; the parent gather reads the population and
    writes the parent rows; the fused kernel reads parents, writes
    children, writes fitness. Counted at minimum-traffic (perfect
    reuse within each pass); the real number can only be higher, so
    %-of-peak is an upper bound on how well the chip is being fed.
    The ``fused`` candidate streams bool genomes (1 B/gene), the
    packed candidates 32 genes/uint32 word — the models differ ~6×.
    The ``packed_evolve`` mega-kernel touches HBM once per NGEN
    generations (population in + out), so its per-generation traffic is
    that total amortised — for it, %-of-peak stops being a meaningful
    ceiling and mostly documents how little HBM is left in the loop."""
    if candidate == "fused":
        row_bytes = LENGTH  # bool_ genome, 1 byte per gene
    else:
        row_bytes = ((LENGTH + 31) // 32) * 4
    pop_bytes = POP * row_bytes
    fit_bytes = POP * 4
    if candidate == "packed_evolve":
        return (2 * pop_bytes + 2 * fit_bytes) // NGEN
    return fit_bytes + (2 * pop_bytes) + (2 * pop_bytes + fit_bytes)


def _toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def make_run_xla(tb):
    """Portable path: the public eaSimple building blocks."""
    def gen_step(pop, key):
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, gather(pop, idx), tb, 0.5, 0.2)
        return evaluate_invalid(off, tb.evaluate), None

    @jax.jit
    def run(key, pop):
        pop, _ = lax.scan(gen_step, pop, jax.random.split(key, NGEN))
        return pop.wvalues[:, 0]

    return run


def make_run_fused():
    """TPU path: tournament select + fused Pallas variation/eval."""
    def gen_step(carry, key):
        genomes, fit = carry
        k_sel, k_var = jax.random.split(key)
        idx = ops.sel_tournament(k_sel, fit[:, None], POP, tournsize=3)
        children, newfit = fused_variation_eval(
            k_var, genomes[idx], cxpb=0.5, mutpb=0.2, indpb=0.05,
            prng="hw", block_i=1024, interpret=False)
        return (children, newfit), None

    @jax.jit
    def run(key, genomes, fit):
        (_, f), _ = lax.scan(gen_step, (genomes, fit),
                             jax.random.split(key, NGEN))
        return f

    return run


def packed_selector(select="sorted"):
    """The headline config's tournament (tournsize 3) as an index
    selector. ``"binned"`` swaps the full lexsort for the counting-sort
    rank path (bit-exact winners — OneMax fitness is integer in
    [0, LENGTH]). Shared with bench_profile.py so the profiled
    configuration can never drift from the measured one."""
    if select == "binned":
        return lambda k, w, n: ops.sel_tournament_binned(
            k, w, n, tournsize=3, low=0, high=LENGTH)
    return lambda k, w, n: ops.sel_tournament_sorted(k, w, n, tournsize=3)


def make_run_packed(select="sorted", block_i=1024):
    """TPU path, bit-packed genomes: 32 genes/uint32 word cuts the
    genome HBM stream 8× (see deap_tpu.ops.packed); rank-based
    tournament avoids per-aspirant fitness gathers. ``block_i`` is the
    kernel's rows-per-grid-program tile — raced because the per-program
    footprint is tiny (16 B/row) and fewer, larger programs may beat
    the 1024-row default at this kernel's scale."""
    sel = packed_selector(select)

    def gen_step(carry, key):
        packed, fit = carry
        k_sel, k_var = jax.random.split(key)
        idx = sel(k_sel, fit[:, None], POP)
        children, newfit = ops.fused_variation_eval_packed(
            k_var, packed[idx], LENGTH, cxpb=0.5, mutpb=0.2, indpb=0.05,
            prng="hw", block_i=block_i, interpret=False)
        return (children, newfit), None

    @jax.jit
    def run(key, packed, fit):
        (_, f), _ = lax.scan(gen_step, (packed, fit),
                             jax.random.split(key, NGEN))
        return f

    return run


def make_run_evolve():
    """TPU path, whole-GA mega-kernel: NGEN generations inside ONE
    Pallas program, population resident in VMEM (ops.packed
    evolve_packed). The candidate that attacks the launch/dispatch
    overhead the r3 roofline arithmetic exposed (~2.2 ms/gen measured
    vs ~9 us of actual HBM traffic)."""
    @jax.jit
    def run(key, packed, fit):
        _, f = ops.evolve_packed(
            key, packed, fit, LENGTH, NGEN, tournsize=3, cxpb=0.5,
            mutpb=0.2, indpb=0.05, prng="hw", interpret=False)
        return f

    return run


def make_run_selgather():
    """TPU path, VMEM-resident selection: tournament + parent gather in
    ONE single-program Pallas kernel (the packed population and fitness
    fit in VMEM whole at this scale — see
    ops.packed.sel_tournament_gather_packed), then the tiled fused
    variation kernel. No sort, no rank permutation, no XLA gather."""
    def gen_step(carry, key):
        packed, fit = carry
        k_sel, k_var = jax.random.split(key)
        parents = ops.sel_tournament_gather_packed(
            k_sel, packed, fit, tournsize=3, prng="hw", interpret=False)
        children, newfit = ops.fused_variation_eval_packed(
            k_var, parents, LENGTH, cxpb=0.5, mutpb=0.2, indpb=0.05,
            prng="hw", block_i=1024, interpret=False)
        return (children, newfit), None

    @jax.jit
    def run(key, packed, fit):
        (_, f), _ = lax.scan(gen_step, (packed, fit),
                             jax.random.split(key, NGEN))
        return f

    return run


# ------------------------- multi-objective headline: NSGA-II, 3 obj ----

MO_POP = 50_000
MO_NOBJ = 3
MO_DIM = 12
MO_NGEN = 3
MO_REPS = 3


def make_run_nsga2_3obj():
    """One jit'd NSGA-II epoch at mu=50k on 3-objective DTLZ2: DCD
    mating selection, gaussian variation, evaluation, and (mu + lambda)
    environmental selection over the 100k union. Both selections run
    ``nd_rank(impl='auto')``, i.e. the M=3 engine this metric exists to
    track — with the dominance-matrix path this configuration is
    O(fronts · n²) per generation and simply does not run at this scale
    on a CPU host (see bench.py --nd3 for the direct comparison)."""
    from deap_tpu import benchmarks as bm
    from deap_tpu import mo

    eval_batch = jax.vmap(lambda xi: bm.dtlz2(xi, MO_NOBJ))

    def gen_step(carry, key):
        x, w = carry
        k_sel, k_mut = jax.random.split(key)
        parents = x[mo.sel_tournament_dcd(k_sel, w, MO_POP)]
        off = jnp.clip(
            parents + 0.02 * jax.random.normal(k_mut, parents.shape),
            0.0, 1.0)
        woff = -eval_batch(off)  # minimisation -> weighted values
        xall = jnp.concatenate([x, off])
        wall = jnp.concatenate([w, woff])
        keep = mo.sel_nsga2(None, wall, MO_POP)
        return (xall[keep], wall[keep]), None

    @jax.jit
    def run(key, x, w):
        (x, w), _ = lax.scan(gen_step, (x, w),
                             jax.random.split(key, MO_NGEN))
        return w

    return run


def _mo_setup():
    from deap_tpu import benchmarks as bm

    x = jax.random.uniform(jax.random.key(5), (MO_POP, MO_DIM))
    w = -jax.vmap(lambda xi: bm.dtlz2(xi, MO_NOBJ))(x)
    return x, w


def mo_line(backend: str) -> dict:
    """The nsga2_pop50k_3obj_generations_per_sec headline row."""
    x, w = _mo_setup()
    run = make_run_nsga2_3obj()
    sync(run(jax.random.key(200), x, w))  # compile + warm
    times = []
    for r in range(MO_REPS):
        t0 = time.perf_counter()
        sync(run(jax.random.key(201 + r), x, w))
        times.append(time.perf_counter() - t0)
    times = sorted(times)
    median_dt = times[len(times) // 2]
    gens = MO_NGEN / median_dt
    return {
        "metric": "nsga2_pop50k_3obj_generations_per_sec",
        "value": round(gens, 4),
        "unit": "gens/sec",
        "backend": backend,
        "pop": MO_POP, "nobj": MO_NOBJ, "ngen": MO_NGEN,
        "best": round(MO_NGEN / times[0], 4),
        "spread_pct": round(100 * (times[-1] - times[0]) / median_dt, 1),
        "n_samples": len(times),
    }


def nd3_lines() -> list:
    """The acceptance measurement behind the M=3 engine: nd_rank at
    n=50k, 3 objectives, every impl, on the current backend — the new
    paths with the median-of-reps protocol, the matrix oracle once
    (it is the denominator, and it runs for minutes on a CPU host).
    Also verifies the auto path returns ranks bit-identical to the
    dominance-matrix oracle before any timing is reported."""
    from deap_tpu import mo

    n = MO_POP
    w = jax.random.normal(jax.random.key(7), (n, MO_NOBJ))
    rows = []
    ranks = {}
    for impl, reps in (("sweep", 3), ("dc", 3), ("auto", 3),
                       ("matrix", 1)):
        fn = jax.jit(lambda w, impl=impl: mo.nd_rank(w, impl=impl))
        if reps > 1:
            sync(fn(w))  # compile + warm; the single-shot matrix run
            # is timed cold — its compile seconds vanish next to the
            # minutes of peeling, and a second multi-minute run buys
            # no precision the speedup quotient needs
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            r = sync(fn(w))
            times.append(time.perf_counter() - t0)
        ranks[impl] = r
        times = sorted(times)
        rows.append({
            "metric": "nd_rank_pop50k_3obj_seconds",
            "impl": impl, "value": round(times[len(times) // 2], 4),
            "unit": "seconds", "n": n, "nobj": MO_NOBJ,
            "n_samples": len(times),
            "backend": jax.default_backend(),
        })
    import numpy as np

    exact = bool((np.asarray(ranks["auto"])
                  == np.asarray(ranks["matrix"])).all())
    by_impl = {r["impl"]: r["value"] for r in rows}
    rows.append({
        "metric": "nd_rank_pop50k_3obj_speedup_vs_matrix",
        "value": round(by_impl["matrix"] / by_impl["auto"], 1),
        "unit": "x", "auto_equals_matrix_oracle": exact,
        "backend": jax.default_backend(),
    })
    return rows


# --------------------------------------- probe overhead (pop=100k) ----

#: long enough that the per-RUN host costs of telemetry (the eager
#: gen-0 measure, the post-scan row decode, the journal writes) sit in
#: the same proportion a real run pays, not inflated ~5x by a short one
PROBE_NGEN = 100
PROBE_REPS = 4


def _headline_probes(n: int):
    """The probe set the headline config carries under --journal and
    --probes: vector-genome diversity, landscape stats, selection
    pressure + lineage — the full search-dynamics picture for a
    single-objective GA (FrontProbe is MO-only). Selection pressure is
    decimated to every 4th generation: its count pass is a serial CPU
    scatter over the 100k pool (~5 ms) and the statistic moves slowly;
    the gauges hold their value in between so every journal row still
    carries all 12 metrics."""
    from deap_tpu.telemetry.probes import (DiversityProbe, FitnessProbe,
                                           SelectionProbe)

    return [DiversityProbe(sample=256), FitnessProbe(),
            SelectionProbe(n=n, every=4)]


def make_run_xla_probed(tb, tel, probes):
    """The probed twin of :func:`make_run_xla`: the same eaSimple scan
    with the telemetry meter + probe pipeline threaded as carry, jitted
    ONCE — the steady-state formulation every long run and every
    jit-wrapped caller gets. (The ``algorithms.ea_simple`` convenience
    entry re-traces its eager scan per Python call; that one-time
    ~1 s trace cost is a per-call constant, not a per-generation probe
    cost, so the paired measurement jits both sides like the headline
    does.)"""
    from deap_tpu.algorithms import _tel_measure

    meter = tel.meter
    _tel = tel

    def gen_step(carry, xs):
        pop, ms = carry
        key, gen = xs
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, gather(pop, idx), tb, 0.5, 0.2)
        nevals = jnp.sum(~off.valid)
        off = evaluate_invalid(off, tb.evaluate)
        ms = _tel_measure(_tel, ms, nevals, off, gen, sel_idx=idx,
                          sel_pool=pop.size, parent_idx=idx)
        return (off, ms), ms

    @jax.jit
    def run(key, pop, ms0):
        (pop, _), rows = lax.scan(
            gen_step, (pop, ms0),
            (jax.random.split(key, PROBE_NGEN),
             jnp.arange(1, PROBE_NGEN + 1)))
        return pop.wvalues[:, 0], rows

    return run


def probe_overhead_lines(out_path: str = "BENCH_PROBES.json") -> list:
    """The probe acceptance measurement: the headline OneMax config
    (pop=100k) probe-off vs probe-on, back-to-back in ONE session (the
    only pairing that means anything on a noisy box — same protocol as
    the gp race), both sides jitted once like the headline's
    ``make_run_xla``. The probe-on side pays everything a steady-state
    run pays: the meter carry in the scan, the per-generation probe
    compute for 12 metrics, the post-scan row decode and the journal
    writes. ``bench_report.py --tripwire`` fails if the committed
    overhead exceeds 3%."""
    from deap_tpu.telemetry import RunTelemetry

    jax.config.update("jax_platforms", "cpu")
    tb, pop = _setup()
    probes = _headline_probes(POP)

    journal_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "bench_probes_journal.jsonl")
    from deap_tpu.algorithms import _tel_declare

    tel = RunTelemetry(journal_path)
    tel.__enter__()
    tel.begin_run("bench_probe_overhead", tb, declare=_tel_declare,
                  probes=probes, ngen=PROBE_NGEN, n=POP)
    ms0 = tel.meter.init()

    probed = make_run_xla_probed(tb, tel, probes)

    # make_run_xla is pinned to the headline NGEN; the off side needs
    # the same PROBE_NGEN scan, identically jitted
    def base_step(pop, key):
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, gather(pop, idx), tb, 0.5, 0.2)
        return evaluate_invalid(off, tb.evaluate), None

    @jax.jit
    def base(key, pop):
        pop, _ = lax.scan(base_step, pop,
                          jax.random.split(key, PROBE_NGEN))
        return pop.wvalues[:, 0]

    def run_off():
        sync(base(jax.random.key(77), pop))

    def run_on():
        w, rows = probed(jax.random.key(77), pop, ms0)
        sync(w)
        # the host half of the telemetry contract: decode + journal
        tel.journal.meter_rows(tel.meter, rows)

    try:
        run_off()  # compile + warm
        run_on()
        t_off, t_on = [], []
        # INTERLEAVED off/on reps: this box's load drifts on the
        # minute scale, so two sequential blocks measure the drift,
        # not the probes (first attempt read 12% "overhead" that a
        # per-probe attribution showed was pure block-ordering noise)
        for _ in range(PROBE_REPS):
            t0 = time.perf_counter()
            run_off()
            t_off.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            run_on()
            t_on.append(time.perf_counter() - t0)
        t_off, t_on = sorted(t_off), sorted(t_on)
        tel.end_run("bench_probe_overhead", ngen=PROBE_NGEN)
    finally:
        tel.__exit__(None, None, None)
    env = _env_fingerprint("cpu")
    n_metrics = sum(len(p.metric_names) for p in probes)
    rows = []
    for name, times in (("off", t_off), ("on", t_on)):
        med = times[len(times) // 2]
        rows.append({
            "metric": f"onemax_pop100k_probe_{name}_generations_per_sec",
            "value": round(PROBE_NGEN / med, 3), "unit": "gens/sec",
            "backend": "cpu", "pop": POP, "ngen": PROBE_NGEN,
            "n_samples": len(times),
            "best": round(PROBE_NGEN / times[0], 3),
            "spread_pct": round(100 * (times[-1] - times[0]) / med, 1),
            "env": env,
        })
        if name == "on":
            rows[-1]["n_probe_metrics"] = n_metrics
    # overhead compares MIN-of-reps: on a multi-tenant box the noise is
    # one-sided (contention only ever slows a rep down), so the paired
    # minima estimate the deterministic probe cost where medians-of-few
    # measure whoever else was running (observed 97% spread)
    rows.append({
        "metric": "onemax_pop100k_probe_overhead_pct",
        "value": round(100 * (t_on[0] - t_off[0]) / t_off[0], 2),
        "unit": "pct", "threshold_pct": 3.0, "estimator": "min_of_reps",
        "env": env,
    })
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": env,
            "config": {"pop": POP, "length": LENGTH, "ngen": PROBE_NGEN,
                       "reps": PROBE_REPS,
                       "probes": [type(p).__name__ for p in probes]},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# ---------------------------------- fused variation plane (pop=100k) ----

#: the fusion pair's scan length / interleaved reps (probe-bench
#: protocol: min-of-reps, contention noise is one-sided)
FUSION_NGEN = 50
FUSION_REPS = 3
#: rounds per timed sample of the GP-compaction pair — each round is
#: one generation's worth of flag→index work, microseconds to
#: milliseconds, so a sample aggregates many
COMPACTION_ROUNDS = 100
COMPACTION_POP = POP


def _fusion_steps(tb):
    """The paired headline-config generation steps: identical select +
    varAnd + evaluate chain, unfused vs fused — the ONLY difference is
    the variation plane's execution (`fused=False` composition vs the
    fused one-pass with the selection gather composed in). Bit-identity
    of the two scans is asserted before any timing (a fused plane that
    drifted would make the speedup row meaningless)."""
    def unfused_step(pop, key):
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, gather(pop, idx), tb, 0.5, 0.2,
                      fused=False)
        return evaluate_invalid(off, tb.evaluate), None

    def fused_step(pop, key):
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, pop, tb, 0.5, 0.2, fused="xla",
                      sel_idx=idx)
        return evaluate_invalid(off, tb.evaluate), None

    def mk(step):
        @jax.jit
        def run(key, pop):
            pop, _ = lax.scan(step, pop,
                              jax.random.split(key, FUSION_NGEN))
            return pop.wvalues[:, 0]
        return run

    return mk(unfused_step), mk(fused_step)


def fusion_lines(out_path: str = "BENCH_FUSION.json",
                 coldstart: bool = True) -> list:
    """The fused-variation acceptance measurement: the headline OneMax
    config (pop=100k) with the variation plane unfused vs fused,
    back-to-back interleaved in ONE session (min-of-reps), after
    asserting the two scans are bit-identical; plus the measured
    RNG-bound fraction (the bit-parity contract forces both sides to
    draw the same per-gene threefry masks, which dominate the CPU
    step — the context without which the speedup row misreads); the GP
    variation-compaction pair (host round trip vs on-device
    prefix-sum, same protocol) plus the ``compaction='auto'``
    resolution; and — unless ``coldstart=False`` — the persistent-
    compile-cache cold/warm ``time_to_first_generation`` rows.
    ``bench_report.py --tripwire`` gates the SHIPPED configuration:
    the fused default must not fall >10% below unfused, and auto
    compaction must track the measured winner. TPU rows (where the
    fused kernel's one-HBM-pass actually pays) come from
    ``_fusion_tpu_probe.py`` in a relay window and are cached-flagged
    like every TPU bench row."""
    from deap_tpu.ops import variation as _V

    jax.config.update("jax_platforms", "cpu")
    tb, pop = _setup()
    run_off, run_on = _fusion_steps(tb)

    w_off = run_off(jax.random.key(50), pop)
    w_on = run_on(jax.random.key(50), pop)
    if not bool((w_off == w_on).all()):
        raise AssertionError(
            "fused variation plane diverged from the unfused "
            "composition — refusing to time a wrong answer")

    t_off, t_on = [], []
    for _ in range(FUSION_REPS):
        t0 = time.perf_counter()
        sync(run_off(jax.random.key(51), pop))
        t_off.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        sync(run_on(jax.random.key(51), pop))
        t_on.append(time.perf_counter() - t0)
    t_off, t_on = sorted(t_off), sorted(t_on)

    # the shared-RNG denominator: both sides draw these exact bits
    # (bit-parity), so no fusion can touch this fraction of the step
    plan = _V.resolve_plan(tb)
    g0 = jax.tree_util.tree_leaves(pop.genomes)[0]
    masks = jax.jit(lambda k: _V.var_and_masks(
        k, POP, LENGTH, 0.5, 0.2, plan, g0.dtype))
    sync(masks(jax.random.key(52))[-2])
    t_rng = []
    for _ in range(FUSION_REPS):
        t0 = time.perf_counter()
        sync(masks(jax.random.key(52))[-2])
        t_rng.append(time.perf_counter() - t0)
    rng_pct = round(100 * FUSION_NGEN * min(t_rng) / t_off[0], 1)

    env = _env_fingerprint("cpu")
    rows = []
    for name, times in (("unfused", t_off), ("fused", t_on)):
        med = times[len(times) // 2]
        rows.append({
            "metric": f"onemax_pop100k_varplane_{name}"
                      "_generations_per_sec",
            "value": round(FUSION_NGEN / med, 3), "unit": "gens/sec",
            "backend": "cpu", "pop": POP, "ngen": FUSION_NGEN,
            "n_samples": len(times),
            "best": round(FUSION_NGEN / times[0], 3),
            "spread_pct": round(100 * (times[-1] - times[0]) / med, 1),
            "env": env,
        })
    rows.append({
        "metric": "onemax_pop100k_varplane_fused_speedup_x",
        "value": round(t_off[0] / t_on[0], 3), "unit": "x",
        "estimator": "min_of_reps", "bit_identical": True,
        # the bit-parity ceiling on this backend: with rng_bound_pct of
        # the step spent drawing masks both sides must share bit-for-
        # bit, the ideal fused speedup is 1/(rng_bound_pct/100) — the
        # fused win lives on TPU (one HBM pass vs 6+), this row guards
        # against the default regressing on CPU
        "rng_bound_pct": rng_pct,
        "env": env,
    })

    # ---- GP variation-compaction pair (host vs device vs auto) ----
    from deap_tpu.gp.loop import make_compaction_pipelines, \
        resolve_compaction

    host_fn, dev_fn = make_compaction_pipelines(0.5, 0.1)
    n = COMPACTION_POP
    # parity gate before timing (same key → identical index arrays)
    (h, hc), (d, dc) = host_fn(jax.random.key(60), n), \
        dev_fn(jax.random.key(60), n)
    assert hc == dc and all(
        bool((a == b).all()) for a, b in zip(h, d)), \
        "compaction pipelines diverged"

    def sample(fn):
        t0 = time.perf_counter()
        for r in range(COMPACTION_ROUNDS):
            fn(jax.random.key(61 + r), n)
        return time.perf_counter() - t0

    sample(host_fn), sample(dev_fn)  # warm both shape classes
    ct_host, ct_dev = [], []
    for _ in range(FUSION_REPS):
        ct_host.append(sample(host_fn))
        ct_dev.append(sample(dev_fn))
    ct_host, ct_dev = sorted(ct_host), sorted(ct_dev)
    for name, times in (("host", ct_host), ("device", ct_dev)):
        med = times[len(times) // 2]
        rows.append({
            "metric": f"gp_compaction_pop100k_{name}_rounds_per_sec",
            "value": round(COMPACTION_ROUNDS / med, 2),
            "unit": "rounds/sec", "backend": "cpu", "pop": n,
            "n_samples": len(times),
            "best": round(COMPACTION_ROUNDS / times[0], 2),
            "spread_pct": round(100 * (times[-1] - times[0]) / med, 1),
            "env": env,
        })
    resolved = resolve_compaction("auto")
    t_auto = ct_host if resolved == "host" else ct_dev
    t_best = min(ct_host[0], ct_dev[0])
    rows.append({
        "metric": "gp_compaction_pop100k_auto_vs_best_x",
        # the shipped guarantee: compaction='auto' resolves to the
        # measured winner for this backend (device on accelerators,
        # where the host fetch is a real transfer+sync; host on CPU,
        # where numpy's serial scan is bandwidth-optimal)
        "value": round(t_best / t_auto[0], 3), "unit": "x",
        "resolved": resolved, "backend": "cpu",
        "estimator": "min_of_reps", "bit_identical": True,
        "threshold_x": 0.9, "env": env,
    })

    if coldstart:
        rows.extend(coldstart_lines())

    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": env,
            "config": {"pop": POP, "length": LENGTH,
                       "ngen": FUSION_NGEN, "reps": FUSION_REPS,
                       "compaction_rounds": COMPACTION_ROUNDS},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# -------------------------------- compile-cache cold-start economics ----

def _coldstart_child(cache_dir: str, mode: str = "warm") -> None:
    """Measure time_to_first_generation in THIS fresh process, split
    into the ISSUE-18 per-phase waterfall: process import → cache open
    → artifact deserialize / compile → first step. ``mode``:

    - ``cold``/``warm`` — persistent XLA compile cache only (empty vs
      populated ``cache_dir``); ``cold`` also POPULATES the sibling
      artifact store so the ``artifact`` run has blobs to load;
    - ``artifact`` — compile cache AND the executable artifact store:
      the program deserializes (``jax.experimental.
      serialize_executable``) instead of compiling.

    Prints one JSON line with the phase dict, the total, and a sha256
    digest of the first generation's fitness vector — the parent's
    bit-identity gate across all three modes."""
    import hashlib

    import numpy as np

    t_entry = time.perf_counter()
    spawn_wall = float(os.environ.get("BENCH_COLDSTART_T0") or 0.0)
    import_s = max(0.0, time.time() - spawn_wall) if spawn_wall else None

    jax.config.update("jax_platforms", "cpu")
    t0 = time.perf_counter()
    _compilecache.enable(cache_dir)
    store = None
    if mode in ("cold", "artifact"):
        from deap_tpu.support.artifacts import enable_artifact_store
        store = enable_artifact_store(
            os.path.join(cache_dir, "artifacts"))
    cache_open_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    tb, pop = _setup()
    run_off, _ = _fusion_steps(tb)
    key = jax.random.key(70)
    lowered = run_off.lower(key, pop)
    setup_s = time.perf_counter() - t0

    from deap_tpu.telemetry.costs import _hlo_fingerprint
    hlo = _hlo_fingerprint(lowered)
    deserialize_s = compile_s = 0.0
    compiled = None
    if store is not None:
        t0 = time.perf_counter()
        compiled = store.get("bench.coldstart", hlo)
        deserialize_s = time.perf_counter() - t0
    from_artifact = compiled is not None
    if compiled is None:
        t0 = time.perf_counter()
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        if store is not None:
            store.put("bench.coldstart", hlo, compiled)

    t0 = time.perf_counter()
    out = np.asarray(compiled(key, pop))
    first_step_s = time.perf_counter() - t0

    phases = {"cache_open": round(cache_open_s, 4),
              "setup_lower": round(setup_s, 4),
              "artifact_deserialize": round(deserialize_s, 4),
              "compile": round(compile_s, 4),
              "first_step": round(first_step_s, 4)}
    if import_s is not None:
        phases["process_import"] = round(import_s, 4)
    print(json.dumps({
        "time_to_first_generation_seconds":
            round(time.perf_counter() - t_entry, 4),
        "phases": phases, "mode": mode,
        "from_artifact": from_artifact,
        "digest": hashlib.sha256(out.tobytes()).hexdigest()}))


def coldstart_lines(out_path: str = "BENCH_COLDSTART.json") -> list:
    """The ROADMAP-item-5 / ISSUE-18 metric: per-phase
    ``time_to_first_generation`` for a fresh process under three cache
    regimes, each in its own subprocess so compilation state cannot
    leak —

    - ``cold``: empty persistent compile cache (populates both the
      XLA cache and the executable artifact store on the way);
    - ``warm``: the now-populated XLA compile cache, **no** artifact
      store — the "fully-warm" baseline;
    - ``artifact``: the artifact store active — first generation via
      ``deserialize_and_load`` instead of a compile.

    Committed as ``BENCH_COLDSTART.json``; ``bench_report.py``'s
    ``coldstart_tripwire`` gates artifact ≤ 1.5× warm and digest
    identity of all three modes."""
    import shutil
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="bench_coldstart_cache_")
    me = os.path.abspath(__file__)
    env = dict(os.environ, JAX_PLATFORMS="cpu", DEAP_TPU_SKIP_PROBE="1")
    env.pop("DEAP_TPU_COMPILE_CACHE", None)  # the child gets it by arg
    env.pop("DEAP_TPU_ARTIFACT_CACHE", None)
    results = {}
    try:
        for phase in ("cold", "warm", "artifact"):
            env["BENCH_COLDSTART_T0"] = repr(time.time())
            r = subprocess.run(
                [sys.executable, me, "--coldstart-child", cache_dir,
                 phase],
                env=env, capture_output=True, text=True, timeout=600)
            d = None
            for ln in (r.stdout or "").splitlines():
                try:
                    cand = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if "time_to_first_generation_seconds" in cand:
                    d = cand
            if d is None:
                print(f"bench: coldstart {phase} child failed; stderr "
                      f"tail: {(r.stderr or '')[-300:]}",
                      file=sys.stderr)
                return []
            results[phase] = d
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    envfp = _env_fingerprint("cpu")
    ttfg = {p: results[p]["time_to_first_generation_seconds"]
            for p in results}
    rows = [{
        "metric": f"onemax_pop100k_time_to_first_generation_{p}_seconds",
        "value": ttfg[p], "unit": "seconds", "backend": "cpu",
        "pop": POP,
        "compile_cache": "empty" if p == "cold" else "warm",
        "artifact_store": p != "warm",
        "from_artifact": results[p]["from_artifact"],
        "phases": results[p]["phases"],
        "env": envfp,
    } for p in ("cold", "warm", "artifact")]
    rows.append({
        "metric": "onemax_pop100k_coldstart_warm_speedup_x",
        "value": round(ttfg["cold"] / ttfg["warm"], 3),
        "unit": "x", "env": envfp,
    })
    rows.append({
        "metric": "coldstart_artifact_vs_warm_x",
        "value": round(ttfg["artifact"] / ttfg["warm"], 3),
        "unit": "x", "gate": "<= 1.5",
        "note": "artifact-warm first generation relative to a fully-"
                "warm (populated XLA cache) fresh process",
        "artifact_loaded": results["artifact"]["from_artifact"],
        "env": envfp,
    })
    rows.append({
        "metric": "coldstart_artifact_digest_identical",
        "value": (results["artifact"]["digest"]
                  == results["cold"]["digest"]
                  == results["warm"]["digest"]),
        "unit": "bool", "gate": "== true",
        "digest": results["cold"]["digest"][:16],
        "env": envfp,
    })
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": envfp,
            "config": {"pop": POP, "length": LENGTH,
                       "ngen": FUSION_NGEN},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# ------------------------------------- multi-tenant serving (1k runs) ----

#: the serving scenario: 1k concurrent small tenants per workload —
#: the north-star shape (millions of users, mostly tiny jobs)
SERVING_TENANTS = 1000
SERVING_LANES = 1024          # pow-2 lane lattice point covering 1k
SERVING_ONEMAX = dict(pop=16, length=32, ngen=10)
SERVING_CMA = dict(dim=8, lambda_=8, ngen=10)
SERVING_REPS = 3


def _serving_onemax_setup():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    spec = FitnessSpec((1.0,))
    pop0 = init_population(
        jax.random.key(0), SERVING_ONEMAX["pop"],
        ops.bernoulli_genome(SERVING_ONEMAX["length"]), spec)
    return tb, pop0


def _serving_min_of_reps(fn, reps=SERVING_REPS):
    fn()  # compile + warm
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return min(times)


def serving_lines(out_path: str = "BENCH_SERVING.json") -> list:
    """The multi-run serving acceptance measurement (ROADMAP item 1):
    aggregate generations/sec for 1k concurrent small tenants driven
    through ONE vectorized multi-run scan
    (:class:`deap_tpu.serving.MultiRunEngine`, 1024-lane lattice
    batch) vs the SAME 1k jobs run sequentially in the same session —
    min-of-reps on both sides, for a OneMax GA bucket and a CMA-ES
    ask-tell bucket.

    The sequential baseline is the STEELMAN: one pre-jitted solo
    runner (the exact factory step the engine vmaps) reused across all
    1k tenants, so it pays one dispatch per tenant and zero retraces.
    The library's actual sequential entry point (``algorithms.
    ea_simple`` per job) retraces its freshly-closed step every call
    and lands orders of magnitude slower — committed as an ungated
    context row (measured on a subsample, labelled as such), because
    bounding exactly that retrace churn is what the serving layer's
    shape buckets are for."""
    from deap_tpu import algorithms as algos
    from deap_tpu.serving.multirun import MultiRunEngine
    from deap_tpu.strategies import cma as _cma

    n = SERVING_TENANTS
    rows = []
    envfp = _env_fingerprint("cpu")

    # ------------------------------------------------ OneMax GA bucket ----
    tb, pop0 = _serving_onemax_setup()
    ngen = SERVING_ONEMAX["ngen"]
    keys = jax.vmap(jax.random.key)(jnp.arange(1000, 1000 + n))
    pops = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n,) + a.shape), pop0)

    step = algos.make_ea_simple_step(tb, 0.5, 0.2)

    def solo(key, pop):
        pop, hof, _ = algos._pop_loop_init(pop, tb, 0, None)
        (pop, hof), _ = lax.scan(step, (pop, hof),
                                 jax.random.split(key, ngen))
        return pop

    solo_j = jax.jit(solo)

    def run_sequential():
        for i in range(n):
            out = solo_j(keys[i], pop0)
        sync(out.fitness)

    eng = MultiRunEngine("ea_simple", tb)

    def run_batched():
        b = eng.pack_fresh(keys, pops, ngen,
                           {"cxpb": 0.5, "mutpb": 0.2},
                           n_lanes=SERVING_LANES)
        b, _ = eng.advance(b, ngen)
        sync(b["shadow"][0].fitness)

    seq_s = _serving_min_of_reps(run_sequential, reps=2)
    bat_s = _serving_min_of_reps(run_batched)
    total_gens = n * ngen
    rows += [
        {"metric": "serving_onemax_1k_sequential_gens_per_sec",
         "value": round(total_gens / seq_s, 1), "unit": "gens/sec",
         "tenants": n, "seconds": round(seq_s, 4),
         "baseline": "steelman (pre-jitted solo runner, zero retraces)",
         **SERVING_ONEMAX, "env": envfp},
        {"metric": "serving_onemax_1k_batched_gens_per_sec",
         "value": round(total_gens / bat_s, 1), "unit": "gens/sec",
         "tenants": n, "lanes": SERVING_LANES,
         "seconds": round(bat_s, 4), **SERVING_ONEMAX, "env": envfp},
        {"metric": "serving_onemax_1k_batched_vs_sequential_x",
         "value": round(seq_s / bat_s, 2), "unit": "x", "env": envfp},
    ]

    # today's library entry point, per job (retraces every call):
    # subsampled — the full 1k would take ~30 min of pure recompiles,
    # which is precisely the pathology the bucket lattice removes
    sub = 5
    t0 = time.perf_counter()
    for i in range(sub):
        algos.ea_simple(keys[i], pop0, tb, 0.5, 0.2, ngen)
    per_tenant = (time.perf_counter() - t0) / sub
    rows.append({
        "metric": "serving_onemax_entrypoint_seconds_per_tenant",
        "value": round(per_tenant, 3), "unit": "seconds/tenant",
        "n_measured": sub, "extrapolated": True,
        "note": ("algorithms.ea_simple per job retraces its step "
                 "closure every call; ungated context row"),
        "env": envfp})

    # ------------------------------------------------ CMA-ES bucket ----
    dim, lam = SERVING_CMA["dim"], SERVING_CMA["lambda_"]
    ngen_c = SERVING_CMA["ngen"]
    strat = _cma.Strategy(centroid=[3.0] * dim, sigma=0.5, lambda_=lam)
    tbc = Toolbox()
    tbc.register("evaluate", lambda g: (g ** 2).sum(-1))
    tbc.register("generate", strat.generate)
    tbc.register("update", strat.update)
    st0 = strat.initial_state()
    keys_c = jax.vmap(jax.random.key)(jnp.arange(5000, 5000 + n))
    states = jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a[None], (n,) + a.shape), st0)

    step_c = algos.make_ea_generate_update_step(tbc, strat.spec, lam)

    def solo_c(key, st):
        (st, _), _ = lax.scan(step_c, (st, None),
                              jax.random.split(key, ngen_c))
        return st

    solo_cj = jax.jit(solo_c)

    def run_sequential_c():
        for i in range(n):
            out = solo_cj(keys_c[i], st0)
        sync(out.centroid)

    eng_c = MultiRunEngine("ea_generate_update", tbc, spec=strat.spec,
                           state_template=st0)

    def run_batched_c():
        b = eng_c.pack_fresh(keys_c, states, ngen_c,
                             n_lanes=SERVING_LANES)
        b, _ = eng_c.advance(b, ngen_c)
        sync(b["shadow"][0].centroid)

    seq_c = _serving_min_of_reps(run_sequential_c, reps=2)
    bat_c = _serving_min_of_reps(run_batched_c)
    total_c = n * ngen_c
    rows += [
        {"metric": "serving_cma_1k_sequential_gens_per_sec",
         "value": round(total_c / seq_c, 1), "unit": "gens/sec",
         "tenants": n, "seconds": round(seq_c, 4),
         "baseline": "steelman (pre-jitted solo runner, zero retraces)",
         **SERVING_CMA, "env": envfp},
        {"metric": "serving_cma_1k_batched_gens_per_sec",
         "value": round(total_c / bat_c, 1), "unit": "gens/sec",
         "tenants": n, "lanes": SERVING_LANES,
         "seconds": round(bat_c, 4), **SERVING_CMA, "env": envfp},
        {"metric": "serving_cma_1k_batched_vs_sequential_x",
         "value": round(seq_c / bat_c, 2), "unit": "x", "env": envfp},
    ]

    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": envfp,
            "config": {"tenants": n, "lanes": SERVING_LANES,
                       "onemax": SERVING_ONEMAX, "cma": SERVING_CMA,
                       "reps": SERVING_REPS},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# --------------------- batched GP generations serving (ISSUE 14) ----

#: the GP serving scenario: N small symbolic-regression tenants, each
#: an independent run packed on the run axis of ONE union-mask scan
GP_SERVING = dict(tenants=64, pop=64, max_len=32, points=64, ngen=16)
GP_SERVING_ISLAND = dict(tenants=16, n_islands=4, island_size=16,
                         freq=2, mig_k=2, length=12, ngen=8)


def gp_serving_lines(out_path: str = "BENCH_GP_SERVING.json") -> list:
    """The batched-GP serving acceptance measurement (ISSUE 14): N
    symbolic-regression tenants through ONE run-axis scan
    (:class:`deap_tpu.serving.GpMultiRunEngine`) vs the SAME N jobs
    run sequentially through the solo host-dispatch loop — min-of-reps
    both sides — plus the island-epoch pair
    (:class:`deap_tpu.serving.IslandMultiRunEngine` vs a pre-jitted
    solo epoch driver) and a same-session solo ``bench_gp``
    headline row (the ``--gp-race`` number must not regress while the
    batched mode exists in the same build).

    The sequential baseline is the STEELMAN: one warm
    :func:`~deap_tpu.gp.loop.make_symbreg_loop` runner reused across
    all tenants (its per-mask jitted parts stay cached), so the gap
    measured is exactly the per-generation host dispatch × N the run
    axis amortises — not retrace churn. Bit-identity of the batched
    lanes vs solo is asserted and committed as its own row; the
    tripwire requires it True."""
    import numpy as np

    import bench_gp
    from deap_tpu import gp as _gp
    from deap_tpu.gp.loop import make_symbreg_loop
    from deap_tpu.gp.tree import make_generator
    from deap_tpu.parallel.island import island_init, make_island_step
    from deap_tpu.serving import (GpJobSpec, GpMultiRunEngine,
                                  IslandJobSpec, IslandMultiRunEngine)

    rows = []
    envfp = _env_fingerprint("cpu")

    # ------------------------------------------- symbreg GP bucket ----
    n, ngen = GP_SERVING["tenants"], GP_SERVING["ngen"]
    pop, ml, pts = (GP_SERVING["pop"], GP_SERVING["max_len"],
                    GP_SERVING["points"])
    pset = _gp.math_set(n_args=1)
    X = jnp.linspace(-1.0, 1.0, pts, endpoint=False)[:, None]
    y = X[:, 0] ** 3 + X[:, 0] ** 2 + X[:, 0]
    tree_gen = make_generator(pset, ml, 1, 3, "full")
    founders = [jax.vmap(tree_gen)(
        jax.random.split(jax.random.key(i), pop)) for i in range(n)]
    keys = [jax.random.key(9000 + i) for i in range(n)]
    hyper = {"cxpb": 0.5, "mutpb": 0.2}

    solo = make_symbreg_loop(pset, ml, X, y, cxpb=0.5, mutpb=0.2)
    # two warm trajectories: distinct growth paths hit different
    # mask-lattice classes before any timed rep (bench_gp protocol)
    solo(keys[0], founders[0], ngen)
    solo(keys[1], founders[1], ngen)

    def run_sequential():
        for i in range(n):
            solo(keys[i], founders[i], ngen)

    spec = GpJobSpec(pset=pset, max_len=ml, X=X, y=y)
    eng = GpMultiRunEngine(spec)

    def run_batched():
        b = eng.pack_fresh(keys, founders, ngen, hyper)
        b, _ = eng.advance(b, ngen)
        sync(b["carry"]["genomes"]["nodes"])

    seq_s = _serving_min_of_reps(run_sequential, reps=2)
    bat_s = _serving_min_of_reps(run_batched)

    # bit-identity: every batched lane vs its solo run, full results
    solo_res = [solo(keys[i], founders[i], ngen) for i in range(n)]
    b = eng.pack_fresh(keys, founders, ngen, hyper)
    b, seg = eng.advance(b, ngen)
    bat_res = [eng.lane_result(eng.unpack(b, i),
                               eng.lane_records((seg,), i))
               for i in range(n)]

    def _eq(a, c):
        return jax.tree_util.tree_all(jax.tree_util.tree_map(
            lambda u, v: bool(np.array_equal(np.asarray(u),
                                             np.asarray(v))), a, c))

    bit = all(
        _eq({k: s[k] for k in ("genomes", "depths", "fitness",
                               "best_genome")},
            {k: r[k] for k in ("genomes", "depths", "fitness",
                               "best_genome")})
        and s["nevals"] == r["nevals"]
        and s["best_fitness"] == r["best_fitness"]
        for s, r in zip(solo_res, bat_res))

    total = n * ngen
    rows += [
        {"metric": "gp_serving_symbreg_64_sequential_gens_per_sec",
         "value": round(total / seq_s, 1), "unit": "gens/sec",
         "tenants": n, "seconds": round(seq_s, 4),
         "baseline": ("steelman (one warm make_symbreg_loop reused, "
                      "zero retraces)"),
         "pop": pop, "max_len": ml, "points": pts, "ngen": ngen,
         "env": envfp},
        {"metric": "gp_serving_symbreg_64_batched_gens_per_sec",
         "value": round(total / bat_s, 1), "unit": "gens/sec",
         "tenants": n, "seconds": round(bat_s, 4),
         "pop": pop, "max_len": ml, "points": pts, "ngen": ngen,
         "env": envfp},
        {"metric": "gp_serving_symbreg_64_batched_vs_sequential_x",
         "value": round(seq_s / bat_s, 2), "unit": "x", "env": envfp},
        {"metric": "gp_serving_bit_identical", "value": bool(bit),
         "unit": "bool", "lanes_checked": n, "env": envfp},
    ]

    # ------------------------------------------- island-epoch pair ----
    ni, epochs = (GP_SERVING_ISLAND["tenants"],
                  GP_SERVING_ISLAND["ngen"])
    isl, size = (GP_SERVING_ISLAND["n_islands"],
                 GP_SERVING_ISLAND["island_size"])
    freq, mig_k = GP_SERVING_ISLAND["freq"], GP_SERVING_ISLAND["mig_k"]
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    inits = [island_init(jax.random.key(i), isl, size,
                         ops.bernoulli_genome(
                             GP_SERVING_ISLAND["length"]),
                         FitnessSpec((1.0,))) for i in range(ni)]
    ikeys = [jax.random.key(7000 + i) for i in range(ni)]
    istep = make_island_step(tb, 0.5, 0.2, freq, mig_k)

    def solo_island(key, pops):
        # the solo epoch driver's exact fold_in(key, epoch) schedule,
        # rolled into one jitted program — the steelman again
        def body(pops, e):
            return istep(jax.random.fold_in(key, e), pops), None
        pops, _ = lax.scan(body, pops, jnp.arange(epochs))
        return pops

    solo_ij = jax.jit(solo_island)

    def run_sequential_i():
        for i in range(ni):
            out = solo_ij(ikeys[i], inits[i])
        sync(out.fitness)

    ieng = IslandMultiRunEngine(tb, IslandJobSpec(isl, size, freq,
                                                  mig_k))

    def run_batched_i():
        b = ieng.pack_fresh(ikeys, inits, epochs,
                            {"cxpb": 0.5, "mutpb": 0.2})
        b, _ = ieng.advance(b, epochs)
        sync(b["carry"]["pops"].fitness)

    seq_i = _serving_min_of_reps(run_sequential_i, reps=2)
    bat_i = _serving_min_of_reps(run_batched_i)
    total_i = ni * epochs
    rows += [
        {"metric": "gp_serving_island_16_sequential_epochs_per_sec",
         "value": round(total_i / seq_i, 1), "unit": "epochs/sec",
         "tenants": ni, "seconds": round(seq_i, 4),
         "baseline": "steelman (pre-jitted solo epoch scan)",
         "n_islands": isl, "island_size": size, "freq": freq,
         "mig_k": mig_k, "ngen": epochs, "env": envfp},
        {"metric": "gp_serving_island_16_batched_epochs_per_sec",
         "value": round(total_i / bat_i, 1), "unit": "epochs/sec",
         "tenants": ni, "seconds": round(bat_i, 4),
         "n_islands": isl, "island_size": size, "freq": freq,
         "mig_k": mig_k, "ngen": epochs, "env": envfp},
        {"metric": "gp_serving_island_16_batched_vs_sequential_x",
         "value": round(seq_i / bat_i, 2), "unit": "x", "env": envfp},
    ]

    # ----------------------------- same-session solo headline row ----
    # the --gp-race number, re-measured in THIS session: the tripwire
    # compares it against the committed BENCH_GP.json so a batched-mode
    # regression of the solo loop can't hide behind a stale headline
    pset_r = _gp.math_set(n_args=1)
    pset_r.arity_table()
    Xr, yr = bench_gp._X_y()
    solo_row = bench_gp.new_loop_row(pset_r, Xr, yr)
    solo_row["env"] = envfp
    solo_row["note"] = ("same-session solo headline "
                        "(gp-race unregressed gate)")
    rows.append(solo_row)

    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": envfp,
            "config": {"gp": GP_SERVING, "island": GP_SERVING_ISLAND,
                       "reps": SERVING_REPS},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# ------------------------------ network service plane (ISSUE 11) ----

SERVICE_N = 1000            # tenants through real sockets
SERVICE_CLIENTS = 8         # concurrent client threads (one core —
#                             more threads only thrash the GIL)
SERVICE_REPS = 3            # interleaved in-process/socket pairs
#: the 1k-tenant job. The service's intrinsic per-tenant cost is
#: FIXED (~0.9 ms: wire encode + JSON + HTTP, measured by phase
#: accounting) — at ngen=10 that fixed cost is a sixth of the whole
#: job, so the committed overhead ratio uses a 30-generation job
#: (still tiny) with the config explicit here
SERVICE_JOB = dict(pop=16, length=32, ngen=30)
SERVICE_SEG = 5
SERVICE_LANES_FIXED = 64    # in-process == service lane budget
SERVICE_BURST_N = 320       # autoscale pair: bursty load size
SERVICE_BURSTS = 4
SERVICE_BURST_GAP_S = 0.5
#: burst-job config — deliberately the dispatch/boundary-bound regime
#: (tiny pops, many tenants): each segment boundary costs a FIXED
#: host overhead plus ~1 ms/resident, so packing more residents per
#: batch amortizes the fixed cost — the same regime where the PR 7
#: multirun engine measured its 6.8× — and a bigger lane budget also
#: admits a whole burst at once, collapsing queue waits. (The
#: opposite, device-bound regime — pop=1024 — was measured too: one
#: CPU core is already saturated at 8 lanes there, so no lane budget
#: can buy throughput without parallel hardware; see ROADMAP.)
SERVICE_BURST_JOB = dict(pop=16, length=32, ngen=160)
#: the autoscaler's ceiling EXCEEDS the backlog (512 > 320 jobs): the
#: demonstrated win is admission — the whole burst backlog becomes
#: resident once the ceiling is reached, instead of queueing behind
#: 8 fixed lanes for a full job duration
SERVICE_BURST_MAX_LANES = 512


def _service_problem():
    """The service-bench problem factory: per-tenant seeded OneMax
    jobs that are bit-reproducible from (tenant_id, params) alone —
    the same factory feeds the in-process reference and the socket
    run, so equal digests mean the transport added nothing."""
    from deap_tpu.serving import Job

    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    spec = FitnessSpec((1.0,))

    def onemax(tid, params):
        seed = int(params["seed"])
        pop = init_population(
            jax.random.key(seed),
            int(params.get("pop", SERVICE_JOB["pop"])),
            ops.bernoulli_genome(
                int(params.get("length", SERVICE_JOB["length"]))),
            spec)
        return Job(tenant_id=tid, family="ea_simple", toolbox=tb,
                   key=jax.random.key(10_000 + seed), init=pop,
                   ngen=int(params.get("ngen", SERVICE_JOB["ngen"])),
                   hyper={"cxpb": 0.5, "mutpb": 0.2},
                   program="svc_onemax")

    return onemax


def _service_sched_kwargs(max_lanes):
    # fair_quantum off + checkpoint only on eviction: the pair measures
    # transport/control overhead, not checkpoint traffic
    return dict(max_lanes=max_lanes, segment_len=SERVICE_SEG,
                fair_quantum=None, checkpoint_every=0,
                telemetry=False)


def _service_wait_p99(registry, bucket_label=None):
    """Bucket-resolution queue-wait p99 across every bucket child."""
    from deap_tpu.telemetry.metrics import Histogram

    hist = registry._instruments.get("deap_serving_queue_wait_seconds")
    if not isinstance(hist, Histogram) or not hist._children:
        return None
    worst = 0.0
    for key in list(hist._children):
        q = hist.quantile(0.99, **dict(zip(hist.labels, key)))
        if q is not None:
            worst = max(worst, q)
    return worst


def _journal_wait_p99(journal_rows):
    """EXACT queue-wait p99 from the scheduler's per-admission
    ``wait_s`` journal samples — the Prometheus histogram only has
    bucket resolution, which flaps at bucket edges; the committed
    off/on comparison uses the exact values."""
    waits = [r["wait_s"] for r in journal_rows
             if r.get("kind") in ("tenant_admitted", "tenant_resumed")
             and isinstance(r.get("wait_s"), (int, float))]
    if not waits:
        return None
    waits.sort()
    return round(waits[min(len(waits) - 1,
                           int(0.99 * (len(waits) - 1)))], 3)


def service_lines(out_path: str = "BENCH_SERVICE.json") -> list:
    """The network-service acceptance measurement (ISSUE 11, ROADMAP
    item 1): (1) 1k tenants driven through REAL loopback sockets
    (submit + long-poll result, 24 client threads) vs the SAME jobs
    through the Scheduler in-process — wall-clock overhead gated <=10%
    and per-tenant results bit-identical (wire digests); (2) a bursty
    240-job load on an 8-lane service with the autoscaler OFF vs ON —
    the ON run's journal must contain lane-changing
    ``autoscale_decision`` events and its queue-wait p99 must improve.
    The bucket lattice (8..64 lanes) is prewarmed under the persistent
    compile cache first, so both timed runs measure control behaviour,
    not compiles."""
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from deap_tpu.serving import (AutoscaleConfig, AutoscalePolicy,
                                  EvolutionService, Scheduler,
                                  ServiceClient)
    from deap_tpu.serving.wire import result_digest
    from deap_tpu.support.compilecache import enable_compile_cache
    from deap_tpu.telemetry.journal import read_journal
    from deap_tpu.telemetry.metrics import MetricsRegistry

    envfp = _env_fingerprint("cpu")
    onemax = _service_problem()
    work = tempfile.mkdtemp(prefix="deap_svc_bench_")
    cache = os.path.join(work, "xla_cache")
    enable_compile_cache(cache)
    rows = []

    def specs(n):
        return [(f"t{i:04d}", {"seed": i}) for i in range(n)]

    # ---- lattice warmup: compile the (lanes, horizon) lattice points
    # both timed configs touch into the persistent cache, so neither
    # timed run pays a cold compile. Two warm schedulers because the
    # key horizon differs (ngen=10 → 16 vs ngen=40 → 64) and a
    # bucket's horizon only grows.
    warm = Scheduler(os.path.join(work, "warm"),
                     **_service_sched_kwargs(SERVICE_LANES_FIXED))
    warm.prewarm([onemax("warm0", {"seed": 0})],
                 lane_counts=(32, 64))
    warm.close()
    warmb = Scheduler(os.path.join(work, "warmb"),
                      **_service_sched_kwargs(SERVICE_LANES_FIXED))
    warmb.prewarm([onemax("warmb0", {"seed": 0,
                                     **SERVICE_BURST_JOB})],
                  lane_counts=(8, 16, 32, 64, 128, 256,
                               SERVICE_BURST_MAX_LANES))
    warmb.close()

    # ---- the overhead pair, INTERLEAVED min-of-reps: this box's
    # background load swings single-shot pairs by tens of percent in
    # either direction; alternating the two sides and taking each
    # side's min is the same one-sided-noise defence the probes/fusion
    # pairs use
    def inproc_run(rep):
        t0 = time.perf_counter()
        with Scheduler(os.path.join(work, f"inproc{rep}"),
                       **_service_sched_kwargs(SERVICE_LANES_FIXED)
                       ) as s:
            for tid, params in specs(SERVICE_N):
                s.submit(onemax(tid, params))
            results = s.run()
        dt = time.perf_counter() - t0
        digests = {tid: result_digest(r) for tid, r in results.items()}
        return dt, digests

    def socket_run(rep):
        reg = MetricsRegistry()
        svc = EvolutionService(
            os.path.join(work, f"svc{rep}"), {"onemax": onemax},
            metrics=reg, **_service_sched_kwargs(SERVICE_LANES_FIXED))

        def drive(chunk):
            # batch submit + batch long-poll: one round trip each —
            # the per-request handler cost matters when client and
            # server share cores (and in production, batch admission
            # is how a front end talks to a scheduler anyway)
            c = ServiceClient(svc.url)
            tids = c.submit_many([
                {"problem": "onemax", "params": p, "tenant_id": tid}
                for tid, p in chunk])
            got = c.results_many(tids, wait=True, timeout=600)
            c.close()
            out = {}
            for tid, entry in got.items():
                assert entry["status"] == "finished", (tid, entry)
                out[tid] = entry["result"]["digest"]
            return out

        all_specs = specs(SERVICE_N)
        # contiguous chunks: a client's tenants are admitted in
        # adjacent waves, so its batch long-poll resolves mid-run and
        # result encoding overlaps later waves' compute — strided
        # chunks made every client's batch complete at the very end,
        # serialising all 1k result encodes into a post-run tail
        per = (SERVICE_N + SERVICE_CLIENTS - 1) // SERVICE_CLIENTS
        chunks = [all_specs[i * per:(i + 1) * per]
                  for i in range(SERVICE_CLIENTS)]
        digests = {}
        t0 = time.perf_counter()
        with ThreadPoolExecutor(SERVICE_CLIENTS) as pool:
            for out in pool.map(drive, chunks):
                digests.update(out)
        dt = time.perf_counter() - t0
        p99 = _service_wait_p99(reg)
        svc.close()
        return dt, digests, p99

    inproc_times, socket_times = [], []
    inproc_digests = svc_digests = None
    wait_p99 = None
    for rep in range(SERVICE_REPS):
        dt, d = inproc_run(rep)
        inproc_times.append(dt)
        inproc_digests = d if inproc_digests is None else inproc_digests
        dt, d, p99 = socket_run(rep)
        socket_times.append(dt)
        if svc_digests is None:
            svc_digests, wait_p99 = d, p99
    inproc_s, svc_s = min(inproc_times), min(socket_times)

    bit_identical = svc_digests == inproc_digests
    overhead_pct = 100.0 * (svc_s - inproc_s) / inproc_s
    total_gens = SERVICE_N * SERVICE_JOB["ngen"]
    rows += [
        {"metric": "service_1k_inprocess_seconds",
         "value": round(inproc_s, 3), "unit": "seconds",
         "tenants": SERVICE_N, "lanes": SERVICE_LANES_FIXED,
         "gens_per_sec": round(total_gens / inproc_s, 1),
         "reps": [round(t, 3) for t in inproc_times],
         **SERVICE_JOB, "env": envfp},
        {"metric": "service_1k_socket_seconds",
         "value": round(svc_s, 3), "unit": "seconds",
         "tenants": SERVICE_N, "clients": SERVICE_CLIENTS,
         "lanes": SERVICE_LANES_FIXED,
         "gens_per_sec": round(total_gens / svc_s, 1),
         "reps": [round(t, 3) for t in socket_times],
         "queue_wait_p99_s": wait_p99, **SERVICE_JOB, "env": envfp},
        {"metric": "service_vs_inprocess_overhead_pct",
         "value": round(overhead_pct, 2), "unit": "%",
         "gate": "<= 10",
         "note": "interleaved min-of-reps pair, same session",
         "env": envfp},
        {"metric": "service_bit_identical",
         "value": bool(bit_identical), "unit": "bool",
         "tenants_compared": len(svc_digests), "env": envfp},
    ]

    # --------------------------------------- autoscale off/on pair ----
    def bursty_specs(n):
        return [(f"b{i:04d}", {"seed": i, **SERVICE_BURST_JOB})
                for i in range(n)]

    def bursty_run(label, autoscale):
        reg = MetricsRegistry()
        root = os.path.join(work, label)
        svc = EvolutionService(
            root, {"onemax": onemax}, metrics=reg,
            autoscale=autoscale, **_service_sched_kwargs(8))
        per = SERVICE_BURST_N // SERVICE_BURSTS

        def drive(chunk):
            c = ServiceClient(svc.url)
            tids = c.submit_many([
                {"problem": "onemax", "params": p, "tenant_id": tid}
                for tid, p in chunk])
            got = c.results_many(tids, wait=True, timeout=600)
            for tid, entry in got.items():
                assert entry["status"] == "finished", (tid, entry)
            c.close()

        t0 = time.perf_counter()
        sp = bursty_specs(SERVICE_BURST_N)
        # pool must hold EVERY burst's clients at once — a worker that
        # blocks long-polling burst 1 must not delay burst 2's
        # submissions, or the load silently stops being bursty
        with ThreadPoolExecutor(8 * SERVICE_BURSTS) as pool:
            futs = []
            for b in range(SERVICE_BURSTS):
                burst = sp[b * per:(b + 1) * per]
                futs += [pool.submit(drive, burst[i::8])
                         for i in range(8)]
                time.sleep(SERVICE_BURST_GAP_S)
            for f in futs:
                f.result()
        wall = time.perf_counter() - t0
        svc.close()
        journal = read_journal(os.path.join(root, "journal.jsonl"))
        p99 = _journal_wait_p99(journal)
        decisions = [r for r in journal
                     if r.get("kind") == "autoscale_decision"]
        return wall, p99, decisions

    off_wall, off_p99, _ = bursty_run("as_off", autoscale=None)
    # spill disabled: it targets long-IDLE tenants (ask-tell tenants
    # parked between client rounds); under this saturated burst every
    # resident is mid-job and spilling would thrash checkpoints —
    # measured: 100 spills and a WORSE p99. Lanes + prewarm are the
    # right actuators here.
    # down_after effectively off too: autoscale ticks are step-paced
    # and steps are milliseconds here — a 1 s gap between bursts reads
    # as hundreds of "idle" observations, and scaling down between
    # bursts just re-thrashes the lattice when the next burst lands
    on_wall, on_p99, decisions = bursty_run(
        "as_on", autoscale=AutoscalePolicy(AutoscaleConfig(
            max_lanes=SERVICE_BURST_MAX_LANES, up_after=1, cooldown=1,
            queue_high=1, spill_idle_segments=10 ** 9,
            down_after=10 ** 9)))
    lane_moves = [d for d in decisions if d.get("action") == "lanes"]
    prewarms = [d for d in decisions if d.get("action") == "prewarm"]
    improvement = (off_p99 / on_p99) if (off_p99 and on_p99) else None
    rows += [
        {"metric": "service_autoscale_off_queue_wait_p99_s",
         "value": off_p99, "unit": "seconds",
         "jobs": SERVICE_BURST_N, "bursts": SERVICE_BURSTS,
         **SERVICE_BURST_JOB,
         "lanes": 8, "wall_s": round(off_wall, 3), "env": envfp},
        {"metric": "service_autoscale_on_queue_wait_p99_s",
         "value": on_p99, "unit": "seconds",
         "jobs": SERVICE_BURST_N, "bursts": SERVICE_BURSTS,
         **SERVICE_BURST_JOB,
         "lanes_start": 8, "lanes_max": SERVICE_BURST_MAX_LANES,
         "wall_s": round(on_wall, 3),
         "lane_decisions": [
             {"from": d["lanes_from"], "to": d["lanes_to"]}
             for d in lane_moves],
         "prewarm_decisions": len(prewarms), "env": envfp},
        {"metric": "service_autoscale_queue_wait_p99_improvement_x",
         "value": (round(improvement, 2) if improvement else None),
         "unit": "x", "gate": ">= 1.0",
         "autoscale_decisions": len(decisions), "env": envfp},
    ]

    shutil.rmtree(work, ignore_errors=True)
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": envfp,
            "config": {"tenants": SERVICE_N,
                       "clients": SERVICE_CLIENTS, "job": SERVICE_JOB,
                       "segment_len": SERVICE_SEG,
                       "lanes": SERVICE_LANES_FIXED,
                       "burst": {"jobs": SERVICE_BURST_N,
                                 "bursts": SERVICE_BURSTS,
                                 **SERVICE_BURST_JOB}},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# ------------------------------ tracing overhead plane (ISSUE 15) ----

def tracing_lines(out_path: str = "BENCH_TRACING.json") -> list:
    """The tracing-overhead acceptance measurement (ISSUE 15): the 1k
    tenant socket config from :func:`service_lines` run three ways in
    one session — tracing fully off (``trace_sample=None``), sampled
    at 0.1, and always-on at 1.0 — interleaved min-of-reps so this
    box's background-load swings can't fake an overhead. Gates: the
    sampled arm costs <= 3% over off, and all three arms produce
    bit-identical per-tenant wire digests (spans observe, never
    steer)."""
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from deap_tpu.serving import (EvolutionService, Scheduler,
                                  ServiceClient)
    from deap_tpu.support.compilecache import enable_compile_cache
    from deap_tpu.telemetry.metrics import MetricsRegistry

    envfp = _env_fingerprint("cpu")
    onemax = _service_problem()
    work = tempfile.mkdtemp(prefix="deap_trace_bench_")
    cache = os.path.join(work, "xla_cache")
    enable_compile_cache(cache)

    def specs(n):
        return [(f"t{i:04d}", {"seed": i}) for i in range(n)]

    # lattice warmup, same as service_lines: both timed lane counts
    # into the persistent cache so no arm pays a cold compile
    warm = Scheduler(os.path.join(work, "warm"),
                     **_service_sched_kwargs(SERVICE_LANES_FIXED))
    warm.prewarm([onemax("warm0", {"seed": 0})],
                 lane_counts=(32, 64))
    warm.close()

    ARMS = (("off", None), ("sampled", 0.1), ("always", 1.0))

    def arm_run(label, sample, rep):
        reg = MetricsRegistry()
        svc = EvolutionService(
            os.path.join(work, f"{label}{rep}"), {"onemax": onemax},
            metrics=reg, trace_sample=sample,
            **_service_sched_kwargs(SERVICE_LANES_FIXED))

        def drive(chunk):
            c = ServiceClient(svc.url)
            tids = c.submit_many([
                {"problem": "onemax", "params": p, "tenant_id": tid}
                for tid, p in chunk])
            got = c.results_many(tids, wait=True, timeout=600)
            c.close()
            out = {}
            for tid, entry in got.items():
                assert entry["status"] == "finished", (tid, entry)
                out[tid] = entry["result"]["digest"]
            return out

        all_specs = specs(SERVICE_N)
        per = (SERVICE_N + SERVICE_CLIENTS - 1) // SERVICE_CLIENTS
        chunks = [all_specs[i * per:(i + 1) * per]
                  for i in range(SERVICE_CLIENTS)]
        digests = {}
        t0 = time.perf_counter()
        with ThreadPoolExecutor(SERVICE_CLIENTS) as pool:
            for out in pool.map(drive, chunks):
                digests.update(out)
        dt = time.perf_counter() - t0
        svc.close()
        return dt, digests

    # interleaved AND rotated: all three arms run within each rep (a
    # load spike hits every arm), and the order rotates per rep so no
    # arm always sits in the same slot — first-in-rep position alone
    # is worth a few percent on this box (page cache, GC debt from
    # the previous service), which min-of-reps can only cancel if
    # every arm samples every position
    times = {label: [] for label, _ in ARMS}
    digests = {label: None for label, _ in ARMS}
    for rep in range(SERVICE_REPS):
        order = ARMS[rep % len(ARMS):] + ARMS[:rep % len(ARMS)]
        for label, sample in order:
            dt, d = arm_run(label, sample, rep)
            times[label].append(dt)
            if digests[label] is None:
                digests[label] = d

    best = {label: min(ts) for label, ts in times.items()}
    bit_identical = (digests["off"] == digests["sampled"]
                     == digests["always"])
    sampled_pct = 100.0 * (best["sampled"] - best["off"]) / best["off"]
    always_pct = 100.0 * (best["always"] - best["off"]) / best["off"]
    total_gens = SERVICE_N * SERVICE_JOB["ngen"]
    rows = []
    for label, _ in ARMS:
        rows.append(
            {"metric": f"tracing_{label}_seconds",
             "value": round(best[label], 3), "unit": "seconds",
             "tenants": SERVICE_N, "clients": SERVICE_CLIENTS,
             "lanes": SERVICE_LANES_FIXED,
             "gens_per_sec": round(total_gens / best[label], 1),
             "reps": [round(t, 3) for t in times[label]],
             **SERVICE_JOB, "env": envfp})
    rows += [
        {"metric": "tracing_sampled_overhead_pct",
         "value": round(sampled_pct, 2), "unit": "%",
         "gate": "<= 3",
         "note": "interleaved min-of-reps triple, same session",
         "env": envfp},
        {"metric": "tracing_always_overhead_pct",
         "value": round(always_pct, 2), "unit": "%",
         "note": "informational — lifecycle+phase spans on every "
                 "request", "env": envfp},
        {"metric": "tracing_bit_identical",
         "value": bool(bit_identical), "unit": "bool",
         "tenants_compared": len(digests["off"]), "env": envfp},
    ]

    shutil.rmtree(work, ignore_errors=True)
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": envfp,
            "config": {"tenants": SERVICE_N,
                       "clients": SERVICE_CLIENTS, "job": SERVICE_JOB,
                       "segment_len": SERVICE_SEG,
                       "lanes": SERVICE_LANES_FIXED,
                       "samples": {label: s for label, s in ARMS}},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# ------------------------------- canary observability (ISSUE 19) ----

#: the canary job is deliberately tiny (ngen 8 vs the load's 30): it
#: rides the production scheduler, so its cost IS the overhead the
#: <= 3% gate bounds
CANARY_JOB = dict(seed=424242, pop=16, length=32, ngen=8)
CANARY_CADENCE = 20         # boundaries between canaries under load
CANARY_DETECT_SEG = 2       # segment_len of the detection mini-run


def canary_lines(out_path: str = "BENCH_CANARY.json") -> list:
    """The canary/alerting acceptance measurement (ISSUE 19), two
    halves in one session:

    1. **Clean-run cost + false positives** — the 1k-tenant socket
       config from :func:`service_lines` run canary-off vs canary-on
       (known-answer canaries every ``CANARY_CADENCE`` boundaries,
       burn-rate alert engine live), interleaved min-of-reps. Gates:
       overhead <= 3% and ZERO alert transitions / canary failures
       across every clean canary-on rep — a paging signal that cries
       wolf is worse than none.
    2. **Detection latency** — a dedicated run with
       ``CorruptResult`` armed for the second canary (the first
       learns the trust-on-first-use reference): the corrupted wire
       digest must produce the ``canary_failed`` row, the ``canary``
       alarm and the FIRING ``canary_failure`` alert within two
       segment boundaries of the canary completing.
    """
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from deap_tpu.resilience.faultinject import (CorruptResult,
                                                 FaultPlan)
    from deap_tpu.serving import (EvolutionService, Scheduler,
                                  ServiceClient)
    from deap_tpu.serving.canary import CanarySpec
    from deap_tpu.support.compilecache import enable_compile_cache
    from deap_tpu.telemetry.journal import read_journal
    from deap_tpu.telemetry.metrics import MetricsRegistry
    from deap_tpu.telemetry.probes import HealthMonitor

    envfp = _env_fingerprint("cpu")
    onemax = _service_problem()
    work = tempfile.mkdtemp(prefix="deap_canary_bench_")
    enable_compile_cache(os.path.join(work, "xla_cache"))

    canary_params = dict(CANARY_JOB)
    canary_seed = canary_params.pop("seed")

    def canary_spec(cadence=CANARY_CADENCE):
        return CanarySpec("onemax",
                          dict(canary_params, seed=canary_seed),
                          cadence_boundaries=cadence)

    # lattice warmup, same as the other service benches: the timed
    # lane count into the persistent cache so no arm pays a cold
    # compile (the canary job shape warms in rep 0's first arm)
    warm = Scheduler(os.path.join(work, "warm"),
                     **_service_sched_kwargs(SERVICE_LANES_FIXED))
    warm.prewarm([onemax("warm0", {"seed": 0})], lane_counts=(64,))
    warm.close()

    def arm_run(label, with_canary, rep):
        root = os.path.join(work, f"{label}{rep}")
        svc = EvolutionService(
            root, {"onemax": onemax}, metrics=MetricsRegistry(),
            canary=canary_spec() if with_canary else None,
            **_service_sched_kwargs(SERVICE_LANES_FIXED))

        def drive(chunk):
            c = ServiceClient(svc.url)
            tids = c.submit_many([
                {"problem": "onemax", "params": p, "tenant_id": tid}
                for tid, p in chunk])
            got = c.results_many(tids, wait=True, timeout=600)
            c.close()
            for tid, entry in got.items():
                assert entry["status"] == "finished", (tid, entry)

        all_specs = [(f"t{i:04d}", {"seed": i})
                     for i in range(SERVICE_N)]
        per = (SERVICE_N + SERVICE_CLIENTS - 1) // SERVICE_CLIENTS
        chunks = [all_specs[i * per:(i + 1) * per]
                  for i in range(SERVICE_CLIENTS)]
        t0 = time.perf_counter()
        with ThreadPoolExecutor(SERVICE_CLIENTS) as pool:
            list(pool.map(drive, chunks))
        dt = time.perf_counter() - t0
        svc.close()
        rows = read_journal(os.path.join(root, "journal.jsonl"))
        alerts = [r for r in rows if r.get("kind") == "alert"]
        failed = [r for r in rows
                  if r.get("kind") == "canary_failed"]
        oks = [r for r in rows if r.get("kind") == "canary_ok"]
        return dt, alerts, failed, oks

    ARMS = (("canary_off", False), ("canary_on", True))
    times = {label: [] for label, _ in ARMS}
    false_alerts = 0
    false_failures = 0
    clean_oks = 0
    for rep in range(SERVICE_REPS):
        order = ARMS[rep % len(ARMS):] + ARMS[:rep % len(ARMS)]
        for label, with_canary in order:
            dt, alerts, failed, oks = arm_run(
                label, with_canary, rep)
            times[label].append(dt)
            false_alerts += len(alerts)
            false_failures += len(failed)
            if with_canary:
                clean_oks += len(oks)

    best = {label: min(ts) for label, ts in times.items()}
    overhead_pct = (100.0 * (best["canary_on"] - best["canary_off"])
                    / best["canary_off"])

    # -- detection latency: corrupt the SECOND canary (the first
    # learns the clean reference), cadence 1 so boundaries tick fast
    det_root = os.path.join(work, "detect")
    health = HealthMonitor()
    svc = EvolutionService(
        det_root, {"onemax": onemax}, metrics=MetricsRegistry(),
        health=health,
        fault_plan=FaultPlan([CorruptResult(
            tenant_substr="canary-2")]),
        canary=canary_spec(cadence=1),
        max_lanes=8, segment_len=CANARY_DETECT_SEG,
        fair_quantum=None, checkpoint_every=0, telemetry=False)
    t0 = time.perf_counter()
    detect_wall = None
    deadline = time.time() + 300
    while time.time() < deadline:
        if svc.canary.failed >= 1 and svc.alerts.firing():
            detect_wall = time.perf_counter() - t0
            break
        time.sleep(0.05)
    alarm_fired = any(a.get("alarm") == "canary"
                      for a in health.alarms)
    firing = list(svc.alerts.firing())
    svc.close()
    rows = read_journal(os.path.join(det_root, "journal.jsonl"))
    idx_fail = next((i for i, r in enumerate(rows)
                     if r.get("kind") == "canary_failed"), None)
    idx_alert = next((i for i, r in enumerate(rows)
                      if r.get("kind") == "alert"
                      and r.get("state") == "firing"
                      and r.get("name") == "canary_failure"), None)
    if idx_fail is not None and idx_alert is not None:
        detect_boundaries = len(
            [r for r in rows[idx_fail:idx_alert]
             if r.get("kind") == "slo"])
    else:
        detect_boundaries = None
    detected = (idx_fail is not None and idx_alert is not None
                and alarm_fired and "canary_failure" in firing)

    total_gens = SERVICE_N * SERVICE_JOB["ngen"]
    rows_out = []
    for label, _ in ARMS:
        rows_out.append(
            {"metric": f"{label}_seconds",
             "value": round(best[label], 3), "unit": "seconds",
             "tenants": SERVICE_N, "clients": SERVICE_CLIENTS,
             "lanes": SERVICE_LANES_FIXED,
             "gens_per_sec": round(total_gens / best[label], 1),
             "reps": [round(t, 3) for t in times[label]],
             **SERVICE_JOB, "env": envfp})
    rows_out += [
        {"metric": "canary_overhead_pct",
         "value": round(overhead_pct, 2), "unit": "%", "gate": "<= 3",
         "cadence_boundaries": CANARY_CADENCE,
         "canary_job": CANARY_JOB,
         "note": "interleaved min-of-reps pair, same session",
         "env": envfp},
        {"metric": "canary_false_alarms",
         "value": int(false_alerts + false_failures), "unit": "count",
         "gate": "== 0", "alert_rows": int(false_alerts),
         "canary_failed_rows": int(false_failures),
         "clean_canary_ok_rows": int(clean_oks),
         "reps": SERVICE_REPS, "env": envfp},
        {"metric": "canary_detection_boundaries",
         "value": detect_boundaries, "unit": "segment boundaries",
         "gate": "<= 2",
         "detect_wall_s": (round(detect_wall, 3)
                           if detect_wall is not None else None),
         "segment_len": CANARY_DETECT_SEG, "env": envfp},
        {"metric": "canary_detected",
         "value": bool(detected), "unit": "bool",
         "alarm": bool(alarm_fired), "firing": firing,
         "env": envfp},
    ]

    shutil.rmtree(work, ignore_errors=True)
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": envfp,
            "config": {"tenants": SERVICE_N,
                       "clients": SERVICE_CLIENTS, "job": SERVICE_JOB,
                       "segment_len": SERVICE_SEG,
                       "lanes": SERVICE_LANES_FIXED,
                       "canary_job": CANARY_JOB,
                       "cadence_boundaries": CANARY_CADENCE},
            "tail": "\n".join(json.dumps(r) for r in rows_out),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows_out


# ------------------------------- service chaos plane (ISSUE 12) ----

CHAOS_N = 200               # live retrying tenants under the kill
CHAOS_NGEN = 24
CHAOS_SEG = 3
CHAOS_LANES = 64
CHAOS_KILL_STEP = 6         # driver step the child SIGKILLs itself at
CHAOS_CLIENTS = 8
#: recovery-wall budget for the chaos_tripwire gate (kill → last
#: tenant converged on the restarted service). Tightened from 120 s
#: (pre-ISSUE-18 measured 21.4 s: cold start dominated) to 30 s now
#: that the restarted child takes the startup fast path — executable
#: artifact store + warm-handoff prewarm + batched WAL replay +
#: pipelined checkpoint restore + fsync-free boundary checkpoints;
#: measured 8.5-12.5 s across trials on the 1-core bench host (the
#: spread is kill-position noise: how much of the run remained to
#: recompute when the SIGKILL landed) — see BENCH_CHAOS.json
CHAOS_RECOVERY_BUDGET_S = 30.0


def service_chaos_lines(out_path: str = "BENCH_CHAOS.json") -> list:
    """The fault-tolerance acceptance measurement (ISSUE 12): a child
    service process ``SIGKILL``s itself mid-run (deterministic
    ``KillServiceAt`` fault) under ``CHAOS_N`` live tenants driven by
    concurrent retrying clients (jittered backoff + idempotency keys);
    a supervisor restarts it over the same root (admission-WAL replay
    + checkpoint resume). Committed gates: **zero lost jobs**, **100%
    wire-digest identity** against an uninterrupted in-process run,
    and recovery wall time within ``CHAOS_RECOVERY_BUDGET_S``."""
    import shutil
    import tempfile

    from deap_tpu.serving import chaos

    envfp = _env_fingerprint("cpu")
    work = tempfile.mkdtemp(prefix="deap_chaos_bench_")
    specs = chaos.chaos_specs(CHAOS_N, ngen=CHAOS_NGEN)

    t0 = time.perf_counter()
    ref = chaos.reference_digests(os.path.join(work, "ref"), specs,
                                  segment_len=CHAOS_SEG,
                                  max_lanes=CHAOS_LANES)
    ref_s = time.perf_counter() - t0

    out = chaos.run_chaos(
        os.path.join(work, "svc"), n_tenants=CHAOS_N, ngen=CHAOS_NGEN,
        kill_at_step=CHAOS_KILL_STEP, segment_len=CHAOS_SEG,
        max_lanes=CHAOS_LANES, clients=CHAOS_CLIENTS,
        converge_timeout_s=900,
        # the ISSUE-18 startup fast path: both children share a
        # root-local persistent compile cache, which also enables the
        # executable artifact store + warm-handoff manifest — the
        # restarted child deserializes the pre-kill lattice
        compile_cache=os.path.join(work, "cache"))
    identical = sum(1 for tid, d in out["digests"].items()
                    if ref.get(tid) == d)
    shutil.rmtree(work, ignore_errors=True)

    cfg = {"tenants": CHAOS_N, "ngen": CHAOS_NGEN,
           "segment_len": CHAOS_SEG, "lanes": CHAOS_LANES,
           "clients": CHAOS_CLIENTS, "kill_at_step": CHAOS_KILL_STEP,
           "compile_cache": True}
    rows = [
        {"metric": "chaos_kill_delivered",
         "value": out["kill_rc"] == -9, "unit": "bool",
         "kill_rc": out["kill_rc"], **cfg, "env": envfp},
        {"metric": "chaos_lost_jobs",
         "value": len(out["lost"]), "unit": "jobs", "gate": "== 0",
         "lost": out["lost"][:20], **cfg, "env": envfp},
        {"metric": "chaos_digest_identity_frac",
         "value": round(identical / CHAOS_N, 6), "unit": "frac",
         "gate": "== 1.0", "identical": identical,
         "compared": len(out["digests"]), **cfg, "env": envfp},
        {"metric": "chaos_recovery_seconds",
         "value": out["recovery_s"], "unit": "seconds",
         "gate": f"<= {CHAOS_RECOVERY_BUDGET_S:.0f}",
         "note": "kill -> last tenant converged on the restarted "
                 "service (artifact-store cold start + warm-handoff "
                 "prewarm + batched WAL replay + pipelined restore "
                 "included)",
         **cfg, "env": envfp},
        {"metric": "chaos_wall_seconds",
         "value": out["wall_s"], "unit": "seconds",
         "client_errors": out["client_errors"],
         "reference_inprocess_s": round(ref_s, 3), **cfg,
         "env": envfp},
    ]
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": envfp,
            "config": cfg,
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# -------------------------------- load observatory plane (ISSUE 17) ----

LOADGEN_N = 40              # arrivals per gated traffic model
LOADGEN_RATE = 20.0         # Poisson arrivals/s (compressed open loop)
LOADGEN_JOB = dict(pop=16, length=32, ngen=12)
LOADGEN_SEG = 3
LOADGEN_LANES = 16
LOADGEN_SEED = 2026         # schedule seed — committed rows must be
#                             regenerable from (model, seed) alone
LOADGEN_REPLAY_SPEED = 2.0  # journal replay pace multiplier
LOADGEN_ATTR_N = 12         # arrivals per attribution arm
LOADGEN_ATTR_DELAY_S = 3.0  # injected segment stall (the regression)
#: open-loop pacing tolerance for the replay-fidelity gate — sleep
#: scheduling plus per-arrival thread spawn on a busy CPU box
LOADGEN_FIDELITY_BUDGET_S = 0.5


def _loadgen_slos(slo_mod):
    """The per-model gate set for the committed loadgen rows —
    DEFAULT_SLOS thresholds recalibrated to this bench's compressed
    open-loop burst (40 jobs in ~2 s against 16 CPU lanes queues
    much deeper than production pacing would)."""
    S = slo_mod.SloSpec
    return (
        S("admission_p99", "admission_p99", 60.0,
          "fresh submissions admitted within 60 s at p99"),
        S("queue_wait_p99", "queue_wait_p99", 120.0,
          "no tenant (incl. resumes) queued over 120 s at p99"),
        S("segment_p99", "segment_p99", 30.0,
          "scheduler segments under 30 s at p99"),
        S("shed_rate", "shed_rate", 0.05,
          "under 5% of offered load shed per window"),
        S("deadline_miss_rate", "deadline_miss_rate", 0.01,
          "under 1% of admitted arrivals miss their deadline"),
    )


def loadgen_lines(out_path: str = "BENCH_LOADGEN.json") -> list:
    """The load-observatory acceptance measurement (ISSUE 17): seeded
    open-loop traffic models driven through real loopback sockets with
    windowed SLO curves + gates per model, a record→replay round trip
    (journal-reconstructed arrival process re-run at
    ``LOADGEN_REPLAY_SPEED``× with a pacing-fidelity gate AND
    per-tenant digest identity against the recorded run), a
    regression-attribution demo (an injected ``segment``-seam stall
    must be attributed to the ``segment`` phase), and the transport
    gate: loadgen-path digests bit-identical to the same jobs through
    the Scheduler in-process."""
    import shutil
    import tempfile

    from deap_tpu.serving import (DiurnalTraffic, EvolutionService,
                                  PoissonTraffic, Scheduler,
                                  run_schedule, schedule_from_journal)
    from deap_tpu.serving.loadgen import replay_fidelity
    from deap_tpu.serving.wire import result_digest
    from deap_tpu.resilience.faultinject import DelaySegment, FaultPlan
    from deap_tpu.support.compilecache import enable_compile_cache
    from deap_tpu.telemetry import slo as slo_mod
    from deap_tpu.telemetry.journal import read_journal
    from deap_tpu.telemetry.metrics import MetricsRegistry

    envfp = _env_fingerprint("cpu")
    onemax = _service_problem()
    base_params = {k: v for k, v in LOADGEN_JOB.items()}

    def problem(tid, params):
        # loadgen arrivals share one params dict per model; the seed
        # comes from the tenant id's numeric suffix so every tenant is
        # a distinct, reproducible job — and a replayed tenant
        # (``rp-<original>``) derives the SAME seed, making replay
        # digests comparable to the recorded run's
        p = dict(params or {})
        p.setdefault("seed", int(tid.rsplit("-", 1)[-1]))
        return onemax(tid, p)

    work = tempfile.mkdtemp(prefix="deap_loadgen_bench_")
    enable_compile_cache(os.path.join(work, "xla_cache"))
    slos = _loadgen_slos(slo_mod)
    rows = []

    sched_kwargs = dict(max_lanes=LOADGEN_LANES,
                        segment_len=LOADGEN_SEG, fair_quantum=None,
                        checkpoint_every=0, telemetry=False)
    warm = Scheduler(os.path.join(work, "warm"), **sched_kwargs)
    warm.prewarm([problem("w-0-00000", base_params)],
                 lane_counts=(4, 8, 16))
    warm.close()

    def run_model(label, model, *, schedule=None, speed=1.0,
                  faults=None, trace=None):
        """One traffic run on a fresh service root: open-loop drive,
        windowed curve + journaled gates, journal rows back out."""
        root = os.path.join(work, label)
        svc = EvolutionService(root, {"onemax": problem},
                               metrics=MetricsRegistry(),
                               max_poll_s=10.0, fault_plan=faults,
                               trace_sample=trace, **sched_kwargs)
        sched = schedule if schedule is not None \
            else model.schedule(seed=LOADGEN_SEED)
        # one worker per arrival: the pacer must never block on a
        # full pool, or the "open-loop" schedule silently degrades to
        # closed-loop and the replay-fidelity gate measures the pool,
        # not the pacing
        rep = run_schedule(sched, svc.url, speed=speed,
                           max_workers=len(sched.arrivals),
                           poll_timeout_s=600.0, journal=svc.journal)
        jrows = list(read_journal(os.path.join(root, "journal.jsonl")))
        curve = slo_mod.windowed_curve(jrows, window_s=1.0)
        gates = slo_mod.evaluate_gates(curve, slos,
                                       journal=svc.journal,
                                       model=sched.model, bench=label)
        svc.close()
        return sched, rep, jrows, curve, gates

    # ---- gated traffic models: Poisson + diurnal sinusoid ----------
    models = [
        ("poisson", PoissonTraffic(
            rate_per_s=LOADGEN_RATE, problem="onemax",
            params=base_params, n=LOADGEN_N,
            abandon_frac=0.1, abandon_range=(0.2, 1.0))),
        ("diurnal", DiurnalTraffic(
            base_rate=LOADGEN_RATE / 4, peak_rate=LOADGEN_RATE,
            period_s=2.0, problem="onemax", params=base_params,
            n=LOADGEN_N)),
    ]
    recorded = {}
    for label, model in models:
        sched, rep, jrows, curve, gates = run_model(label, model)
        recorded[label] = (sched, rep, jrows)
        rows.append({
            "metric": f"loadgen_{label}_slo_green",
            "value": all(g["ok"] for g in gates), "unit": "bool",
            "gate": "== True", "seed": LOADGEN_SEED,
            "arrivals": len(sched.arrivals), "counts": rep.counts,
            "wall_s": rep.wall_s,
            "planned_s": round(sched.duration_s, 3),
            "gates": gates,
            "curve": curve, **LOADGEN_JOB, "env": envfp})

    # ---- transport gate: loadgen digests == in-process digests ----
    psched, prep, pjrows = recorded["poisson"]
    with Scheduler(os.path.join(work, "inproc"), **sched_kwargs) as s:
        for a in psched.arrivals:
            s.submit(problem(a.tenant_id, a.params))
        ref = {tid: result_digest(r)
               for tid, r in s.run().items()}
    got = prep.digests()   # the non-abandoned overlap set
    identical = sum(1 for tid, d in got.items() if ref.get(tid) == d)
    rows.append({
        "metric": "loadgen_bit_identical_frac",
        "value": (round(identical / len(got), 6) if got else None),
        "unit": "frac", "gate": "== 1.0", "compared": len(got),
        "abandoned": prep.counts.get("abandoned", 0),
        "note": "loadgen socket path vs Scheduler in-process, "
                "non-abandoned overlap set", "env": envfp})

    # ---- journal replay: reconstruct poisson's arrival process ----
    rsched = schedule_from_journal(pjrows, "onemax",
                                   params=base_params,
                                   speed=LOADGEN_REPLAY_SPEED)
    _, rrep, _, rcurve, rgates = run_model(
        "replay", None, schedule=rsched)
    fid = replay_fidelity(rsched, rrep.results)
    rdig = rrep.digests()
    rmatch = sum(1 for tid, d in rdig.items()
                 if ref.get(tid[len("rp-"):]) == d)
    rows.append({
        "metric": "loadgen_replay_fidelity_s",
        "value": fid["max_abs_err_s"], "unit": "seconds",
        "gate": f"<= {LOADGEN_FIDELITY_BUDGET_S}",
        "speed": LOADGEN_REPLAY_SPEED, "fidelity": fid,
        "reconstructed": len(rsched.arrivals),
        "recorded": len(psched.arrivals),
        "replay_digest_identical": rmatch,
        "replay_digests_compared": len(rdig),
        "slo_green": all(g["ok"] for g in rgates),
        "counts": rrep.counts, "wall_s": rrep.wall_s,
        "note": "arrival process reconstructed from job_submitted "
                "journal rows, re-run at 2x; digests vs the in-process "
                "reference", "env": envfp})

    # ---- attribution demo: injected segment stall names itself ----
    attr_model = PoissonTraffic(rate_per_s=LOADGEN_RATE / 2,
                                problem="onemax", params=base_params,
                                n=LOADGEN_ATTR_N)
    # discarded warm-up arm: cold compiles land INSIDE segment spans,
    # so a cache-asymmetric base/probe pair would attribute compile
    # warmth, not the injected stall — warm every lane count this
    # arrival pattern packs first, then measure both arms warm
    run_model("attr_warm", attr_model, trace=1.0)
    _, _, base_rows, _, _ = run_model("attr_base", attr_model,
                                      trace=1.0)
    _, _, probe_rows, _, _ = run_model(
        "attr_probe", attr_model, trace=1.0,
        faults=FaultPlan([DelaySegment(2, LOADGEN_ATTR_DELAY_S,
                                       event="segment")]))
    att = slo_mod.attribute_regression(base_rows, probe_rows)
    rows.append({
        "metric": "loadgen_attribution_top_phase",
        "value": att["top_phase"], "unit": "phase",
        "gate": "== segment",
        "injected_delay_s": LOADGEN_ATTR_DELAY_S,
        "top_delta_s": att["top_delta_s"],
        "end_to_end_delta_s": att["end_to_end_delta"],
        "phases": att["phases"],
        "note": "DelaySegment fired on the scheduler's in-segment "
                "seam; the per-phase p99 diff must name the segment "
                "phase, not just 'it got slower'", "env": envfp})

    shutil.rmtree(work, ignore_errors=True)
    cfg = {"arrivals": LOADGEN_N, "rate_per_s": LOADGEN_RATE,
           "job": LOADGEN_JOB, "segment_len": LOADGEN_SEG,
           "lanes": LOADGEN_LANES, "seed": LOADGEN_SEED,
           "replay_speed": LOADGEN_REPLAY_SPEED,
           "attr_delay_s": LOADGEN_ATTR_DELAY_S}
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": envfp,
            "config": cfg,
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# ------------------------------ zero-downtime operations (ISSUE 20) ----

MIG_N = 6            # tenants in the rolling-upgrade drill
MIG_NGEN = 30        # long enough that the rollout catches residents
#                      mid-run (at ngen=12 they finish before the drain)
#: per-tenant migration pause budget: checkpoint-at-boundary → resumed
#: on the adopting side. The point of live migration is to be far
#: cheaper than a kill/restart cycle — bench_report cross-checks this
#: p99 against BENCH_CHAOS's whole-service recovery wall.
MIG_PAUSE_BUDGET_S = 30.0
MIG_LG_N = 10        # arrivals per upgrade-under-load loadgen arm
MIG_LG_RATE = 6.0    # Poisson arrivals/s
MIG_LG_NGEN = 24     # arm job length — residents must straddle the roll
MIG_LG_AT_S = 1.5    # schedule offset at which the rollout fires


def migration_lines(out_path: str = "BENCH_MIGRATION.json") -> list:
    """The zero-downtime acceptance measurement (ISSUE 20), two arms:

    1. **Rolling-upgrade drill** (subprocess pair): an old-version
       child (known-answer canary on) serves ``MIG_N`` live tenants;
       a new-version child starts with the checkpoint compat gate
       open; ``POST /v1/drain?handoff=<new>`` migrates every resident
       mid-run through fsync'd WAL ownership-transfer records. Gates:
       zero lost jobs, 100% wire-digest identity vs the uninterrupted
       reference, canaries green on BOTH sides, at least one journaled
       ``compat_restore`` (the version skew was real), and migration
       pause p99 within ``MIG_PAUSE_BUDGET_S``.
    2. **Upgrade-under-load delta**: the same seeded Poisson schedule
       driven twice — once against a single service (baseline), once
       with an :class:`~deap_tpu.serving.UpgradePlan` rolling the
       fleet mid-schedule. Gates: the upgrade arm completes every
       arrival, bit-identical to the baseline arm, and at least one
       arrival observed ``migrated`` and re-offered (the rollout
       really crossed live traffic); the completion-latency p99 delta
       is committed ungated as the cost-of-rollout signal."""
    import dataclasses
    import shutil
    import tempfile

    from deap_tpu.serving import (PoissonTraffic, UpgradePlan,
                                  run_schedule)
    from deap_tpu.serving import chaos as chaos_mod

    envfp = _env_fingerprint("cpu")
    work = tempfile.mkdtemp(prefix="deap_migration_bench_")
    rows = []

    def p99(vals):
        if not vals:
            return None
        s = sorted(vals)
        return round(s[min(len(s) - 1, int(0.99 * len(s)))], 4)

    # ---- arm 1: rolling-upgrade drill ------------------------------
    specs = chaos_mod.chaos_specs(MIG_N, ngen=MIG_NGEN)
    ref = chaos_mod.reference_digests(os.path.join(work, "ref"),
                                      specs)
    drill = chaos_mod.run_upgrade_drill(os.path.join(work, "drill"),
                                        n_tenants=MIG_N,
                                        ngen=MIG_NGEN)
    identical = sum(1 for tid, d in drill["digests"].items()
                    if ref.get(tid) == d)
    canary_failed = (drill["old_kinds"].get("canary_failed", 0)
                     + drill["new_kinds"].get("canary_failed", 0))
    cfg = {"tenants": MIG_N, "ngen": MIG_NGEN}
    rows += [
        {"metric": "upgrade_lost_jobs",
         "value": len(drill["lost"]), "unit": "jobs", "gate": "== 0",
         "lost": drill["lost"][:20], "old_rc": drill["old_rc"],
         **cfg, "env": envfp},
        {"metric": "upgrade_digest_identity_frac",
         "value": round(identical / MIG_N, 6), "unit": "frac",
         "gate": "== 1.0", "identical": identical,
         "compared": len(drill["digests"]), **cfg, "env": envfp},
        {"metric": "upgrade_canary_failed",
         "value": canary_failed, "unit": "rows", "gate": "== 0",
         "canary_ok": (drill["old_kinds"].get("canary_ok", 0)
                       + drill["new_kinds"].get("canary_ok", 0)),
         **cfg, "env": envfp},
        {"metric": "upgrade_compat_restores",
         "value": drill["new_kinds"].get("compat_restore", 0),
         "unit": "rows", "gate": ">= 1",
         "note": "new-version child restoring old-version checkpoint "
                 "stamps under the explicit compat gate", **cfg,
         "env": envfp},
        {"metric": "migration_pause_p99_s",
         "value": p99(drill["migration_pauses_s"]),
         "unit": "seconds", "gate": f"<= {MIG_PAUSE_BUDGET_S:.0f}",
         "pauses_s": drill["migration_pauses_s"][:20],
         "migrations": len(drill["migration_pauses_s"]),
         "drain_s": drill["drain_s"],
         "note": "per-tenant ownership-transfer pause: checkpoint at "
                 "segment boundary -> transferred on the source "
                 "(adoption ACKed)", **cfg, "env": envfp},
    ]

    # ---- arm 2: upgrade-under-load delta ---------------------------
    base = PoissonTraffic(rate_per_s=MIG_LG_RATE, problem="onemax",
                          params=dict(pop=16, length=32,
                                      ngen=MIG_LG_NGEN),
                          n=MIG_LG_N).schedule(seed=LOADGEN_SEED)
    # per-arrival seeds: the chaos problem registry requires one, and
    # distinct jobs make the arm-to-arm digest identity meaningful
    sched = dataclasses.replace(base, arrivals=tuple(
        dataclasses.replace(a, params={**a.params, "seed": i})
        for i, a in enumerate(base.arrivals)))

    def lg_arm(label, *, rolling: bool):
        """One loadgen pass on a fresh child; with ``rolling`` the
        UpgradePlan spawns a new-version compat-gated child and drains
        the old one into it mid-schedule."""
        root = os.path.join(work, label)
        os.makedirs(root, exist_ok=True)
        ready = os.path.join(root, "ready.url")
        proc = chaos_mod._spawn_child(
            os.path.join(root, "svc"), chaos_mod._free_port(), ready,
            telemetry=True,
            version=("0.1.0+bench-old" if rolling else None))
        procs = [proc]
        url = chaos_mod._wait_ready(proc, ready)

        def handoff():
            ready2 = os.path.join(root, "ready2.url")
            p2 = chaos_mod._spawn_child(
                os.path.join(root, "svc2"), chaos_mod._free_port(),
                ready2, telemetry=True, compat_restore=True,
                version="0.1.1+bench-new")
            procs.append(p2)
            new_url = chaos_mod._wait_ready(p2, ready2)
            chaos_mod._post_drain(url, handoff=new_url)
            proc.wait(timeout=300)   # old child exits once drained
            return new_url

        plan = (UpgradePlan(at_s=MIG_LG_AT_S, handoff=handoff)
                if rolling else None)
        try:
            return run_schedule(sched, url,
                                max_workers=len(sched.arrivals),
                                poll_timeout_s=600.0, upgrade=plan)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
                    try:
                        p.wait(timeout=60)
                    except Exception:
                        p.kill()

    t0 = time.perf_counter()
    base_rep = lg_arm("lg_base", rolling=False)
    up_rep = lg_arm("lg_up", rolling=True)
    lg_wall_s = time.perf_counter() - t0

    def latencies(rep):
        return [r.done_t - r.submit_t for r in rep.results
                if r.done_t is not None and r.submit_t is not None]

    base_dig, up_dig = base_rep.digests(), up_rep.digests()
    lg_identical = sum(1 for tid, d in up_dig.items()
                      if base_dig.get(tid) == d)
    lg_lost = [a.tenant_id for a in sched.arrivals
               if a.tenant_id not in up_dig]
    base_p99, up_p99 = p99(latencies(base_rep)), p99(latencies(up_rep))
    lcfg = {"arrivals": MIG_LG_N, "rate_per_s": MIG_LG_RATE,
            "ngen": MIG_LG_NGEN, "upgrade_at_s": MIG_LG_AT_S,
            "seed": LOADGEN_SEED}
    rows += [
        {"metric": "upgrade_loadgen_lost_jobs",
         "value": len(lg_lost), "unit": "jobs", "gate": "== 0",
         "lost": lg_lost[:20], "counts": up_rep.counts,
         **lcfg, "env": envfp},
        {"metric": "upgrade_loadgen_digest_identity_frac",
         "value": (round(lg_identical / len(up_dig), 6)
                   if up_dig else None),
         "unit": "frac", "gate": "== 1.0",
         "identical": lg_identical, "compared": len(up_dig),
         **lcfg, "env": envfp},
        {"metric": "upgrade_loadgen_migrated_reoffers",
         "value": up_rep.migrated_reoffers or 0, "unit": "arrivals",
         "gate": ">= 1",
         "upgrade_t": up_rep.upgrade_t,
         "upgrade_ready_t": up_rep.upgrade_ready_t,
         "note": "arrivals that observed the terminal `migrated` "
                 "status and re-offered to the new side — proof the "
                 "rollout crossed live traffic", **lcfg,
         "env": envfp},
        {"metric": "upgrade_loadgen_p99_delta_s",
         "value": (round(up_p99 - base_p99, 4)
                   if None not in (up_p99, base_p99) else None),
         "unit": "seconds", "baseline_p99_s": base_p99,
         "upgrade_p99_s": up_p99, "wall_s": round(lg_wall_s, 3),
         "note": "completion-latency p99, rolling-upgrade arm minus "
                 "baseline arm on the identical schedule (ungated: "
                 "the cost-of-rollout signal)", **lcfg,
         "env": envfp},
    ]

    shutil.rmtree(work, ignore_errors=True)
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": envfp,
            "config": {**cfg, "pause_budget_s": MIG_PAUSE_BUDGET_S,
                       "loadgen": lcfg},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# ---------------------------------- resilience overhead (pop=100k) ----

#: headline config length for the paired segmented-vs-monolithic rows
#: (matches PROBE_NGEN so the per-run constants sit in real proportion)
RES_NGEN = 100
#: generations per segment — at pop=100k/CPU a checkpoint lands every
#: ~8 s of compute, the right granularity/overhead trade for this scale
RES_SEGMENT = 50
RES_REPS = 3


def resilience_overhead_lines(out_path: str = "BENCH_RESILIENCE.json",
                              ) -> list:
    """The resilience acceptance measurement: the headline OneMax
    config (pop=100k) run as ONE monolithic scan vs the SAME scan step
    driven in ``RES_SEGMENT``-generation segments by ``ResilientRun``
    with a crash-consistent checkpoint (fsync + CRC) at every segment
    boundary — back-to-back interleaved in one session, min-of-reps
    (the probe-bench protocol: contention noise is one-sided). Both
    sides reuse one prebuilt step closure so the paired rows compare
    steady-state cost, not per-call retrace constants.
    ``bench_report.py --tripwire`` gates the committed overhead ≤3%."""
    import shutil
    import tempfile

    from jax import lax as _lax

    from deap_tpu.algorithms import _pop_loop_init, make_ea_simple_step
    from deap_tpu.resilience import ResilientRun
    from deap_tpu.resilience.engine import _ScanLoopSpec

    jax.config.update("jax_platforms", "cpu")
    tb, pop0 = _setup()
    key = jax.random.key(90)
    step = make_ea_simple_step(tb, 0.5, 0.2)
    pop, hof, record0 = _pop_loop_init(pop0, tb, 0, None)
    carry0 = (pop, hof)

    def run_off():
        carry, _ = _lax.scan(step, carry0,
                             jax.random.split(key, RES_NGEN))
        sync(carry[0].fitness)

    ckdir = tempfile.mkdtemp(prefix="bench_resilience_")
    # ONE spec across reps: its cached jitted segment scan compiles
    # once (a real run compiles once too — a fresh spec per rep would
    # time 25-gen-scan recompiles, not the segmentation)
    spec = _ScanLoopSpec(
        "ea_simple", step, key, carry0, RES_NGEN, None, None,
        record0=record0,
        build_result=lambda st, recs: st["carry"][0])

    def run_on():
        # double_buffer defaults on: the boundary checkpoint's
        # serialize+fsync overlaps the next segment's compute — the
        # change that moves this pair under the tightened 1.5% gate
        res = ResilientRun(os.path.join(ckdir, "ck"),
                           segment_len=RES_SEGMENT, keep=2)
        res.ckpt.clear()  # each rep is a fresh run, not a resume
        out = res._drive(spec, RES_NGEN)
        sync(out.fitness)

    try:
        run_off()  # compile + warm (one executable serves both sides)
        run_on()
        t_off, t_on = [], []
        for _ in range(RES_REPS):
            t0 = time.perf_counter()
            run_off()
            t_off.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            run_on()
            t_on.append(time.perf_counter() - t0)
        t_off, t_on = sorted(t_off), sorted(t_on)
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)
    env = _env_fingerprint("cpu")
    n_ckpts = (RES_NGEN + RES_SEGMENT - 1) // RES_SEGMENT
    rows = []
    for name, times in (("monolithic", t_off), ("segmented", t_on)):
        med = times[len(times) // 2]
        row = {
            "metric": f"onemax_pop100k_resilience_{name}"
                      "_generations_per_sec",
            "value": round(RES_NGEN / med, 3), "unit": "gens/sec",
            "backend": "cpu", "pop": POP, "ngen": RES_NGEN,
            "n_samples": len(times),
            "best": round(RES_NGEN / times[0], 3),
            "spread_pct": round(100 * (times[-1] - times[0]) / med, 1),
            "env": env,
        }
        if name == "segmented":
            row["segment_len"] = RES_SEGMENT
            row["n_checkpoints"] = n_ckpts
            row["double_buffer"] = True
        rows.append(row)
    rows.append({
        "metric": "onemax_pop100k_resilience_overhead_pct",
        "value": round(100 * (t_on[0] - t_off[0]) / t_off[0], 2),
        "unit": "pct", "threshold_pct": 1.5, "double_buffer": True,
        "estimator": "min_of_reps", "segment_len": RES_SEGMENT,
        "n_checkpoints": n_ckpts, "env": env,
    })
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": env,
            "config": {"pop": POP, "length": LENGTH, "ngen": RES_NGEN,
                       "segment_len": RES_SEGMENT, "reps": RES_REPS},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# -------------------------------------------------------- costs bench ----
#
# The observability-layer acceptance measurement (ISSUE 9): the
# headline OneMax config (pop=100k) driven by ResilientRun over a
# donating ShardingPlan, (a) with NO observability vs (b) with the
# FULL third layer enabled — ProgramObservatory (per-program
# cost/memory analysis at the AOT seam), the serving metrics registry,
# and the flight recorder (one traced segment per trace_every +
# device-memory snapshots at every boundary). Bit-identity against the
# untouched monolithic scan is asserted BEFORE timing; the paired rows
# are min-of-reps interleaved same-session (the probe-bench protocol)
# and bench_report.py --tripwire gates the overhead <= 3% and requires
# nonzero aliased (donated) bytes on every donating generation-step
# program profile.
#
# Cadence note: the profiler costs ~10% of wall time WHILE tracing, so
# the flight-recorder tax is trace duty-cycle times that. The measured
# config traces 1 segment in 8 (25 of 200 gens, 12.5% duty; production
# cadences are sparser still) — that is what "flight recorder at
# trace_every cadence" costs, as opposed to running the whole run
# under the profiler (trace_every=1, ~10%, never the shipped default).

COSTS_NGEN = 200
COSTS_SEGMENT = 25
COSTS_TRACE_EVERY = 8


def _costs_program_rows(profiles, env) -> list:
    """One committed row per distinct program label: flops / bytes
    accessed / compile seconds / donated alias bytes — the per-program
    attribution the tripwire audits. Of a label's profiles (one per
    input signature) the COLD one is committed — later signatures of
    the same program dedup inside XLA's compile cache and report
    millisecond compiles that say nothing about the program."""
    by_label = {}
    for p in profiles:
        prev = by_label.get(p["label"])
        if prev is None or p.get("compile_s", 0) > prev.get("compile_s", 0):
            by_label[p["label"]] = p
    rows = []
    for label in sorted(by_label):
        p = by_label[label]
        safe = label.replace("/", "_").replace(":", "_")
        rows.append({
            "metric": f"program_cost_{safe}",
            "value": p.get("flops"), "unit": "flops",
            "bytes_accessed": p.get("bytes_accessed"),
            "compile_s": p.get("compile_s"),
            "argument_bytes": p.get("argument_bytes"),
            "output_bytes": p.get("output_bytes"),
            "temp_bytes": p.get("temp_bytes"),
            "aliased_bytes": p.get("aliased_bytes"),
            "donating": bool(p.get("donating")),
            "hlo_hash": p.get("hlo_hash"),
            "env": env,
        })
    return rows


def costs_lines(out_path: str = "BENCH_COSTS.json") -> list:
    import shutil
    import tempfile

    import numpy as np
    from jax import lax as _lax

    from deap_tpu.algorithms import _pop_loop_init, make_ea_simple_step
    from deap_tpu.parallel import ShardingPlan
    from deap_tpu.resilience import ResilientRun
    from deap_tpu.resilience.engine import _ScanLoopSpec
    from deap_tpu.strategies import cma
    from deap_tpu.telemetry import ProgramObservatory
    from deap_tpu.telemetry.metrics import MetricsRegistry

    jax.config.update("jax_platforms", "cpu")
    tb, pop0 = _setup()
    key = jax.random.key(90)
    plan = ShardingPlan.for_population()
    step = make_ea_simple_step(tb, 0.5, 0.2, plan=plan)
    pop_placed = plan.place(pop0)
    pop, hof, record0 = _pop_loop_init(pop_placed, tb, 0, None)
    # the donated carry is consumed per drive: rebuild it fresh per run
    make_carry = lambda: (plan.place(pop), hof)

    # the untouched-loop oracle: one monolithic scan, no plan, no
    # segmenting, no observability — the bit-identity reference
    plain_step = make_ea_simple_step(tb, 0.5, 0.2)
    oracle_carry, _ = _lax.scan(plain_step, (pop, hof),
                                jax.random.split(key, COSTS_NGEN))
    oracle = np.asarray(oracle_carry[0].genomes)

    ckdir = tempfile.mkdtemp(prefix="bench_costs_")
    # ONE spec across reps and both sides: its jitted/AOT segment
    # executables compile once (see resilience_overhead_lines)
    spec = _ScanLoopSpec(
        "ea_simple", step, key, make_carry(), COSTS_NGEN, None, None,
        record0=record0, build_result=lambda st, recs: st["carry"][0],
        plan=plan)

    registry = MetricsRegistry()
    observatory = ProgramObservatory()

    def run_off():
        res = ResilientRun(os.path.join(ckdir, "off"),
                           segment_len=COSTS_SEGMENT, keep=2, plan=plan)
        res.ckpt.clear()
        spec.carry0 = make_carry()
        out = res._drive(spec, COSTS_NGEN)
        sync(out.fitness)
        return out

    def run_on():
        # the FULL third layer: program observatory + metrics +
        # flight recorder (trace every other segment, device-memory
        # snapshot at every boundary)
        with observatory:
            res = ResilientRun(os.path.join(ckdir, "on"),
                               segment_len=COSTS_SEGMENT, keep=2,
                               plan=plan, metrics=registry,
                               trace_every=COSTS_TRACE_EVERY,
                               trace_dir=os.path.join(ckdir, "flight"))
            res.ckpt.clear()
            spec.carry0 = make_carry()
            out = res._drive(spec, COSTS_NGEN)
            sync(out.fitness)
            return out

    try:
        off_pop = run_off()  # compile + warm
        on_pop = run_on()
        # acceptance: full observability is bit-identical to the
        # untouched monolithic loop
        for name, got in (("observability_off", off_pop),
                          ("observability_on", on_pop)):
            assert np.array_equal(np.asarray(got.genomes), oracle), \
                f"{name} diverged from the untouched monolithic scan"
        t_off, t_on = [], []
        for _ in range(RES_REPS):
            t0 = time.perf_counter()
            run_off()
            t_off.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            run_on()
            t_on.append(time.perf_counter() - t0)
        t_off, t_on = sorted(t_off), sorted(t_on)

        # a second donating generation-step program for the
        # per-program table: the CMA ask-tell loop (context rows)
        strat = cma.Strategy(centroid=[0.0] * 16, sigma=0.5,
                             lambda_=64)
        ctb = Toolbox()
        ctb.register("evaluate",
                     lambda g: -jnp.sum(g ** 2, -1).astype(jnp.float32))
        ctb.register("generate", strat.generate)
        ctb.register("update", strat.update)
        with observatory:
            res = ResilientRun(os.path.join(ckdir, "cma"),
                               segment_len=COSTS_SEGMENT, plan=plan)
            res.ea_generate_update(jax.random.key(7),
                                   strat.initial_state(), ctb,
                                   COSTS_NGEN, spec=strat.spec)
    finally:
        shutil.rmtree(ckdir, ignore_errors=True)

    env = _env_fingerprint("cpu")
    rows = []
    for name, times in (("observability_off", t_off),
                        ("observability_on", t_on)):
        med = times[len(times) // 2]
        row = {
            "metric": f"onemax_pop100k_{name}_generations_per_sec",
            "value": round(COSTS_NGEN / med, 3), "unit": "gens/sec",
            "backend": "cpu", "pop": POP, "ngen": COSTS_NGEN,
            "n_samples": len(times),
            "best": round(COSTS_NGEN / times[0], 3),
            "spread_pct": round(100 * (times[-1] - times[0]) / med, 1),
            "env": env,
        }
        if name == "observability_on":
            row.update(segment_len=COSTS_SEGMENT,
                       trace_every=COSTS_TRACE_EVERY,
                       n_programs=len(observatory.profiles),
                       metrics="registry+flight_recorder+observatory")
        rows.append(row)
    rows.append({
        "metric": "onemax_pop100k_observability_overhead_pct",
        "value": round(100 * (t_on[0] - t_off[0]) / t_off[0], 2),
        "unit": "pct", "threshold_pct": 3.0,
        "estimator": "min_of_reps", "segment_len": COSTS_SEGMENT,
        "trace_every": COSTS_TRACE_EVERY, "env": env,
    })
    rows.extend(_costs_program_rows(observatory.profiles, env))
    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": env,
            "config": {"pop": POP, "length": LENGTH, "ngen": COSTS_NGEN,
                       "segment_len": COSTS_SEGMENT, "reps": RES_REPS,
                       "trace_every": COSTS_TRACE_EVERY},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


# ------------------------------------------------------- tuning bench ----
#
# The dispatch-tuner acceptance measurement (ISSUE 16): every
# probe-able knob's cold probe against a fresh tuning cache (the
# winner must be within 5% of the fastest static candidate and report
# a passing identity check), a segment_len sweep persisted out of band
# for the segment_len='auto' call sites (final populations asserted
# bit-identical across segment lengths first), and the amortisation
# half — a fresh tuner session re-resolving every probed key from the
# warm cache, its total wall gated <= 1% of one headline GP run.

TUNE_ND_N = 4000
TUNE_POP = 1024
TUNE_GP_ML = 64
TUNE_GP_POINTS = 64
TUNE_SEG_CANDIDATES = (5, 10, 20, 40)
TUNE_SEG_POP = 512
TUNE_SEG_NGEN = 40
TUNE_WARM_THRESHOLD_PCT = 1.0
TUNE_WINNER_THRESHOLD_X = 0.95


def tuning_lines(out_path: str = "BENCH_TUNING.json") -> list:
    import shutil
    import tempfile

    import numpy as np

    from deap_tpu import tuning
    from deap_tpu.gp.loop import make_symbreg_loop, resolve_compaction
    from deap_tpu.gp.pset import math_set
    from deap_tpu.gp.tree import make_generator
    from deap_tpu.mo.emo import nd_rank
    from deap_tpu.resilience import ResilientRun
    from deap_tpu.serving import GpJobSpec, Job, Scheduler
    from deap_tpu.strategies.cma import Strategy
    from deap_tpu.telemetry.journal import RunJournal, read_journal

    jax.config.update("jax_platforms", "cpu")
    env = _env_fingerprint("cpu")
    work = tempfile.mkdtemp(prefix="bench_tuning_")
    cache_dir = os.path.join(work, "cache")
    jpath = os.path.join(work, "journal.jsonl")

    # shared inputs — one concrete workload per decision point
    w = jax.random.normal(jax.random.key(7), (TUNE_ND_N, 3),
                          jnp.float32)
    pset = math_set(n_args=1)
    Xp = np.linspace(-1, 1, TUNE_GP_POINTS) \
        .reshape(TUNE_GP_POINTS, 1).astype(np.float32)
    yp = (Xp[:, 0] ** 3 + Xp[:, 0]).astype(np.float32)
    tb = _toolbox()
    pop = evaluate_invalid(
        init_population(jax.random.key(3), TUNE_POP,
                        ops.bernoulli_genome(LENGTH),
                        FitnessSpec((1.0,))), tb.evaluate)
    gen = make_generator(pset, TUNE_GP_ML, 1, 3, "full")
    founders = jax.vmap(gen)(jax.random.split(jax.random.key(5), 32))

    tuning.tuner._reset_for_tests()
    tuning.enable(cache_dir, reps=3)
    rows = []
    try:
        # ---- cold probes: walk every inline decision point once ----
        t_cold = time.perf_counter()
        with RunJournal(jpath):
            nd_rank(w)                                      # nd_impl
            resolve_compaction("auto", TUNE_POP)            # compaction
            Strategy(np.zeros(16, np.float32), 0.5,
                     eigh_impl="auto")                      # eigh_impl
            var_and(jax.random.key(11), pop, tb, 0.5, 0.2)  # fused
            loop = make_symbreg_loop(pset, TUNE_GP_ML, Xp, yp,
                                     mode="auto")           # gp_mode
            sched = Scheduler(os.path.join(work, "srv"), max_lanes=4,
                              segment_len=4, telemetry=False,
                              metrics=False)
            sched.submit(Job(                               # gp_batch
                tenant_id="bench", family="gp", toolbox=None,
                key=jax.random.key(5), init=founders, ngen=8,
                hyper={"cxpb": 0.5, "mutpb": 0.2},
                spec=GpJobSpec(pset=pset, max_len=TUNE_GP_ML, X=Xp,
                               y=yp)))
        cold_wall = time.perf_counter() - t_cold

        # ---- segment_len: the out-of-band sweep (cache/env knob) ----
        seg_times, seg_pops = {}, {}
        t_seg = time.perf_counter()
        for s in TUNE_SEG_CANDIDATES:
            best = float("inf")
            for rep in range(2):
                res = ResilientRun(
                    os.path.join(work, f"seg{s}_{rep}"), segment_len=s)
                seg_pop = init_population(
                    jax.random.key(21), TUNE_SEG_POP,
                    ops.bernoulli_genome(LENGTH), FitnessSpec((1.0,)))
                t0 = time.perf_counter()
                out, _, _ = res.ea_simple(jax.random.key(22), seg_pop,
                                          tb, 0.5, 0.2, TUNE_SEG_NGEN)
                sync(out.fitness)
                dt = time.perf_counter() - t0
                best = min(best, dt)  # rep 0 pays the compiles
            seg_times[str(s)] = best
            seg_pops[s] = np.asarray(out.genomes)
        seg_ref = seg_pops[TUNE_SEG_CANDIDATES[0]]
        seg_identical = all(np.array_equal(seg_ref, p)
                            for p in seg_pops.values())
        assert seg_identical, \
            "segment_len changed the trajectory — resilience parity broke"
        seg_winner = min(seg_times, key=seg_times.get)
        tuning.active_tuner().record(
            "segment_len", (), seg_winner, timings=seg_times,
            probe_s=time.perf_counter() - t_seg, identity="bitwise",
            program="resilient_scan", default="10")

        # ---- the probed-decision rows, straight from the journal ----
        decisions = [r for r in read_journal(jpath)
                     if r.get("kind") == "tuning_decision"
                     and r.get("source") == "probe"]
        decisions.append({"knob": "segment_len", "bucket": "",
                          "winner": seg_winner, "default": "10",
                          "timings": seg_times, "identity": "bitwise",
                          "probe_s": round(time.perf_counter() - t_seg,
                                           6)})
        cold = {}
        for d in decisions:
            timings = {k: v for k, v in (d.get("timings") or {}).items()
                       if v is not None}
            if not timings:
                continue
            t_win = timings[d["winner"]]
            t_def = timings.get(str(d.get("default")))
            cold[d["knob"]] = d["winner"]
            rows.append({
                "metric": f"tuning_{d['knob']}_probe",
                # fastest-static / winner: 1.0 when the tuner picked
                # the measured argmin (always, on a fresh probe) —
                # the gate guards replayed/edited caches
                "value": round(min(timings.values()) / t_win, 4),
                "unit": "x", "threshold_x": TUNE_WINNER_THRESHOLD_X,
                "winner": d["winner"], "default": d.get("default"),
                "speedup_vs_default_x":
                    round(t_def / t_win, 3) if t_def else None,
                "bucket": d.get("bucket"),
                "identity": d.get("identity"),
                "probe_s": d.get("probe_s"),
                "timings": {k: round(v, 6)
                            for k, v in timings.items()},
                "backend": "cpu", "env": env,
            })

        # ---- warm half: a fresh session resolves from the cache ----
        tuning.tuner._reset_for_tests()
        tuning.enable(cache_dir)
        warm_keys = (
            ("nd_impl", (3, tuning.shape_bucket(TUNE_ND_N))),
            ("compaction", ()),
            ("eigh_impl", (16,)),
            ("fused", cold_fused_bucket(decisions)),
            ("gp_mode", (TUNE_GP_ML,)),
            ("segment_len", ()),
        )
        t0 = time.perf_counter()
        warm = {knob: tuning.resolve(knob, bucket=bucket,
                                     default="_static_", check=None)
                for knob, bucket in warm_keys}
        warm_s = time.perf_counter() - t0  # includes the file read
        for knob, got in warm.items():
            want = cold.get(knob)
            assert want is None or got == want, \
                f"warm cache replayed {knob}={got!r}, probed {want!r}"
        assert "_static_" not in warm.values(), \
            f"a warm key missed the cache: {warm}"

        # headline: one tuned GP symbreg run, the workload the warm
        # resolves amortise against
        loop(jax.random.key(31), founders, 2)          # warm compiles
        t0 = time.perf_counter()
        loop(jax.random.key(31), founders, 10)
        headline_s = time.perf_counter() - t0
        rows.append({
            "metric": "tuning_warm_overhead_pct",
            "value": round(100 * warm_s / headline_s, 4),
            "unit": "pct", "threshold_pct": TUNE_WARM_THRESHOLD_PCT,
            "warm_resolve_s": round(warm_s, 6),
            "n_keys": len(warm_keys),
            "headline_s": round(headline_s, 6),
            "headline": f"symbreg pop=32 ml={TUNE_GP_ML} ngen=10",
            "backend": "cpu", "env": env,
        })
        rows.append({
            "metric": "tuning_cold_probe_wall_seconds",
            "value": round(cold_wall, 3), "unit": "seconds",
            "n_knobs": len(cold), "backend": "cpu", "env": env,
        })
    finally:
        tuning.disable()
        tuning.tuner._reset_for_tests()
        shutil.rmtree(work, ignore_errors=True)

    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": env,
            "config": {"nd_n": TUNE_ND_N, "pop": TUNE_POP,
                       "gp_max_len": TUNE_GP_ML,
                       "seg_candidates": list(TUNE_SEG_CANDIDATES),
                       "seg_pop": TUNE_SEG_POP,
                       "seg_ngen": TUNE_SEG_NGEN, "reps": 3},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


def cold_fused_bucket(decisions: list) -> tuple:
    """The fused knob's probe bucket, recovered from its journal row
    (it encodes op/pop/len/dtype — simpler to read back than to
    recompute the tuner's bucketing here)."""
    for d in decisions:
        if d.get("knob") == "fused":
            return tuple(d.get("bucket", "").split("/"))
    return ()


# --------------------------------------------------------- mesh bench ----
#
# The sharding-plan acceptance measurement (ISSUE 8): on a forced
# 8-virtual-device CPU mesh, (1) the island epoch driven by the
# pmap-era shard_map/ppermute path vs the SAME epoch as a
# plan-compiled global program (migration lowered to resharding by the
# partitioner) — paired same-session, the pjit path gated >= 0.95x;
# (2) the donate_argnums row — one jitted ea_simple generation step at
# pop=100k driven carry-to-carry with and without donation, plus the
# proof the generation-step copy is gone (the donated carry's buffers
# are consumed in place: deleted after the call, their bytes counted);
# (3) the CMA serving bucket's batched-eigh pair — the vmapped lane
# update with LAPACK eigh (serial per-lane loop) vs the pure-XLA
# Jacobi solver that vectorises across lanes (the eigh-loop bound on
# the committed 3.0x CMA serving number).
#
# Runs as a CHILD process (bench.py --mesh re-execs with XLA_FLAGS
# forcing the virtual device count, which must be set before jax
# initialises).

MESH_DEVICES = 8
MESH_ISLANDS = 8
MESH_EPOCHS = 3
MESH_FREQ = 2
MESH_REPS = 3
MESH_DON_GENS = 20
MESH_EIGH_LANES = 1024   # the BENCH_SERVING CMA bucket scale
MESH_EIGH_DIM = 8
MESH_EIGH_NGEN = 10


def mesh_lines(out_path: str = "BENCH_MESH.json") -> list:
    import gc

    from deap_tpu.algorithms import make_ea_simple_step
    from deap_tpu.core.population import init_population as _initpop
    from deap_tpu.parallel import (ShardingPlan, island_init,
                                   make_island_step, population_mesh,
                                   shard_population)
    from deap_tpu.serving.multirun import MultiRunEngine
    from deap_tpu.strategies import cma as _cma

    jax.config.update("jax_platforms", "cpu")
    n_dev = len(jax.devices())
    if n_dev < MESH_DEVICES:
        raise SystemExit(
            f"mesh bench needs {MESH_DEVICES} devices, found {n_dev} — "
            "run via `bench.py --mesh` (the parent sets XLA_FLAGS)")
    env = _env_fingerprint("cpu")
    env["n_devices"] = n_dev
    rows = []

    # ---- (1) island epoch: shard_map ("pmap-era") vs plan (pjit) ----
    tb = _toolbox()
    island_size = POP // MESH_ISLANDS
    pops0 = island_init(jax.random.key(5), MESH_ISLANDS, island_size,
                        ops.bernoulli_genome(LENGTH),
                        FitnessSpec((1.0,)))
    pops0 = jax.vmap(lambda p: evaluate_invalid(p, tb.evaluate))(pops0)

    mesh = population_mesh(MESH_DEVICES, ("island",))
    step_sm = make_island_step(tb, 0.5, 0.2, freq=MESH_FREQ, mig_k=8,
                               mesh=mesh)
    pops_sm0 = shard_population(pops0, mesh, "island")
    plan_i = ShardingPlan.for_islands(MESH_DEVICES, donate=False)
    step_pj = make_island_step(tb, 0.5, 0.2, freq=MESH_FREQ, mig_k=8,
                               plan=plan_i)
    pops_pj0 = plan_i.place(pops0)

    def epochs(step, p):
        for e in range(MESH_EPOCHS):
            p = step(jax.random.fold_in(jax.random.key(9), e), p)
        sync(p.fitness)

    epochs(step_sm, pops_sm0)  # compile + warm, both paths
    epochs(step_pj, pops_pj0)
    t_sm, t_pj = [], []
    for _ in range(MESH_REPS):  # interleaved: contention hits both
        t0 = time.perf_counter()
        epochs(step_sm, pops_sm0)
        t_sm.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        epochs(step_pj, pops_pj0)
        t_pj.append(time.perf_counter() - t0)
    eps = MESH_EPOCHS
    for name, times in (("shardmap", sorted(t_sm)),
                        ("pjit", sorted(t_pj))):
        med = times[len(times) // 2]
        rows.append({
            "metric": f"island_pop100k_{name}_epochs_per_sec",
            "value": round(eps / med, 3), "unit": "epochs/sec",
            "backend": "cpu", "pop": POP, "islands": MESH_ISLANDS,
            "freq": MESH_FREQ, "epochs": eps,
            "n_samples": len(times),
            "best": round(eps / times[0], 3),
            "spread_pct": round(100 * (times[-1] - times[0]) / med, 1),
            "env": env})
    ratio = min(t_sm) / min(t_pj)  # >1 means pjit faster
    rows.append({
        "metric": "mesh_pjit_vs_shardmap_ratio",
        "value": round(ratio, 3), "unit": "x", "threshold": 0.95,
        "estimator": "min_of_reps", "env": env})
    del pops_sm0, pops_pj0, pops0
    gc.collect()

    # ---- (2) donation: the generation-step copy eliminated ----
    tb2, pop100k = _setup()
    plan_p = ShardingPlan.for_population(MESH_DEVICES)  # donate=True
    # the PLAN-threaded step: its with_sharding_constraint pins the
    # output population to the input's layout, which is what lets XLA
    # alias the donated carry at all (an unconstrained step's output
    # sharding drifts and the donation is silently unusable)
    step = make_ea_simple_step(tb2, 0.5, 0.2, plan=plan_p)
    jit_nodon = jax.jit(step)
    jit_don = plan_p.compile(step, donate_argnums=(0,), label="donate")
    key = jax.random.key(11)

    def drive(jitted):
        carry = (plan_p.place(pop100k), None)
        for g in range(MESH_DON_GENS):
            carry, _ = jitted(carry, jax.random.fold_in(key, g))
        sync(carry[0].fitness)
        return carry

    drive(jit_nodon)  # compile + warm
    drive(jit_don)
    # proof of in-place aliasing: the donated carry's buffers are
    # consumed by the call — count the bytes that stopped being copied
    probe = (plan_p.place(pop100k), None)
    leaves = jax.tree_util.tree_leaves(probe)
    jit_don(probe, key)
    donated_bytes = sum(
        l.nbytes for l in leaves
        if isinstance(l, jax.Array) and l.is_deleted())
    t_nod, t_don = [], []
    for _ in range(MESH_REPS):
        t0 = time.perf_counter()
        drive(jit_nodon)
        t_nod.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        drive(jit_don)
        t_don.append(time.perf_counter() - t0)
    for name, times in (("nodonate", sorted(t_nod)),
                        ("donate", sorted(t_don))):
        med = times[len(times) // 2]
        rows.append({
            "metric": f"ea_step_pop100k_{name}_generations_per_sec",
            "value": round(MESH_DON_GENS / med, 3), "unit": "gens/sec",
            "backend": "cpu", "pop": POP, "gens": MESH_DON_GENS,
            "n_samples": len(times),
            "best": round(MESH_DON_GENS / times[0], 3),
            "spread_pct": round(100 * (times[-1] - times[0]) / med, 1),
            "env": env})
    rows.append({
        "metric": "mesh_donation",
        "value": round(min(t_nod) / min(t_don), 3), "unit": "x",
        "donated_mb": round(donated_bytes / 1e6, 2),
        "copy_eliminated": donated_bytes > 0,
        "estimator": "min_of_reps", "env": env})
    del pop100k
    gc.collect()

    # ---- (3) CMA serving bucket: batched eigh (lapack vs jacobi) ----
    eigh_times = {}
    for impl in ("lapack", "jacobi"):
        strat = _cma.Strategy(centroid=[2.0] * MESH_EIGH_DIM,
                              sigma=0.3, lambda_=MESH_EIGH_DIM,
                              eigh_impl=impl)
        tbc = Toolbox()
        tbc.register("evaluate", lambda g: (g ** 2).sum(-1))
        tbc.register("generate", strat.generate)
        tbc.register("update", strat.update)
        eng = MultiRunEngine("ea_generate_update", tbc,
                             spec=strat.spec,
                             state_template=strat.initial_state())
        keys = jnp.stack([jax.random.key(300 + i)
                          for i in range(MESH_EIGH_LANES)])
        inits = [strat.initial_state(sigma=0.2 + 0.01 * i)
                 for i in range(MESH_EIGH_LANES)]
        batch0 = eng.pack_fresh(keys, inits, ngen=MESH_EIGH_NGEN)

        def adv():
            b, _ = eng.advance(batch0, MESH_EIGH_NGEN)
            sync(b["gen"])

        adv()  # compile + warm
        ts = []
        for _ in range(MESH_REPS):
            t0 = time.perf_counter()
            adv()
            ts.append(time.perf_counter() - t0)
        eigh_times[impl] = sorted(ts)
        med = eigh_times[impl][len(ts) // 2]
        lane_gens = MESH_EIGH_LANES * MESH_EIGH_NGEN
        rows.append({
            "metric": f"cma_serving_eigh_{impl}_lane_gens_per_sec",
            "value": round(lane_gens / med, 1), "unit": "gens/sec",
            "backend": "cpu", "lanes": MESH_EIGH_LANES,
            "dim": MESH_EIGH_DIM, "ngen": MESH_EIGH_NGEN,
            "n_samples": len(ts),
            "best": round(lane_gens / eigh_times[impl][0], 1),
            "spread_pct": round(
                100 * (eigh_times[impl][-1] - eigh_times[impl][0])
                / med, 1),
            "env": env})
    rows.append({
        "metric": "cma_serving_batched_eigh_speedup_x",
        "value": round(min(eigh_times["lapack"])
                       / min(eigh_times["jacobi"]), 3),
        "unit": "x", "estimator": "min_of_reps",
        "lanes": MESH_EIGH_LANES, "dim": MESH_EIGH_DIM, "env": env})

    if out_path:
        payload = {
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "env": env,
            "config": {"pop": POP, "length": LENGTH,
                       "devices": MESH_DEVICES,
                       "islands": MESH_ISLANDS, "freq": MESH_FREQ,
                       "epochs": MESH_EPOCHS, "reps": MESH_REPS,
                       "donate_gens": MESH_DON_GENS,
                       "eigh_lanes": MESH_EIGH_LANES,
                       "eigh_dim": MESH_EIGH_DIM},
            "tail": "\n".join(json.dumps(r) for r in rows),
        }
        with open(out_path, "w") as fh:
            json.dump(payload, fh, indent=1)
    return rows


def _journal_probe_run(tel, tb, pop):
    """--journal satellite: a short probed headline-config run so the
    journal carries per-generation probe rows (search-dynamics
    metrics), not just wall times."""
    from deap_tpu import algorithms

    algorithms.ea_simple(jax.random.key(88), pop, tb, 0.5, 0.2, 5,
                         telemetry=tel, probes=_headline_probes(POP))


def _env_fingerprint(backend: str) -> dict:
    """jax version / backend / device kind — stamped on every emitted
    row so committed BENCH_*.json rows distinguish cached-replay from
    fresh-capture environments. Never initialises the XLA client when
    the backend is the (single-client) TPU: the race children must be
    the only attachers, so the parent reports the kind as unattached."""
    fp = {"jax": jax.__version__, "backend": backend}
    if backend == "cpu":
        try:
            fp["device_kind"] = jax.devices()[0].device_kind
        except Exception:
            pass
    else:
        fp["device_kind"] = "tpu (parent unattached)"
    return fp


def _time_samples(run, *args, journal=None):
    """All REPS wall-second samples of run(*args) after a warm-up
    compile — the raw material for the median+spread headline protocol
    (VERDICT r3 #7: a single sample per window rode ±25% noise).

    With a journal, the warm-up marks the journal steady, so any
    compile during the timed reps surfaces as a ``retrace`` event —
    a retrace inside the measurement window invalidates the sample."""
    sync(run(jax.random.key(100), *args))  # compile + warm
    if journal is not None:
        journal.mark_steady("headline_warm")
    times = []
    for r in range(REPS):
        t0 = time.perf_counter()
        sync(run(jax.random.key(101 + r), *args))
        times.append(time.perf_counter() - t0)
        if journal is not None:
            journal.event("rep", rep=r, seconds=round(times[-1], 6))
    return times


CANDIDATES = ("fused", "packed_sorted", "packed_binned",
              "packed_binned_b4096", "packed_binned_b8192",
              "packed_selgather", "packed_evolve")

# tpu_capture's re-race predicate needs the roster size without
# importing this module (our import probes the relay); fail loudly on
# drift, like SUITE_CONFIG_NAMES/COMPONENT_NAMES
from tpu_capture import N_CANDIDATES  # noqa: E402

if len(CANDIDATES) != N_CANDIDATES:
    raise SystemExit("CANDIDATES drifted from tpu_capture.N_CANDIDATES")


def _setup():
    """The benchmark population — shared by every candidate and the CPU
    baseline so they can never desynchronise."""
    tb = _toolbox()
    pop = init_population(
        jax.random.key(1), POP, ops.bernoulli_genome(LENGTH),
        FitnessSpec((1.0,)))
    return tb, evaluate_invalid(pop, tb.evaluate)


def _run_candidate(name: str) -> list:
    """All REPS wall-second samples for one TPU candidate path. Packed
    names are ``packed_<select>[_b<block_i>]``."""
    _, pop = _setup()
    fit = pop.wvalues[:, 0]
    if name == "fused":
        return _time_samples(make_run_fused(), pop.genomes, fit)
    if name == "packed_selgather":
        packed = ops.pack_genomes(pop.genomes)
        _validate_selgather(packed, fit)
        return _time_samples(make_run_selgather(), packed, fit)
    if name == "packed_evolve":
        packed = ops.pack_genomes(pop.genomes)
        _validate_evolve(packed, fit)
        return _time_samples(make_run_evolve(), packed, fit)
    parts = name.split("_")
    block_i = 1024
    if parts[-1].startswith("b") and parts[-1][1:].isdigit():
        block_i = int(parts.pop()[1:])
    select = "_".join(parts[1:])
    packed = ops.pack_genomes(pop.genomes)
    return _time_samples(make_run_packed(select, block_i), packed, fit)


def _validate_selgather(packed, fit):
    """Semantic gate run BEFORE the selgather candidate is timed: the
    kernel leans on Mosaic's dynamic_gather lowering at a lane extent
    no test exercises on real hardware, and a miscompiled-but-fast
    gather must never win the race. Raises on failure — the candidate
    subprocess then produces no timing and the race continues."""
    import numpy as np

    par = ops.sel_tournament_gather_packed(
        jax.random.key(7), packed, fit, tournsize=3, prng="hw",
        interpret=False)
    # membership over ALL rows: the set lookup is ~100 ms next to the
    # race itself, and a gather miscompile confined to late rows must
    # fail here, not leak into a timed win (advisor r3)
    par_np = np.asarray(par)
    pop_set = {r.tobytes() for r in np.asarray(packed)}
    if not all(r.tobytes() in pop_set for r in par_np):
        raise AssertionError("selgather: non-member parent rows")
    uplift = float(ops.packed_fitness(par).mean()) - float(fit.mean())
    if uplift <= 0.5:
        raise AssertionError(
            f"selgather: no selection pressure (uplift {uplift:.3f})")


def _validate_evolve(packed, fit):
    """Semantic gate run BEFORE the mega-kernel candidate is timed —
    the whole GA loop lives in one kernel, so a miscompile would
    produce a fast wrong answer with nothing else to catch it.
    Selection-only generations must return exact population members
    with popcount-consistent fitness; the full config must climb
    OneMax. Raises on failure (candidate resolves 'failed')."""
    import numpy as np

    sub, subfit = packed[:4096], fit[:4096]
    pop2, fit2 = ops.evolve_packed(
        jax.random.key(11), sub, subfit, LENGTH, 3, cxpb=0.0,
        mutpb=0.0, indpb=0.05, prng="hw", interpret=False)
    pop_set = {r.tobytes() for r in np.asarray(sub)}
    if not all(r.tobytes() in pop_set for r in np.asarray(pop2)):
        raise AssertionError("evolve: non-member rows (selection-only)")
    if not (np.asarray(ops.packed_fitness(pop2))
            == np.asarray(fit2)).all():
        raise AssertionError("evolve: fitness/popcount mismatch")
    _, f5 = ops.evolve_packed(
        jax.random.key(12), packed, fit, LENGTH, 5, cxpb=0.5,
        mutpb=0.2, indpb=0.05, prng="hw", interpret=False)
    uplift = float(f5.mean()) - float(fit.mean())
    if uplift <= 3.0:
        raise AssertionError(
            f"evolve: no OneMax climb over 5 gens (uplift {uplift:.2f})")


def _race_isolated(timeout_s: int = 900):
    """Race the TPU candidates in subprocesses so a relay wedge during
    one compile (observed 2026-07-31, mid-eigh) costs that candidate
    only. Returns ``(best_median_seconds, outcomes, best_times,
    best_name)``: ``outcomes`` maps every candidate to "timed" /
    "failed" (the candidate's semantic gate raised — a structured,
    deterministic resolution) / "died" (unexplained child death,
    retryable) / "timeout" / "unreached" (relay died before its turn),
    so tpu_capture's re-race predicate can tell a fully-resolved
    roster from a partial race; ``best_times``
    is the winning candidate's full sample list (median+spread
    protocol) and ``best_name`` which candidate produced it (the
    utilization line's byte model depends on it)."""
    import subprocess

    me = os.path.abspath(__file__)
    env = dict(os.environ, DEAP_TPU_SKIP_PROBE="1")
    # mid-race liveness checks must be the 1 s port scan only — the
    # slow stage would re-attach the single-client TPU between
    # candidates (and burn its 180 s timeout on a wedged relay)
    os.environ["DEAP_TPU_SKIP_PROBE"] = "1"
    best = float("inf")
    best_times = []
    best_name = None
    outcomes = {name: "unreached" for name in CANDIDATES}
    for name in CANDIDATES:
        if not axon_tunnel_reachable():
            print(f"bench: relay port closed before {name}; stopping "
                  "race", file=sys.stderr)
            break  # relay died mid-race; keep what we have
        try:
            r = subprocess.run(
                [sys.executable, me, "--candidate", name], env=env,
                capture_output=True, text=True, timeout=timeout_s)
            got = None
            times = []
            gate_failed = None
            for ln in r.stdout.splitlines():
                if not ln.startswith("{"):
                    continue
                # stray JSON lines (library logs) must not abort the
                # candidate's line loop and discard a later timing
                try:
                    d = json.loads(ln)
                except json.JSONDecodeError:
                    continue
                if "seconds" in d:
                    got = d["seconds"]
                    times = d.get("times", [got])
                elif "gate_failed" in d:
                    gate_failed = d["gate_failed"]
            if got is not None:
                outcomes[name] = "timed"
                # candidates compare on MEDIAN, like the headline —
                # a single lucky sample must not pick the winner (and
                # with it the byte model) out of the noise floor
                med = sorted(times)[len(times) // 2]
                if med < best:
                    best, best_times, best_name = med, times, name
            elif gate_failed is not None:
                # the candidate's own semantic gate raised — a
                # deterministic resolution (structured line printed by
                # the child), terminal for this roster
                outcomes[name] = "failed"
                print(f"bench: candidate {name} gate failed: "
                      f"{gate_failed}", file=sys.stderr)
            else:
                # unexplained child death (relay wedge with the port
                # still open, attach conflict, OOM kill): retryable —
                # it must NOT satisfy the full-race predicate
                outcomes[name] = "died"
                print(f"bench: candidate {name} died without a "
                      f"verdict; stderr tail: {(r.stderr or '')[-400:]}",
                      file=sys.stderr)
                if not axon_tunnel_reachable():
                    print("bench: relay down after child death; "
                          "stopping race", file=sys.stderr)
                    break
        except subprocess.TimeoutExpired:
            outcomes[name] = "timeout"
            print(f"bench: candidate {name} timed out after "
                  f"{timeout_s}s", file=sys.stderr)
    return best, outcomes, best_times, best_name


def _probe_backend(timeout_s: int = 240) -> str:
    """Which backend jax resolves to — asked in a THROWAWAY subprocess.
    The accelerator is single-client (tunnel relay and libtpu alike):
    if the orchestrating parent initialised it, every candidate child
    would block on attach. The probe child exits immediately, releasing
    the client before the race starts."""
    import subprocess

    env = dict(os.environ, DEAP_TPU_SKIP_PROBE="1")
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            env=env, capture_output=True, text=True, timeout=timeout_s)
        lines = r.stdout.strip().splitlines()
        return lines[-1] if lines else "none"
    except subprocess.TimeoutExpired:
        return "none"


def _cached_tpu_row():
    """The most recent valid TPU headline row captured this round
    (``TPU_EVIDENCE_{ROUND}.jsonl``, written by tpu_capture.py) — or,
    when this round never saw an uptime window, the most recent prior
    round's, stamped with its source file. Replayed — clearly marked —
    when the relay is down at measurement time: a timestamped on-chip
    measurement is strictly more informative than a live CPU-fallback
    number, and the relay has been reachable for well under an hour
    per round."""
    import glob

    from tpu_capture import EVIDENCE, headline_rows

    rows = headline_rows()
    src = os.path.basename(EVIDENCE)
    if not rows:
        # no window this round yet: fall back to the most recent prior
        # round's evidence, through the SAME validity filter
        here = os.path.dirname(os.path.abspath(__file__))
        for path in sorted(glob.glob(
                os.path.join(here, "TPU_EVIDENCE_r*.jsonl")),
                reverse=True):
            prior = headline_rows(path)
            if prior:
                rows, src = prior, os.path.basename(path)
                break
    if not rows:
        return None
    # most-recent, not best-ever: the replay must report what the code
    # currently does, not cherry-pick a superseded peak
    row = max(rows, key=lambda r: r["measured_at"] or "")
    row["cache_source"] = src
    return row


def main(journal_path=None):
    backend = _probe_backend() if _TUNNEL_OK else "cpu"
    tel = None
    if journal_path:
        # --journal: emit a run journal alongside the headline rows —
        # header fingerprint, compile/retrace events (a retrace inside
        # the timed reps invalidates the sample), per-rep wall times,
        # span aggregates, summary. Header must not attach the
        # single-client TPU from this parent process.
        from deap_tpu.telemetry import RunTelemetry
        tel = RunTelemetry(journal_path, init_backend=(backend == "cpu"))
        tel.__enter__()
        tel.journal.header(init_backend=(backend == "cpu"),
                           bench="onemax_pop100k", pop=POP, ngen=NGEN)
    try:
        _main_measure(backend, tel)
    finally:
        if tel is not None:
            tel.__exit__(None, None, None)


def _main_measure(backend, tel=None):
    journal = tel.journal if tel is not None else None
    if backend != "tpu":
        # DEAP_TPU_BENCH_LIVE=1 forces a live (CPU-fallback) run —
        # needed when measuring changes to the portable XLA path on a
        # machine whose evidence file already holds a TPU row
        cached = (None if os.environ.get("DEAP_TPU_BENCH_LIVE")
                  else _cached_tpu_row())
        if cached is not None:
            cached["cached"] = True
            # a distinct backend value so naive backend=="tpu" checks
            # can never mistake a replay for a live measurement
            # (advisor r3); headline_rows() filters on "cached" too
            cached["backend"] = "tpu-cached"
            cached["cache_note"] = (
                "relay down at measurement time; replaying the most "
                "recent TPU capture from TPU_EVIDENCE (relay timeline: "
                "TPU_PROBE_LOG.jsonl)")
            # env describes the *emitting* process; the measurement
            # environment is whatever captured the replayed row
            cached["env"] = _env_fingerprint("cpu")
            if journal is not None:
                journal.event("headline", **cached)
            print(json.dumps(cached))
            return
    outcomes, times, winner = {}, [], None
    if backend == "tpu":
        dt, outcomes, times, winner = _race_isolated()
        if dt == float("inf"):
            # every isolated candidate died (relay wedged under us):
            # report an honest failure line rather than hanging
            # stamp resolution counts so tpu_capture._have_full_race
            # can treat a fully-resolved all-failed race as terminal
            # instead of re-running it every window (advisor r4)
            print(json.dumps({
                "metric": "onemax_pop100k_generations_per_sec",
                "value": 0.0, "unit": "gens/sec", "vs_baseline": 0.0,
                "backend": "tpu", "error": "all candidates failed",
                "candidates": outcomes,
                "n_candidates": 0,
                "n_resolved": sum(v in ("timed", "failed")
                                  for v in outcomes.values())}))
            return
    else:
        backend = "cpu"
        jax.config.update("jax_platforms", "cpu")
        tb, pop = _setup()
        times = _time_samples(make_run_xla(tb), pop, journal=journal)
        dt = min(times)
        if tel is not None:
            # after the timed reps: a short probed run so the journal
            # carries search-dynamics rows for the headline config (its
            # compiles land after mark_steady and journal as retraces —
            # correctly: they are post-warmup compiles, outside the reps)
            _journal_probe_run(tel, tb, pop)

    times = sorted(times)
    median_dt = times[len(times) // 2]
    gens_per_sec = NGEN / median_dt
    line = {
        "metric": "onemax_pop100k_generations_per_sec",
        # the headline is the MEDIAN of the winner's samples — a
        # single best-of sample rode ±25% window-to-window noise in r3
        "value": round(gens_per_sec, 2),
        "unit": "gens/sec",
        "vs_baseline": round(gens_per_sec / REFERENCE_GENS_PER_SEC, 1),
        "backend": backend,
        "env": _env_fingerprint(backend),
        "best": round(NGEN / times[0], 2),
        "spread_pct": round(100 * (times[-1] - times[0]) / median_dt, 1),
        "n_samples": len(times),
        # per-candidate resolution — "timed"/"failed" are terminal,
        # "timeout"/"unreached" mean the race was partial (tpu_capture's
        # re-race predicate keys on this)
        "candidates": outcomes,
        "n_candidates": sum(v == "timed" for v in outcomes.values()),
        "n_resolved": sum(v in ("timed", "failed")
                          for v in outcomes.values()),
    }
    if backend == "tpu":
        # the honest "MFU" of a popcount workload: analytic HBM
        # bytes/gen (per the WINNING candidate's genome layout) against
        # the v5e bandwidth roof — meaningless for a CPU fallback run,
        # so only stamped on live TPU rows
        bpg = _hbm_bytes_per_gen(winner or "packed")
        gbps = bpg * gens_per_sec / 1e9
        line["winner"] = winner
        line["hbm_bytes_per_gen"] = bpg
        line["achieved_gbps"] = round(gbps, 2)
        line["pct_of_peak_bw"] = round(100 * gbps / PEAK_HBM_GBPS, 2)
    if not _TUNNEL_OK:
        # self-describing CPU fallback: the axon relay was down at
        # measurement time — this line is not a TPU regression signal
        line["tunnel_down"] = True
    if journal is not None:
        journal.event("headline", **line)
    print(json.dumps(line))
    if backend == "cpu":
        # the multi-objective headline rides along on CPU runs (the
        # TPU race roster is pinned by tpu_capture; on-chip MO capture
        # is a suite concern). Distinct metric name — headline parsers
        # key on "metric" and never see this as the onemax row.
        mline = mo_line(backend)
        mline["env"] = _env_fingerprint(backend)
        if not _TUNNEL_OK:
            mline["tunnel_down"] = True
        if journal is not None:
            journal.event("headline", **mline)
        print(json.dumps(mline))


if __name__ == "__main__":
    if "--gp-race" in sys.argv:
        # the GP interpreter race: reference-proxy vs scan-loop vs the
        # specialized host loop, back-to-back in one session, plus
        # per-component deltas (mask/grouped/dedup/tiling) — committed
        # as BENCH_GP.json (see bench_gp.py)
        import bench_gp

        i = sys.argv.index("--gp-race")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        bench_gp.main(nxt if nxt and not nxt.startswith("--")
                      else "BENCH_GP.json")
    elif "--probes" in sys.argv:
        # the probe-overhead acceptance measurement: headline config
        # probe-off vs probe-on, same session (committed as
        # BENCH_PROBES.json; bench_report.py --tripwire gates on it)
        i = sys.argv.index("--probes")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = nxt if nxt and not nxt.startswith("--") else "BENCH_PROBES.json"
        for row in probe_overhead_lines(out):
            print(json.dumps(row), flush=True)
    elif "--fusion" in sys.argv:
        # the fused-variation acceptance measurement: headline config
        # with the variation plane unfused vs fused (bit-identity
        # asserted first), the GP compaction host-vs-device pair, and
        # the compile-cache cold/warm rows — committed as
        # BENCH_FUSION.json; bench_report.py --tripwire gates the pairs
        i = sys.argv.index("--fusion")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_FUSION.json")
        for row in fusion_lines(out,
                                coldstart="--no-coldstart" not in sys.argv):
            print(json.dumps(row), flush=True)
    elif "--serving" in sys.argv:
        # the multi-tenant serving acceptance measurement: 1k
        # concurrent OneMax + CMA tenants through one vectorized
        # multi-run scan vs the same 1k sequentially, same session
        # (committed as BENCH_SERVING.json; bench_report.py --tripwire
        # gates the batched/sequential ratios)
        jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--serving")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_SERVING.json")
        for row in serving_lines(out):
            print(json.dumps(row), flush=True)
    elif "--gp-serving" in sys.argv:
        # the batched-GP serving acceptance measurement (ISSUE 14): 64
        # symbreg tenants through one run-axis scan vs the same 64
        # sequentially through the solo loop (bit-identity asserted),
        # the island-epoch pair, and a same-session solo headline row
        # — committed as BENCH_GP_SERVING.json; bench_report.py
        # --tripwire gates the ratio, the bit row and the solo number
        jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--gp-serving")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_GP_SERVING.json")
        for row in gp_serving_lines(out):
            print(json.dumps(row), flush=True)
    elif "--service-chaos" in sys.argv:
        # the fault-tolerance acceptance measurement (ISSUE 12): a
        # child service SIGKILLed mid-run under 200 live retrying
        # tenants, supervisor restart over the same root — committed
        # as BENCH_CHAOS.json; bench_report.py --tripwire gates zero
        # lost jobs / 100% digest identity / bounded recovery wall
        jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--service-chaos")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_CHAOS.json")
        for row in service_chaos_lines(out):
            print(json.dumps(row), flush=True)
    elif "--loadgen" in sys.argv:
        # the load-observatory acceptance measurement (ISSUE 17):
        # seeded open-loop traffic models with windowed SLO curves +
        # gates, journal record→replay with a pacing-fidelity gate +
        # digest identity, and the segment-stall attribution demo —
        # committed as BENCH_LOADGEN.json; bench_report.py --tripwire
        # gates green SLOs / fidelity / bit-identity / "segment"
        jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--loadgen")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_LOADGEN.json")
        for row in loadgen_lines(out):
            print(json.dumps(row), flush=True)
    elif "--migration" in sys.argv:
        # the zero-downtime acceptance measurement (ISSUE 20): the
        # rolling-upgrade drill (old-version child drains ?handoff=
        # into a compat-gated new-version child — zero lost, 100%
        # digest identity, canaries green, compat_restore journaled,
        # pause p99 budget) plus the upgrade-under-load loadgen delta
        # — committed as BENCH_MIGRATION.json; bench_report.py
        # --tripwire gates every row and cross-checks the pause p99
        # against BENCH_CHAOS's whole-service recovery wall
        jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--migration")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_MIGRATION.json")
        for row in migration_lines(out):
            print(json.dumps(row), flush=True)
    elif "--canary" in sys.argv:
        # the canary/alerting acceptance measurement (ISSUE 19): the
        # 1k-tenant socket config canary-off vs canary-on (zero false
        # alarms, overhead <= 3%) plus the injected-corruption
        # detection-latency run (firing alert within two segment
        # boundaries) — committed as BENCH_CANARY.json;
        # bench_report.py --tripwire gates all three
        jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--canary")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_CANARY.json")
        for row in canary_lines(out):
            print(json.dumps(row), flush=True)
    elif "--tracing" in sys.argv:
        # the tracing-overhead acceptance measurement (ISSUE 15): the
        # 1k-tenant socket config with tracing off vs sampled 0.1 vs
        # always-on 1.0, interleaved min-of-reps, bit-identical wire
        # digests asserted — committed as BENCH_TRACING.json;
        # bench_report.py --tripwire gates sampled overhead <= 3%
        jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--tracing")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_TRACING.json")
        for row in tracing_lines(out):
            print(json.dumps(row), flush=True)
    elif "--service" in sys.argv:
        # the network-service acceptance measurement (ISSUE 11): 1k
        # tenants through real loopback sockets vs the same jobs
        # in-process (overhead <= 10%, bit-identical wire digests),
        # plus the bursty autoscaler-off/on queue-wait p99 pair —
        # committed as BENCH_SERVICE.json; bench_report.py --tripwire
        # gates overhead/bit-identity/p99-improvement
        jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--service")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_SERVICE.json")
        for row in service_lines(out):
            print(json.dumps(row), flush=True)
    elif "--tuning" in sys.argv:
        # the dispatch-tuner acceptance measurement (ISSUE 16): cold
        # probes for every tunable knob (winner within 5% of the best
        # static candidate, identity checks passing), the out-of-band
        # segment_len sweep, and the warm-cache amortisation row
        # (fresh-session resolves <= 1% of a headline GP run) —
        # committed as BENCH_TUNING.json; bench_report.py --tripwire
        # gates all three
        jax.config.update("jax_platforms", "cpu")
        i = sys.argv.index("--tuning")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_TUNING.json")
        for row in tuning_lines(out):
            print(json.dumps(row), flush=True)
    elif "--mesh-child" in sys.argv:
        # the re-exec'd worker: XLA_FLAGS already forces the virtual
        # device count (set by the parent below, before jax init)
        out = sys.argv[sys.argv.index("--mesh-child") + 1]
        for row in mesh_lines(out):
            print(json.dumps(row), flush=True)
    elif "--mesh" in sys.argv:
        # the sharding-plan acceptance measurement (ISSUE 8): paired
        # shard_map-vs-pjit island rows, the donate_argnums row, and
        # the CMA batched-eigh pair on a forced 8-virtual-device CPU
        # mesh — committed as BENCH_MESH.json; bench_report.py
        # --tripwire gates pjit >= 0.95x shard_map and the donation
        # row. Re-execs itself: the virtual device count only takes
        # effect when XLA_FLAGS is set before jax initialises.
        import subprocess

        i = sys.argv.index("--mesh")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_MESH.json")
        child_env = dict(os.environ)
        flags = [f for f in child_env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append("--xla_force_host_platform_device_count="
                     f"{MESH_DEVICES}")
        child_env["XLA_FLAGS"] = " ".join(flags)
        child_env["JAX_PLATFORMS"] = "cpu"
        raise SystemExit(subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mesh-child",
             out], env=child_env).returncode)
    elif "--coldstart-child" in sys.argv:
        i = sys.argv.index("--coldstart-child")
        mode = (sys.argv[i + 2] if i + 2 < len(sys.argv)
                and not sys.argv[i + 2].startswith("--") else "warm")
        _coldstart_child(sys.argv[i + 1], mode)
    elif "--coldstart" in sys.argv:
        # the cold-start waterfall (ROADMAP item 5 / ISSUE 18):
        # per-phase time_to_first_generation under empty / warm-XLA /
        # artifact-store cache regimes — committed BENCH_COLDSTART.json
        for row in coldstart_lines():
            print(json.dumps(row), flush=True)
    elif "--costs" in sys.argv:
        # the observability-layer acceptance measurement (ISSUE 9):
        # headline config with the full third layer off vs on
        # (program observatory + metrics registry + flight recorder),
        # bit-identity asserted first, plus one committed
        # program_cost_* row per compiled program with
        # flops/bytes/compile-time/donated-alias-bytes — committed as
        # BENCH_COSTS.json; bench_report.py --tripwire gates overhead
        # <= 3% and nonzero aliasing on donating programs
        i = sys.argv.index("--costs")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_COSTS.json")
        for row in costs_lines(out):
            print(json.dumps(row), flush=True)
    elif "--resilience" in sys.argv:
        # the resilience acceptance measurement: monolithic scan vs
        # ResilientRun-segmented run with per-segment crash-consistent
        # checkpoints, same session (committed as BENCH_RESILIENCE.json;
        # bench_report.py --tripwire gates overhead <= 3%)
        i = sys.argv.index("--resilience")
        nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
        out = (nxt if nxt and not nxt.startswith("--")
               else "BENCH_RESILIENCE.json")
        for row in resilience_overhead_lines(out):
            print(json.dumps(row), flush=True)
    elif "--nd3" in sys.argv:
        # the M>=3 nd-sort acceptance measurement: per-impl nd_rank
        # timings at n=50k plus the NSGA-II 3-obj generations/sec row,
        # one JSON line each (committed as BENCH_ND3.json)
        jax.config.update("jax_platforms", "cpu")
        for row in nd3_lines():
            print(json.dumps(row), flush=True)
        print(json.dumps(mo_line("cpu")), flush=True)
    elif "--candidate" in sys.argv:
        name = sys.argv[sys.argv.index("--candidate") + 1]
        try:
            times = _run_candidate(name)
        except AssertionError as e:
            # a semantic gate raising is a DETERMINISTIC resolution —
            # the structured line is what lets the parent distinguish
            # it from a transient child death (which must stay
            # retryable in later windows)
            print(json.dumps({"candidate": name,
                              "gate_failed": str(e)[:300]}))
            sys.exit(1)
        print(json.dumps({"candidate": name, "seconds": min(times),
                          "times": times}))
    else:
        journal_path = None
        if "--journal" in sys.argv:
            i = sys.argv.index("--journal")
            nxt = sys.argv[i + 1] if i + 1 < len(sys.argv) else None
            journal_path = (nxt if nxt and not nxt.startswith("--")
                            else "bench_journal.jsonl")
        main(journal_path)
