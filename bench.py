"""Headline benchmark: OneMax GA, pop=100k, 100-bit genomes, eaSimple
config (cxTwoPoint cxpb=.5, mutFlipBit(0.05) mutpb=.2, selTournament(3))
— the BASELINE.json north-star configuration.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "gens/sec", "vs_baseline": N}

``vs_baseline`` is measured against the reference CPU implementation run
on this machine: examples/ga/onemax.py scaled to pop=100k = 0.1681
generations/sec (5.947 s/gen, see BASELINE.md). Target is >=100x.
"""

import json
import time

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu import ops
from deap_tpu.algorithms import evaluate_invalid, var_and
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import gather, init_population
from deap_tpu.core.toolbox import Toolbox

REFERENCE_GENS_PER_SEC = 0.1681  # CPU DEAP, measured 2026-07-29 (BASELINE.md)

POP = 100_000
LENGTH = 100
NGEN = 100


def main():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(
        jax.random.key(1), POP, ops.bernoulli_genome(LENGTH),
        FitnessSpec((1.0,)))
    pop = evaluate_invalid(pop, tb.evaluate)

    def gen_step(pop, key):
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, gather(pop, idx), tb, 0.5, 0.2)
        return evaluate_invalid(off, tb.evaluate), None

    @jax.jit
    def run(key, pop):
        pop, _ = lax.scan(gen_step, pop, jax.random.split(key, NGEN))
        return pop

    # compile + warmup
    jax.block_until_ready(run(jax.random.key(2), pop))
    t0 = time.perf_counter()
    out = jax.block_until_ready(run(jax.random.key(3), pop))
    dt = time.perf_counter() - t0

    gens_per_sec = NGEN / dt
    print(json.dumps({
        "metric": "onemax_pop100k_generations_per_sec",
        "value": round(gens_per_sec, 2),
        "unit": "gens/sec",
        "vs_baseline": round(gens_per_sec / REFERENCE_GENS_PER_SEC, 1),
    }))


if __name__ == "__main__":
    main()
