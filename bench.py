"""Headline benchmark: OneMax GA, pop=100k, 100-bit genomes, eaSimple
config (cxTwoPoint cxpb=.5, mutFlipBit(0.05) mutpb=.2, selTournament(3))
— the BASELINE.json north-star configuration.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "gens/sec", "vs_baseline": N}

``vs_baseline`` is measured against the reference CPU implementation run
on this machine: examples/ga/onemax.py scaled to pop=100k = 0.1681
generations/sec (5.947 s/gen, see BASELINE.md). Target is >=100x.

On TPU the generation step runs the fused Pallas kernel
(deap_tpu.ops.kernels.fused_variation_eval): two-point crossover +
flip-bit mutation + popcount fitness in one HBM pass, with per-gene
random bits from the core's hardware PRNG. Off-TPU it falls back to the
portable XLA path (var_and + masked re-evaluation).

Timing note: device completion is forced by fetching a scalar reduction
of the result — on remote-attached TPU runtimes ``jax.block_until_ready``
can return before execution finishes, silently inflating throughput.
The scalar fetch's fixed round-trip latency is amortised over NGEN.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _axon_probe import axon_tunnel_reachable

_TUNNEL_OK = axon_tunnel_reachable()
if not _TUNNEL_OK:
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax

if not _TUNNEL_OK:
    # the axon sitecustomize pins jax_platforms at import; re-force cpu
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax import lax

from deap_tpu import ops
from deap_tpu.algorithms import evaluate_invalid, var_and
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import gather, init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.ops.kernels import fused_variation_eval
from deap_tpu.support.profiling import sync

REFERENCE_GENS_PER_SEC = 0.1681  # CPU DEAP, measured 2026-07-29 (BASELINE.md)

POP = 100_000
LENGTH = 100
NGEN = 200
REPS = 3


def _toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def make_run_xla(tb):
    """Portable path: the public eaSimple building blocks."""
    def gen_step(pop, key):
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, gather(pop, idx), tb, 0.5, 0.2)
        return evaluate_invalid(off, tb.evaluate), None

    @jax.jit
    def run(key, pop):
        pop, _ = lax.scan(gen_step, pop, jax.random.split(key, NGEN))
        return pop.wvalues[:, 0]

    return run


def make_run_fused():
    """TPU path: tournament select + fused Pallas variation/eval."""
    def gen_step(carry, key):
        genomes, fit = carry
        k_sel, k_var = jax.random.split(key)
        idx = ops.sel_tournament(k_sel, fit[:, None], POP, tournsize=3)
        children, newfit = fused_variation_eval(
            k_var, genomes[idx], cxpb=0.5, mutpb=0.2, indpb=0.05,
            prng="hw", block_i=1024, interpret=False)
        return (children, newfit), None

    @jax.jit
    def run(key, genomes, fit):
        (_, f), _ = lax.scan(gen_step, (genomes, fit),
                             jax.random.split(key, NGEN))
        return f

    return run


def packed_selector(select="sorted"):
    """The headline config's tournament (tournsize 3) as an index
    selector. ``"binned"`` swaps the full lexsort for the counting-sort
    rank path (bit-exact winners — OneMax fitness is integer in
    [0, LENGTH]). Shared with bench_profile.py so the profiled
    configuration can never drift from the measured one."""
    if select == "binned":
        return lambda k, w, n: ops.sel_tournament_binned(
            k, w, n, tournsize=3, low=0, high=LENGTH)
    return lambda k, w, n: ops.sel_tournament_sorted(k, w, n, tournsize=3)


def make_run_packed(select="sorted", block_i=1024):
    """TPU path, bit-packed genomes: 32 genes/uint32 word cuts the
    genome HBM stream 8× (see deap_tpu.ops.packed); rank-based
    tournament avoids per-aspirant fitness gathers. ``block_i`` is the
    kernel's rows-per-grid-program tile — raced because the per-program
    footprint is tiny (16 B/row) and fewer, larger programs may beat
    the 1024-row default at this kernel's scale."""
    sel = packed_selector(select)

    def gen_step(carry, key):
        packed, fit = carry
        k_sel, k_var = jax.random.split(key)
        idx = sel(k_sel, fit[:, None], POP)
        children, newfit = ops.fused_variation_eval_packed(
            k_var, packed[idx], LENGTH, cxpb=0.5, mutpb=0.2, indpb=0.05,
            prng="hw", block_i=block_i, interpret=False)
        return (children, newfit), None

    @jax.jit
    def run(key, packed, fit):
        (_, f), _ = lax.scan(gen_step, (packed, fit),
                             jax.random.split(key, NGEN))
        return f

    return run


def make_run_selgather():
    """TPU path, VMEM-resident selection: tournament + parent gather in
    ONE single-program Pallas kernel (the packed population and fitness
    fit in VMEM whole at this scale — see
    ops.packed.sel_tournament_gather_packed), then the tiled fused
    variation kernel. No sort, no rank permutation, no XLA gather."""
    def gen_step(carry, key):
        packed, fit = carry
        k_sel, k_var = jax.random.split(key)
        parents = ops.sel_tournament_gather_packed(
            k_sel, packed, fit, tournsize=3, prng="hw", interpret=False)
        children, newfit = ops.fused_variation_eval_packed(
            k_var, parents, LENGTH, cxpb=0.5, mutpb=0.2, indpb=0.05,
            prng="hw", block_i=1024, interpret=False)
        return (children, newfit), None

    @jax.jit
    def run(key, packed, fit):
        (_, f), _ = lax.scan(gen_step, (packed, fit),
                             jax.random.split(key, NGEN))
        return f

    return run


def _time(run, *args):
    """Best-of-REPS wall seconds of run(*args); sync() is the actual
    completion barrier (see support.profiling.sync)."""
    sync(run(jax.random.key(100), *args))  # compile + warm
    best = float("inf")
    for r in range(REPS):
        t0 = time.perf_counter()
        sync(run(jax.random.key(101 + r), *args))
        best = min(best, time.perf_counter() - t0)
    return best


CANDIDATES = ("fused", "packed_sorted", "packed_binned",
              "packed_binned_b4096", "packed_binned_b8192",
              "packed_selgather")

# tpu_capture's re-race predicate needs the roster size without
# importing this module (our import probes the relay); fail loudly on
# drift, like SUITE_CONFIG_NAMES/COMPONENT_NAMES
from tpu_capture import N_CANDIDATES  # noqa: E402

if len(CANDIDATES) != N_CANDIDATES:
    raise SystemExit("CANDIDATES drifted from tpu_capture.N_CANDIDATES")


def _setup():
    """The benchmark population — shared by every candidate and the CPU
    baseline so they can never desynchronise."""
    tb = _toolbox()
    pop = init_population(
        jax.random.key(1), POP, ops.bernoulli_genome(LENGTH),
        FitnessSpec((1.0,)))
    return tb, evaluate_invalid(pop, tb.evaluate)


def _run_candidate(name: str) -> float:
    """Best-of-REPS seconds for one TPU candidate path. Packed names
    are ``packed_<select>[_b<block_i>]``."""
    _, pop = _setup()
    fit = pop.wvalues[:, 0]
    if name == "fused":
        return _time(make_run_fused(), pop.genomes, fit)
    if name == "packed_selgather":
        packed = ops.pack_genomes(pop.genomes)
        _validate_selgather(packed, fit)
        return _time(make_run_selgather(), packed, fit)
    parts = name.split("_")
    block_i = 1024
    if parts[-1].startswith("b") and parts[-1][1:].isdigit():
        block_i = int(parts.pop()[1:])
    select = "_".join(parts[1:])
    packed = ops.pack_genomes(pop.genomes)
    return _time(make_run_packed(select, block_i), packed, fit)


def _validate_selgather(packed, fit):
    """Semantic gate run BEFORE the selgather candidate is timed: the
    kernel leans on Mosaic's dynamic_gather lowering at a lane extent
    no test exercises on real hardware, and a miscompiled-but-fast
    gather must never win the race. Raises on failure — the candidate
    subprocess then produces no timing and the race continues."""
    import numpy as np

    par = ops.sel_tournament_gather_packed(
        jax.random.key(7), packed, fit, tournsize=3, prng="hw",
        interpret=False)
    par_np = np.asarray(par[:2048])
    pop_set = {r.tobytes() for r in np.asarray(packed)}
    if not all(r.tobytes() in pop_set for r in par_np):
        raise AssertionError("selgather: non-member parent rows")
    uplift = float(ops.packed_fitness(par).mean()) - float(fit.mean())
    if uplift <= 0.5:
        raise AssertionError(
            f"selgather: no selection pressure (uplift {uplift:.3f})")


def _race_isolated(timeout_s: int = 900):
    """Race the TPU candidates in subprocesses so a relay wedge during
    one compile (observed 2026-07-31, mid-eigh) costs that candidate
    only. Returns ``(best_seconds, n_completed)`` — +inf if every
    candidate died; ``n_completed`` counts candidates that actually
    produced a timing, so a partial race is never mistaken for a full
    one (tpu_capture's re-race predicate)."""
    import subprocess

    me = os.path.abspath(__file__)
    env = dict(os.environ, DEAP_TPU_SKIP_PROBE="1")
    # mid-race liveness checks must be the 1 s port scan only — the
    # slow stage would re-attach the single-client TPU between
    # candidates (and burn its 180 s timeout on a wedged relay)
    os.environ["DEAP_TPU_SKIP_PROBE"] = "1"
    best = float("inf")
    n_completed = 0
    for name in CANDIDATES:
        if not axon_tunnel_reachable():
            print(f"bench: relay port closed before {name}; stopping "
                  "race", file=sys.stderr)
            break  # relay died mid-race; keep what we have
        try:
            r = subprocess.run(
                [sys.executable, me, "--candidate", name], env=env,
                capture_output=True, text=True, timeout=timeout_s)
            got = None
            for ln in r.stdout.splitlines():
                if ln.startswith("{"):
                    got = json.loads(ln)["seconds"]
                    best = min(best, got)
            if got is not None:
                n_completed += 1
            if got is None:
                print(f"bench: candidate {name} produced no result; "
                      f"stderr tail: {(r.stderr or '')[-400:]}",
                      file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"bench: candidate {name} timed out after "
                  f"{timeout_s}s", file=sys.stderr)
        except (json.JSONDecodeError, KeyError) as e:
            print(f"bench: candidate {name} output unparseable: {e}",
                  file=sys.stderr)
    return best, n_completed


def _probe_backend(timeout_s: int = 240) -> str:
    """Which backend jax resolves to — asked in a THROWAWAY subprocess.
    The accelerator is single-client (tunnel relay and libtpu alike):
    if the orchestrating parent initialised it, every candidate child
    would block on attach. The probe child exits immediately, releasing
    the client before the race starts."""
    import subprocess

    env = dict(os.environ, DEAP_TPU_SKIP_PROBE="1")
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.default_backend())"],
            env=env, capture_output=True, text=True, timeout=timeout_s)
        lines = r.stdout.strip().splitlines()
        return lines[-1] if lines else "none"
    except subprocess.TimeoutExpired:
        return "none"


def _cached_tpu_row():
    """The most recent valid TPU headline row captured this round
    (``TPU_EVIDENCE_{ROUND}.jsonl``, written by tpu_capture.py), or
    None. Replayed — clearly marked — when the relay is down at
    measurement time: a timestamped on-chip measurement is strictly
    more informative than a live CPU-fallback number, and the relay
    has been reachable for well under an hour per round."""
    from tpu_capture import headline_rows

    rows = headline_rows()
    # most-recent, not best-ever: the replay must report what the code
    # currently does, not cherry-pick a superseded peak
    return (max(rows, key=lambda r: r["measured_at"] or "")
            if rows else None)


def main():
    backend = _probe_backend() if _TUNNEL_OK else "cpu"
    if backend != "tpu":
        # DEAP_TPU_BENCH_LIVE=1 forces a live (CPU-fallback) run —
        # needed when measuring changes to the portable XLA path on a
        # machine whose evidence file already holds a TPU row
        cached = (None if os.environ.get("DEAP_TPU_BENCH_LIVE")
                  else _cached_tpu_row())
        if cached is not None:
            cached["cached"] = True
            cached["cache_note"] = (
                "relay down at measurement time; replaying the most "
                "recent TPU capture from TPU_EVIDENCE (relay timeline: "
                "TPU_PROBE_LOG.jsonl)")
            print(json.dumps(cached))
            return
    n_completed = 0
    if backend == "tpu":
        dt, n_completed = _race_isolated()
        if dt == float("inf"):
            # every isolated candidate died (relay wedged under us):
            # report an honest failure line rather than hanging
            print(json.dumps({
                "metric": "onemax_pop100k_generations_per_sec",
                "value": 0.0, "unit": "gens/sec", "vs_baseline": 0.0,
                "backend": "tpu", "error": "all candidates failed"}))
            return
    else:
        backend = "cpu"
        jax.config.update("jax_platforms", "cpu")
        tb, pop = _setup()
        dt = _time(make_run_xla(tb), pop)

    gens_per_sec = NGEN / dt
    line = {
        "metric": "onemax_pop100k_generations_per_sec",
        "value": round(gens_per_sec, 2),
        "unit": "gens/sec",
        "vs_baseline": round(gens_per_sec / REFERENCE_GENS_PER_SEC, 1),
        "backend": backend,
        # how many candidates actually finished — a partial race (relay
        # died mid-window) must not satisfy tpu_capture's full-roster
        # re-race predicate
        "n_candidates": n_completed,
    }
    if not _TUNNEL_OK:
        # self-describing CPU fallback: the axon relay was down at
        # measurement time — this line is not a TPU regression signal
        line["tunnel_down"] = True
    print(json.dumps(line))


if __name__ == "__main__":
    if "--candidate" in sys.argv:
        name = sys.argv[sys.argv.index("--candidate") + 1]
        print(json.dumps({"candidate": name,
                          "seconds": _run_candidate(name)}))
    else:
        main()
