"""TPU capture child for the fused-variation pairs (bench.py --fusion).

Runs in its OWN process (the relay TPU is single-client: the
orchestrating parent must never attach — same discipline as bench.py's
race candidates). Three measurements, one JSON line each on stdout:

1. hardware parity gate: ``ops.kernels.fused_variation`` on the real
   core vs the fused XLA apply on identical masks — bit-equal, else a
   structured ``gate_failed`` line (a fast wrong kernel must never
   produce a committed row);
2. the variation-plane pair at the headline config (pop=100k):
   unfused composition vs ``fused='kernel'``, same scanned protocol;
3. the GP compaction pair (host round trip — a real PCIe/relay sync
   here — vs on-device prefix-sum).
"""

import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

POP, L, NGEN, REPS = 100_000, 100, 20, 3


def main() -> int:
    from deap_tpu import ops
    from deap_tpu.algorithms import evaluate_invalid, var_and
    from deap_tpu.core.fitness import FitnessSpec
    from deap_tpu.core.population import gather, init_population
    from deap_tpu.core.toolbox import Toolbox
    from deap_tpu.gp.loop import make_compaction_pipelines
    from deap_tpu.ops import variation as V
    from deap_tpu.ops.kernels import fused_variation

    if jax.default_backend() != "tpu":
        print(json.dumps({"gate_failed": "backend is not tpu"}))
        return 1
    kind = jax.devices()[0].device_kind

    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)

    # ---- 1. hardware parity gate (small, fast) ----
    n, l = 512, 96
    g = jax.random.bernoulli(jax.random.key(3), 0.5, (n, l))
    plan = V.resolve_plan(tb)
    cx_row, lo, hi, do_mut, mask, arg = V.var_and_masks(
        jax.random.key(4), n, l, 0.6, 0.4, plan, g.dtype)
    pos = V.pair_partner_positions(n)
    want = V.apply_variation(g, None, pos, cx_row, lo, hi, do_mut,
                             mask, arg, "flip")
    got = fused_variation(g, jnp.arange(n, dtype=jnp.int32), pos,
                          cx_row, lo, hi, do_mut, mask, None,
                          mut_kind="flip", block_i=256,
                          interpret=False)
    if not bool((got == want).all()):
        bad = int(jnp.sum(jnp.any(got != want, axis=-1)))
        print(json.dumps({"gate_failed":
                          f"kernel != xla apply on {bad} rows (hw)"}))
        return 1
    print(json.dumps({"hw_parity": True, "device_kind": kind}),
          flush=True)

    # ---- 2. variation-plane pair at pop=100k ----
    pop = init_population(jax.random.key(1), POP,
                          ops.bernoulli_genome(L), FitnessSpec((1.0,)))
    pop = evaluate_invalid(pop, tb.evaluate)

    def unfused_step(p, key):
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, p.wvalues, p.size)
        off = var_and(k_var, gather(p, idx), tb, 0.5, 0.2, fused=False)
        return evaluate_invalid(off, tb.evaluate), None

    def fused_step(p, key):
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, p.wvalues, p.size)
        off = var_and(k_var, p, tb, 0.5, 0.2, fused="kernel",
                      sel_idx=idx)
        return evaluate_invalid(off, tb.evaluate), None

    def mk(step):
        @jax.jit
        def run(key, p):
            p, _ = lax.scan(step, p, jax.random.split(key, NGEN))
            return p.wvalues[:, 0]
        return run

    run_u, run_f = mk(unfused_step), mk(fused_step)
    wu = run_u(jax.random.key(50), pop)
    wf = run_f(jax.random.key(50), pop)
    if not bool((wu == wf).all()):
        print(json.dumps({"gate_failed":
                          "fused scan diverged from unfused on hw"}))
        return 1

    def fetch(x):  # force completion via scalar fetch (bench.py note)
        return float(jnp.sum(x))

    rows = []
    t_u, t_f = [], []
    for r in range(REPS):
        t0 = time.perf_counter()
        fetch(run_u(jax.random.key(60 + r), pop))
        t_u.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        fetch(run_f(jax.random.key(60 + r), pop))
        t_f.append(time.perf_counter() - t0)
    for name, ts in (("unfused", t_u), ("fused", t_f)):
        ts = sorted(ts)
        rows.append({
            "metric": f"onemax_pop100k_varplane_{name}"
                      "_generations_per_sec",
            "value": round(NGEN / ts[len(ts) // 2], 3),
            "unit": "gens/sec", "backend": "tpu",
            "device_kind": kind, "pop": POP, "ngen": NGEN,
            "n_samples": len(ts),
            "best": round(NGEN / ts[0], 3),
        })
    rows.append({
        "metric": "onemax_pop100k_varplane_fused_speedup_x",
        "value": round(min(t_u) / min(t_f), 3), "unit": "x",
        "backend": "tpu", "device_kind": kind,
        "estimator": "min_of_reps", "bit_identical": True,
        "threshold_x": 1.2,
    })

    # ---- 3. GP compaction pair (host sync is real PCIe here) ----
    host_fn, dev_fn = make_compaction_pipelines(0.5, 0.1)
    n = POP
    (h, hc), (d, dc) = (host_fn(jax.random.key(70), n),
                        dev_fn(jax.random.key(70), n))
    if hc != dc or not all(bool((a == b).all()) for a, b in zip(h, d)):
        print(json.dumps({"gate_failed": "compaction parity (hw)"}))
        return 1
    for r in range(4):  # warm both shape classes
        host_fn(jax.random.fold_in(jax.random.key(8), r), n)
        dev_fn(jax.random.fold_in(jax.random.key(8), r), n)
    ROUNDS = 50
    ct_h, ct_d = [], []
    for _ in range(REPS):
        t0 = time.perf_counter()
        for r in range(ROUNDS):
            host_fn(jax.random.fold_in(jax.random.key(9), r), n)
        ct_h.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        for r in range(ROUNDS):
            dev_fn(jax.random.fold_in(jax.random.key(9), r), n)
        ct_d.append(time.perf_counter() - t0)
    for name, ts in (("host", ct_h), ("device", ct_d)):
        ts = sorted(ts)
        rows.append({
            "metric": f"gp_compaction_pop100k_{name}_rounds_per_sec",
            "value": round(ROUNDS / ts[len(ts) // 2], 2),
            "unit": "rounds/sec", "backend": "tpu",
            "device_kind": kind, "pop": n, "n_samples": len(ts),
            "best": round(ROUNDS / ts[0], 2),
        })
    rows.append({
        "metric": "gp_compaction_pop100k_device_speedup_x",
        "value": round(min(ct_h) / min(ct_d), 3), "unit": "x",
        "backend": "tpu", "device_kind": kind,
        "estimator": "min_of_reps", "bit_identical": True,
        "threshold_x": 1.2,
    })
    for row in rows:
        print(json.dumps(row), flush=True)
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # structured resolution for the parent
        print(json.dumps({"gate_failed": repr(e)[:400]}))
        sys.exit(1)
