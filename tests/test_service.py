"""Network service plane — RPC front end, drain, auth, wire parity.

The acceptance bar of ``deap_tpu/serving/service.py``: a job submitted
over a real loopback socket must return a result **bit-identical** to
the same job run through the :class:`Scheduler` in-process (the wire
codec transports raw array bytes, and the digest makes the comparison
one string equal); SIGTERM drains gracefully (in-flight segment
finishes, residents checkpoint tenant-stamped, ``service_drain``
journals) and a restarted service resumes every drained tenant
bit-exactly against an uninterrupted run. Plus the satellites: bearer
auth + per-token quotas (``auth_rejected`` journaling), the unified
``/metrics`` + ``/healthz`` port, the scheduler's
:class:`SchedulerBusyError` thread contract, the journal-kind doc
drift gate and the client's no-jax pin.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.serving import (
    EvolutionService,
    Job,
    Scheduler,
    SchedulerBusyError,
    ServiceClient,
    ServiceError,
)
from deap_tpu.serving.service import SERVICE_JOURNAL_KINDS
from deap_tpu.serving.wire import pack, result_digest, unpack
from deap_tpu.strategies import cma
from deap_tpu.telemetry import read_journal
from deap_tpu.telemetry.metrics import MetricsRegistry, serve_metrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _onemax_toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


_TB = _onemax_toolbox()
_STRAT = cma.Strategy(centroid=[2.0] * 4, sigma=0.4, lambda_=8)
_TBC = Toolbox()
_TBC.register("evaluate", lambda g: (g ** 2).sum(-1))
_TBC.register("generate", _STRAT.generate)
_TBC.register("update", _STRAT.update)


def _onemax_job(tid, params):
    seed = int(params.get("seed", 0))
    pop = init_population(jax.random.key(seed), 16,
                          ops.bernoulli_genome(12), FitnessSpec((1.0,)))
    return Job(tenant_id=tid, family="ea_simple", toolbox=_TB,
               key=jax.random.key(1000 + seed), init=pop,
               ngen=int(params.get("ngen", 6)),
               hyper={"cxpb": 0.5, "mutpb": 0.2}, program="onemax")


def _sphere_job(tid, params):
    seed = int(params.get("seed", 0))
    return Job(tenant_id=tid, family="ea_generate_update",
               toolbox=_TBC, key=jax.random.key(5000 + seed),
               init=_STRAT.initial_state(
                   sigma=float(params.get("sigma", 0.7))),
               ngen=int(params.get("ngen", 6)), spec=_STRAT.spec,
               program="sphere")


PROBLEMS = {"onemax": _onemax_job, "sphere": _sphere_job}


def _inprocess_digests(root, jobs):
    """The same jobs through the Scheduler directly — the bit-identity
    reference the service must match."""
    with Scheduler(str(root), max_lanes=2, segment_len=2) as sched:
        for j in jobs:
            sched.submit(j)
        results = sched.run()
    return {tid: result_digest(res) for tid, res in results.items()}


# ------------------------------------------------- wire codec ----

def test_wire_roundtrip_bit_exact():
    rng = np.random.default_rng(0)
    payload = {
        "f32": rng.standard_normal(7).astype(np.float32),
        "f64": np.array([np.nan, -np.inf, 1e-300]),
        "bools": np.array([True, False]),
        "nested": (np.arange(5, dtype=np.int8), "text", 3, None),
    }
    back = unpack(json.loads(json.dumps(pack(payload))))
    assert isinstance(back["nested"], tuple)
    for a, b in [(payload["f32"], back["f32"]),
                 (payload["f64"], back["f64"]),
                 (payload["bools"], back["bools"]),
                 (payload["nested"][0], back["nested"][0])]:
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()


def test_result_digest_separates_runs():
    j1 = _onemax_job("a", {"seed": 1})
    j2 = _onemax_job("a", {"seed": 2})
    assert result_digest((j1.init,)) != result_digest((j2.init,))
    assert result_digest((j1.init,)) == result_digest((j1.init,))


# ------------------------------------------------- e2e service ----

def test_service_e2e_bit_identical_to_inprocess(tmp_path):
    """Mixed-family jobs from two concurrent client threads through a
    real loopback socket: streamed per-segment results arrive, and
    every tenant's wire digest equals the same job run through the
    Scheduler in-process."""
    specs = [("onemax", {"seed": 3, "ngen": 6}, "ga0"),
             ("onemax", {"seed": 4, "ngen": 4}, "ga1"),
             ("sphere", {"seed": 1, "ngen": 6}, "cma0")]
    ref = _inprocess_digests(
        tmp_path / "ref",
        [PROBLEMS[p](tid, params) for p, params, tid in specs])

    got = {}
    stream_events = {}
    errors = []

    def client_thread(my_specs, do_stream):
        try:
            c = ServiceClient(svc.url)
            if do_stream:  # per-job routes + NDJSON streaming
                tids = [c.submit(p, params=params, tenant_id=tid)
                        for p, params, tid in my_specs]
                stream_events[tids[0]] = list(c.stream(tids[0]))
                for tid in tids:
                    res = c.result(tid, wait=True, timeout=120)
                    assert res["status"] == "finished", res
                    got[tid] = res["result"]["digest"]
            else:  # the batch routes: one round trip each way
                tids = c.submit_many(
                    [{"problem": p, "params": params,
                      "tenant_id": tid}
                     for p, params, tid in my_specs])
                assert tids == [tid for _, _, tid in my_specs]
                for tid, entry in c.results_many(
                        tids, wait=True, timeout=120).items():
                    assert entry["status"] == "finished", entry
                    got[tid] = entry["result"]["digest"]
        except Exception as e:  # surface in the main thread
            errors.append(e)

    with EvolutionService(str(tmp_path / "svc"), PROBLEMS,
                          max_lanes=2, segment_len=2,
                          metrics=MetricsRegistry()) as svc:
        t1 = threading.Thread(
            target=client_thread, args=(specs[:2], True))
        t2 = threading.Thread(
            target=client_thread, args=(specs[2:], False))
        t1.start(); t2.start()
        t1.join(timeout=300); t2.join(timeout=300)
    assert not errors, errors
    assert got == ref  # bit-identical across the socket

    evs = stream_events["ga0"]
    kinds = [e["event"] for e in evs]
    assert kinds[0] == "status" and kinds[-1] == "finished"
    segs = [e for e in evs if e["event"] == "segment"]
    assert segs and segs[-1]["gen"] == 6
    # per-segment results decode to this segment's logbook rows
    rec = ServiceClient.decode_records(segs[0])
    assert rec is not None and "nevals" in rec
    assert len(rec["nevals"]) == segs[0]["gen"] - segs[0]["gen_from"]

    rows = read_journal(str(tmp_path / "svc" / "journal.jsonl"))
    kinds = {r.get("kind") for r in rows}
    assert {"service_request", "job_submitted",
            "tenant_finished"} <= kinds


def test_service_sigterm_drain_restart_bit_exact(tmp_path):
    """SIGTERM mid-run: the in-flight segment finishes, the resident
    tenant checkpoints (tenant-stamped), ``service_drain`` journals,
    the stream ends with a ``drained`` event — and a restarted service
    over the same root resumes the tenant to a result bit-identical to
    an uninterrupted run."""
    NGEN = 12
    ref = _inprocess_digests(
        tmp_path / "ref", [_onemax_job("tA", {"seed": 3,
                                              "ngen": NGEN})])["tA"]

    def kill_after_first_segment(step):
        # deterministic mid-run preemption: one segment (gen=2 of 12)
        # completed, then a REAL SIGTERM; wait for the main-thread
        # handler to register the drain before releasing the driver,
        # so exactly one segment ran
        if step == 1:
            os.kill(os.getpid(), signal.SIGTERM)
            assert svc._drain_req.wait(30)

    root = str(tmp_path / "svc")
    svc = EvolutionService(root, PROBLEMS, max_lanes=2, segment_len=2,
                          metrics=MetricsRegistry(),
                          step_hook=kill_after_first_segment)
    ds = svc.install_signal_handlers()
    try:
        c = ServiceClient(svc.url)
        c.submit("onemax", params={"seed": 3, "ngen": NGEN},
                 tenant_id="tA")
        events = [ev["event"] for ev in c.stream("tA")]
        assert svc._drained.wait(60)
        assert events[-1] == "drained"
        assert "segment" in events
        res = c.result("tA", wait=False)
        assert res["status"] == "drained" and "result" not in res
        with pytest.raises(ServiceError) as ei:
            c.submit("onemax", params={"seed": 9})
        assert ei.value.code == 503  # draining refuses admissions
    finally:
        ds.uninstall()
        svc.close()

    rows = read_journal(os.path.join(root, "journal.jsonl"))
    drains = [r for r in rows if r.get("kind") == "service_drain"]
    assert len(drains) == 1 and drains[0]["checkpointed"] == ["tA"]
    from deap_tpu.support.checkpoint import Checkpointer
    ck = Checkpointer(os.path.join(root, "tenants", "tA", "ckpt"))
    assert ck.meta()["tenant_id"] == "tA"

    # restart over the same root; resubmitting the same job resumes
    with EvolutionService(root, PROBLEMS, max_lanes=2,
                          segment_len=2,
                          metrics=MetricsRegistry()) as svc2:
        c2 = ServiceClient(svc2.url)
        c2.submit("onemax", params={"seed": 3, "ngen": NGEN},
                  tenant_id="tA")
        res = c2.result("tA", wait=True, timeout=120)
    assert res["status"] == "finished"
    assert res["result"]["digest"] == ref
    rows2 = read_journal(os.path.join(root, "journal.jsonl"))
    kinds = [r.get("kind") for r in rows2]
    assert "tenant_checkpoint_found" in kinds
    assert "tenant_resumed" in kinds


def test_service_auth_quota_and_isolation(tmp_path):
    tokens = {"alice-key": {"tenant": "alice", "max_jobs": 1},
              "bob-key": {"tenant": "bob"}}
    with EvolutionService(str(tmp_path), PROBLEMS, tokens=tokens,
                          max_lanes=2, segment_len=2,
                          metrics=MetricsRegistry()) as svc:
        # missing / unknown tokens
        with pytest.raises(ServiceError) as ei:
            ServiceClient(svc.url).submit("onemax")
        assert ei.value.code == 401
        with pytest.raises(ServiceError) as ei:
            ServiceClient(svc.url, token="wrong").submit("onemax")
        assert ei.value.code == 403
        # /healthz and /metrics stay open (liveness + Prometheus)
        assert ServiceClient(svc.url).healthz()["status"] == "ok"
        ServiceClient(svc.url).metrics_text()

        alice = ServiceClient(svc.url, token="alice-key")
        bob = ServiceClient(svc.url, token="bob-key")
        tid = alice.submit("onemax", params={"seed": 1, "ngen": 8},
                           tenant_id="alice-job")
        # quota: alice has max_jobs=1 in flight
        with pytest.raises(ServiceError) as ei:
            alice.submit("onemax", params={"seed": 2})
        assert ei.value.code == 429
        # isolation: bob cannot read alice's job
        with pytest.raises(ServiceError) as ei:
            bob.status("alice-job")
        assert ei.value.code == 403
        assert alice.result(tid, wait=True,
                            timeout=120)["status"] == "finished"
        # quota freed after completion
        tid2 = alice.submit("onemax", params={"seed": 2, "ngen": 4})
        assert tid2.startswith("alice-")
        alice.result(tid2, wait=True, timeout=120)
    rows = read_journal(str(tmp_path / "journal.jsonl"))
    reasons = {r.get("reason") for r in rows
               if r.get("kind") == "auth_rejected"}
    assert {"missing_token", "unknown_token", "quota",
            "foreign_tenant"} <= reasons


def test_service_unified_metrics_port(tmp_path):
    """Satellite: the service port serves the scheduler's registry at
    /metrics (plus /healthz liveness) — and serve_metrics() on the
    same registry still works standalone, returning identical
    families."""
    reg = MetricsRegistry()
    with EvolutionService(str(tmp_path), PROBLEMS, max_lanes=2,
                          segment_len=2, metrics=reg) as svc:
        c = ServiceClient(svc.url)
        tid = c.submit("onemax", params={"seed": 1, "ngen": 4})
        c.result(tid, wait=True, timeout=120)
        text = c.metrics_text()
        assert "deap_serving_queue_depth" in text
        assert "deap_serving_tenants_finished_total" in text
        assert c.healthz()["status"] == "ok"
        with serve_metrics(reg) as standalone:
            import urllib.request
            body = urllib.request.urlopen(standalone.url,
                                          timeout=10).read().decode()
        def families(t):
            return {line.split()[2] for line in t.splitlines()
                    if line.startswith("# TYPE")}
        assert families(body) == families(text)


# ------------------------------------------ scheduler thread contract ----

def test_scheduler_busy_error_concurrent_entry(tmp_path):
    """A second thread entering the scheduler mid-call gets a loud
    SchedulerBusyError instead of corrupting bucket state."""
    sched = Scheduler(str(tmp_path), max_lanes=2)
    caught = []

    def intruder():
        try:
            sched.submit(_onemax_job("x", {}))
        except SchedulerBusyError as e:
            caught.append(e)

    with sched._exclusive("step"):  # the driver is "inside a call"
        t = threading.Thread(target=intruder)
        t.start()
        t.join(timeout=30)
    assert len(caught) == 1
    assert "single-threaded by contract" in str(caught[0])
    # the guard is reentrant for its owner: run() -> step() nests
    sched.submit(_onemax_job("y", {"ngen": 2}))
    sched.run()
    sched.close()


def test_scheduler_busy_error_non_driver_thread(tmp_path):
    """After bind_driver, mutating calls from any other thread are
    rejected outright — the service's queue-handoff contract."""
    sched = Scheduler(str(tmp_path), max_lanes=2)
    done = threading.Event()

    def driver():
        sched.bind_driver()
        done.set()

    t = threading.Thread(target=driver, name="drv")
    t.start(); t.join(timeout=30)
    assert done.is_set()
    with pytest.raises(SchedulerBusyError, match="bound to driver"):
        sched.submit(_onemax_job("z", {}))
    with pytest.raises(SchedulerBusyError):
        sched.step()
    sched.close()


# ----------------------------------------------------- drift gates ----

def test_service_journal_kinds_documented():
    """Every service-plane journal kind appears in the telemetry.md
    kind table — same drift contract as the probe catalogue."""
    doc = os.path.join(REPO, "docs", "advanced", "telemetry.md")
    with open(doc) as fh:
        text = fh.read()
    assert SERVICE_JOURNAL_KINDS  # the gate must gate something
    for kind in SERVICE_JOURNAL_KINDS:
        assert f"`{kind}`" in text, (
            f"journal kind {kind!r} undocumented in "
            "docs/advanced/telemetry.md")


def test_client_imports_without_jax():
    """A submit/scrape box must never initialise an XLA backend: the
    stdlib client (and the wire codec it pulls in) load standalone
    with jax never entering sys.modules."""
    client_py = os.path.join(REPO, "deap_tpu", "serving", "client.py")
    code = (
        "import importlib.util, sys\n"
        f"spec = importlib.util.spec_from_file_location("
        f"'client_standalone', {client_py!r})\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(mod)\n"
        "c = mod.ServiceClient('http://127.0.0.1:1')\n"
        "payload = mod.wire.pack({'a': __import__('numpy')"
        ".arange(3)})\n"
        "assert mod.wire.unpack(payload)['a'].tolist() == [0, 1, 2]\n"
        "assert 'jax' not in sys.modules, 'client pulled in jax'\n"
        "assert 'deap_tpu' not in sys.modules\n"
        "print('NOJAX_OK')\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "NOJAX_OK" in out.stdout


# ------------------------------------- satellite: GP tenants ----
# The service plane has only ever carried scan-family jobs; these pin
# the two host-visible service behaviours — the idleness/spill
# actuator and admission-WAL replay — with a batched-GP tenant.

from deap_tpu.gp.pset import math_set as _math_set
from deap_tpu.gp.tree import make_generator as _make_generator
from deap_tpu.serving import GpJobSpec

_GP_PSET = _math_set(n_args=1)
_GP_ML = 24
_GP_X = np.linspace(-1, 1, 12).reshape(12, 1).astype(np.float32)
_GP_Y = (_GP_X[:, 0] ** 2 + _GP_X[:, 0]).astype(np.float32)
_GP_SPEC = GpJobSpec(pset=_GP_PSET, max_len=_GP_ML, X=_GP_X, y=_GP_Y)


def _gp_founders(seed, n=16):
    gen = _make_generator(_GP_PSET, _GP_ML, 1, 3, "full")
    return jax.vmap(gen)(jax.random.split(jax.random.key(seed), n))


def _gp_job(tid, params):
    seed = int(params.get("seed", 0))
    return Job(tenant_id=tid, family="gp", toolbox=None,
               key=jax.random.key(3000 + seed),
               init=_gp_founders(seed),
               ngen=int(params.get("ngen", 8)),
               hyper={"cxpb": 0.5, "mutpb": 0.2}, spec=_GP_SPEC,
               program="gp_symbreg")


GP_PROBLEMS = {**PROBLEMS, "gp_symbreg": _gp_job}


def test_gp_tenant_idleness_and_spill(tmp_path):
    """``note_interaction()`` drives a GP tenant's idleness clock and
    ``request_spill`` swaps it out (checkpoint → queue tail) at the
    next boundary — then the run still finishes bit-identical to an
    unspilled one."""
    ref = _inprocess_digests(tmp_path / "ref",
                             [_gp_job("g0", {"seed": 4, "ngen": 10})])
    sched = Scheduler(str(tmp_path / "run"), max_lanes=2,
                      segment_len=2)
    sched.submit(_gp_job("g0", {"seed": 4, "ngen": 10}))
    sched.step()
    snap = sched.slo_snapshot()
    row = next(iter(snap.values()))
    assert row["family"] == "gp"
    tid, segments, gens_idle = row["idle"][0]
    assert tid == "g0" and gens_idle == 2  # 2 gens, never polled
    sched.tenants["g0"].note_interaction()
    assert next(iter(sched.slo_snapshot().values()))["idle"][0][2] == 0

    # spill at the next boundary: evicted + checkpointed, then resumes
    sched.request_spill("g0")
    sched.step()
    assert sched.tenants["g0"].has_checkpoint
    results = sched.run()
    sched.close()
    assert result_digest(results["g0"]) == ref["g0"]
    rows = read_journal(os.path.join(str(tmp_path / "run"),
                                     "journal.jsonl"))
    assert any(r.get("kind") == "tenant_evicted"
               and r.get("reason") == "spill" for r in rows)


def test_gp_tenant_wal_replay_bit_exact(tmp_path):
    """Admission-WAL replay with a GP tenant: drain mid-run, restart
    the service over the same root WITHOUT resubmitting — the WAL
    readmits the job, the checkpoint resumes it, and the result is
    bit-identical to an uninterrupted in-process run."""
    NGEN = 10
    ref = _inprocess_digests(
        tmp_path / "ref",
        [_gp_job("gA", {"seed": 6, "ngen": NGEN})])["gA"]

    def kill_after_first_segment(step):
        if step == 1:
            os.kill(os.getpid(), signal.SIGTERM)
            assert svc._drain_req.wait(30)

    root = str(tmp_path / "svc")
    svc = EvolutionService(root, GP_PROBLEMS, max_lanes=2,
                           segment_len=2,
                           metrics=MetricsRegistry(),
                           step_hook=kill_after_first_segment)
    ds = svc.install_signal_handlers()
    try:
        c = ServiceClient(svc.url)
        c.submit("gp_symbreg", params={"seed": 6, "ngen": NGEN},
                 tenant_id="gA")
        assert svc._drained.wait(120)
        res = c.result("gA", wait=False)
        assert res["status"] == "drained" and "result" not in res
    finally:
        ds.uninstall()
        svc.close()

    # restart, NO resubmission: the WAL replay is the only admission
    with EvolutionService(root, GP_PROBLEMS, max_lanes=2,
                          segment_len=2,
                          metrics=MetricsRegistry()) as svc2:
        c2 = ServiceClient(svc2.url)
        res = c2.result("gA", wait=True, timeout=300)
    assert res["status"] == "finished"
    assert res["result"]["digest"] == ref
    rows = read_journal(os.path.join(root, "journal.jsonl"))
    kinds = [r.get("kind") for r in rows]
    assert "wal_replay" in kinds
    assert "tenant_resumed" in kinds


def test_client_abandonment_leaves_service_healthy(tmp_path):
    """The loadgen's impatient-client model (ISSUE 17): a client whose
    ``abandon_after_s`` fires mid-long-poll gets a local
    :class:`ClientAbandoned` — the service never sees an error, the
    tenant keeps running, and a patient client later collects the
    bit-identical result."""
    from deap_tpu.serving import ClientAbandoned

    ref = _inprocess_digests(tmp_path / "ref",
                             [_onemax_job("tA", {"seed": 2,
                                                 "ngen": 12})])["tA"]
    with EvolutionService(str(tmp_path / "svc"), PROBLEMS,
                          max_lanes=2, segment_len=2,
                          metrics=MetricsRegistry()) as svc:
        impatient = ServiceClient(svc.url, abandon_after_s=0.2)
        impatient.submit("onemax", params={"seed": 2, "ngen": 12},
                         tenant_id="tA")
        with pytest.raises(ClientAbandoned):
            impatient.result("tA", wait=True, timeout=120)
        # nobody polls an abandoned tenant: its idleness clock grows
        # with every generation, which is exactly what makes it the
        # autoscaler's preferred spill victim (attribute reads only —
        # a result poll would count as an interaction and reset it)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            t = svc.scheduler.tenants.get("tA")
            if t is not None and t.gen >= 4:
                break
            time.sleep(0.05)
        assert t is not None and t.gens_since_interaction > 0
        # the abandonment is local: the service stays responsive and
        # the abandoned tenant runs to completion for anyone who asks
        patient = ServiceClient(svc.url)
        assert patient.healthz()["status"] == "ok"
        res = patient.result("tA", wait=True, timeout=120)
        assert res["status"] == "finished"
        assert res["result"]["digest"] == ref
        # non-wait requests never arm the abandon timer
        impatient2 = ServiceClient(svc.url, abandon_after_s=0.01)
        assert impatient2.result("tA", wait=False)["status"] \
            == "finished"


def test_slo_rows_carry_load_counters(tmp_path):
    """Every per-boundary ``slo`` journal row folds in the cumulative
    arrival / shed / deadline-miss counters (ISSUE 17) so the windowed
    SLO curves compute from the journal alone — and
    ``slo_snapshot()`` exposes the same counters live."""
    with EvolutionService(str(tmp_path), PROBLEMS, max_lanes=2,
                          segment_len=2, max_pending=1,
                          metrics=MetricsRegistry()) as svc:
        c = ServiceClient(svc.url)
        c.submit("onemax", params={"seed": 1, "ngen": 8},
                 tenant_id="tA")
        # past max_pending: shed with 429 + Retry-After, counted
        with pytest.raises(ServiceError) as ei:
            c.submit("onemax", params={"seed": 2}, tenant_id="tB")
        assert ei.value.code == 429
        assert ei.value.retry_after is not None
        c.result("tA", wait=True, timeout=120)
        counts = svc.scheduler.load_counts()  # any-thread safe
        assert counts["sheds"] == 1
        assert sum(counts["arrivals"].values()) == 1
        jpath = svc.journal.path
    rows = read_journal(jpath)
    slo = [r for r in rows if r.get("kind") == "slo"]
    assert slo, "no slo rows journaled"
    for r in slo:
        assert "arrivals" in r and "sheds" in r \
            and "deadline_misses" in r
    # cumulative: the last row carries the final shed count
    assert slo[-1]["sheds"] == 1
    assert any(r.get("kind") == "load_shed" for r in rows)
    # slo_snapshot() folds the same counters in (driverless scheduler
    # here: with a service attached it must go through the driver)
    with Scheduler(str(tmp_path / "snap"), max_lanes=2,
                   segment_len=2) as s:
        s.submit(_onemax_job("tS", {"seed": 1, "ngen": 2}))
        s.note_shed(3)
        s.note_deadline_miss()
        snap = s.slo_snapshot()
        assert snap and all(
            b["sheds"] == 3 and b["deadline_misses"] == 1
            and b["arrivals"] == 1 for b in snap.values())


def test_injected_429_counts_as_shed(tmp_path):
    """:class:`Reject429` — the loadgen's deterministic retry-storm
    source — answers a submit with 429 + ``Retry-After`` *after* the
    server-side effects stand: journaled ``load_shed`` with
    ``reason='injected_429'``, counted by ``note_shed``, and the job
    (already admitted) still finishes."""
    from deap_tpu.resilience.faultinject import FaultPlan, Reject429

    plan = FaultPlan([Reject429("/v1/jobs", times=1,
                                retry_after_s=2.0)])
    with EvolutionService(str(tmp_path), PROBLEMS, max_lanes=2,
                          segment_len=2, metrics=MetricsRegistry(),
                          fault_plan=plan) as svc:
        c = ServiceClient(svc.url)
        with pytest.raises(ServiceError) as ei:
            c.submit("onemax", params={"seed": 5, "ngen": 6},
                     tenant_id="tA", idempotency_key="k1")
        assert ei.value.code == 429
        assert ei.value.retry_after == 2.0
        # single-shot: the storm hits exactly when scheduled
        res = c.result("tA", wait=True, timeout=120)
        assert res["status"] == "finished"
        assert svc.scheduler.load_counts()["sheds"] == 1
        jpath = svc.journal.path
    rows = read_journal(jpath)
    shed = [r for r in rows if r.get("kind") == "load_shed"]
    assert len(shed) == 1 and shed[0]["reason"] == "injected_429"
    slo = [r for r in rows if r.get("kind") == "slo"]
    assert slo and slo[-1]["sheds"] == 1
