"""Multi-swarm PSO, speciation PSO and BIPOP-CMA-ES tests (reference:
examples/pso/multiswarm.py, examples/pso/speciation.py,
examples/es/cma_bipop.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import strategies
from deap_tpu.benchmarks import movingpeaks as mp


def two_peaks(x):
    """Static two-peak landscape: maxima 10 at -3·1 and 8 at +3·1."""
    d1 = jnp.linalg.norm(x - (-3.0), axis=-1)
    d2 = jnp.linalg.norm(x - 3.0, axis=-1)
    return jnp.maximum(10.0 - d1, 8.0 - d2)


def test_multiswarm_finds_peak_static():
    ms = strategies.MultiSwarmPSO(two_peaks, pmin=-6.0, pmax=6.0,
                                  rcloud=0.5)
    s = ms.init(jax.random.key(0), nswarms=3, nparticles=8, dim=2,
                capacity=8)
    step = jax.jit(ms.step)
    for g in range(40):
        s = step(jax.random.key(g), s)
    _, f = ms.best(s)
    assert float(f) > 9.0
    assert int(s.nevals) > 0


def test_multiswarm_anti_convergence_spawns():
    """Once all swarms converge, a new swarm must activate (the
    anti-convergence rule, multiswarm.py:163-165)."""
    ms = strategies.MultiSwarmPSO(two_peaks, pmin=-6.0, pmax=6.0)
    s = ms.init(jax.random.key(1), nswarms=1, nparticles=4, dim=2,
                capacity=4)
    # collapse the single swarm onto one point → diameter 0 → converged
    s = s.replace(x=jnp.zeros_like(s.x))
    s2 = ms.step(jax.random.key(2), s)
    assert int(s2.active.sum()) == 2


def test_multiswarm_exclusion_reinits_worse():
    """Two swarms whose bests are within rexcl: the worse one loses its
    best (multiswarm.py:203-215)."""
    ms = strategies.MultiSwarmPSO(two_peaks, pmin=-6.0, pmax=6.0)
    s = ms.init(jax.random.key(3), nswarms=2, nparticles=4, dim=2,
                capacity=4)
    # both swarms sit on the same good peak, swarm 0 slightly better
    near = jnp.full_like(s.x[0], -3.0)
    x = s.x.at[0].set(near).at[1].set(near + 0.01)
    s = s.replace(x=x)
    s = ms.step(jax.random.key(4), s)          # establish bests
    s2 = ms.step(jax.random.key(5), s)         # exclusion trips
    f = np.asarray(s2.sbest_f[:2])
    assert np.isinf(f).any() and not np.isinf(f).all()


def test_multiswarm_on_movingpeaks_change_recovery():
    """After the landscape moves, change detection must convert the
    converged swarm to a quantum cloud (bests reset) instead of staying
    stuck on the stale optimum."""
    cfg = mp.MovingPeaksConfig(dim=2, **{
        k: v for k, v in mp.SCENARIO_1.items()
        if k not in ("pfunc", "bfunc")})
    state = mp.mp_init(jax.random.key(10), cfg)

    def make_eval(st):
        return lambda x: mp.mp_evaluate(cfg, st, x)[1][:, 0]

    ms = strategies.MultiSwarmPSO(make_eval(state), pmin=cfg.min_coord,
                                  pmax=cfg.max_coord, rcloud=0.5)
    s = ms.init(jax.random.key(11), nswarms=3, nparticles=6, dim=2,
                capacity=8)
    for g in range(15):
        s = ms.step(jax.random.key(20 + g), s)
    before = float(ms.best(s)[1])
    assert np.isfinite(before)
    # move the peaks, swap the closure, step again
    state2 = mp.change_peaks(cfg, state)
    ms.evaluate = make_eval(state2)
    s = ms.step(jax.random.key(40), s)
    s = ms.step(jax.random.key(41), s)
    assert np.isfinite(float(ms.best(s)[1]))


def test_species_seeds_structure():
    """Two tight clusters → exactly two seeds; every particle joins the
    seed of its own cluster (speciation.py:133-146)."""
    kx = jax.random.key(6)
    a = jax.random.normal(kx, (10, 2)) * 0.1 + jnp.asarray([3.0, 3.0])
    b = jax.random.normal(jax.random.key(7), (10, 2)) * 0.1 - 3.0
    x = jnp.concatenate([a, b])
    f = jnp.arange(20, dtype=jnp.float32)
    is_seed, species = strategies.species_seeds(x, f, rs=1.0)
    assert int(is_seed.sum()) == 2
    sp = np.asarray(species)
    assert len(set(sp[:10])) == 1 and len(set(sp[10:])) == 1
    assert sp[0] != sp[10]
    # each seed is its own species
    for i in np.flatnonzero(np.asarray(is_seed)):
        assert sp[i] == i


def test_speciation_pso_tracks_both_peaks():
    sp = strategies.SpeciationPSO(two_peaks, pmin=-6.0, pmax=6.0, rs=3.0,
                                  pmax_size=10)
    s = sp.init(jax.random.key(8), n=60, dim=2)
    step = jax.jit(sp.step)
    for g in range(30):
        s = step(jax.random.key(100 + g), s)
    # global best near 10; and some particle near the second peak too
    assert float(s.pbest_f.max()) > 9.0
    d2 = np.linalg.norm(np.asarray(s.pbest_x) - 3.0, axis=-1)
    assert d2.min() < 1.5


def test_bipop_cmaes_sphere():
    """BIPOP on sphere n=5 must reach < 1e-8 within few restarts (the
    CMA quality gate of test_algorithms.py:53-66 under the restart
    harness) and must exercise both regimes' bookkeeping."""
    def sphere(x):
        return jnp.sum(x ** 2, axis=-1)

    best_x, best_f, logbooks = strategies.bipop_cmaes(
        jax.random.key(12), sphere, dim=5, sigma0=2.0, nrestarts=2)
    assert best_f < 1e-8
    assert len(logbooks) >= 2
    cols = logbooks[0][0]
    assert {"gen", "evals", "restart", "regime", "min"} <= set(cols)
