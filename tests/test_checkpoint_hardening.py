"""Crash-consistent checkpoint format + Checkpointer robustness.

The hardened format (per-leaf CRC32, format version, fsync-before-
rename) must detect every byte-level corruption instead of restoring
silently-wrong state; the Checkpointer must fall back past corrupt
files to the newest valid step, never rotate away the last verified-
good snapshot, and survive its directory being removed under a live
run. Round-trip coverage spans every state family the resilience
driver snapshots: strategy states (CMA / (1+λ) / MO-CMA), GP
concrete-genome populations with depth arrays, island-sharded pytrees,
and Meter/probe carries.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import ops
from deap_tpu.algorithms import evaluate_invalid
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.resilience.faultinject import corrupt_file
from deap_tpu.support import (
    CheckpointCorruptError,
    Checkpointer,
    checkpoint_meta,
    restore_state,
    save_state,
    verify_checkpoint,
)


def _assert_tree_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ------------------------------------------------------- PRNG impl fix ----

@pytest.mark.parametrize("impl", ["threefry2x32", "rbg"])
def test_prng_key_impl_roundtrips_both_impls(tmp_path, impl):
    """The impl name is stored canonically at pack time (no repr
    parsing) and must round-trip for every typed-key impl."""
    key = jax.random.key(123, impl=impl)
    path = str(tmp_path / "k.pkl")
    save_state(path, {"key": key})
    out = restore_state(path)["key"]
    assert jnp.issubdtype(out.dtype, jax.dtypes.prng_key)
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(out)),
        np.asarray(jax.random.key_data(key)))
    np.testing.assert_array_equal(
        np.asarray(jax.random.uniform(out, (4,))),
        np.asarray(jax.random.uniform(key, (4,))))


def test_legacy_v1_payload_still_restores(tmp_path):
    """Files written by the pre-CRC format (plain {leaves, treedef})
    keep restoring — old runs must stay resumable."""
    import pickle

    key = jax.random.key(7)
    leaves, treedef = jax.tree_util.tree_flatten(
        {"a": jnp.arange(5), "n": 3})
    payload = {"leaves": [np.asarray(l) if isinstance(l, jax.Array)
                          else l for l in leaves],
               "treedef": treedef}
    path = str(tmp_path / "v1.pkl")
    with open(path, "wb") as f:
        pickle.dump(payload, f)
    out = restore_state(path)
    assert out["n"] == 3
    np.testing.assert_array_equal(np.asarray(out["a"]), np.arange(5))
    del key


# --------------------------------------------------- corruption paths ----

def test_crc_detects_flipped_bytes(tmp_path):
    path = str(tmp_path / "s.pkl")
    save_state(path, {"x": jnp.arange(4096, dtype=jnp.float32)})
    verify_checkpoint(path)  # pristine file verifies
    corrupt_file(path, mode="flip")
    with pytest.raises(CheckpointCorruptError):
        restore_state(path)
    with pytest.raises(CheckpointCorruptError):
        verify_checkpoint(path)


def test_truncated_file_detected(tmp_path):
    path = str(tmp_path / "s.pkl")
    save_state(path, {"x": jnp.arange(4096, dtype=jnp.int32)})
    corrupt_file(path, mode="truncate", offset=-128)
    with pytest.raises(CheckpointCorruptError):
        restore_state(path)


def test_restore_falls_back_to_newest_valid_step(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "c"), keep=4)
    for s in range(4):
        ckpt.save(s, {"s": jnp.asarray(s)})
    # corrupt the two newest files; restore must fall back to step 1
    corrupt_file(ckpt._path(3), mode="flip")
    corrupt_file(ckpt._path(2), mode="truncate", offset=-64)
    state = ckpt.restore()
    assert int(state["s"]) == 1
    step, state2 = ckpt.restore_latest()
    assert step == 1 and int(state2["s"]) == 1
    # an explicit step never falls back — it raises
    with pytest.raises(CheckpointCorruptError):
        ckpt.restore(3)


def test_all_corrupt_raises(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "c"), keep=3)
    ckpt.save(0, {"s": 0})
    corrupt_file(ckpt._path(0), mode="flip")
    with pytest.raises(CheckpointCorruptError):
        ckpt.restore()


def test_restore_latest_verifies_each_file_once(tmp_path, monkeypatch):
    """ISSUE 18: the tenant-filtered restore walk used to CRC-sweep a
    file in checkpoint_meta() and then AGAIN in restore_state() —
    restore_latest must load + verify each candidate exactly once."""
    from deap_tpu.support import checkpoint as cp

    ckpt = Checkpointer(str(tmp_path / "c"), keep=4)
    for s in range(3):
        ckpt.save(s, {"s": jnp.asarray(s)}, meta={"tenant_id": "t1"})
    calls: list = []
    real = cp._verify_payload

    def counting(path, payload):
        calls.append(path)
        return real(path, payload)

    monkeypatch.setattr(cp, "_verify_payload", counting)
    step, state = ckpt.restore_latest(tenant_id="t1")
    assert step == 2 and int(state["s"]) == 2
    # one verification total: the newest file passed, walk stopped
    assert calls == [ckpt._path(2)]

    # a corrupt newest file is verified AT MOST once (a flip landing
    # in pickle structure fails the load before CRC verification even
    # starts), skipped, and the walk verifies the next file once —
    # never the same path twice
    calls.clear()
    corrupt_file(ckpt._path(2), mode="flip")
    step, _ = ckpt.restore_latest(tenant_id="t1")
    assert step == 1
    assert calls in ([ckpt._path(2), ckpt._path(1)],
                     [ckpt._path(1)])


def test_save_without_fsync_round_trips(tmp_path):
    """fsync=False (the per-boundary serving mode) keeps the atomic
    rename and the CRC format — only the two fsync syscalls go."""
    ckpt = Checkpointer(str(tmp_path / "c"), keep=2, fsync=False)
    state = {"x": jnp.arange(64, dtype=jnp.float32),
             "key": jax.random.key(5)}
    ckpt.save(0, state, meta={"tenant_id": "t1"})
    verify_checkpoint(ckpt._path(0))  # full per-leaf CRC sweep passes
    step, got = ckpt.restore_latest(tenant_id="t1")
    assert step == 0
    _assert_tree_equal(
        {"x": state["x"],
         "key": jax.random.key_data(state["key"])},
        {"x": got["x"], "key": jax.random.key_data(got["key"])})


def test_post_save_verify_does_not_reload_payload(tmp_path,
                                                  monkeypatch):
    """ISSUE 18: Checkpointer.save's post-write check is a raw
    read-back CRC compare — it must not re-unpickle the file (the old
    verify_checkpoint() round cost ~1.2s/run at serving frequency)."""
    from deap_tpu.support import checkpoint as cp

    ckpt = Checkpointer(str(tmp_path / "c"), keep=2)
    loads: list = []
    real = cp._load_payload

    def counting(path):
        loads.append(path)
        return real(path)

    monkeypatch.setattr(cp, "_load_payload", counting)
    ckpt.save(0, {"s": jnp.arange(16)})
    assert loads == []  # no unpickle on the save path
    # ... while a corrupted write is still caught (read-back compare)
    real_save = cp.save_state

    def torn_save(path, state, meta=None, **kw):
        crc = real_save(path, state, meta=meta, **kw)
        corrupt_file(path, mode="truncate", offset=-32)
        return crc

    monkeypatch.setattr(cp, "save_state", torn_save)
    ckpt.save(1, {"s": jnp.arange(16)})
    monkeypatch.undo()
    assert 1 not in ckpt._verified


def test_rotation_never_deletes_last_verified_good(tmp_path,
                                                   monkeypatch):
    """A save whose own post-write verification fails must rotate
    nothing: deleting by count alone could remove the only good
    snapshot."""
    import deap_tpu.support.checkpoint as cp

    ckpt = Checkpointer(str(tmp_path / "c"), keep=1)
    ckpt.save(0, {"s": 0})
    assert ckpt.steps() == [0]

    real_save = cp.save_state

    def broken_save(path, state, meta=None, **kw):
        crc = real_save(path, state, meta=meta, **kw)
        corrupt_file(path, mode="flip")  # disk fault on the new file
        return crc

    monkeypatch.setattr(cp, "save_state", broken_save)
    ckpt.save(1, {"s": 1})
    monkeypatch.undo()
    # keep=1 would normally leave only step 1 — but step 1 is bad, so
    # step 0 (the last verified-good checkpoint) must survive
    assert 0 in ckpt.steps()
    assert int(ckpt.restore()["s"]) == 0
    # a later healthy save rotates normally again
    ckpt.save(2, {"s": 2})
    assert int(ckpt.restore()["s"]) == 2


def test_steps_empty_when_directory_removed(tmp_path):
    """Directory removed out from under a live run: steps()/
    latest_step() degrade to empty, restore() raises a clear error."""
    import shutil

    d = str(tmp_path / "gone")
    ckpt = Checkpointer(d, keep=2)
    ckpt.save(0, {"s": 0})
    shutil.rmtree(d)
    assert ckpt.steps() == []
    assert ckpt.latest_step() is None
    assert ckpt.restore_latest() is None
    with pytest.raises(FileNotFoundError, match="gone"):
        ckpt.restore()
    with pytest.raises(FileNotFoundError, match="step 0"):
        ckpt.restore(0)


def test_meta_roundtrip_without_state(tmp_path):
    path = str(tmp_path / "m.pkl")
    save_state(path, {"x": jnp.zeros(8)},
               meta={"run_id": "abc123", "step": 7})
    meta = checkpoint_meta(path)
    assert meta["run_id"] == "abc123" and meta["step"] == 7
    # every save stamps its writer — the rolling-upgrade compat gate's
    # decision input (and the reason meta is no longer caller-only)
    assert meta["deap_tpu_version"]
    assert meta["checkpoint_format"] >= 3
    ckpt = Checkpointer(str(tmp_path / "c"))
    ckpt.save(3, {"x": 1}, meta={"run_id": "zzz"})
    assert ckpt.meta()["run_id"] == "zzz"


def test_checkpoint_event_broadcast(tmp_path):
    """save_state surfaces a ``checkpoint`` event in any open journal."""
    from deap_tpu.telemetry import RunJournal, read_journal

    jpath = str(tmp_path / "j.jsonl")
    with RunJournal(jpath) as j:
        save_state(str(tmp_path / "s.pkl"), {"x": 1})
        del j
    kinds = [r["kind"] for r in read_journal(jpath)]
    assert "checkpoint" in kinds


# ------------------------------- version stamps + compat gate (PR 20) ----

def test_newer_format_version_refused_by_name(tmp_path):
    """A file carrying format_version > this build's is refused with
    CheckpointFormatError (a CheckpointCorruptError subclass), not an
    arbitrary unpickle failure — the old-code-meets-new-file half of a
    rolling upgrade."""
    import pickle

    from deap_tpu.support import CheckpointFormatError
    from deap_tpu.support.checkpoint import FORMAT_VERSION

    path = str(tmp_path / "future.pkl")
    save_state(path, {"x": jnp.arange(4)})
    with open(path, "rb") as f:
        payload = pickle.load(f)
    payload["format_version"] = FORMAT_VERSION + 1
    with open(path, "wb") as f:
        pickle.dump(payload, f)
    with pytest.raises(CheckpointFormatError):
        restore_state(path)
    with pytest.raises(CheckpointCorruptError):   # subclass contract:
        restore_state(path)                       # old callers keep
    #                                               catching it


def test_cross_version_restore_gated(tmp_path, monkeypatch):
    """A checkpoint stamped by another deap_tpu version refuses to
    restore until the compat gate is explicitly opened; the gated
    restore journals a ``compat_restore`` row, and meta reads stay
    exempt (you can always inspect what you cannot restore)."""
    from deap_tpu.support import (CheckpointFormatError,
                                  allow_compat_restore)
    from deap_tpu.telemetry import RunJournal, read_journal

    path = str(tmp_path / "old.pkl")
    monkeypatch.setenv("DEAP_TPU_VERSION_OVERRIDE", "0.0.9+old")
    save_state(path, {"x": jnp.arange(8)}, meta={"tenant_id": "t-1"})
    monkeypatch.setenv("DEAP_TPU_VERSION_OVERRIDE", "0.1.1+new")

    with pytest.raises(CheckpointFormatError):
        restore_state(path)
    assert checkpoint_meta(path)["deap_tpu_version"] == "0.0.9+old"
    verify_checkpoint(path)   # integrity != restorability

    jpath = str(tmp_path / "j.jsonl")
    with RunJournal(jpath):
        with allow_compat_restore():
            out = restore_state(path)
    np.testing.assert_array_equal(np.asarray(out["x"]), np.arange(8))
    rows = [r for r in read_journal(jpath)
            if r["kind"] == "compat_restore"]
    assert rows and rows[0]["written_by"] == "0.0.9+old"
    assert rows[0]["running"] == "0.1.1+new"
    assert rows[0]["tenant_id"] == "t-1"
    # the gate snapped shut on context exit
    with pytest.raises(CheckpointFormatError):
        restore_state(path)


def test_same_version_restore_needs_no_gate(tmp_path):
    path = str(tmp_path / "same.pkl")
    save_state(path, {"x": jnp.arange(3)})
    out = restore_state(path)   # no gate, no error, no journal row
    np.testing.assert_array_equal(np.asarray(out["x"]), np.arange(3))


# ------------------------------------------- state-family round trips ----

def test_roundtrip_cma_state(tmp_path):
    from deap_tpu.strategies import cma

    strat = cma.Strategy(centroid=[0.5] * 8, sigma=0.3)
    state = strat.initial_state()
    # advance once so the state is not all-zeros
    genomes = strat.generate(jax.random.key(0), state)
    values = -jnp.sum(genomes ** 2, axis=-1, keepdims=True)
    state = strat.update(state, genomes, values)
    path = str(tmp_path / "cma.pkl")
    save_state(path, state)
    _assert_tree_equal(state, restore_state(path))


def test_roundtrip_one_plus_lambda_state(tmp_path):
    from deap_tpu.strategies import cma

    strat = cma.StrategyOnePlusLambda(
        parent=jnp.zeros(6), parent_fitness=[1.0], sigma=0.4, lambda_=8)
    state = strat.initial_state()
    path = str(tmp_path / "opl.pkl")
    save_state(path, state)
    _assert_tree_equal(state, restore_state(path))


def test_roundtrip_mo_cma_state(tmp_path):
    from deap_tpu.strategies import cma

    pop = jax.random.uniform(jax.random.key(1), (8, 5))
    fits = jax.random.uniform(jax.random.key(2), (8, 2))
    strat = cma.StrategyMultiObjective(pop, fits, sigma=0.3, mu=4,
                                       lambda_=4)
    state = strat.initial_state()
    path = str(tmp_path / "mo.pkl")
    save_state(path, state)
    _assert_tree_equal(state, restore_state(path))


def test_roundtrip_gp_population_with_depths(tmp_path):
    import deap_tpu.gp as gp
    from deap_tpu.gp.tree import prefix_depths

    ps = gp.math_set(n_args=1)
    genomes = jax.vmap(gp.gen_half_and_half(ps, 48, 1, 3))(
        jax.random.split(jax.random.key(4), 64))
    arity = ps.arity_table()
    depths = jax.vmap(lambda g: prefix_depths(
        g["nodes"], g["length"], arity))(genomes)
    state = {"genomes": genomes, "depths": depths,
             "nevals": [64, 10, 12]}
    path = str(tmp_path / "gp.pkl")
    save_state(path, state)
    out = restore_state(path)
    _assert_tree_equal(state["genomes"], out["genomes"])
    np.testing.assert_array_equal(np.asarray(depths),
                                  np.asarray(out["depths"]))
    assert out["nevals"] == [64, 10, 12]


def test_roundtrip_island_stacked_population(tmp_path):
    from deap_tpu.parallel import island_init

    pops = island_init(jax.random.key(5), 4, 16,
                       ops.bernoulli_genome(12), FitnessSpec((1.0,)))
    pops = jax.vmap(lambda p: evaluate_invalid(
        p, lambda g: g.sum(-1).astype(jnp.float32)))(pops)
    path = str(tmp_path / "isl.pkl")
    save_state(path, {"pops": pops, "epoch": 3})
    out = restore_state(path)
    assert out["epoch"] == 3
    _assert_tree_equal(pops, out["pops"])


def test_roundtrip_meter_and_probe_carry(tmp_path):
    """The Meter state the loops thread as carry — including probe
    ``internal`` gauges (FitnessProbe's previous-best, stagnation) —
    must survive a checkpoint so a resumed run's telemetry continues
    rather than restarting."""
    from deap_tpu.telemetry import Meter
    from deap_tpu.telemetry.probes import FitnessProbe

    meter = Meter()
    meter.counter("nevals")
    meter.gauge("best")
    probe = FitnessProbe()
    probe.declare(meter)
    ms = meter.init()
    ms = meter.inc(ms, "nevals", 42)
    ms = meter.set(ms, "best", 7.5)
    pop = init_population(jax.random.key(0), 32,
                          ops.bernoulli_genome(8), FitnessSpec((1.0,)))
    pop = evaluate_invalid(pop, lambda g: g.sum(-1).astype(jnp.float32))
    ms = probe(meter, ms, pop=pop)
    path = str(tmp_path / "meter.pkl")
    save_state(path, {"mstate": ms})
    out = restore_state(path)["mstate"]
    _assert_tree_equal(ms, out)
    # a second probe application on the restored carry behaves
    # identically to one on the live carry (stagnation continuity)
    _assert_tree_equal(probe(meter, ms, pop=pop),
                       probe(meter, out, pop=pop))


def test_fsync_every_journal_policy(tmp_path):
    """RunJournal(fsync_every=n): rows are fsync'd in batches of n, a
    torn tail appended by a killed writer still parses via
    read_journal's tolerance, and offsets line up."""
    from deap_tpu.telemetry import RunJournal, read_journal

    jpath = str(tmp_path / "j.jsonl")
    j = RunJournal(jpath, fsync_every=2)
    for i in range(5):
        j.event("tick", i=i)
    # the file on disk already holds every flushed row
    rows = read_journal(jpath)
    assert [r["i"] for r in rows if r["kind"] == "tick"] == list(range(5))
    j.close()
    # simulate a kill mid-write: append a torn (newline-less) line
    with open(jpath, "a") as fh:
        fh.write('{"t": 1.0, "kind": "tick", "i": 99')
    rows = read_journal(jpath)
    assert rows.tear_offset is not None
    assert [r["i"] for r in rows if r["kind"] == "tick"] == list(range(5))
    with pytest.raises(ValueError):
        read_journal(jpath, strict=True)


def test_resilient_run_tenant_id_round_trip(tmp_path):
    """ResilientRun(tenant_id=...) stamps every checkpoint's v2 meta
    and resumes only checkpoints carrying that stamp: the same dir
    resumed under the right tenant continues bit-exactly; under a
    different tenant it refuses (fresh init instead of cross-restore)."""
    from deap_tpu.core.toolbox import Toolbox
    from deap_tpu.resilience import ResilientRun
    from deap_tpu.support.checkpoint import Checkpointer

    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    pop = init_population(jax.random.key(0), 32,
                          ops.bernoulli_genome(8), FitnessSpec((1.0,)))
    key = jax.random.key(1)
    d = str(tmp_path / "ckpt")

    res = ResilientRun(d, segment_len=2, tenant_id="alice",
                       double_buffer=False)
    p1, lb1, _ = res.ea_simple(key, pop, tb, 0.5, 0.2, ngen=4)
    ck = Checkpointer(d)
    assert ck.meta()["tenant_id"] == "alice"

    # same tenant over the same dir: resumes (already complete -> same
    # final population, logbook re-assembled bit-identically)
    res2 = ResilientRun(d, segment_len=2, tenant_id="alice",
                        double_buffer=False)
    p2, lb2, _ = res2.ea_simple(key, pop, tb, 0.5, 0.2, ngen=4)
    np.testing.assert_array_equal(np.asarray(p1.genomes),
                                  np.asarray(p2.genomes))
    assert res2.resumed_from is not None

    # a different tenant pointed at the same dir never cross-restores:
    # restore_latest filters on the stamp, so the drive re-inits
    res3 = ResilientRun(d, segment_len=2, tenant_id="mallory",
                        double_buffer=False)
    assert res3.ckpt.restore_latest(tenant_id="mallory") is None
