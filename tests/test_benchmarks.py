"""Benchmark-suite tests: known optima/values (self-contained versions of
the parity sweep run against the reference at build time — all functions
matched the reference numerically to rtol 2e-4 on random inputs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import benchmarks as bm
from deap_tpu.benchmarks import binary, movingpeaks as mp, tools as btools
from deap_tpu.native import hypervolume as native_hv


def test_single_objective_known_optima():
    z6 = jnp.zeros(6)
    assert float(bm.sphere(z6)[0]) == 0.0
    assert float(bm.rastrigin(z6)[0]) == 0.0
    assert abs(float(bm.ackley(z6)[0])) < 1e-6
    assert float(bm.griewank(z6)[0]) == 0.0
    assert float(bm.rosenbrock(jnp.ones(6))[0]) == 0.0
    assert abs(float(bm.bohachevsky(z6)[0])) < 1e-6
    assert abs(float(bm.schwefel(jnp.full(4, 420.96874636))[0])) < 1e-2
    assert abs(float(bm.himmelblau(jnp.array([3.0, 2.0]))[0])) < 1e-10
    # h1 maximum is 2 at (8.6998, 6.7665)
    assert abs(float(bm.h1(jnp.array([8.6998, 6.7665]))[0]) - 2.0) < 1e-3


def test_multiobjective_shapes_and_fronts():
    x = jnp.concatenate([jnp.array([0.3]), jnp.zeros(29)])
    f = bm.zdt1(x)
    # on the optimal front (g=1): f2 = 1 - sqrt(f1)
    np.testing.assert_allclose(
        np.asarray(f), [0.3, 1.0 - np.sqrt(0.3)], rtol=1e-5)
    f = bm.zdt2(x)
    np.testing.assert_allclose(np.asarray(f), [0.3, 1.0 - 0.09], rtol=1e-5)
    for fn, nobj in [(bm.kursawe, 2), (bm.fonseca, 2), (bm.poloni, 2),
                     (bm.dent, 2)]:
        out = fn(jnp.full(3, 0.5))
        assert out.shape == (nobj,)
    for obj in (2, 3, 4):
        for fn in (bm.dtlz1, bm.dtlz2, bm.dtlz3, bm.dtlz5, bm.dtlz6,
                   bm.dtlz7):
            assert fn(jnp.full(8, 0.4), obj).shape == (obj,)
    # dtlz2 optimal front: tail at 0.5 → Σ f² = 1
    f = bm.dtlz2(jnp.concatenate([jnp.array([0.3, 0.7]), jnp.full(6, 0.5)]), 3)
    assert abs(float(jnp.sum(f ** 2)) - 1.0) < 1e-5


def test_benchmarks_vmap_batched():
    pop = jax.random.uniform(jax.random.key(0), (128, 10))
    vals = jax.vmap(bm.rastrigin)(pop)
    assert vals.shape == (128, 1)
    vals = jax.vmap(bm.zdt1)(pop)
    assert vals.shape == (128, 2)


def test_binary_traps_and_royal_road():
    ones = jnp.ones(8, jnp.int32)
    zeros = jnp.zeros(8, jnp.int32)
    assert float(binary.trap(ones)[0]) == 8.0
    assert float(binary.trap(zeros)[0]) == 7.0
    assert float(binary.inv_trap(zeros)[0]) == 8.0
    assert float(binary.inv_trap(ones)[0]) == 7.0
    # chuang_f1 has optima 40 at all-ones+[1] and all-zeros+[0]
    f1_ones = binary.chuang_f1(jnp.ones(41, jnp.int32))
    f1_zeros = binary.chuang_f1(jnp.zeros(41, jnp.int32))
    assert float(f1_ones[0]) == 40.0 and float(f1_zeros[0]) == 40.0
    # royal road: all ones of 64 bits order 8 → 64
    assert float(binary.royal_road1(jnp.ones(64, jnp.int32), 8)[0]) == 64.0
    assert float(binary.royal_road1(jnp.zeros(64, jnp.int32), 8)[0]) == 0.0
    assert float(binary.royal_road2(jnp.ones(64, jnp.int32), 4)[0]) > 64.0


def test_bin2float_decodes():
    @binary.bin2float(0.0, 1.0, 4)
    def decoded_sum(d):
        return jnp.sum(d, keepdims=True)

    bits = jnp.array([1, 1, 1, 1, 0, 0, 0, 0], jnp.int32)
    np.testing.assert_allclose(np.asarray(decoded_sum(bits)), [1.0], rtol=1e-6)


def test_transform_decorators():
    evaluate = btools.translate(jnp.array([1.0, 1.0]))(bm.sphere)
    np.testing.assert_allclose(
        np.asarray(evaluate(jnp.array([1.0, 1.0]))), [0.0], atol=1e-6)
    evaluate.translate(jnp.zeros(2))
    np.testing.assert_allclose(
        np.asarray(evaluate(jnp.array([1.0, 1.0]))), [2.0], atol=1e-6)

    theta = jnp.pi / 2
    rot = jnp.array([[jnp.cos(theta), -jnp.sin(theta)],
                     [jnp.sin(theta), jnp.cos(theta)]])
    evaluate = btools.rotate(rot)(bm.plane)
    # inverse rotation of (0, 1) is (1, 0) → plane = 1
    np.testing.assert_allclose(
        np.asarray(evaluate(jnp.array([0.0, 1.0]))), [1.0], atol=1e-6)

    evaluate = btools.scale(jnp.array([2.0, 2.0]))(bm.sphere)
    np.testing.assert_allclose(
        np.asarray(evaluate(jnp.array([2.0, 2.0]))), [2.0], atol=1e-6)

    noisy = btools.noise(0.5)(bm.sphere)
    v1 = noisy(jnp.ones(2), jax.random.key(0))
    v2 = noisy(jnp.ones(2), jax.random.key(1))
    assert float(v1[0]) != float(v2[0])

    clipper = btools.bound((jnp.zeros(3), jnp.ones(3)), "clip")(
        lambda x: x * 3.0)
    assert float(clipper(jnp.ones(3)).max()) == 1.0
    mirror = btools.bound((jnp.zeros(1), jnp.ones(1)), "mirror")(
        lambda x: x)
    np.testing.assert_allclose(np.asarray(mirror(jnp.array([1.2]))), [0.8],
                               rtol=1e-5)


def test_hypervolume_exact_values():
    # 2-D staircase
    assert native_hv(np.array([[1.0, 2.0], [2.0, 1.0]]), np.array([3.0, 3.0])) == 3.0
    # 3-D inclusion-exclusion
    pts = np.array([[0.0, 0.0, 1.0], [1.0, 1.0, 0.0]])
    assert native_hv(pts, np.array([2.0, 2.0, 2.0])) == 5.0
    # dominated point contributes nothing
    pts = np.array([[1.0, 1.0], [1.5, 1.5]])
    assert native_hv(pts, np.array([2.0, 2.0])) == 1.0
    # metric wrapper flips weighted values to minimisation space
    hv = btools.hypervolume(np.array([[1.0, 2.0], [2.0, 1.0]]),
                            ref=[3.0, 3.0])
    assert hv == 3.0


def test_metrics():
    front = jnp.array([[0.0, 1.0], [0.5, 0.5], [1.0, 0.0]])
    opt = jnp.array([[0.0, 1.0], [0.5, 0.5], [1.0, 0.0]])
    assert btools.convergence(front, opt) == 0.0
    assert btools.igd(front, opt) == 0.0
    d = btools.diversity(front, (0.0, 1.0), (1.0, 0.0))
    assert d < 1e-6  # perfectly spread


def test_movingpeaks_eval_and_change():
    cfg = mp.MovingPeaksConfig(dim=2, **{k: v for k, v in
                                         mp.SCENARIO_1.items()})
    cfg = mp.MovingPeaksConfig(dim=2, npeaks=5, period=100)
    state = mp.mp_init(jax.random.key(0), cfg)
    pop = jax.random.uniform(jax.random.key(1), (50, 2), minval=0.0,
                             maxval=100.0)
    state1, vals = mp.mp_evaluate(cfg, state, pop)
    assert vals.shape == (50, 1)
    assert int(state1.nevals) == 50
    # peaks unchanged until the period boundary
    np.testing.assert_allclose(np.asarray(state1.position),
                               np.asarray(state.position))
    state2, _ = mp.mp_evaluate(cfg, state1, pop)  # nevals 100 → change
    assert not np.allclose(np.asarray(state2.position),
                           np.asarray(state1.position))
    # the change resets the running error (reference: _optimum = None)
    assert float(state2.current_error) == float("inf")
    assert float(mp.offline_error(state2)) > 0.0
    # next batch re-establishes a finite running minimum
    state3, _ = mp.mp_evaluate(cfg, state2, pop)
    assert np.isfinite(float(state3.current_error))
    assert np.isfinite(float(mp.offline_error(state3)))
    # evaluating exactly at a peak is optimal
    peak0 = state.position[0]
    _, v = mp.mp_evaluate(cfg, state, peak0[None, :])
    assert float(v[0, 0]) <= float(mp.global_maximum(cfg, state)) + 1e-5


def test_movingpeaks_inside_jit():
    cfg = mp.MovingPeaksConfig(dim=3, npeaks=4, period=10)
    state = mp.mp_init(jax.random.key(2), cfg)

    @jax.jit
    def step(state, genomes):
        return mp.mp_evaluate(cfg, state, genomes)

    g = jax.random.uniform(jax.random.key(3), (12, 3), maxval=100.0)
    state, vals = step(state, g)
    assert int(state.nevals) == 12
    assert bool(jnp.isfinite(vals).all())


def test_movingpeaks_exact_matches_per_eval_sequence():
    """exact=True must reproduce per-evaluation trigger semantics
    bit-for-bit: a batch that straddles one or more period boundaries
    equals the same evaluations fed one at a time (batch=1 IS
    per-eval granularity on the default path), including mid-batch
    landscape changes, PRNG stream, and error bookkeeping."""
    cfg = mp.MovingPeaksConfig(dim=2, npeaks=4, period=7)
    pop = jax.random.uniform(jax.random.key(5), (30, 2), minval=0.0,
                             maxval=100.0)

    # exact batched: 30 evals cross boundaries at 7, 14, 21, 28
    st_b = mp.mp_init(jax.random.key(4), cfg)
    st_b, vals_b = mp.mp_evaluate(cfg, st_b, pop, exact=True)

    # sequential oracle: one individual per call
    st_s = mp.mp_init(jax.random.key(4), cfg)
    vals_s = []
    for i in range(30):
        st_s, v = mp.mp_evaluate(cfg, st_s, pop[i][None, :])
        vals_s.append(float(v[0, 0]))

    np.testing.assert_allclose(np.asarray(vals_b)[:, 0],
                               np.asarray(vals_s), rtol=1e-6)
    assert int(st_b.nevals) == int(st_s.nevals) == 30
    np.testing.assert_allclose(np.asarray(st_b.position),
                               np.asarray(st_s.position), rtol=1e-6)
    np.testing.assert_allclose(float(st_b.offline_error_sum),
                               float(st_s.offline_error_sum), rtol=1e-6)
    np.testing.assert_allclose(float(st_b.current_error),
                               float(st_s.current_error), rtol=1e-6)

    # values split across a boundary: prefix on the old landscape,
    # suffix on the new (first 7 match a no-change evaluation, the
    # batch as a whole does not)
    st0 = mp.mp_init(jax.random.key(4), cfg)
    nochange = mp.MovingPeaksConfig(dim=2, npeaks=4, period=0)
    _, vals_static = mp.mp_evaluate(nochange, st0, pop)
    np.testing.assert_allclose(np.asarray(vals_b[:7, 0]),
                               np.asarray(vals_static[:7, 0]), rtol=1e-6)
    assert not np.allclose(np.asarray(vals_b[:, 0]),
                           np.asarray(vals_static[:, 0]))

    # non-crossing batch takes the fully-batched path and equals the
    # default mode exactly
    st_a = mp.mp_init(jax.random.key(6), cfg)
    st_e, ve = mp.mp_evaluate(cfg, st_a, pop[:5], exact=True)
    st_d, vd = mp.mp_evaluate(cfg, st_a, pop[:5])
    np.testing.assert_allclose(np.asarray(ve), np.asarray(vd))
    np.testing.assert_allclose(float(st_e.offline_error_sum),
                               float(st_d.offline_error_sum))

    # exact mode works under jit
    je = jax.jit(lambda s, g: mp.mp_evaluate(cfg, s, g, exact=True))
    st_j, vj = je(mp.mp_init(jax.random.key(4), cfg), pop)
    np.testing.assert_allclose(np.asarray(vj), np.asarray(vals_b),
                               rtol=1e-6)


def test_movingpeaks_maximums_contains_global():
    cfg = mp.MovingPeaksConfig(**{**mp.SCENARIO_2, "dim": 3, "period": 0})
    state = mp.mp_init(jax.random.key(3), cfg)
    vals, pos = mp.maximums(cfg, state)
    assert vals.shape == (cfg.npeaks,)
    assert pos.shape == (cfg.npeaks, 3)
    np.testing.assert_allclose(
        float(vals.max()), float(mp.global_maximum(cfg, state)), rtol=1e-6)


def test_optimal_fronts_are_nondominated_and_exact():
    """Analytic ZDT/DTLZ optimal fronts (counterpart of the reference's
    pareto_front/*.json fixtures)."""
    import jax.numpy as jnp

    from deap_tpu.benchmarks import tools as bt

    for name in ("zdt1", "zdt2", "zdt3", "zdt4", "zdt6"):
        f = bt.optimal_front(name, 80)
        assert f.shape == (80, 2)
        dom = ((f[None] <= f[:, None]).all(-1)
               & (f[None] < f[:, None]).any(-1)).any(1)
        assert not bool(dom.any()), name
        assert bool((jnp.diff(f[:, 0]) >= -1e-7).all()), name  # f1-sorted
    # zdt3 spans all five disconnected segments, not just the first
    assert float(bt.optimal_front("zdt3", 80)[-1, 0]) > 0.8
    # zdt6's attained f1 range with distinct extremes
    f6 = bt.optimal_front("zdt6", 80)
    assert abs(float(f6[0, 0]) - 0.2808) < 0.02
    assert float(f6[-1, 0]) == 1.0
    d1 = bt.optimal_front("dtlz1", 60, nobj=3)
    assert d1.shape[0] >= 60 and jnp.allclose(d1.sum(1), 0.5, atol=1e-5)
    d2 = bt.optimal_front("dtlz2", 60, nobj=3)
    assert d2.shape[0] >= 60
    assert jnp.allclose(jnp.linalg.norm(d2, axis=1), 1.0, atol=1e-5)
    # convergence of the exact front to itself ≈ 0 (sampling residual)
    assert bt.convergence(bt.optimal_front("zdt1", 50),
                          bt.optimal_front("zdt1", 400)) < 0.01
