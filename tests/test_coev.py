"""Co-evolution tests (reference: examples/coev/hillis.py competitive
host-parasite, examples/coev/coop_base.py cooperative species)."""

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu import coev, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

L = 32


def _toolbox(indpb=0.05):
    tb = Toolbox()
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=indpb)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def test_competitive_opposite_weights():
    """Hosts minimise the shared encounter value, parasites maximise it:
    after evaluation both carry the same raw values but opposite
    wvalues (hillis.py:131-134 + FitnessMin/FitnessMax creation)."""
    k = jax.random.key(0)
    hosts = init_population(k, 16, ops.bernoulli_genome(L),
                            FitnessSpec((-1.0,)))
    parasites = init_population(jax.random.key(1), 16,
                                ops.bernoulli_genome(L), FitnessSpec((1.0,)))
    eval_pair = lambda h, p: jnp.sum(h == p).astype(jnp.float32)
    h2, p2 = coev.competitive_eval(hosts, parasites, eval_pair)
    np.testing.assert_array_equal(h2.fitness, p2.fitness)
    np.testing.assert_allclose(np.asarray(h2.wvalues),
                               -np.asarray(p2.wvalues))
    assert bool(h2.valid.all()) and bool(p2.valid.all())


def test_competitive_arms_race():
    """Parasites evolve toward matching hosts (score rises), hosts away
    (score falls): with both sides adapting, the mean encounter score
    should stay bounded away from the extremes — the signature of an
    arms race rather than a one-sided collapse."""
    htb, ptb = _toolbox(), _toolbox()
    hosts = init_population(jax.random.key(0), 64,
                            ops.bernoulli_genome(L), FitnessSpec((-1.0,)))
    parasites = init_population(jax.random.key(1), 64,
                                ops.bernoulli_genome(L), FitnessSpec((1.0,)))
    eval_pair = lambda h, p: jnp.sum(h == p).astype(jnp.float32)
    hosts, parasites = coev.competitive_eval(hosts, parasites, eval_pair)

    step = jax.jit(lambda k, h, p: coev.competitive_step(
        k, h, p, htb, ptb, eval_pair))
    for g in range(15):
        hosts, parasites = step(jax.random.key(10 + g), hosts, parasites)
    mean = float(hosts.fitness.mean())
    assert 4.0 < mean < L - 4.0


def test_coop_species_improve_jointly():
    """coop_base schema-matching, tensorised: three species each cover a
    third of a 48-bit target; joint fitness = matches of the assembled
    string. Cooperative evolution must raise the assembled score."""
    n_species, seg = 3, 16
    target = jax.random.bernoulli(jax.random.key(99), 0.5,
                                  (n_species * seg,)).astype(jnp.int8)

    def evaluate(i, genomes, reps):
        parts = [jnp.broadcast_to(reps[j], genomes.shape) if j != i
                 else genomes for j in range(n_species)]
        assembled = jnp.concatenate(parts, axis=-1)
        return jnp.sum(assembled == target, axis=-1).astype(jnp.float32)

    tb = _toolbox(indpb=1.0 / seg)
    species = [
        init_population(jax.random.key(i), 32, ops.bernoulli_genome(seg),
                        FitnessSpec((1.0,)))
        for i in range(n_species)
    ]
    species = [coev.coop_eval_species(i, s, [
        jnp.zeros((seg,), jnp.int8)] * n_species, evaluate)
        for i, s in enumerate(species)]
    reps = coev.coop_representatives(species)

    def best_joint(species, reps):
        return max(float(s.wvalues.max()) for s in species)

    before = best_joint(species, reps)
    step = jax.jit(lambda k, sp, r: coev.coop_step(
        k, sp, r, tb, evaluate, cxpb=0.6, mutpb=1.0))
    for g in range(20):
        species, reps = step(jax.random.key(200 + g), species, reps)
    after = best_joint(species, reps)
    assert after >= before
    assert after >= 0.85 * (n_species * seg)


def test_coop_per_species_toolboxes():
    """A per-species toolbox list is accepted (hillis uses two distinct
    toolboxes; the coop ladder customises per-species operators)."""
    seg = 8
    target = jnp.ones((2 * seg,), jnp.int8)

    def evaluate(i, genomes, reps):
        parts = [jnp.broadcast_to(reps[j], genomes.shape) if j != i
                 else genomes for j in range(2)]
        assembled = jnp.concatenate(parts, axis=-1)
        return jnp.sum(assembled == target, axis=-1).astype(jnp.float32)

    tbs = [_toolbox(0.1), _toolbox(0.2)]
    species = [
        init_population(jax.random.key(i), 16, ops.bernoulli_genome(seg),
                        FitnessSpec((1.0,)))
        for i in range(2)
    ]
    species = [coev.coop_eval_species(i, s, [
        jnp.zeros((seg,), jnp.int8)] * 2, evaluate)
        for i, s in enumerate(species)]
    reps = coev.coop_representatives(species)
    species, reps = coev.coop_step(jax.random.key(3), species, reps, tbs,
                                   evaluate)
    assert len(species) == 2 and len(reps) == 2


def test_match_set_strength_and_contributions():
    """match_counts / match_set_strength / match_set_contributions agree
    with a hand-computed Potter & De Jong match set (reference
    coop_base.py:44-98)."""
    import numpy as np

    targets = jnp.array([[1, 1, 0, 0],
                         [0, 0, 1, 1]], jnp.int8)
    reps = [jnp.array([1, 1, 0, 0], jnp.int8),   # perfect on t0, 0 on t1
            jnp.array([0, 0, 1, 0], jnp.int8)]   # 1 on t0, 3 on t1
    m = np.asarray(coev.match_counts(jnp.stack(reps), targets))
    assert m.tolist() == [[4.0, 0.0], [1.0, 3.0]]

    # species 1 member [0,0,1,1]: set = {rep0, member}
    genomes = jnp.array([[0, 0, 1, 1]], jnp.int8)
    s = coev.match_set_strength(1, genomes, reps, targets)
    # t0: max(rep0=4, member=0) = 4; t1: max(rep0=0, member=4) = 4
    assert float(s[0]) == 4.0

    contribs = np.asarray(coev.match_set_contributions(reps, targets))
    # t0 claimed by rep0 (4), t1 by rep1 (3) → [4/2, 3/2]
    assert contribs.tolist() == [2.0, 1.5]


def test_coop_rung_gen_fixed_species_count():
    """coop_gen.py's rung: NUM_SPECIES chosen up front and CONSTANT —
    no additions, no extinctions, whatever the fitness does."""
    import examples.coev.coop_evol as ce

    out = ce.main(smoke=True, mode="gen", num_species=3, verbose=False,
                  return_trace=True)
    assert [c for _, c, _ in out["trace"]] == [3] * len(out["trace"])


def test_coop_rung_adapt_fixed_schedule():
    """coop_adapt.py's rung (section 4.2.3: 'A species is added each
    100 generations'): additions follow the FIXED round schedule, not
    stagnation — count is exactly 1 + rounds_elapsed // ADAPT_LENGTH."""
    import examples.coev.coop_evol as ce

    out = ce.main(smoke=False, mode="adapt", verbose=False,
                  return_trace=True)
    for rnd, count, _ in out["trace"]:
        assert count == 1 + rnd // ce.ADAPT_LENGTH, (rnd, count)


def test_coop_rung_evol_stagnation_dynamics():
    """coop_evol.py's rung: species arrive only through stagnation
    (count never jumps by more than +1 per round; extinctions may make
    it shrink at an addition), at least one stagnation fires in a full
    run, and the population never goes extinct."""
    import examples.coev.coop_evol as ce

    out = ce.main(smoke=False, mode="evol", verbose=False,
                  return_trace=True)
    counts = [c for _, c, _ in out["trace"]]
    assert all(c >= 1 for c in counts)
    deltas = [b - a for a, b in zip(counts, counts[1:])]
    assert all(d <= 1 for d in deltas)
    assert any(d != 0 for d in deltas), "no stagnation event in 40 rounds"


def test_coop_rung_niche_species_separate():
    """coop_niche.py's rung: with one species per schema, the final
    representatives settle into DISTINCT niches (the reference's
    observable is the printed representatives matching different
    schemata). Each schema's fixed block must be claimed by some
    representative with high match density, and representatives must
    not all pile onto one block."""
    import examples.coev.coop_evol as ce
    import numpy as np

    out = ce.main(smoke=False, mode="niche", verbose=False,
                  return_trace=True)
    reps = [np.asarray(r) for r in out["reps"]]
    schematas = out["schematas"]
    n_types = len(schematas)
    L = len(schematas[0])
    block = L // n_types
    # density of 1s each rep has inside each schema's fixed block
    dens = np.array([[r[i * block:(i + 1) * block].mean()
                      for i in range(n_types)] for r in reps])
    claimed = set(dens.argmax(axis=1).tolist())
    assert len(claimed) >= 2, dens
    # every block is matched well by its best-claiming representative
    assert (dens.max(axis=0) > 0.75).all(), dens


def test_coop_evol_ladder_smoke():
    """The evolving-species ladder runs every rung and improves the
    collaboration (counterpart of coop_niche/gen/adapt/evol).

    Floors are above the random-start expectation: a lone random
    species' best ≈ 32 + 4/√30·E[max z of 50] ≈ 33.6 (mean-over-30-
    targets of Binomial(64, ½) matches); with the 3-species niche setup
    the representative union starts higher, so its floor is higher.
    Observed smoke finals across seeds: ≥ 37.4 (single-species modes),
    ≥ 41.7 (niche)."""
    import examples.coev.coop_evol as ce

    for mode in ("niche", "gen", "adapt", "evol"):
        best = ce.main(smoke=True, mode=mode, verbose=False)
        assert best > (40.0 if mode == "niche" else 35.0), mode
