"""Co-evolution tests (reference: examples/coev/hillis.py competitive
host-parasite, examples/coev/coop_base.py cooperative species)."""

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu import coev, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

L = 32


def _toolbox(indpb=0.05):
    tb = Toolbox()
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=indpb)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def test_competitive_opposite_weights():
    """Hosts minimise the shared encounter value, parasites maximise it:
    after evaluation both carry the same raw values but opposite
    wvalues (hillis.py:131-134 + FitnessMin/FitnessMax creation)."""
    k = jax.random.key(0)
    hosts = init_population(k, 16, ops.bernoulli_genome(L),
                            FitnessSpec((-1.0,)))
    parasites = init_population(jax.random.key(1), 16,
                                ops.bernoulli_genome(L), FitnessSpec((1.0,)))
    eval_pair = lambda h, p: jnp.sum(h == p).astype(jnp.float32)
    h2, p2 = coev.competitive_eval(hosts, parasites, eval_pair)
    np.testing.assert_array_equal(h2.fitness, p2.fitness)
    np.testing.assert_allclose(np.asarray(h2.wvalues),
                               -np.asarray(p2.wvalues))
    assert bool(h2.valid.all()) and bool(p2.valid.all())


def test_competitive_arms_race():
    """Parasites evolve toward matching hosts (score rises), hosts away
    (score falls): with both sides adapting, the mean encounter score
    should stay bounded away from the extremes — the signature of an
    arms race rather than a one-sided collapse."""
    htb, ptb = _toolbox(), _toolbox()
    hosts = init_population(jax.random.key(0), 64,
                            ops.bernoulli_genome(L), FitnessSpec((-1.0,)))
    parasites = init_population(jax.random.key(1), 64,
                                ops.bernoulli_genome(L), FitnessSpec((1.0,)))
    eval_pair = lambda h, p: jnp.sum(h == p).astype(jnp.float32)
    hosts, parasites = coev.competitive_eval(hosts, parasites, eval_pair)

    step = jax.jit(lambda k, h, p: coev.competitive_step(
        k, h, p, htb, ptb, eval_pair))
    for g in range(15):
        hosts, parasites = step(jax.random.key(10 + g), hosts, parasites)
    mean = float(hosts.fitness.mean())
    assert 4.0 < mean < L - 4.0


def test_coop_species_improve_jointly():
    """coop_base schema-matching, tensorised: three species each cover a
    third of a 48-bit target; joint fitness = matches of the assembled
    string. Cooperative evolution must raise the assembled score."""
    n_species, seg = 3, 16
    target = jax.random.bernoulli(jax.random.key(99), 0.5,
                                  (n_species * seg,)).astype(jnp.int8)

    def evaluate(i, genomes, reps):
        parts = [jnp.broadcast_to(reps[j], genomes.shape) if j != i
                 else genomes for j in range(n_species)]
        assembled = jnp.concatenate(parts, axis=-1)
        return jnp.sum(assembled == target, axis=-1).astype(jnp.float32)

    tb = _toolbox(indpb=1.0 / seg)
    species = [
        init_population(jax.random.key(i), 32, ops.bernoulli_genome(seg),
                        FitnessSpec((1.0,)))
        for i in range(n_species)
    ]
    species = [coev.coop_eval_species(i, s, [
        jnp.zeros((seg,), jnp.int8)] * n_species, evaluate)
        for i, s in enumerate(species)]
    reps = coev.coop_representatives(species)

    def best_joint(species, reps):
        return max(float(s.wvalues.max()) for s in species)

    before = best_joint(species, reps)
    step = jax.jit(lambda k, sp, r: coev.coop_step(
        k, sp, r, tb, evaluate, cxpb=0.6, mutpb=1.0))
    for g in range(20):
        species, reps = step(jax.random.key(200 + g), species, reps)
    after = best_joint(species, reps)
    assert after >= before
    assert after >= 0.85 * (n_species * seg)


def test_coop_per_species_toolboxes():
    """A per-species toolbox list is accepted (hillis uses two distinct
    toolboxes; the coop ladder customises per-species operators)."""
    seg = 8
    target = jnp.ones((2 * seg,), jnp.int8)

    def evaluate(i, genomes, reps):
        parts = [jnp.broadcast_to(reps[j], genomes.shape) if j != i
                 else genomes for j in range(2)]
        assembled = jnp.concatenate(parts, axis=-1)
        return jnp.sum(assembled == target, axis=-1).astype(jnp.float32)

    tbs = [_toolbox(0.1), _toolbox(0.2)]
    species = [
        init_population(jax.random.key(i), 16, ops.bernoulli_genome(seg),
                        FitnessSpec((1.0,)))
        for i in range(2)
    ]
    species = [coev.coop_eval_species(i, s, [
        jnp.zeros((seg,), jnp.int8)] * 2, evaluate)
        for i, s in enumerate(species)]
    reps = coev.coop_representatives(species)
    species, reps = coev.coop_step(jax.random.key(3), species, reps, tbs,
                                   evaluate)
    assert len(species) == 2 and len(reps) == 2
