"""Burn-rate alert engine — state machine, determinism, metrics race.

The acceptance bar of ``deap_tpu/telemetry/alerts.py`` (ISSUE 19):
the multi-window state machine follows its documented transition
table exactly, the engine is deterministic (same sample stream and
config → byte-identical journaled transitions — it never reads a
clock), the canary rule's epsilon burn makes ANY failing sample fire
even when surrounded by passing canaries, and the metrics plane the
alerts export through survives a snapshot-vs-observe hammer (the
``samples()``/``expose()`` iteration is now taken under the registry
lock — satellite (c))."""

import json
import threading

import pytest

from deap_tpu.telemetry.alerts import (ALERT_STATE_VALUES,
                                       ALERT_STATES, AlertEngine,
                                       AlertRule, default_rules,
                                       service_rules)
from deap_tpu.telemetry.metrics import (MetricsRegistry, alarms_total,
                                        alert_state_gauge,
                                        metrics_text)


class _Sink:
    def __init__(self):
        self.rows = []

    def event(self, kind, **payload):
        self.rows.append(dict(kind=kind, **payload))


def _engine(**rule_kw):
    kw = dict(name="r", metric="m", threshold=0.5,
              fast_window_s=10.0, slow_window_s=60.0, burn=0.5)
    kw.update(rule_kw)
    sink = _Sink()
    return AlertEngine([AlertRule(**kw)], journal=sink), sink


# ---------------------------------------------------- state machine ----

def test_states_and_gauge_encoding():
    assert ALERT_STATES == ("inactive", "pending", "firing",
                            "resolved")
    # resolved encodes as 0 so the gauge shows live state, not history
    assert ALERT_STATE_VALUES["resolved"] == 0
    assert ALERT_STATE_VALUES["firing"] == 2


def test_rule_validation():
    with pytest.raises(ValueError):
        AlertRule("r", "m", 1.0, fast_window_s=0.0)
    with pytest.raises(ValueError):
        AlertRule("r", "m", 1.0, fast_window_s=10.0, slow_window_s=5.0)
    with pytest.raises(ValueError):
        AlertRule("r", "m", 1.0, burn=0.0)
    with pytest.raises(ValueError):
        AlertRule("r", "m", 1.0, burn=1.5)
    with pytest.raises(ValueError):
        AlertEngine([AlertRule("dup", "m", 1.0),
                     AlertRule("dup", "m2", 1.0)])


def test_no_samples_never_transitions():
    eng, sink = _engine()
    for t in (0.0, 5.0, 100.0):
        assert eng.tick(t) == []
    assert eng.state("r") == "inactive"
    assert sink.rows == []


def test_none_values_are_skipped():
    eng, _ = _engine()
    eng.observe(1.0, "m", None)
    eng.tick(2.0)
    assert eng.state("r") == "inactive"


def test_fast_hot_slow_cold_goes_pending_then_firing():
    # slow window twice the fast one: early hot samples make the fast
    # window burn before the slow window accumulates confidence
    eng, sink = _engine(fast_window_s=10.0, slow_window_s=20.0)
    for t in (0.0, 1.0):
        eng.observe(t, "m", 0.0)          # cold history
    for t in (12.0, 13.0, 14.0):
        eng.observe(t, "m", 1.0)          # hot burst
    # at t=15: fast window (5..15] is all hot; slow window (-5..15]
    # still majority-diluted by the cold samples? 3 hot / 5 = 0.6 ≥
    # 0.5 — tune the cold history so slow stays below the burn
    eng2, sink2 = _engine(fast_window_s=10.0, slow_window_s=20.0)
    for t in (0.0, 1.0, 2.0, 3.0):
        eng2.observe(t, "m", 0.0)
    for t in (12.0, 13.0, 14.0):
        eng2.observe(t, "m", 1.0)
    out = eng2.tick(15.0)
    assert eng2.state("r") == "pending"    # fast 3/3, slow 3/7
    assert [tr["to"] for tr in out] == ["pending"]
    # hot keeps coming: the slow window crosses the burn → firing
    for t in (16.0, 17.0, 18.0, 19.0):
        eng2.observe(t, "m", 1.0)
    eng2.tick(20.0)
    assert eng2.state("r") == "firing"
    assert [r["state"] for r in sink2.rows] == ["pending", "firing"]


def test_pending_decays_to_inactive():
    eng, sink = _engine(fast_window_s=10.0, slow_window_s=40.0)
    for t in (0.0, 1.0, 2.0, 3.0, 4.0):
        eng.observe(t, "m", 0.0)
    eng.observe(5.0, "m", 1.0)
    eng.tick(14.0)                         # fast (4..14]: only the hot
    assert eng.state("r") == "pending"     # slow 1/6 stays cold
    eng.tick(20.0)                         # hot sample left the window
    assert eng.state("r") == "inactive"
    assert [r["state"] for r in sink.rows] == ["pending", "inactive"]


def test_firing_resolves_then_collapses_silently():
    eng, sink = _engine()
    eng.observe(1.0, "m", 1.0)
    eng.tick(2.0)                          # 1/1 in both → firing
    assert eng.state("r") == "firing"
    eng.observe(3.0, "m", 0.0)
    eng.observe(4.0, "m", 0.0)
    eng.tick(5.0)                          # fast burn 1/3 < 0.5
    assert eng.state("r") == "resolved"
    eng.tick(6.0)                          # silent collapse
    assert eng.state("r") == "inactive"
    assert [r["state"] for r in sink.rows] == ["firing", "resolved"]
    # the collapse journaled nothing
    assert len(sink.rows) == 2


def test_sample_trim_never_changes_verdicts():
    eng, _ = _engine(fast_window_s=5.0, slow_window_s=10.0)
    for t in range(100):
        eng.observe(float(t), "m", 1.0 if t % 2 else 0.0)
        eng.tick(float(t) + 0.5)
    # trimmed buffer only holds the slow window
    assert all(t > 90.5 - 10.0 for t, _ in eng._samples["r"])


# ------------------------------------------------------ determinism ----

def test_determinism_identical_streams_identical_transitions():
    import random
    rng = random.Random(19)
    stream = [(i * 0.5, rng.random()) for i in range(400)]

    def run():
        eng, sink = _engine(threshold=0.6, fast_window_s=5.0,
                            slow_window_s=30.0)
        for t, v in stream:
            eng.observe(t, "m", v)
            if int(t * 2) % 4 == 0:
                eng.tick(t)
        return json.dumps(sink.rows, sort_keys=True)

    assert run() == run()


def test_observe_curve_feeds_window_edges():
    eng = AlertEngine(default_rules())
    eng.observe_curve([
        {"t0": 0.0, "t1": 1.0, "shed_rate": 0.5,
         "deadline_miss_rate": 0.0},
        {"t0": 1.0, "t1": 2.0, "shed_rate": 0.5},
    ])
    eng.tick(2.0)
    assert eng.state("shed_rate") == "firing"
    assert eng.state("deadline_miss_rate") == "inactive"
    # queue_wait_p99 got no samples at all: untouched
    assert eng.state("queue_wait_p99") == "inactive"


# ------------------------------------------------------ canary rule ----

def test_canary_epsilon_burn_fires_despite_passing_neighbours():
    """A known-answer failure is an incident, not a rate: one failing
    canary surrounded by passing ones at a tight cadence must fire
    the same tick, and resolve once the fast window is clean."""
    eng = AlertEngine(service_rules())
    for i in range(8):
        eng.observe(float(i) * 0.2, "canary_fail", 0.0)
        eng.tick(float(i) * 0.2)
    assert eng.state("canary_failure") == "inactive"
    eng.observe(2.0, "canary_fail", 1.0)
    out = eng.tick(2.0)
    assert eng.state("canary_failure") == "firing"
    assert [tr["to"] for tr in out] == ["firing"]
    assert eng.firing() == ["canary_failure"]
    # clean canaries resume; the failure ages out of the 10 s fast
    # window and the alert resolves
    for i in range(70):
        t = 2.5 + i * 0.2
        eng.observe(t, "canary_fail", 0.0)
        eng.tick(t)
    assert eng.state("canary_failure") == "inactive"
    states = [tr["to"] for tr in eng.transitions
              if tr["name"] == "canary_failure"]
    assert states == ["firing", "resolved"]


def test_snapshot_shape():
    eng = AlertEngine(service_rules())
    snap = eng.snapshot()
    assert [s["name"] for s in snap] == \
        ["canary_failure", "shed_rate", "deadline_miss_rate"]
    for s in snap:
        assert set(s) >= {"name", "metric", "threshold", "burn",
                          "state", "since", "fast_burn", "slow_burn",
                          "fast_window_s", "slow_window_s"}
        assert s["state"] == "inactive"


# ------------------------------------------- metrics exposition race ----

def test_alarm_and_alert_instruments_register_once():
    reg = MetricsRegistry()
    c = alarms_total(reg)
    assert alarms_total(reg) is c
    g = alert_state_gauge(reg)
    assert alert_state_gauge(reg) is g
    c.inc(kind="canary")
    g.set(2, name="canary_failure")
    text = metrics_text(reg)
    assert 'deap_alarms_total{kind="canary"} 1' in text
    assert 'deap_alert_state{name="canary_failure"} 2' in text


def test_metrics_exposition_hammer_vs_concurrent_observes():
    """Satellite (c): ``samples()`` used to iterate the live child
    dict while observers insert new label children — a dict-changed-
    size crash under concurrency. The snapshot is now taken under the
    registry lock; this hammer pins it (fails with RuntimeError on
    the unlocked iteration)."""
    reg = MetricsRegistry()
    hist = reg.histogram("h", "hammer", labels=("k",),
                         buckets=(0.1, 1.0, 10.0))
    ctr = reg.counter("c", "hammer", labels=("k",))
    gge = reg.gauge("g", "hammer", labels=("k",))
    stop = threading.Event()
    errors = []

    def observer():
        i = 0
        while not stop.is_set():
            hist.observe(i % 7, k=f"h{i % 97}")
            ctr.inc(k=f"c{i % 97}")
            gge.set(i, k=f"g{i % 97}")
            i += 1

    def scraper():
        while not stop.is_set():
            try:
                text = metrics_text(reg)
                assert "# TYPE h histogram" in text
            except Exception as e:  # pragma: no cover - the bug
                errors.append(e)
                return

    threads = ([threading.Thread(target=observer) for _ in range(3)]
               + [threading.Thread(target=scraper) for _ in range(2)])
    for th in threads:
        th.start()
    import time
    time.sleep(1.0)
    stop.set()
    for th in threads:
        th.join(timeout=10)
    assert not errors, errors
