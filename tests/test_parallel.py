"""Island-model / migration / sharding tests on the 8-virtual-device CPU
mesh — the TPU-native analog of the reference's pickle-round-trip
"distribution without a cluster" tests (SURVEY.md §4.3)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import Population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.parallel import (
    island_init,
    make_island_step,
    mig_ring,
    population_mesh,
    shard_population,
)


def _stacked_demes(n_demes=3, size=4):
    # deme d, individual i → fitness 10*d + i (best of deme d = 10d+size-1)
    fit = (10.0 * jnp.arange(n_demes)[:, None]
           + jnp.arange(size)[None, :])[..., None]
    genomes = fit.copy()
    return Population(
        genomes=genomes, fitness=fit,
        valid=jnp.ones((n_demes, size), bool), spec=FitnessSpec((1.0,)))


def test_mig_ring_moves_best_around_ring():
    pops = _stacked_demes(3, 4)
    out = mig_ring(jax.random.key(0), pops, k=1)
    f = np.asarray(out.fitness[..., 0])
    # deme bests: d0=3, d1=13, d2=23; each deme's best slot is overwritten
    # by the previous deme's best (replacement=None → emigrants replaced)
    np.testing.assert_array_equal(np.sort(f[0]), [0.0, 1.0, 2.0, 23.0])
    np.testing.assert_array_equal(np.sort(f[1]), [3.0, 10.0, 11.0, 12.0])
    np.testing.assert_array_equal(np.sort(f[2]), [13.0, 20.0, 21.0, 22.0])


def _toolbox(length):
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def test_island_step_single_device_improves():
    length = 32
    tb = _toolbox(length)
    pops = island_init(jax.random.key(0), 4, 64,
                       ops.bernoulli_genome(length), FitnessSpec((1.0,)))
    from deap_tpu.algorithms import evaluate_invalid
    pops = jax.vmap(lambda p: evaluate_invalid(p, tb.evaluate))(pops)
    before = float(pops.fitness.max())
    step = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=5, mig_k=2)
    key = jax.random.key(1)
    for i in range(4):
        pops = step(jax.random.fold_in(key, i), pops)
    assert float(pops.fitness.max()) > before
    assert bool(pops.valid.all())


def test_island_step_sharded_over_mesh():
    assert len(jax.devices()) >= 8, "conftest must provide 8 cpu devices"
    length = 16
    tb = _toolbox(length)
    mesh = population_mesh(8, ("island",))
    pops = island_init(jax.random.key(2), 8, 32,
                       ops.bernoulli_genome(length), FitnessSpec((1.0,)))
    from deap_tpu.algorithms import evaluate_invalid
    pops = jax.vmap(lambda p: evaluate_invalid(p, tb.evaluate))(pops)
    pops = shard_population(pops, mesh, "island")
    step = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=3, mig_k=2,
                            mesh=mesh)
    out = step(jax.random.key(3), pops)
    assert out.fitness.shape == (8, 32, 1)
    assert bool(out.valid.all())
    # migration happened: run until some island contains a genome it could
    # only plausibly have gotten via improvement + migration pressure
    key = jax.random.key(4)
    for i in range(5):
        out = step(jax.random.fold_in(key, i), out)
    assert float(out.fitness.max()) >= float(pops.fitness.max())


def test_sharded_matches_local_semantics():
    """Same seed, same config: the mesh version must compute the same
    *kind* of result (shapes/validity), and local demes stay independent
    between migrations."""
    length = 16
    tb = _toolbox(length)
    pops = island_init(jax.random.key(5), 8, 16,
                       ops.bernoulli_genome(length), FitnessSpec((1.0,)))
    from deap_tpu.algorithms import evaluate_invalid
    pops = jax.vmap(lambda p: evaluate_invalid(p, tb.evaluate))(pops)
    mesh = population_mesh(8, ("island",))
    step_local = make_island_step(tb, cxpb=0.6, mutpb=0.3, freq=2, mig_k=1)
    step_mesh = make_island_step(tb, cxpb=0.6, mutpb=0.3, freq=2, mig_k=1,
                                 mesh=mesh)
    out_local = step_local(jax.random.key(6), pops)
    out_mesh = step_mesh(jax.random.key(6), shard_population(pops, mesh, "island"))
    assert out_local.fitness.shape == out_mesh.fitness.shape
    assert bool(out_mesh.valid.all()) and bool(out_local.valid.all())


def test_mig_ring_migarray_topology():
    """migarray routes deme i's emigrants to deme migarray[i] — the
    reference contract (migration.py:29-30) on the stacked-deme tensor:
    default None must equal the explicit serial ring, and an arbitrary
    permutation must deliver each deme's best row to its target."""
    import jax.numpy as jnp
    import numpy as np

    from deap_tpu import ops
    from deap_tpu.core.fitness import FitnessSpec
    from deap_tpu.core.population import init_population
    from deap_tpu.algorithms import evaluate_invalid
    from deap_tpu.parallel import island_init, mig_ring

    n_demes, size, L = 4, 6, 8
    pops = island_init(jax.random.key(0), n_demes, size,
                       ops.bernoulli_genome(L), FitnessSpec((1.0,)))
    pops = jax.vmap(
        lambda p: evaluate_invalid(p, lambda g: g.sum(-1).astype(jnp.float32))
    )(pops)

    # make every deme's fitness values globally distinct so routing
    # errors cannot hide behind ties: deme d's rows live in [100d, 100d+L]
    offsets = 100.0 * jnp.arange(n_demes, dtype=jnp.float32)
    pops = pops.replace(fitness=pops.fitness + offsets[:, None, None])

    ring = mig_ring(jax.random.key(1), pops, k=1)
    explicit = mig_ring(jax.random.key(1), pops, k=1,
                        migarray=[1, 2, 3, 0])
    np.testing.assert_array_equal(np.asarray(ring.fitness),
                                  np.asarray(explicit.fitness))

    # arbitrary permutation: 0→2, 1→0, 2→3, 3→1. With sel_best/k=1 and
    # default replacement, deme dst's best row is overwritten by deme
    # src's best value — compute the full expected arrays in numpy.
    migarray = [2, 0, 3, 1]
    f = np.asarray(pops.fitness[:, :, 0])
    expect = f.copy()
    for src, dst in enumerate(migarray):
        expect[dst, f[dst].argmax()] = f[src].max()
    out = mig_ring(jax.random.key(2), pops, k=1, migarray=migarray)
    np.testing.assert_allclose(np.asarray(out.fitness[:, :, 0]), expect)

    # non-permutation migarrays must fail loudly, not route silently
    import pytest
    with pytest.raises(ValueError):
        mig_ring(jax.random.key(3), pops, k=1, migarray=[1, 2, 1, 0])


@pytest.mark.slow
def test_weak_scaling_smoke():
    """bench_scaling's sanitized-subprocess measurement works end to
    end at n=2 in smoke sizes: both paths produce finite throughput
    rows. The full 1/2/4/8 curve (SCALING.json) is produced by
    ``python bench_scaling.py``; this guards the harness itself."""
    import importlib
    import os as _os
    import sys as _sys

    _os.environ["DEAP_TPU_SCALING_SMOKE"] = "1"
    try:
        root = _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
        _sys.path.insert(0, root)
        import bench_scaling
        importlib.reload(bench_scaling)   # pick up the smoke sizes
        row = bench_scaling.measure(2)
        assert row["n_devices"] == 2
        assert row["island_gens_per_sec"] > 0
        assert row["sp_evals_per_sec"] > 0
    finally:
        del _os.environ["DEAP_TPU_SCALING_SMOKE"]
        _sys.path.remove(root)
