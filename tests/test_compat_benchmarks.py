"""compat.benchmarks: the drop-in `from deap import benchmarks` surface.

List individuals in, fitness tuples out (reference
benchmarks/__init__.py), pure-Python decorators with the reference's
update-method protocol (benchmarks/tools.py), reference-grouping
bin2float (binary.py:20-41), and a per-evaluation MovingPeaks whose
change trigger advances on the exact eval count (movingpeaks.py:241) —
the granularity the tensor batch path deliberately trades away.
"""

import math
import random

import numpy as np
import pytest

from deap_tpu.compat import base, benchmarks, creator, tools


def test_functions_take_lists_and_return_tuples():
    assert benchmarks.sphere([1.0, 2.0]) == (5.0,)
    assert benchmarks.rastrigin([0.0, 0.0]) == (0.0,)
    out = benchmarks.zdt1([0.5] * 6)
    assert isinstance(out, tuple) and len(out) == 2
    assert all(isinstance(v, float) for v in out)
    out = benchmarks.dtlz3([0.5] * 7, 3)
    assert len(out) == 3
    out = benchmarks.kursawe([0.1, 0.2, 0.3])
    assert len(out) == 2
    v = benchmarks.shekel([5.0, 5.0], [[5.0, 5.0], [2.0, 2.0]],
                          [0.1, 0.2])
    assert len(v) == 1 and v[0] > 0

    random.seed(42)
    r1 = benchmarks.rand([0, 0])
    random.seed(42)
    assert r1 == (random.random(),)


def test_registers_as_toolbox_evaluate():
    creator.create("CBFit", base.Fitness, weights=(-1.0,))
    creator.create("CBInd", list, fitness=creator.CBFit)
    tb = base.Toolbox()
    tb.register("evaluate", benchmarks.ackley)
    ind = creator.CBInd([0.0, 0.0, 0.0])
    ind.fitness.values = tb.evaluate(ind)
    assert ind.fitness.values[0] == pytest.approx(0.0, abs=1e-5)


def test_binary_bin2float_and_building_blocks():
    dec = benchmarks.binary.bin2float(0.0, 1.0, 4)(lambda d: (sum(d),))
    assert dec([1, 1, 1, 1, 0, 0, 0, 0]) == (1.0,)
    # half-scale group: 0b1000 / 15
    assert dec([1, 0, 0, 0, 1, 1, 1, 1])[0] == pytest.approx(8 / 15 + 1.0)
    assert benchmarks.binary.trap([1, 1, 1, 1]) == 4.0
    assert benchmarks.binary.trap([0, 1, 0, 0]) == 2.0
    assert benchmarks.binary.inv_trap([0, 0, 0, 0]) == 4.0
    assert benchmarks.binary.chuang_f1([1] * 41) == (40.0,)
    assert benchmarks.binary.royal_road1([1] * 16, 4) == (16.0,)
    # R2 = R1(order 4) + R1(order 8) = 16 + 16 (reference-verified)
    assert benchmarks.binary.royal_road2([1] * 16, 4) == (32.0,)


def test_gp_targets_return_floats():
    v = benchmarks.gp.kotanchek([1.0, 2.0])
    assert isinstance(v, float)
    assert benchmarks.gp.salustowicz_1d([0.0]) == pytest.approx(0.0)


def test_tools_decorators_reference_semantics():
    evaluate = lambda ind: (sum(ind),)

    ev = benchmarks.tools.translate([1.0, 2.0])(evaluate)
    assert ev([1.0, 2.0]) == (0.0,)
    ev.translate([0.0, 0.0])
    assert ev([1.0, 2.0]) == (3.0,)

    ev = benchmarks.tools.scale([2.0, 4.0])(evaluate)
    assert ev([2.0, 4.0]) == (2.0,)

    rot = [[0.0, -1.0], [1.0, 0.0]]  # 90 degrees
    ev = benchmarks.tools.rotate(rot)(lambda ind: (ind[0],))
    assert ev([3.0, 7.0])[0] == pytest.approx(7.0)

    ev = benchmarks.tools.noise(lambda: 0.25)(evaluate)
    assert ev([1.0]) == (1.25,)
    ev.noise(None)
    assert ev([1.0]) == (1.0,)


def test_tools_metrics_on_individuals():
    creator.create("CBFit2", base.Fitness, weights=(-1.0, -1.0))
    creator.create("CBInd2", list, fitness=creator.CBFit2)
    pop = []
    for vals in [(1.0, 3.0), (2.0, 2.0), (3.0, 1.0)]:
        ind = creator.CBInd2([0.0])
        ind.fitness.values = vals
        pop.append(ind)
    assert benchmarks.tools.hypervolume(pop, ref=[4.0, 4.0]) == \
        pytest.approx(6.0)
    d = benchmarks.tools.diversity(pop, (0.0, 4.0), (4.0, 0.0))
    assert 0.0 <= d <= 1.0
    c = benchmarks.tools.convergence(pop, [[1.0, 3.0], [3.0, 1.0]])
    assert c == pytest.approx(math.sqrt(2) / 3)
    assert benchmarks.tools.igd([[1, 1]], [[0, 0], [2, 2]]) == \
        pytest.approx(math.sqrt(2))


def test_movingpeaks_per_eval_granularity():
    mp = benchmarks.movingpeaks.MovingPeaks(
        dim=2, seed=3, period=5,
        **{k: v for k, v in benchmarks.movingpeaks.SCENARIO_1.items()
           if k != "period"})
    h0 = np.asarray(mp.state.height).copy()
    for _ in range(4):
        mp([50.0, 50.0])
    # 4 evals: no change yet — per-eval counter, not batch granularity
    np.testing.assert_allclose(np.asarray(mp.state.height), h0)
    mp([50.0, 50.0])
    assert mp.nevals == 5
    assert not np.allclose(np.asarray(mp.state.height), h0)

    gm_val, gm_pos = mp.globalMaximum()
    maxima = mp.maximums()
    # sorted descending, global maximum first (ref movingpeaks.py:193)
    vals = [v for v, _ in maxima]
    assert vals == sorted(vals, reverse=True)
    assert gm_val == pytest.approx(vals[0], rel=1e-6)
    assert len(gm_pos) == 2
    assert mp.offlineError() > 0

    n = mp.nevals
    out = mp([50.0, 50.0], count=False)
    assert isinstance(out, tuple) and mp.nevals == n  # state untouched

    mp.changePeaks()
    assert mp.currentError() == float("inf")


def test_movingpeaks_global_maximum_uses_peak_own_value():
    """globalMaximum/maximums must report pfunc(pos, pos, h, w) — the
    peak's own value (ref movingpeaks.py:190, 204) — not the raw
    height. sphere_peak's own value is 0 regardless of height, the
    case a height shortcut gets wrong."""
    mp = benchmarks.movingpeaks.MovingPeaks(
        dim=2, seed=1, npeaks=4,
        pfunc=benchmarks.movingpeaks.sphere_peak)
    val, pos = mp.globalMaximum()
    assert val == pytest.approx(0.0, abs=1e-6)
    assert all(v == pytest.approx(0.0, abs=1e-6)
               for v, _ in mp.maximums())
    # cone: own value == height, so the shortcut and the real thing
    # agree — pin that the value still matches the raw height there
    mp2 = benchmarks.movingpeaks.MovingPeaks(
        dim=2, seed=1, npeaks=4, pfunc=benchmarks.movingpeaks.cone)
    val2, _ = mp2.globalMaximum()
    assert val2 == pytest.approx(float(np.asarray(mp2.state.height).max()))
