"""Program cost/memory observatory + flight recorder contracts.

Four pins:

1. **Bit-identity** — the full third observability layer (program
   observatory + metrics registry + flight recorder at trace_every
   cadence) produces populations/logbooks identical to the untouched
   loop: the AOT-compiled executable IS the program jit would build.
2. **Program profiles** — every compiled segment program journals a
   ``program_profile`` event with flops/bytes, memory analysis and an
   HLO fingerprint; donating (plan-compiled) programs show **nonzero
   aliased bytes** — the PR 8 donation contract proven per program.
3. **hlo_drift** — recompiling the same (label, input signature) to a
   different HLO (a silent retrace: same shapes, changed closure)
   fires the HealthMonitor ``hlo_drift`` alarm and journals it.
4. **Flight recorder** — ``ResilientRun(trace_every=k)`` leaves xplane
   trace dirs and pprof memory snapshots under the run dir and
   journals ``flight_trace`` / ``device_memory`` events.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.parallel import ShardingPlan
from deap_tpu.resilience import ResilientRun
from deap_tpu.telemetry import (ProgramObservatory, RunJournal,
                                observatory, read_journal)
from deap_tpu.telemetry.costs import instrument
from deap_tpu.telemetry.metrics import MetricsRegistry
from deap_tpu.telemetry.probes import HealthMonitor

NGEN = 10
SEG = 4  # not dividing NGEN: exercises the short-tail program too


def _toolbox(indpb=0.1):
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=indpb)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def _pop(seed=0, n=64, length=16):
    return init_population(jax.random.key(seed), n,
                           ops.bernoulli_genome(length),
                           FitnessSpec((1.0,)))


# ------------------------------------------------------- bit identity ----

def test_full_observability_layer_bit_identical(tmp_path):
    tb = _toolbox()
    pop = _pop()
    key = jax.random.key(42)
    ref_pop, ref_log, _ = algorithms.ea_simple(key, pop, tb, 0.5, 0.2,
                                               NGEN)

    jpath = str(tmp_path / "run.jsonl")
    reg = MetricsRegistry()
    with RunJournal(jpath) as journal:
        with ProgramObservatory(journal=journal) as obs:
            res = ResilientRun(str(tmp_path / "ck"), segment_len=SEG,
                               trace_every=2, metrics=reg)
            got_pop, got_log, _ = res.ea_simple(key, pop, tb, 0.5, 0.2,
                                                NGEN)

    np.testing.assert_array_equal(np.asarray(ref_pop.genomes),
                                  np.asarray(got_pop.genomes))
    np.testing.assert_array_equal(np.asarray(ref_pop.fitness),
                                  np.asarray(got_pop.fitness))
    assert len(ref_log) == len(got_log)
    for ra, rb in zip(ref_log, got_log):
        for k in ra:
            np.testing.assert_array_equal(np.asarray(ra[k]),
                                          np.asarray(rb[k]))

    # the layer observed itself into the journal
    rows = read_journal(jpath)
    kinds = {e.get("kind") for e in rows}
    assert "program_profile" in kinds
    assert "flight_trace" in kinds
    assert "device_memory" in kinds
    # two xs shapes (full segment + short tail) → >= 2 programs
    profiles = [e for e in rows if e.get("kind") == "program_profile"]
    assert len(profiles) >= 2
    for p in profiles:
        assert p["label"] == "resilient_ea_simple"
        assert isinstance(p.get("hlo_hash"), str) and p["hlo_hash"]
        assert p.get("compile_s", 0) > 0
        assert isinstance(p.get("flops"), (int, float))
    assert len({p["hlo_hash"] for p in profiles}) >= 2
    # no drift: distinct signatures are legitimate distinct programs
    assert not obs.drifts
    # the metrics registry saw the segments
    assert "deap_resilience_segment_seconds_bucket" in reg.metrics_text()


def test_flight_recorder_artifacts(tmp_path):
    tb = _toolbox()
    with ProgramObservatory():
        res = ResilientRun(str(tmp_path / "ck"), segment_len=SEG,
                           trace_every=2)
        res.ea_simple(jax.random.key(1), _pop(), tb, 0.5, 0.2, NGEN)
    flight = str(tmp_path / "ck" / "flight")
    assert os.path.isdir(flight)
    entries = sorted(os.listdir(flight))
    # 3 segments (4+4+2), trace_every=2 → traces of segments 0 and 2
    assert [e for e in entries if e.startswith("seg_")]
    assert [e for e in entries if e.startswith("mem_")
            and e.endswith(".pprof.gz")]
    # every traced segment dir holds a real xplane capture
    for seg in (e for e in entries if e.startswith("seg_")):
        found = []
        for root, _dirs, files in os.walk(os.path.join(flight, seg)):
            found.extend(files)
        assert found, f"empty trace dir {seg}"


def test_trace_every_validation(tmp_path):
    with pytest.raises(ValueError):
        ResilientRun(str(tmp_path / "ck"), trace_every=0)


# -------------------------------------------------- donation contract ----

def test_donating_program_reports_aliased_bytes(tmp_path):
    tb = _toolbox()
    plan = ShardingPlan.for_population(1)
    with ProgramObservatory() as obs:
        res = ResilientRun(str(tmp_path / "ck"), segment_len=SEG,
                           plan=plan)
        got, _, _ = res.ea_simple(jax.random.key(3), _pop(), tb, 0.5,
                                  0.2, NGEN)
    ref, _, _ = algorithms.ea_simple(jax.random.key(3), _pop(), tb,
                                     0.5, 0.2, NGEN)
    np.testing.assert_array_equal(np.asarray(ref.genomes),
                                  np.asarray(got.genomes))
    donating = [p for p in obs.profiles if p["donating"]]
    assert donating, "plan-compiled segment programs must tag donating"
    for p in donating:
        assert p.get("aliased_bytes", 0) > 0, (
            "donating generation-step program shows zero aliased "
            f"bytes: {p}")


# ---------------------------------------------------------- hlo drift ----

def test_hlo_drift_alarm_fires_on_forced_retrace(tmp_path):
    """The silent-retrace regression: same label, same input
    signature, different program (a changed closure — here a mutated
    toolbox operator) → hlo_drift through the HealthMonitor and the
    journal."""
    jpath = str(tmp_path / "drift.jsonl")
    mon = HealthMonitor(early_stop=("hlo_drift",))
    x = jnp.arange(8.0)
    with RunJournal(jpath) as journal:
        with ProgramObservatory(journal=journal, health=mon) as obs:
            f1 = instrument(jax.jit(lambda v: v * 2.0), "gen_step")
            f1(x)
            # the "retrace": a rebuilt program under the SAME label
            # with the SAME signature but different math
            f2 = instrument(jax.jit(lambda v: v * 3.0), "gen_step")
            f2(x)
    assert len(obs.profiles) == 2
    assert len(obs.drifts) == 1
    drift = obs.drifts[0]
    assert drift["alarm"] == "hlo_drift"
    assert drift["program"] == "gen_step"
    assert drift["prev_hlo_hash"] != drift["hlo_hash"]
    # HealthMonitor recorded it and honoured early_stop
    assert mon.alarms and mon.alarms[0]["alarm"] == "hlo_drift"
    assert mon.stop_requested
    assert "hlo_drift" in HealthMonitor.ALARM_KINDS
    rows = read_journal(jpath)
    alarms = [e for e in rows if e.get("kind") == "alarm"]
    assert alarms and alarms[0]["alarm"] == "hlo_drift"


def test_no_drift_for_identical_recompile():
    """The same program rebuilt identically is NOT drift."""
    x = jnp.arange(8.0)
    with ProgramObservatory() as obs:
        instrument(jax.jit(lambda v: v * 2.0), "stable")(x)
        instrument(jax.jit(lambda v: v * 2.0), "stable")(x)
    assert len(obs.profiles) == 2
    assert not obs.drifts


def test_distinct_signatures_are_not_drift():
    """A new input shape is a legitimate new program, never drift."""
    with ProgramObservatory() as obs:
        f = instrument(jax.jit(lambda v: v + 1), "shapes")
        f(jnp.arange(8.0))
        f(jnp.arange(16.0))
    assert len(obs.profiles) == 2
    assert not obs.drifts


# ----------------------------------------------------- wrapper hygiene ----

def test_inactive_observatory_is_passthrough():
    assert observatory() is None
    calls = []
    jitted = jax.jit(lambda v: v * 2)
    f = instrument(jitted, "idle")
    out = f(jnp.arange(4.0))
    np.testing.assert_array_equal(np.asarray(out), [0.0, 2.0, 4.0, 6.0])
    assert not calls
    # attribute passthrough: the AOT entry points still reachable
    assert f.lower is jitted.lower


def test_signature_cache_compiles_once_per_shape():
    with ProgramObservatory() as obs:
        f = instrument(jax.jit(lambda v: v * 2), "cached")
        for _ in range(4):
            f(jnp.arange(8.0))
    assert len(obs.profiles) == 1


def test_static_args_stripped_for_compiled_call():
    with ProgramObservatory() as obs:
        f = instrument(
            jax.jit(lambda v, k: v[:k], static_argnames=("k",)),
            "static", static_argnames=("k",))
        out = f(jnp.arange(8.0), k=3)
        np.testing.assert_array_equal(np.asarray(out), [0.0, 1.0, 2.0])
        out = f(jnp.arange(8.0), k=5)  # new static value → new program
        assert out.shape == (5,)
    assert len(obs.profiles) == 2


def test_instrumented_callable_under_enclosing_trace():
    """Invoked inside another jit there is no standalone executable:
    the wrapper must inline transparently and profile nothing."""
    with ProgramObservatory() as obs:
        inner = instrument(jax.jit(lambda v: v * 2), "inner")
        outer = jax.jit(lambda v: inner(v) + 1)
        out = outer(jnp.arange(4.0))
        np.testing.assert_array_equal(np.asarray(out),
                                      [1.0, 3.0, 5.0, 7.0])
        # the enclosing-trace bypass must not poison later top-level
        # calls: those still profile
        inner(jnp.arange(4.0))
    assert [p["label"] for p in obs.profiles] == ["inner"]


def test_broken_aot_path_falls_back(tmp_path):
    """A callable without .lower must not break under observation —
    journal the error, keep executing."""
    jpath = str(tmp_path / "err.jsonl")
    with RunJournal(jpath) as journal:
        with ProgramObservatory(journal=journal):
            f = instrument(lambda v: v * 2, "plainfn")
            assert f(3) == 6
            assert f(4) == 8  # broken flag short-circuits thereafter
    rows = read_journal(jpath)
    assert any(e.get("kind") == "program_profile_error" for e in rows)


# ------------------------------------------------------ report planes ----

def test_report_renders_observability_planes(tmp_path):
    """--health renders the program cost table, the scheduler SLO
    summary and the device-memory sparkline from the new journal
    kinds."""
    from deap_tpu.telemetry import report

    jpath = str(tmp_path / "obs.jsonl")
    with RunJournal(jpath) as j:
        j.header(init_backend=False)
        j.event("program_profile", label="plan/resilient_ea_simple",
                hlo_hash="abcd1234ef", compile_s=1.25, donating=True,
                flops=1e9, bytes_accessed=4.2e8, argument_bytes=1000,
                output_bytes=1000, temp_bytes=64, aliased_bytes=960)
        j.event("program_profile", label="serving/ea_simple/advance",
                hlo_hash="ffff000011", compile_s=0.5, donating=False,
                flops=2e6, bytes_accessed=1e6, aliased_bytes=0)
        for i in range(4):
            j.event("slo", bucket="ea_simple:onemax", lanes=2,
                    residents=2, queue_depth=2 - i // 2,
                    occupancy=1.0, gens_advanced=6,
                    segment_s=0.1 + 0.01 * i, gens_per_sec=60.0 - i)
            j.event("device_memory", step=3 * (i + 1),
                    live_bytes={"cpu": 1000 + 100 * i})
        j.event("flight_trace", lo=0, hi=3, dir="/tmp/fl/seg_000000")
        j.event("tenant_evicted", tenant_id="t1", gen=3)
        j.event("alarm", alarm="hlo_drift",
                program="plan/resilient_ea_simple",
                prev_hlo_hash="abcd1234ef", hlo_hash="deadbeef00",
                prev_flops=1e9, flops=2e9,
                prev_bytes_accessed=4.2e8, bytes_accessed=8e8)
        j.summary()
    text = report.render_report(jpath)
    assert "## Programs (2 compiled)" in text
    assert "plan/resilient_ea_simple" in text
    assert "MiB" in text  # bytes humanised
    assert "## Scheduler SLO" in text
    assert "queue depth" in text and "occupancy" in text
    assert "gens/s" in text
    assert "p50=" in text and "p99=" in text
    assert "## Flight recorder" in text
    assert "device memory" in text
    assert "xplane trace of segment [0, 3)" in text
    assert "hlo_drift" in text and "silent retrace" in text
    assert "1 eviction(s)" in text
