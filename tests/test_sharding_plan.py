"""Mesh-native ShardingPlan + elastic resharded resume — pinned.

The acceptance bar of the sharding plan (ISSUE 8): a plan-compiled loop
computes bit-identical results on ANY mesh size (sharding is layout,
not semantics), so an n=8-mesh checkpoint restores and continues on
n=4 and n=1 — populations, logbooks, hall of fames and strategy states
bit-exact against the uninterrupted n=8 run — for ea_simple, CMA and
the island family. Plus: the per-shard v3 checkpoint layout, the
corrupt-shard fallback, the loud ``sharding_fallback`` journaling on a
jax without pjit support, the nd-sort / GP plan hooks, and the batched
Jacobi eigh that unblocks the CMA serving bucket (solo == vmapped
bit-identity). Runs on the 8-virtual-device CPU mesh from conftest.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.parallel import ShardingPlan, sharding_mode
from deap_tpu.parallel import island_init, make_island_step
from deap_tpu.parallel import mesh as mesh_mod
from deap_tpu.resilience import FaultPlan, KillAt, ResilientRun
from deap_tpu.resilience.faultinject import InjectedCrash
from deap_tpu.strategies import cma

NGEN = 9
SEG = 3


def _toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def _pop(n=64, length=16, seed=0):
    return init_population(jax.random.key(seed), n,
                           ops.bernoulli_genome(length),
                           FitnessSpec((1.0,)))


def _assert_pop_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.genomes),
                                  np.asarray(b.genomes))
    np.testing.assert_array_equal(np.asarray(a.fitness),
                                  np.asarray(b.fitness))
    np.testing.assert_array_equal(np.asarray(a.valid),
                                  np.asarray(b.valid))


def _assert_logbook_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert set(ra) == set(rb)
        for k in ra:
            np.testing.assert_array_equal(np.asarray(ra[k]),
                                          np.asarray(rb[k]))


# ------------------------------------------------------------- the plan ----

def test_plan_leaf_rule_and_placement():
    plan = ShardingPlan.for_population(8, donate=False)
    assert plan.n_shards == 8 and plan.mode == "pjit"
    pop_rows = jnp.zeros((64, 16))
    odd = jnp.zeros((6, 6))
    scalar = jnp.float32(1.0)
    key = jax.random.key(0)
    assert plan.leaf_sharding(pop_rows).spec == plan.spec("pop")
    assert plan.leaf_sharding(odd).spec == plan.spec()
    assert plan.leaf_sharding(scalar).spec == plan.spec()
    assert plan.leaf_sharding(key).spec == plan.spec()
    placed = plan.place({"a": pop_rows, "b": odd, "n": 3})
    assert placed["a"].sharding.spec == plan.spec("pop")
    assert placed["b"].sharding.spec == plan.spec()
    assert placed["n"] == 3
    d = plan.describe()
    assert d["n_devices"] == 8 and d["axes"] == ["pop"]


def test_plan_place_fresh_copy_survives_donation():
    """A donating compile deletes its argument buffers; ``place`` must
    hand it copies, never the caller's array."""
    plan = ShardingPlan.for_population(8)  # donate=True default
    x = jnp.arange(64.0)
    placed = plan.place(plan.place(x))  # second place would alias
    f = plan.compile(lambda a: a + 1, donate_argnums=(0,))
    f(placed)
    assert not x.is_deleted()


def test_plan_compiled_loop_bit_identical_across_mesh_sizes():
    """The core property everything else rests on: the same global
    program computes the same bits on n=1/2/4/8 shards."""
    tb, pop, key = _toolbox(), _pop(), jax.random.key(1)
    ref, lb_ref, hof_ref = algorithms.ea_simple(
        key, pop, tb, 0.5, 0.2, ngen=NGEN, halloffame_size=4)
    for nd in (8, 4, 1):
        got, lb, hof = algorithms.ea_simple(
            key, pop, tb, 0.5, 0.2, ngen=NGEN, halloffame_size=4,
            plan=ShardingPlan.for_population(nd))
        _assert_pop_equal(ref, got)
        _assert_logbook_equal(lb_ref, lb)
        np.testing.assert_array_equal(np.asarray(hof_ref.fitness),
                                      np.asarray(hof.fitness))
    assert not pop.fitness.is_deleted()  # donation never ate the input


def test_plan_mu_loops_bit_identical():
    tb, pop, key = _toolbox(), _pop(), jax.random.key(2)
    plan = ShardingPlan.for_population(8)
    p1, lb1, _ = algorithms.ea_mu_plus_lambda(
        key, pop, tb, 64, 128, 0.4, 0.3, ngen=NGEN)
    p2, lb2, _ = algorithms.ea_mu_plus_lambda(
        key, pop, tb, 64, 128, 0.4, 0.3, ngen=NGEN, plan=plan)
    _assert_pop_equal(p1, p2)
    _assert_logbook_equal(lb1, lb2)
    p1, lb1, _ = algorithms.ea_mu_comma_lambda(
        key, pop, tb, 64, 128, 0.4, 0.3, ngen=NGEN)
    p2, lb2, _ = algorithms.ea_mu_comma_lambda(
        key, pop, tb, 64, 128, 0.4, 0.3, ngen=NGEN, plan=plan)
    _assert_pop_equal(p1, p2)
    _assert_logbook_equal(lb1, lb2)


# ------------------------------------------------------- elastic resume ----

def _elastic_chain(run_factory, result_cmp, tmp_path):
    """Drive ``run_factory(plan, fault_plan, dir)`` through the n=8 →
    n=4 → n=1 kill/resume chain and compare against the uninterrupted
    n=8 run with ``result_cmp(ref, got)``."""
    ref = run_factory(ShardingPlan.for_population(8), None,
                      str(tmp_path / "ref"))
    d = str(tmp_path / "chain")
    with pytest.raises(InjectedCrash):
        run_factory(ShardingPlan.for_population(8),
                    FaultPlan([KillAt(3, when="after_save")]), d)
    with pytest.raises(InjectedCrash):
        run_factory(ShardingPlan.for_population(4),
                    FaultPlan([KillAt(6, when="after_save")]), d)
    got = run_factory(ShardingPlan.for_population(1), None, d)
    result_cmp(ref, got)


def test_elastic_resume_ea_simple(tmp_path):
    tb, pop, key = _toolbox(), _pop(), jax.random.key(3)

    def run(plan, fault_plan, d):
        return ResilientRun(d, segment_len=SEG, plan=plan,
                            fault_plan=fault_plan).ea_simple(
            key, pop, tb, 0.5, 0.2, ngen=NGEN, halloffame_size=4)

    def cmp(ref, got):
        _assert_pop_equal(ref[0], got[0])
        _assert_logbook_equal(ref[1], got[1])
        np.testing.assert_array_equal(np.asarray(ref[2].fitness),
                                      np.asarray(got[2].fitness))
        np.testing.assert_array_equal(np.asarray(ref[2].genomes),
                                      np.asarray(got[2].genomes))

    _elastic_chain(run, cmp, tmp_path)


def test_elastic_resume_cma(tmp_path):
    strat = cma.Strategy(centroid=[0.0] * 6, sigma=0.5, lambda_=16)
    tb = Toolbox()
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    tb.register("evaluate", lambda g: -jnp.sum(g ** 2, axis=-1))
    key = jax.random.key(4)

    def run(plan, fault_plan, d):
        return ResilientRun(d, segment_len=SEG, plan=plan,
                            fault_plan=fault_plan).ea_generate_update(
            key, strat.initial_state(), tb, ngen=NGEN, spec=strat.spec,
            halloffame_size=3)

    def cmp(ref, got):
        for a, b in zip(jax.tree_util.tree_leaves(ref[0]),
                        jax.tree_util.tree_leaves(got[0])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        _assert_logbook_equal(ref[1], got[1])
        np.testing.assert_array_equal(np.asarray(ref[2].fitness),
                                      np.asarray(got[2].fitness))

    _elastic_chain(run, cmp, tmp_path)


def test_elastic_resume_island(tmp_path):
    """The island family: migration is a deme-axis roll the partitioner
    reshards — one global program, so the epoch step rebuilt on a
    SMALLER plan continues the n=8 run bit-exactly."""
    tb = _toolbox()
    pops0 = island_init(jax.random.key(2), 8, 16,
                        ops.bernoulli_genome(16), FitnessSpec((1.0,)))
    pops0 = jax.vmap(lambda p: algorithms.evaluate_invalid(
        p, tb.evaluate))(pops0)
    key = jax.random.key(7)

    def run(n_devices, fault_plan, d):
        plan = ShardingPlan.for_islands(n_devices)
        step = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=2,
                                mig_k=1, plan=plan)
        return ResilientRun(d, segment_len=2, plan=plan,
                            fault_plan=fault_plan).island_run(
            step, key, pops0, 8)

    def cmp(ref, got):
        _assert_pop_equal(ref, got)

    # island KillAt fires on epochs: kill at 4 then at 6
    ref = run(8, None, str(tmp_path / "r"))
    d = str(tmp_path / "chain")
    with pytest.raises(InjectedCrash):
        run(8, FaultPlan([KillAt(4, when="after_save")]), d)
    with pytest.raises(InjectedCrash):
        run(4, FaultPlan([KillAt(6, when="after_save")]), d)
    got = run(1, None, d)
    cmp(ref, got)
    # and the plan path equals the plain single-device step
    step_plain = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=2,
                                  mig_k=1)
    plain = pops0
    for epoch in range(8):
        plain = step_plain(jax.random.fold_in(key, epoch), plain)
    cmp(plain, got)


def test_elastic_resume_journals_mesh_change(tmp_path):
    from deap_tpu.telemetry import RunTelemetry, read_journal

    tb, pop, key = _toolbox(), _pop(), jax.random.key(5)
    d = str(tmp_path / "ck")
    with pytest.raises(InjectedCrash):
        ResilientRun(d, segment_len=SEG,
                     plan=ShardingPlan.for_population(8),
                     fault_plan=FaultPlan([KillAt(3, when="after_save")])).ea_simple(
            key, pop, tb, 0.5, 0.2, ngen=NGEN)
    jpath = str(tmp_path / "journal.jsonl")
    with RunTelemetry(jpath) as tel:
        ResilientRun(d, segment_len=SEG, telemetry=tel,
                     plan=ShardingPlan.for_population(4)).ea_simple(
            key, pop, tb, 0.5, 0.2, ngen=NGEN)
    rows = read_journal(jpath)
    elastic = [r for r in rows if r.get("kind") == "elastic_resume"]
    assert len(elastic) == 1
    assert elastic[0]["from_mesh"]["n_devices"] == 8
    assert elastic[0]["to_mesh"]["n_devices"] == 4


# ------------------------------------------- v3 checkpoint shard layout ----

def test_checkpoint_v3_per_shard_layout(tmp_path):
    from deap_tpu.support.checkpoint import (_SHARD_TAG, _pack_leaf,
                                             restore_state, save_state)

    plan = ShardingPlan.for_population(8, donate=False)
    x = jnp.arange(64.0).reshape(16, 4)
    placed = plan.place(x)
    packed = _pack_leaf(placed)
    assert packed[_SHARD_TAG] and len(packed["shards"]) == 8
    assert _pack_leaf(packed) is packed  # idempotent (async writer)
    path = str(tmp_path / "ck.pkl")
    save_state(path, {"pop": placed, "k": jax.random.key(1)},
               meta={"mesh": plan.describe()})
    got = restore_state(path)
    np.testing.assert_array_equal(np.asarray(got["pop"]), np.asarray(x))
    # replicated leaves stay monolithic
    rep = _pack_leaf(plan.place(jnp.zeros(6)))
    assert isinstance(rep, np.ndarray)


def test_checkpoint_corrupt_shard_falls_back(tmp_path):
    """A flipped byte inside a sharded leaf must fail the CRC →
    CheckpointCorruptError → Checkpointer falls back to the previous
    valid step, exactly like any other corruption."""
    from deap_tpu.support.checkpoint import Checkpointer

    plan = ShardingPlan.for_population(8, donate=False)
    ck = Checkpointer(str(tmp_path / "ck"), keep=3)
    s1 = {"pop": plan.place(jnp.arange(64.0).reshape(16, 4)), "gen": 1}
    s2 = {"pop": plan.place(jnp.arange(64.0).reshape(16, 4) * 2),
          "gen": 2}
    ck.save(1, s1)
    path2 = ck.save(2, s2)
    with open(path2, "r+b") as fh:  # flip a byte mid-payload
        fh.seek(os.path.getsize(path2) // 2)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0xFF]))
    step, state = ck.restore_latest()
    assert step == 1 and state["gen"] == 1


# ----------------------------------------------------- fallback journal ----

def test_sharding_fallback_is_journaled(tmp_path, monkeypatch):
    """On a jax without the pjit plan, the plan must select the
    shard_map/plain path LOUDLY: a ``sharding_fallback`` event in every
    open journal, and the computation still runs."""
    from deap_tpu.telemetry import RunTelemetry, read_journal

    monkeypatch.setattr(mesh_mod, "_MODE_CACHE", ["shard_map"])
    monkeypatch.setattr(mesh_mod, "_FALLBACK_SEEN", set())
    jpath = str(tmp_path / "journal.jsonl")
    tb, pop, key = _toolbox(), _pop(32, 8), jax.random.key(6)
    with RunTelemetry(jpath) as tel:  # noqa: F841 — open journal
        plan = ShardingPlan.for_population(2)
        assert plan.mode == "shard_map"
        p1, _, _ = algorithms.ea_simple(key, pop, tb, 0.5, 0.2, ngen=3,
                                        plan=plan)
        # island builder selects the shard_map path under the same plan
        step = make_island_step(_toolbox(), cxpb=0.5, mutpb=0.2,
                                freq=1, mig_k=1,
                                plan=ShardingPlan.for_islands(2))
    rows = read_journal(jpath)
    kinds = [r for r in rows
             if r.get("kind") == "sharding_fallback"]
    wheres = {r["where"] for r in kinds}
    assert "ShardingPlan" in wheres
    assert "make_island_step" in wheres
    # degraded, not wrong: same results as the plain loop
    p2, _, _ = algorithms.ea_simple(key, pop, tb, 0.5, 0.2, ngen=3)
    _assert_pop_equal(p1, p2)


def test_sharding_mode_detects_pjit_on_pinned_jax():
    assert sharding_mode() == "pjit"


# ------------------------------------------------- nd-sort and GP hooks ----

def test_nd_rank_plan_parity():
    from deap_tpu.mo.emo import nd_rank

    w = jax.random.normal(jax.random.key(8), (256, 3))
    plan = ShardingPlan.for_population(8, donate=False)
    for impl in ("matrix", "dc"):
        ref = np.asarray(nd_rank(w, impl=impl))
        got = np.asarray(nd_rank(plan.place(w, fresh=False), impl=impl,
                                 plan=plan))
        np.testing.assert_array_equal(ref, got)


def test_gp_loop_plan_parity():
    import deap_tpu.gp as gp
    from deap_tpu.gp.loop import make_symbreg_loop

    ps = gp.math_set(n_args=1)
    X = jnp.linspace(-1.0, 1.0, 32, endpoint=False)[:, None]
    y = X[:, 0] ** 3 + X[:, 0]
    genomes = jax.vmap(gp.gen_half_and_half(ps, 48, 1, 2))(
        jax.random.split(jax.random.key(3), 128))
    ref = make_symbreg_loop(ps, 48, X, y, height_limit=6)(
        jax.random.key(9), genomes, 4)
    plan = ShardingPlan.for_population(8, donate=False)
    got = make_symbreg_loop(ps, 48, X, y, height_limit=6, plan=plan)(
        jax.random.key(9), genomes, 4)
    np.testing.assert_array_equal(np.asarray(ref["fitness"]),
                                  np.asarray(got["fitness"]))
    for k in ("nodes", "consts", "length"):
        np.testing.assert_array_equal(np.asarray(ref["genomes"][k]),
                                      np.asarray(got["genomes"][k]))
    assert ref["nevals"] == got["nevals"]


# ------------------------------------------------------- batched eigh ----

def test_eigh_jacobi_reconstructs():
    from deap_tpu.ops.linalg import eigh_jacobi

    rng = np.random.default_rng(0)
    for d in (2, 6, 8, 16):
        M = rng.normal(size=(d, d)).astype(np.float32)
        C = (M @ M.T + d * np.eye(d)).astype(np.float32)
        w, V = eigh_jacobi(jnp.asarray(C))
        w, V = np.asarray(w), np.asarray(V)
        assert np.all(np.diff(w) >= 0)  # ascending, like lapack eigh
        scale = np.abs(C).max()
        assert np.abs(V @ np.diag(w) @ V.T - C).max() <= 1e-4 * scale
        assert np.abs(V @ V.T - np.eye(d)).max() <= 1e-4
        ref = np.linalg.eigvalsh(C.astype(np.float64))
        assert np.abs(np.sort(w) - ref).max() <= 1e-4 * np.abs(ref).max()


def test_eigh_jacobi_vmap_bit_identical_to_solo():
    from deap_tpu.ops.linalg import eigh_jacobi

    rng = np.random.default_rng(1)
    Cs = []
    for _ in range(8):
        M = rng.normal(size=(6, 6)).astype(np.float32)
        Cs.append(M @ M.T + 6 * np.eye(6, dtype=np.float32))
    Cs = jnp.asarray(np.stack(Cs))
    bw, bV = jax.jit(jax.vmap(eigh_jacobi))(Cs)
    for i in range(8):
        sw, sV = eigh_jacobi(Cs[i])
        np.testing.assert_array_equal(np.asarray(sw), np.asarray(bw[i]))
        np.testing.assert_array_equal(np.asarray(sV), np.asarray(bV[i]))


def test_cma_jacobi_serving_solo_equals_batched():
    """The satellite's contract: a CMA bucket built with
    eigh_impl='jacobi' (whose eigendecomposition vectorises across
    vmapped lanes instead of looping LAPACK per lane) keeps the
    serving engine's per-lane bit-identity — solo trajectories ==
    batched trajectories, strategy state pytrees included."""
    from deap_tpu.serving.multirun import multirun

    strat = cma.Strategy(centroid=[3.0] * 6, sigma=0.5, lambda_=12,
                         eigh_impl="jacobi")
    tb = Toolbox()
    tb.register("evaluate", lambda g: (g ** 2).sum(-1))
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    states = [strat.initial_state(sigma=s) for s in (0.3, 0.5, 0.9)]
    keys = [jax.random.key(100 + r) for r in range(3)]
    ngens = [6, 4, 3]
    res = multirun("ea_generate_update", tb, keys, states, ngens,
                   segment_len=2, spec=strat.spec,
                   state_template=states[0], halloffame_size=2)
    for r in range(3):
        st, slb, sh = algorithms.ea_generate_update(
            keys[r], states[r], tb, ngens[r], spec=strat.spec,
            halloffame_size=2)
        bt, blb, bh = res[r]
        for la, lb in zip(jax.tree_util.tree_leaves(st),
                          jax.tree_util.tree_leaves(bt)):
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))
        _assert_logbook_equal(slb, blb)
        np.testing.assert_array_equal(np.asarray(sh.fitness),
                                      np.asarray(bh.fitness))


def test_cma_lapack_bucket_journals_eigh_hint(tmp_path):
    from deap_tpu.serving.multirun import MultiRunEngine
    from deap_tpu.telemetry import RunTelemetry, read_journal

    strat = cma.Strategy(centroid=[2.0] * 4, sigma=0.4, lambda_=8)
    tb = Toolbox()
    tb.register("evaluate", lambda g: (g ** 2).sum(-1))
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    jpath = str(tmp_path / "journal.jsonl")
    with RunTelemetry(jpath):
        MultiRunEngine("ea_generate_update", tb, spec=strat.spec,
                       state_template=strat.initial_state())
    rows = read_journal(jpath)
    hints = [r for r in rows
             if r.get("kind") == "serving_eigh_hint"]
    assert hints and "jacobi" in hints[0]["hint"]
