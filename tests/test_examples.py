"""Examples-as-system-tests: run the model zoo in smoke mode.

The reference's de-facto integration suite is its 40 runnable examples
(examples/speed.txt; SURVEY.md §4.5). Each example here exposes
``main(smoke=True)`` with reduced sizes; this module asserts they run
and, where cheap, that they hit a sanity threshold.

Tiering: the FULL zoo runs by default — 41 of 53 smokes silently
skipping is how a regression hides (VERDICT r3). Set
``DEAP_TPU_CORE_EXAMPLES_ONLY=1`` to restrict to the CORE subset (one
canonical program per family, ~12 programs) when iterating locally;
each example compiles several XLA programs, so the full zoo takes tens
of minutes on one CPU core. The whole module is marked ``slow``, so
``-m fast`` skips it entirely.
"""

import importlib
import os
import pathlib
import sys

import pytest

pytestmark = pytest.mark.slow

ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

# One canonical program per family: the default smoke set.
CORE = {
    "examples.ga.onemax_short",
    "examples.ga.onemax_island_sharded",
    "examples.ga.tsp",
    "examples.ga.nsga2",
    "examples.gp.symbreg",
    "examples.gp.ant",
    "examples.es.cma_minfct",
    "examples.de.basic",
    "examples.eda.pbil",
    "examples.pso.basic",
    "examples.coev.coop",
    "examples.compat_onemax",
}

EXAMPLES = [
    "examples.ga.onemax",
    "examples.ga.onemax_fused",
    "examples.ga.onemax_short",
    "examples.ga.onemax_numpy",
    "examples.ga.onemax_mp",
    "examples.ga.onemax_island",
    "examples.ga.onemax_island_sharded",
    "examples.ga.onemax_multidemic",
    "examples.ga.tsp",
    "examples.ga.knapsack",
    "examples.ga.nqueens",
    "examples.ga.kursawefct",
    "examples.ga.nsga2",
    "examples.ga.nsga2_large",
    "examples.ga.nsga3",
    "examples.ga.mo_rhv",
    "examples.ga.sortingnetwork",
    "examples.ga.evosn",
    "examples.ga.knn",
    "examples.ga.evoknn",
    "examples.ga.xkcd",
    "examples.gp.symbreg",
    "examples.gp.symbreg_harm",
    "examples.gp.symbreg_epsilon_lexicase",
    "examples.gp.adf_symbreg",
    "examples.gp.parity",
    "examples.gp.multiplexer",
    "examples.gp.spambase",
    "examples.gp.ant",
    "examples.es.fctmin",
    "examples.es.onefifth",
    "examples.es.cma_minfct",
    "examples.es.cma_plus_lambda",
    "examples.es.cma_plotting",
    "examples.es.cma_mo",
    "examples.es.cma_bipop",
    "examples.de.basic",
    "examples.de.sphere",
    "examples.de.dynamic",
    "examples.eda.pbil",
    "examples.eda.emna",
    "examples.pso.basic",
    "examples.pso.multiswarm",
    "examples.pso.speciation",
    "examples.coev.coop",
    "examples.coev.coop_evol",
    "examples.coev.hillis",
    "examples.coev.symbreg",
    "examples.bbob",
    "examples.compat_onemax",
    "examples.compat_symbreg",
    "examples.compat_nsga2",
    "examples.neuroevolution.cartpole",
]


@pytest.mark.parametrize("module_name", EXAMPLES)
def test_example_smoke(module_name):
    if (module_name not in CORE
            and os.environ.get("DEAP_TPU_CORE_EXAMPLES_ONLY")):
        pytest.skip("core-only tier (DEAP_TPU_CORE_EXAMPLES_ONLY=1)")
    mod = importlib.import_module(module_name)
    result = mod.main(smoke=True)
    assert result is not None


def test_gp_ant_native_smoke():
    from examples.gp import ant

    best = ant.main(smoke=True, native=True)
    assert best >= 0.0


def test_onemax_full_run_reaches_quality():
    """The README config (onemax_short, pop 300 ngen 40) must come close
    to the 100-bit optimum — the reference's canonical outcome."""
    from examples.ga import onemax_short

    best = onemax_short.main(smoke=False)
    assert best >= 95.0


@pytest.mark.slow
def test_nsga2_pop50k_end_to_end_quality_gate():
    """The BASELINE.json pop=50k NSGA-II config end to end (VERDICT r4
    weak #6): 20 generations at pop=50k through the exact O(n log n)
    staircase nd-sort, gated on the reference's hypervolume bar
    (>116.0 vs ref [11,11], deap/tests/test_algorithms.py:110-113).
    Measured 118.05 on this box (~0.6 s/gen on one CPU core)."""
    from examples.ga import nsga2_large

    hv = nsga2_large.main(pop=50_000, ngen=20)
    assert hv > 116.0, hv


@pytest.mark.slow
def test_tsp_gr17_reaches_reference_optimum():
    """Direct quality comparability with the reference (VERDICT r2
    missing #5): on the reference's own gr17 instance the GA must
    reach its known optimum 2085 (the full-config seeded run finds it
    exactly). Skipped where the reference tree is absent."""
    import pathlib

    gr17 = pathlib.Path("/root/reference/examples/ga/tsp/gr17.json")
    if not gr17.exists():
        pytest.skip("reference gr17 instance not available")
    from examples.ga import tsp

    best = tsp.main(smoke=False, instance=str(gr17))
    assert best == 2085.0


@pytest.mark.slow
def test_tsp_gr24_reaches_reference_optimum():
    """Same comparability gate on the larger gr24 instance: since the
    r5 memetic upgrade (shuffle kick + batched 2-opt polish,
    ops.mut_two_opt) the seeded full-config run reaches the published
    optimum 1272 (was 1347, a 5.9% gap, under pure PMX+shuffle).

    A missing reference instance FAILS this test rather than skipping
    it (VERDICT r5 weak #9: the silent skip made the repo demonstrate
    nothing on real TSPLIB data while looking green) — opt out
    explicitly with DEAP_TPU_ALLOW_MISSING_REF=1 on hosts that never
    vendored the reference tree. The quality bar is a pinned-seed
    tolerance band around the published optimum, not exact float
    equality: a platform/JAX-version RNG change may land a near-optimal
    tour, and `best == 1272.0` was flaky-by-construction."""
    import os
    import pathlib

    gr24 = pathlib.Path("/root/reference/examples/ga/tsp/gr24.json")
    if not gr24.exists():
        if os.environ.get("DEAP_TPU_ALLOW_MISSING_REF"):
            pytest.skip("reference gr24 instance not available "
                        "(DEAP_TPU_ALLOW_MISSING_REF set)")
        pytest.fail(
            f"reference TSP instance {gr24} is absent — the gr24 "
            "comparability gate cannot run. Vendor the instance or set "
            "DEAP_TPU_ALLOW_MISSING_REF=1 to acknowledge the gap "
            "explicitly (it no longer skips silently).")
    from examples.ga import tsp

    best = tsp.main(smoke=False, instance=str(gr24))
    # published optimum 1272; accept a pinned-seed band of +1.5% so a
    # platform RNG drift that lands a near-optimal tour doesn't flake,
    # while a real regression (the pre-r5 1347 = +5.9%) still fails
    assert 1272.0 <= best <= 1272.0 * 1.015, best


@pytest.mark.slow
def test_spambase_quality_on_reference_csv():
    """Typed-GP spam classification on the reference's real UCI
    spambase.csv (57 features; fixed 400-row subset — the reference
    example's per-evaluation sample size): the seeded full-config run
    measures 0.902 accuracy vs the ~0.61 majority-class baseline;
    pinned at >= 0.85. Skipped where the reference tree is absent."""
    import pathlib

    csv = pathlib.Path("/root/reference/examples/gp/spambase.csv")
    if not csv.exists():
        pytest.skip("reference spambase.csv not available")
    from examples.gp import spambase

    acc = spambase.main(smoke=False, csv_path=str(csv))
    assert acc >= 0.85, acc


@pytest.mark.slow
def test_evoknn_quality_on_reference_heart_scale():
    """Feature-selection NSGA-II on the reference's real
    heart_scale.csv (13 features, 270 rows, the evoknn fixture): the
    seeded full-config run measures 0.856 best leave-one-out accuracy
    on the front; pinned at >= 0.82. Skipped where the reference tree
    is absent."""
    import pathlib

    csv = pathlib.Path("/root/reference/examples/ga/heart_scale.csv")
    if not csv.exists():
        pytest.skip("reference heart_scale.csv not available")
    from examples.ga import evoknn

    acc = evoknn.main(smoke=False, csv_path=str(csv))
    assert acc >= 0.82, acc


def test_zoo_report_artifact_green():
    """The committed full-configuration validation artifact
    (examples/ZOO_REPORT.json, VERDICT r2 item 7) must cover the whole
    zoo and be all-green. Regenerate with
    ``python examples/speed.py --full --cpu --report
    examples/ZOO_REPORT.json``; this just keeps the artifact honest."""
    import json
    import pathlib

    path = (pathlib.Path(__file__).parent.parent / "examples"
            / "ZOO_REPORT.json")
    assert path.exists(), "examples/ZOO_REPORT.json not committed"
    report = json.loads(path.read_text())
    assert report["mode"] == "full"
    n_programs = len(EXAMPLES)
    assert report["total"] == n_programs, (report["total"], n_programs)
    bad = [r["example"] for r in report["results"] if r["ok"] is not True]
    assert not bad, f"zoo report has failures: {bad}"
    assert report["passed"] == report["total"]
