"""Child process for the 2-process multi-host test.

Usage: ``python _multihost_child.py <coordinator> <num_procs> <rank>``.
Each process exposes 4 virtual CPU devices, joins the distributed
runtime via :func:`deap_tpu.parallel.initialize`, and runs the same
SPMD program over the 8-device global mesh: one island epoch with a
cross-process ``ppermute`` migration ring, then one genome-axis-sharded
evaluation with a cross-process ``psum``. Prints ``MULTIHOST_CHILD_OK``
on success; any assertion or hang fails the parent test.
"""

import os
import sys
import time

coordinator, num_procs, rank = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

_t0 = time.perf_counter()


def _mark(phase):
    print(f"MULTIHOST_CHILD_PHASE {phase} t={time.perf_counter()-_t0:.1f}s",
          flush=True)


import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# multi-process CPU collectives need the gloo backend, selected before
# backend initialisation
jax.config.update("jax_cpu_collectives_implementation", "gloo")
# XLA compiles dominate this child's runtime on a loaded box (VERDICT
# r2 weak #6); a persistent compilation cache makes every run after the
# first cheap. Override the location with DEAP_TPU_XLA_CACHE.
_cache = os.environ.get("DEAP_TPU_XLA_CACHE",
                        "/tmp/deap_tpu_multihost_xla_cache")
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

from deap_tpu import FitnessSpec, Toolbox, ops  # noqa: E402
from deap_tpu.algorithms import evaluate_invalid  # noqa: E402
from deap_tpu.parallel import (  # noqa: E402
    genome_mesh,
    global_population_mesh,
    initialize,
    is_distributed,
    island_init,
    make_island_step,
    make_sharded_evaluator,
    process_count,
    process_index,
    shard_genomes,
    shard_population,
)

_mark("import")
initialize(coordinator, num_procs, rank)
_mark("distributed-init")
assert process_count() == num_procs, process_count()
assert process_index() == rank
assert is_distributed()
assert jax.local_device_count() == 4
assert jax.device_count() == 4 * num_procs

LENGTH = 16
tb = Toolbox()
tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
tb.register("mate", ops.cx_two_point)
tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
tb.register("select", ops.sel_tournament, tournsize=3)
spec = FitnessSpec((1.0,))

# --- island epoch over the global mesh: the migration ring's boundary
# hop crosses the process boundary (DCN analog) ---------------------------
n_islands = jax.device_count()
mesh = global_population_mesh(("island",))
pops = island_init(jax.random.key(0), n_islands, 8,
                   ops.bernoulli_genome(LENGTH), spec)
pops = jax.vmap(lambda p: evaluate_invalid(p, tb.evaluate))(pops)
pops = shard_population(pops, mesh, "island")
step = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=2, mig_k=2,
                        mesh=mesh)
out = step(jax.random.key(1), pops)
# replicated scalars are readable on every process and force the
# cross-process program to actually execute
all_valid = bool(jax.jit(lambda p: p.valid.all())(out))
best = float(jax.jit(lambda p: p.fitness.max())(out))
assert all_valid
assert 0.0 <= best <= LENGTH
_mark("island-epoch")

# --- genome-axis (SP) sharded evaluation: per-shard partial fitness
# combined with a psum that crosses the process boundary ------------------
gmesh = genome_mesh(n_pop_shards=jax.device_count() // 2,
                    n_genome_shards=2)
genomes = jax.random.bernoulli(
    jax.random.key(2), 0.5, (16, 32)).astype(jnp.float32)
evaluate = make_sharded_evaluator(lambda g: g.sum(-1), gmesh,
                                  combine="sum")
vals = evaluate(shard_genomes(genomes, gmesh))
total = float(jax.jit(jnp.sum)(vals))
expect = float(genomes.sum())
assert abs(total - expect) < 1e-3, (total, expect)

_mark("genome-shard")
print(f"MULTIHOST_CHILD_OK rank={rank} best={best} "
      f"runtime={time.perf_counter()-_t0:.1f}s")
