"""Live-population specialization parity suite.

Every dispatch specialization of the batched GP interpreter —
live-vocab masks, unique-genome dedup, opcode-major grouped mode, the
Pallas fused dispatch kernel, points tiling — must be BIT-identical to
the plain full-vocab scan interpreter; specialization is a performance
decision, never a semantics one. Also pins the mask-lattice retrace
budget (via the telemetry journal's build events), the ADF mask
composition, and the host-dispatch loop engine's algebraically-carried
depth arrays.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deap_tpu import gp
from deap_tpu.gp.interpreter import (
    _dedup_rows,
    _depths_np,
    _ends_np,
    _grouped_eval_kernel_builder,
    _round_chunks,
    _used_ops,
    build_grouped_schedule,
)
from deap_tpu.gp.tree import prefix_depths, subtree_ends_all

ML = 48


def _population(pset, seeds, min_d=1, max_d=5, ml=ML):
    gen = gp.gen_half_and_half(pset, ml, min_d, max_d)
    pop = [gen(jax.random.key(s)) for s in seeds]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *pop)


def _bloat_varying(pset, ml=ML):
    """Tiny trees, deep trees, and duplicated rows in one population —
    the shapes that exercise max_active bounding, dedup, and the
    grouped schedule's (depth, opcode) runs at once."""
    small = _population(pset, range(8), 0, 1, ml)
    deep = _population(pset, range(100, 108), 4, 6, ml)
    pop = jax.tree_util.tree_map(
        lambda a, b: jnp.concatenate([a, b, a[:4]]), small, deep)
    return pop


@pytest.fixture(scope="module")
def pset():
    ps = gp.math_set(n_args=2)
    ps.arity_table()
    return ps


@pytest.fixture(scope="module")
def X():
    return jnp.stack([jnp.linspace(-2.0, 2.0, 33),
                      jnp.linspace(0.5, 3.0, 33)], axis=1)


#: (pset id, pop fingerprint) -> reference output; the full-vocab scan
#: reference compile is the suite's long pole, so share it
_REF_CACHE: dict = {}


def _reference(pset, genomes, X, ml=ML):
    key = (id(pset), ml, genomes["nodes"].shape,
           hash(np.asarray(genomes["nodes"]).tobytes()))
    if key not in _REF_CACHE:
        ref = gp.make_batch_interpreter(pset, ml, specialize="none")
        _REF_CACHE[key] = np.asarray(jax.jit(ref)(genomes, X))
    return _REF_CACHE[key]


@pytest.mark.parametrize("kw", [
    dict(mode="scan"),
    dict(mode="grouped"),
    dict(mode="grouped", dedup=False),
    dict(mode="grouped", points_tile=10),   # non-divisible tile
    # each further variant pays its own ~10 s interpreter compile on
    # this box — exhaustive coverage rides the slow tier
    pytest.param(dict(mode="scan", dedup=False),
                 marks=pytest.mark.slow),
    pytest.param(dict(mode="sweep"), marks=pytest.mark.slow),
    pytest.param(dict(mode="grouped", chunk=16),
                 marks=pytest.mark.slow),
    pytest.param(dict(mode="scan", points_tile=16),
                 marks=pytest.mark.slow),
])
def test_specializations_bit_identical(pset, X, kw):
    genomes = _bloat_varying(pset)
    want = _reference(pset, genomes, X)
    got = np.asarray(gp.make_batch_interpreter(pset, ML, **kw)(genomes, X))
    np.testing.assert_array_equal(got, want)


def test_traced_fallback_bit_identical(pset, X):
    """Inside jit the dispatcher must fall back to the traced full
    chain (grouped included) and still match."""
    genomes = _bloat_varying(pset)
    want = _reference(pset, genomes, X)
    for mode in ("scan", "grouped"):
        f = gp.make_batch_interpreter(pset, ML, mode=mode)
        got = np.asarray(jax.jit(f)(genomes, X))
        np.testing.assert_array_equal(got, want)


def test_erc_heavy_dedup_parity(pset, X):
    """ERC-heavy trees: rows differing ONLY in constant values must not
    dedup together, and grouped's inline-constant operands must match
    the chain exactly."""
    ps = pset
    genomes = _population(ps, range(24), 1, 3)
    # duplicate every tree, then perturb the copies' ERC values
    def dup(a):
        return jnp.concatenate([a, a])
    genomes = jax.tree_util.tree_map(dup, genomes)
    is_erc = (genomes["nodes"] == ps.erc_id)
    bumped = jnp.where(is_erc, genomes["consts"] + 0.125,
                       genomes["consts"])
    genomes = dict(genomes)
    genomes["consts"] = jnp.concatenate(
        [genomes["consts"][:24], bumped[24:]])
    want = _reference(ps, genomes, X)
    for mode in ("scan", "grouped"):
        got = np.asarray(
            gp.make_batch_interpreter(ps, ML, mode=mode)(genomes, X))
        np.testing.assert_array_equal(got, want)
    first, inv = _dedup_rows(np.asarray(genomes["nodes"]),
                             np.asarray(genomes["consts"]),
                             np.asarray(genomes["length"]))
    # perturbed ERC copies are distinct genomes
    assert len(first) > 24


def test_typed_pset_parity(X):
    ps = gp.spam_set(n_features=2)
    ps.arity_table()
    gen = gp.make_generator_typed(ps, ML, 2, 4)
    pop = [gen(jax.random.key(s)) for s in range(16)]
    genomes = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *pop)
    want = _reference(ps, genomes, X)
    for kw in (dict(mode="scan"), dict(mode="grouped"),
               dict(mode="sweep")):
        got = np.asarray(
            gp.make_batch_interpreter(ps, ML, **kw)(genomes, X))
        np.testing.assert_array_equal(got, want)


def test_adf_masked_parity():
    main = gp.PrimitiveSet("MAIN", 1)
    main.add_primitive(jnp.add, 2, "add")
    main.add_primitive(jnp.multiply, 2, "mul")
    main.add_adf("ADF0", 1, branch=1)
    sub = gp.PrimitiveSet("ADF0", 1)
    sub.add_primitive(jnp.subtract, 2, "sub")
    sub.add_primitive(jnp.cos, 1, "cos")
    branches = [(main, 24), (sub, 16)]
    geng = gp.make_adf_generator(branches, 1, 3)
    pop = [geng(jax.random.key(s)) for s in range(12)]
    genomes = tuple(
        jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                               *[p[b] for p in pop])
        for b in range(2))
    X = jnp.linspace(-1.0, 1.0, 9)[:, None]
    plain = gp.make_adf_batch_interpreter(branches, specialize="none")
    want = np.asarray(jax.jit(plain)(genomes, X))
    masked = gp.make_adf_batch_interpreter(branches)
    got = np.asarray(masked(genomes, X))
    np.testing.assert_array_equal(got, want)
    # traced fallback of the masked interpreter
    got_j = np.asarray(jax.jit(masked)(genomes, X))
    np.testing.assert_array_equal(got_j, want)


def test_mask_lattice_bounds_rebuilds(tmp_path):
    """The monotone mask union bounds evaluator rebuilds by n_ops: a
    population stream whose vocab oscillates must not rebuild once the
    union covers it — journaled build events are the evidence (the PR 2
    retrace plumbing)."""
    from deap_tpu.telemetry.journal import RunJournal, read_journal

    ps = gp.math_set(n_args=1)
    ps.arity_table()
    f = gp.make_batch_interpreter(ps, 24, mode="scan", dedup=False)
    X = jnp.linspace(-1.0, 1.0, 7)[:, None]

    def pop_with_ops(ops_subset):
        # hand-built single-op trees: op(ARG0, ARG0) or op(ARG0)
        rows = []
        for op in ops_subset:
            ar = int(ps.arity_table()[op])
            nodes = [op] + [ps.n_ops] * ar
            g = {"nodes": jnp.asarray(nodes + [0] * (24 - len(nodes)),
                                      jnp.int32),
                 "consts": jnp.zeros(24, jnp.float32),
                 "length": jnp.asarray(len(nodes), jnp.int32)}
            rows.append(g)
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *rows)

    path = tmp_path / "j.jsonl"
    with RunJournal(str(path)) as journal:
        journal.header(init_backend=False)
        streams = [(0,), (0, 1), (0,), (1, 2), (0, 2), (1,), (0, 1, 2),
                   (2,), (0, 1)]
        for subset in streams:
            f(pop_with_ops(subset), X)
    events = read_journal(str(path))
    builds = [e for e in events if e.get("kind") == "gp_interpreter_build"]
    dispatches = [e for e in events if e.get("kind") == "gp_dispatch"]
    # monotone union: at most one build per newly-seen opcode (3 here)
    assert len(builds) <= 3, builds
    assert dispatches and set(dispatches[-1]["mask"]) >= {"add", "sub",
                                                          "mul"}
    # the batching dimensions ride every dispatch row: the solo
    # dispatcher is the n_lanes=1 point of the same budget ledger
    assert all(d["n_lanes"] == 1 for d in dispatches), dispatches
    assert all(d["mask_popcount"] == len(d["mask"]) for d in dispatches)


def test_batched_engine_journals_lane_dims(tmp_path):
    """The run-axis GP engine journals its union-mask rebuilds under
    the same ``gp_dispatch``/``gp_interpreter_build`` kinds, stamped
    with its lane count and union-mask popcount — the mask-lattice
    rebuild budget stays auditable under batching."""
    from deap_tpu.serving.gp_multirun import GpJobSpec, GpMultiRunEngine
    from deap_tpu.telemetry.journal import RunJournal, read_journal

    ps = gp.math_set(n_args=1)
    ps.arity_table()
    X = np.linspace(-1.0, 1.0, 7, dtype=np.float32)[:, None]
    y = (X[:, 0] ** 2).astype(np.float32)
    gen = gp.gen_half_and_half(ps, 24, 1, 2)

    def founders(seed):
        return jax.vmap(gen)(jax.random.split(jax.random.key(seed), 8))

    path = tmp_path / "j.jsonl"
    with RunJournal(str(path)) as journal:
        journal.header(init_backend=False)
        eng = GpMultiRunEngine(GpJobSpec(pset=ps, max_len=24, X=X, y=y))
        batch = eng.pack_fresh(
            jnp.stack([jax.random.key(0), jax.random.key(1)]),
            [founders(0), founders(1)], 3,
            {"cxpb": 0.5, "mutpb": 0.2}, n_lanes=2)
        eng.advance(batch, 3)
    events = read_journal(str(path))
    disp = [e for e in events if e.get("kind") == "gp_dispatch"]
    builds = [e for e in events
              if e.get("kind") == "gp_interpreter_build"]
    assert disp and all(d["mode"] == "batched" for d in disp)
    assert all(d["n_lanes"] == 2 for d in disp), disp
    assert all(d["mask_popcount"] == len(d["mask"]) for d in disp)
    # every evaluator (re)build inside the engine carries the lane
    # count; monotone mask union bounds them by n_ops
    assert builds and all("n_lanes" in b and "mask_popcount" in b
                          for b in builds)
    assert len(builds) <= ps.n_ops


def test_grouped_schedule_chunks_pure(pset):
    """Every chunk of the grouped schedule holds exactly one opcode and
    children land in strictly earlier chunks than their parents."""
    genomes = _bloat_varying(pset)
    nodes = np.asarray(genomes["nodes"])
    consts = np.asarray(genomes["consts"])
    length = np.asarray(genomes["length"])
    arity_np = np.asarray(pset.arity_table())
    ends = _ends_np(nodes, length, arity_np)
    depths = _depths_np(ends, length)
    # numpy ends/depths agree with the jax closed forms
    for i in range(0, len(length), 5):
        g = jax.tree_util.tree_map(lambda a: a[i], genomes)
        je = np.asarray(subtree_ends_all(g["nodes"], g["length"],
                                         pset.arity_table()))
        jd = np.asarray(prefix_depths(g["nodes"], g["length"],
                                      pset.arity_table()))
        live = np.arange(ML) < int(length[i])
        np.testing.assert_array_equal(ends[i][live], je[live])
        np.testing.assert_array_equal(depths[i][live], jd[live])
    mask = _used_ops(pset.n_ops, nodes, length)
    chunk = 16
    s = build_grouped_schedule(pset, nodes, consts, length, ends, depths,
                               mask, chunk)
    # chunk count sits on the lattice and covers every instruction
    # (plus the per-(depth, opcode)-run alignment padding)
    assert s["nchunks"] == _round_chunks(s["nchunks"])
    assert s["nchunks"] * chunk >= s["n_instructions"]
    total = s["nchunks"] * chunk
    assert s["src_idx"].shape == (total, pset.max_arity)
    # REAL operand slots (j < the chunk opcode's arity) always point
    # strictly below the instruction's own row — children sort into
    # earlier positions, terminals are arg rows or inline constants —
    # so the sequential chunk order is a valid evaluation order.
    # (Slots past the arity are gathered then discarded by
    # ``fn(*ops[:arity])`` and may point anywhere in bounds.)
    own_row = pset.n_args + np.arange(total)
    chunk_arity = arity_np[np.asarray(mask)][s["chunk_ops"]]   # [nchunks]
    pos_arity = np.repeat(chunk_arity, chunk)                  # [total]
    si = np.asarray(s["src_idx"])
    for j in range(pset.max_arity):
        sel = pos_arity > j
        assert (si[sel, j] < own_row[sel]).all()
    assert (si < pset.n_args + total).all() and (si >= 0).all()


def test_grouped_kernel_interpret_parity():
    """The Pallas fused gather-dispatch-scatter kernel (interpret mode
    off-TPU) matches the scan chain bit-for-bit."""
    ps = gp.math_set(n_args=1)
    ps.arity_table()
    genomes = _population(ps, range(10), 1, 3, ml=24)
    X = jnp.linspace(-2.0, 2.0, 8)[:, None]
    want = _reference(ps, genomes, X, ml=24)
    nodes = np.asarray(genomes["nodes"])
    consts = np.asarray(genomes["consts"])
    length = np.asarray(genomes["length"])
    arity_np = np.asarray(ps.arity_table())
    ends = _ends_np(nodes, length, arity_np)
    depths = _depths_np(ends, length)
    mask = _used_ops(ps.n_ops, nodes, length)
    sched = build_grouped_schedule(ps, nodes, consts, length, ends,
                                   depths, mask, chunk=8)
    fn = _grouped_eval_kernel_builder(ps, mask, 8)
    args = [jnp.asarray(sched[k]) for k in
            ("chunk_ops", "src_idx", "src_const", "src_isc")]
    buf = fn(*args, X)
    preds = np.where(sched["root_isc"][:, None],
                     sched["root_const"][:, None],
                     np.asarray(buf)[sched["root_idx"]])
    np.testing.assert_array_equal(preds, want)


# ------------------------------------------------------- loop engine ----

def test_loop_carried_depths_exact_and_limited():
    """The engine's algebraically-spliced depth arrays must equal a
    prefix_depths recomputation after many generations, every tree must
    stay a valid prefix, and Koza's height limit must hold."""
    from deap_tpu.gp.loop import make_symbreg_loop

    POP, ml = 256, 48
    ps = gp.math_set(n_args=1)
    ps.arity_table()
    X = jnp.linspace(-1.0, 1.0, 32, endpoint=False)[:, None]
    y = X[:, 0] ** 3 + X[:, 0]
    gen = gp.gen_half_and_half(ps, ml, 1, 2)
    genomes = jax.vmap(gen)(jax.random.split(jax.random.key(3), POP))
    run = make_symbreg_loop(ps, ml, X, y, height_limit=6)
    r = run(jax.random.key(0), genomes, 12)

    arity = ps.arity_table()
    dep_re = np.asarray(jax.vmap(
        lambda g: prefix_depths(g["nodes"], g["length"], arity))(
        r["genomes"]))
    lens = np.asarray(r["genomes"]["length"])
    live = np.arange(ml)[None, :] < lens[:, None]
    np.testing.assert_array_equal(
        np.where(live, np.asarray(r["depths"]), 0),
        np.where(live, dep_re, 0))
    assert (np.max(np.where(live, dep_re, 0), axis=1) <= 6).all()

    arity_np = np.asarray(arity)
    nodes = np.asarray(r["genomes"]["nodes"])
    for i in range(0, POP, 17):
        need = 1
        for t in range(int(lens[i])):
            need += arity_np[nodes[i, t]] - 1
        assert need == 0 and lens[i] >= 1

    # invalid-only evaluation: per-gen nevals strictly below pop
    assert all(ne <= POP for ne in r["nevals"])
    assert np.mean(r["nevals"][1:]) < POP


@pytest.mark.slow
def test_loop_improves_fitness():
    from deap_tpu.gp.loop import make_symbreg_loop

    POP, ml = 512, 48
    ps = gp.math_set(n_args=1)
    ps.arity_table()
    X = jnp.linspace(-1.0, 1.0, 32, endpoint=False)[:, None]
    y = X[:, 0] ** 2 + X[:, 0]
    gen = gp.gen_half_and_half(ps, ml, 1, 2)
    genomes = jax.vmap(gen)(jax.random.split(jax.random.key(5), POP))
    run = make_symbreg_loop(ps, ml, X, y)
    r0 = run(jax.random.key(1), genomes, 0)
    r = run(jax.random.key(1), genomes, 15)
    assert r["best_fitness"] >= r0["best_fitness"]
    assert -r["best_fitness"] < 0.2, -r["best_fitness"]
