"""compat.gp: the reference's list-based GP API end-to-end — symbolic
regression with creator/toolbox/eaSimple, stack-compile (no eval), and
the variation operators' structural invariants."""

import operator
import random

import pytest

from deap_tpu.compat import algorithms, base, creator, gp, tools


def protected_div(a, b):
    return 1.0 if b == 0 else a / b


def _rand101():
    # module-level so per-test pset rebuilds re-register the SAME
    # generator (a fresh lambda per rebuild would warn — by design)
    return random.uniform(-1, 1)


@pytest.fixture
def pset():
    ps = gp.PrimitiveSet("MAIN", 1)
    ps.addPrimitive(operator.add, 2)
    ps.addPrimitive(operator.sub, 2)
    ps.addPrimitive(operator.mul, 2)
    ps.addPrimitive(protected_div, 2, name="div")
    ps.addTerminal(1.0)
    ps.addEphemeralConstant("rand101", _rand101)
    ps.renameArguments(ARG0="x")
    return ps


def valid_prefix(tree):
    need = 1
    for node in tree:
        need += node.arity - 1
    return need == 0


def test_generate_compile_eval(pset):
    random.seed(7)
    for _ in range(20):
        t = gp.genHalfAndHalf(pset, 1, 4)
        assert valid_prefix(t)
        f = gp.compile(t, pset)
        v = f(1.5)
        assert isinstance(v, float)
    s = str(t)
    assert s  # printable


def test_compile_known_tree(pset):
    add = pset.mapping["add"]
    mul = pset.mapping["mul"]
    x = pset.mapping["x"]
    one = pset.mapping["1.0"]
    # (x + 1) * x
    t = gp.PrimitiveTree([mul, add, x, one, x])
    f = gp.compile(t, pset)
    assert f(3.0) == 12.0
    assert "mul(add(x, 1.0), x)" == str(t)
    assert t.height == 2
    assert t.search_subtree(1) == slice(1, 4)


def test_crossover_and_mutations_preserve_validity(pset):
    random.seed(11)
    for _ in range(30):
        a = gp.genFull(pset, 2, 3)
        b = gp.genGrow(pset, 2, 4)
        c1, c2 = gp.cxOnePoint(gp.PrimitiveTree(a), gp.PrimitiveTree(b))
        assert valid_prefix(c1) and valid_prefix(c2)
        m1, = gp.mutUniform(gp.PrimitiveTree(a),
                            lambda pset, type_: gp.genGrow(pset, 0, 2),
                            pset)
        assert valid_prefix(m1)
        m2, = gp.mutNodeReplacement(gp.PrimitiveTree(a), pset)
        assert valid_prefix(m2)
        m3, = gp.mutEphemeral(gp.PrimitiveTree(a))
        assert valid_prefix(m3)
        m4, = gp.mutInsert(gp.PrimitiveTree(a), pset)
        assert valid_prefix(m4) and len(m4) >= len(a)
        m5, = gp.mutShrink(gp.PrimitiveTree(gp.genFull(pset, 2, 2)))
        assert valid_prefix(m5)


def test_static_limit(pset):
    random.seed(3)
    parent = gp.PrimitiveTree(gp.genFull(pset, 2, 2))
    deep = gp.PrimitiveTree(gp.genFull(pset, 5, 5))
    # operator returns an over-limit offspring: the decorator must hand
    # back a copy of the *parent* instead (gp.py:890-931)
    limited = gp.staticLimit(key=lambda t: t.height, max_value=3)(
        lambda t: (deep,))
    out, = limited(parent)
    assert out is not parent and list(out) == list(parent)
    # under-limit offspring pass through untouched
    ok = gp.PrimitiveTree(gp.genFull(pset, 1, 1))
    passthrough = gp.staticLimit(key=lambda t: t.height, max_value=3)(
        lambda t: (ok,))
    out2, = passthrough(parent)
    assert out2 is ok


def test_symbreg_end_to_end(pset):
    """Mini quartic regression via the full reference workflow
    (examples/gp/symbreg.py shape)."""
    random.seed(318)
    creator.create("FitnessMinGP", base.Fitness, weights=(-1.0,))
    creator.create("IndividualGP", gp.PrimitiveTree,
                   fitness=creator.FitnessMinGP)

    toolbox = base.Toolbox()
    toolbox.register("expr", gp.genHalfAndHalf, pset=pset, min_=1, max_=2)
    toolbox.register("individual", lambda: creator.IndividualGP(
        toolbox.expr()))
    toolbox.register("population", lambda n: [toolbox.individual()
                                              for _ in range(n)])

    points = [x / 10.0 for x in range(-10, 10)]

    def evaluate(ind):
        f = gp.compile(ind, pset)
        err = 0.0
        for x in points:
            err += (f(x) - (x ** 4 + x ** 3 + x ** 2 + x)) ** 2
        return (err / len(points),)

    toolbox.register("evaluate", evaluate)
    toolbox.register("select", tools.selTournament, tournsize=3)
    toolbox.register("mate", gp.cxOnePoint)
    toolbox.register("expr_mut", gp.genFull, min_=0, max_=2)
    toolbox.register("mutate", gp.mutUniform, expr=lambda pset, type_:
                     toolbox.expr_mut(pset=pset), pset=pset)
    toolbox.decorate("mate", gp.staticLimit(
        key=lambda t: t.height, max_value=17))
    toolbox.decorate("mutate", gp.staticLimit(
        key=lambda t: t.height, max_value=17))

    pop = toolbox.population(60)
    pop, logbook = algorithms.eaSimple(
        pop, toolbox, cxpb=0.5, mutpb=0.2, ngen=8, verbose=False)
    best = min(pop, key=lambda i: i.fitness.values[0])
    assert best.fitness.values[0] < 5.0  # improved well past random


def test_compile_iterative_no_depth_limit(pset):
    # 3000-deep unary chain: the reference's eval dies past ~90; a
    # recursive evaluator would die near the interpreter limit
    pset2 = gp.PrimitiveSet("DEEP", 1)
    pset2.addPrimitive(lambda a: a + 1.0, 1, name="inc")
    inc = pset2.mapping["inc"]
    x = pset2.mapping["ARG0"]
    t = gp.PrimitiveTree([inc] * 3000 + [x])
    f = gp.compile(t, pset2)
    assert f(0.0) == 3000.0


def test_compile_adf_with_arguments():
    adf = gp.PrimitiveSet("ADF0", 1)
    adf.addPrimitive(operator.mul, 2)
    main = gp.PrimitiveSet("MAIN", 1)
    main.addPrimitive(operator.add, 2)
    main.addADF(adf)
    # ADF0(x) = x * x; main = add(x, ADF0(x)) -> x + x^2
    t_adf = gp.PrimitiveTree([adf.mapping["mul"], adf.mapping["ARG0"],
                              adf.mapping["ARG0"]])
    t_main = gp.PrimitiveTree([main.mapping["add"], main.mapping["ARG0"],
                               main.mapping["ADF0"], main.mapping["ARG0"]])
    f = gp.compileADF([t_main, t_adf], [main, adf])
    assert f(3.0) == 12.0
    # shared sets are not mutated: a second individual compiles cleanly
    f2 = gp.compileADF([t_main, t_adf], [main, adf])
    assert f2(2.0) == 6.0
    assert main.mapping["ADF0"].fn is None


def test_mut_ephemeral_rejects_bad_mode(pset):
    t = gp.genFull(pset, 1, 2)
    with pytest.raises(ValueError):
        gp.mutEphemeral(gp.PrimitiveTree(t), mode="On")


def test_mut_shrink_keeps_tiny_trees(pset):
    add = pset.mapping["add"]
    x = pset.mapping["x"]
    one = pset.mapping["1.0"]
    t = gp.PrimitiveTree([add, x, one])
    out, = gp.mutShrink(gp.PrimitiveTree(t))
    assert list(out) == list(t)  # height 1: never shrunk (gp.py:862-863)


def test_gp_tree_pickle_roundtrip(pset):
    """GP trees incl. ephemerals round-trip (test_pickle.py:109-131)."""
    import pickle

    random.seed(99)
    creator.create("FMinP", base.Fitness, weights=(-1.0,))
    creator.create("IndP", gp.PrimitiveTree, fitness=creator.FMinP)
    ind = creator.IndP(gp.genFull(pset, 2, 3))
    ind.fitness.values = (1.5,)
    clone = pickle.loads(pickle.dumps(ind))
    assert str(clone) == str(ind)
    assert clone.fitness.values == (1.5,)
    f1, f2 = gp.compile(ind, pset), gp.compile(clone, pset)
    assert f1(0.7) == f2(0.7)


def test_ephemeral_name_collision_warns():
    a = gp.PrimitiveSet("EA", 1)
    fn = lambda: 0.5
    a.addEphemeralConstant("shared_eph", fn)
    b = gp.PrimitiveSet("EB", 1)
    b.addEphemeralConstant("shared_eph", fn)  # same function: silent
    with pytest.warns(RuntimeWarning, match="re-registered"):
        b.addEphemeralConstant("shared_eph", lambda: 999.0)


def test_ephemeral_restore_unregistered_is_diagnosable():
    from deap_tpu.compat.gp import _restore_ephemeral

    with pytest.raises(RuntimeError, match="has not been built"):
        _restore_ephemeral("never_registered_eph", 1.0)
