"""Run-journal telemetry subsystem (deap_tpu.telemetry).

Pins the acceptance contract of ISSUE 2: per-generation meter rows in
the JSONL journal, retrace events via jax.monitoring, per-span
aggregates for every genome_shard/* collective — and, above all, that
enabling telemetry changes no computed result (population/logbook
arrays bit-identical)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.telemetry import (
    Meter,
    RunJournal,
    RunTelemetry,
    read_journal,
    strategy_probe,
    toolbox_fingerprint,
)


def _onemax_toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def _onemax_pop(key, n=64, length=32):
    return init_population(key, n, ops.bernoulli_genome(length),
                           FitnessSpec((1.0,)))


# ================================================================ Meter ====

def test_meter_counter_gauge_histogram_semantics():
    m = Meter()
    m.counter("n")
    m.gauge("g")
    m.histogram("h", lo=0.0, hi=10.0, bins=5)
    s = m.init()
    s = m.inc(s, "n", 3)
    s = m.inc(s, "n")
    s = m.set(s, "g", 2.5)
    # values land in [lo,hi) buckets; out-of-range clamps to the edges
    s = m.observe(s, "h", jnp.array([0.5, 1.0, 9.9, -3.0, 42.0]))
    assert int(s["n"]) == 4
    assert float(s["g"]) == 2.5
    np.testing.assert_array_equal(np.asarray(s["h"]), [3, 0, 0, 0, 2])
    # masked observe drops rows but keeps geometry
    s = m.observe(s, "h", jnp.array([5.0, 5.0]),
                  mask=jnp.array([True, False]))
    np.testing.assert_array_equal(np.asarray(s["h"]), [3, 0, 1, 0, 2])


def test_meter_declarations_idempotent_and_checked():
    m = Meter()
    m.counter("n")
    m.counter("n")  # same spec: fine (algorithm + probe may both declare)
    with pytest.raises(ValueError):
        m.counter("n", dtype=jnp.int64)  # different spec: loud
    with pytest.raises(KeyError):
        m.inc(m.init(), "missing")
    m.gauge("g")
    with pytest.raises(TypeError):
        m.inc(m.init(), "g")  # kind mismatch


def test_meter_rows_decode_stacked_scan_output():
    m = Meter()
    m.counter("n")
    m.gauge("g")

    def step(s, x):
        s = m.inc(s, "n")
        s = m.set(s, "g", x)
        return s, s

    _, stacked = jax.lax.scan(step, m.init(), jnp.arange(3.0))
    rows = m.rows(stacked)
    assert [r["n"] for r in rows] == [1, 2, 3]
    assert [r["g"] for r in rows] == [0.0, 1.0, 2.0]
    assert json.dumps(rows)  # JSON-serialisable end to end


# ===================================================== bit-identicality ====

def test_meter_carry_bit_identical_across_loops(tmp_path):
    """Enabling telemetry threads extra carry through every scanned
    loop but must change no computed result — population and logbook
    arrays bit-identical."""
    tb = _onemax_toolbox()
    pop0 = _onemax_pop(jax.random.key(1))
    runs = {
        "ea_simple": lambda tel: algorithms.ea_simple(
            jax.random.key(2), pop0, tb, 0.5, 0.2, 8, halloffame_size=3,
            telemetry=tel),
        "ea_mu_plus_lambda": lambda tel: algorithms.ea_mu_plus_lambda(
            jax.random.key(3), pop0, tb, mu=64, lambda_=64, cxpb=0.5,
            mutpb=0.2, ngen=8, telemetry=tel),
        "ea_mu_comma_lambda": lambda tel: algorithms.ea_mu_comma_lambda(
            jax.random.key(4), pop0, tb, mu=64, lambda_=96, cxpb=0.5,
            mutpb=0.2, ngen=8, telemetry=tel),
    }
    for name, run in runs.items():
        base_pop, base_lb, base_hof = run(None)
        with RunTelemetry(str(tmp_path / f"{name}.jsonl")) as tel:
            tel_pop, tel_lb, tel_hof = run(tel)
        np.testing.assert_array_equal(
            np.asarray(base_pop.genomes), np.asarray(tel_pop.genomes),
            err_msg=f"{name}: genomes drifted under telemetry")
        np.testing.assert_array_equal(
            np.asarray(base_pop.fitness), np.asarray(tel_pop.fitness),
            err_msg=f"{name}: fitness drifted under telemetry")
        assert base_lb.select("nevals") == tel_lb.select("nevals"), name
        if base_hof is not None:
            np.testing.assert_array_equal(
                np.asarray(base_hof.fitness), np.asarray(tel_hof.fitness),
                err_msg=f"{name}: hall of fame drifted under telemetry")


# ========================================================= the journal ====

def test_ea_simple_journal_acceptance(tmp_path):
    """The OneMax acceptance run: meter rows for every generation,
    header + run events, and >= 1 retrace event once a post-steady
    shape change forces a recompile."""
    tb = _onemax_toolbox()
    path = str(tmp_path / "run.jsonl")
    ngen = 10
    with RunTelemetry(path) as tel:
        pop, logbook, _ = algorithms.ea_simple(
            jax.random.key(2), _onemax_pop(jax.random.key(1)), tb,
            0.5, 0.2, ngen, telemetry=tel)
        # second run, different population size: the silent-recompile
        # failure mode — must surface as retrace events, not vanish
        algorithms.ea_simple(
            jax.random.key(5), _onemax_pop(jax.random.key(6), n=32), tb,
            0.5, 0.2, 4, telemetry=tel)
    events = read_journal(path)
    kinds = [e["kind"] for e in events]
    assert kinds.count("header") == 1
    header = events[kinds.index("header")]
    assert header["env"]["jax"] == jax.__version__
    assert header["env"]["backend"] == "cpu"
    assert "digest" in header["toolbox"]

    meters = [e for e in events if e["kind"] == "meter"]
    # run 1: gens 0..ngen, run 2: gens 0..4
    assert [m["gen"] for m in meters[: ngen + 1]] == list(range(ngen + 1))
    assert meters[0]["nevals"] == 64  # whole initial population
    assert meters[ngen]["nevals"] >= meters[1]["nevals"]  # monotone
    assert meters[ngen]["best"] == float(np.max(np.asarray(pop.fitness)))
    for m in meters:
        assert set(m) >= {"gen", "nevals", "best", "mean",
                          "evaluated_frac"}

    assert "steady" in kinds
    retraces = [e for e in events if e["kind"] == "retrace"]
    assert len(retraces) >= 1, "post-steady recompile must be journaled"
    assert all(e["dur_s"] >= 0 for e in retraces)
    assert kinds[-1] == "summary"
    assert events[-1]["n_retraces"] == len(retraces)


def test_island_genome_shard_journal_acceptance(tmp_path):
    """The 8-island acceptance run (8 virtual CPU devices, see
    conftest): per-epoch meter rows with the meter carried inside the
    jit'd island step, plus span aggregates for every genome_shard/*
    collective captured without any xplane trace."""
    from deap_tpu.algorithms import evaluate_invalid
    from deap_tpu.parallel import island_init, make_island_step
    from deap_tpu.parallel.genome_shard import (genome_mesh,
                                                make_sharded_evaluator,
                                                shard_genomes)
    from deap_tpu.parallel.mesh import population_mesh, shard_population

    tb = _onemax_toolbox()
    path = str(tmp_path / "island.jsonl")
    with RunTelemetry(path) as tel:
        tel.journal.header(toolbox=tb)
        mesh = population_mesh(8, ("island",))
        pops = island_init(jax.random.key(0), 8, 16,
                           ops.bernoulli_genome(24), FitnessSpec((1.0,)))
        pops = jax.vmap(lambda p: evaluate_invalid(p, tb.evaluate))(pops)
        pops = shard_population(pops, mesh, "island")
        step = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=2, mig_k=2,
                                mesh=mesh, telemetry=tel)
        mstate = tel.meter.init()
        for epoch in range(3):
            pops, mstate = step(jax.random.fold_in(jax.random.key(9), epoch),
                                pops, mstate)
            tel.journal.event("meter", gen=epoch,
                              **tel.meter.row(mstate))
        # the genome-sharded evaluator exercises every combine mode's
        # collective under the active SpanRecorder
        gmesh = genome_mesh(n_pop_shards=1, n_genome_shards=8)
        g = jax.random.bernoulli(jax.random.key(5), 0.5, (16, 64))
        for combine in ("sum", "mean", "max"):
            ev = make_sharded_evaluator(
                lambda s: s.sum(-1).astype(jnp.float32), gmesh,
                combine=combine)
            ev(shard_genomes(g, gmesh))

    events = read_journal(path)
    meters = [e for e in events if e["kind"] == "meter"]
    assert len(meters) == 3
    assert meters[-1]["epochs"] == 3
    assert meters[-1]["generations"] == 6
    assert meters[-1]["migrants"] == 3 * 2 * 8
    assert meters[-1]["best"] <= 24.0 and meters[-1]["best"] > 0

    spans = {e["name"]: e for e in events if e["kind"] == "span"}
    for expected in ("genome_shard/partial_eval", "genome_shard/psum",
                     "genome_shard/pmean", "genome_shard/pmax",
                     "island/ppermute"):
        assert expected in spans, f"missing span aggregate: {expected}"
        agg = spans[expected]
        assert agg["count"] >= 1
        assert agg["total_s"] >= 0
        assert set(agg) >= {"count", "total_s", "mean_s", "p50_s",
                            "p99_s", "max_s"}


def test_generate_update_strategy_probe(tmp_path):
    """ea_generate_update + strategy_probe: CMA-ES internals (sigma,
    condition number) ride the scan as gauges — and telemetry changes
    nothing."""
    from deap_tpu.strategies import cma

    dim = 4
    strat = cma.Strategy(centroid=[0.5] * dim, sigma=0.3, lambda_=8)
    tb = Toolbox()
    tb.register("evaluate", lambda x: jnp.sum(x ** 2, axis=-1))
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)

    base_state, base_lb, _ = algorithms.ea_generate_update(
        jax.random.key(3), strat.initial_state(), tb, ngen=5,
        spec=strat.spec)
    path = str(tmp_path / "cma.jsonl")
    with RunTelemetry(path, probe=strategy_probe(strat)) as tel:
        tel_state, tel_lb, _ = algorithms.ea_generate_update(
            jax.random.key(3), strat.initial_state(), tb, ngen=5,
            spec=strat.spec, telemetry=tel)
    np.testing.assert_array_equal(np.asarray(base_state.centroid),
                                  np.asarray(tel_state.centroid))
    np.testing.assert_array_equal(np.asarray(base_state.C),
                                  np.asarray(tel_state.C))

    meters = [e for e in read_journal(path) if e["kind"] == "meter"]
    assert len(meters) == 5
    for m in meters:
        assert m["sigma"] > 0
        assert m["cond"] >= 1.0 - 1e-5
        assert m["nevals"] % 8 == 0
    assert meters[-1]["nevals"] == 40


def test_strategy_probe_rejects_plain_objects():
    with pytest.raises(TypeError):
        strategy_probe(object())


def test_streaming_emitter(tmp_path):
    """stream=True ships live per-generation rows through
    jax.debug.callback into the journal (and stderr)."""
    tb = _onemax_toolbox()
    path = str(tmp_path / "stream.jsonl")
    with RunTelemetry(path, stream=True) as tel:
        algorithms.ea_simple(
            jax.random.key(2), _onemax_pop(jax.random.key(1), n=16), tb,
            0.5, 0.2, 4, telemetry=tel)
    live = [e for e in read_journal(path) if e["kind"] == "meter_live"]
    assert len(live) >= 4  # gen 0 (eager) + in-scan callbacks
    gens = {e["gen"] for e in live}
    assert gens >= {1, 2, 3, 4}
    for e in live:
        assert "best" in e and "nevals" in e


def test_shared_journal_and_broadcast(tmp_path):
    """Several runs can share one journal; broadcast() reaches every
    open journal (the GP-interpreter/checkpoint event path)."""
    from deap_tpu.telemetry import broadcast

    path = str(tmp_path / "shared.jsonl")
    with RunJournal(path) as journal:
        journal.header(init_backend=False)
        broadcast("custom_event", detail="x")
        tb = _onemax_toolbox()
        with RunTelemetry(journal) as tel:
            algorithms.ea_simple(
                jax.random.key(2), _onemax_pop(jax.random.key(1), n=16),
                tb, 0.5, 0.2, 2, telemetry=tel)
    events = read_journal(path)
    kinds = [e["kind"] for e in events]
    assert "custom_event" in kinds
    assert "run_start" in kinds and "run_end" in kinds
    # a closed journal is inert: broadcast after close writes nothing
    n = len(events)
    broadcast("after_close")
    assert len(read_journal(path)) == n


def test_checkpoint_event_broadcast(tmp_path):
    from deap_tpu.support.checkpoint import save_state

    path = str(tmp_path / "ckpt.jsonl")
    with RunJournal(path) as journal:
        save_state(str(tmp_path / "s.ckpt"), {"x": jnp.arange(4)})
    events = read_journal(path)
    ck = [e for e in events if e["kind"] == "checkpoint"]
    assert len(ck) == 1 and ck[0]["bytes"] > 0


def test_toolbox_fingerprint_stable_and_sensitive():
    tb1, tb2 = _onemax_toolbox(), _onemax_toolbox()
    fp1, fp2 = toolbox_fingerprint(tb1), toolbox_fingerprint(tb2)
    assert fp1["digest"] == fp2["digest"]
    assert "select" in fp1["aliases"]
    tb2.register("select", ops.sel_tournament, tournsize=5)
    assert toolbox_fingerprint(tb2)["digest"] != fp1["digest"]


def test_read_journal_skips_malformed_lines(tmp_path):
    path = str(tmp_path / "j.jsonl")
    with open(path, "w") as fh:
        fh.write('{"kind": "header"}\n')
        fh.write('{"kind": "meter", "gen": 1,\n')  # crashed mid-write
        fh.write('{"kind": "summary"}\n')
    events = read_journal(path)
    assert [e["kind"] for e in events] == ["header", "summary"]


def test_journal_timestamps_survive_wall_clock_step(tmp_path,
                                                    monkeypatch):
    """Row `t` deltas come from time.monotonic(): an NTP step (wall
    clock jumping backwards mid-run) must never yield backwards or
    negative `t`; the wall-clock epoch stays available in the header
    as `wall_start`."""
    import deap_tpu.telemetry.journal as journal_mod

    path = str(tmp_path / "ntp.jsonl")
    j = RunJournal(path)
    j.header(init_backend=False)
    j.event("before_step", i=0)

    # the NTP step: wall clock jumps 1h into the past. monotonic is
    # untouched (it cannot go backwards, by definition).
    real_time = journal_mod.time.time

    class _SteppedTime:
        monotonic = staticmethod(journal_mod.time.monotonic)

        @staticmethod
        def time():
            return real_time() - 3600.0

    monkeypatch.setattr(journal_mod, "time", _SteppedTime)
    j.event("after_step", i=1)
    j.event("after_step", i=2)
    j.close()

    rows = read_journal(path)
    ts = [e["t"] for e in rows]
    assert all(t >= 0 for t in ts), f"negative t after NTP step: {ts}"
    assert ts == sorted(ts), f"non-monotonic t after NTP step: {ts}"
    header = rows[0]
    assert header["kind"] == "header"
    # wall_start documents the open's epoch (pre-step wall clock)
    assert abs(header["wall_start"] - real_time()) < 120.0
