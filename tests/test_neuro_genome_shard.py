"""Neuroevolution environment and genome-axis (SP/CP) sharding tests."""

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu.benchmarks.cartpole import (
    cartpole_step,
    initial_state,
    mlp_policy,
    rollout,
    rollout_population,
)
from deap_tpu.parallel.genome_shard import (
    genome_mesh,
    make_sharded_evaluator,
    shard_genomes,
)


def test_cartpole_physics_sane():
    s = jnp.zeros(4)
    # pushing right: x_dot stays 0 on the first Euler step (position
    # integrates before acceleration lands), then turns positive
    s2, failed = cartpole_step(s, jnp.int32(1))
    assert float(s2[1]) > 0.0
    s3, _ = cartpole_step(s2, jnp.int32(1))
    assert float(s3[1]) > float(s2[1])
    assert not bool(failed)
    # a pole at the failure angle fails
    bad = jnp.asarray([0.0, 0.0, 0.25, 0.0])
    _, failed = cartpole_step(bad, jnp.int32(0))
    assert bool(failed)


def test_rollout_rewards_bounded_and_policy_matters():
    policy, n_params = mlp_policy((4, 8, 2))
    key = jax.random.key(0)
    zero = jnp.zeros((n_params,))
    r_zero = float(rollout(policy, zero, key, max_steps=200))
    assert 0.0 <= r_zero <= 200.0
    # among random policies some survive longer than others
    genomes = jax.random.normal(jax.random.key(1), (32, n_params))
    rs = jax.vmap(lambda p: rollout(policy, p, key, 200))(genomes)
    assert float(rs.max()) > float(rs.min())


def test_rollout_population_matches_per_episode_scan():
    """The early-exit batch rollout must reproduce the per-episode scan
    path's returns exactly — same physics, same reward-per-step-entered
    -alive accounting — while stopping early once the batch is dead."""
    policy, n_params = mlp_policy((4, 8, 2))
    genomes = jax.random.normal(jax.random.key(3), (16, n_params)) * 0.5
    keys = jax.random.split(jax.random.key(4), 3)
    batch = rollout_population(policy, genomes, keys, max_steps=200,
                               chunk=25)
    ref = jax.vmap(lambda p: jax.vmap(
        lambda k: rollout(policy, p, k, 200))(keys))(genomes)
    np.testing.assert_allclose(np.asarray(batch), np.asarray(ref))


def test_rollout_population_compaction_levels_match():
    """Force the compaction cascade through several halving levels and
    check exact agreement with the per-episode scan path — including
    episodes that reach the step cap while levels are still draining."""
    policy, n_params = mlp_policy((4, 8, 2))
    genomes = jax.random.normal(jax.random.key(9), (400, n_params)) * 0.5
    keys = jax.random.split(jax.random.key(10), 3)   # B = 1200
    batch = rollout_population(policy, genomes, keys, max_steps=200,
                               chunk=10, min_size=64)
    ref = jax.vmap(lambda p: jax.vmap(
        lambda k: rollout(policy, p, k, 200))(keys))(genomes)
    np.testing.assert_allclose(np.asarray(batch), np.asarray(ref))


def test_rollout_population_rejects_nondivisible_chunk():
    policy, n_params = mlp_policy((4, 8, 2))
    genomes = jnp.zeros((2, n_params))
    keys = jax.random.split(jax.random.key(0), 2)
    import pytest

    with pytest.raises(ValueError):
        rollout_population(policy, genomes, keys, max_steps=100,
                           chunk=33)


def test_neuroevolution_example_improves():
    from examples.neuroevolution.cartpole import main

    best = main(smoke=True)
    # random init hovers near ~10-30 steps; evolution should exceed that
    assert best > 40.0


def test_genome_shard_matches_unsharded():
    """Partial-sum fitness over a genome-sharded population must equal
    the single-device computation exactly (OneMax over 8 shards)."""
    mesh = genome_mesh(n_pop_shards=1, n_genome_shards=8)
    n, L = 64, 512
    genomes = jax.random.bernoulli(jax.random.key(2), 0.5, (n, L))

    evaluate = make_sharded_evaluator(
        lambda g: g.sum(-1).astype(jnp.float32), mesh, combine="sum")
    got = evaluate(shard_genomes(genomes.astype(jnp.float32), mesh))
    want = genomes.sum(-1).astype(jnp.float32)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_genome_shard_2d_mesh():
    """DP x SP: both axes sharded (4 pop x 2 genome shards)."""
    mesh = genome_mesh(n_pop_shards=4, n_genome_shards=2)
    n, L = 32, 64
    genomes = jax.random.normal(jax.random.key(3), (n, L))
    evaluate = make_sharded_evaluator(
        lambda g: (g ** 2).sum(-1), mesh, combine="sum")
    got = evaluate(shard_genomes(genomes, mesh))
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray((genomes ** 2).sum(-1)),
                               rtol=1e-5)


def test_genome_shard_mean_and_max():
    mesh = genome_mesh(n_pop_shards=1, n_genome_shards=8)
    n, L = 16, 128
    genomes = jax.random.normal(jax.random.key(4), (n, L))
    ev_mean = make_sharded_evaluator(lambda g: g.mean(-1), mesh, "mean")
    ev_max = make_sharded_evaluator(lambda g: g.max(-1), mesh, "max")
    np.testing.assert_allclose(
        np.asarray(ev_mean(shard_genomes(genomes, mesh))),
        np.asarray(genomes.mean(-1)), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(ev_max(shard_genomes(genomes, mesh))),
        np.asarray(genomes.max(-1)), rtol=1e-6)
