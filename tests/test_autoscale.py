"""Autoscaler decision function — pure unit tests, no sockets, no jax.

``deap_tpu/serving/autoscale.py`` is deliberately a pure decision
module (synthetic metric snapshots in → lane counts / prewarm set /
spill list out), so its control behaviour — above all the hysteresis
that keeps an oscillating queue from flapping the lane budget — is
testable without a scheduler, a socket, or an XLA backend. The module
is loaded by file path here (like ``telemetry/report.py``'s no-jax
pin) and its import surface is AST-gated to the standard library.
"""

import ast
import importlib.util
import os
import sys

AUTOSCALE_PY = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "deap_tpu", "serving", "autoscale.py")

_spec = importlib.util.spec_from_file_location("_autoscale_standalone",
                                               AUTOSCALE_PY)
autoscale = importlib.util.module_from_spec(_spec)
# dataclasses resolve string annotations through sys.modules — the
# standalone module must be registered before exec
sys.modules["_autoscale_standalone"] = autoscale
_spec.loader.exec_module(autoscale)

AutoscaleConfig = autoscale.AutoscaleConfig
AutoscalePolicy = autoscale.AutoscalePolicy


def snap(queue=0, occ=0.0, lanes=8, p99=None, idle=()):
    return {"b": {"queue_depth": queue, "occupancy": occ,
                  "lanes": lanes, "queue_wait_p99": p99,
                  "residents": int(occ * lanes), "idle": idle}}


def policy(**kw):
    return AutoscalePolicy(AutoscaleConfig(**kw))


def test_module_imports_stdlib_only():
    """The decision function must stay runnable on a box with no jax:
    every import in autoscale.py is standard library."""
    with open(AUTOSCALE_PY) as fh:
        tree = ast.parse(fh.read())
    mods = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            mods |= {a.name.split(".")[0] for a in node.names}
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            mods.add((node.module or "").split(".")[0])
    allowed = set(sys.stdlib_module_names)
    assert mods <= allowed, f"non-stdlib imports: {mods - allowed}"


def test_scale_up_needs_consecutive_pressure():
    p = policy(up_after=2, max_lanes=64)
    assert not p.decide(snap(queue=3, occ=1.0, lanes=8)).lane_counts
    d = p.decide(snap(queue=3, occ=1.0, lanes=8))
    assert d.lane_counts == {"b": 16}
    assert "scale_up" in d.reasons["b"]


def test_wait_p99_alone_triggers_pressure():
    p = policy(up_after=2, wait_p99_high=0.5)
    p.decide(snap(queue=0, occ=0.9, lanes=4, p99=2.0))
    d = p.decide(snap(queue=0, occ=0.9, lanes=4, p99=2.0))
    assert d.lane_counts == {"b": 8}


def test_no_flapping_on_oscillating_queue_depth():
    """A queue that alternates burst/empty every observation never
    accumulates `up_after` consecutive pressured reads — the lane
    budget must not move, in either direction, over many cycles."""
    p = policy(up_after=2, down_after=3)
    for i in range(40):
        pressured = i % 2 == 0
        d = p.decide(snap(queue=5 if pressured else 0,
                          occ=1.0 if pressured else 0.9, lanes=8))
        assert not d.lane_counts, (i, d)
        assert not d.spill


def test_cooldown_blocks_back_to_back_scale_ups():
    p = policy(up_after=2, cooldown=2, max_lanes=64)
    p.decide(snap(queue=3, lanes=8))
    assert p.decide(snap(queue=3, lanes=8)).lane_counts == {"b": 16}
    # pressure persists, but the bucket is cooling down
    assert not p.decide(snap(queue=3, lanes=16)).lane_counts
    assert not p.decide(snap(queue=3, lanes=16)).lane_counts
    # cooldown over: two more pressured reads scale again
    p.decide(snap(queue=3, lanes=16))
    assert p.decide(snap(queue=3, lanes=16)).lane_counts == {"b": 32}


def test_scale_up_clamps_to_max_lanes():
    p = policy(up_after=1, max_lanes=16)
    assert p.decide(snap(queue=9, occ=0.5,
                         lanes=8)).lane_counts == {"b": 16}
    p2 = policy(up_after=1, max_lanes=16)
    assert not p2.decide(snap(queue=9, occ=0.5,
                              lanes=16)).lane_counts


def test_scale_down_needs_sustained_idleness_and_floor():
    p = policy(down_after=3, min_lanes=4, cooldown=0)
    for _ in range(2):
        assert not p.decide(snap(queue=0, occ=0.2,
                                 lanes=16)).lane_counts
    d = p.decide(snap(queue=0, occ=0.2, lanes=16))
    assert d.lane_counts == {"b": 8}
    assert "scale_down" in d.reasons["b"]
    # at the floor: never below min_lanes
    p2 = policy(down_after=1, min_lanes=4, cooldown=0)
    assert not p2.decide(snap(queue=0, occ=0.0, lanes=4)).lane_counts


def test_busy_but_not_pressured_is_not_idle():
    p = policy(down_after=1, cooldown=0)
    # full lanes, empty queue: healthy steady state, leave it alone
    assert not p.decide(snap(queue=0, occ=1.0, lanes=8)).lane_counts


def test_prewarm_predicts_next_lattice_point_once():
    p = policy(up_after=3, prewarm_ahead=True)
    d1 = p.decide(snap(queue=2, lanes=8))
    assert d1.prewarm == [("b", 16)]       # predicted ahead of need
    assert not d1.lane_counts              # ...before the scale-up
    d2 = p.decide(snap(queue=2, lanes=8))
    assert not d2.prewarm                  # predicted only once
    d3 = p.decide(snap(queue=2, lanes=8))
    assert d3.lane_counts == {"b": 16}


def test_spill_idle_tenants_at_lane_ceiling():
    p = policy(up_after=1, max_lanes=8, spill_idle_segments=4)
    idle = (("t-old", 9), ("t-young", 1), ("t-mid", 5))
    d = p.decide(snap(queue=1, occ=1.0, lanes=8, idle=idle))
    # at max lanes + full occupancy: longest-resident spillables go,
    # bounded by the queue depth
    assert d.spill == ["t-old"]
    assert "spill" in d.reasons["b"]
    # below the idle threshold nothing is spillable
    p2 = policy(up_after=1, max_lanes=8, spill_idle_segments=4)
    d2 = p2.decide(snap(queue=2, occ=1.0, lanes=8,
                        idle=(("t-young", 1),)))
    assert not d2.spill


def test_spill_prefers_gens_idle_over_residency_age():
    """ISSUE 12 satellite: with the true idleness signal present
    (``(tenant, segments_resident, gens_since_interaction)`` triples),
    spills go to genuinely parked tenants in gens-idle order — not to
    whoever has merely held a lane longest."""
    p = policy(up_after=1, max_lanes=8, spill_idle_segments=2,
               spill_idle_gens=4)
    idle = (("mid-job", 9, 0),    # oldest resident, client polling it
            ("parked", 4, 40),    # nobody has polled for 40 gens
            ("semi", 6, 10))
    d = p.decide(snap(queue=2, occ=1.0, lanes=8, idle=idle))
    # gens-idle order; the mid-job resident is excluded outright
    assert d.spill == ["parked", "semi"]


def test_spill_never_takes_actively_polled_tenants():
    """Mid-job residents whose clients are interacting (gens-idle 0)
    are never spilled, no matter their residency age — the
    spill-thrash fix for the BENCH_SERVICE bursty pair."""
    p = policy(up_after=1, max_lanes=8, spill_idle_segments=2,
               spill_idle_gens=1)
    idle = (("hot1", 50, 0), ("hot2", 60, 0))
    d = p.decide(snap(queue=3, occ=1.0, lanes=8, idle=idle))
    assert d.spill == []


def test_spill_legacy_pairs_still_use_residency():
    """2-tuple snapshots (no idleness signal) keep the pre-ISSUE-12
    residency-age behaviour."""
    p = policy(up_after=1, max_lanes=8, spill_idle_segments=4)
    d = p.decide(snap(queue=1, occ=1.0, lanes=8,
                      idle=(("t-old", 9), ("t-young", 1))))
    assert d.spill == ["t-old"]


def test_buckets_are_independent():
    p = policy(up_after=2)
    two = {**snap(queue=3, lanes=8),
           "quiet": {"queue_depth": 0, "occupancy": 0.1, "lanes": 8,
                     "queue_wait_p99": None, "idle": ()}}
    p.decide(two)
    d = p.decide(two)
    assert set(d.lane_counts) == {"b"}   # quiet bucket untouched


def test_decision_truthiness():
    p = policy()
    assert not p.decide(snap())          # empty decision is falsy
