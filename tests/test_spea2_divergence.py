"""Quantified SPEA2 divergences vs the reference implementation.

sel_spea2 documents the deliberate divergences from the reference's
selSPEA2 (/root/reference/deap/tools/emo.py:692-842):

1. (closed in r5/r6) the truncation tie-break formerly capped its
   lexicographic compare at depth 8; r5 took it to full depth with
   the reference's lowest-alive-index residual tie-break (exact set
   parity in float64), and r6 closed the float32 gap: the truncation
   loop's distances are computed in double-float32 (error-free
   two-sum/two-product, ~48 significant bits) and compared
   lexicographically on (hi, lo), so the f32 path reproduces the
   reference's float64 tie structure exactly GIVEN THE SAME INPUTS.
   What remains out of reach by construction is caller-side input
   quantization: objectives rounded to f32 before selection are
   different numbers than their f64 originals, and no selector
   arithmetic can recover ordering information destroyed upstream —
   the f32 test therefore feeds both implementations the same
   f32-quantized values;
2. the reference's upper-triangular density artifact (distances only
   filled for j > i, emo.py:733-740) is *not* reproduced — we use the
   full distance matrix the paper specifies;
3. sel_spea2_stream's bounded-candidate environmental step replaces
   the iterative minimum-distance removal loop.

VERDICT r2 weak #4 asked that each divergence be *measured*, not
assumed. This module runs both implementations on adversarial
(tie-heavy) and random fronts and asserts selection-set overlap
bounds; the measured numbers are recorded in PARITY.md.

Skipped (like test_stream_parity) when the reference tree or 2to3 is
unavailable.
"""

import pathlib
import random
import shutil
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deap_tpu import mo

REF = pathlib.Path("/root/reference/deap")
SCRATCH = pathlib.Path("/tmp/refdeap_parity")
TOOL = shutil.which("2to3")

pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not REF.exists() or TOOL is None,
                       reason="reference tree or 2to3 not available"),
]


@pytest.fixture(scope="module")
def ref_tools():
    """The 2to3-converted reference's tools module (same scratch cache
    as test_stream_parity)."""
    import test_stream_parity as tsp

    tsp.require_vetted_reference()
    marker = SCRATCH / ".converted"
    fingerprint = tsp._ref_fingerprint()
    if not (marker.exists() and marker.read_text() == fingerprint):
        # rebuild via the parity harness's cache recipe: the
        # fingerprint check keeps the 2to3 scratch honest when the
        # reference tree changes
        if SCRATCH.exists():
            shutil.rmtree(SCRATCH)
        SCRATCH.mkdir(parents=True)
        shutil.copytree(REF, SCRATCH / "deap")
        subprocess.run(
            [TOOL, "-w", "-n", "--no-diffs", str(SCRATCH / "deap")],
            check=True, capture_output=True, timeout=300)
        marker.write_text(fingerprint)
    sys.path.insert(0, str(SCRATCH))
    try:
        import deap.base  # noqa: F401
        import deap.tools as rt

        yield rt
    finally:
        sys.path.remove(str(SCRATCH))


def _ref_select(ref_tools_mod, w: np.ndarray, k: int) -> set:
    """Run the reference selSPEA2 on maximisation objectives ``w``."""
    import deap.base as ref_base

    class F(ref_base.Fitness):
        weights = (1.0,) * w.shape[1]

    pop = []
    for i, row in enumerate(w):
        ind = type("I", (list,), {})([0.0])
        ind.fitness = F()
        ind.fitness.values = tuple(float(v) for v in row)
        ind.idx = i
        pop.append(ind)
    random.seed(0)  # _randomizedSelect pivots
    return {ind.idx for ind in ref_tools_mod.selSPEA2(pop, k)}


def _our_select(w: np.ndarray, k: int, x64: bool = False) -> set:
    """x64=True runs the selector in float64 — required for exact
    reference parity on tie-heavy fronts, where the tie structure of
    squared distances is precision-dependent (sel_spea2 is
    dtype-preserving, so the cast here decides the arithmetic)."""
    if x64:
        with jax.enable_x64(True):
            idx = mo.sel_spea2(jax.random.key(0),
                               jnp.asarray(w, jnp.float64), k)
            return {int(i) for i in np.asarray(idx)}
    idx = mo.sel_spea2(jax.random.key(0), jnp.asarray(w, jnp.float32), k)
    return {int(i) for i in np.asarray(idx)}


def _overlap(a: set, b: set, k: int) -> float:
    return len(a & b) / k


# ---------------------------------------------------------------- fronts ----

def _random_mixed(n, seed, nobj=2):
    rng = np.random.default_rng(seed)
    return rng.uniform(0.0, 10.0, (n, nobj))


def _overfull_front(n, seed):
    """All mutually non-dominated (f2 = 10 - f1): truncation active."""
    rng = np.random.default_rng(seed)
    f1 = np.sort(rng.uniform(0.0, 10.0, n))
    return np.stack([f1, 10.0 - f1], axis=1)


def _tie_heavy_front(n):
    """Adversarial for the depth-8 tie cap: an equally spaced trade-off
    line with every point duplicated — NN distances are massively tied
    (0 to the twin, one shared spacing to both neighbours), so the
    truncation's lexicographic compare runs deep before differing."""
    m = n // 2
    f1 = np.linspace(0.0, 10.0, m)
    pts = np.stack([f1, 10.0 - f1], axis=1)
    return np.repeat(pts, 2, axis=0)


def test_spea2_random_front_overlap(ref_tools):
    """Random mixed fronts: divergences only bite on exact-tie
    truncation and the density artifact, so overlap stays high."""
    scores = []
    for seed in (1, 2, 3):
        w = _random_mixed(200, seed)
        ours = _our_select(w, 60)
        refs = _ref_select(ref_tools, w, 60)
        scores.append(_overlap(ours, refs, 60))
    print("random-front overlaps:", scores)
    assert min(scores) >= 0.95, scores


def test_spea2_overfull_truncation_overlap(ref_tools):
    """All-nondominated archive, truncation removes 70% — the loop the
    depth cap + full-matrix density could diverge on."""
    scores = []
    for seed in (5, 6, 7):
        w = _overfull_front(200, seed)
        ours = _our_select(w, 60)
        refs = _ref_select(ref_tools, w, 60)
        scores.append(_overlap(ours, refs, 60))
    print("overfull-front overlaps:", scores)
    assert min(scores) >= 0.95, scores


def test_spea2_tie_heavy_truncation_exact(ref_tools):
    """The adversarial case for truncation tie-breaking. Since r5 the
    removal loop compares sorted-distance vectors to FULL depth with
    lowest-alive-index residual tie-break — the reference's exact rule
    (emo.py:776-790) — so in float64 the selected SET must match the
    reference exactly. (The historic 0.875/0.85 overlaps came from the
    depth-8 cap and from float32 distance ties; both are now closed —
    f32 remains the documented precision divergence below.)"""
    w = _tie_heavy_front(120)           # 60 duplicate pairs
    k = 80                              # keep more than the 60 pairs
    ours = _our_select(w, k, x64=True)
    refs = _ref_select(ref_tools, w, k)
    ov = _overlap(ours, refs, k)
    print("tie-heavy overlap (f64):", ov)
    assert ov == 1.0, ov


def test_spea2_tie_heavy_truncation_f32_exact(ref_tools):
    """float32 run of the same front, BOTH implementations fed the
    same f32-quantized objectives (float() of an f32 value is exact,
    so the reference sees bit-identical inputs): since the truncation
    loop compares double-float32 distances — f64-equivalent given the
    inputs, pinned reference-free by tests/test_mo.py — the selected
    SET must now match the reference exactly in f32 too. (Historic:
    0.85 overlap when plain f32 distances collapsed distinct f64
    distances into spurious ties — VERDICT r5 weak #7, closed.)"""
    w = _tie_heavy_front(120).astype(np.float32)
    k = 80
    ours = _our_select(w, k)
    refs = _ref_select(ref_tools, w.astype(np.float64), k)
    ov = _overlap(ours, refs, k)
    print("tie-heavy overlap (f32):", ov)

    # structural check kept: among the 40 dropped, every duplicate
    # pair retains at least one member (maximal spread under ties)
    def pair_counts(sel):
        c = np.zeros(60, np.int32)
        for i in sel:
            c[i // 2] += 1
        return c

    for name, sel in (("ours", ours), ("ref", refs)):
        c = pair_counts(sel)
        assert (c >= 1).all(), (name, c)
    assert ov == 1.0, ov


def test_spea2_underfull_density_fill_overlap(ref_tools):
    """Under-full archive → density fill ranks the dominated rows.
    Here the reference's upper-triangle artifact (emo.py:733-740) is
    the live divergence: its kth-NN distance for row i only sees
    j > i. Overlap is therefore the measured cost of NOT reproducing
    the artifact."""
    scores = []
    for seed in (11, 12, 13):
        rng = np.random.default_rng(seed)
        # a dominated cascade: only ~8 rows non-dominated, k = 60
        base = rng.uniform(0, 1, (200, 1))
        w = np.concatenate([base, base], axis=1) * 10.0
        w += rng.uniform(0, 0.05, w.shape)
        ours = _our_select(w, 60)
        refs = _ref_select(ref_tools, w, 60)
        scores.append(_overlap(ours, refs, 60))
    print("underfull-fill overlaps:", scores)
    assert min(scores) >= 0.95, scores


def test_spea2_stream_vs_dense():
    """sel_spea2_stream's bounded-candidate step vs the dense
    selector, on sizes where both run: divergence shrinks as the
    candidate budget grows (the documented convergence claim)."""
    rng = np.random.default_rng(21)
    w = rng.uniform(0, 10, (2048, 2)).astype(np.float32)
    k = 256
    dense = _our_select(w, k)
    lo = {int(i) for i in np.asarray(mo.sel_spea2_stream(
        jax.random.key(1), jnp.asarray(w), k, candidates=k))}
    hi = {int(i) for i in np.asarray(mo.sel_spea2_stream(
        jax.random.key(1), jnp.asarray(w), k, candidates=2048))}
    ov_lo = _overlap(lo, dense, k)
    ov_hi = _overlap(hi, dense, k)
    print(f"stream-vs-dense overlap: candidates=k {ov_lo:.3f}, "
          f"candidates=n {ov_hi:.3f}")
    assert ov_hi >= ov_lo - 0.05        # budget growth must not hurt
    assert ov_hi >= 0.95, ov_hi
