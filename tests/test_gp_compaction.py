"""On-device GP variation compaction — np.resize semantics + parity.

Satellite contract of the fused-variation PR: before the on-device
prefix-sum compaction replaced the host ``np.nonzero``/``np.resize``
round trip, the host path's exact pad behaviour (np.resize pads by
CYCLING the source array) is pinned here as a regression oracle — so
device-vs-host parity is a tested equality of padded index arrays, not
an assertion in a docstring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import gp
from deap_tpu.gp.interpreter import _round_size, compact_indices
from deap_tpu.gp.loop import (make_compaction_pipelines,
                              make_flag_compactor, make_symbreg_loop)


# ------------------------------------------------ np.resize semantics ----

def test_np_resize_pads_by_cycling():
    """The host compaction's pad rule, pinned: ``np.resize(a, P)``
    repeats the source cyclically — out[k] == a[k % len(a)] — for both
    growth and truncation. The device compaction reproduces exactly
    this rule; if a numpy upgrade ever changed it, this test (not a
    silent parity break) is what fails."""
    a = np.asarray([5, 9, 2])
    np.testing.assert_array_equal(np.resize(a, 7),
                                  [5, 9, 2, 5, 9, 2, 5])
    np.testing.assert_array_equal(np.resize(a, 2), [5, 9])
    idx = np.arange(7) % len(a)
    np.testing.assert_array_equal(np.resize(a, 7), a[idx])


@pytest.mark.parametrize("n,p,seed", [(100, 0.3, 0), (64, 0.0, 1),
                                      (64, 1.0, 2), (1, 0.5, 3),
                                      (7, 0.6, 4), (513, 0.1, 5)])
def test_compact_indices_matches_nonzero_resize(n, p, seed):
    mask = np.asarray(jax.random.bernoulli(jax.random.key(seed), p,
                                           (n,)))
    idx, count = jax.jit(compact_indices, static_argnums=1)(
        jnp.asarray(mask), n)
    idx, count = np.asarray(idx), int(count)
    nz = np.nonzero(mask)[0]
    assert count == len(nz)
    if count:
        np.testing.assert_array_equal(idx, np.resize(nz, n))
        # and every lattice slice equals the host path's padded array
        for P in {min(_round_size(count), n), min(count, n), n}:
            np.testing.assert_array_equal(idx[:P], np.resize(nz, P))
    else:
        assert not idx.any()


def test_compact_indices_is_jit_static_shaped():
    """Same compiled shape for every count — the property that lets the
    compaction live inside one jit with zero host involvement."""
    f = jax.jit(compact_indices, static_argnums=1)
    shapes = set()
    for seed in range(4):
        mask = jax.random.bernoulli(jax.random.key(seed), 0.4, (96,))
        idx, count = f(mask, 96)
        shapes.add(idx.shape)
    assert shapes == {(96,)}


# ----------------------------------------------------- pipeline parity ----

@pytest.mark.parametrize("n", [2, 101, 1000])
def test_compaction_pipelines_bit_identical(n):
    host_fn, dev_fn = make_compaction_pipelines(0.5, 0.1)
    key = jax.random.key(n)
    (h, hc), (d, dc) = host_fn(key, n), dev_fn(key, n)
    assert hc == dc
    for a, b in zip(h, d):
        assert a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_flag_compactor_counts_match_flags():
    fc = make_flag_compactor(0.6, 0.2)
    n = 200
    cx_idx, mut_idx, t_idx, counts = fc(jax.random.key(1), n)
    n_cx, n_mut, n_t = (int(c) for c in np.asarray(counts))
    k_pair, k_ind = jax.random.split(jax.random.key(1))
    do_cx = np.asarray(jax.random.bernoulli(k_pair, 0.6, (n // 2,)))
    do_mut = np.asarray(jax.random.bernoulli(k_ind, 0.2, (n,)))
    assert n_cx == do_cx.sum() and n_mut == do_mut.sum()
    touched = do_mut.copy()
    touched[np.repeat(np.nonzero(do_cx)[0] * 2, 2)
            + np.tile([0, 1], do_cx.sum())] = True
    assert n_t == touched.sum()
    np.testing.assert_array_equal(np.asarray(t_idx)[:n_t],
                                  np.nonzero(touched)[0])


def test_resolve_compaction_auto_and_validation():
    from deap_tpu.gp.loop import resolve_compaction

    assert resolve_compaction("device") == "device"
    assert resolve_compaction("host") == "host"
    expect = "host" if jax.default_backend() == "cpu" else "device"
    assert resolve_compaction("auto") == expect
    with pytest.raises(ValueError, match="compaction"):
        resolve_compaction("nope")


# ------------------------------------------------- full-loop parity ----

def test_gp_loop_device_compaction_bit_identical():
    """The whole host-dispatch GP engine, host- vs device-compacted:
    same key → identical final genomes, depths, fitness, nevals."""
    POP, ml = 128, 48
    ps = gp.math_set(n_args=1)
    ps.arity_table()
    X = jnp.linspace(-1.0, 1.0, 32, endpoint=False)[:, None]
    y = X[:, 0] ** 2 + X[:, 0]
    gen = gp.gen_half_and_half(ps, ml, 1, 2)
    genomes = jax.vmap(gen)(jax.random.split(jax.random.key(3), POP))
    res = {}
    for mode in ("host", "device"):
        run = make_symbreg_loop(ps, ml, X, y, height_limit=6,
                                compaction=mode)
        res[mode] = run(jax.random.key(0), genomes, 8)
    a, b = res["host"], res["device"]
    for x, yv in zip(jax.tree_util.tree_leaves(a["genomes"]),
                     jax.tree_util.tree_leaves(b["genomes"])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(yv))
    np.testing.assert_array_equal(np.asarray(a["fitness"]),
                                  np.asarray(b["fitness"]))
    np.testing.assert_array_equal(np.asarray(a["depths"]),
                                  np.asarray(b["depths"]))
    assert a["nevals"] == b["nevals"]
    assert a["best_fitness"] == b["best_fitness"]


def test_gp_loop_journal_evidence(tmp_path):
    """The journal/span evidence behind 'zero host syncs in the
    variation compaction': the device-path run journals
    ``variation_dispatch`` with a 12-byte per-generation host fetch,
    and the host path's full-array fetch span never appears in its
    span aggregates (while the host-path run's does)."""
    from deap_tpu.telemetry import RunTelemetry
    from deap_tpu.telemetry.journal import read_journal

    POP, ml = 64, 32
    ps = gp.math_set(n_args=1)
    ps.arity_table()
    X = jnp.linspace(-1.0, 1.0, 16, endpoint=False)[:, None]
    y = X[:, 0] ** 2
    gen = gp.gen_half_and_half(ps, ml, 1, 2)
    genomes = jax.vmap(gen)(jax.random.split(jax.random.key(4), POP))

    spans = {}
    for mode in ("host", "device"):
        path = str(tmp_path / f"{mode}.jsonl")
        with RunTelemetry(path) as tel:
            run = make_symbreg_loop(ps, ml, X, y, compaction=mode,
                                    telemetry=tel)
            run(jax.random.key(0), genomes, 4)
        rows = read_journal(path)
        disp = [e for e in rows
                if e.get("kind") == "variation_dispatch"
                and e.get("op") == "gp_loop"]
        assert disp and disp[0]["path"] == mode
        if mode == "device":
            assert disp[0]["host_fetch_bytes_per_gen"] == 12
        else:
            assert disp[0]["host_fetch_bytes_per_gen"] > POP
        spans[mode] = {e.get("name") for e in rows
                       if e.get("kind") == "span"}
    assert "gp_loop/host_compaction_fetch" in spans["host"]
    assert "gp_loop/host_compaction_fetch" not in spans["device"]
