"""Search-dynamics probes (telemetry.probes) — ISSUE 4's contract.

Four layers:

1. probe math against oracles (hv_proxy vs the native WFG
   hypervolume, unique counts vs numpy, selection pressure on crafted
   index vectors, stagnation bookkeeping over a synthetic scan);
2. the pinned-parity guarantee: probes on/off leaves
   populations/logbooks/hofs bit-identical across all four
   algorithms.py loops, the island mesh path and the GP host loop;
3. HealthMonitor tripwires on synthetic rows + journal wiring;
4. the acceptance runs: an OneMax ea_simple journal and an 8-island +
   genome-shard journal each carrying >= 6 distinct probe metrics per
   generation plus a synthetic-triggered alarm, rendered end-to-end by
   ``bench_report.py --health`` in a subprocess that never imports
   jax.
"""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.telemetry import (
    DiversityProbe,
    FitnessProbe,
    FrontProbe,
    HealthMonitor,
    Meter,
    RunTelemetry,
    SelectionProbe,
    TreeDiversityProbe,
    exact_hypervolume,
    read_journal,
)
from deap_tpu.telemetry.probes import _unique_count

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def _onemax_toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def _onemax_pop(key, n=64, length=32):
    return init_population(key, n, ops.bernoulli_genome(length),
                           FitnessSpec((1.0,)))


def _probe_set(n):
    return [DiversityProbe(sample=16), FitnessProbe(),
            SelectionProbe(n=n)]


def _mo_pop(w, key=None, spec_len=None):
    w = jnp.asarray(w, jnp.float32)
    m = spec_len or w.shape[1]
    pop = init_population(key or jax.random.key(0), w.shape[0],
                          ops.bernoulli_genome(8), FitnessSpec((1.0,) * m))
    return pop.with_fitness(w)


# ========================================================= probe math ====

def test_unique_count_matches_numpy():
    rng = np.random.RandomState(0)
    base = rng.randint(-5, 5, size=(37, 9)).astype(np.int32)
    rows = np.concatenate([base, base[:11]])  # guaranteed clones
    got = int(_unique_count(jnp.asarray(rows)))
    want = len(np.unique(rows, axis=0))
    assert got == want
    assert int(_unique_count(jnp.asarray(rows[:1]))) == 1


def test_diversity_probe_clones_and_distances():
    # 4 copies each of two antipodal bitstrings: 2 distinct of 8 rows,
    # every cross-pair distance = sqrt(L), every same-pair = 0
    L = 16
    a = np.zeros(L, bool)
    b = np.ones(L, bool)
    g = jnp.asarray(np.stack([a, b] * 4))
    pop = init_population(jax.random.key(0), 8, ops.bernoulli_genome(L),
                          FitnessSpec((1.0,)))
    pop = pop.replace(genomes=g).with_fitness(jnp.zeros(8))
    m = Meter()
    p = DiversityProbe(sample=8)
    p.declare(m)
    s = p(m, m.init(), pop=pop)
    assert float(s["div_unique_frac"]) == 0.25
    assert float(s["div_pdist_min"]) == 0.0  # clones exist
    # ordered cross pairs: 32 of 56 at distance sqrt(16)=4
    np.testing.assert_allclose(float(s["div_pdist_mean"]),
                               4.0 * 32 / 56, rtol=1e-6)
    # msd identity: mean over ordered pairs of squared distance
    np.testing.assert_allclose(float(s["div_msd"]), 16.0 * 32 / 56,
                               rtol=1e-6)


def test_tree_diversity_probe_entropy_and_clones():
    gp = pytest.importorskip("deap_tpu.gp")
    ps = gp.math_set(n_args=1)
    n_ops = ps.n_ops
    L = 8
    # genome 0: single terminal (no ops); genome 1..3: op 0 at root —
    # clones of each other; genome 4: op 1 at root
    term = n_ops  # ARG0
    rows = np.full((5, L), term, np.int32)
    lengths = np.array([1, 3, 3, 3, 3], np.int32)
    for i in (1, 2, 3):
        rows[i, 0] = 0
    rows[4, 0] = 1
    genomes = {"nodes": jnp.asarray(rows),
               "consts": jnp.zeros((5, L), jnp.float32),
               "length": jnp.asarray(lengths)}
    pop = init_population(jax.random.key(0), 5, ops.bernoulli_genome(4),
                          FitnessSpec((1.0,)))
    pop = pop.replace(genomes=genomes).with_fitness(jnp.zeros(5))
    m = Meter()
    p = TreeDiversityProbe(ps)
    p.declare(m)
    s = p(m, m.init(), pop=pop)
    # opcode histogram: op0 x3, op1 x1 -> H = -(3/4 ln 3/4 + 1/4 ln 1/4)
    want_h = -(0.75 * np.log(0.75) + 0.25 * np.log(0.25))
    np.testing.assert_allclose(float(s["gp_opcode_entropy"]), want_h,
                               rtol=1e-5)
    assert float(s["gp_clone_rate"]) == pytest.approx(1 - 3 / 5)
    assert float(s["gp_mean_size"]) == pytest.approx(np.mean(lengths))
    # the host-dispatch loop hands over the interpreter's exact count
    s2 = p(m, m.init(), pop=pop, host_clone_rate=0.125)
    assert float(s2["gp_clone_rate"]) == 0.125


def test_fitness_probe_velocity_and_stagnation():
    m = Meter()
    p = FitnessProbe()
    p.declare(m)
    s = m.init()
    bests = [1.0, 3.0, 3.0, 3.0, 5.0]
    ages, vels = [], []
    for b in bests:
        pop = _mo_pop(np.full((8, 1), b, np.float32))
        s = p(m, s, pop=pop)
        ages.append(int(s["stagnation_age"]))
        vels.append(float(s["fit_velocity"]))
    assert ages == [0, 0, 1, 2, 0]
    assert vels == [0.0, 2.0, 0.0, 0.0, 2.0]
    assert float(s["fit_gap"]) == 0.0  # best == median on a flat pop


def test_selection_probe_pressure_math():
    m = Meter()
    p = SelectionProbe(n=8)
    p.declare(m)
    s = m.init()
    # all 8 selections hit row 0: eff parents 1, 7/8 never selected
    s = p(m, s, sel_idx=jnp.zeros(8, jnp.int32), sel_pool=8,
          parent_idx=jnp.zeros(8, jnp.int32))
    assert float(s["sel_eff_parents"]) == pytest.approx(1.0)
    assert float(s["sel_loss_diversity"]) == pytest.approx(7 / 8)
    assert float(s["lineage_depth_mean"]) == 1.0
    # uniform selection: eff parents n, loss 0
    s = p(m, s, sel_idx=jnp.arange(8), sel_pool=8,
          parent_idx=jnp.arange(8))
    assert float(s["sel_eff_parents"]) == pytest.approx(8.0)
    assert float(s["sel_loss_diversity"]) == 0.0
    assert int(s["lineage_depth_max"]) == 2


def test_selection_probe_every_decimation():
    """every=k updates the pressure gauges on k-th generations only
    (holding in between) while lineage advances every generation."""
    m = Meter()
    p = SelectionProbe(n=4, every=2)
    p.declare(m)
    s = m.init()
    uni, conc = jnp.arange(4), jnp.zeros(4, jnp.int32)
    s = p(m, s, sel_idx=uni, sel_pool=4, parent_idx=uni,
          gen=jnp.int32(0))                       # gen 0: updates
    assert float(s["sel_eff_parents"]) == pytest.approx(4.0)
    s = p(m, s, sel_idx=conc, sel_pool=4, parent_idx=conc,
          gen=jnp.int32(1))                       # gen 1: held
    assert float(s["sel_eff_parents"]) == pytest.approx(4.0)
    assert int(s["lineage_depth_max"]) == 2       # lineage not held
    s = p(m, s, sel_idx=conc, sel_pool=4, parent_idx=conc,
          gen=jnp.int32(2))                       # gen 2: updates
    assert float(s["sel_eff_parents"]) == pytest.approx(1.0)


@pytest.mark.parametrize("m_obj", [1, 2, 3])
def test_front_probe_hv_matches_native_oracle(m_obj):
    """hv_proxy is the EXACT hypervolume of the sampled points — pin it
    against the native WFG implementation, including duplicates and
    dominated points."""
    rng = np.random.RandomState(7 + m_obj)
    w = rng.rand(60, m_obj).astype(np.float32)
    w[10] = w[3]          # duplicate
    w[11] = w[4] * 0.5    # dominated
    pop = _mo_pop(w)
    m = Meter()
    p = FrontProbe(ref=(0.0,) * m_obj, max_points=64)
    p.declare(m)
    s = jax.jit(lambda pp: p(m, m.init(), pop=pp))(pop)
    np.testing.assert_allclose(
        float(s["hv_proxy"]),
        exact_hypervolume(w, (0.0,) * m_obj), rtol=1e-5)
    assert 0.0 < float(s["front_frac"]) <= 1.0
    assert float(s["front_spread"]) >= 0.0


def test_front_probe_rejects_high_m_and_ref_mismatch():
    pop = _mo_pop(np.random.RandomState(0).rand(10, 4).astype(np.float32))
    m = Meter()
    p = FrontProbe(ref=(0.0,) * 4)
    p.declare(m)
    with pytest.raises(ValueError, match="M <= 3"):
        p(m, m.init(), pop=pop)
    p2 = FrontProbe(ref=(0.0, 0.0))
    p2.declare(m)
    with pytest.raises(ValueError, match="objectives"):
        p2(m, m.init(), pop=pop)


def test_front_probe_exact_every_journals_host_hv(tmp_path):
    """exact_every=k ships the sample to the host every k gens and the
    native exact hypervolume lands as hv_exact events agreeing with the
    in-scan proxy."""
    w = np.random.RandomState(3).rand(32, 2).astype(np.float32)
    pop = _mo_pop(w)
    path = str(tmp_path / "hv.jsonl")
    with RunTelemetry(path) as tel:
        tel.journal.header(init_backend=False)
        p = FrontProbe(ref=(0.0, 0.0), max_points=32, exact_every=2)
        p.declare(tel.meter)
        s = tel.meter.init()
        for gen in range(4):
            s = p(tel.meter, s, pop=pop, gen=jnp.int32(gen),
                  journal=tel.journal)
        jax.effects_barrier()
        proxy = float(s["hv_proxy"])
    hv = [e for e in read_journal(path) if e["kind"] == "hv_exact"]
    assert [e["gen"] for e in hv] == [0, 2]
    for e in hv:
        assert e["value"] == pytest.approx(proxy, rel=1e-5)


def test_meter_internal_gauges_stay_out_of_rows():
    m = Meter()
    m.gauge("visible")
    m.gauge("carry", internal=True)
    m.gauge("depths", shape=(4,), dtype=jnp.int32, internal=True)
    s = m.init()
    row = m.row(s)
    assert "visible" in row
    assert "carry" not in row and "depths" not in row
    assert "carry" in s  # still real carry state


# ================================================== pinned parity ====

def test_probes_pinned_identical_across_loops(tmp_path):
    """The PR 2 meter guarantee extended to probes: probe-on runs leave
    populations/logbooks/hofs bit-identical across all four loops."""
    tb = _onemax_toolbox()
    pop0 = _onemax_pop(jax.random.key(1))
    runs = {
        "ea_simple": lambda tel, pr: algorithms.ea_simple(
            jax.random.key(2), pop0, tb, 0.5, 0.2, 6, halloffame_size=3,
            telemetry=tel, probes=pr),
        "ea_mu_plus_lambda": lambda tel, pr: algorithms.ea_mu_plus_lambda(
            jax.random.key(3), pop0, tb, mu=64, lambda_=64, cxpb=0.5,
            mutpb=0.2, ngen=6, telemetry=tel, probes=pr),
        "ea_mu_comma_lambda": lambda tel, pr: algorithms.ea_mu_comma_lambda(
            jax.random.key(4), pop0, tb, mu=64, lambda_=96, cxpb=0.5,
            mutpb=0.2, ngen=6, telemetry=tel, probes=pr),
    }
    for name, run in runs.items():
        base_pop, base_lb, base_hof = run(None, ())
        with RunTelemetry(str(tmp_path / f"{name}.jsonl")) as tel:
            tel_pop, tel_lb, tel_hof = run(tel, _probe_set(64))
        np.testing.assert_array_equal(
            np.asarray(base_pop.genomes), np.asarray(tel_pop.genomes),
            err_msg=f"{name}: genomes drifted under probes")
        np.testing.assert_array_equal(
            np.asarray(base_pop.fitness), np.asarray(tel_pop.fitness),
            err_msg=f"{name}: fitness drifted under probes")
        assert base_lb.select("nevals") == tel_lb.select("nevals"), name
        if base_hof is not None:
            np.testing.assert_array_equal(
                np.asarray(base_hof.fitness), np.asarray(tel_hof.fitness),
                err_msg=f"{name}: hall of fame drifted under probes")
        meters = [e for e in read_journal(str(tmp_path / f"{name}.jsonl"))
                  if e["kind"] == "meter"]
        probe_keys = [k for k in meters[-1]
                      if k.startswith(("div_", "fit_", "sel_",
                                       "stagnation"))]
        assert len(probe_keys) >= 6, (name, sorted(meters[-1]))


def test_probes_pinned_identical_generate_update(tmp_path):
    """ea_generate_update: probes compose with strategy_probe and the
    strategy state stays bit-identical."""
    from deap_tpu.strategies import cma
    from deap_tpu.telemetry import strategy_probe

    dim = 4
    strat = cma.Strategy(centroid=[0.5] * dim, sigma=0.3, lambda_=8)
    tb = Toolbox()
    tb.register("evaluate", lambda x: jnp.sum(x ** 2, axis=-1))
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)

    base_state, _, _ = algorithms.ea_generate_update(
        jax.random.key(3), strat.initial_state(), tb, ngen=5,
        spec=strat.spec)
    path = str(tmp_path / "cma.jsonl")
    with RunTelemetry(path, probe=strategy_probe(strat)) as tel:
        tel_state, _, _ = algorithms.ea_generate_update(
            jax.random.key(3), strat.initial_state(), tb, ngen=5,
            spec=strat.spec, telemetry=tel,
            probes=[DiversityProbe(sample=8), FitnessProbe()])
    np.testing.assert_array_equal(np.asarray(base_state.centroid),
                                  np.asarray(tel_state.centroid))
    np.testing.assert_array_equal(np.asarray(base_state.C),
                                  np.asarray(tel_state.C))
    meters = [e for e in read_journal(path) if e["kind"] == "meter"]
    assert len(meters) == 5
    for m in meters:
        assert m["sigma"] > 0          # strategy_probe still works
        assert "div_msd" in m and "stagnation_age" in m


def test_probes_require_telemetry():
    tb = _onemax_toolbox()
    pop0 = _onemax_pop(jax.random.key(1), n=8, length=8)
    with pytest.raises(ValueError, match="telemetry"):
        algorithms.ea_simple(jax.random.key(2), pop0, tb, 0.5, 0.2, 2,
                             probes=[FitnessProbe()])


def test_probes_pinned_identical_island_mesh(tmp_path):
    """The shard_map'd island path: probes + in-shard meter reductions
    leave the stacked populations bit-identical."""
    from deap_tpu.algorithms import evaluate_invalid
    from deap_tpu.parallel import island_init, make_island_step
    from deap_tpu.parallel.mesh import population_mesh, shard_population

    tb = _onemax_toolbox()
    mesh = population_mesh(8, ("island",))

    def mkpops():
        pops = island_init(jax.random.key(0), 8, 16,
                           ops.bernoulli_genome(24), FitnessSpec((1.0,)))
        pops = jax.vmap(lambda p: evaluate_invalid(p, tb.evaluate))(pops)
        return shard_population(pops, mesh, "island")

    pops_a = mkpops()
    step_a = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=2, mig_k=2,
                              mesh=mesh)
    for e in range(3):
        pops_a = step_a(jax.random.fold_in(jax.random.key(9), e), pops_a)

    pops_b = mkpops()
    path = str(tmp_path / "island.jsonl")
    with RunTelemetry(path) as tel:
        tel.journal.header(toolbox=tb)
        step_b = make_island_step(
            tb, cxpb=0.5, mutpb=0.2, freq=2, mig_k=2, mesh=mesh,
            telemetry=tel, probes=[DiversityProbe(sample=16),
                                   FitnessProbe()])
        mstate = tel.meter.init()
        for e in range(3):
            pops_b, mstate = step_b(
                jax.random.fold_in(jax.random.key(9), e), pops_b, mstate)
            tel.record_row(mstate, e)
    np.testing.assert_array_equal(np.asarray(pops_a.genomes),
                                  np.asarray(pops_b.genomes))
    np.testing.assert_array_equal(np.asarray(pops_a.fitness),
                                  np.asarray(pops_b.fitness))
    np.testing.assert_array_equal(np.asarray(pops_a.valid),
                                  np.asarray(pops_b.valid))


def test_probes_pinned_identical_gp_loop():
    """GP host-dispatch loop: probes leave the evolved population and
    best fitness bit-identical."""
    gp = pytest.importorskip("deap_tpu.gp")
    from deap_tpu.gp.loop import make_symbreg_loop

    POP, ml = 64, 24
    ps = gp.math_set(n_args=1)
    X = jnp.linspace(-1.0, 1.0, 16, endpoint=False)[:, None]
    y = X[:, 0] ** 2 + X[:, 0]
    gen = gp.gen_half_and_half(ps, ml, 1, 2)
    genomes = jax.vmap(gen)(jax.random.split(jax.random.key(3), POP))

    ra = make_symbreg_loop(ps, ml, X, y, height_limit=6)(
        jax.random.key(0), genomes, 4)
    import tempfile
    with RunTelemetry(tempfile.mktemp(suffix=".jsonl")) as tel:
        rb = make_symbreg_loop(
            ps, ml, X, y, height_limit=6, telemetry=tel,
            probes=[TreeDiversityProbe(ps), FitnessProbe(),
                    SelectionProbe(n=POP)])(jax.random.key(0), genomes, 4)
    np.testing.assert_array_equal(np.asarray(ra["genomes"]["nodes"]),
                                  np.asarray(rb["genomes"]["nodes"]))
    np.testing.assert_array_equal(np.asarray(ra["fitness"]),
                                  np.asarray(rb["fitness"]))
    assert ra["best_fitness"] == rb["best_fitness"]
    assert ra["nevals"] == rb["nevals"]


# ==================================================== health monitor ====

def test_health_monitor_each_tripwire_and_rearm():
    hm = HealthMonitor(clone_rate_max=0.5, diversity_floor=0.1,
                       stagnation_window=2)
    assert hm.check_row({"best": 1.0, "div_msd": 5.0}, gen=0) == []
    # clone spike via the div_unique_frac fallback
    a = hm.check_row({"best": 2.0, "div_unique_frac": 0.3}, gen=1)
    assert [x["alarm"] for x in a] == ["clone_spike"]
    # premature convergence fires once, re-arms on recovery
    a = hm.check_row({"best": 3.0, "div_msd": 0.01}, gen=2)
    assert [x["alarm"] for x in a] == ["premature_convergence"]
    assert hm.check_row({"best": 4.0, "div_msd": 0.01}, gen=3) == []
    hm.check_row({"best": 5.0, "div_msd": 5.0}, gen=4)   # recovery
    a = hm.check_row({"best": 6.0, "div_msd": 0.01}, gen=5)
    assert [x["alarm"] for x in a] == ["premature_convergence"]
    # zero-improvement: monitor tracks best itself (no stagnation_age)
    hm2 = HealthMonitor(stagnation_window=2)
    for g, b in enumerate([1.0, 1.0, 1.0]):
        fired = hm2.check_row({"best": b}, gen=g)
    assert [x["alarm"] for x in fired] == ["zero_improvement"]
    # fires once; improvement re-arms
    assert hm2.check_row({"best": 1.0}, gen=3) == []
    hm2.check_row({"best": 9.0}, gen=4)
    for g, b in enumerate([9.0, 9.0], start=5):
        fired = hm2.check_row({"best": b}, gen=g)
    assert [x["alarm"] for x in fired] == ["zero_improvement"]
    # stagnation_age from a FitnessProbe takes precedence
    hm3 = HealthMonitor(stagnation_window=3)
    assert hm3.check_row({"best": 1.0, "stagnation_age": 3}, gen=0)


def test_health_monitor_non_finite_and_early_stop():
    hm = HealthMonitor(early_stop=("non_finite",), improvement_eps=0.0)
    a = hm.check_row({"best": float("nan"), "mean": 1.0}, gen=7)
    assert a[0]["alarm"] == "non_finite" and a[0]["metrics"] == ["best"]
    assert hm.stop_requested
    calls = []
    hm2 = HealthMonitor(on_alarm=calls.append)
    hm2.check_row({"mean": float("inf")}, gen=1)
    assert calls and calls[0]["alarm"] == "non_finite"
    assert not hm2.stop_requested  # early_stop not armed


def test_health_monitor_premature_min_gen_gate():
    hm = HealthMonitor(diversity_floor=0.1, premature_min_gen=10)
    assert hm.check_row({"div_msd": 0.01}, gen=3)   # early: fires
    hm2 = HealthMonitor(diversity_floor=0.1, premature_min_gen=10)
    assert hm2.check_row({"div_msd": 0.01}, gen=50) == []  # late: ok


# ================================================== journal hardening ====

def test_read_journal_torn_tail(tmp_path):
    """A killed writer leaves a torn final line: default read returns
    the complete rows and reports the tear's byte offset; strict
    raises."""
    path = str(tmp_path / "torn.jsonl")
    good = b'{"kind": "header"}\n{"kind": "meter", "gen": 1}\n'
    with open(path, "wb") as fh:
        fh.write(good)
        fh.write(b'{"kind": "meter", "gen": 2, "best": 12.')  # killed here
    rows = read_journal(path)
    assert [e["kind"] for e in rows] == ["header", "meter"]
    assert rows.tear_offset == len(good)
    assert rows.skipped_offsets == []
    with pytest.raises(ValueError, match=f"byte {len(good)}"):
        read_journal(path, strict=True)


def test_read_journal_interior_garbage_offsets(tmp_path):
    path = str(tmp_path / "mid.jsonl")
    l1 = b'{"kind": "header"}\n'
    l2 = b'{"kind": "meter", "gen": 1,\n'  # crashed mid-write, newline
    with open(path, "wb") as fh:
        fh.write(l1 + l2 + b'{"kind": "summary"}\n')
    rows = read_journal(path)
    assert [e["kind"] for e in rows] == ["header", "summary"]
    assert rows.tear_offset is None
    assert rows.skipped_offsets == [len(l1)]
    with pytest.raises(ValueError):
        read_journal(path, strict=True)


def test_read_journal_clean_file_has_no_tear(tmp_path):
    path = str(tmp_path / "ok.jsonl")
    with open(path, "w") as fh:
        fh.write('{"kind": "header"}\n{"kind": "summary"}\n')
    rows = read_journal(path, strict=True)
    assert len(rows) == 2 and rows.tear_offset is None


# ======================================================== acceptance ====

def _render_health_no_jax(journal_path):
    """bench_report.py --health in a clean subprocess; assert jax never
    gets imported and return the rendered report."""
    code = (
        "import sys, runpy\n"
        f"sys.argv = ['bench_report.py', '--health', {journal_path!r}]\n"
        f"runpy.run_path({os.path.join(REPO, 'bench_report.py')!r}, "
        "run_name='__main__')\n"
        "assert 'jax' not in sys.modules, 'health report imported jax'\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    return r.stdout


def test_acceptance_ea_simple_probed_journal(tmp_path):
    """OneMax ea_simple: >= 6 distinct probe metrics per generation,
    a synthetic-triggered alarm, and the --health report renders it
    without jax."""
    tb = _onemax_toolbox()
    path = str(tmp_path / "run.jsonl")
    # synthetic trigger: a diversity floor no bitstring population can
    # satisfy, so premature_convergence must fire
    hm = HealthMonitor(diversity_floor=1e9, stagnation_window=1)
    with RunTelemetry(path, health=hm) as tel:
        algorithms.ea_simple(
            jax.random.key(2), _onemax_pop(jax.random.key(1)), tb,
            0.5, 0.2, 8, telemetry=tel, probes=_probe_set(64))
    events = read_journal(path)
    meters = [e for e in events if e["kind"] == "meter"]
    assert len(meters) == 9  # gen 0..8
    probe_names = {"div_msd", "div_pdist_mean", "div_pdist_std",
                   "div_pdist_min", "div_unique_frac", "fit_gap",
                   "fit_velocity", "stagnation_age", "sel_eff_parents",
                   "sel_loss_diversity", "lineage_depth_mean",
                   "lineage_depth_max"}
    for m in meters:
        assert len(probe_names & set(m)) >= 6, sorted(m)
    alarms = [e for e in events if e["kind"] == "alarm"]
    assert alarms, "synthetic threshold must trigger >= 1 alarm"
    assert any(a["alarm"] == "premature_convergence" for a in alarms)

    report = _render_health_no_jax(path)
    assert "div_msd" in report and "Alarms" in report
    assert "premature_convergence" in report


@pytest.mark.slow
def test_acceptance_island_genome_shard_probed_journal(tmp_path):
    """8-island + genome-shard acceptance run: per-epoch meter rows
    with >= 6 probe metrics, in-shard reduction spans, a synthetic
    alarm, and a no-jax --health render."""
    from deap_tpu.algorithms import evaluate_invalid
    from deap_tpu.parallel import island_init, make_island_step
    from deap_tpu.parallel.genome_shard import (genome_mesh,
                                                make_sharded_evaluator,
                                                shard_genomes)
    from deap_tpu.parallel.mesh import population_mesh, shard_population

    tb = _onemax_toolbox()
    path = str(tmp_path / "island.jsonl")
    hm = HealthMonitor(diversity_floor=1e9)
    with RunTelemetry(path, health=hm) as tel:
        tel.journal.header(toolbox=tb)
        mesh = population_mesh(8, ("island",))
        pops = island_init(jax.random.key(0), 8, 16,
                           ops.bernoulli_genome(24), FitnessSpec((1.0,)))
        pops = jax.vmap(lambda p: evaluate_invalid(p, tb.evaluate))(pops)
        pops = shard_population(pops, mesh, "island")
        step = make_island_step(
            tb, cxpb=0.5, mutpb=0.2, freq=2, mig_k=2, mesh=mesh,
            telemetry=tel,
            probes=[DiversityProbe(sample=16), FitnessProbe()])
        mstate = tel.meter.init()
        for epoch in range(3):
            pops, mstate = step(
                jax.random.fold_in(jax.random.key(9), epoch), pops,
                mstate)
            tel.record_row(mstate, epoch)
        gmesh = genome_mesh(n_pop_shards=1, n_genome_shards=8)
        g = jax.random.bernoulli(jax.random.key(5), 0.5, (16, 64))
        ev = make_sharded_evaluator(
            lambda s: s.sum(-1).astype(jnp.float32), gmesh,
            combine="sum")
        ev(shard_genomes(g, gmesh))

    events = read_journal(path)
    meters = [e for e in events if e["kind"] == "meter"]
    assert len(meters) == 3
    probe_names = {"div_msd", "div_pdist_mean", "div_pdist_std",
                   "div_pdist_min", "div_unique_frac", "fit_gap",
                   "fit_velocity", "stagnation_age"}
    for m in meters:
        assert len(probe_names & set(m)) >= 6, sorted(m)
        assert m["best"] > 0 and m["epochs"] >= 1
    alarms = [e for e in events if e["kind"] == "alarm"]
    assert any(a["alarm"] == "premature_convergence" for a in alarms)
    spans = {e["name"] for e in events if e["kind"] == "span"}
    # the meter reductions ride the sharded epoch under named spans
    assert {"island/pmax", "island/psum",
            "genome_shard/psum"} <= spans, spans

    report = _render_health_no_jax(path)
    assert "premature_convergence" in report
    assert "island/pmax" in report
