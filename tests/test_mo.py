"""Multi-objective selection tests: unit semantics + the reference's
quality-gate integration tests (NSGA-II/III on ZDT1, 100 gens, MU=16,
hypervolume > 116.0 with ref point [11, 11] — deap/tests/
test_algorithms.py:32,110-116,227-230)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from deap_tpu import benchmarks as bm
from deap_tpu import mo, ops
from deap_tpu.algorithms import evaluate_invalid, var_and
from deap_tpu.benchmarks.tools import hypervolume
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import Population, concat, gather, init_population
from deap_tpu.core.toolbox import Toolbox


def _w_min(values):
    return -jnp.asarray(values, jnp.float32)  # weights (-1, -1)


def test_nd_rank_three_fronts():
    values = jnp.array([
        [1.0, 1.0],   # front 0
        [2.0, 2.0],   # front 1 (dominated by [1,1])
        [1.0, 3.0],   # front 0 (incomparable with [1,1]? no — [1,1] dominates)
        [3.0, 3.0],   # front 2
    ])
    ranks = mo.nd_rank(_w_min(values))
    # [1,1] dominates all others; [2,2] and [1,3] incomparable
    np.testing.assert_array_equal(np.asarray(ranks), [0, 1, 1, 2])


def test_nd_rank_equal_fitness_share_rank():
    values = jnp.array([[1.0, 1.0], [1.0, 1.0], [2.0, 2.0]])
    ranks = mo.nd_rank(_w_min(values))
    np.testing.assert_array_equal(np.asarray(ranks), [0, 0, 1])


def test_crowding_distances_exact():
    # one front, 4 points on a line; interior distances per Deb's formula
    values = jnp.array([[0.0, 3.0], [1.0, 2.0], [2.0, 1.0], [3.0, 0.0]])
    w = _w_min(values)
    ranks = jnp.zeros(4, jnp.int32)
    d = mo.crowding_distances(w, ranks)
    d = np.asarray(d)
    assert np.isinf(d[0]) and np.isinf(d[3])
    # interior: ((2-0)/ (2*3)) * 2 objectives = 2/3 total
    np.testing.assert_allclose(d[1:3], 2.0 / 3.0, rtol=1e-5)


def test_sel_nsga2_takes_fronts_then_crowding():
    values = jnp.array([
        [0.0, 2.0], [2.0, 0.0], [1.0, 1.0],      # front 0
        [2.0, 2.0],                               # front 1
        [3.0, 3.0],                               # front 2
    ])
    idx = mo.sel_nsga2(None, _w_min(values), 4)
    picked = set(np.asarray(idx).tolist())
    assert {0, 1, 2} <= picked and 4 not in picked


def test_sel_tournament_dcd_prefers_dominating():
    values = jnp.array([[0.0, 0.0]] + [[5.0, 5.0]] * 7)
    idx = mo.sel_tournament_dcd(jax.random.key(0), _w_min(values), 8)
    # individual 0 dominates everyone: it must win every tournament it enters
    counts = np.bincount(np.asarray(idx), minlength=8)
    assert counts[0] >= 1
    # a dominated individual facing 0 never wins
    assert bool(jnp.all(values[idx].sum(-1) <= 10.0))


def test_sel_spea2_keeps_nondominated():
    values = jnp.array([
        [1.0, 4.0], [2.0, 2.0], [4.0, 1.0],      # nondominated
        [5.0, 5.0], [6.0, 6.0],
    ])
    idx = mo.sel_spea2(jax.random.key(1), _w_min(values), 3)
    assert set(np.asarray(idx).tolist()) == {0, 1, 2}
    # truncation: 4 nondominated, keep 3 — drops one of the crowded pair
    values = jnp.array([[0.0, 4.0], [1.9, 2.0], [2.0, 1.9], [4.0, 0.0]])
    idx = mo.sel_spea2(jax.random.key(2), _w_min(values), 3)
    picked = set(np.asarray(idx).tolist())
    assert len(picked) == 3 and {0, 3} <= picked


def test_sel_spea2_f32_truncation_matches_f64():
    """The float32 divergence gate, reference-free (VERDICT r5 weak
    #7): the truncation loop compares double-float32 (hi, lo)
    distances, so on the SAME inputs the f32 selection set must equal
    the f64 one — including the adversarial fully-tied front where
    plain f32 distances collapsed distinct f64 distances into spurious
    ties (historic 0.85 set overlap). f32 is the TPU-native dtype, so
    this pins exactly the on-chip behaviour; the reference-tree
    counterpart is tests/test_spea2_divergence.py."""
    fronts = []
    m = 60
    f1 = np.linspace(0.0, 10.0, m)
    fronts.append(np.repeat(np.stack([f1, 10.0 - f1], 1), 2, axis=0))
    rng = np.random.default_rng(3)
    fronts.append(rng.uniform(0.0, 10.0, (200, 2)))
    f1 = np.sort(rng.uniform(0.0, 10.0, 200))
    fronts.append(np.stack([f1, 10.0 - f1], axis=1))
    for w in fronts:
        w32 = w.astype(np.float32)
        k = (2 * len(w)) // 3
        ours = set(np.asarray(mo.sel_spea2(
            jax.random.key(0), jnp.asarray(w32), k)).tolist())
        with jax.experimental.enable_x64():
            ref = set(np.asarray(mo.sel_spea2(
                jax.random.key(0),
                jnp.asarray(w32.astype(np.float64)), k)).tolist())
        assert ours == ref, (len(ours & ref), k)


def test_uniform_reference_points():
    rp = mo.uniform_reference_points(3, p=4)
    assert rp.shape == (15, 3)
    np.testing.assert_allclose(np.asarray(rp.sum(1)), 1.0, rtol=1e-6)


ZDT1_SPEC = FitnessSpec((-1.0, -1.0))
NDIM = 5  # the reference gate config (test_algorithms.py:70)
MU = 16


def _zdt1_toolbox():
    tb = Toolbox()
    tb.register("evaluate", jax.vmap(bm.zdt1))
    tb.register("mate", ops.cx_simulated_binary_bounded, eta=20.0, low=0.0,
                up=1.0)
    tb.register("mutate", ops.mut_polynomial_bounded, eta=20.0, low=0.0,
                up=1.0, indpb=1.0 / NDIM)
    return tb


def _run_zdt1(key, environmental_select, ngen=100):
    tb = _zdt1_toolbox()
    kinit, krun = jax.random.split(jax.random.key(7) if key is None else key)
    pop = init_population(kinit, MU, ops.uniform_genome(NDIM), ZDT1_SPEC)
    pop = evaluate_invalid(pop, tb.evaluate)

    def step(pop, key):
        k1, k2, k3 = jax.random.split(key, 3)
        idx = mo.sel_tournament_dcd(k1, pop.wvalues, MU)
        off = var_and(k2, gather(pop, idx), tb, cxpb=0.9, mutpb=1.0)
        off = evaluate_invalid(off, tb.evaluate)
        pool = concat([pop, off])
        sel = environmental_select(k3, pool.wvalues, MU)
        return gather(pool, sel), None

    run = jax.jit(lambda pop, keys: lax.scan(step, pop, keys)[0])
    return run(pop, jax.random.split(krun, ngen))


def test_nsga2_zdt1_hypervolume_gate():
    pop = _run_zdt1(jax.random.key(11), mo.sel_nsga2)
    hv = hypervolume(pop, ref=[11.0, 11.0])
    assert hv > 116.0, hv  # optimum 120.777 (test_algorithms.py:32)
    # bounds check like the reference (:115-116)
    g = np.asarray(pop.genomes)
    assert g.min() >= 0.0 and g.max() <= 1.0


def test_nsga3_zdt1_hypervolume_gate():
    rp = mo.uniform_reference_points(2, p=12)
    select = lambda key, w, k: mo.sel_nsga3(key, w, k, rp)
    pop = _run_zdt1(jax.random.key(12), select)
    hv = hypervolume(pop, ref=[11.0, 11.0])
    assert hv > 116.0, hv
    g = np.asarray(pop.genomes)
    assert g.min() >= 0.0 and g.max() <= 1.0


def test_nsga3_with_memory_runs():
    rp = mo.uniform_reference_points(2, p=6)
    sel = mo.emo.SelNSGA3WithMemory(rp)
    values = jax.random.uniform(jax.random.key(3), (20, 2))
    idx1 = sel(jax.random.key(4), -values, 8)
    idx2 = sel(jax.random.key(5), -values, 8)
    assert idx1.shape == (8,) and idx2.shape == (8,)
    assert sel.memory is not None


def test_nd_rank_staircase_matches_matrix_oracle():
    """The exact O(n log n) bi-objective staircase sort must agree with
    the dominance-matrix peel on every tie structure: random rows,
    duplicated rows (fitness-grouping), grid ties (single-coordinate
    equality), a fully-tied population, a total-order chain, and a
    single front."""
    rng = np.random.default_rng(3)
    cases = [
        rng.uniform(0, 1, (257, 2)),
        np.repeat(rng.uniform(0, 1, (128, 2)), 2, axis=0),
        rng.integers(0, 7, (300, 2)).astype(float),
        np.tile(rng.uniform(0, 1, (1, 2)), (50, 1)),
        np.stack([np.arange(100.0), np.arange(100.0)], 1),
        np.stack([np.sort(rng.uniform(0, 1, 100)),
                  1 - np.sort(rng.uniform(0, 1, 100))], 1),
    ]
    for w in cases:
        w = jnp.asarray(w, jnp.float32)
        oracle = np.asarray(mo.emo.nd_rank(w, impl="matrix"))
        fast = np.asarray(mo.nd_rank_staircase(w))
        np.testing.assert_array_equal(fast, oracle)
        # max_rank sentinel contract matches too
        np.testing.assert_array_equal(
            np.asarray(mo.nd_rank_staircase(w, max_rank=2)),
            np.asarray(mo.emo.nd_rank(w, impl="matrix", max_rank=2)))


def test_nd_rank_staircase_dispatch_and_contract():
    """'auto' routes bi-objective populations >= the tiled threshold to
    the staircase path; >2 objectives must reject impl='staircase'
    loudly; return_peels reports the true front count."""
    rng = np.random.default_rng(4)
    w = jnp.asarray(rng.uniform(0, 1, (mo.emo.ND_TILED_THRESHOLD, 2)),
                    jnp.float32)
    auto = np.asarray(mo.emo.nd_rank(w))            # impl='auto'
    stair = np.asarray(mo.nd_rank_staircase(w))
    np.testing.assert_array_equal(auto, stair)
    with pytest.raises(ValueError, match="nobj"):
        mo.nd_rank_staircase(jnp.zeros((8, 3)))
    _, peels = mo.nd_rank_staircase(w, return_peels=True)
    _, peels_m = mo.emo.nd_rank(
        w[:512], impl="matrix", return_peels=True)
    _, peels_s = mo.nd_rank_staircase(w[:512], return_peels=True)
    assert int(peels_s) == int(peels_m)
    assert int(peels) >= int(peels_s)   # more rows, >= as many fronts


def test_sel_nsga2_staircase_matches_matrix():
    """sel_nsga2 selects the same SET whichever exact nd-sort backs it
    (crowding ties within a front can reorder, the set cannot
    change)."""
    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.uniform(0, 1, (400, 2)), jnp.float32)
    a = set(np.asarray(mo.sel_nsga2(None, w, 100, nd="matrix")).tolist())
    b = set(np.asarray(
        mo.sel_nsga2(None, w, 100, nd="staircase")).tolist())
    assert a == b


def test_nd_rank_max_rank_early_stop():
    w = jax.random.normal(jax.random.key(42), (60, 2))
    full = np.asarray(mo.emo.nd_rank(w, impl="matrix"))
    capped = np.asarray(mo.emo.nd_rank(w, max_rank=2, impl="matrix"))
    # first two fronts identical; everything deeper left at sentinel n
    assert (capped[full < 2] == full[full < 2]).all()
    assert (capped[full >= 2] == 60).all()


def test_sel_nsga2_rejects_unknown_nd():
    w = jax.random.normal(jax.random.key(0), (8, 2))
    with pytest.raises(ValueError, match="nd"):
        mo.sel_nsga2(jax.random.key(1), w, 4, nd="tilted")


def _near_ordered(n, key=7):
    """Near-totally-ordered population: ~n fronts, the peel loop's
    worst case (VERDICT r2 weak #3)."""
    base = jnp.arange(n, dtype=jnp.float32)
    jitter = 0.01 * jax.random.normal(jax.random.key(key), (n,))
    return jnp.stack([base, base + jitter], axis=1)  # maximisation


def test_nd_rank_cover_k_exact_for_topk():
    """cover_k stops peeling once k rows are ranked; the ranked prefix
    is exact and everything unpeeled keeps the rank-n sentinel, so any
    top-k cut is unchanged."""
    n, k = 200, 50
    w = _near_ordered(n)
    full = np.asarray(mo.emo.nd_rank(w, impl="matrix"))
    part = np.asarray(mo.emo.nd_rank(w, impl="matrix", cover_k=k))
    ranked = part < n
    assert ranked.sum() >= k
    assert (part[ranked] == full[ranked]).all()
    # ranked rows are exactly the best `covered` rows by true rank
    assert full[ranked].max() < full[~ranked].min()


def test_sel_nsga2_cover_k_matches_full_peel():
    """The default cover_k early exit must not change NSGA-II selection
    — on the many-front worst case and on a random population."""
    for w in (_near_ordered(128),
              jax.random.normal(jax.random.key(3), (128, 3))):
        ranks_full = mo.emo.nd_rank(w, impl="matrix")
        crowd = mo.emo.crowding_distances(w, ranks_full)
        want = np.asarray(jnp.lexsort((-crowd, ranks_full))[:48])
        got = np.asarray(mo.sel_nsga2(jax.random.key(0), w, 48))
        np.testing.assert_array_equal(got, want)


def test_nd_rank_count_fallback_ordering():
    """fallback='count' (Fonseca-Fleming dominance-count ranks past the
    peel budget) is exact on a totally ordered remainder and always
    dominance-consistent: a dominator ranks strictly better."""
    n = 100
    base = jnp.arange(n, dtype=jnp.float32)
    w_total = jnp.stack([base, base], axis=1)       # totally ordered
    exact = np.asarray(mo.emo.nd_rank(w_total, impl="matrix"))
    capped = np.asarray(mo.emo.nd_rank(
        w_total, impl="matrix", max_rank=5, fallback="count"))
    # ranks differ in value past the budget but the ordering is exact
    assert (np.argsort(capped, kind="stable")
            == np.argsort(exact, kind="stable")).all()

    w = jax.random.normal(jax.random.key(9), (80, 2))
    r = np.asarray(mo.emo.nd_rank(w, impl="matrix", max_rank=1,
                                  fallback="count"))
    wn = np.asarray(w)
    for i in range(80):
        for j in range(80):
            if (wn[j] >= wn[i]).all() and (wn[j] > wn[i]).any():
                assert r[j] < r[i], (i, j)


def test_nd_rank_tiled_cover_k_and_fallback():
    from deap_tpu.ops.kernels import nd_rank_tiled

    w = _near_ordered(96)
    full = np.asarray(mo.emo.nd_rank(w, impl="matrix"))
    part = np.asarray(nd_rank_tiled(w, cover_k=24, interpret=True))
    ranked = part < 96
    assert ranked.sum() >= 24 and (part[ranked] == full[ranked]).all()
    fb = np.asarray(nd_rank_tiled(w, 4, fallback="count", interpret=True))
    assert (np.argsort(fb, kind="stable")
            == np.argsort(full, kind="stable")).all()
