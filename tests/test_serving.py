"""Serving layer — multi-run vectorization + multi-tenant scheduler.

The acceptance bar of ``deap_tpu/serving/``: a tenant's batched
trajectory must be **bit-identical** to the same job run solo through
the monolithic loops — populations, logbooks, halls of fame and
per-generation Meter/probe rows — pinned here for ea_simple,
mu+lambda, mu,lambda and the CMA ask-tell family (mixed per-run ngen
and hyperparameters in one batch). Plus the scheduler half: shape
buckets and the pow-2 lane lattice, segment-cadence execution,
checkpoint-as-swap-unit eviction/resume under contention, per-tenant
health early-stop, and prewarm journaling.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.serving import (
    Job,
    MultiRunEngine,
    Scheduler,
    bucket_key,
    multirun,
    pad_pow2,
    prewarm,
)
from deap_tpu.strategies import cma
from deap_tpu.support.stats import Statistics
from deap_tpu.telemetry import RunTelemetry, read_journal
from deap_tpu.telemetry.probes import (
    DiversityProbe,
    FitnessProbe,
    HealthMonitor,
)


def _toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def _pops(n_runs=3, n=24, length=16):
    spec = FitnessSpec((1.0,))
    return [init_population(jax.random.key(s), n,
                            ops.bernoulli_genome(length), spec)
            for s in range(n_runs)]


def _keys(n_runs=3, base=100):
    return [jax.random.key(base + s) for s in range(n_runs)]


def _assert_pop_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.genomes),
                                  np.asarray(b.genomes))
    np.testing.assert_array_equal(np.asarray(a.fitness),
                                  np.asarray(b.fitness))
    np.testing.assert_array_equal(np.asarray(a.valid),
                                  np.asarray(b.valid))


def _assert_logbook_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert set(ra) == set(rb)
        for k in ra:
            np.testing.assert_array_equal(np.asarray(ra[k]),
                                          np.asarray(rb[k]))


HYPER = [{"cxpb": 0.5, "mutpb": 0.2}, {"cxpb": 0.7, "mutpb": 0.1},
         {"cxpb": 0.3, "mutpb": 0.3}]


# ------------------------------------------- batched-vs-solo parity ----

def test_multirun_ea_simple_bit_identity():
    """Mixed ngen + per-run cxpb/mutpb in one batch == each job solo
    (populations, logbooks incl. stats fields, hofs)."""
    tb, pops, keys = _toolbox(), _pops(), _keys()
    stats = Statistics()
    stats.register("max", jnp.max)
    stats.register("mean", jnp.mean)
    ngen = [7, 5, 7]
    res = multirun("ea_simple", tb, keys, pops, ngen, HYPER,
                   segment_len=3, stats=stats, halloffame_size=4)
    for r in range(3):
        sp, slb, sh = algorithms.ea_simple(
            keys[r], pops[r], tb, HYPER[r]["cxpb"], HYPER[r]["mutpb"],
            ngen[r], stats=stats, halloffame_size=4)
        bp, blb, bh = res[r]
        _assert_pop_equal(sp, bp)
        _assert_logbook_equal(slb, blb)
        np.testing.assert_array_equal(np.asarray(sh.genomes),
                                      np.asarray(bh.genomes))
        np.testing.assert_array_equal(np.asarray(sh.fitness),
                                      np.asarray(bh.fitness))


def test_multirun_mu_plus_lambda_bit_identity():
    tb, pops, keys = _toolbox(), _pops(), _keys(base=40)
    res = multirun("ea_mu_plus_lambda", tb, keys, pops, 6, HYPER,
                   segment_len=4, mu=24, lambda_=24, halloffame_size=3)
    for r in range(3):
        sp, slb, sh = algorithms.ea_mu_plus_lambda(
            keys[r], pops[r], tb, 24, 24, HYPER[r]["cxpb"],
            HYPER[r]["mutpb"], 6, halloffame_size=3)
        bp, blb, bh = res[r]
        _assert_pop_equal(sp, bp)
        _assert_logbook_equal(slb, blb)
        np.testing.assert_array_equal(np.asarray(sh.fitness),
                                      np.asarray(bh.fitness))


def test_multirun_mu_comma_lambda_bit_identity():
    tb, pops, keys = _toolbox(), _pops(), _keys(base=60)
    res = multirun("ea_mu_comma_lambda", tb, keys, pops, [6, 4, 6],
                   HYPER, mu=24, lambda_=24)
    for r, ngen in enumerate([6, 4, 6]):
        sp, slb, sh = algorithms.ea_mu_comma_lambda(
            keys[r], pops[r], tb, 24, 24, HYPER[r]["cxpb"],
            HYPER[r]["mutpb"], ngen)
        bp, blb, bh = res[r]
        _assert_pop_equal(sp, bp)
        _assert_logbook_equal(slb, blb)


def test_multirun_cma_bit_identity():
    """The CMA ask-tell path: per-run sigma through the initial state,
    mixed ngen; full strategy-state pytree pinned bitwise (this is the
    family whose covariance update exposed the masked-stepping fusion
    hazard — the shadow-carry construction is what keeps it exact)."""
    strat = cma.Strategy(centroid=[3.0] * 6, sigma=0.5, lambda_=12)
    tb = Toolbox()
    tb.register("evaluate", lambda g: (g ** 2).sum(-1))
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    states = [strat.initial_state(sigma=s) for s in (0.3, 0.5, 0.9)]
    keys = _keys(base=7)
    ngens = [8, 5, 3]
    res = multirun("ea_generate_update", tb, keys, states, ngens,
                   segment_len=3, spec=strat.spec,
                   state_template=states[0], halloffame_size=2)
    for r in range(3):
        st, slb, sh = algorithms.ea_generate_update(
            keys[r], states[r], tb, ngens[r], spec=strat.spec,
            halloffame_size=2)
        bt, blb, bh = res[r]
        for la, lb in zip(jax.tree_util.tree_leaves(st),
                          jax.tree_util.tree_leaves(bt)):
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))
        _assert_logbook_equal(slb, blb)
        np.testing.assert_array_equal(np.asarray(sh.fitness),
                                      np.asarray(bh.fitness))


def test_multirun_pack_fresh_matches_lane_init():
    """The vectorized admission path (pack_fresh) and the
    lane-at-a-time path (lane_init + pack) build bit-identical
    batches — the scheduler uses the latter, the bench the former."""
    tb, pops, keys = _toolbox(), _pops(), _keys(base=80)
    eng1 = MultiRunEngine("ea_simple", tb)
    lanes = [eng1.lane_init(keys[r], pops[r], 5, HYPER[0])
             for r in range(3)]
    b1 = eng1.pack(lanes, n_lanes=4, horizon=8)
    eng2 = MultiRunEngine("ea_simple", tb)
    b2 = eng2.pack_fresh(keys, pops, 5, HYPER[0], n_lanes=4,
                         horizon=8)
    for k in ("carry", "gen", "ngen", "keys", "hyper", "record0"):
        for la, lb in zip(jax.tree_util.tree_leaves(b1[k]),
                          jax.tree_util.tree_leaves(b2[k])):
            np.testing.assert_array_equal(np.asarray(la),
                                          np.asarray(lb))


# ------------------------------------- batched telemetry parity ----

def test_batched_meter_probe_rows_match_solo(tmp_path):
    """The vmapped Meter/probe carry (DiversityProbe + FitnessProbe)
    decodes to per-run rows IDENTICAL to each solo run's journal rows
    for the same seeds — per-run telemetry survives batching."""
    tb, pops, keys = _toolbox(), _pops(), _keys()
    NGEN = 6
    probes = lambda: (DiversityProbe(sample=16), FitnessProbe())

    solo_rows = []
    for r in range(3):
        path = str(tmp_path / f"solo{r}.jsonl")
        with RunTelemetry(path) as tel:
            algorithms.ea_simple(keys[r], pops[r], tb, 0.5, 0.2, NGEN,
                                 telemetry=tel, probes=probes())
        solo_rows.append([e for e in read_journal(path)
                          if e.get("kind") == "meter"])

    with RunTelemetry(str(tmp_path / "batch.jsonl")) as tel:
        eng = MultiRunEngine("ea_simple", tb, telemetry=tel,
                             probes=probes())
        lanes = [eng.lane_init(keys[r], pops[r], NGEN,
                               {"cxpb": 0.5, "mutpb": 0.2})
                 for r in range(3)]
        batch = eng.pack(lanes, n_lanes=4, horizon=8)
        segs = []
        while not eng.done(batch).all():
            batch, seg = eng.advance(batch, 4)
            segs.append(seg)
        for r in range(3):
            rows = eng.lane_meter_rows(segs, r, lane=lanes[r])
            srows = solo_rows[r]
            assert len(rows) == len(srows) == NGEN + 1
            for got, want in zip(rows, srows):
                want = {k: v for k, v in want.items()
                        if k not in ("kind", "t")}
                assert set(got) == set(want)
                for k in got:
                    assert got[k] == want[k], (r, got.get("gen"), k)


def test_multirun_rejects_streaming_telemetry(tmp_path):
    tb = _toolbox()
    with RunTelemetry(str(tmp_path / "j.jsonl"), stream=True) as tel:
        with pytest.raises(ValueError, match="stream"):
            MultiRunEngine("ea_simple", tb, telemetry=tel)


# --------------------------------------------- bucket lattice ----

def test_pad_pow2():
    assert [pad_pow2(n) for n in (1, 2, 3, 5, 8, 9)] == \
        [1, 2, 4, 8, 8, 16]
    assert pad_pow2(9, cap=8) == 8
    with pytest.raises(ValueError):
        pad_pow2(0)


def test_bucket_key_separates_shapes_and_programs():
    tb = _toolbox()
    pops = _pops(1) + [init_population(
        jax.random.key(9), 24, ops.bernoulli_genome(32),
        FitnessSpec((1.0,)))]
    mk = lambda pop, fam="ea_simple", prog="p", **kw: Job(
        tenant_id="x", family=fam, toolbox=tb, key=jax.random.key(0),
        init=pop, ngen=5, program=prog, **kw)
    base = bucket_key(mk(pops[0]))
    assert bucket_key(mk(pops[0])) == base          # same config
    assert bucket_key(mk(pops[1])) != base          # genome length
    assert bucket_key(mk(pops[0], prog="q")) != base  # program tag
    assert bucket_key(mk(pops[0], fam="ea_mu_plus_lambda", mu=8,
                         lambda_=16)) != base       # family
    assert bucket_key(mk(pops[0], halloffame_size=2)) != base


# ------------------------------------------------- scheduler ----

def _jobs(tb, n=4, ngen=5, **kw):
    jobs = []
    for i in range(n):
        pop = init_population(jax.random.key(i), 16,
                              ops.bernoulli_genome(12),
                              FitnessSpec((1.0,)))
        jobs.append(Job(tenant_id=f"t{i}", family="ea_simple",
                        toolbox=tb, key=jax.random.key(100 + i),
                        init=pop, ngen=ngen,
                        hyper={"cxpb": 0.5, "mutpb": 0.2},
                        program="onemax", **kw))
    return jobs


def test_scheduler_eviction_resume_bit_identity(tmp_path):
    """Contention (4 tenants, 2 lanes, quantum 1) forces checkpoint
    eviction and swap-in resume; every tenant's result must still be
    bit-identical to its solo run, the journal must show the swap
    ledger, and every meter row must carry its tenant_id."""
    tb = _toolbox()
    jobs = _jobs(tb)
    with Scheduler(str(tmp_path), max_lanes=2, segment_len=3,
                   fair_quantum=1) as sched:
        for j in jobs:
            sched.submit(j)
        results = sched.run()

    assert set(results) == {j.tenant_id for j in jobs}
    for j in jobs:
        sp, slb, _ = algorithms.ea_simple(
            j.key, j.init, tb, 0.5, 0.2, j.ngen)
        bp, blb, _ = results[j.tenant_id]
        _assert_pop_equal(sp, bp)
        _assert_logbook_equal(slb, blb)

    rows = read_journal(str(tmp_path / "journal.jsonl"))
    kinds = [e.get("kind") for e in rows]
    assert kinds.count("tenant_finished") == len(jobs)
    assert "tenant_evicted" in kinds and "tenant_resumed" in kinds
    meters = [e for e in rows if e.get("kind") == "meter"]
    assert meters and all("tenant_id" in e for e in meters)
    # per-tenant isolation on disk: each tenant's checkpoints live
    # under its own run dir with its id stamped in the meta
    for j in jobs[:2]:
        d = tmp_path / "tenants" / j.tenant_id / "ckpt"
        if d.exists() and any(d.iterdir()):
            from deap_tpu.support.checkpoint import (
                Checkpointer, checkpoint_meta)
            ck = Checkpointer(str(d))
            meta = ck.meta()
            assert meta["tenant_id"] == j.tenant_id
            with pytest.raises(ValueError):
                checkpoint_meta(ck.path_for(ck.latest_step()),
                                tenant_id="intruder")


def test_scheduler_health_early_stop_frees_slot(tmp_path):
    """A tenant whose HealthMonitor trips ``zero_improvement`` with
    early_stop is finished at the segment boundary (status stopped,
    partial logbook), freeing its lane; co-tenants are untouched."""
    tb = Toolbox()
    # constant fitness: best never improves, stagnation fires
    tb.register("evaluate",
                lambda g: jnp.zeros(g.shape[0], jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    jobs = _jobs(tb, n=2, ngen=9)
    jobs[0].health = HealthMonitor(stagnation_window=2,
                                   early_stop=("zero_improvement",))
    with Scheduler(str(tmp_path), max_lanes=2, segment_len=3) as sched:
        for j in jobs:
            sched.submit(j)
        results = sched.run()
        stopped = sched.tenants["t0"]
        other = sched.tenants["t1"]
    assert stopped.status == "stopped"
    assert stopped.stopped_at is not None and stopped.stopped_at < 9
    assert other.status == "finished" and other.gen == 9
    # the stopped tenant's partial logbook covers exactly its gens
    _, lb, _ = results["t0"]
    assert len(lb) == stopped.stopped_at + 1
    rows = read_journal(str(tmp_path / "journal.jsonl"))
    alarms = [e for e in rows if e.get("kind") == "alarm"]
    assert alarms and all(e["tenant_id"] == "t0" for e in alarms)


def test_scheduler_two_buckets_round_robin(tmp_path):
    """A GA bucket and a CMA bucket coexist: distinct compiled
    programs, both finish, results bit-identical to solo."""
    tb = _toolbox()
    strat = cma.Strategy(centroid=[2.0] * 4, sigma=0.4, lambda_=8)
    tbc = Toolbox()
    tbc.register("evaluate", lambda g: (g ** 2).sum(-1))
    tbc.register("generate", strat.generate)
    tbc.register("update", strat.update)
    ga = _jobs(tb, n=1, ngen=4)[0]
    cj = Job(tenant_id="cma0", family="ea_generate_update",
             toolbox=tbc, key=jax.random.key(5),
             init=strat.initial_state(sigma=0.7), ngen=4,
             spec=strat.spec, program="sphere")
    with Scheduler(str(tmp_path), max_lanes=2, segment_len=2) as sched:
        sched.submit(ga)
        sched.submit(cj)
        results = sched.run()
    assert set(results) == {"t0", "cma0"}
    st, slb, _ = algorithms.ea_generate_update(
        cj.key, cj.init, tbc, 4, spec=strat.spec)
    bt, blb, _ = results["cma0"]
    for la, lb_ in zip(jax.tree_util.tree_leaves(st),
                       jax.tree_util.tree_leaves(bt)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb_))
    _assert_logbook_equal(slb, blb)


def test_scheduler_prewarm_journals_per_bucket(tmp_path):
    tb = _toolbox()
    jobs = _jobs(tb, n=2)
    with Scheduler(str(tmp_path), max_lanes=2, segment_len=3) as sched:
        warmed = prewarm(sched, jobs)
        assert warmed == 1  # both jobs share one bucket
    rows = read_journal(str(tmp_path / "journal.jsonl"))
    pw = [e for e in rows if e.get("kind") == "prewarm"]
    assert len(pw) == 1
    assert pw[0]["family"] == "ea_simple" and pw[0]["lanes"] == 2
    assert pw[0]["compile_s"] > 0


def test_tenant_checkpoint_cannot_cross_restore(tmp_path):
    """Two tenants writing into the SAME directory (misconfiguration):
    the tenant-filtered restore walks past the other tenant's newer
    checkpoint instead of handing it over."""
    from deap_tpu.support.checkpoint import Checkpointer
    ck = Checkpointer(str(tmp_path / "shared"))
    ck.save(3, {"who": "a"}, meta={"tenant_id": "A"})
    ck.save(5, {"who": "b"}, meta={"tenant_id": "B"})
    step, state = ck.restore_latest(tenant_id="A")
    assert (step, state["who"]) == (3, "a")
    step, state = ck.restore_latest(tenant_id="B")
    assert (step, state["who"]) == (5, "b")
    assert ck.restore_latest(tenant_id="C") is None
    # unfiltered restore keeps its original semantics: newest valid
    assert ck.restore_latest()[0] == 5
