"""Multi-host (parallel.multihost): single-process no-op init, global
mesh construction, and a REAL 2-process `jax.distributed` run on CPU
(SURVEY.md §2.3 P3 parity — the SCOOP analog; the reference's stand-in
is the pickle round-trip suite, deap/tests/test_pickle.py:38-154)."""

import os
import pathlib
import socket
import subprocess
import sys

import jax
import pytest

from deap_tpu.parallel import (
    global_population_mesh,
    initialize,
    is_distributed,
    process_count,
    process_index,
)


def test_initialize_single_process_noop():
    initialize()  # must not raise or hang without a cluster env
    assert process_count() == 1
    assert process_index() == 0
    assert not is_distributed()


def test_global_mesh_covers_all_devices():
    mesh = global_population_mesh(("pop",))
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("pop",)


def test_global_mesh_2d_layout():
    n = len(jax.devices())
    mesh = global_population_mesh(("island", "genome"), shape=(n, 1))
    assert mesh.devices.shape == (n, 1)


@pytest.mark.slow
def test_two_process_distributed_epoch():
    """Two local processes form a jax.distributed runtime over a port,
    build one 8-device global CPU mesh (4 virtual devices each), run an
    island epoch whose migration ring crosses the process boundary, and
    a genome-sharded evaluation whose psum does too."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"

    child = pathlib.Path(__file__).parent / "_multihost_child.py"
    repo_root = pathlib.Path(__file__).parent.parent
    env = dict(os.environ)
    # the child pins its own XLA flags/platform; drop the suite's
    env.pop("XLA_FLAGS", None)
    # the child script's sys.path[0] is tests/, not the repo root, so
    # deap_tpu must come via PYTHONPATH — do not rely on an editable
    # install being present (container resets drop it)
    env["PYTHONPATH"] = os.pathsep.join(
        [str(repo_root)] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH")
                            else []))
    procs = [
        subprocess.Popen(
            [sys.executable, str(child), coordinator, "2", str(rank)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
            cwd=str(pathlib.Path(__file__).parent.parent))
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"MULTIHOST_CHILD_OK rank={rank}" in out, out
        # surface the measured child runtime + phase marks in the test
        # output (-s / failure reports): the children share a
        # persistent XLA compilation cache, so only the first-ever run
        # pays the compiles that once threatened the 420 s budget
        for line in out.splitlines():
            if line.startswith(("MULTIHOST_CHILD_PHASE",
                                "MULTIHOST_CHILD_OK")):
                print(f"[rank {rank}] {line}")
