"""Multi-host helpers (parallel.multihost): single-process no-op init,
global mesh construction (SURVEY.md §2.3 P3 parity — the SCOOP analog)."""

import jax

from deap_tpu.parallel import (
    global_population_mesh,
    initialize,
    is_distributed,
    process_count,
    process_index,
)


def test_initialize_single_process_noop():
    initialize()  # must not raise or hang without a cluster env
    assert process_count() == 1
    assert process_index() == 0
    assert not is_distributed()


def test_global_mesh_covers_all_devices():
    mesh = global_population_mesh(("pop",))
    assert mesh.devices.size == len(jax.devices())
    assert mesh.axis_names == ("pop",)


def test_global_mesh_2d_layout():
    n = len(jax.devices())
    mesh = global_population_mesh(("island", "genome"), shape=(n, 1))
    assert mesh.devices.shape == (n, 1)
