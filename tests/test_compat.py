"""CPU/list backend tests — the reference's own test semantics
(test_creator.py slice-swap, Fitness compare, test_pickle.py round
trips) plus the jax_map bridge (list individuals, one device
evaluation)."""

import pickle
import random

import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu.compat import algorithms, base, creator, jax_map, tools


@pytest.fixture(autouse=True)
def _seed():
    random.seed(64)


@pytest.fixture(scope="module")
def types():
    creator.create("FitnessMax", base.Fitness, weights=(1.0,))
    creator.create("FitnessMulti", base.Fitness, weights=(1.0, -1.0))
    creator.create("Individual", list, fitness=creator.FitnessMax)
    return creator


def test_creator_list_individual(types):
    ind = creator.Individual([1, 0, 1])
    assert list(ind) == [1, 0, 1]
    assert not ind.fitness.valid
    ind.fitness.values = (2.0,)
    assert ind.fitness.valid and ind.fitness.values == (2.0,)
    del ind.fitness.values
    assert not ind.fitness.valid


def test_creator_slice_swap(types):
    """The slice-swap semantics test_creator.py:33-60 checks."""
    a = creator.Individual([1, 2, 3, 4])
    b = creator.Individual([5, 6, 7, 8])
    a[1:3], b[1:3] = b[1:3], a[1:3]
    assert list(a) == [1, 6, 7, 4]
    assert list(b) == [5, 2, 3, 8]


def test_creator_numpy_deepcopy_no_aliasing(types):
    import copy

    creator.create("NpInd", np.ndarray, fitness=creator.FitnessMax)
    x = creator.NpInd([1.0, 2.0, 3.0])
    y = copy.deepcopy(x)
    y[0] = 99.0
    assert x[0] == 1.0   # the ndarray deepcopy fix (creator.py:51-73)


def test_fitness_weighted_compare(types):
    f1 = creator.FitnessMulti((2.0, 1.0))   # w = (2, -1)
    f2 = creator.FitnessMulti((1.0, 2.0))   # w = (1, -2)
    assert f1 > f2
    assert f1.dominates(f2)
    assert not f2.dominates(f1)
    f3 = creator.FitnessMulti((2.0, 0.5))
    assert f3.dominates(f1)


def test_pickle_roundtrip(types):
    """Picklability is the reference's distribution invariant
    (test_pickle.py:38-154)."""
    ind = creator.Individual([0, 1, 1, 0])
    ind.fitness.values = (2.0,)
    clone = pickle.loads(pickle.dumps(ind))
    assert list(clone) == list(ind)
    assert clone.fitness.values == ind.fitness.values
    pop = [creator.Individual([i]) for i in range(4)]
    assert [list(i) for i in pickle.loads(pickle.dumps(pop))] == [
        [0], [1], [2], [3]]


def test_toolbox_register_decorate(types):
    tb = base.Toolbox()
    tb.register("inc", lambda x, d: x + d, d=5)
    assert tb.inc(1) == 6

    def double_out(fn):
        def wrapped(*a, **k):
            return 2 * fn(*a, **k)
        return wrapped

    tb.decorate("inc", double_out)
    assert tb.inc(1) == 12
    tb.unregister("inc")
    assert not hasattr(tb, "inc")


def test_easimple_onemax_cpu(types):
    tb = base.Toolbox()
    tb.register("attr", random.randint, 0, 1)
    tb.register("individual", tools.initRepeat, creator.Individual,
                tb.attr, 30)
    tb.register("population", tools.initRepeat, list, tb.individual)
    tb.register("evaluate", lambda ind: (float(sum(ind)),))
    tb.register("mate", tools.cxTwoPoint)
    tb.register("mutate", tools.mutFlipBit, indpb=0.05)
    tb.register("select", tools.selTournament, tournsize=3)

    pop = tb.population(n=50)
    hof = tools.HallOfFame(1)
    stats = tools.Statistics(lambda ind: ind.fitness.values)
    stats.register("max", np.max)
    pop, logbook = algorithms.eaSimple(pop, tb, 0.5, 0.2, 20,
                                       stats=stats, halloffame=hof)
    assert hof[0].fitness.values[0] >= 25.0
    assert logbook[0]["gen"] == 0 and logbook[-1]["gen"] == 20


def test_jax_map_bridge(types):
    """List individuals, device evaluation: the jax-backed map must
    produce the same fitnesses as the serial map and count as the only
    evaluation path."""
    tb = base.Toolbox()
    tb.register("evaluate", lambda ind: (float(sum(ind)),))
    tb.register("map", jax_map(
        lambda g: g.sum(-1).astype(jnp.float32)))

    pop = [creator.Individual([random.randint(0, 1) for _ in range(16)])
           for _ in range(32)]
    fits = tb.map(tb.evaluate, pop)
    assert fits == [(float(sum(ind)),) for ind in pop]
    assert tb.map(tb.evaluate, []) == []


def test_easimple_with_jax_map(types):
    """Full eaSimple over list individuals with the device evaluating."""
    tb = base.Toolbox()
    tb.register("attr", random.randint, 0, 1)
    tb.register("individual", tools.initRepeat, creator.Individual,
                tb.attr, 30)
    tb.register("population", tools.initRepeat, list, tb.individual)
    tb.register("evaluate", lambda ind: (_ for _ in ()).throw(
        AssertionError("scalar evaluate must be bypassed")))
    tb.register("mate", tools.cxTwoPoint)
    tb.register("mutate", tools.mutFlipBit, indpb=0.05)
    tb.register("select", tools.selTournament, tournsize=3)
    tb.register("map", jax_map(lambda g: g.sum(-1).astype(jnp.float32)))

    pop = tb.population(n=50)
    pop, logbook = algorithms.eaSimple(pop, tb, 0.5, 0.2, 15)
    best = max(ind.fitness.values[0] for ind in pop)
    assert best >= 24.0


def test_multistatistics_and_varor(types):
    tb = base.Toolbox()
    tb.register("evaluate", lambda ind: (float(sum(ind)),))
    tb.register("mate", tools.cxOnePoint)
    tb.register("mutate", tools.mutFlipBit, indpb=0.1)
    tb.register("select", tools.selBest)

    pop = [creator.Individual([random.randint(0, 1) for _ in range(10)])
           for _ in range(20)]
    stats = tools.MultiStatistics(
        fitness=tools.Statistics(lambda ind: ind.fitness.values),
        size=tools.Statistics(len))
    stats.register("avg", np.mean)
    pop, logbook = algorithms.eaMuPlusLambda(
        pop, tb, mu=20, lambda_=40, cxpb=0.4, mutpb=0.4, ngen=5,
        stats=stats)
    assert "fitness" in logbook.chapters and "size" in logbook.chapters


# -------------------------------------------- MO selectors / support ----

def _mo_population(n=20, seed=5):
    import random as _r

    _r.seed(seed)
    creator.create("FMin2", base.Fitness, weights=(-1.0, -1.0))
    creator.create("IndMO", list, fitness=creator.FMin2)
    pop = []
    for _ in range(n):
        ind = creator.IndMO([_r.random(), _r.random()])
        ind.fitness.values = (ind[0], ind[1])
        pop.append(ind)
    return pop


def test_sort_nondominated_fronts_are_nondominated():
    pop = _mo_population()
    fronts = tools.sortNondominated(pop, len(pop))
    assert sum(len(f) for f in fronts) == len(pop)
    first = fronts[0]
    for a in first:
        for b in first:
            assert not a.fitness.dominates(b.fitness)


def test_sel_nsga2_and_crowding():
    pop = _mo_population(30)
    chosen = tools.selNSGA2(pop, 10)
    assert len(chosen) == 10
    # every first-front member that fits must be selected (emo.py:15-50)
    first = {id(i) for i in tools.sortNondominated(pop, 10)[0]}
    chosen_ids = {id(c) for c in chosen}
    if len(first) <= 10:
        assert first <= chosen_ids
    tools.assignCrowdingDist(pop)
    assert all(hasattr(p.fitness, "crowding_dist") for p in pop)
    assert tools.sortNondominated(pop, 0) == []
    assert tools.sortNondominated([], 5) == []


def test_sel_spea2_and_tournament_dcd():
    pop = _mo_population(24)
    assert len(tools.selSPEA2(pop, 8)) == 8
    assert len(tools.selTournamentDCD(pop, 12)) == 12


def test_sel_nsga3_with_reference_points():
    pop = _mo_population(24)
    ref = tools.uniformReferencePoints(2, p=6)
    chosen = tools.selNSGA3(pop, 8, ref)
    assert len(chosen) == 8


def test_pareto_front_archive():
    pop = _mo_population(40)
    front = tools.ParetoFront()
    front.update(pop)
    for a in front:
        for b in front:
            assert not a.fitness.dominates(b.fitness)
    # re-update with the same population: no duplicates
    n = len(front)
    front.update(pop)
    assert len(front) == n


def test_mig_ring_exchanges_best():
    import random as _r

    _r.seed(9)
    creator.create("FMax2", base.Fitness, weights=(1.0,))
    creator.create("IndM", list, fitness=creator.FMax2)
    demes = []
    for d in range(3):
        deme = []
        for i in range(5):
            ind = creator.IndM([d * 10 + i])
            ind.fitness.values = (float(d * 10 + i),)
            deme.append(ind)
        demes.append(deme)
    tools.migRing(demes, 2, tools.selBest)
    # deme 1 received deme 0's best (9 came from deme 0? deme0 best = 4)
    vals1 = sorted(ind[0] for ind in demes[1])
    assert 4 in vals1 and 3 in vals1


def test_history_genealogy():
    """Reference idiom (support.py:21-152): variation mutates its inputs
    in place, so the produced individuals' OLD indices are the parent
    record."""
    creator.create("FMaxH", base.Fitness, weights=(1.0,))
    creator.create("IndH", list, fitness=creator.FMaxH)
    hist = tools.History()
    a, b = creator.IndH([1]), creator.IndH([2])
    hist.update([a, b])
    pa, pb = a.history_index, b.history_index

    def mate(x, y):
        x[0], y[0] = y[0], x[0]  # in-place variation
        return x, y

    out1, out2 = hist.decorator(mate)(a, b)
    g = hist.getGenealogy(out1)
    assert set(g[out1.history_index]) == {pa, pb}


def test_compat_cma_sphere_gate():
    """compat.cma.Strategy through eaGenerateUpdate hits the reference's
    quality gate (best < 1e-8 on sphere; deap/tests/
    test_algorithms.py:53-66)."""
    import random

    from deap_tpu.compat import algorithms, base, cma, creator, tools

    creator.create("FitCMA", base.Fitness, weights=(-1.0,))
    creator.create("IndCMA", list, fitness=creator.FitCMA)
    random.seed(3)
    strat = cma.Strategy(centroid=[5.0] * 5, sigma=5.0, lambda_=20)
    tb = base.Toolbox()
    tb.register("evaluate", lambda ind: (sum(x * x for x in ind),))
    tb.register("generate", strat.generate, creator.IndCMA)
    tb.register("update", strat.update)
    hof = tools.HallOfFame(1)
    algorithms.eaGenerateUpdate(tb, ngen=120, halloffame=hof,
                                verbose=False)
    assert hof[0].fitness.values[0] < 1e-8
    assert strat.update_count == 120
    assert strat.sigma < 1.0  # converged step size


def test_compat_cma_one_plus_lambda():
    import random

    from deap_tpu.compat import algorithms, base, cma, creator, tools

    creator.create("FitOPL", base.Fitness, weights=(-1.0,))
    creator.create("IndOPL", list, fitness=creator.FitOPL)
    random.seed(5)
    parent = creator.IndOPL([3.0] * 5)
    parent.fitness.values = (sum(x * x for x in parent),)
    strat = cma.StrategyOnePlusLambda(parent, sigma=2.0, lambda_=8)
    tb = base.Toolbox()
    tb.register("evaluate", lambda ind: (sum(x * x for x in ind),))
    tb.register("generate", strat.generate, creator.IndOPL)
    tb.register("update", strat.update)
    hof = tools.HallOfFame(1)
    algorithms.eaGenerateUpdate(tb, ngen=150, halloffame=hof,
                                verbose=False)
    assert hof[0].fitness.values[0] < 1e-6


def test_compat_mo_cma_improves_front():
    import math
    import random

    import numpy as np

    from deap_tpu.compat import base, cma, creator

    creator.create("FitMOC", base.Fitness, weights=(-1.0, -1.0))
    creator.create("IndMOC", list, fitness=creator.FitMOC)

    def zdt1(ind):
        x = [min(max(v, 0.0), 1.0) for v in ind]
        g = 1.0 + 9.0 * sum(x[1:]) / (len(x) - 1)
        return x[0], g * (1.0 - math.sqrt(x[0] / g))

    random.seed(11)
    MU, NDIM = 12, 8
    pop = []
    for _ in range(MU):
        ind = creator.IndMOC(random.uniform(0, 1) for _ in range(NDIM))
        ind.fitness.values = zdt1(ind)
        pop.append(ind)
    f0 = np.array([ind.fitness.values for ind in pop])
    strat = cma.StrategyMultiObjective(pop, sigma=1.0, mu=MU, lambda_=MU)
    for _ in range(50):
        off = strat.generate(creator.IndMOC)
        assert all(hasattr(ind, "_ps") for ind in off)  # reference tag
        for ind in off:
            ind.fitness.values = zdt1(ind)
        strat.update(off)
    f = np.array([zdt1(list(r)) for r in strat.parents])
    assert f[:, 1].mean() < f0[:, 1].mean()  # front moved down


def test_compat_mo_cma_survives_offspring_reordering():
    """Parent indices travel on the ``_ps`` tags, so sorting offspring
    between generate() and update() stays correct (reference
    cma.py:500-504 reads _ps per individual)."""
    import math
    import random

    from deap_tpu.compat import base, cma, creator

    creator.create("FitMOR", base.Fitness, weights=(-1.0, -1.0))
    creator.create("IndMOR", list, fitness=creator.FitMOR)

    def f(ind):
        return sum(ind), sum((x - 1) ** 2 for x in ind)

    random.seed(2)
    pop = []
    for _ in range(8):
        ind = creator.IndMOR(random.uniform(0, 1) for _ in range(4))
        ind.fitness.values = f(ind)
        pop.append(ind)
    strat = cma.StrategyMultiObjective(pop, sigma=0.5, mu=8, lambda_=8)
    off = strat.generate(creator.IndMOR)
    for ind in off:
        ind.fitness.values = f(ind)
    random.shuffle(off)  # legal against the reference
    strat.update(off)  # must not mis-assign parents or raise


def test_compat_one_plus_lambda_parent_has_fitness():
    from deap_tpu.compat import base, cma, creator

    creator.create("FitOPF", base.Fitness, weights=(-1.0,))
    creator.create("IndOPF", list, fitness=creator.FitOPF)
    parent = creator.IndOPF([2.0, 2.0])
    parent.fitness.values = (8.0,)
    strat = cma.StrategyOnePlusLambda(parent, sigma=1.0, lambda_=4)
    p = strat.parent
    assert p.fitness.valid
    assert abs(p.fitness.values[0] - 8.0) < 1e-6


def test_compat_nsga2_zdt1_hypervolume_gate():
    """The reference's flagship quality gate (deap/tests/
    test_algorithms.py:90-116) run verbatim through the drop-in
    surface: NSGA-II on ZDT1, MU=16, 100 generations, final
    hypervolume > 116 of ref point [11, 11] and bounds respected.

    Like the reference's, this gate is seed-pinned, and generations are
    1.5x the reference's 100 for margin (the reference tunes NGEN for
    its gates too, test_algorithms.py:183-184): at NGEN=100 both this
    loop (112.2-116.7 across seeds) and the reference itself
    (113.4-115.4, identical seeds and metric) sit on the 116 knife
    edge; at NGEN=150 the pinned trajectory scores ~118.9 and reaches
    ~120.2 by 200 (optimum 120.777)."""
    import math
    import random

    import numpy as np

    from deap_tpu.compat import base, creator, tools
    from deap_tpu.native import hypervolume as hv

    creator.create("FitZDT", base.Fitness, weights=(-1.0, -1.0))
    creator.create("IndZDT", list, fitness=creator.FitZDT)

    def zdt1(ind):
        g = 1.0 + 9.0 * sum(ind[1:]) / (len(ind) - 1)
        return ind[0], g * (1.0 - math.sqrt(ind[0] / g))

    NDIM, MU, NGEN = 30, 16, 150
    tb = base.Toolbox()
    tb.register("attr", random.uniform, 0.0, 1.0)
    tb.register("individual", tools.initRepeat, creator.IndZDT,
                tb.attr, NDIM)
    tb.register("population", tools.initRepeat, list, tb.individual)
    tb.register("evaluate", zdt1)
    tb.register("mate", tools.cxSimulatedBinaryBounded,
                low=0.0, up=1.0, eta=20.0)
    tb.register("mutate", tools.mutPolynomialBounded,
                low=0.0, up=1.0, eta=20.0, indpb=1.0 / NDIM)
    tb.register("select", tools.selNSGA2)

    random.seed(42)
    pop = tb.population(n=MU)
    for ind in pop:
        ind.fitness.values = tb.evaluate(ind)
    pop = tb.select(pop, len(pop))
    for _ in range(NGEN):
        offspring = tools.selTournamentDCD(pop, len(pop))
        offspring = [tb.clone(ind) for ind in offspring]
        for i1, i2 in zip(offspring[::2], offspring[1::2]):
            if random.random() <= 0.9:
                tb.mate(i1, i2)
            tb.mutate(i1)
            tb.mutate(i2)
            del i1.fitness.values, i2.fitness.values
        for ind in offspring:
            if not ind.fitness.valid:
                ind.fitness.values = tb.evaluate(ind)
        pop = tb.select(pop + offspring, MU)

    front = np.array([ind.fitness.values for ind in pop])
    value = hv(front, np.array([11.0, 11.0]))
    assert value > 116.0, value  # optimum 120.777
    assert bool((front >= 0).all() and (front <= 11).all())


def test_creator_array_individuals_roundtrip():
    """array.array individuals via class_replacers (creator.py:76-93):
    typecode threading, deepcopy/pickle with fitness, slice swap —
    the reference's test_creator array coverage."""
    import array
    import copy
    import pickle

    from deap_tpu.compat import base, creator, tools

    creator.create("ArrFitT", base.Fitness, weights=(1.0,))
    creator.create("ArrIndT", array.array, typecode="b",
                   fitness=creator.ArrFitT)

    ind = creator.ArrIndT([1, 0, 1, 1])
    assert list(ind) == [1, 0, 1, 1] and ind.typecode == "b"
    ind.fitness.values = (3.0,)

    c = copy.deepcopy(ind)
    c.fitness.values = (9.0,)
    assert list(c) == list(ind)
    assert ind.fitness.values == (3.0,)

    p = pickle.loads(pickle.dumps(ind))
    assert list(p) == [1, 0, 1, 1] and p.fitness.values == (3.0,)

    d, e = creator.ArrIndT([0, 1, 2, 3]), creator.ArrIndT([4, 5, 6, 7])
    d[1:3], e[1:3] = e[1:3], d[1:3]
    assert list(d) == [0, 5, 6, 3] and list(e) == [4, 1, 2, 7]

    a, b = creator.ArrIndT([1, 1, 1, 1]), creator.ArrIndT([0, 0, 0, 0])
    tools.cxTwoPoint(a, b)
    assert sorted(list(a) + list(b)) == [0] * 4 + [1] * 4

    assert array.array in creator.class_replacers  # extension point
