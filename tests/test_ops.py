"""Operator unit tests — exact invariants plus light distributional checks
(counterpart of the reference's operator doctests, SURVEY.md §4.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import ops
from deap_tpu.core.fitness import FitnessSpec


KEYS = [jax.random.key(i) for i in range(5)]


def _is_permutation(x):
    return np.array_equal(np.sort(np.asarray(x)), np.arange(len(x)))


# ------------------------------------------------------------- crossover ----

def test_cx_one_point_swaps_tails():
    a = jnp.zeros(10, jnp.int32)
    b = jnp.ones(10, jnp.int32)
    c1, c2 = ops.cx_one_point(KEYS[0], a, b)
    c1, c2 = np.asarray(c1), np.asarray(c2)
    # complementary children; single switch point in [1, L-1]
    assert (c1 + c2 == 1).all()
    switches = np.count_nonzero(np.diff(c1))
    assert switches == 1
    assert c1[0] == 0 and c2[0] == 1


def test_cx_two_point_swaps_segment():
    a = jnp.zeros(12, jnp.int32)
    b = jnp.ones(12, jnp.int32)
    c1, c2 = ops.cx_two_point(KEYS[1], a, b)
    c1 = np.asarray(c1)
    assert (c1 + np.asarray(c2) == 1).all()
    assert np.count_nonzero(np.diff(c1)) in (1, 2)  # segment may touch the end
    assert c1[0] == 0  # segment starts at >= 1


def test_cx_uniform_only_swaps():
    a = jnp.arange(50)
    b = jnp.arange(50) + 100
    c1, c2 = ops.cx_uniform(KEYS[2], a, b, indpb=0.5)
    swapped = np.asarray(c1 != a)
    assert swapped.any() and not swapped.all()
    np.testing.assert_array_equal(np.asarray(c1 + c2), np.asarray(a + b))


@pytest.mark.parametrize("cx", [ops.cx_partialy_matched, ops.cx_ordered])
def test_permutation_crossovers_preserve_permutation(cx):
    for key in KEYS:
        k1, k2 = jax.random.split(key)
        a = jax.random.permutation(k1, 12).astype(jnp.int32)
        b = jax.random.permutation(k2, 12).astype(jnp.int32)
        c1, c2 = cx(key, a, b)
        assert _is_permutation(c1), cx.__name__
        assert _is_permutation(c2), cx.__name__


def test_cx_upmx_preserves_permutation():
    for key in KEYS:
        k1, k2 = jax.random.split(key)
        a = jax.random.permutation(k1, 15).astype(jnp.int32)
        b = jax.random.permutation(k2, 15).astype(jnp.int32)
        c1, c2 = ops.cx_uniform_partialy_matched(key, a, b, indpb=0.4)
        assert _is_permutation(c1) and _is_permutation(c2)


def test_cx_ordered_keeps_other_parents_segment():
    # with identical parents OX must be identity
    a = jnp.arange(10, dtype=jnp.int32)
    c1, c2 = ops.cx_ordered(KEYS[0], a, a)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(a))
    np.testing.assert_array_equal(np.asarray(c2), np.asarray(a))


def test_cx_blend_and_sbx_mean_preserving():
    a = jnp.array([1.0, 2.0, 3.0])
    b = jnp.array([5.0, 6.0, 7.0])
    c1, c2 = ops.cx_blend(KEYS[3], a, b, alpha=0.5)
    np.testing.assert_allclose(np.asarray(c1 + c2), np.asarray(a + b), rtol=1e-5)
    c1, c2 = ops.cx_simulated_binary(KEYS[3], a, b, eta=15.0)
    np.testing.assert_allclose(np.asarray(c1 + c2), np.asarray(a + b), rtol=1e-5)


def test_cx_sbx_bounded_respects_bounds():
    key = KEYS[4]
    a = jax.random.uniform(KEYS[0], (30,), minval=-3.0, maxval=3.0)
    b = jax.random.uniform(KEYS[1], (30,), minval=-3.0, maxval=3.0)
    c1, c2 = ops.cx_simulated_binary_bounded(key, a, b, eta=20.0, low=-3.0, up=3.0)
    assert float(jnp.max(jnp.abs(c1))) <= 3.0 + 1e-6
    assert float(jnp.max(jnp.abs(c2))) <= 3.0 + 1e-6
    # multiset of genes preserved where untouched: every gene of child is
    # produced from the same gene slot of the parents
    touched = np.asarray((c1 != a) | (c2 != b))
    assert touched.any()


def test_cx_messy_one_point_lengths():
    g1 = jnp.arange(1, 7, dtype=jnp.int32)  # len 6 of cap 10
    g1 = jnp.pad(g1, (0, 4))
    g2 = jnp.arange(101, 105, dtype=jnp.int32)  # len 4 of cap 10
    g2 = jnp.pad(g2, (0, 6))
    (c1, n1), (c2, n2) = ops.cx_messy_one_point(KEYS[2], g1, 6, g2, 4)
    n1, n2 = int(n1), int(n2)
    c1, c2 = np.asarray(c1), np.asarray(c2)
    assert (c1[n1:] == 0).all() and (c2[n2:] == 0).all()
    assert n1 + n2 == 10  # total genes conserved (no truncation here)


def test_cx_es_variants():
    a, sa = jnp.zeros(8), jnp.full(8, 0.5)
    b, sb = jnp.ones(8), jnp.full(8, 2.0)
    (c1, s1), (c2, s2) = ops.cx_es_two_point(KEYS[0], a, sa, b, sb)
    # same points for values and strategies
    np.testing.assert_array_equal(np.asarray(c1 == b), np.asarray(s1 == sb))
    (c1, s1), (c2, s2) = ops.cx_es_blend(KEYS[1], a, sa, b, sb, alpha=0.1)
    np.testing.assert_allclose(np.asarray(s1 + s2), 2.5, rtol=1e-5)


# -------------------------------------------------------------- mutation ----

def test_mut_gaussian_masks():
    g = jnp.zeros(1000)
    out = ops.mut_gaussian(KEYS[0], g, mu=0.0, sigma=1.0, indpb=0.1)
    frac = float((out != 0).mean())
    assert 0.05 < frac < 0.2


def test_mut_flip_bit():
    g = jnp.zeros(1000, dtype=bool)
    out = ops.mut_flip_bit(KEYS[1], g, indpb=0.05)
    frac = float(out.mean())
    assert 0.01 < frac < 0.12


def test_mut_uniform_int_bounds():
    g = jnp.zeros(500, jnp.int32)
    out = ops.mut_uniform_int(KEYS[2], g, low=2, up=5, indpb=1.0)
    o = np.asarray(out)
    assert o.min() >= 2 and o.max() <= 5
    assert set(np.unique(o)) == {2, 3, 4, 5}


def test_mut_polynomial_bounded_in_bounds():
    g = jax.random.uniform(KEYS[0], (200,), minval=-3.0, maxval=3.0)
    out = ops.mut_polynomial_bounded(KEYS[3], g, eta=20.0, low=-3.0, up=3.0, indpb=1.0)
    assert float(jnp.max(jnp.abs(out))) <= 3.0 + 1e-6
    assert bool(jnp.any(out != g))


def test_mut_shuffle_preserves_multiset():
    g = jnp.arange(20, dtype=jnp.int32)
    out = ops.mut_shuffle_indexes(KEYS[4], g, indpb=0.5)
    assert _is_permutation(out)
    assert bool(jnp.any(out != g))


def test_mut_two_opt_improves_and_stays_permutation():
    """2-opt sweep: output stays a permutation, tour length never
    increases, and a tour with one obvious crossing gets uncrossed."""
    import numpy as np

    rng = np.random.default_rng(7)
    pts = rng.uniform(0, 1, (16, 2))
    dist = jnp.asarray(
        np.linalg.norm(pts[:, None] - pts[None, :], axis=-1), jnp.float32)

    def length(p):
        p = np.asarray(p)
        return float(np.asarray(dist)[p, np.roll(p, -1)].sum())

    for seed in range(4):
        g = jnp.asarray(np.random.default_rng(seed).permutation(16),
                        jnp.int32)
        out = ops.mut_two_opt(KEYS[0], g, dist)
        assert _is_permutation(out)
        assert length(out) <= length(g) + 1e-5
        # a local optimum: no single reversal improves further
        again = ops.mut_two_opt(KEYS[1], out, dist, steps=1)
        assert length(again) >= length(out) - 1e-5


def test_mut_es_log_normal():
    g = jnp.zeros(16)
    s = jnp.full(16, 1.0)
    g2, s2 = ops.mut_es_log_normal(KEYS[0], g, s, c=1.0, indpb=1.0)
    assert bool(jnp.all(s2 > 0))
    assert bool(jnp.any(g2 != 0))
    # strategy floor decorator
    floored = ops.strategy_floor(0.9)(ops.mut_es_log_normal)
    _, s3 = floored(KEYS[1], g, s, c=1.0, indpb=1.0)
    assert float(jnp.min(s3)) >= 0.9 - 1e-6


# ------------------------------------------------------------- selection ----

def _w(values, weights=(1.0,)):
    spec = FitnessSpec(weights)
    v = jnp.asarray(values, jnp.float32)
    if v.ndim == 1:
        v = v[:, None]
    return v * spec.warray


def test_sel_best_worst():
    w = _w([3.0, 1.0, 2.0, 5.0])
    np.testing.assert_array_equal(np.asarray(ops.sel_best(None, w, 2)), [3, 0])
    np.testing.assert_array_equal(np.asarray(ops.sel_worst(None, w, 2)), [1, 2])


def test_sel_tournament_pressure():
    w = _w(jnp.arange(100.0))
    idx = ops.sel_tournament(KEYS[0], w, 1000, tournsize=3)
    assert float(jnp.mean(idx)) > 60  # max of 3 uniform draws ≈ 74 mean


def test_sel_roulette_proportionate():
    w = _w([1.0, 1.0, 8.0])
    idx = np.asarray(ops.sel_roulette(KEYS[1], w, 2000))
    frac2 = (idx == 2).mean()
    assert 0.7 < frac2 < 0.9


def test_sel_sus_spread():
    w = _w(jnp.ones(10))
    idx = np.asarray(ops.sel_stochastic_universal_sampling(KEYS[2], w, 10))
    # equal fitness → every individual picked exactly once
    assert sorted(idx.tolist()) == list(range(10))


def test_sel_double_tournament_parsimony():
    # equal fitness → pure parsimony pressure toward short genomes
    w = _w(jnp.ones(50))
    lengths = jnp.arange(50.0)
    idx = ops.sel_double_tournament(
        KEYS[3], w, lengths, 500, fitness_size=2, parsimony_size=2.0,
        fitness_first=True)
    assert float(jnp.mean(jnp.take(lengths, idx))) < 20.0


def test_sel_lexicase_elite_always_wins():
    # individual 0 strictly best on every case (minimisation)
    values = jnp.array([[0.0, 0.0], [1.0, 2.0], [2.0, 1.0]])
    idx = ops.sel_lexicase(KEYS[4], values, weights=jnp.array([-1.0, -1.0]), k=20)
    assert set(np.asarray(idx).tolist()) == {0}


def test_sel_epsilon_lexicase():
    values = jnp.array([[0.0, 0.0], [0.05, 0.05], [5.0, 5.0]])
    idx = ops.sel_epsilon_lexicase(
        KEYS[0], values, weights=jnp.array([-1.0, -1.0]), k=40, epsilon=0.1)
    picked = set(np.asarray(idx).tolist())
    assert 2 not in picked and picked <= {0, 1} and len(picked) == 2


def test_sel_automatic_epsilon_lexicase():
    values = jnp.array([[0.0], [0.01], [0.02], [10.0]])
    idx = ops.sel_automatic_epsilon_lexicase(
        KEYS[1], values, weights=jnp.array([-1.0]), k=30)
    assert 3 not in set(np.asarray(idx).tolist())


def test_batched_helpers():
    key = KEYS[0]
    G1 = jnp.zeros((6, 8), jnp.int32)
    G2 = jnp.ones((6, 8), jnp.int32)
    c1, c2 = ops.pair_vmap(ops.cx_two_point)(key, G1, G2)
    assert c1.shape == (6, 8)
    np.testing.assert_array_equal(np.asarray(c1 + c2), 1)
    out = ops.genome_vmap(ops.mut_flip_bit)(key, G1.astype(bool), indpb=0.3)
    assert out.shape == (6, 8)


def test_sel_tournament_sorted_matches_distribution():
    """Rank-based tournament must match the gather-based one in winner
    distribution (chi-square-free check: empirical win counts over many
    draws track the analytic rank distribution for both)."""
    import numpy as np
    from deap_tpu.ops.selection import sel_tournament, sel_tournament_sorted

    n, k, t = 16, 4096, 3
    w = jax.random.normal(jax.random.key(0), (n, 1))
    a = np.asarray(sel_tournament(jax.random.key(1), w, k, tournsize=t))
    b = np.asarray(sel_tournament_sorted(jax.random.key(2), w, k, tournsize=t))
    order = np.asarray(jnp.argsort(-w[:, 0]))
    # empirical selection frequency by fitness rank
    rank_of = np.empty(n, int); rank_of[order] = np.arange(n)
    fa = np.bincount(rank_of[a], minlength=n) / k
    fb = np.bincount(rank_of[b], minlength=n) / k
    # analytic: P(winner has rank r) = ((n-r)^t - (n-r-1)^t) / n^t
    r = np.arange(n)
    p = ((n - r) ** t - (n - r - 1) ** t) / n ** t
    assert np.abs(fa - p).max() < 0.03
    assert np.abs(fb - p).max() < 0.03


def test_sel_tournament_sorted_minimisation():
    from deap_tpu.ops.selection import sel_tournament_sorted

    # weights applied upstream: maximisation of wvalues; all-best check
    w = jnp.array([[0.0], [10.0], [1.0]])
    idx = sel_tournament_sorted(jax.random.key(3), w, 8, tournsize=3)
    assert set(np.asarray(idx).tolist()) <= {0, 1, 2}
    # with tournsize == n*large, winner is almost always the best row
    idx = sel_tournament_sorted(jax.random.key(4), w, 64, tournsize=16)
    assert (np.asarray(idx) == 1).mean() > 0.9


def test_sel_tournament_binned_matches_sorted_exactly():
    """counting_order_desc must be bit-identical to lex_sort_desc on
    integer-valued single-objective fitness (stable ties), so the
    binned tournament returns the same winners for the same key."""
    from deap_tpu.core.fitness import lex_sort_desc
    from deap_tpu.ops.selection import (
        counting_order_desc,
        sel_tournament_binned,
        sel_tournament_sorted,
    )

    f = jax.random.randint(jax.random.key(11), (500,), 0, 101)
    w = f.astype(jnp.float32)[:, None]
    assert (counting_order_desc(w[:, 0], 0, 100) == lex_sort_desc(w)).all()
    # both prefix formulations (full-length cumsum / MXU-tiled matmul)
    # are bit-identical to the lexsort, including at non-tile-multiple n
    for mode in ("scan", "mxu"):
        assert (counting_order_desc(w[:, 0], 0, 100, mode=mode)
                == lex_sort_desc(w)).all(), mode

    ksel = jax.random.key(12)
    a = sel_tournament_sorted(ksel, w, 300, tournsize=3)
    b = sel_tournament_binned(ksel, w, 300, tournsize=3, low=0, high=100)
    assert (np.asarray(a) == np.asarray(b)).all()

    # contract violations fail loudly when values are concrete
    # (inside jit they would be silently clipped into edge buckets)
    with pytest.raises(ValueError, match="outside the declared"):
        sel_tournament_binned(ksel, w, 300, tournsize=3, low=0, high=50)
    with pytest.raises(ValueError, match="not integer-valued"):
        sel_tournament_binned(ksel, w + 0.5, 300, tournsize=3,
                              low=0, high=101)
