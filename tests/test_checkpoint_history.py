"""Checkpoint round-trip + genealogy tracking tests.

Counterpart of the reference's pickle-round-trip suite
(deap/tests/test_pickle.py, the distributed proxy per SURVEY.md §4.3)
and the History genealogy semantics (deap/tools/support.py:21-152) —
extended with what the reference cannot test: bit-exact resume of a
running evolution including its PRNG key.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu import ops
from deap_tpu.algorithms import evaluate_invalid, var_and
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import gather, init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.support import (
    Checkpointer,
    History,
    lineage_init,
    lineage_step,
    pair_parents,
    restore_state,
    save_state,
)


def _onemax_pop(key, n=16, length=8):
    pop = init_population(
        key, n, ops.bernoulli_genome(length), FitnessSpec((1.0,)))
    return evaluate_invalid(pop, lambda g: g.sum(-1).astype(jnp.float32))


def test_save_restore_population_pytree(tmp_path):
    pop = _onemax_pop(jax.random.key(0))
    path = str(tmp_path / "state.pkl")
    save_state(path, {"pop": pop, "gen": 7})
    out = restore_state(path)
    assert out["gen"] == 7
    np.testing.assert_array_equal(np.asarray(out["pop"].genomes),
                                  np.asarray(pop.genomes))
    np.testing.assert_array_equal(np.asarray(out["pop"].fitness),
                                  np.asarray(pop.fitness))
    assert out["pop"].spec.weights == pop.spec.weights


def test_save_restore_prng_key_bit_exact(tmp_path):
    key = jax.random.key(42)
    path = str(tmp_path / "key.pkl")
    save_state(path, {"key": key, "split": jax.random.split(key, 3)})
    out = restore_state(path)
    a = jax.random.uniform(out["key"], (4,))
    b = jax.random.uniform(key, (4,))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert out["split"].shape == (3,)


def test_resume_is_bit_exact(tmp_path):
    """Run 4 gens; checkpoint at gen 2; resume and verify gens 3-4 match."""
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_one_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=2)

    def gen_step(key, pop):
        k_sel, k_var = jax.random.split(key)
        idx = tb.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, gather(pop, idx), tb, 0.6, 0.3)
        return evaluate_invalid(off, tb.evaluate)

    ckpt = Checkpointer(str(tmp_path / "ckpts"), keep=2)
    pop = _onemax_pop(jax.random.key(1))
    key = jax.random.key(2)
    straight = None
    for gen in range(4):
        key, sub = jax.random.split(key)
        pop = gen_step(sub, pop)
        if gen == 1:
            ckpt.save(gen, {"pop": pop, "key": key, "gen": gen})
        if gen == 3:
            straight = pop

    state = ckpt.restore()
    assert state["gen"] == 1
    pop2, key2 = state["pop"], state["key"]
    for gen in range(2, 4):
        key2, sub = jax.random.split(key2)
        pop2 = gen_step(sub, pop2)
    np.testing.assert_array_equal(np.asarray(pop2.genomes),
                                  np.asarray(straight.genomes))
    np.testing.assert_array_equal(np.asarray(pop2.fitness),
                                  np.asarray(straight.fitness))


def test_checkpointer_rotation(tmp_path):
    ckpt = Checkpointer(str(tmp_path / "c"), keep=2)
    for s in range(5):
        ckpt.save(s, {"s": s})
    assert ckpt.steps() == [3, 4]
    assert ckpt.latest_step() == 4
    assert ckpt.restore()["s"] == 4
    assert ckpt.restore(3)["s"] == 3


def test_lineage_ids_and_history():
    lin = lineage_init(4)                     # founders 1..4
    hist = History()
    hist.found(4)
    # gen 1: children from parents (0,1), (1,0), (2,2), (3,3)
    pidx = jnp.asarray([[0, 1], [1, 0], [2, 2], [3, 3]])
    lin, parent_ids = lineage_step(lin, pidx)
    np.testing.assert_array_equal(np.asarray(lin.ids), [5, 6, 7, 8])
    hist.record(np.asarray(parent_ids))
    assert hist.genealogy_tree[5] == (1, 2)
    assert hist.genealogy_tree[7] == (3,)     # self-pair dedups to one
    # gen 2: all children of individual id 5 (index 0)
    lin, parent_ids = lineage_step(lin, jnp.zeros((4, 2), jnp.int32))
    hist.record(np.asarray(parent_ids))
    assert hist.genealogy_tree[9] == (5,)
    gene = hist.get_genealogy(9)
    assert gene[9] == (5,) and gene[5] == (1, 2)
    # depth limit
    assert 5 not in hist.get_genealogy(9, max_depth=1)


def test_genealogy_diamond_shared_ancestors():
    """Diamond lineage: D is an ancestor of A along two lines (A→B→D,
    A→C→D). BFS with a visited set must return it ONCE, expand it once,
    and honour max_depth at its shallowest occurrence — the reference's
    per-path recursion re-walks shared ancestors, which blows up
    combinatorially once crossover recombines relatives."""
    hist = History()
    hist.found(1)                                  # id 1 = D (founder)
    hist.record(np.asarray([[1], [1]]))            # gen1: B=2, C=3 of D
    hist.record(np.asarray([[2, 3]]))              # gen2: A=4 of B and C
    gene = hist.get_genealogy(4)
    assert gene == {4: (2, 3), 2: (1,), 3: (1,)}
    # depth 1: only A's own parents; D (depth 2 on both lines) excluded
    assert hist.get_genealogy(4, max_depth=1) == {4: (2, 3)}
    # a long chain hanging off one diamond arm must not be re-walked
    # through the other: build diamond-of-diamonds and check linearity
    hist2 = History()
    hist2.found(1)
    n_layers = 40
    for _ in range(n_layers):                      # each layer: a diamond
        top = hist2._next_id - 1
        hist2.record(np.asarray([[top], [top]]))   # two children of top
        a, b = hist2._next_id - 2, hist2._next_id - 1
        hist2.record(np.asarray([[a, b]]))         # merge them
    gene2 = hist2.get_genealogy(hist2._next_id - 1)
    # 3 nodes per layer (merge + two arms), founder reached via layer 1
    assert len(gene2) == 3 * n_layers
    assert gene2[2] == (1,) and gene2[3] == (1,)


def test_pair_parents_matches_varand_pairing():
    sel = jnp.asarray([4, 2, 7, 1])
    cx = jnp.asarray([True, False])
    p = np.asarray(pair_parents(sel, cx))
    np.testing.assert_array_equal(p[0], [4, 2])   # pair 0 crossed
    np.testing.assert_array_equal(p[1], [2, 4])
    np.testing.assert_array_equal(p[2], [7, 7])   # pair 1 didn't
    np.testing.assert_array_equal(p[3], [1, 1])


def test_lineage_inside_jit_scan():
    """Lineage bookkeeping must be jit/scan-compatible (stays on device)."""
    lin = lineage_init(4)

    def step(carry, idx):
        lin = carry
        lin, parents = lineage_step(lin, idx)
        return lin, parents

    idxs = jnp.zeros((3, 4, 2), jnp.int32)
    lin_out, recs = jax.jit(lambda l, i: jax.lax.scan(step, l, i))(lin, idxs)
    assert int(lin_out.next_id) == 17
    assert recs.shape == (3, 4, 2)
    hist = History()
    hist.found(4)
    hist.record_scan(np.asarray(recs))
    assert hist.genealogy_tree[9] == (5,)


def test_checkpoint_roundtrip_of_sharded_population(tmp_path):
    """Checkpointing a mesh-sharded population must gather to host on
    save and resume bit-exactly after re-sharding — the multi-device
    version of the reference's pickle-checkpoint recipe."""
    from deap_tpu.parallel import population_mesh, shard_population

    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)

    mesh = population_mesh()
    pop = init_population(jax.random.key(0), 32,
                          ops.bernoulli_genome(8), FitnessSpec((1.0,)))
    pop = evaluate_invalid(pop, tb.evaluate)
    pop = shard_population(pop, mesh)
    key = jax.random.key(1)

    def gen(key, pop):
        k_sel, k_var, key = jax.random.split(key, 3)
        idx = tb.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, gather(pop, idx), tb, 0.5, 0.2)
        return key, evaluate_invalid(off, tb.evaluate)

    key, pop = gen(key, pop)          # advance two generations sharded
    key, pop = gen(key, pop)

    path = str(tmp_path / "sharded.ckpt")
    save_state(path, {"pop": pop, "key": key})

    # continue WITHOUT restoring (ground truth)
    _, expect = gen(key, pop)

    # restore, re-shard, continue — must match bit-exactly
    state = restore_state(path)
    rpop = shard_population(state["pop"], mesh)
    _, got = gen(state["key"], rpop)

    np.testing.assert_array_equal(np.asarray(got.genomes),
                                  np.asarray(expect.genomes))
    np.testing.assert_array_equal(np.asarray(got.fitness),
                                  np.asarray(expect.fitness))
