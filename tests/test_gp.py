"""GP subsystem tests: prefix-tree mechanics, batched interpreter,
variation operators, and the canonical symbolic-regression convergence
gate (reference: deap/gp.py + examples/gp/symbreg.py seed-318 run)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import algorithms, gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import Population, init_population
from deap_tpu.core.toolbox import Toolbox

MAX_LEN = 64


@pytest.fixture(scope="module")
def pset():
    return gp.math_set(n_args=1)


def valid_prefix(genome, pset):
    """A prefix array is well-formed iff the arity walk closes exactly at
    ``length`` (searchSubtree invariant, gp.py:174-184)."""
    arity = np.asarray(pset.arity_table())
    nodes = np.asarray(genome["nodes"])
    length = int(genome["length"])
    need = 1
    for t in range(length):
        need += arity[nodes[t]] - 1
    return need == 0 and length >= 1


def test_generator_produces_valid_trees(pset):
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 4)
    genomes = jax.vmap(gen)(jax.random.split(jax.random.key(0), 64))
    for i in range(64):
        g = jax.tree_util.tree_map(lambda a: a[i], genomes)
        assert valid_prefix(g, pset)
        assert int(gp.tree_height(g, pset)) <= 4


def test_gen_full_hits_exact_depth(pset):
    gen = gp.gen_full(pset, MAX_LEN, 3, 3)
    for seed in range(8):
        g = gen(jax.random.key(seed))
        assert valid_prefix(g, pset)
        assert int(gp.tree_height(g, pset)) == 3


def test_interpreter_known_expression(pset):
    # (x + 1) * x  →  prefix: mul, add, ARG0, 1.0, ARG0
    from deap_tpu.gp.string import from_string, to_string

    genome = from_string("mul(add(ARG0, 1.0), ARG0)", pset, MAX_LEN)
    assert valid_prefix(genome, pset)
    interp = gp.make_interpreter(pset, MAX_LEN)
    X = jnp.linspace(-2, 2, 9)[:, None]
    got = interp(genome, X)
    want = (X[:, 0] + 1.0) * X[:, 0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    s = to_string(genome, pset)
    assert "ARG0" in s or "x" in s


def test_interpreter_factories_cached_per_pset(pset):
    # repeated factory calls hand back the SAME callables (identity-
    # stable closures keep downstream jit caches warm), the primitive
    # dispatch is built once per set, and the cached arity table is
    # one device array, not a rebuild per evaluation pass
    assert gp.make_interpreter(pset, MAX_LEN) is gp.make_interpreter(
        pset, MAX_LEN)
    assert gp.make_batch_interpreter(pset, MAX_LEN) is \
        gp.make_batch_interpreter(pset, MAX_LEN)
    assert gp.make_interpreter(pset, MAX_LEN + 1) is not \
        gp.make_interpreter(pset, MAX_LEN)
    from deap_tpu.gp.interpreter import _prim_rows_builder
    assert _prim_rows_builder(pset) is _prim_rows_builder(pset)
    assert pset.arity_table() is pset.arity_table()
    # growing the set invalidates: fresh rows, fresh arity table
    fresh = gp.math_set(n_args=1)
    before = (_prim_rows_builder(fresh), fresh.arity_table(),
              gp.make_interpreter(fresh, MAX_LEN))
    fresh.add_primitive(jnp.minimum, 2, name="min2")
    assert _prim_rows_builder(fresh) is not before[0]
    assert fresh.arity_table() is not before[1]
    assert gp.make_interpreter(fresh, MAX_LEN) is not before[2]
    assert int(fresh.arity_table()[fresh.n_ops - 1]) == 2


def test_interpreter_protected_div(pset):
    from deap_tpu.gp.string import from_string

    genome = from_string("protectedDiv(1.0, ARG0)", pset, MAX_LEN)
    interp = gp.make_interpreter(pset, MAX_LEN)
    X = jnp.array([[0.0], [2.0]])
    got = np.asarray(interp(genome, X))
    assert got[0] == 1.0 and got[1] == 0.5


def test_prefix_depths_match_python_walk(pset):
    """The closed-form ancestor-count depths (gp.tree.prefix_depths,
    which tree_height now reduces over) must match a direct recursive
    walk of the prefix."""
    from deap_tpu.gp.tree import prefix_depths

    arity_np = np.asarray(pset.arity_table())
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 5)
    for seed in range(12):
        g = gen(jax.random.key(seed))
        nodes = np.asarray(g["nodes"])
        length = int(g["length"])

        depths = np.zeros(length, np.int32)

        def walk(i, d):
            depths[i] = d
            j = i + 1
            for _ in range(arity_np[nodes[i]]):
                j = walk(j, d + 1)
            return j

        end = walk(0, 0)
        assert end == length
        got = np.asarray(prefix_depths(
            g["nodes"], g["length"], pset.arity_table()))[:length]
        np.testing.assert_array_equal(got, depths)
        assert int(gp.tree_height(g, pset)) == int(depths.max())


def test_sweep_interpreter_matches_scan(pset):
    """mode='sweep' (level-synchronous evaluation) must agree exactly
    with the serial scan path on a mixed-size population."""
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 4)
    pop = [gen(jax.random.key(s)) for s in range(24)]
    genomes = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *pop)
    X = jnp.linspace(-2, 2, 13)[:, None]
    scan = gp.make_batch_interpreter(pset, MAX_LEN, mode="scan")
    sweep = gp.make_batch_interpreter(pset, MAX_LEN, mode="sweep")
    np.testing.assert_allclose(np.asarray(sweep(genomes, X)),
                               np.asarray(scan(genomes, X)), rtol=1e-6)


def test_batch_interpreter_matches_single_tree(pset):
    """The active-length-bounded batch path must agree exactly with the
    full-width per-tree interpreter on a mixed-size population (the
    dynamic trip count T=max(length) only skips padding slots)."""
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 4)
    pop = [gen(jax.random.key(s)) for s in range(32)]
    genomes = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *pop)
    X = jnp.linspace(-2, 2, 17)[:, None]
    single = gp.make_interpreter(pset, MAX_LEN)
    batch = gp.make_batch_interpreter(pset, MAX_LEN)
    want = jax.vmap(lambda g: single(g, X))(genomes)
    got = batch(genomes, X)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6)
    # and under jit with a different (smaller) max population length
    tiny = jax.tree_util.tree_map(lambda a: a[:4], genomes)
    got2 = jax.jit(batch)(tiny, X)
    want2 = jax.vmap(lambda g: single(g, X))(tiny)
    np.testing.assert_allclose(np.asarray(got2), np.asarray(want2),
                               rtol=1e-6)


def test_subtree_end_matches_python_walk(pset):
    gen = gp.gen_half_and_half(pset, MAX_LEN, 2, 5)
    arity = pset.arity_table()
    arity_np = np.asarray(arity)
    for seed in range(6):
        g = gen(jax.random.key(seed + 10))
        nodes = np.asarray(g["nodes"])
        for i in range(int(g["length"])):
            end, need = i, 1
            while need:
                need += arity_np[nodes[end]] - 1
                end += 1
            assert int(gp.subtree_end(g["nodes"], arity, i)) == end


def test_cx_one_point_preserves_validity(pset):
    gen = gp.gen_half_and_half(pset, MAX_LEN, 2, 5)
    cx = gp.make_cx_one_point(pset)
    keys = jax.random.split(jax.random.key(3), 32)
    for i in range(0, 32, 2):
        g1, g2 = gen(keys[i]), gen(keys[i + 1])
        c1, c2 = cx(jax.random.fold_in(keys[i], 7), g1, g2)
        assert valid_prefix(c1, pset)
        assert valid_prefix(c2, pset)
        # total node count is conserved by a swap
        assert (int(c1["length"]) + int(c2["length"])
                == int(g1["length"]) + int(g2["length"])) or (
            int(c1["length"]) == int(g1["length"]))  # oversize → unchanged


def test_mutations_preserve_validity(pset):
    gen = gp.gen_half_and_half(pset, MAX_LEN, 2, 5)
    muts = [
        gp.make_mut_uniform(pset, gp.gen_full(pset, MAX_LEN, 0, 2)),
        gp.make_mut_node_replacement(pset),
        gp.make_mut_ephemeral(pset, "one"),
        gp.make_mut_ephemeral(pset, "all"),
        gp.make_mut_insert(pset),
        gp.make_mut_shrink(pset),
    ]
    for seed in range(4):
        g = gen(jax.random.key(seed + 20))
        for m, mut in enumerate(muts):
            out = mut(jax.random.key(100 * seed + m), g)
            assert valid_prefix(out, pset), f"mutation {m} broke the tree"


def test_mut_shrink_exempts_tiny_trees(pset):
    from deap_tpu.gp.string import from_string

    mut = gp.make_mut_shrink(pset)
    g = from_string("add(ARG0, 1.0)", pset, MAX_LEN)  # len 3, height 1...
    # reference exempts len < 3 — this is len 3 with the op AT the root,
    # so no below-root operator exists and it must pass unchanged
    out = mut(jax.random.key(0), g)
    np.testing.assert_array_equal(np.asarray(out["nodes"]),
                                  np.asarray(g["nodes"]))


def test_static_limit_keeps_parent(pset):
    gen_deep = gp.gen_full(pset, MAX_LEN, 5, 5)
    mut = gp.make_mut_uniform(pset, gen_deep)
    limited = gp.static_limit(
        lambda g: gp.tree_height(g, pset), 3)(mut)
    gen = gp.gen_full(pset, MAX_LEN, 2, 2)
    g = gen(jax.random.key(1))
    out = limited(jax.random.key(2), g)
    assert int(gp.tree_height(out, pset)) <= 3


def test_symbreg_quartic_converges(pset):
    """The canonical GP loop: quartic regression x⁴+x³+x²+x over 20
    points in [-1, 1) (examples/gp/symbreg.py:55-75). Quality gate: MSE
    of the best individual < 0.05 after 40 generations."""
    X = jnp.linspace(-1, 1, 20)[:, None]
    y = X[:, 0] ** 4 + X[:, 0] ** 3 + X[:, 0] ** 2 + X[:, 0]
    evaluate = gp.make_population_evaluator(
        pset, MAX_LEN, lambda pred, y_: jnp.mean((pred - y_) ** 2))

    tb = Toolbox()
    tb.register("evaluate", lambda genomes: -evaluate(genomes, X, y))
    height_limit = gp.static_limit(lambda g: gp.tree_height(g, pset), 17)
    tb.register("mate", height_limit(gp.make_cx_one_point(pset)))
    tb.register("mutate", height_limit(
        gp.make_mut_uniform(pset, gp.gen_full(pset, MAX_LEN, 0, 2))))
    tb.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(
        jax.random.key(318), 300, gp.gen_half_and_half(pset, MAX_LEN, 1, 2),
        FitnessSpec((1.0,)))
    pop, logbook, hof = algorithms.ea_simple(
        jax.random.key(318), pop, tb, cxpb=0.5, mutpb=0.1, ngen=40,
        halloffame_size=1)
    best_mse = float(-hof.fitness[0, 0])
    assert best_mse < 0.05


def test_to_graph_structure(pset):
    # mul(add(ARG0, 1.0), ARG0): edges root->add, root->ARG0, add->leaves
    from deap_tpu.gp.string import from_string, to_graph

    genome = from_string("mul(add(ARG0, 1.0), ARG0)", pset, MAX_LEN)
    nodes, edges, labels = to_graph(genome, pset)
    assert nodes == [0, 1, 2, 3, 4]
    assert set(edges) == {(0, 1), (0, 4), (1, 2), (1, 3)}
    assert labels[0] == "mul" and labels[1] == "add"
    assert labels[2] == "ARG0" and labels[4] == "ARG0"
    assert "1.0" in labels[3]


def test_to_graph_single_terminal(pset):
    from deap_tpu.gp.string import from_string, to_graph

    genome = from_string("ARG0", pset, MAX_LEN)
    nodes, edges, labels = to_graph(genome, pset)
    assert nodes == [0] and edges == [] and labels[0] == "ARG0"


def test_tensor_interpreter_agrees_with_compat_compile():
    """The batched stack interpreter and the compat (Python-object)
    evaluator compute identical values for the same trees — the tensor
    node encoding converted to compat nodes by name."""
    import math
    import operator

    import numpy as np

    from deap_tpu import gp as tgp
    from deap_tpu.compat import gp as cgp

    tpset = tgp.math_set(n_args=1)
    interp = tgp.make_interpreter(tpset, 48)
    gen = tgp.gen_half_and_half(tpset, 48, 2, 4)
    # even point count keeps x away from 0: near-singular protectedDiv
    # denominators make f32 (tensor) and f64 (compat) trig argument
    # reduction legitimately diverge
    X = jnp.linspace(-1.0, 1.0, 8)[:, None]

    cset = cgp.PrimitiveSet("MAIN", 1)
    cset.addPrimitive(operator.add, 2)
    cset.addPrimitive(operator.sub, 2)
    cset.addPrimitive(operator.mul, 2)
    # same protection rule as the tensor pset: 1.0 iff b == 0 exactly
    cset.addPrimitive(lambda a, b: a / b if b != 0.0 else 1.0, 2,
                      name="protectedDiv")
    cset.addPrimitive(operator.neg, 1)
    cset.addPrimitive(math.cos, 1)
    cset.addPrimitive(math.sin, 1)

    def to_compat(genome):
        nodes = np.asarray(genome["nodes"])
        consts = np.asarray(genome["consts"])
        out = []
        for i in range(int(genome["length"])):
            nid = int(nodes[i])
            if nid < tpset.n_ops:
                out.append(cset.mapping[tpset.primitives[nid].name])
            elif nid < tpset.n_ops + tpset.n_args:
                out.append(cset.mapping[f"ARG{nid - tpset.n_ops}"])
            else:
                v = float(consts[i])
                out.append(cgp.Terminal(repr(v), v, object))
        return cgp.PrimitiveTree(out)

    checked = 0
    for i in range(25):
        g = gen(jax.random.key(1000 + i))
        f = cgp.compile(to_compat(g), cset)
        tensor_out = np.asarray(interp(g, X))
        compat_out = np.array([f(float(x)) for x in X[:, 0]],
                              np.float32)
        # protected division thresholds may legitimately differ at
        # near-zero denominators; skip trees that hit that edge
        if not np.isfinite(compat_out).all():
            continue
        assert np.allclose(tensor_out, compat_out, rtol=1e-4,
                           atol=1e-5), tgp.to_string(g, tpset)
        checked += 1
    assert checked >= 15


def test_compat_from_string_round_trip():
    """PrimitiveTree.from_string (gp.py:106-153) inverts the prefix
    printer for function-call-style expressions."""
    import operator
    import random

    from deap_tpu.compat import gp as cgp

    pset = cgp.PrimitiveSet("MAIN", 1)
    pset.addPrimitive(operator.add, 2)
    pset.addPrimitive(operator.mul, 2)
    pset.addPrimitive(operator.neg, 1)
    pset.addTerminal(3.0)
    pset.renameArguments(ARG0="x")
    random.seed(7)
    for _ in range(20):
        t = cgp.genGrow(pset, 2, 4)
        t2 = cgp.PrimitiveTree.from_string(str(t), pset)
        f1 = cgp.compile(t, pset)
        f2 = cgp.compile(t2, pset)
        for x in (-1.0, 0.25, 2.0):
            assert abs(f1(x) - f2(x)) < 1e-9
