"""Static drift-check: every collective issued from ``deap_tpu/parallel``
must be lexically wrapped in a named profiling ``span(...)``.

The per-collective spans are the only way cross-shard time stays
attributable (xplane scopes when a trace is possible, SpanRecorder
wall-time aggregates when it is not — the n=8 weak-scaling cliff
investigation depends on them). A new collective added without a span
would silently rot that coverage; this AST walk makes the omission a
test failure instead.

The mesh-native ShardingPlan widened the set: on the pjit path the
explicit collectives disappear into the partitioner, and the ops that
move or pin data across the mesh are the *resharding* ops instead —
``device_put`` (plan placement / elastic-resume reshard) and
``with_sharding_constraint`` (in-jit layout pins). Those carry the
same attribution duty, so they sit in the same gate.
"""

import ast
import os

import deap_tpu.parallel as parallel_pkg

#: call names that issue (or dispatch to) a collective. ``collective``
#: covers genome_shard's table-dispatched psum/pmean/pmax call site —
#: the function reference lives in _COMBINE_COLLECTIVES, the call goes
#: through a local name. ``device_put``/``with_sharding_constraint``
#: are the ShardingPlan's resharding ops — data movement the pjit
#: path performs instead of explicit collectives.
COLLECTIVE_CALLS = {"psum", "pmean", "pmax", "ppermute", "all_gather",
                    "all_to_all", "collective", "device_put",
                    "with_sharding_constraint"}

PARALLEL_DIR = os.path.dirname(os.path.abspath(parallel_pkg.__file__))


def _call_name(node: ast.Call):
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_axis_size_idiom(node: ast.Call) -> bool:
    """``psum(1, axis)`` is the mesh-metadata spelling of axis_size —
    it constant-folds to the mesh shape and moves no data, so it is
    exempt from the span requirement (parallel/mesh.py)."""
    return (bool(node.args)
            and isinstance(node.args[0], ast.Constant)
            and node.args[0].value == 1)


def _span_wrapped(node: ast.AST, parents: dict) -> bool:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            for item in cur.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call) and _call_name(ce) == "span":
                    return True
        cur = parents.get(cur)
    return False


def _collective_calls(tree: ast.AST):
    parents = {}
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            parents[child] = parent
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and _call_name(node) in COLLECTIVE_CALLS
                and not _is_axis_size_idiom(node)):
            yield node, parents


def test_every_parallel_collective_is_span_wrapped():
    violations = []
    n_checked = 0
    for fname in sorted(os.listdir(PARALLEL_DIR)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(PARALLEL_DIR, fname)
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        for node, parents in _collective_calls(tree):
            n_checked += 1
            if not _span_wrapped(node, parents):
                violations.append(
                    f"{fname}:{node.lineno}: {_call_name(node)}(...) "
                    "outside any span(...) block")
    # the check must actually be exercising call sites — an empty scan
    # would pass vacuously if the detection logic rotted instead
    assert n_checked >= 3, (
        f"only {n_checked} collective call sites found under parallel/ "
        "— the AST detection itself has drifted")
    assert not violations, (
        "collectives without a named profiling span (add `with "
        "span(\"<module>/<collective>\"):` — see genome_shard.py):\n"
        + "\n".join(violations))


def test_plan_resharding_ops_are_span_wrapped():
    """The plan's resharding ops actually exist under the gate (the
    widened COLLECTIVE_CALLS set must be exercising real call sites,
    not vacuously passing): plan.py wraps its device_put and
    with_sharding_constraint in plan/* spans."""
    path = os.path.join(PARALLEL_DIR, "plan.py")
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    names = set()
    for node, parents in _collective_calls(tree):
        assert _span_wrapped(node, parents)
        names.add(_call_name(node))
    assert "device_put" in names
    assert "with_sharding_constraint" in names


def test_genome_shard_span_names_cover_every_combine_mode():
    """The span name table and the collective table live in one dict
    (genome_shard._COMBINE_COLLECTIVES) precisely so they cannot drift;
    pin that the names stay the documented ``genome_shard/<collective>``
    scheme for every combine mode."""
    from deap_tpu.parallel.genome_shard import _COMBINE_COLLECTIVES

    assert set(_COMBINE_COLLECTIVES) == {"sum", "mean", "max"}
    for mode, (cname, fn) in _COMBINE_COLLECTIVES.items():
        assert cname in COLLECTIVE_CALLS
        assert fn.__name__ == cname
