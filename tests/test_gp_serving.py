"""Batched GP generations through the serving plane — bit-identity.

The acceptance bar of ``deap_tpu/serving/gp_multirun.py``: N GP runs
packed on a leading run axis (one jitted scan, union-mask specialized
evaluation, per-lane fold_in key schedules) must be **bit-identical**
per lane to the solo host-dispatch loop (``gp/loop.py``), across the
matrix the tentpole names — mixed ngen × ERC-heavy × typed-flavoured
(bool vocabulary) × ADF lanes — plus the island run-axis engine vs the
solo epoch driver, the Scheduler end-to-end (eviction/resume included)
and the ResilientRun segmented driver with a mid-run resume.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deap_tpu import gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.gp.loop import make_gp_loop, make_symbreg_loop
from deap_tpu.gp.pset import bool_set, math_set
from deap_tpu.gp.tree import make_generator
from deap_tpu.parallel.island import island_init, make_island_step
from deap_tpu.resilience.engine import ResilientRun
from deap_tpu.serving import (
    GpJobSpec,
    GpMultiRunEngine,
    IslandJobSpec,
    IslandMultiRunEngine,
    Job,
    Scheduler,
)

ML = 32
N = 24
P = 12


def _tree_eq(a, b):
    return jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda x, z: bool(np.array_equal(np.asarray(x), np.asarray(z))),
        a, b))


def _assert_gp_result_equal(solo, batched, label=""):
    for k in ("genomes", "depths", "fitness", "best_genome"):
        assert _tree_eq(solo[k], batched[k]), f"{label}: {k} differs"
    assert solo["nevals"] == batched["nevals"], label
    assert solo["best_fitness"] == batched["best_fitness"], label


def _founders(pset, seed, n=N, max_len=ML, depth=3):
    gen = make_generator(pset, max_len, 1, depth, "full")
    ks = jax.random.split(jax.random.key(seed), n)
    return jax.vmap(gen)(ks)


def _symbreg_data(n_points=P):
    X = np.linspace(-1, 1, n_points).reshape(n_points, 1) \
        .astype(np.float32)
    y = (X[:, 0] ** 2 + X[:, 0]).astype(np.float32)
    return X, y


def _run_batched(eng, keys, inits, ngens, hypers, segment_len=3,
                 n_lanes=None):
    """Drive the engine the way the scheduler does — lane_init, pack
    into a padded slot count, segmented advance, per-lane decode."""
    n = len(keys)
    lanes = [eng.lane_init(k, g0, ng, h)
             for k, g0, ng, h in zip(keys, inits, ngens, hypers)]
    batch = eng.pack(lanes, n_lanes=n_lanes or n, horizon=max(ngens))
    segs = []
    while not eng.done(batch)[:n].all():
        batch, seg = eng.advance(batch, segment_len)
        segs.append(seg)
    return [eng.lane_result(eng.unpack(batch, i),
                            eng.lane_records(segs, i))
            for i in range(n)]


# ------------------------------------------------ symbreg / mixed ngen ----

def test_gp_batched_mixed_ngen_bit_identity():
    """Mixed-ngen lanes (the completion latch + uneven masks) against
    the solo symbreg loop — the tentpole's core contract. math_set
    carries an ERC, so ephemeral sampling rides every lane."""
    pset = math_set(n_args=1)
    X, y = _symbreg_data()
    solo = make_symbreg_loop(pset, ML, X, y, cxpb=0.5, mutpb=0.2)
    ngens = [7, 4, 7, 2]
    solo_res = [solo(jax.random.key(100 + i), _founders(pset, i), ng)
                for i, ng in enumerate(ngens)]

    spec = GpJobSpec(pset=pset, max_len=ML, X=X, y=y)
    eng = GpMultiRunEngine(spec)
    out = _run_batched(
        eng, [jax.random.key(100 + i) for i in range(4)],
        [_founders(pset, i) for i in range(4)], ngens,
        [{"cxpb": 0.5, "mutpb": 0.2}] * 4,
        n_lanes=6)  # 2 padding slots: inactive lanes must stay no-ops
    for i in range(4):
        _assert_gp_result_equal(solo_res[i], out[i], f"lane {i}")


def test_gp_batched_erc_heavy_bit_identity():
    """ERC-heavy lanes: high mutpb + deep donor trees hammer the
    ephemeral sampler and the mutation donor vocabulary — the path
    that forces union-mask replays."""
    pset = math_set(n_args=1, erc_low=-2.0, erc_high=2.0)
    X, y = _symbreg_data()
    solo = make_symbreg_loop(pset, ML, X, y, cxpb=0.3, mutpb=0.6,
                             mut_max=3)
    solo_res = [solo(jax.random.key(7 + i), _founders(pset, 50 + i), 5)
                for i in range(2)]

    spec = GpJobSpec(pset=pset, max_len=ML, X=X, y=y, mut_max=3)
    eng = GpMultiRunEngine(spec)
    out = _run_batched(
        eng, [jax.random.key(7 + i) for i in range(2)],
        [_founders(pset, 50 + i) for i in range(2)], [5, 5],
        [{"cxpb": 0.3, "mutpb": 0.6}] * 2, segment_len=2)
    for i in range(2):
        _assert_gp_result_equal(solo_res[i], out[i], f"erc lane {i}")


# ------------------------------------------- typed-flavoured (bool) ----

def test_gp_batched_bool_vocab_custom_eval_bit_identity():
    """The typed-problem formulation (bool vocabulary, even-parity
    target) through the custom-``evaluate`` mode: the engine and the
    solo loop share ONE trace-safe row-independent evaluator, so
    bit-identity isolates the key-schedule/variation mirroring."""
    pset = bool_set(n_args=2)
    interp = gp.make_batch_interpreter(pset, 24, mode="scan",
                                       dedup=False)
    X = jnp.asarray([[0, 0], [0, 1], [1, 0], [1, 1]], jnp.float32)
    y = jnp.asarray([0, 1, 1, 0], jnp.float32)  # XOR / even parity

    def evaluate(genomes):
        preds = interp(genomes, X)
        return -jnp.mean((preds - y[None, :]) ** 2, axis=1)

    solo = make_gp_loop(pset, 24, evaluate, cxpb=0.5, mutpb=0.3)
    solo_res = [solo(jax.random.key(31 + i),
                     _founders(pset, 80 + i, max_len=24), 5)
                for i in range(2)]

    spec = GpJobSpec(pset=pset, max_len=24, evaluate=evaluate,
                     name="parity")
    eng = GpMultiRunEngine(spec)
    out = _run_batched(
        eng, [jax.random.key(31 + i) for i in range(2)],
        [_founders(pset, 80 + i, max_len=24) for i in range(2)],
        [5, 5], [{"cxpb": 0.5, "mutpb": 0.3}] * 2, segment_len=2)
    for i in range(2):
        _assert_gp_result_equal(solo_res[i], out[i], f"bool lane {i}")


# --------------------------------------------------------- ADF lanes ----

def test_gp_batched_adf_lanes_bit_identity():
    """ADF-flavoured lanes: the MAIN branch evolves (its pset carries
    the ADF0 call op) while a frozen defined-function branch rides
    inside a shared row-independent evaluator built on the masked ADF
    batch interpreter — the documented way ADF trees join the batch."""
    main = gp.PrimitiveSet("MAIN", 1)
    main.add_primitive(jnp.add, 2, "add")
    main.add_primitive(jnp.multiply, 2, "mul")
    main.add_adf("ADF0", 1, branch=1)
    sub = gp.PrimitiveSet("ADF0", 1)
    sub.add_primitive(jnp.subtract, 2, "sub")
    sub.add_primitive(jnp.cos, 1, "cos")
    branches = [(main, 24), (sub, 16)]
    # specialize="none": a shared custom evaluator must compute the
    # same bits eagerly (solo loop) and under trace (batched scan) —
    # the mask-specialized interpreter re-specializes on whatever
    # concrete sub-batch the solo loop hands it, which is exactly the
    # bit-instability the custom-evaluate contract rules out
    adf_interp = gp.make_adf_batch_interpreter(branches,
                                               specialize="none")
    # one frozen ADF0 body shared by every row: cos(ARG0)
    sub_gen = make_generator(sub, 16, 1, 2, "full")
    sub_g = sub_gen(jax.random.key(999))
    X = jnp.linspace(-1.0, 1.0, 9)[:, None]
    y = jnp.cos(X[:, 0]) * X[:, 0]

    def evaluate(genomes):
        rows = genomes["nodes"].shape[0]
        sub_b = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (rows,) + a.shape), sub_g)
        preds = adf_interp((genomes, sub_b), X)
        # max-abs (Chebyshev) loss: elementwise ops + a max reduction
        # are bit-stable under any fusion order — a mean's summation
        # can reassociate between the eager (solo) and traced (batch)
        # compilations of the same evaluator and break bit-identity
        return -jnp.max(jnp.abs(preds - y[None, :]), axis=1)

    solo = make_gp_loop(main, 24, evaluate, cxpb=0.5, mutpb=0.2)
    solo_res = [solo(jax.random.key(61 + i),
                     _founders(main, 90 + i, max_len=24), 4)
                for i in range(2)]

    spec = GpJobSpec(pset=main, max_len=24, evaluate=evaluate,
                     name="adf")
    eng = GpMultiRunEngine(spec)
    out = _run_batched(
        eng, [jax.random.key(61 + i) for i in range(2)],
        [_founders(main, 90 + i, max_len=24) for i in range(2)],
        [4, 4], [{"cxpb": 0.5, "mutpb": 0.2}] * 2, segment_len=2)
    for i in range(2):
        _assert_gp_result_equal(solo_res[i], out[i], f"adf lane {i}")


# ----------------------------------------------------- island run axis ----

def _island_toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def _island_init(seed, n_islands=4, island_size=16):
    return island_init(jax.random.key(seed), n_islands, island_size,
                       ops.bernoulli_genome(12), FitnessSpec((1.0,)))


def test_island_run_axis_vs_solo_epoch_bit_identity():
    """N island runs (per-lane cxpb/mutpb, mixed epoch budgets) on the
    run axis vs the solo ``make_island_step`` epoch driver keyed
    ``fold_in(key, epoch)`` — migration ring, tournament, everything."""
    tb = _island_toolbox()
    spec = IslandJobSpec(n_islands=4, island_size=16, freq=2, mig_k=2)
    ngens = [5, 3, 5]
    hypers = [{"cxpb": 0.5, "mutpb": 0.2}, {"cxpb": 0.7, "mutpb": 0.1},
              {"cxpb": 0.5, "mutpb": 0.2}]
    solo_pops = []
    for i, ng in enumerate(ngens):
        step = make_island_step(tb, hypers[i]["cxpb"],
                                hypers[i]["mutpb"], 2, 2)
        pops = _island_init(i)
        key = jax.random.key(100 + i)
        for epoch in range(ng):
            pops = step(jax.random.fold_in(key, epoch), pops)
        solo_pops.append(pops)

    eng = IslandMultiRunEngine(tb, spec)
    out = _run_batched(
        eng, [jax.random.key(100 + i) for i in range(3)],
        [_island_init(i) for i in range(3)], ngens, hypers,
        segment_len=2, n_lanes=4)
    for i, s in enumerate(solo_pops):
        assert _tree_eq((s.genomes, s.fitness, s.valid),
                        (out[i].genomes, out[i].fitness, out[i].valid)), \
            f"island lane {i} diverged from the solo epoch driver"


# --------------------------------------------- Scheduler end-to-end ----

def test_scheduler_gp_island_eviction_resume_bit_identity(tmp_path):
    """GP and island jobs through the Scheduler — including forced
    eviction/resume (3 GP tenants on 2 lanes, fair_quantum=1) — must
    return results bit-identical to solo, expose the job family in
    ``slo_snapshot()`` and in the family-labelled residents gauge."""
    pset = math_set(n_args=1)
    X, y = _symbreg_data(16)
    solo = make_symbreg_loop(pset, ML, X, y, cxpb=0.5, mutpb=0.2)
    ngens = [13, 9, 13]
    founders = [_founders(pset, i, n=32) for i in range(3)]
    solo_res = [solo(jax.random.key(100 + i), founders[i], ng)
                for i, ng in enumerate(ngens)]
    spec = GpJobSpec(pset=pset, max_len=ML, X=X, y=y)

    tb = _island_toolbox()
    ispec = IslandJobSpec(n_islands=4, island_size=16, freq=2, mig_k=2)
    ingens = [7, 5]
    ihyp = [{"cxpb": 0.5, "mutpb": 0.2}, {"cxpb": 0.7, "mutpb": 0.1}]
    solo_pops = []
    for i, ng in enumerate(ingens):
        step = make_island_step(tb, ihyp[i]["cxpb"], ihyp[i]["mutpb"],
                                2, 2)
        pops = _island_init(i)
        key = jax.random.key(200 + i)
        for epoch in range(ng):
            pops = step(jax.random.fold_in(key, epoch), pops)
        solo_pops.append(pops)

    sched = Scheduler(str(tmp_path), max_lanes=2, segment_len=4,
                      fair_quantum=1)
    gp_ids = [sched.submit(Job(
        tenant_id=f"gp{i}", family="gp", toolbox=None,
        key=jax.random.key(100 + i), init=founders[i], ngen=ng,
        hyper={"cxpb": 0.5, "mutpb": 0.2}, spec=spec))
        for i, ng in enumerate(ngens)]
    isl_ids = [sched.submit(Job(
        tenant_id=f"isl{i}", family="island", toolbox=tb,
        key=jax.random.key(200 + i), init=_island_init(i), ngen=ng,
        hyper=ihyp[i], spec=ispec))
        for i, ng in enumerate(ingens)]
    results = sched.run()

    for i, jid in enumerate(gp_ids):
        _assert_gp_result_equal(solo_res[i], results[jid], f"gp{i}")
    for i, jid in enumerate(isl_ids):
        s, r = solo_pops[i], results[jid]
        assert _tree_eq((s.genomes, s.fitness, s.valid),
                        (r.genomes, r.fitness, r.valid)), f"isl{i}"
    snap = sched.slo_snapshot()
    assert sorted({row["family"] for row in snap.values()}) \
        == ["gp", "island"]
    text = sched.metrics.metrics_text()
    assert "deap_serving_family_residents" in text
    assert 'family="gp"' in text and 'family="island"' in text
    sched.close()


def test_scheduler_rejects_gp_island_jobs_without_spec(tmp_path):
    with Scheduler(str(tmp_path)) as sched:
        with pytest.raises(ValueError, match="spec"):
            sched.submit(Job(tenant_id="g", family="gp", toolbox=None,
                             key=jax.random.key(0), init={}, ngen=2,
                             hyper={"cxpb": 0.5, "mutpb": 0.2}))
        with pytest.raises(ValueError, match="spec"):
            sched.submit(Job(tenant_id="i", family="island",
                             toolbox=_island_toolbox(),
                             key=jax.random.key(0), init={}, ngen=2,
                             hyper={"cxpb": 0.5, "mutpb": 0.2}))


# ------------------------------------------- ResilientRun.multirun ----

def test_resilient_multirun_gp_segmented_and_resumed(tmp_path):
    """The batched driver under ResilientRun: a packed GP batch
    checkpointed at segment boundaries finishes bit-identical to solo,
    and a FRESH engine resuming the batch from a mid-run checkpoint
    (union mask regrown from the restored genomes) stays bit-exact."""
    pset = math_set(n_args=1)
    X, y = _symbreg_data()
    solo = make_symbreg_loop(pset, ML, X, y, cxpb=0.5, mutpb=0.2)
    ngens = [8, 5]
    keys = [jax.random.key(40 + i) for i in range(2)]
    inits = [_founders(pset, i) for i in range(2)]
    solo_res = [solo(keys[i], inits[i], ng)
                for i, ng in enumerate(ngens)]
    spec = GpJobSpec(pset=pset, max_len=ML, X=X, y=y)
    hyper = {"cxpb": 0.5, "mutpb": 0.2}

    res = ResilientRun(str(tmp_path / "a"), segment_len=3)
    out = res.multirun(GpMultiRunEngine(spec), keys, inits, ngens,
                       hyper=hyper)
    for i in range(2):
        _assert_gp_result_equal(solo_res[i], out[i], f"seg lane {i}")

    # mid-run checkpoint written by one engine, resumed by ANOTHER
    from deap_tpu.resilience.engine import _EngineBatchSpec
    root2 = str(tmp_path / "b")
    res2 = ResilientRun(root2, segment_len=3)
    sp = _EngineBatchSpec(GpMultiRunEngine(spec), keys, inits, ngens,
                          [hyper] * 2)
    st = sp.init()
    st["_resilience"] = {"algorithm": sp.algorithm,
                         "run_id": "partial", "ngen": max(ngens)}
    st = sp.segment(st, 0, 3)
    res2.ckpt.save(3, st, meta=dict(st["_resilience"], step=3))
    res3 = ResilientRun(root2, segment_len=3)
    out2 = res3.multirun(GpMultiRunEngine(spec), keys, inits, ngens,
                         hyper=hyper)
    assert res3.resumed_from == "partial"
    for i in range(2):
        _assert_gp_result_equal(solo_res[i], out2[i],
                                f"resumed lane {i}")
