"""Unit tests for the extended compat operator family.

Covers the list-based operators added for full reference-API parity
(reference deap/tools/{crossover,mutation,selection,constraint}.py and
deap/gp.py): permutation crossovers, bounded SBX/polynomial, ES
operators, the lexicase family, double tournament, SUS, penalty
decorators, leaf-biased + semantic GP variation, graph export, and
HARM-GP. All checks are hand-computed invariants — RNG-stream parity
against the reference was verified at build time (see commit message).
"""

import math
import operator
import random

from deap_tpu.compat import base, creator, gp as cgp, tools


def setup_function(_):
    random.seed(1234)


# ----------------------------------------------------------- crossovers ----

def _perms(n=12):
    return random.sample(range(n), n), random.sample(range(n), n)


def test_permutation_crossovers_preserve_permutations():
    for op in (tools.cxPartialyMatched,
               lambda a, b: tools.cxUniformPartialyMatched(a, b, 0.3),
               tools.cxOrdered):
        for _ in range(50):
            a, b = _perms()
            c1, c2 = op(list(a), list(b))
            assert sorted(c1) == sorted(c2) == list(range(12))


def test_cx_ordered_keeps_middle_slice_swapped():
    random.seed(9)
    a, b = _perms(8)
    c1, c2 = tools.cxOrdered(list(a), list(b))
    assert sorted(c1) == list(range(8)) and sorted(c2) == list(range(8))


def test_sbx_bounded_respects_bounds_and_mean():
    for _ in range(50):
        a = [random.uniform(0, 1) for _ in range(6)]
        b = [random.uniform(0, 1) for _ in range(6)]
        c1, c2 = tools.cxSimulatedBinaryBounded(list(a), list(b),
                                                eta=15.0, low=0.0, up=1.0)
        assert all(0.0 <= x <= 1.0 for x in c1 + c2)
    # unbounded SBX preserves the per-gene mean exactly
    a = [0.2, 0.8]
    b = [0.6, 0.4]
    c1, c2 = tools.cxSimulatedBinary(list(a), list(b), eta=5.0)
    for i in range(2):
        assert math.isclose(c1[i] + c2[i], a[i] + b[i])


def test_cx_messy_changes_lengths():
    random.seed(3)
    lengths = set()
    for _ in range(30):
        c1, c2 = tools.cxMessyOnePoint(list(range(8)), list(range(20, 30)))
        lengths.add((len(c1), len(c2)))
        assert len(c1) + len(c2) == 18
    assert len(lengths) > 1  # length-changing, unlike cxOnePoint


def _es_pair(n=6):
    creator.create("FitES", base.Fitness, weights=(-1.0,))
    creator.create("IndES", list, fitness=creator.FitES, strategy=None)
    i1 = creator.IndES(random.random() for _ in range(n))
    i1.strategy = [random.random() for _ in range(n)]
    i2 = creator.IndES(random.random() for _ in range(n))
    i2.strategy = [random.random() for _ in range(n)]
    return i1, i2


def test_es_two_point_mirrors_values_and_strategies():
    i1, i2 = _es_pair()
    v = (list(i1), list(i2), list(i1.strategy), list(i2.strategy))
    c1, c2 = tools.cxESTwoPoint(i1, i2)
    # values and strategy swapped over the same segment: multiset union
    # preserved, and positions where values swapped are exactly the
    # positions where strategies swapped
    for j in range(6):
        took_other = c1[j] == v[1][j] and v[0][j] != v[1][j]
        assert (c1.strategy[j] == (v[3][j] if took_other else v[2][j]))


def test_es_blend_and_lognormal_touch_strategy():
    i1, i2 = _es_pair()
    s_before = list(i1.strategy)
    tools.cxESBlend(i1, i2, alpha=0.3)
    assert i1.strategy != s_before
    (m,) = tools.mutESLogNormal(i1, c=1.0, indpb=1.0)
    assert all(s > 0 for s in m.strategy)


# ------------------------------------------------------------ mutations ----

def test_mut_polynomial_bounded_stays_in_bounds():
    for _ in range(50):
        a = [random.uniform(0, 1) for _ in range(8)]
        (m,) = tools.mutPolynomialBounded(list(a), eta=20.0, low=0.0,
                                          up=1.0, indpb=1.0)
        assert all(0.0 <= x <= 1.0 for x in m)
        assert m != a


def test_bounds_sequence_validation():
    try:
        tools.mutPolynomialBounded([0.5] * 4, 20.0, [0.0] * 2, 1.0, 1.0)
    except IndexError:
        pass
    else:
        raise AssertionError("short bound sequence must raise IndexError")


# ----------------------------------------------------------- selections ----

def _pop_with_fitness(values, lengths=None):
    creator.create("FitSel", base.Fitness, weights=(1.0,))
    creator.create("IndSel", list, fitness=creator.FitSel)
    pop = []
    for i, v in enumerate(values):
        n = lengths[i] if lengths else 3
        ind = creator.IndSel(range(n))
        ind.fitness.values = v if isinstance(v, tuple) else (v,)
        pop.append(ind)
    return pop


def test_sus_is_fitness_proportionate_and_spread():
    pop = _pop_with_fitness([10.0, 1.0, 1.0, 1.0])
    counts = 0
    for _ in range(100):
        chosen = tools.selStochasticUniversalSampling(pop, 4)
        counts += sum(1 for c in chosen if c is pop[0])
    # pop[0] holds 10/13 of the mass → expect ≥ 3 of 4 slots typically
    assert counts > 250


def test_double_tournament_applies_parsimony_pressure():
    random.seed(7)
    # equal fitness, very different sizes → parsimony should favor short
    pop = _pop_with_fitness([1.0] * 20, lengths=[2] * 10 + [20] * 10)
    chosen = tools.selDoubleTournament(pop, 200, fitness_size=2,
                                       parsimony_size=1.8,
                                       fitness_first=True)
    short = sum(1 for c in chosen if len(c) == 2)
    assert short > 120  # 1.8/2 = 90% preference for the shorter


def test_lexicase_exact_on_disjoint_specialists():
    creator.create("FitLex", base.Fitness, weights=(1.0, 1.0))
    creator.create("IndLex", list, fitness=creator.FitLex)
    a = creator.IndLex([0])
    a.fitness.values = (1.0, 0.0)
    b = creator.IndLex([1])
    b.fitness.values = (0.0, 1.0)
    c = creator.IndLex([2])
    c.fitness.values = (0.0, 0.0)
    chosen = tools.selLexicase([a, b, c], 50)
    assert all(x is not c for x in chosen)  # c never best on any case

    eps = tools.selEpsilonLexicase([a, b, c], 50, epsilon=2.0)
    assert any(x is c for x in eps)  # within ε of best on every case

    auto = tools.selAutomaticEpsilonLexicase([a, b, c], 20)
    assert len(auto) == 20


# ------------------------------------------------------------- penalties ----

def test_delta_penalty_formula():
    creator.create("FitPen", base.Fitness, weights=(-1.0, 1.0))
    creator.create("IndPen", list, fitness=creator.FitPen)

    def feasible(ind):
        return sum(ind) < 2

    def distance(ind):
        return sum(ind) - 2.0

    wrapped = tools.DeltaPenalty(feasible, 100.0, distance)(
        lambda ind: (sum(ind), len(ind)))
    ok = creator.IndPen([0.5, 1.0])
    assert wrapped(ok) == (1.5, 2)
    bad = creator.IndPen([3.0, 1.0])
    # Δ_i - w_i·d: (100 - (-1)·2, 100 - (+1)·2)
    assert wrapped(bad) == (102.0, 98.0)
    assert tools.DeltaPenality is tools.DeltaPenalty


def test_closest_valid_penalty_formula():
    creator.create("FitPen2", base.Fitness, weights=(-1.0,))
    creator.create("IndPen2", list, fitness=creator.FitPen2)

    def feasible(ind):
        return max(ind) <= 1.0

    def project(ind):
        return type(ind)(min(x, 1.0) for x in ind)

    def distance(valid, ind):
        return sum((a - b) ** 2 for a, b in zip(valid, ind))

    wrapped = tools.ClosestValidPenalty(feasible, project, 2.0, distance)(
        lambda ind: (sum(ind),))
    bad = creator.IndPen2([3.0, 0.5])
    # f(valid)=1.5, d=4, w=-1 → 1.5 - (-1)·2·4 = 9.5
    assert wrapped(bad) == (9.5,)
    assert tools.ClosestValidPenality is tools.ClosestValidPenalty


# ------------------------------------------------------------------- gp ----

def _pset():
    pset = cgp.PrimitiveSet("MAIN", 1)
    pset.addPrimitive(operator.add, 2)
    pset.addPrimitive(operator.sub, 2)
    pset.addPrimitive(operator.mul, 2)
    pset.addPrimitive(
        lambda x: 1.0 / (1.0 + math.exp(-max(-60.0, min(60.0, x)))), 1,
        name="lf")
    pset.addTerminal(3.0)
    return pset


def test_cx_one_point_leaf_biased_valid_trees():
    pset = _pset()
    for _ in range(20):
        t1 = cgp.genGrow(pset, 2, 4)
        t2 = cgp.genGrow(pset, 2, 4)
        c1, c2 = cgp.cxOnePointLeafBiased(t1, t2, termpb=0.1)
        for c in (c1, c2):
            f = cgp.compile(c, pset)
            assert isinstance(f(0.5), float)


def test_semantic_crossover_is_convex_combination():
    pset = _pset()
    random.seed(21)
    i1 = cgp.genGrow(pset, 2, 3)
    i2 = cgp.genGrow(pset, 2, 3)
    v1 = cgp.compile(cgp.PrimitiveTree(i1), pset)(0.3)
    v2 = cgp.compile(cgp.PrimitiveTree(i2), pset)(0.3)
    c1, c2 = cgp.cxSemantic(cgp.PrimitiveTree(list(i1)),
                            cgp.PrimitiveTree(list(i2)), pset=pset, max=2)
    o1 = cgp.compile(c1, pset)(0.3)
    o2 = cgp.compile(c2, pset)(0.3)
    lo, hi = min(v1, v2), max(v1, v2)
    assert lo - 1e-9 <= o1 <= hi + 1e-9
    assert lo - 1e-9 <= o2 <= hi + 1e-9
    # s·v1+(1-s)·v2 and s·v2+(1-s)·v1 sum to v1+v2
    assert math.isclose(o1 + o2, v1 + v2, rel_tol=1e-9, abs_tol=1e-9)


def test_semantic_mutation_bounded_by_step():
    pset = _pset()
    i1 = cgp.genGrow(pset, 2, 3)
    v1 = cgp.compile(cgp.PrimitiveTree(i1), pset)(0.7)
    (m,) = cgp.mutSemantic(cgp.PrimitiveTree(list(i1)), pset=pset,
                           ms=0.25, max=2)
    mv = cgp.compile(m, pset)(0.7)
    assert abs(mv - v1) <= 0.25 + 1e-9  # |ms·(lf-lf)| ≤ ms since lf∈(0,1)


def test_graph_export_shape():
    pset = _pset()
    t = cgp.genFull(pset, 2, 2)
    nodes, edges, labels = cgp.graph(t)
    assert list(nodes) == list(range(len(t)))
    assert len(edges) == len(t) - 1  # a tree
    assert set(labels) == set(nodes)


def test_harm_runs_and_controls_size():
    pset = cgp.PrimitiveSet("MAIN", 1)
    pset.addPrimitive(operator.add, 2)
    pset.addPrimitive(operator.sub, 2)
    pset.addPrimitive(operator.mul, 2)
    pset.addEphemeralConstant("rndH", lambda: float(random.randint(-1, 1)))

    creator.create("FitHarm", base.Fitness, weights=(-1.0,))
    creator.create("TreeHarm", cgp.PrimitiveTree, fitness=creator.FitHarm)
    tb = base.Toolbox()
    tb.register("expr", cgp.genHalfAndHalf, pset=pset, min_=1, max_=2)
    tb.register("individual", tools.initIterate, creator.TreeHarm, tb.expr)
    tb.register("population", tools.initRepeat, list, tb.individual)
    pts = [x / 5.0 for x in range(-5, 5)]

    def evaluate(ind):
        f = cgp.compile(ind, pset)
        return (sum((f(x) - (x * x + x)) ** 2 for x in pts) / len(pts),)

    tb.register("evaluate", evaluate)
    tb.register("select", tools.selTournament, tournsize=3)
    tb.register("mate", cgp.cxOnePoint)
    tb.register("expr_mut", cgp.genFull, min_=0, max_=2)
    tb.register("mutate", cgp.mutUniform, expr=tb.expr_mut, pset=pset)

    random.seed(4)
    pop = tb.population(n=30)
    hof = tools.HallOfFame(1)
    pop, log = cgp.harm(pop, tb, 0.5, 0.2, ngen=4, alpha=0.05, beta=10,
                        gamma=0.25, rho=0.9, nbrindsmodel=150,
                        halloffame=hof, verbose=False)
    assert len(pop) == 30
    assert log[-1]["gen"] == 4
    assert hof[0].fitness.valid
    # mincutoff=20 floor means sizes stay in check on a tiny problem
    assert max(len(ind) for ind in pop) < 200


def test_nd_rank_log_matches_matrix_peel():
    """The divide-and-conquer nd-sort (compat.ndsort_log — the
    reference's sortLogNondominated algorithm class, emo.py:234-441)
    must produce exactly the matrix-peel ranks on adversarial inputs:
    ties on every objective, exact duplicates, 1..5 objectives."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from deap_tpu.compat.ndsort_log import nd_rank_log
    from deap_tpu.mo import emo

    rng = np.random.default_rng(0)
    for trial in range(25):
        n = int(rng.integers(1, 90))
        m = int(rng.integers(1, 6))
        w = rng.normal(size=(n, m))
        if trial % 3 == 0:
            w = np.round(w * 2) / 2          # heavy coordinate ties
        if trial % 4 == 0 and n > 4:
            w[rng.integers(0, n, 5)] = w[0]  # exact duplicates
        ours = nd_rank_log(w)
        ref = np.asarray(emo.nd_rank(jnp.asarray(w), impl="matrix"))
        assert (ours == ref).all(), (trial, n, m)


def test_sort_log_nondominated_uses_dc_and_matches_standard():
    creator.create("FitLogDC", base.Fitness, weights=(-1.0, -1.0, -1.0))
    creator.create("IndLogDC", list, fitness=creator.FitLogDC)
    random.seed(3)
    pop = []
    for _ in range(60):
        ind = creator.IndLogDC([random.random() for _ in range(3)])
        ind.fitness.values = tuple(ind)
        pop.append(ind)
    log_fronts = tools.sortLogNondominated(pop, 60)
    std_fronts = tools.sortNondominated(pop, 60)
    assert [sorted(map(id, f)) for f in log_fronts] == \
        [sorted(map(id, f)) for f in std_fronts]


def test_nsga3_with_memory_and_log_sort():
    creator.create("FitMO3", base.Fitness, weights=(-1.0, -1.0))
    creator.create("IndMO3", list, fitness=creator.FitMO3)
    random.seed(8)
    pop = []
    for _ in range(24):
        ind = creator.IndMO3([random.random(), random.random()])
        ind.fitness.values = (ind[0], ind[1])
        pop.append(ind)
    select = tools.selNSGA3WithMemory(tools.uniformReferencePoints(2, 6))
    assert len(select(pop, 12)) == 12
    assert len(select(pop, 12)) == 12  # second call uses the memory
    fronts = tools.sortLogNondominated(pop, 12)
    assert sum(len(f) for f in fronts) >= 12
    # reference shape quirk: log variant returns the BARE front with
    # first_front_only (emo.py:275-276), the standard variant a list
    first = tools.sortLogNondominated(pop, 12, first_front_only=True)
    assert first == fronts[0]
    std_first = tools.sortNondominated(pop, 12, first_front_only=True)
    assert std_first == [fronts[0]]
    idx = tools.hypervolume(fronts[0])
    assert 0 <= idx < len(fronts[0])
