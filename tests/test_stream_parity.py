"""Exact-stream parity: compat operators vs the 2to3-converted reference.

PARITY.md claims the compat list operators were validated call-for-call
against the reference on identical stdlib-``random`` streams. This is
that harness, committed so the claim stays reproducible: it converts
``/root/reference/deap`` with 2to3 into a scratch directory (cached),
imports both sides, replays each operator on identical inputs and seeds,
and asserts byte-identical outputs.

Skipped automatically when the reference tree or the ``2to3`` tool is
absent (e.g. on a user machine) — everything else in the suite is
self-contained; this module exists purely to keep the parity claim
honest where the reference is available.
"""

import pathlib
import random
import shutil
import subprocess
import sys

import pytest

REF = pathlib.Path("/root/reference/deap")
SCRATCH = pathlib.Path("/tmp/refdeap_parity")
TOOL = shutil.which("2to3")

pytestmark = [
    pytest.mark.slow,  # copies + 2to3-converts the reference tree
    pytest.mark.skipif(not REF.exists() or TOOL is None,
                       reason="reference tree or 2to3 not available"),
]


def _ref_fingerprint() -> str:
    """Cheap change detector for the reference tree: per-file sizes +
    mtimes. Invalidates the 2to3 scratch when the reference updates."""
    parts = []
    for p in sorted(REF.rglob("*.py")):
        st = p.stat()
        parts.append(f"{p.relative_to(REF)}:{st.st_size}:{st.st_mtime_ns}")
    import hashlib

    return hashlib.sha256("\n".join(parts).encode()).hexdigest()


#: content-hash allowlist: importing the reference executes its
#: module-level code inside the test process, so only a vetted tree may
#: run. Regenerate with ``python -c "import test_stream_parity as t;
#: print(t._ref_content_hash())"`` after reviewing the new tree.
ALLOWLIST = pathlib.Path(__file__).parent / "ref_fingerprint.txt"


def _ref_content_hash() -> str:
    """Order-stable sha256 over the reference tree's .py contents
    (mtime-free, unlike :func:`_ref_fingerprint`, so it survives
    re-checkouts)."""
    import hashlib

    h = hashlib.sha256()
    for p in sorted(REF.rglob("*.py")):
        h.update(str(p.relative_to(REF)).encode())
        h.update(b"\0")
        h.update(p.read_bytes())
    return h.hexdigest()


def require_vetted_reference():
    """Skip (refuse to execute) unless the reference tree's content
    hash matches the committed allowlist."""
    if not ALLOWLIST.exists():
        pytest.skip("tests/ref_fingerprint.txt missing — vet the "
                    "reference tree, then commit its content hash")
    if _ref_content_hash() != ALLOWLIST.read_text().strip():
        pytest.skip("reference tree content changed since it was "
                    "vetted; refusing to import/execute it. Review "
                    "the tree and update tests/ref_fingerprint.txt")


@pytest.fixture(scope="module")
def ref():
    """Import the 2to3-converted reference's base/tools modules."""
    require_vetted_reference()
    marker = SCRATCH / ".converted"
    fingerprint = _ref_fingerprint()
    if not (marker.exists() and marker.read_text() == fingerprint):
        if SCRATCH.exists():
            shutil.rmtree(SCRATCH)
        SCRATCH.mkdir(parents=True)
        shutil.copytree(REF, SCRATCH / "deap")
        subprocess.run(
            [TOOL, "-w", "-n", "--no-diffs", str(SCRATCH / "deap")],
            check=True, capture_output=True, timeout=300)
        marker.write_text(fingerprint)
    sys.path.insert(0, str(SCRATCH))
    try:
        import deap.base as ref_base
        import deap.tools as ref_tools

        yield ref_base, ref_tools
    finally:
        sys.path.remove(str(SCRATCH))


@pytest.fixture(scope="module")
def ours():
    from deap_tpu.compat import base, tools

    return base, tools


SEEDS = (11, 4242, 999331)


def _replay(seed, fn, make_args):
    """Run fn on freshly built args under a fixed random stream."""
    random.seed(seed)
    args = make_args()
    out = fn(*args)
    return args, out, random.getstate()


def _pair(seed, ref_fn, our_fn, make_args):
    """Replay both sides; assert identical outputs AND identical stream
    consumption (same random.getstate afterward)."""
    ref_args, ref_out, ref_state = _replay(seed, ref_fn, make_args)
    our_args, our_out, our_state = _replay(seed, our_fn, make_args)
    assert our_args == ref_args, "in-place results differ"
    assert our_state == ref_state, "random-stream consumption differs"
    return ref_out, our_out


# ---------------------------------------------------------- variation ----

def _perm_pair():
    # two random permutations, built AFTER seeding so both sides agree
    return ([*random.sample(range(8), 8)], [*random.sample(range(8), 8)])


def _real_pair():
    return ([random.uniform(-5, 5) for _ in range(6)],
            [random.uniform(-5, 5) for _ in range(6)])


@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("name,make", [
    ("cxPartialyMatched", _perm_pair),
    ("cxUniformPartialyMatched", None),  # needs indpb
    ("cxOrdered", _perm_pair),
    ("cxTwoPoint", _perm_pair),
    ("cxOnePoint", _perm_pair),
    ("cxMessyOnePoint", _perm_pair),
])
def test_crossover_streams(ref, ours, name, make, seed):
    ref_base, ref_tools = ref
    _, tools = ours
    if name == "cxUniformPartialyMatched":
        fn_r = lambda a, b: ref_tools.cxUniformPartialyMatched(a, b, 0.3)
        fn_o = lambda a, b: tools.cxUniformPartialyMatched(a, b, 0.3)
        _pair(seed, fn_r, fn_o, _perm_pair)
    else:
        _pair(seed, getattr(ref_tools, name), getattr(tools, name), make)


@pytest.mark.parametrize("seed", SEEDS)
def test_sbx_and_bounded_streams(ref, ours, seed):
    _, ref_tools = ref
    _, tools = ours
    _pair(seed,
          lambda a, b: ref_tools.cxSimulatedBinary(a, b, 15.0),
          lambda a, b: tools.cxSimulatedBinary(a, b, 15.0),
          _real_pair)
    _pair(seed,
          lambda a, b: ref_tools.cxSimulatedBinaryBounded(
              a, b, 20.0, -5.0, 5.0),
          lambda a, b: tools.cxSimulatedBinaryBounded(
              a, b, 20.0, -5.0, 5.0),
          _real_pair)


@pytest.mark.parametrize("seed", SEEDS)
def test_mutation_streams(ref, ours, seed):
    _, ref_tools = ref
    _, tools = ours
    mk = lambda: ([random.uniform(-5, 5) for _ in range(6)],)
    _pair(seed,
          lambda i: ref_tools.mutPolynomialBounded(i, 20.0, -5.0, 5.0, 0.4),
          lambda i: tools.mutPolynomialBounded(i, 20.0, -5.0, 5.0, 0.4),
          mk)
    _pair(seed,
          lambda i: ref_tools.mutGaussian(i, 0.0, 1.0, 0.4),
          lambda i: tools.mutGaussian(i, 0.0, 1.0, 0.4),
          mk)
    mk_bits = lambda: ([random.randint(0, 1) for _ in range(12)],)
    _pair(seed,
          lambda i: ref_tools.mutFlipBit(i, 0.3),
          lambda i: tools.mutFlipBit(i, 0.3),
          mk_bits)
    _pair(seed,
          lambda i: ref_tools.mutShuffleIndexes(i, 0.3),
          lambda i: tools.mutShuffleIndexes(i, 0.3),
          mk_bits)


class _ESList(list):
    """Minimal ES individual: a list with a .strategy vector."""

    def __eq__(self, other):  # compare values AND strategy
        return (list.__eq__(self, other)
                and getattr(self, "strategy", None)
                == getattr(other, "strategy", None))

    __hash__ = None


def _es_pair():
    a = _ESList(random.uniform(-5, 5) for _ in range(6))
    a.strategy = [random.uniform(0.1, 1.0) for _ in range(6)]
    b = _ESList(random.uniform(-5, 5) for _ in range(6))
    b.strategy = [random.uniform(0.1, 1.0) for _ in range(6)]
    return (a, b)


@pytest.mark.parametrize("seed", SEEDS)
def test_es_operator_streams(ref, ours, seed):
    _, ref_tools = ref
    _, tools = ours
    _pair(seed,
          lambda a, b: ref_tools.cxESBlend(a, b, 0.5),
          lambda a, b: tools.cxESBlend(a, b, 0.5),
          _es_pair)
    _pair(seed, ref_tools.cxESTwoPoint, tools.cxESTwoPoint, _es_pair)
    mk = lambda: (_es_pair()[0],)
    _pair(seed,
          lambda i: ref_tools.mutESLogNormal(i, 1.0, 0.4),
          lambda i: tools.mutESLogNormal(i, 1.0, 0.4),
          mk)


# ---------------------------------------------------------- selection ----


def _make_scored(base_mod, n=16, nobj=1, varlen=False):
    """n list individuals with fitness + an .idx marker."""

    class F(base_mod.Fitness):
        weights = (1.0,) * nobj

    out = []
    for i in range(n):
        length = random.randint(3, 9) if varlen else 5
        ind = [random.random() for _ in range(length)]
        ind = type("I", (list,), {})(ind)
        ind.fitness = F()
        ind.fitness.values = tuple(random.uniform(0, 10)
                                   for _ in range(nobj))
        ind.idx = i
        out.append(ind)
    return out


def _sel_streams(ref, ours, ref_call, our_call, nobj=1, varlen=False):
    ref_base, _ = ref
    our_base, _ = ours
    for seed in SEEDS:
        random.seed(seed)
        pop_r = _make_scored(ref_base, nobj=nobj, varlen=varlen)
        mid = random.getstate()
        picked_r = [ind.idx for ind in ref_call(pop_r)]
        state_r = random.getstate()

        random.seed(seed)
        pop_o = _make_scored(our_base, nobj=nobj, varlen=varlen)
        assert random.getstate() == mid  # identical inputs
        picked_o = [ind.idx for ind in our_call(pop_o)]
        state_o = random.getstate()

        assert picked_o == picked_r
        assert state_o == state_r


def test_sus_stream(ref, ours):
    _, ref_tools = ref
    _, tools = ours
    _sel_streams(
        ref, ours,
        lambda p: ref_tools.selStochasticUniversalSampling(p, 6),
        lambda p: tools.selStochasticUniversalSampling(p, 6))


def test_double_tournament_stream(ref, ours):
    _, ref_tools = ref
    _, tools = ours
    for fitness_first in (True, False):
        _sel_streams(
            ref, ours,
            lambda p: ref_tools.selDoubleTournament(
                p, 8, 3, 1.4, fitness_first),
            lambda p: tools.selDoubleTournament(
                p, 8, 3, 1.4, fitness_first),
            varlen=True)


def test_lexicase_family_streams(ref, ours):
    _, ref_tools = ref
    _, tools = ours
    _sel_streams(ref, ours,
                 lambda p: ref_tools.selLexicase(p, 5),
                 lambda p: tools.selLexicase(p, 5), nobj=4)
    _sel_streams(ref, ours,
                 lambda p: ref_tools.selEpsilonLexicase(p, 5, 0.5),
                 lambda p: tools.selEpsilonLexicase(p, 5, 0.5), nobj=4)
    _sel_streams(ref, ours,
                 lambda p: ref_tools.selAutomaticEpsilonLexicase(p, 5),
                 lambda p: tools.selAutomaticEpsilonLexicase(p, 5), nobj=4)


def test_tournament_and_roulette_streams(ref, ours):
    _, ref_tools = ref
    _, tools = ours
    _sel_streams(ref, ours,
                 lambda p: ref_tools.selTournament(p, 8, 3),
                 lambda p: tools.selTournament(p, 8, 3))
    _sel_streams(ref, ours,
                 lambda p: ref_tools.selRoulette(p, 6),
                 lambda p: tools.selRoulette(p, 6))


# ------------------------------------------------- MovingPeaks errors ----


def test_movingpeaks_offline_error_matches_reference(ref):
    """On a frozen landscape (period=0) the batch-granularity
    divergence (PARITY.md) vanishes, so our running current/offline
    error bookkeeping must match the reference's per-evaluation
    bookkeeping exactly — same peaks, same evaluation sequence."""
    del ref  # fixture only ensures the converted tree exists on path
    import numpy as np
    from deap.benchmarks import movingpeaks as rmp

    import jax
    import jax.numpy as jnp
    from deap_tpu.benchmarks.movingpeaks import (
        MovingPeaksConfig,
        cone,
        mp_evaluate,
        mp_init,
        offline_error,
    )

    dim, npeaks = 2, 4
    cfg = MovingPeaksConfig(dim=dim, npeaks=npeaks, pfunc=cone,
                            uniform_height=0.0, uniform_width=0.0,
                            min_width=1.0, max_width=12.0, period=0)
    state = mp_init(jax.random.key(5), cfg)

    # reference instance with IDENTICAL peaks, changes disabled
    rng = random.Random(99)
    mp = rmp.MovingPeaks(dim=dim, random=rng, npeaks=npeaks,
                         pfunc=rmp.cone, period=0,
                         min_height=30.0, max_height=70.0,
                         uniform_height=0, min_width=1.0, max_width=12.0,
                         uniform_width=0)
    mp.peaks_position = [np.asarray(p) for p in np.asarray(state.position)]
    mp.peaks_height = [float(h) for h in np.asarray(state.height)]
    mp.peaks_width = [float(w) for w in np.asarray(state.width)]
    mp._optimum = None

    pts = np.asarray(jax.random.uniform(
        jax.random.key(6), (3, 7, dim), minval=0.0, maxval=100.0))

    ref_vals = []
    for batch in pts:
        for x in batch:
            ref_vals.append(mp(list(x))[0])

    our_vals = []
    for batch in pts:
        state, v = mp_evaluate(cfg, state, jnp.asarray(batch))
        our_vals.extend(np.asarray(v)[:, 0].tolist())

    np.testing.assert_allclose(our_vals, ref_vals, rtol=1e-5)
    assert mp.nevals == int(state.nevals)
    np.testing.assert_allclose(float(offline_error(state)),
                               mp.offlineError(), rtol=1e-5)
    np.testing.assert_allclose(float(state.current_error),
                               mp.currentError(), rtol=1e-5)
