"""Static drift-check for the probe catalogue — the probes counterpart
of test_span_coverage.py.

Three ways the probe library rots silently, made loud:

1. A ``*Probe`` class added to ``telemetry/probes.py`` without
   ``@register_probe`` — invisible to tooling that iterates the
   registry (the docs gate below, future report features).
2. A registered probe without an honest ``metric_names`` declaration —
   the report tool and the docs table key on it.
3. A probe or metric missing from the catalogue table in
   ``docs/advanced/telemetry.md`` — the documented probe set and the
   shipped probe set must be the same set.
"""

import ast
import os
import re

import deap_tpu.telemetry.probes as probes_mod
from deap_tpu.telemetry.probes import PROBE_REGISTRY, Probe

PROBES_PATH = os.path.abspath(probes_mod.__file__)
DOC_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "advanced", "telemetry.md")


def _probe_classes_in_source():
    """Every class whose name ends in 'Probe' defined in probes.py
    (AST — not the registry, which is exactly what might have rotted)."""
    with open(PROBES_PATH) as fh:
        tree = ast.parse(fh.read(), filename=PROBES_PATH)
    return {node.name for node in ast.walk(tree)
            if isinstance(node, ast.ClassDef)
            and node.name.endswith("Probe")
            and node.name != "Probe"}


def test_every_probe_class_is_registered():
    source_probes = _probe_classes_in_source()
    assert source_probes, "AST scan found no probe classes — detection drifted"
    missing = source_probes - set(PROBE_REGISTRY)
    assert not missing, (
        f"probe classes defined in probes.py but not @register_probe'd: "
        f"{sorted(missing)} — the docs gate and registry tooling cannot "
        "see them")


def test_every_registered_probe_declares_metric_names():
    assert len(PROBE_REGISTRY) >= 5
    for name, cls in PROBE_REGISTRY.items():
        assert issubclass(cls, Probe), name
        names = getattr(cls, "metric_names", None)
        assert isinstance(names, tuple) and names, (
            f"{name}.metric_names must be a non-empty tuple — the "
            "journal report and docs table key on it")
        assert all(isinstance(n, str) and n for n in names), name
        assert len(set(names)) == len(names), f"{name}: duplicate metrics"


def test_probe_table_in_docs_covers_registry():
    """Every registered probe appears as a `ClassName` row in the
    telemetry doc's probe catalogue, listing every one of its
    metric_names — doc drift is a test failure, not a stale table."""
    with open(DOC_PATH) as fh:
        doc = fh.read()
    table_rows = {m.group(1): m.group(0) for m in re.finditer(
        r"^\| `(\w+Probe)` \|.*$", doc, flags=re.M)}
    for name, cls in PROBE_REGISTRY.items():
        assert name in table_rows, (
            f"{name} missing from the probe catalogue table in "
            f"{DOC_PATH} (docs/advanced/telemetry.md)")
        row = table_rows[name]
        for metric in cls.metric_names:
            assert f"`{metric}`" in row, (
                f"{name}: metric `{metric}` missing from its probe "
                f"catalogue row in docs/advanced/telemetry.md")
    stale = set(table_rows) - set(PROBE_REGISTRY)
    assert not stale, (
        f"docs/advanced/telemetry.md documents unregistered probes: "
        f"{sorted(stale)}")


def test_alarm_kinds_documented():
    """Every HealthMonitor alarm kind appears in the alarm-semantics
    table of docs/advanced/telemetry.md."""
    from deap_tpu.telemetry.probes import HealthMonitor

    with open(DOC_PATH) as fh:
        doc = fh.read()
    for kind in HealthMonitor.ALARM_KINDS:
        assert f"`{kind}`" in doc, (
            f"alarm kind {kind!r} undocumented in "
            "docs/advanced/telemetry.md")
