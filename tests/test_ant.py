"""Artificial-ant tests: JAX rollout vs native C++ simulator agreement,
the known Koza solution reaching 89 food in 543 moves (ant.py:26-46),
and an evolution smoke run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import gp
from deap_tpu.gp import ant as ant_mod
from deap_tpu.gp.string import from_string

MAX_LEN = 64


@pytest.fixture(scope="module")
def setup():
    pset = ant_mod.ant_pset()
    trail, start = ant_mod.parse_trail()
    return pset, trail, start


# Koza's hand solution (ant.py:30-33): eats all 89 pieces in 543 moves
KOZA_SOLUTION = (
    "if_food_ahead(move_forward, prog3(turn_left, "
    "prog2(if_food_ahead(move_forward, turn_right), "
    "prog2(turn_right, prog2(turn_left, turn_right))), "
    "prog2(if_food_ahead(move_forward, turn_left), move_forward)))"
)


def test_trail_has_89_food(setup):
    _, trail, start = setup
    assert trail.sum() == 89
    assert trail.shape == (32, 32)
    assert start == (0, 0)
    assert not trail[start]


def test_koza_solution_eats_89(setup):
    pset, trail, start = setup
    genome = from_string(KOZA_SOLUTION, pset, MAX_LEN)
    evaluate = ant_mod.make_ant_evaluator(pset, MAX_LEN, trail, start,
                                          max_moves=543)
    assert float(evaluate(genome)) == 89.0


def test_koza_solution_eats_89_native(setup):
    pset, trail, start = setup
    from deap_tpu.native.ant_binding import ant_eval

    genome = from_string(KOZA_SOLUTION, pset, MAX_LEN)
    out = ant_eval(np.asarray(genome["nodes"])[None],
                   np.asarray([int(genome["length"])]),
                   trail, start, max_moves=543)
    assert out[0] == 89


def test_jax_and_native_agree_on_random_trees(setup):
    pset, trail, start = setup
    from deap_tpu.native.ant_binding import ant_eval

    gen = gp.make_generator(pset, MAX_LEN, 1, 5)
    genomes = jax.vmap(gen)(jax.random.split(jax.random.key(0), 48))
    evaluate = ant_mod.make_ant_evaluator(pset, MAX_LEN, trail, start,
                                          max_moves=200)
    jax_out = jax.vmap(evaluate)(genomes)
    native_out = ant_eval(np.asarray(genomes["nodes"]),
                          np.asarray(genomes["length"]),
                          trail, start, max_moves=200)
    np.testing.assert_array_equal(np.asarray(jax_out, np.int32),
                                  native_out)


def test_ant_evolution_improves(setup):
    pset, trail, start = setup
    gen = gp.make_generator(pset, MAX_LEN, 1, 4)
    evaluate = ant_mod.make_ant_evaluator(pset, MAX_LEN, trail, start,
                                          max_moves=300)
    cx = gp.make_cx_one_point(pset)
    mut = gp.make_mut_uniform(pset, gp.make_generator(pset, 16, 0, 2,
                                                      "grow"))
    POP = 64
    genomes = jax.vmap(gen)(jax.random.split(jax.random.key(1), POP))
    fits = jax.vmap(evaluate)(genomes)
    f0 = float(fits.max())

    @jax.jit
    def step(key, genomes, fits):
        k_sel, k_cx, k_mut = jax.random.split(key, 3)
        idx = jax.random.randint(k_sel, (POP, 3), 0, POP)
        winner = idx[jnp.arange(POP), jnp.argmax(fits[idx], axis=1)]
        parents = jax.tree_util.tree_map(lambda a: a[winner], genomes)
        mates = jax.tree_util.tree_map(lambda a: jnp.roll(a, 1, 0), parents)
        c1, _ = jax.vmap(cx)(jax.random.split(k_cx, POP), parents, mates)
        c1 = jax.vmap(mut)(jax.random.split(k_mut, POP), c1)
        return c1, jax.vmap(evaluate)(c1)

    for g in range(10):
        genomes, fits = step(jax.random.key(50 + g), genomes, fits)
    assert float(fits.max()) >= max(f0, 10.0)
