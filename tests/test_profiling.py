"""Profiler hooks (support.profiling): annotation transparency, sync
barrier, and host-timed generation loop (SURVEY.md §5.1 parity)."""

import jax
import jax.numpy as jnp

from deap_tpu.support.profiling import annotate, sync, timed_generations


def test_annotate_is_transparent():
    @annotate("variation")
    def f(x):
        return x * 2.0

    assert float(f(jnp.float32(3.0))) == 6.0
    assert float(jax.jit(f)(jnp.float32(3.0))) == 6.0


def test_sync_returns_tree():
    tree = {"a": jnp.arange(4), "b": (jnp.ones(2),)}
    out = sync(tree)
    assert out is tree


def test_timed_generations_progresses_state():
    def step(x):
        return x + 1

    states = list(timed_generations(step, jnp.int32(0), ngen=3))
    assert [g for g, _, _ in states] == [0, 1, 2]
    assert int(states[-1][1]) == 3
    assert all(dt >= 0 for _, _, dt in states)
