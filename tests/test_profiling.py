"""Profiler hooks (support.profiling): annotation transparency, sync
barrier, and host-timed generation loop (SURVEY.md §5.1 parity)."""

import jax
import jax.numpy as jnp

from deap_tpu.support.profiling import (annotate, span, sync,
                                        timed_generations, timed_phases)


def test_annotate_is_transparent():
    @annotate("variation")
    def f(x):
        return x * 2.0

    assert float(f(jnp.float32(3.0))) == 6.0
    assert float(jax.jit(f)(jnp.float32(3.0))) == 6.0


def test_span_is_transparent_inside_jit():
    def f(x):
        with span("collective:psum"):
            return x + 1.0

    assert float(f(jnp.float32(1.0))) == 2.0
    assert float(jax.jit(f)(jnp.float32(1.0))) == 2.0


def test_timed_phases_times_every_label():
    out = timed_phases({
        "a": lambda: jnp.arange(8).sum(),
        "b": lambda: jnp.ones(4) * 2.0,
    }, reps=2)
    assert set(out) == {"a", "b"}
    assert all(t >= 0.0 for t in out.values())


def test_sharded_evaluator_spans_preserve_semantics():
    # the per-collective annotation in genome_shard must never change
    # results: sharded == unsharded on an 8-way genome mesh
    import numpy as np

    from deap_tpu.parallel.genome_shard import (genome_mesh,
                                                make_sharded_evaluator,
                                                shard_genomes)

    mesh = genome_mesh(n_pop_shards=1, n_genome_shards=8)
    g = jax.random.bernoulli(jax.random.key(0), 0.5, (16, 64))
    ev = make_sharded_evaluator(
        lambda s: s.sum(-1).astype(jnp.float32), mesh, combine="sum")
    got = ev(shard_genomes(g, mesh))
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(g.sum(-1), dtype=np.float32))


def test_sync_returns_tree():
    tree = {"a": jnp.arange(4), "b": (jnp.ones(2),)}
    out = sync(tree)
    assert out is tree


def test_timed_generations_progresses_state():
    def step(x):
        return x + 1

    states = list(timed_generations(step, jnp.int32(0), ngen=3))
    assert [g for g, _, _ in states] == [0, 1, 2]
    assert int(states[-1][1]) == 3
    assert all(dt >= 0 for _, _, dt in states)
