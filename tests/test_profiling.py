"""Profiler hooks (support.profiling): annotation transparency, sync
barrier, span wall-time recording, and host-timed generation loop
(SURVEY.md §5.1 parity)."""

import os
import time

import jax
import jax.numpy as jnp

from deap_tpu.support.profiling import (SpanRecorder, annotate,
                                        get_span_recorder, span, sync,
                                        timed_generations, timed_phases)


def test_annotate_is_transparent():
    @annotate("variation")
    def f(x):
        return x * 2.0

    assert float(f(jnp.float32(3.0))) == 6.0
    assert float(jax.jit(f)(jnp.float32(3.0))) == 6.0


def test_span_is_transparent_inside_jit():
    def f(x):
        with span("collective:psum"):
            return x + 1.0

    assert float(f(jnp.float32(1.0))) == 2.0
    assert float(jax.jit(f)(jnp.float32(1.0))) == 2.0


def test_timed_phases_times_every_label():
    out = timed_phases({
        "a": lambda: jnp.arange(8).sum(),
        "b": lambda: jnp.ones(4) * 2.0,
    }, reps=2)
    assert set(out) == {"a", "b"}
    assert all(t >= 0.0 for t in out.values())


def test_sharded_evaluator_spans_preserve_semantics():
    # the per-collective annotation in genome_shard must never change
    # results: sharded == unsharded on an 8-way genome mesh
    import numpy as np

    from deap_tpu.parallel.genome_shard import (genome_mesh,
                                                make_sharded_evaluator,
                                                shard_genomes)

    mesh = genome_mesh(n_pop_shards=1, n_genome_shards=8)
    g = jax.random.bernoulli(jax.random.key(0), 0.5, (16, 64))
    ev = make_sharded_evaluator(
        lambda s: s.sum(-1).astype(jnp.float32), mesh, combine="sum")
    got = ev(shard_genomes(g, mesh))
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(g.sum(-1), dtype=np.float32))


def test_sync_returns_tree():
    tree = {"a": jnp.arange(4), "b": (jnp.ones(2),)}
    out = sync(tree)
    assert out is tree


def test_sync_handles_empty_and_awkward_trees():
    # empty tree, zero-size leading leaf, and non-array leaves must not
    # crash the barrier (they used to: leaves[0] was raveled blindly)
    assert sync({}) == {}
    t = {"a": jnp.zeros((0, 3)), "b": jnp.arange(2)}
    assert sync(t) is t
    t2 = {"x": 3.5, "y": [1, 2], "z": None}
    assert sync(t2) is t2
    assert sync({"only_empty": jnp.zeros((0,))}) is not None


def test_sync_handles_committed_and_sharded_arrays():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deap_tpu.parallel.mesh import population_mesh

    committed = jax.device_put(jnp.arange(8), jax.devices("cpu")[1])
    assert sync(committed) is committed
    mesh = population_mesh(8, ("pop",))
    sharded = jax.device_put(jnp.arange(64.0),
                             NamedSharding(mesh, P("pop")))
    assert sync({"s": sharded})["s"] is sharded


def test_span_recorder_aggregates_and_uninstalls():
    with SpanRecorder() as rec:
        for _ in range(5):
            with span("fast"):
                pass
        with span("slow"):
            time.sleep(0.02)
    agg = rec.aggregates()
    assert agg["fast"]["count"] == 5
    assert agg["slow"]["count"] == 1
    assert agg["slow"]["total_s"] >= 0.015
    assert set(agg["fast"]) >= {"count", "total_s", "mean_s", "p50_s",
                                "p99_s", "max_s"}
    assert agg["fast"]["p50_s"] <= agg["fast"]["p99_s"] <= agg["fast"]["max_s"]
    # leaving the context uninstalls: later spans are not recorded
    assert get_span_recorder() is None
    with span("after"):
        pass
    assert "after" not in rec.aggregates()


def test_span_recorder_records_inside_jit_trace():
    # spans in compiled code fire once per trace — the recorder must
    # capture that (trace-time attribution), and re-running the cached
    # executable must not double-count
    def f(x):
        with span("jit/body"):
            return x * 2.0

    with SpanRecorder() as rec:
        jf = jax.jit(f)
        jf(jnp.float32(1.0))
        jf(jnp.float32(2.0))  # cache hit: no new trace, no new sample
    assert rec.aggregates()["jit/body"]["count"] == 1


def test_span_recorder_semantics_transparent():
    with SpanRecorder():
        def f(x):
            with span("s"):
                return x + 1.0
        assert float(jax.jit(f)(jnp.float32(1.0))) == 2.0


def test_timed_phases_excludes_warmup_and_takes_min_of_reps():
    calls = {"n": 0}

    def thunk():
        calls["n"] += 1
        if calls["n"] == 1:
            time.sleep(0.2)   # the "compile" call: must not be timed
        elif calls["n"] == 2:
            time.sleep(0.05)  # slow rep: min-of-reps must discard it
        return jnp.float32(1.0)

    out = timed_phases({"p": thunk}, reps=2)
    assert calls["n"] == 3  # 1 warmup + 2 timed reps
    assert out["p"] < 0.045, (
        "timed_phases must report the MIN rep, excluding the warmup "
        f"(got {out['p']:.3f}s)")


def test_timed_generations_times_each_step_individually():
    sleeps = [0.0, 0.08, 0.0]

    def step(x):
        time.sleep(sleeps[int(x)])
        return x + 1

    dts = [dt for _, _, dt in timed_generations(step, jnp.int32(0), ngen=3)]
    assert dts[1] >= 0.07, "slow generation must show in its own slot"
    assert dts[0] < 0.07 and dts[2] < 0.07, (
        "fast generations must not absorb the slow one's time")


def test_timed_generations_progresses_state():
    def step(x):
        return x + 1

    states = list(timed_generations(step, jnp.int32(0), ngen=3))
    assert [g for g, _, _ in states] == [0, 1, 2]
    assert int(states[-1][1]) == 3
    assert all(dt >= 0 for _, _, dt in states)


def test_span_recorder_reservoir_turnover_past_bound():
    """The percentile reservoir must be a uniform sample of the WHOLE
    stream, not first-N truncation: samples recorded after the bound
    must be able to displace early ones, while count/total/mean/max
    stay exact."""
    rec = SpanRecorder(max_samples=64, seed=7)
    n = 2000
    # a stream whose values equal their index: early = small values
    for i in range(n):
        rec.record("s", float(i))
    agg = rec.aggregates()
    assert agg["s"]["count"] == n
    assert agg["s"]["total_s"] == float(sum(range(n)))
    assert agg["s"]["mean_s"] == agg["s"]["total_s"] / n
    # max is exact even if the reservoir evicted it
    assert agg["s"]["max_s"] == float(n - 1)
    bucket = rec._samples["s"]
    assert len(bucket) == 64
    # turnover: with first-N truncation every sample would be < 64;
    # a uniform reservoir of 2000 values holds mostly post-bound ones
    assert sum(1 for v in bucket if v >= 64) > 32
    # p50 of a uniform sample over [0, 2000) sits near 1000 — under
    # first-N truncation it would be ~32 (frozen forever)
    assert 500 <= agg["s"]["p50_s"] <= 1500
    assert agg["s"]["p99_s"] > 1500


def test_span_recorder_reservoir_deterministic_per_seed():
    def fill(seed):
        rec = SpanRecorder(max_samples=16, seed=seed)
        for i in range(500):
            rec.record("x", float(i))
        return list(rec._samples["x"])

    assert fill(3) == fill(3)
    assert fill(3) != fill(4)


def test_span_recorder_below_bound_keeps_every_sample():
    rec = SpanRecorder(max_samples=128)
    for i in range(100):
        rec.record("all", float(i))
    assert rec._samples["all"] == [float(i) for i in range(100)]
    agg = rec.aggregates()
    assert agg["all"]["count"] == 100
    assert agg["all"]["p99_s"] == 98.0  # index int(.99 * 99)
    assert agg["all"]["max_s"] == 99.0


def test_device_memory_snapshot(tmp_path):
    from deap_tpu.support.profiling import (device_memory_snapshot,
                                            live_buffer_bytes)

    keep = jnp.ones((256, 256), jnp.float32)  # noqa: F841 (live buffer)
    live = live_buffer_bytes()
    assert sum(live.values()) >= keep.nbytes
    path = str(tmp_path / "mem.pprof.gz")
    snap = device_memory_snapshot(path)
    assert snap["live_bytes"] == live or snap["live_bytes"]
    # the pprof blob landed (or the backend said why)
    if "profile_path" in snap:
        assert os.path.getsize(path) == snap["profile_bytes"] > 0
    else:
        assert "profile_error" in snap


def test_span_recorder_thread_safe_under_hammer():
    """Concurrent request threads all record into one installed
    recorder (the service's profile of use) — counts must be exact
    and aggregation must not tear while recording continues."""
    import threading

    N_THREADS, N_SPANS = 8, 300
    with SpanRecorder(max_samples=128) as rec:
        stop = threading.Event()

        def reader():
            # aggregate concurrently with recording: must never raise
            # (RuntimeError: dict changed size) nor see torn stats
            while not stop.is_set():
                for stats in rec.aggregates().values():
                    assert stats["count"] >= 1

        def writer(i):
            for k in range(N_SPANS):
                with span(f"hammer/{i}"):
                    pass

        threads = [threading.Thread(target=writer, args=(i,))
                   for i in range(N_THREADS)]
        rd = threading.Thread(target=reader)
        rd.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        rd.join()
    agg = rec.aggregates()
    for i in range(N_THREADS):
        assert agg[f"hammer/{i}"]["count"] == N_SPANS
