"""Distributed tracing plane — ids, propagation, stitching, rendering.

The acceptance bar of ``deap_tpu/telemetry/tracing.py``: one
``trace_id`` threads a request from the client socket to the device
program, every id derives deterministically from the request id (the
cross-restart stitching mechanism — no coordination, no propagation
state), a torn journal tail can never split a trace in two, and
``report.py --trace`` renders the waterfall without jax in the
process. The service end-to-end test drives a real loopback socket
and asserts the span spine (queue wait → WAL fsync → admission →
compile → segments → checkpoint → wire encode) lands in the journal
with one trace id and a resolvable parent chain.
"""

import json
import os
import subprocess
import sys
import threading

import pytest

from deap_tpu.telemetry import tracing
from deap_tpu.telemetry.journal import (RunJournal, broadcast,
                                        journal_generations,
                                        read_journal)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT = os.path.join(REPO, "deap_tpu", "telemetry", "report.py")


# ------------------------------------------------------------- ids ----

def test_deterministic_ids_stable():
    assert tracing.trace_id_for("req-1") == tracing.trace_id_for("req-1")
    assert tracing.trace_id_for("req-1") != tracing.trace_id_for("req-2")
    assert len(tracing.trace_id_for("req-1")) == 32
    assert len(tracing.span_id_for("req-1", "request")) == 16
    assert (tracing.root_span_id("req-1")
            == tracing.span_id_for("req-1", "request"))
    assert (tracing.span_id_for("req-1", "client")
            != tracing.span_id_for("req-1", "request"))
    assert len(tracing.new_span_id()) == 16
    assert tracing.new_span_id() != tracing.new_span_id()


def test_traceparent_roundtrip_and_malformed():
    tid = tracing.trace_id_for("req-7")
    sid = tracing.span_id_for("req-7", "client")
    hdr = tracing.format_traceparent(tid, sid, sampled=True)
    assert tracing.parse_traceparent(hdr) == (tid, sid, True)
    hdr0 = tracing.format_traceparent(tid, sid, sampled=False)
    assert tracing.parse_traceparent(hdr0) == (tid, sid, False)
    # malformed / absent / all-zero (W3C: invalid) all parse to None
    for bad in (None, "", "garbage", "00-xyz-abc-01",
                f"00-{'0' * 32}-{sid}-01", f"00-{tid}-{'0' * 16}-01",
                hdr + "-extra"):
        assert tracing.parse_traceparent(bad) is None


def test_sampling_deterministic_and_bounded():
    tr = tracing.Tracer(sample=0.5)
    ids = [tracing.trace_id_for(f"req-{i}") for i in range(400)]
    first = [tr.sampled(t) for t in ids]
    assert first == [tr.sampled(t) for t in ids]  # deterministic
    rate = sum(first) / len(first)
    assert 0.35 < rate < 0.65
    assert all(tracing.Tracer(sample=1.0).sampled(t) for t in ids)
    assert not any(tracing.Tracer(sample=0.0).sampled(t) for t in ids)


def test_context_for_honours_traceparent():
    tr = tracing.Tracer(sample=1.0)
    # no header: both ids derive from the request id
    ctx = tr.context_for("req-9")
    assert ctx.trace_id == tracing.trace_id_for("req-9")
    assert ctx.span_id == tracing.root_span_id("req-9")
    # a valid header wins — its trace continues, its span parents
    hdr = tracing.format_traceparent("ab" * 16, "cd" * 8)
    ctx2 = tr.context_for("req-9", hdr)
    assert ctx2.trace_id == "ab" * 16
    assert ctx2.span_id == "cd" * 8
    # a malformed header falls back to derivation
    ctx3 = tr.context_for("req-9", "not-a-traceparent")
    assert ctx3.trace_id == ctx.trace_id


def test_ambient_context_use_and_ids():
    assert tracing.current() is None
    assert tracing.current_ids() == {}
    ctx = tracing.TraceContext("aa" * 16, "bb" * 8, request_id="r1")
    with tracing.use(ctx):
        assert tracing.current() is ctx
        ids = tracing.current_ids()
        assert ids == {"trace_id": "aa" * 16, "span_id": "bb" * 8,
                       "request_id": "r1"}
        child = ctx.child()
        assert child.trace_id == ctx.trace_id
        assert child.span_id != ctx.span_id
    assert tracing.current() is None
    with tracing.use(None):          # None is a no-op
        assert tracing.current() is None


# -------------------------------------------------------- emission ----

class _Sink:
    def __init__(self):
        self.rows = []

    def event(self, kind, **payload):
        self.rows.append(dict(kind=kind, **payload))


def test_tracer_emit_nulls_self_parent_and_observes_phase():
    sink = _Sink()
    seen = []
    tr = tracing.Tracer(journal=sink, sample=1.0,
                        phase_observe=lambda ph, s: seen.append(ph))
    ctx = tr.context_for("req-3")
    # the root span's id IS the ambient span id — parent must null
    tr.emit("request", 0.5, ctx=ctx,
            span_id=tracing.root_span_id("req-3"), always=True)
    tr.emit("wal.fsync", 0.01, ctx=ctx, phase="wal_fsync", always=True)
    root, child = sink.rows
    assert root["parent_id"] is None
    assert child["parent_id"] == tracing.root_span_id("req-3")
    assert child["request_id"] == "req-3"
    assert seen == ["wal_fsync"]


def test_tracer_span_installs_child_context():
    sink = _Sink()
    tr = tracing.Tracer(journal=sink, sample=1.0)
    ctx = tr.context_for("req-4")
    with tracing.use(ctx):
        with tr.span("outer", always=True) as child:
            assert tracing.current() is child
            tr.emit("inner", 0.001, always=True)
    inner, outer = sink.rows
    assert inner["parent_id"] == outer["span_id"]
    assert outer["parent_id"] == ctx.span_id is not None
    assert {inner["trace_id"], outer["trace_id"]} == {ctx.trace_id}


def test_sampled_out_trace_keeps_lifecycle_spans_only():
    sink = _Sink()
    tr = tracing.Tracer(journal=sink, sample=0.0)
    ctx = tr.context_for("req-5")
    assert ctx.sampled is False
    tr.emit("detail", 0.1, ctx=ctx)                 # dropped
    tr.emit("queue.wait", 0.1, ctx=ctx, always=True)  # lifecycle
    assert [r["name"] for r in sink.rows] == ["queue.wait"]


def test_emit_current_honours_ambient_and_sampling(tmp_path):
    j = RunJournal(str(tmp_path / "j.jsonl"))
    try:
        tracing.emit_current("nothing", 0.1)   # no ambient ctx: no row
        ctx = tracing.TraceContext("aa" * 16, "bb" * 8,
                                   request_id="r", sampled=False)
        with tracing.use(ctx):
            tracing.emit_current("detail", 0.1)           # sampled out
            tracing.emit_current("spine", 0.1, always=True)
    finally:
        j.close()
    rows = [r for r in read_journal(str(tmp_path / "j.jsonl"))
            if r.get("kind") == "trace_span"]
    assert [r["name"] for r in rows] == ["spine"]
    assert rows[0]["parent_id"] == "bb" * 8


# ------------------------------------------- rotation + stitching ----

def test_journal_rotation_preserves_generations(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j1 = RunJournal(path)
    j1.event("trace_span", name="before", trace_id="t" * 32,
             span_id="a" * 16, parent_id=None, dur_s=0.1)
    j1.close()
    j2 = RunJournal(path)   # same path: the restart case
    assert j2.rotated_from == path + ".1"
    j2.event("trace_span", name="after", trace_id="t" * 32,
             span_id="b" * 16, parent_id="a" * 16, dur_s=0.1)
    j2.close()
    gens = journal_generations(path)
    assert gens == [path + ".1", path]
    names = [r["name"] for p in gens for r in read_journal(p)
             if r.get("kind") == "trace_span"]
    assert names == ["before", "after"]


def _groups(path):
    out = []
    for p in journal_generations(path):
        rows = read_journal(p, strict=False)
        hdr = next((r for r in rows if r.get("kind") == "header"), None)
        out.append((hdr, rows))
    return out


def test_assemble_trace_rebases_across_generations():
    rid = "req-x"
    tid = tracing.trace_id_for(rid)
    root = tracing.root_span_id(rid)
    g1 = ({"kind": "header", "wall_start": 100.0},
          [{"kind": "trace_span", "name": "request", "t": 5.0,
            "dur_s": 5.0, "trace_id": tid, "span_id": root,
            "parent_id": None, "request_id": rid}])
    g2 = ({"kind": "header", "wall_start": 110.0},
          [{"kind": "trace_span", "name": "request.replay", "t": 1.0,
            "dur_s": 0.0, "trace_id": tid,
            "span_id": "c" * 16, "parent_id": root,
            "request_id": rid},
           {"kind": "other", "t": 2.0}])
    trace = tracing.assemble_trace([g1, g2], tid)
    assert [s["name"] for s in trace["spans"]] == ["request",
                                                   "request.replay"]
    # rebased onto one wall axis: 100+5-5=100, then 110+1
    assert trace["spans"][0]["start"] == pytest.approx(100.0)
    assert trace["spans"][1]["start"] == pytest.approx(111.0)
    assert trace["orphans"] == []
    assert trace["root"]["name"] == "request"


def test_assemble_trace_synthesizes_lost_root_and_flags_orphans():
    rid = "req-y"
    tid = tracing.trace_id_for(rid)
    rows = [{"kind": "trace_span", "name": "segment", "t": 2.0,
             "dur_s": 1.0, "trace_id": tid, "span_id": "d" * 16,
             "parent_id": tracing.root_span_id(rid),
             "request_id": rid},
            {"kind": "trace_span", "name": "stray", "t": 3.0,
             "dur_s": 0.5, "trace_id": tid, "span_id": "e" * 16,
             "parent_id": "f" * 16, "request_id": rid}]
    trace = tracing.assemble_trace([(None, rows)], tid)
    root = trace["root"]
    assert root["synthetic"] is True
    assert root["span_id"] == tracing.root_span_id(rid)
    # the segment span parents onto the synthesized root; the stray's
    # parent resolves nowhere
    assert trace["orphans"] == ["e" * 16]


def test_torn_tail_never_splits_a_trace(tmp_path):
    """kill -9 mid-write: read_journal(strict=False) drops the torn
    last line; every surviving span still carries the one
    deterministic trace id (satellite: trace continuity)."""
    path = str(tmp_path / "journal.jsonl")
    j = RunJournal(path)
    tr = tracing.Tracer(journal=j, sample=1.0)
    ctx = tr.context_for("req-torn")
    for i in range(5):
        tr.emit(f"segment", 0.1, ctx=ctx, phase="device",
                always=True, gen=i)
    j.close()
    with open(path, "ab") as fh:          # torn tail: half a row
        fh.write(b'{"kind": "trace_span", "name": "half", "trace')
    rows = read_journal(path, strict=False)
    assert rows.tear_offset is not None
    spans = [r for r in rows if r.get("kind") == "trace_span"]
    assert len(spans) == 5
    assert {s["trace_id"] for s in spans} \
        == {tracing.trace_id_for("req-torn")}
    trace = tracing.assemble_trace(
        [(None, rows)], tracing.trace_id_for("req-torn"))
    assert len(trace["spans"]) == 6       # 5 + synthesized root
    assert trace["orphans"] == []


# -------------------------------------------------------- perfetto ----

def test_perfetto_events_shapes(tmp_path):
    spans = [{"kind": "trace_span", "name": "segment", "start": 1.0,
              "end": 1.5, "dur_s": 0.5, "trace_id": "t" * 32,
              "span_id": "a" * 16, "parent_id": None,
              "tenant_id": "t0", "t": 1.5},
             {"kind": "trace_span", "name": "finished", "start": 1.5,
              "end": 1.5, "dur_s": 0.0, "trace_id": "t" * 32,
              "span_id": "b" * 16, "parent_id": "a" * 16, "t": 1.5}]
    ev = tracing.perfetto_events(spans)
    assert ev[0]["ph"] == "X" and ev[0]["dur"] == pytest.approx(5e5)
    assert ev[0]["ts"] == pytest.approx(1e6)
    assert ev[0]["tid"] == "t0"
    assert ev[1]["ph"] == "i"             # zero-duration → instant
    out = str(tmp_path / "trace.json")
    tracing.write_perfetto(out, spans)
    payload = json.load(open(out))
    assert len(payload["traceEvents"]) == 2


# ---------------------------------------- report.py --trace, no jax ----

def _make_traced_journal(root):
    """A handcrafted service-shaped journal with one request's spans."""
    path = os.path.join(root, "journal.jsonl")
    j = RunJournal(path)
    tr = tracing.Tracer(journal=j, sample=1.0)
    rid = "req-cl-abc-1"
    ctx = tr.context_for(rid)
    j.event("job_submitted", tenant_id="t0", family="ea_simple",
            request_id=rid)
    tr.emit("request", 0.9, ctx=ctx,
            span_id=tracing.root_span_id(rid), always=True)
    for name, phase, dur in (("queue.wait", "queue_wait", 0.01),
                             ("wal.fsync", "wal_fsync", 0.002),
                             ("admit.pack", "admission", 0.2),
                             ("compile", "compile", 0.4),
                             ("segment", "device", 0.3),
                             ("checkpoint", "checkpoint", 0.005),
                             ("wire.encode", "wire_encode", 0.001)):
        tr.emit(name, dur, ctx=ctx, phase=phase, always=True,
                tenant_id="t0")
    j.close()
    return path, rid


def test_report_trace_renders_waterfall_without_jax(tmp_path):
    """report.py --trace in a clean subprocess: the waterfall and the
    per-phase table render, tenant-id resolution works, the Perfetto
    export writes — and jax never enters sys.modules (the report's
    laptop/CI triage guarantee extends to the new path)."""
    path, rid = _make_traced_journal(str(tmp_path))
    perfetto = str(tmp_path / "out.json")
    code = (
        "import sys, runpy\n"
        f"sys.argv = ['report.py', '--trace', 't0', "
        f"'--perfetto', {perfetto!r}, {path!r}]\n"
        f"runpy.run_path({REPORT!r}, run_name='__main__')\n"
        "assert 'jax' not in sys.modules, 'trace report imported jax'\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert tracing.trace_id_for(rid) in out
    assert f"request id: {rid}" in out
    assert "resolved from tenant id: t0" in out
    for name in ("queue.wait", "wal.fsync", "admit.pack", "compile",
                 "segment", "checkpoint", "wire.encode"):
        assert name in out
    assert "Phase latency" in out and "queue_wait" in out
    assert len(json.load(open(perfetto))["traceEvents"]) == 8


def test_report_trace_unknown_id_degrades_gracefully(tmp_path):
    path, _ = _make_traced_journal(str(tmp_path))
    from deap_tpu.telemetry.report import render_trace
    msg = render_trace(path, "no-such-id")
    assert "no journal row" in msg


# ------------------------------------- checkpoint row stamping ----

def test_checkpoint_rows_stamp_request_and_tenant_ids(tmp_path):
    """checkpoint saves broadcast with request_id/tenant_id, and a
    successful restore broadcasts a ``checkpoint_restore`` row with
    the same stamps (the formerly-unstamped journal rows)."""
    from deap_tpu.support.checkpoint import Checkpointer
    j = RunJournal(str(tmp_path / "j.jsonl"))
    try:
        ck = Checkpointer(str(tmp_path / "ck"), keep=2)
        ck.save(3, {"x": 1},
                meta={"tenant_id": "t9", "request_id": "req-cl-z-1"})
        got = ck.restore_latest(tenant_id="t9")
        assert got is not None and got[0] == 3
    finally:
        j.close()
    rows = read_journal(str(tmp_path / "j.jsonl"))
    save = next(r for r in rows if r.get("kind") == "checkpoint")
    assert save["tenant_id"] == "t9"
    assert save["request_id"] == "req-cl-z-1"
    restore = next(r for r in rows
                   if r.get("kind") == "checkpoint_restore")
    assert restore["tenant_id"] == "t9"
    assert restore["request_id"] == "req-cl-z-1"
    assert restore["step"] == 3


# ----------------------------------------------- service end-to-end ----

@pytest.mark.slow
def test_service_end_to_end_trace(tmp_path):
    """One job over a real loopback socket with ``trace_sample=1.0``:
    the full span spine lands in the journal under one trace id
    derived from the client's request id, parents resolve, the
    compile span links its ``program_profile`` HLO hash, and the
    per-phase histogram exports on the metrics registry."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_service import PROBLEMS

    from deap_tpu.serving import EvolutionService, ServiceClient
    from deap_tpu.telemetry.metrics import MetricsRegistry

    reg = MetricsRegistry()
    with EvolutionService(str(tmp_path), PROBLEMS, max_lanes=2,
                          segment_len=2, trace_sample=1.0,
                          metrics=reg) as svc:
        with ServiceClient(svc.url) as c:
            tid = c.submit("onemax", {"seed": 3, "ngen": 6},
                           tenant_id="t0")
            res = c.result(tid, wait=True, timeout=120)
            assert res["status"] == "finished"

    rows = read_journal(os.path.join(str(tmp_path), "journal.jsonl"),
                        strict=False)
    spans = [r for r in rows if r.get("kind") == "trace_span"]
    names = {s["name"] for s in spans}
    assert {"request", "submit.build", "wal.fsync", "queue.wait",
            "admit.pack", "compile", "segment", "checkpoint",
            "finished", "wire.encode"} <= names

    # one trace, derived from the client's generated request id
    rid = next(s["request_id"] for s in spans if s.get("request_id"))
    assert rid.startswith("req-cl-")
    assert {s["trace_id"] for s in spans} \
        == {tracing.trace_id_for(rid)}

    # the parent chain resolves — no orphans, root is the HTTP request
    hdr = next(r for r in rows if r.get("kind") == "header")
    trace = tracing.assemble_trace([(hdr, rows)],
                                   tracing.trace_id_for(rid))
    assert trace["orphans"] == []
    assert trace["root"]["name"] == "request"
    assert not trace["root"].get("synthetic")

    # compile spans link the observatory's HLO hash both ways
    compile_span = next(s for s in spans if s["name"] == "compile")
    profiles = [r for r in rows if r.get("kind") == "program_profile"]
    assert profiles and all(p.get("trace_id") for p in profiles)
    assert compile_span["hlo_hash"] in {p["hlo_hash"] for p in profiles}

    # phase histogram exported
    text = reg.metrics_text()
    assert "deap_service_phase_seconds" in text
    assert 'phase="device"' in text


@pytest.mark.slow
def test_autoscale_spill_decision_stamps_request_id(tmp_path):
    """An autoscaler spill that targets a tenant journals the
    submitting request id (the formerly-unstamped
    ``autoscale_decision`` row)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from test_service import PROBLEMS

    from deap_tpu.serving import EvolutionService, ServiceClient
    from deap_tpu.serving.autoscale import AutoscaleDecision

    class SpillT0:
        def __init__(self):
            self.fired = False

        def decide(self, snap):
            if self.fired:
                return AutoscaleDecision()
            self.fired = True
            return AutoscaleDecision(spill=["t0"])

    with EvolutionService(str(tmp_path), PROBLEMS, max_lanes=2,
                          segment_len=2, trace_sample=1.0,
                          autoscale=SpillT0(),
                          autoscale_every=1) as svc:
        with ServiceClient(svc.url) as c:
            c.submit("onemax", {"seed": 5, "ngen": 8}, tenant_id="t0")
            res = c.result("t0", wait=True, timeout=120)
            assert res["status"] == "finished"

    rows = read_journal(os.path.join(str(tmp_path), "journal.jsonl"),
                        strict=False)
    spills = [r for r in rows if r.get("kind") == "autoscale_decision"
              and r.get("action") == "spill"]
    assert spills
    assert all(str(s.get("request_id", "")).startswith("req-cl-")
               for s in spills)
