"""Fleet journal federation — merge, rebase, stitch, render.

The acceptance bar of ``deap_tpu/telemetry/federation.py`` (ISSUE
19): a fleet root of ≥ 3 per-process journal dirs federates into one
monotonic-rebased timeline (rotated generations oldest-first, torn
tails and headerless generations tolerated — the kill-9'd member
still counts), deterministic trace ids stitch one request's spans
across process boundaries with zero coordination, and ``report.py
--fleet`` renders the whole observatory in a subprocess that never
imports jax."""

import json
import os
import subprocess
import sys

from deap_tpu.telemetry import federation, tracing
from deap_tpu.telemetry.federation import (JOURNAL_NAME,
                                           cross_process_traces,
                                           federate, fleet_curve,
                                           fleet_processes,
                                           fleet_summary, fleet_trace,
                                           process_groups,
                                           process_meta,
                                           register_process,
                                           resolve_request_id)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT = os.path.join(REPO, "deap_tpu", "telemetry", "report.py")

RID = "req-fleet-1"


def _write(path, rows, torn_tail=None):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        for row in rows:
            fh.write(json.dumps(row) + "\n")
        if torn_tail is not None:
            fh.write(torn_tail)  # no newline: a writer died mid-write


def _span(t, name, wall_epoch_rid=None, *, trace_id=None, span_id,
          parent_id, dur=0.1, **extra):
    row = dict(t=t, kind="trace_span", name=name,
               trace_id=trace_id or tracing.trace_id_for(RID),
               span_id=span_id, parent_id=parent_id,
               dur_s=dur, request_id=RID)
    row.update(extra)
    return row


def _make_fleet(root):
    """Three processes:

    - ``router``: one generation, holds the request's root span and a
      ``job_submitted`` arrival (epoch 1000.0);
    - ``driver-a``: TWO generations (a kill-9 restart rotated the
      first) — the pre-kill generation carries early spans of the
      same trace and ends in a torn tail, the post-restart generation
      (epoch 1012.0) carries the late spans, an alarm and an alert
      row;
    - ``driver-b``: one generation with NO header (lost in a crash)
      plus an unrelated single-process trace and a shed row.
    """
    tid = tracing.trace_id_for(RID)
    root_sid = tracing.root_span_id(RID)

    p = register_process(root, "router", role="router")
    _write(p, [
        dict(kind="header", t=0.0, run_id="r0", wall_start=1000.0),
        dict(t=0.5, kind="job_submitted", tenant_id="t0",
             request_id=RID),
        _span(1.0, "request", span_id=root_sid, parent_id=None,
              dur=11.0),
    ])

    p = register_process(root, "driver-a", role="driver")
    _write(p + ".1", [
        dict(kind="header", t=0.0, run_id="a0", wall_start=1001.0),
        _span(1.0, "queue.wait", span_id="aaaa000000000001",
              parent_id=root_sid, dur=0.4),
        _span(2.0, "segment", span_id="aaaa000000000002",
              parent_id=root_sid, dur=0.9),
    ], torn_tail='{"t": 3.0, "kind": "trace_span", "na')
    _write(p, [
        dict(kind="header", t=0.0, run_id="a1", wall_start=1012.0),
        _span(0.5, "segment", span_id="aaaa000000000003",
              parent_id=root_sid, dur=0.5),
        dict(t=0.6, kind="alarm", alarm="driver_stall", stalled_s=3.0),
        dict(t=0.7, kind="driver_stall", stalled_s=3.0),
        dict(t=0.9, kind="alert", name="canary_failure",
             state="firing", prev="inactive", at=0.9),
        dict(t=1.0, kind="canary_failed", tenant_id="canary-1",
             request_id="req-c1", expected="aa", got="bb",
             reason="digest_mismatch"),
    ])

    p = register_process(root, "driver-b", role="driver")
    lone = tracing.trace_id_for("req-lonely")
    _write(p, [
        # no header row at all: epoch lost with the crash
        _span(2.0, "wire.encode", span_id="bbbb000000000001",
              parent_id=root_sid, dur=0.2),
        dict(t=2.5, kind="load_shed", tenant_id="t9", new=1),
        _span(3.0, "request", trace_id=lone,
              span_id=tracing.root_span_id("req-lonely"),
              parent_id=None, dur=0.1, request_id="req-lonely"),
    ])
    return tid


# ------------------------------------------------------- fleet root ----

def test_register_process_layout_and_meta(tmp_path):
    root = str(tmp_path)
    p = register_process(root, "alpha", role="driver", port=1234)
    assert p == os.path.join(root, "alpha", JOURNAL_NAME)
    assert os.path.isdir(os.path.dirname(p))
    meta = process_meta(root, "alpha")
    assert meta["process_id"] == "alpha"
    assert meta["role"] == "driver" and meta["port"] == 1234
    # a registered-but-never-journaled member is not listed (no
    # generations); an empty journal file is
    assert fleet_processes(root) == []
    open(p, "w").close()
    assert fleet_processes(root) == ["alpha"]
    # path-escaping ids are rejected
    import pytest
    with pytest.raises(ValueError):
        register_process(root, "../evil")


def test_process_groups_generations_oldest_first(tmp_path):
    root = str(tmp_path)
    _make_fleet(root)
    groups = process_groups(root, "driver-a")
    assert len(groups) == 2
    assert groups[0][0]["run_id"] == "a0"   # rotated .1 comes first
    assert groups[1][0]["run_id"] == "a1"
    # the pre-kill generation's torn tail is tolerated and reported
    assert groups[0][1].tear_offset is not None
    assert groups[1][1].tear_offset is None


# ------------------------------------------------------------ merge ----

def test_federate_rebases_and_sorts_one_timeline(tmp_path):
    root = str(tmp_path)
    _make_fleet(root)
    fed = federate(root)
    assert sorted(fed["processes"]) == ["driver-a", "driver-b",
                                       "router"]
    rows = fed["rows"]
    assert all("process" in r and "wall" in r for r in rows)
    walls = [r["wall"] for r in rows]
    assert walls == sorted(walls)            # one monotone timeline
    # epoch rebase: driver-a's post-restart segment (t=0.5 at epoch
    # 1012.0) lands AFTER its pre-kill spans (t≈2 at epoch 1001.0)
    segs = [r for r in rows if r["process"] == "driver-a"
            and r.get("kind") == "trace_span"
            and r.get("name") == "segment"]
    assert [round(s["wall"], 1) for s in segs] == [1003.0, 1012.5]
    # the headerless member's rows sit at the timeline origin rather
    # than poisoning the merge
    b = [r for r in rows if r["process"] == "driver-b"]
    assert all(r["wall"] == r["t"] for r in b)


def test_process_health_columns(tmp_path):
    root = str(tmp_path)
    _make_fleet(root)
    fed = federate(root)
    a = fed["processes"]["driver-a"]
    assert a["generations"] == 2
    assert a["torn_tails"] == 1
    assert a["missing_headers"] == 0
    assert a["alarms"] == {"driver_stall": 1}
    assert a["driver_stalls"] == 1
    assert a["canary_failed"] == 1 and a["canary_ok"] == 0
    assert a["firing_alerts"] == ["canary_failure"]
    assert a["meta"]["role"] == "driver"
    b = fed["processes"]["driver-b"]
    assert b["missing_headers"] == 1
    assert b["load_sheds"] == 1
    r = fed["processes"]["router"]
    assert r["rows"] == 3 and r["torn_tails"] == 0
    assert r["wall_lo"] == 1000.0


def test_fleet_curve_windows_merged_rows(tmp_path):
    root = str(tmp_path)
    _make_fleet(root)
    fed = federate(root)
    curve = fleet_curve(fed["rows"], window_s=5.0)
    assert curve
    # the arrival and the shed land in the fleet curve
    assert sum(w["arrivals"] for w in curve) == 1
    assert sum(w["sheds"] for w in curve) == 1
    assert fleet_curve([], window_s=5.0) == []


# ----------------------------------------------------------- stitch ----

def test_cross_process_trace_stitch_spans_three_members(tmp_path):
    root = str(tmp_path)
    tid = _make_fleet(root)
    xt = cross_process_traces(root)
    assert len(xt) == 1                      # the lonely trace is not
    assert xt[0]["trace_id"] == tid          # cross-process
    assert xt[0]["processes"] == ["driver-a", "driver-b", "router"]
    assert xt[0]["spans"] == 5               # the torn 6th span is lost
    assert xt[0]["request_id"] == RID

    assert resolve_request_id(root, RID) == RID
    assert resolve_request_id(root, "t0") == RID   # via tenant id
    assert resolve_request_id(root, "nope") is None
    assert fleet_trace(root, "nope") is None

    trace = fleet_trace(root, "t0")
    assert trace["request_id"] == RID
    assert trace["processes"] == ["driver-a", "driver-b", "router"]
    names = {s["name"] for s in trace["spans"]}
    assert {"request", "queue.wait", "segment",
            "wire.encode"} <= names
    # every span resolves to the deterministic root: the kill-9
    # restart and the missing header orphaned nothing
    assert trace["orphans"] == []
    assert trace["root"]["span_id"] == tracing.root_span_id(RID)
    assert not trace["root"].get("synthetic")


def test_fleet_summary_is_the_report_payload(tmp_path):
    root = str(tmp_path)
    _make_fleet(root)
    s = fleet_summary(root, window_s=5.0)
    assert set(s) == {"root", "processes", "rows", "curve",
                      "cross_traces"}
    assert len(s["cross_traces"]) == 1


def test_empty_root_degrades_gracefully(tmp_path):
    root = str(tmp_path / "nothing")
    assert fleet_processes(root) == []
    assert federate(root)["rows"] == []
    assert cross_process_traces(root) == []
    assert fleet_summary(root)["curve"] == []


# ----------------------------------------------------------- render ----

def test_render_fleet_no_jax_subprocess(tmp_path):
    """``report.py --fleet`` in a clean subprocess: the process table,
    the fleet curve, the gates and the cross-process waterfall all
    render — and jax never enters sys.modules (federation is part of
    the laptop/CI triage surface)."""
    root = str(tmp_path)
    tid = _make_fleet(root)
    code = (
        "import sys, runpy\n"
        f"sys.argv = ['report.py', '--fleet', {root!r}]\n"
        f"runpy.run_path({REPORT!r}, run_name='__main__')\n"
        "assert 'jax' not in sys.modules, 'fleet report imported jax'\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "# Fleet:" in out
    assert "3 process(es)" in out
    for pid in ("router", "driver-a", "driver-b"):
        assert pid in out
    assert "▲1 headerless" in out            # driver-b's lost header
    assert "canary_failure" in out           # firing alert column
    assert "driver_stall×1" in out           # fleet alarm rollup
    assert "## Fleet SLO curve" in out
    assert "## Cross-process traces" in out
    assert tid in out
    assert f"request {RID}" in out
    # the waterfall stitched spans from all three members
    assert "### Waterfall" in out
    for name in ("queue.wait", "segment", "wire.encode"):
        assert name in out


def test_render_fleet_empty_root_message(tmp_path):
    from deap_tpu.telemetry.report import render_fleet
    msg = render_fleet(str(tmp_path))
    assert "no registered processes" in msg


def test_federation_module_loads_standalone(tmp_path):
    """The module itself must import without the deap_tpu package
    (stdlib only) — the same guarantee report.py gives."""
    fed_py = os.path.join(REPO, "deap_tpu", "telemetry",
                          "federation.py")
    code = (
        "import sys, importlib.util\n"
        f"spec = importlib.util.spec_from_file_location("
        f"'fed_standalone', {fed_py!r})\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "sys.modules[spec.name] = mod\n"
        "spec.loader.exec_module(mod)\n"
        f"print(sorted(mod.fleet_processes({str(tmp_path)!r})))\n"
        "assert 'jax' not in sys.modules\n"
        "assert 'deap_tpu' not in sys.modules\n"
    )
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert r.stdout.strip() == "[]"
