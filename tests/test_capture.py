"""Unit tests for the TPU-evidence capture machinery (tpu_capture.py).

The relay watcher's stop condition and per-step skip predicates decide
what gets measured during scarce relay uptime windows; a regression
here silently discards evidence (see the 2026-07-31 03:18 window,
where 40 of 44 minutes were spent re-proving captured artifacts).
These tests pin the predicate semantics against synthetic artifacts —
no jax, no relay, no subprocesses.
"""

import json
import sys

import pytest

pytestmark = pytest.mark.fast


@pytest.fixture
def capture(tmp_path, monkeypatch):
    import tpu_capture as t

    monkeypatch.setattr(t, "HERE", str(tmp_path))
    monkeypatch.setattr(t, "EVIDENCE",
                        str(tmp_path / "TPU_EVIDENCE_test.jsonl"))
    return t


def _write(path, rows):
    with open(path, "a") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def _evidence(t, script, results):
    _write(t.EVIDENCE, [{"ts": "x", "script": script, "results": results}])


def test_empty_state_nothing_captured(capture):
    for step in capture.CAPTURED:
        assert not capture.already_captured(step)
    assert not capture.queue_complete()


def test_hw_check_requires_passing_current_version_row(capture):
    V = capture.HW_CHECK_VERSION
    # failed, fallback, outdated-version, and non-core rows must not
    # suppress re-validation
    _evidence(capture, "_tpu_hw_check.py",
              [{"check": "hw_kernels", "ok": False, "version": V}])
    _evidence(capture, "_tpu_hw_check.py", [{"skipped": "no tpu"}])
    _evidence(capture, "_tpu_hw_check.py",
              [{"check": "hw_kernels", "ok": True}])  # pre-version row
    _evidence(capture, "_tpu_hw_check.py",
              [{"check": "selgather", "ok": True, "version": V}])
    assert not capture.already_captured("_tpu_hw_check.py")
    _evidence(capture, "_tpu_hw_check.py",
              [{"check": "hw_kernels", "ok": True, "version": V}])
    # core passed, but the tiled-dominance row hasn't landed yet
    assert not capture.already_captured("_tpu_hw_check.py")
    # a RESOLVED tiled row suffices even if it failed (deterministic
    # Mosaic gap must not re-run the step every window)
    _evidence(capture, "_tpu_hw_check.py",
              [{"check": "tiled_dominance", "ok": False, "version": V,
                "failed": ["crashed: NotImplementedError"]}])
    assert capture.already_captured("_tpu_hw_check.py")


def test_hw_check_tiled_process_abort_resolves_after_two_attempts(capture):
    V = capture.HW_CHECK_VERSION

    def _attempt(relay_up):
        _write(capture.EVIDENCE, [{
            "ts": "x", "script": "_tpu_hw_check.py",
            "relay_up_after": relay_up,
            "results": [{"check": "hw_kernels", "ok": True,
                         "version": V}]}])

    # aborts where the relay died with the step are the RELAY's fault —
    # they must never count toward the deterministic-abort threshold
    _attempt(relay_up=False)
    _attempt(relay_up=False)
    assert not capture.already_captured("_tpu_hw_check.py")
    # a fatal (process-level) abort in the tiled block with the relay
    # still up flushes the core row but never prints a tiled one; one
    # such attempt re-runs, two resolve — the step must not eat 1200 s
    # of every future window
    _attempt(relay_up=True)
    assert not capture.already_captured("_tpu_hw_check.py")
    _attempt(relay_up=True)
    assert capture.already_captured("_tpu_hw_check.py")


def test_headline_rejects_cpu_error_and_zero_rows(capture):
    for bad in ({"value": 3.5, "backend": "cpu", "tunnel_down": True},
                {"value": 0.0, "backend": "tpu",
                 "error": "all candidates failed"},
                {"value": 0.0, "backend": "tpu"}):
        _evidence(capture, "bench.py", [bad])
    assert not capture.already_captured("bench.py")
    # a cached replay row (bench.py re-emitting an earlier capture)
    # must not count as a fresh measurement either
    _evidence(capture, "bench.py",
              [{"value": 449.42, "backend": "tpu", "cached": True}])
    assert not capture.already_captured("bench.py")
    _evidence(capture, "bench.py", [{"value": 449.42, "backend": "tpu"}])
    assert capture.already_captured("bench.py")


def test_suite_needs_every_config_with_tpu_backing(capture, tmp_path):
    suite = tmp_path / capture.SUITE_OUT
    rows = [{"metric": f"{n}_generations_per_sec", "value": 1.0,
             "backend": "tpu"} for n in capture.SUITE_CONFIG_NAMES[:-1]]
    # the last config: error row only
    rows.append({"metric":
                 f"{capture.SUITE_CONFIG_NAMES[-1]}_generations_per_sec",
                 "error": "timeout"})
    _write(suite, rows)
    assert not capture.already_captured("bench_suite.py")
    _write(suite, [{"metric":
                    f"{capture.SUITE_CONFIG_NAMES[-1]}_generations_per_sec",
                    "value": 2.0, "backend": "tpu"}])
    assert capture.already_captured("bench_suite.py")


def test_profile_needs_every_component(capture, tmp_path):
    prof = tmp_path / capture.PROFILE_OUT
    _write(prof, [{"component": c, "ms_per_gen": 1.0, "backend": "tpu"}
                  for c in capture.COMPONENT_NAMES[:-2]])
    assert not capture.already_captured("bench_profile.py")
    # CPU rows for the missing components don't count
    _write(prof, [{"component": capture.COMPONENT_NAMES[-1],
                   "ms_per_gen": 1.0, "backend": "cpu"}])
    assert not capture.already_captured("bench_profile.py")
    _write(prof, [{"component": capture.COMPONENT_NAMES[-1],
                   "ms_per_gen": 1.0, "backend": "tpu"}])
    assert not capture.already_captured("bench_profile.py")
    # an error row IS a resolution (deterministic failure on record)
    _write(prof, [{"component": capture.COMPONENT_NAMES[-2],
                   "error": "NotImplementedError: ...",
                   "backend": "tpu"}])
    assert capture.already_captured("bench_profile.py")


def test_trace_needs_finalised_xplane(capture, tmp_path):
    tdir = tmp_path / capture.TRACE_DIR / "plugins" / "profile" / "run1"
    tdir.mkdir(parents=True)
    # scaffolding without a finalised xplane file must not satisfy
    (tdir / "partial.tmp").write_text("x")
    assert not capture.already_captured("bench_profile.py --trace")
    (tdir / "host.xplane.pb").write_bytes(b"\x00")
    assert capture.already_captured("bench_profile.py --trace")


def test_queue_complete_only_when_everything_landed(capture, tmp_path):
    _evidence(capture, "_tpu_hw_check.py",
              [{"check": "hw_kernels", "ok": True,
                "version": capture.HW_CHECK_VERSION},
               {"check": "tiled_dominance", "ok": True,
                "version": capture.HW_CHECK_VERSION}])
    _evidence(capture, "bench.py", [{"value": 449.4, "backend": "tpu"}])
    _write(tmp_path / capture.SUITE_OUT,
           [{"metric": f"{n}_generations_per_sec", "value": 1.0,
             "backend": "tpu"} for n in capture.SUITE_CONFIG_NAMES])
    _write(tmp_path / capture.PROFILE_OUT,
           [{"component": c, "ms_per_gen": 1.0, "backend": "tpu"}
            for c in capture.COMPONENT_NAMES])
    assert not capture.queue_complete()  # trace still missing
    tdir = tmp_path / capture.TRACE_DIR
    tdir.mkdir(parents=True)
    (tdir / "host.xplane.pb").write_bytes(b"\x00")
    assert not capture.queue_complete()  # zoo still missing
    import json as _json
    (tmp_path / capture.ZOO_OUT).write_text(_json.dumps({
        "results": [{"example": n, "ok": True, "backend": "tpu",
                     "config": "full"}
                    for n in capture.ZOO_FLAGSHIP]}))
    # still incomplete: the headline race predates the full candidate
    # roster (no n_candidates stamp)
    assert not capture.queue_complete()
    _evidence(capture, "bench.py#rerace",
              [{"value": 460.0, "backend": "tpu",
                "n_candidates": capture.N_CANDIDATES}])
    assert capture.queue_complete()


def test_zoo_needs_every_flagship_on_tpu(capture, tmp_path):
    import json as _json

    zoo = tmp_path / capture.ZOO_OUT
    rows = [{"example": n, "ok": True, "backend": "tpu",
             "config": "full"} for n in capture.ZOO_FLAGSHIP[:-1]]
    # timeout row (no backend) must not count as resolved
    rows.append({"example": capture.ZOO_FLAGSHIP[-1],
                 "ok": "subprocess timeout (3600s)", "backend": None})
    zoo.write_text(_json.dumps({"results": rows}))
    assert not capture.already_captured("speed.py#flagship")
    # a FAILING on-chip row is still a resolution (recorded evidence)
    # smoke-config TPU rows must not satisfy the full-config step
    rows[-1] = {"example": capture.ZOO_FLAGSHIP[-1],
                "ok": True, "backend": "tpu", "config": "smoke"}
    zoo.write_text(_json.dumps({"results": rows}))
    assert not capture.already_captured("speed.py#flagship")
    # a FAILING full-config on-chip row is still a resolution
    rows[-1] = {"example": capture.ZOO_FLAGSHIP[-1],
                "ok": "ValueError: boom", "backend": "tpu",
                "config": "full"}
    zoo.write_text(_json.dumps({"results": rows}))
    assert capture.already_captured("speed.py#flagship")


def test_full_race_accepts_deterministic_failures(capture):
    # a roster where one candidate deterministically failed (e.g. the
    # selgather gate raising on an unsupported Mosaic lowering) is
    # RESOLVED — without this, one failing candidate would make the
    # re-race predicate permanently false and the watcher would re-run
    # the race every uptime window forever (advisor r3)
    _evidence(capture, "bench.py#rerace",
              [{"value": 460.0, "backend": "tpu",
                "n_candidates": capture.N_CANDIDATES - 1,
                "n_resolved": capture.N_CANDIDATES}])
    assert capture.already_captured("bench.py#rerace")


def test_full_race_rejects_partial_race(capture):
    # timeout/unreached candidates are NOT resolved: the race was cut
    # short by the window, and a later window must retry it
    _evidence(capture, "bench.py#rerace",
              [{"value": 460.0, "backend": "tpu",
                "n_candidates": 3, "n_resolved": 4}])
    assert not capture.already_captured("bench.py#rerace")


def test_full_race_accepts_fully_resolved_all_failed(capture):
    # the all-candidates-FAILED sentinel (value=0.0, "error" key) is
    # excluded from headline_rows by design, but when every candidate
    # resolved as a deterministic failure it is still a terminal race
    # outcome — without accepting it the watcher would re-run the race
    # every uptime window in that corner (advisor r4)
    _evidence(capture, "bench.py",
              [{"value": 0.0, "backend": "tpu",
                "error": "all candidates failed",
                "n_candidates": 0,
                "n_resolved": capture.N_CANDIDATES}])
    assert capture.already_captured("bench.py#rerace")


def test_full_race_rejects_partial_all_failed(capture):
    # an all-failed row whose resolution count is short (relay died
    # mid-race) must still be retried next window
    _evidence(capture, "bench.py",
              [{"value": 0.0, "backend": "tpu",
                "error": "all candidates failed",
                "n_candidates": 0,
                "n_resolved": capture.N_CANDIDATES - 2}])
    assert not capture.already_captured("bench.py#rerace")


def test_tolerant_jsonl_reader(capture, tmp_path):
    p = tmp_path / "rows.jsonl"
    p.write_text('{"a": 1}\nnot json — a writer died here\n{"b": 2}\n')
    assert capture._jsonl_rows(str(p)) == [{"a": 1}, {"b": 2}]
    assert capture._jsonl_rows(str(tmp_path / "missing.jsonl")) == []
