"""ResilientRun — segmented/checkpointed execution pinned bit-exact.

The acceptance bar of the resilience layer: for every loop family
(the four ``algorithms.py`` scans, the GP host engine, the island
epoch driver), a run chunked into segments with checkpoints between
them — including one interrupted and resumed from disk — produces
populations/logbooks/hofs bit-identical to the uninterrupted monolithic
run. Plus: transient-error retry/backoff with ``degraded`` journaling,
fatal errors propagating unretried, SIGTERM preemption honoured at the
segment boundary, and the non-finite quarantine wrapper. The heavier
fault matrices live in ``tests/test_chaos.py`` (``-m chaos``).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.resilience import (
    QUARANTINE_PENALTY,
    FailSegments,
    FaultPlan,
    Preempted,
    PreemptAt,
    ResilientRun,
    RetryPolicy,
    classify_error,
    nan_inject_evaluate,
    quarantine_non_finite,
)
from deap_tpu.telemetry import RunTelemetry, read_journal

NGEN = 7
SEG = 3  # deliberately not dividing NGEN: last segment is short


def _toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def _pop(n=64, length=16, seed=0):
    return init_population(jax.random.key(seed), n,
                           ops.bernoulli_genome(length),
                           FitnessSpec((1.0,)))


def _assert_pop_equal(a, b):
    np.testing.assert_array_equal(np.asarray(a.genomes),
                                  np.asarray(b.genomes))
    np.testing.assert_array_equal(np.asarray(a.fitness),
                                  np.asarray(b.fitness))
    np.testing.assert_array_equal(np.asarray(a.valid),
                                  np.asarray(b.valid))


def _assert_logbook_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert set(ra) == set(rb)
        for k in ra:
            np.testing.assert_array_equal(np.asarray(ra[k]),
                                          np.asarray(rb[k]))


# ------------------------------------------------ scan-loop families ----

def test_segmented_ea_simple_bit_exact(tmp_path):
    tb, pop, key = _toolbox(), _pop(), jax.random.key(1)
    p1, lb1, h1 = algorithms.ea_simple(key, pop, tb, 0.5, 0.2,
                                       ngen=NGEN, halloffame_size=4)
    res = ResilientRun(str(tmp_path / "ck"), segment_len=SEG)
    p2, lb2, h2 = res.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                                halloffame_size=4)
    _assert_pop_equal(p1, p2)
    _assert_logbook_equal(lb1, lb2)
    np.testing.assert_array_equal(np.asarray(h1.fitness),
                                  np.asarray(h2.fitness))
    np.testing.assert_array_equal(np.asarray(h1.genomes),
                                  np.asarray(h2.genomes))


def test_segmented_mu_plus_lambda_bit_exact(tmp_path):
    tb, pop, key = _toolbox(), _pop(), jax.random.key(2)
    p1, lb1, _ = algorithms.ea_mu_plus_lambda(
        key, pop, tb, 64, 128, 0.4, 0.3, ngen=NGEN)
    res = ResilientRun(str(tmp_path / "ck"), segment_len=2)
    p2, lb2, _ = res.ea_mu_plus_lambda(key, pop, tb, 64, 128, 0.4,
                                       0.3, ngen=NGEN)
    _assert_pop_equal(p1, p2)
    _assert_logbook_equal(lb1, lb2)


def test_segmented_mu_comma_lambda_bit_exact(tmp_path):
    tb, pop, key = _toolbox(), _pop(), jax.random.key(3)
    p1, lb1, _ = algorithms.ea_mu_comma_lambda(
        key, pop, tb, 64, 128, 0.4, 0.3, ngen=NGEN)
    res = ResilientRun(str(tmp_path / "ck"), segment_len=SEG)
    p2, lb2, _ = res.ea_mu_comma_lambda(key, pop, tb, 64, 128, 0.4,
                                        0.3, ngen=NGEN)
    _assert_pop_equal(p1, p2)
    _assert_logbook_equal(lb1, lb2)


def test_segmented_generate_update_bit_exact(tmp_path):
    from deap_tpu.strategies import cma

    strat = cma.Strategy(centroid=[0.0] * 6, sigma=0.5)
    tb = Toolbox()
    tb.register("generate", strat.generate)
    tb.register("update", strat.update)
    tb.register("evaluate", lambda g: -jnp.sum(g ** 2, axis=-1))
    key = jax.random.key(4)
    s1, lb1, h1 = algorithms.ea_generate_update(
        key, strat.initial_state(), tb, ngen=NGEN, spec=strat.spec,
        halloffame_size=3)
    res = ResilientRun(str(tmp_path / "ck"), segment_len=SEG)
    s2, lb2, h2 = res.ea_generate_update(
        key, strat.initial_state(), tb, ngen=NGEN, spec=strat.spec,
        halloffame_size=3)
    for a, b in zip(jax.tree_util.tree_leaves(s1),
                    jax.tree_util.tree_leaves(s2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    _assert_logbook_equal(lb1, lb2)
    np.testing.assert_array_equal(np.asarray(h1.fitness),
                                  np.asarray(h2.fitness))


# ------------------------------------------------------ host families ----

def test_segmented_gp_loop_bit_exact(tmp_path):
    import deap_tpu.gp as gp
    from deap_tpu.gp.loop import make_symbreg_loop

    ps = gp.math_set(n_args=1)
    X = jnp.linspace(-1.0, 1.0, 32, endpoint=False)[:, None]
    y = X[:, 0] ** 3 + X[:, 0]
    genomes = jax.vmap(gp.gen_half_and_half(ps, 48, 1, 2))(
        jax.random.split(jax.random.key(3), 128))
    run = make_symbreg_loop(ps, 48, X, y, height_limit=6)
    r1 = run(jax.random.key(9), genomes, NGEN)
    run2 = make_symbreg_loop(ps, 48, X, y, height_limit=6)
    res = ResilientRun(str(tmp_path / "ck"), segment_len=SEG)
    r2 = res.gp_loop(run2, jax.random.key(9), genomes, NGEN)
    np.testing.assert_array_equal(np.asarray(r1["fitness"]),
                                  np.asarray(r2["fitness"]))
    for k in ("nodes", "consts", "length"):
        np.testing.assert_array_equal(np.asarray(r1["genomes"][k]),
                                      np.asarray(r2["genomes"][k]))
    np.testing.assert_array_equal(np.asarray(r1["depths"]),
                                  np.asarray(r2["depths"]))
    assert r1["nevals"] == r2["nevals"]
    assert r1["best_fitness"] == r2["best_fitness"]


def test_segmented_island_bit_exact(tmp_path):
    from deap_tpu.parallel import island_init, make_island_step

    tb = _toolbox()
    pops = island_init(jax.random.key(2), 4, 32,
                       ops.bernoulli_genome(16), FitnessSpec((1.0,)))
    pops = jax.vmap(lambda p: algorithms.evaluate_invalid(
        p, tb.evaluate))(pops)
    step = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=3, mig_k=2)
    key = jax.random.key(7)
    ref = pops
    for epoch in range(5):
        ref = step(jax.random.fold_in(key, epoch), ref)
    res = ResilientRun(str(tmp_path / "ck"), segment_len=2)
    got = res.island_run(step, key, pops, 5)
    _assert_pop_equal(ref, got)


def test_segmented_island_mesh_bit_exact(tmp_path):
    """The shard_map'd island path: checkpoint gathers to host, resume
    re-applies placement via ``reshard=`` — still bit-exact against
    the uninterrupted sharded run (8 virtual CPU devices, conftest)."""
    from functools import partial

    from deap_tpu.parallel import (island_init, make_island_step,
                                   population_mesh, shard_population)

    assert len(jax.devices()) >= 8
    tb = _toolbox()
    mesh = population_mesh(8, ("island",))
    pops = island_init(jax.random.key(2), 8, 16,
                       ops.bernoulli_genome(16), FitnessSpec((1.0,)))
    pops = jax.vmap(lambda p: algorithms.evaluate_invalid(
        p, tb.evaluate))(pops)
    pops = shard_population(pops, mesh, "island")
    step = make_island_step(tb, cxpb=0.5, mutpb=0.2, freq=2, mig_k=1,
                            mesh=mesh)
    key = jax.random.key(7)
    ref = pops
    for epoch in range(4):
        ref = step(jax.random.fold_in(key, epoch), ref)

    from deap_tpu.resilience import FaultPlan, InjectedCrash, KillAt

    d = str(tmp_path / "ck")
    reshard = partial(shard_population, mesh=mesh, axis="island")
    with pytest.raises(InjectedCrash):
        ResilientRun(d, segment_len=2,
                     fault_plan=FaultPlan([KillAt(4)])).island_run(
            step, key, pops, 4, reshard=reshard)
    got = ResilientRun(d, segment_len=2).island_run(
        step, key, pops, 4, reshard=reshard)
    _assert_pop_equal(ref, got)


# --------------------------------------------------------- preemption ----

def test_sigterm_preempts_then_resumes_bit_exact(tmp_path):
    """A real SIGTERM mid-run: the driver finishes the in-flight
    segment, checkpoints, journals ``preempted`` and raises
    ``Preempted``; re-invoking the same call resumes and the final
    state is bit-identical to an uninterrupted run."""
    tb, pop, key = _toolbox(), _pop(), jax.random.key(5)
    p1, lb1, _ = algorithms.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    d = str(tmp_path / "ck")
    jpath = str(tmp_path / "j.jsonl")
    with RunTelemetry(jpath) as tel:
        res = ResilientRun(d, segment_len=2, telemetry=tel,
                           fault_plan=FaultPlan([PreemptAt(4)]))
        with pytest.raises(Preempted) as exc:
            res.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    assert exc.value.step == 4
    assert os.path.exists(exc.value.path)
    rows = read_journal(jpath)
    assert any(r["kind"] == "preempted" for r in rows)

    p2, lb2, _ = ResilientRun(d, segment_len=2).ea_simple(
        key, pop, tb, 0.5, 0.2, ngen=NGEN)
    _assert_pop_equal(p1, p2)
    _assert_logbook_equal(lb1, lb2)


def test_resume_journals_run_id_chain(tmp_path):
    """Segment linkage: the resumed run journals ``resumed`` with the
    prior run's id (read from checkpoint meta), so report tooling can
    stitch the segments into one timeline."""
    tb, pop, key = _toolbox(), _pop(), jax.random.key(6)
    d = str(tmp_path / "ck")
    with RunTelemetry(str(tmp_path / "a.jsonl")) as tel:
        res1 = ResilientRun(d, segment_len=2, telemetry=tel,
                            fault_plan=FaultPlan([PreemptAt(2)]))
        with pytest.raises(Preempted):
            res1.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
        first_id = res1.run_id
    with RunTelemetry(str(tmp_path / "b.jsonl")) as tel:
        res2 = ResilientRun(d, segment_len=2, telemetry=tel)
        res2.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
        assert res2.resumed_from == first_id
    rows = read_journal(str(tmp_path / "b.jsonl"))
    resumed = [r for r in rows if r["kind"] == "resumed"]
    assert resumed and resumed[0]["resumed_from"] == first_id
    assert resumed[0]["step"] == 2


def test_refuses_resume_of_different_algorithm(tmp_path):
    tb, pop, key = _toolbox(), _pop(), jax.random.key(8)
    d = str(tmp_path / "ck")
    with pytest.raises(Preempted):
        ResilientRun(d, segment_len=2,
                     fault_plan=FaultPlan([PreemptAt(2)])).ea_simple(
            key, pop, tb, 0.5, 0.2, ngen=NGEN)
    with pytest.raises(ValueError, match="refusing to resume"):
        ResilientRun(d, segment_len=2).ea_mu_comma_lambda(
            key, pop, tb, 64, 128, 0.4, 0.3, ngen=NGEN)


# ------------------------------------------------- failure handling ----

def test_transient_retry_backoff_and_degraded_events(tmp_path):
    """Two injected RESOURCE_EXHAUSTED failures on one segment: the
    driver backs off, calls the degrade hook, journals two ``degraded``
    events, and the final result is still bit-exact (retries re-run
    from the in-memory pre-segment state)."""
    tb, pop, key = _toolbox(), _pop(), jax.random.key(9)
    p1, _, _ = algorithms.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    jpath = str(tmp_path / "j.jsonl")
    sleeps, degrades = [], []
    with RunTelemetry(jpath) as tel:
        res = ResilientRun(
            str(tmp_path / "ck"), segment_len=2, telemetry=tel,
            retry=RetryPolicy(max_retries=3, backoff_s=0.01,
                              sleep=sleeps.append),
            degrade_cb=lambda kind, exc: degrades.append(kind)
            or "halved eval batch",
            fault_plan=FaultPlan([FailSegments(lo=2, times=2)]))
        p2, _, _ = res.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    _assert_pop_equal(p1, p2)
    assert degrades == ["resource_exhausted"] * 2
    assert len(sleeps) == 2 and sleeps[1] > sleeps[0]  # backoff grows
    rows = read_journal(jpath)
    degraded = [r for r in rows if r["kind"] == "degraded"]
    assert len(degraded) == 2
    assert degraded[0]["error_kind"] == "resource_exhausted"
    assert degraded[0]["action"] == "halved eval batch"


def test_retry_budget_exhausted_raises(tmp_path):
    from deap_tpu.resilience import InjectedTransient

    tb, pop, key = _toolbox(), _pop(), jax.random.key(10)
    res = ResilientRun(
        str(tmp_path / "ck"), segment_len=2,
        retry=RetryPolicy(max_retries=1, backoff_s=0.0,
                          sleep=lambda s: None),
        fault_plan=FaultPlan([FailSegments(lo=0, times=5)]))
    with pytest.raises(InjectedTransient):
        res.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)


def test_fatal_error_propagates_unretried(tmp_path):
    """A deterministic failure (shape error, assertion) must not burn
    retries — classify_error returns None and it propagates at once."""
    tb, pop, key = _toolbox(), _pop(), jax.random.key(11)
    attempts = []

    class _Boom(FaultPlan):
        def fire(self, event, **ctx):
            if event == "segment_attempt":
                attempts.append(ctx["attempt"])
                raise ValueError("deterministic bug")

    res = ResilientRun(str(tmp_path / "ck"), segment_len=2,
                       fault_plan=_Boom())
    with pytest.raises(ValueError, match="deterministic bug"):
        res.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    assert attempts == [0]


def test_classify_error_vocabulary():
    assert classify_error(
        RuntimeError("RESOURCE_EXHAUSTED: oom")) == "resource_exhausted"
    assert classify_error(
        RuntimeError("Out of memory allocating 1g")) == "resource_exhausted"
    assert classify_error(
        RuntimeError("UNAVAILABLE: socket closed")) == "transient"
    assert classify_error(ValueError("bad shape")) is None
    assert classify_error(AssertionError("x")) is None


# ---------------------------------------------------------- quarantine ----

def test_quarantine_substitutes_penalty_and_journals(tmp_path):
    tb = _toolbox()
    pop = _pop()
    wrapped = quarantine_non_finite(
        nan_inject_evaluate(tb.evaluate, [3, 5]))
    jpath = str(tmp_path / "q.jsonl")
    from deap_tpu.telemetry import RunJournal

    with RunJournal(jpath):
        vals = np.asarray(wrapped(pop.genomes))
        jax.effects_barrier()
    assert np.isfinite(vals).all()
    assert vals[3] == np.float32(QUARANTINE_PENALTY)
    assert vals[5] == np.float32(QUARANTINE_PENALTY)
    rows = read_journal(jpath)
    q = [r for r in rows if r["kind"] == "quarantine"]
    assert q and q[0]["n"] == 2


def test_quarantine_probe_counts_and_alarms(tmp_path):
    """QuarantineProbe Meter-counts sentinel rows each generation and
    its count feeds the HealthMonitor's existing non_finite alarm —
    without the probe the sentinel substitution would silence it."""
    from deap_tpu.telemetry.probes import HealthMonitor, QuarantineProbe

    tb = _toolbox()
    tb.register("evaluate", quarantine_non_finite(
        nan_inject_evaluate(
            lambda g: g.sum(-1).astype(jnp.float32), [0, 1, 2]),
        journal=False))
    pop, key = _pop(), jax.random.key(12)
    jpath = str(tmp_path / "qa.jsonl")
    health = HealthMonitor()
    with RunTelemetry(jpath, health=health) as tel:
        algorithms.ea_simple(key, pop, tb, 0.5, 0.2, ngen=3,
                             telemetry=tel, probes=(QuarantineProbe(),))
    rows = read_journal(jpath)
    meters = [r for r in rows if r["kind"] == "meter"]
    assert meters and all("quarantined" in r for r in meters)
    assert meters[0]["quarantined"] == 3  # the injected rows
    alarms = [r for r in rows if r["kind"] == "alarm"]
    assert alarms and alarms[0]["alarm"] == "non_finite"
    assert "quarantined" in alarms[0]["metrics"]


# ----------------------------------------------- telemetry invariance ----

def test_segmented_telemetry_on_bit_identical(tmp_path):
    """Segmenting + telemetry + probes together still change no
    computed result (the PR-2/PR-4 invariant extended to segments)."""
    from deap_tpu.telemetry.probes import DiversityProbe, FitnessProbe

    tb, pop, key = _toolbox(), _pop(), jax.random.key(13)
    p1, lb1, _ = algorithms.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    with RunTelemetry(str(tmp_path / "t.jsonl")) as tel:
        res = ResilientRun(str(tmp_path / "ck"), segment_len=SEG,
                           telemetry=tel)
        p2, lb2, _ = res.ea_simple(
            key, pop, tb, 0.5, 0.2, ngen=NGEN,
            probes=(DiversityProbe(sample=32), FitnessProbe()))
    _assert_pop_equal(p1, p2)
    _assert_logbook_equal(lb1, lb2)
    rows = read_journal(str(tmp_path / "t.jsonl"))
    meters = [r for r in rows if r["kind"] == "meter"]
    assert len(meters) == NGEN + 1  # gen 0 .. NGEN, across segments
    assert [r["gen"] for r in meters] == list(range(NGEN + 1))


# -------------------------------------- double-buffered checkpoints ----

def test_double_buffer_matches_sync_results_and_checkpoints(tmp_path):
    """Async boundary writes change nothing observable: same final
    population/logbook as the synchronous driver, and the checkpoint
    files restore to bit-identical state pytrees."""
    from deap_tpu.support.checkpoint import Checkpointer

    tb, pop, key = _toolbox(), _pop(), jax.random.key(21)
    results = {}
    for db in (False, True):
        res = ResilientRun(str(tmp_path / f"ck_{db}"), segment_len=SEG,
                           double_buffer=db)
        results[db] = res.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN,
                                    halloffame_size=4)
    (p1, lb1, h1), (p2, lb2, h2) = results[False], results[True]
    _assert_pop_equal(p1, p2)
    _assert_logbook_equal(lb1, lb2)
    s1 = Checkpointer(str(tmp_path / "ck_False")).restore()
    s2 = Checkpointer(str(tmp_path / "ck_True")).restore()
    s1.pop("_resilience")  # carries per-driver run ids, by design
    s2.pop("_resilience")
    l1 = jax.tree_util.tree_leaves(s1)
    l2 = jax.tree_util.tree_leaves(s2)
    assert len(l1) == len(l2)

    def _np(x):
        if isinstance(x, jax.Array) and jnp.issubdtype(
                x.dtype, jax.dtypes.prng_key):
            return np.asarray(jax.random.key_data(x))
        return np.asarray(x)

    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(_np(a), _np(b))


def test_double_buffer_resume_bit_exact(tmp_path):
    """Preempt after the first ASYNCHRONOUSLY-written segment, then
    resume in a fresh driver — the async write must be durable before
    Preempted is raised, and the resumed run bit-exact."""
    tb, pop, key = _toolbox(), _pop(), jax.random.key(22)
    p1, lb1, _ = algorithms.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    res = ResilientRun(str(tmp_path / "ck"), segment_len=SEG)
    assert res.double_buffer
    res.preempt_requested = True  # honoured after the first segment
    with pytest.raises(Preempted):
        res.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    assert res.ckpt.latest_step() == SEG  # the async write landed
    res2 = ResilientRun(str(tmp_path / "ck"), segment_len=SEG)
    p2, lb2, _ = res2.ea_simple(key, pop, tb, 0.5, 0.2, ngen=NGEN)
    _assert_pop_equal(p1, p2)
    _assert_logbook_equal(lb1, lb2)


def test_async_writer_snapshot_immune_to_mutation(tmp_path):
    """The double-buffer contract: in-place mutation of the live state
    dict AFTER submit cannot leak into the file (the GP loop mutates
    its state dict in place between segments)."""
    import time as _time

    from deap_tpu.support.checkpoint import (AsyncCheckpointWriter,
                                             Checkpointer)

    ck = Checkpointer(str(tmp_path / "ck"))
    writer = AsyncCheckpointWriter()
    state = {"gen": 3, "vals": jnp.arange(4), "log": [1, 2]}
    writer.submit(ck, 3, state, meta={"m": 1})
    state["gen"] = 99          # rebind
    state["log"].append(777)   # in-place append
    writer.wait()
    got = ck.restore(3)
    assert got["gen"] == 3
    assert got["log"] == [1, 2]
    np.testing.assert_array_equal(np.asarray(got["vals"]),
                                  np.arange(4))
    assert ck.meta(3)["m"] == 1
    del _time


def test_async_writer_error_surfaces_on_wait(tmp_path):
    from deap_tpu.support.checkpoint import (AsyncCheckpointWriter,
                                             Checkpointer)

    class _Boom(Checkpointer):
        def save(self, *a, **kw):
            raise OSError("disk gone")

    writer = AsyncCheckpointWriter()
    writer.submit(_Boom(str(tmp_path / "ck")), 1, {"x": 1})
    with pytest.raises(OSError, match="disk gone"):
        writer.wait()
    # the writer is reusable after the failure surfaced
    ck = Checkpointer(str(tmp_path / "ck2"))
    writer.submit(ck, 2, {"x": 2})
    writer.wait()
    assert ck.restore(2) == {"x": 2}


def test_fault_plan_forces_synchronous_saves(tmp_path):
    """Chaos plans assume the checkpoint exists the moment 'saved'
    fires — a fault_plan must disable double buffering."""
    from deap_tpu.resilience.faultinject import FaultPlan

    res = ResilientRun(str(tmp_path / "ck"), fault_plan=FaultPlan())
    assert res.double_buffer is False
    res2 = ResilientRun(str(tmp_path / "ck2"))
    assert res2.double_buffer is True
