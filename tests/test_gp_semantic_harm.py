"""Semantic GP and HARM-GP tests (reference: deap/gp.py:1215-1329
mutSemantic/cxSemantic, gp.py:938-1135 harm)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

MAX_LEN = 160


@pytest.fixture(scope="module")
def pset():
    ps = gp.math_set(n_args=1, trig=False)
    gp.add_semantic_primitives(ps)
    return ps


def valid_prefix(genome, pset):
    arity = np.asarray(pset.arity_table())
    nodes = np.asarray(genome["nodes"])
    length = int(genome["length"])
    need = 1
    for t in range(length):
        need += arity[nodes[t]] - 1
    return need == 0 and length >= 1


def test_mut_semantic_semantics(pset):
    """child(x) == parent(x) + ms·(lf(tr1(x)) − lf(tr2(x))); with fixed
    ms the mutated output must differ from the parent by a bounded
    perturbation |delta| <= ms."""
    expr = gp.make_generator(pset, 16, 1, 2, "grow")
    mut = gp.make_mut_semantic(pset, expr, MAX_LEN, ms=0.5)
    interp = gp.make_interpreter(pset, MAX_LEN)
    gen = gp.make_generator(pset, MAX_LEN, 1, 3)
    X = jnp.linspace(-1, 1, 16)[:, None]
    for seed in range(6):
        g = gen(jax.random.key(seed))
        child = mut(jax.random.key(100 + seed), g)
        assert valid_prefix(child, pset)
        before = interp(g, X)
        after = interp(child, X)
        delta = np.asarray(after - before)
        assert np.all(np.abs(delta) <= 0.5 + 1e-5)
        # lf outputs are in (0,1) so the perturbation is rarely exactly 0
        assert child["length"] > g["length"]


def test_cx_semantic_convex_combination(pset):
    """child1(x) = lf(tr)(x)·p1(x) + (1−lf(tr)(x))·p2(x) lies between
    the parents pointwise."""
    expr = gp.make_generator(pset, 16, 1, 2, "grow")
    cx = gp.make_cx_semantic(pset, expr, MAX_LEN)
    interp = gp.make_interpreter(pset, MAX_LEN)
    gen = gp.make_generator(pset, 48, 1, 3)
    X = jnp.linspace(-1, 1, 16)[:, None]
    for seed in range(6):
        g1 = gen(jax.random.key(seed))
        g2 = gen(jax.random.key(50 + seed))
        c1, c2 = cx(jax.random.key(200 + seed), g1, g2)
        assert valid_prefix(c1, pset) and valid_prefix(c2, pset)
        p1, p2 = interp(g1, X), interp(g2, X)
        lo = np.minimum(np.asarray(p1), np.asarray(p2)) - 1e-4
        hi = np.maximum(np.asarray(p1), np.asarray(p2)) + 1e-4
        o1 = np.asarray(interp(c1, X))
        o2 = np.asarray(interp(c2, X))
        assert np.all((o1 >= lo) & (o1 <= hi))
        assert np.all((o2 >= lo) & (o2 <= hi))


def test_semantic_overflow_returns_parent(pset):
    expr = gp.make_generator(pset, 16, 1, 2, "grow")
    mut = gp.make_mut_semantic(pset, expr, 24, ms=0.5)   # tiny width
    gen = gp.make_generator(pset, 24, 3, 4, "full")
    g = gen(jax.random.key(0))
    child = mut(jax.random.key(1), g)
    # composed program cannot fit 24 slots → parent unchanged
    np.testing.assert_array_equal(child["nodes"], g["nodes"])


def test_requires_semantic_primitives():
    bare = gp.PrimitiveSet("BARE", 1)
    bare.add_primitive(jnp.add, 2, "add")
    bare.add_terminal(1.0)
    expr = gp.make_generator(bare, 8, 1, 2, "grow")
    with pytest.raises(ValueError, match="required in order to perform"):
        gp.make_mut_semantic(bare, expr, 32)


def test_harm_controls_bloat(pset):
    """symbreg_harm-shaped run: evolve x²+x with HARM and without; HARM's
    mean tree size must stay well below the unconstrained run's."""
    max_len = 64
    gen = gp.make_generator(pset, max_len, 1, 3)
    expr_small = gp.make_generator(pset, 16, 0, 2, "grow")
    interp = gp.make_interpreter(pset, max_len)
    X = jnp.linspace(-1, 1, 20)[:, None]
    y = X[:, 0] ** 2 + X[:, 0]

    def evaluate(genomes):
        preds = jax.vmap(lambda g: interp(g, X))(genomes)
        return -jnp.mean((preds - y) ** 2, axis=-1)

    tb = Toolbox()
    tb.register("evaluate", evaluate)
    tb.register("mate", gp.make_cx_one_point(pset))
    tb.register("mutate", gp.make_mut_uniform(pset, expr_small))
    tb.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(0), 64,
                          lambda k: gen(k), FitnessSpec((1.0,)))
    out, logbook, _ = gp.harm(jax.random.key(1), pop, tb, 0.5, 0.2,
                              ngen=8, nbrindsmodel=256, mincutoff=10)
    sizes = np.asarray(out.genomes["length"])
    assert len(logbook) == 9
    assert logbook[0]["gen"] == 0 and logbook[-1]["gen"] == 8
    # HARM must keep mean size bounded: cutoff floor is 10, decay beyond
    assert sizes.mean() < 40.0
    assert np.all(sizes >= 1)
    # fitness should not collapse: best individual still evaluates
    assert np.isfinite(np.asarray(out.wvalues).max())
