"""Support-object tests — Statistics exact outputs, Logbook formatting,
HallOfFame/ParetoArchive semantics (counterpart of test_statistics.py,
test_logbook.py and HallOfFame behaviour in the reference)."""

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import Population
from deap_tpu.support import (
    HallOfFame,
    Logbook,
    MultiStatistics,
    Statistics,
    hof_best,
    hof_init,
    hof_update,
    pareto_init,
    pareto_update,
)


def _pop(values, genomes=None, weights=(1.0,)):
    v = jnp.asarray(values, jnp.float32)
    if v.ndim == 1:
        v = v[:, None]
    n = v.shape[0]
    if genomes is None:
        genomes = jnp.arange(n, dtype=jnp.float32)[:, None] * jnp.ones((1, 3))
    return Population(
        genomes=jnp.asarray(genomes), fitness=v, valid=jnp.ones(n, bool),
        spec=FitnessSpec(weights))


def test_statistics_exact_values():
    # counterpart of deap/tests/test_statistics.py exact-dict assertions
    stats = Statistics(key=lambda pop: pop.fitness[:, 0])
    stats.register("avg", jnp.mean)
    stats.register("max", jnp.max)
    res = stats.compile(_pop([1.0, 2.0, 3.0, 4.0]))
    assert float(res["avg"]) == 2.5
    assert float(res["max"]) == 4.0


def test_multistatistics_chapters():
    s1 = Statistics(key=lambda pop: pop.fitness[:, 0])
    s2 = Statistics(key=lambda pop: pop.genomes.sum(-1))
    ms = MultiStatistics(fitness=s1, size=s2)
    ms.register("avg", jnp.mean)
    res = ms.compile(_pop([2.0, 4.0]))
    assert set(res.keys()) == {"fitness", "size"}
    assert float(res["fitness"]["avg"]) == 3.0


def test_logbook_chapters_stream():
    # counterpart of deap/tests/test_logbook.py smoke formatting
    logbook = Logbook()
    logbook.header = ["gen", "fitness", "size"]
    logbook.record(gen=0, fitness={"avg": 1.0, "max": 2.0}, size={"avg": 3.0})
    logbook.record(gen=1, fitness={"avg": 1.5, "max": 2.5}, size={"avg": 2.0})
    text = str(logbook)
    assert "fitness" in text and "size" in text and "avg" in text
    assert logbook.chapters["fitness"].select("avg") == [1.0, 1.5]
    # stream is incremental
    lb2 = Logbook()
    lb2.record(a=1)
    first = lb2.stream
    lb2.record(a=2)
    second = lb2.stream
    assert "1" in first and "2" in second and "1" not in second.splitlines()[-1]


def test_logbook_scalar_collapses_to_python_types():
    # 0-d arrays must come back as native Python scalars (so "%g"
    # formatting and JSON serialisation never see numpy types);
    # n-d arrays pass through
    from deap_tpu.support.logbook import _scalar

    assert _scalar(np.float32(2.5)) == 2.5
    assert isinstance(_scalar(np.float32(2.5)), float)
    assert _scalar(np.int64(3)) == 3
    assert isinstance(_scalar(np.int64(3)), int)
    assert isinstance(_scalar(jnp.float32(1.5)), float)
    arr = np.arange(3)
    assert _scalar(arr) is arr


def test_logbook_pop_zero_index_shifts_stream_window():
    lb = Logbook()
    lb.record(a=1)
    lb.record(a=2)
    _ = lb.stream          # both streamed; buffindex == 2
    lb.pop(0)              # removed an already-streamed entry
    assert lb.buffindex == 1
    lb.record(a=3)
    assert lb.stream.strip().splitlines()[-1].strip() == "3"


def test_logbook_pop_negative_index_keeps_stream_window():
    # pop(-1) removes the newest (not-yet-streamed) entry; the raw
    # `buffindex > index` comparison treated every negative index as
    # already-streamed and re-streamed an old entry
    lb = Logbook()
    for a in (1, 2, 3):
        lb.record(a=a)
    _ = lb.stream          # buffindex == 3
    lb.record(a=4)
    lb.pop(-1)             # drop the unstreamed a=4
    assert lb.buffindex == 3
    lb.record(a=5)
    out = lb.stream
    assert out.strip() == "5", (
        f"already-streamed entries leaked back into stream: {out!r}")


def test_hof_tracks_best_and_dedups():
    pop = _pop([3.0, 1.0, 3.0, 5.0],
               genomes=jnp.array([[1.0], [2.0], [1.0], [3.0]]))
    hof = hof_init(3, pop)
    hof = hof_update(hof, pop)
    assert bool(hof.filled.all())
    # duplicate genome (1.0) at fitness 3.0 appears once; the third slot
    # falls through to the genuinely-next individual (fitness 1.0)
    np.testing.assert_allclose(np.asarray(hof.fitness[:, 0]), [5.0, 3.0, 1.0])
    g = np.asarray(hof.genomes[:, 0])
    assert g[0] == 3.0 and set(g[1:]) == {1.0, 2.0}

    # updating with a worse population changes nothing
    worse = _pop([0.5, 0.2], genomes=jnp.array([[9.0], [8.0]]))
    hof2 = hof_update(hof, worse)
    np.testing.assert_allclose(np.asarray(hof2.fitness), np.asarray(hof.fitness))

    # a new best displaces the tail
    better = _pop([7.0], genomes=jnp.array([[4.0]]))
    hof3 = hof_update(hof2, better)
    np.testing.assert_allclose(np.asarray(hof3.fitness[:, 0]), [7.0, 5.0, 3.0])
    bg, bf = hof_best(hof3)
    assert float(bf[0]) == 7.0 and float(bg[0]) == 4.0


def test_hof_update_inside_jit():
    pop = _pop([1.0, 2.0])
    hof = hof_init(2, pop)

    @jax.jit
    def f(hof, pop):
        return hof_update(hof, pop)

    out = f(hof, pop)
    assert float(out.fitness[0, 0]) == 2.0


def test_pareto_archive_keeps_nondominated():
    # two-objective minimisation
    pop = _pop(
        jnp.array([[1.0, 4.0], [2.0, 2.0], [4.0, 1.0], [3.0, 3.0], [1.0, 4.0]]),
        genomes=jnp.array([[1.0], [2.0], [3.0], [4.0], [5.0]]),
        weights=(-1.0, -1.0))
    arch = pareto_init(8, pop)
    arch = pareto_update(arch, pop)
    filled = np.asarray(arch.filled)
    fits = np.asarray(arch.fitness)[filled]
    # [3,3] dominated by [2,2]; one duplicate [1,4] genome 5 dropped? No —
    # distinct genomes with equal fitness both stay (neither dominates).
    assert filled.sum() == 4
    assert [3.0, 3.0] not in fits.tolist()
    # a new dominating point evicts dominated members
    better = _pop(jnp.array([[0.5, 0.5]]), genomes=jnp.array([[6.0]]),
                  weights=(-1.0, -1.0))
    arch2 = pareto_update(arch, better)
    filled2 = np.asarray(arch2.filled)
    assert filled2.sum() == 1
    np.testing.assert_allclose(np.asarray(arch2.fitness[0]), [0.5, 0.5])


def test_pareto_archive_dedups_equal_genomes():
    pop = _pop(jnp.array([[1.0, 1.0], [1.0, 1.0]]),
               genomes=jnp.array([[1.0], [1.0]]), weights=(-1.0, -1.0))
    arch = pareto_init(4, pop)
    arch = pareto_update(arch, pop)
    assert int(np.asarray(arch.filled).sum()) == 1
