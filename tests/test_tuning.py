"""Self-tuning dispatch runtime — the probe-and-persist contract.

The acceptance bar of ``deap_tpu/tuning``: probe winners round-trip
through the JSON cache across processes (the cache file itself staying
stdlib-readable), the invalidation ladder works (format stamp, jax
stamp, ``hlo_drift`` eviction), a warm cache replays the same decision
without re-probing, the env escape hatches override everything — and,
the load-bearing pin, tuned dispatch is **bit-identical** to every
forced-static dispatch at every decision point (nd_rank, the GP
interpreter mode, compaction, fused variation, CMA eigh, the
Scheduler's batched-vs-solo GP admission).
"""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from deap_tpu import ops, tuning
from deap_tpu.algorithms import evaluate_invalid, var_and
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.gp.loop import make_symbreg_loop, resolve_compaction
from deap_tpu.gp.pset import math_set
from deap_tpu.gp.tree import make_generator
from deap_tpu.mo.emo import _nd_static_auto, nd_rank
from deap_tpu.resilience.engine import ResilientRun
from deap_tpu.serving import GpJobSpec, Job, Scheduler
from deap_tpu.serving.tenant import bucket_key
from deap_tpu.strategies.cma import Strategy
from deap_tpu.telemetry.costs import ProgramObservatory
from deap_tpu.telemetry.journal import RunJournal, read_journal
from deap_tpu.tuning import DispatchTuner, TuningCache
from deap_tpu.tuning.cache import CACHE_FORMAT, FILENAME

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ML = 32
N = 24
P = 12


@pytest.fixture(autouse=True)
def _fresh_tuner(tmp_path, monkeypatch):
    """Every test gets a disabled tuner, a clean journal-dedup set, no
    ``DEAP_TPU_TUNE*`` environment, and a private cache directory."""
    for var in [v for v in os.environ if v.startswith("DEAP_TPU_TUNE")]:
        monkeypatch.delenv(var, raising=False)
    monkeypatch.setenv(tuning.cache.ENV_DIR, str(tmp_path / "tunecache"))
    tuning.tuner._reset_for_tests()
    yield
    tuning.tuner._reset_for_tests()


def _decisions(path, knob=None):
    rows = [r for r in read_journal(str(path))
            if r.get("kind") == "tuning_decision"]
    if knob is not None:
        rows = [r for r in rows if r.get("knob") == knob]
    return rows


def _entries():
    cache = TuningCache()
    cache.refresh()
    return cache.entries()


def _w(n=600, nobj=3, seed=0):
    return jax.random.normal(jax.random.key(seed), (n, nobj),
                             jnp.float32)


# ------------------------------------------------------ cache plumbing ----

def test_cache_roundtrip_across_processes(tmp_path):
    """A winner put by one process is read back by another — and the
    cache module stays importable (by file path) without deap_tpu or
    jax, the same stdlib-only contract the health report rides."""
    cdir = str(tmp_path / "xproc")
    parent = TuningCache(cdir)
    parent.put("cpu/cpu/nd_impl/3/1024", {
        "winner": "dc", "timings": {"dc": 0.001, "matrix": 0.002},
        "probe_s": 0.1, "identity": "bitwise", "program": "nd_rank",
        "stamp": {"format": CACHE_FORMAT, "jax": "x"},
    })
    cache_py = os.path.join(REPO, "deap_tpu", "tuning", "cache.py")
    code = (
        "import importlib.util, sys\n"
        f"spec = importlib.util.spec_from_file_location('_tc', "
        f"{cache_py!r})\n"
        "mod = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(mod)\n"
        f"cache = mod.TuningCache({cdir!r})\n"
        "entry = cache.get('cpu/cpu/nd_impl/3/1024')\n"
        "assert entry and entry['winner'] == 'dc', entry\n"
        "cache.put('cpu/cpu/gp_mode/64', {'winner': 'sweep'})\n"
        "assert 'jax' not in sys.modules\n"
        "assert 'deap_tpu' not in sys.modules\n"
        "print('child-ok')\n")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "child-ok" in r.stdout
    # the child's put merged with (not clobbered) the parent's entry
    parent.refresh()
    assert parent.get("cpu/cpu/gp_mode/64")["winner"] == "sweep"
    assert parent.get("cpu/cpu/nd_impl/3/1024")["winner"] == "dc"


def test_cache_stamp_and_format_invalidation(tmp_path):
    cdir = str(tmp_path / "stamps")
    cache = TuningCache(cdir)
    stamp = {"format": CACHE_FORMAT, "jax": jax.__version__}
    cache.put("k", {"winner": "a", "stamp": stamp})
    assert cache.get("k", stamp=stamp)["winner"] == "a"
    # a jax upgrade misses every old entry
    assert cache.get("k", stamp={"format": CACHE_FORMAT,
                                 "jax": "other"}) is None
    # a cache-format bump discards the whole file
    with open(cache.path) as fh:
        doc = json.load(fh)
    doc["format"] = CACHE_FORMAT - 1
    with open(cache.path, "w") as fh:
        json.dump(doc, fh)
    fresh = TuningCache(cdir)
    assert fresh.entries() == {}
    # and a torn/garbage file reads as empty, never raises
    with open(cache.path, "w") as fh:
        fh.write("{not json")
    assert TuningCache(cdir).entries() == {}


# --------------------------------------------------- probe → persist ----

def test_nd_probe_persists_bit_identical_winner(tmp_path):
    """The headline ladder walk: nd_rank(impl='auto') under an active
    tuner probes the candidate impls, persists the measured winner, and
    the tuned ranks equal every forced-static impl bit for bit."""
    tuning.enable()
    w = _w()
    jpath = tmp_path / "run.jsonl"
    with RunJournal(str(jpath)):
        tuned = np.asarray(nd_rank(w))
    rows = _decisions(jpath, "nd_impl")
    assert len(rows) == 1 and rows[0]["source"] == "probe"
    assert rows[0]["identity"] == "bitwise"
    entries = _entries()
    key = [k for k in entries if "/nd_impl/" in k]
    assert len(key) == 1 and "/3/1024" in key[0]
    entry = entries[key[0]]
    assert entry["winner"] == rows[0]["winner"]
    assert entry["program"] == "nd_rank"
    assert set(entry["timings"]) >= {"matrix", "sweep", "dc"}
    for impl in ("matrix", "sweep", "dc"):
        np.testing.assert_array_equal(tuned,
                                      np.asarray(nd_rank(w, impl=impl)),
                                      err_msg=impl)


def test_warm_cache_replays_decision_without_reprobing(tmp_path):
    """Probe determinism: a second 'process' (fresh tuner session over
    the same cache dir) resolves the same winner from the cache — the
    journal says source='cache', and no new probe timings appear."""
    tuning.enable()
    w = _w()
    with RunJournal(str(tmp_path / "cold.jsonl")):
        cold = np.asarray(nd_rank(w))
    winner = _decisions(tmp_path / "cold.jsonl", "nd_impl")[0]["winner"]

    tuning.tuner._reset_for_tests()  # forget the session memo
    tuning.enable()
    jpath = tmp_path / "warm.jsonl"
    with RunJournal(str(jpath)):
        warm = np.asarray(nd_rank(w))
    rows = _decisions(jpath, "nd_impl")
    assert len(rows) == 1
    assert rows[0]["source"] == "cache" and rows[0]["cache_hit"]
    assert rows[0]["winner"] == winner
    np.testing.assert_array_equal(cold, warm)


def test_decision_journaled_once_per_key(tmp_path):
    tuning.enable()
    w = _w()
    jpath = tmp_path / "run.jsonl"
    with RunJournal(str(jpath)):
        nd_rank(w)
        nd_rank(w)  # session memo: no second probe, no second row
        nd_rank(_w(n=3000))  # a new shape bucket is a new decision
    rows = _decisions(jpath, "nd_impl")
    assert len(rows) == 2
    assert {r["bucket"] for r in rows} == {"3/1024", "3/4096"}


def test_tuner_off_is_bitwise_static(tmp_path):
    """No tuner, no env: the ladder bottoms out at the static default
    with no journal rows and no cache file — today's behaviour."""
    jpath = tmp_path / "run.jsonl"
    w = _w()
    with RunJournal(str(jpath)):
        auto = np.asarray(nd_rank(w))
    static = _nd_static_auto(600, 3, jax.default_backend())
    np.testing.assert_array_equal(auto,
                                  np.asarray(nd_rank(w, impl=static)))
    assert _decisions(jpath) == []
    assert not os.path.exists(os.path.join(
        os.environ[tuning.cache.ENV_DIR], FILENAME))


def test_under_jit_ladder_stops_at_cache(tmp_path):
    """Probing is impossible on tracers: under jit the tuner must not
    attempt to call candidates, and the static default flows through."""
    tuning.enable()
    w = _w(n=256)

    @jax.jit
    def ranked(x):
        return nd_rank(x)

    tuned = np.asarray(ranked(w))
    static = _nd_static_auto(256, 3, jax.default_backend())
    np.testing.assert_array_equal(
        tuned, np.asarray(nd_rank(w, impl=static)))
    # no probe ran, so nothing was persisted for the traced call
    assert not any("/nd_impl/" in k for k in _entries())


# -------------------------------------------------- env escape hatches ----

def test_env_override_wins_without_tuner(tmp_path, monkeypatch):
    monkeypatch.setenv("DEAP_TPU_TUNE_ND_IMPL", "matrix")
    w = _w()
    jpath = tmp_path / "run.jsonl"
    with RunJournal(str(jpath)):
        forced = np.asarray(nd_rank(w))
    np.testing.assert_array_equal(forced,
                                  np.asarray(nd_rank(w, impl="matrix")))
    rows = _decisions(jpath, "nd_impl")
    assert rows and rows[0]["source"] == "env"
    assert rows[0]["winner"] == "matrix"


def test_env_override_rejects_unknown_candidate(monkeypatch):
    monkeypatch.setenv("DEAP_TPU_TUNE_ND_IMPL", "warp_speed")
    with pytest.raises(ValueError, match="warp_speed"):
        nd_rank(_w(n=64))


def test_env_int_threshold_overrides(monkeypatch):
    # default ND_PREFIX_THRESHOLD=512 keeps n=64 nobj=4 on the matrix
    assert _nd_static_auto(64, 4, "cpu") == "matrix"
    monkeypatch.setenv("DEAP_TPU_TUNE_ND_PREFIX_THRESHOLD", "1")
    assert _nd_static_auto(64, 4, "cpu") == "dc"
    monkeypatch.setenv("DEAP_TPU_TUNE_ND_PREFIX_THRESHOLD", "junk")
    assert _nd_static_auto(64, 4, "cpu") == "matrix"


def test_segment_len_auto_env_and_fallbacks(tmp_path, monkeypatch):
    monkeypatch.setenv("DEAP_TPU_TUNE_SEGMENT_LEN", "7")
    res = ResilientRun(str(tmp_path / "ck1"), segment_len="auto")
    assert res.segment_len == 7
    # unparseable / non-positive env values fall back to the static 10
    monkeypatch.setenv("DEAP_TPU_TUNE_SEGMENT_LEN", "soon")
    assert ResilientRun(str(tmp_path / "ck2"),
                        segment_len="auto").segment_len == 10
    monkeypatch.setenv("DEAP_TPU_TUNE_SEGMENT_LEN", "0")
    assert ResilientRun(str(tmp_path / "ck3"),
                        segment_len="auto").segment_len == 10


def test_segment_len_auto_reads_cache_winner(tmp_path):
    """The cache/env-only integer knob: a winner recorded out of band
    (the ``bench.py --tuning`` path) steers ``segment_len='auto'``."""
    tuner = tuning.enable()
    tuner.record("segment_len", (), "25",
                 timings={"10": 0.002, "25": 0.001}, probe_s=0.1,
                 identity="bitwise", program="resilient_scan")
    assert ResilientRun(str(tmp_path / "ck"),
                        segment_len="auto").segment_len == 25
    assert Scheduler(str(tmp_path / "srv"), segment_len="auto",
                     max_lanes=1, telemetry=False,
                     metrics=False).segment_len == 25


# --------------------------------------------------------- invalidation ----

def test_hlo_drift_evicts_and_reprobes(tmp_path):
    tuning.enable()
    w = _w()
    j1 = tmp_path / "j1.jsonl"
    with RunJournal(str(j1)):
        nd_rank(w)
        assert any("/nd_impl/" in k for k in _entries())
        evicted = tuning.note_hlo_drift("nd_rank")
        assert evicted == 1
        assert not any("/nd_impl/" in k for k in _entries())
        nd_rank(w)  # the session memo was dropped too: re-probes
    rows = read_journal(str(j1))
    inval = [r for r in rows if r.get("kind") == "tuning_invalidation"]
    assert len(inval) == 1 and inval[0]["reason"] == "hlo_drift"
    assert "/nd_impl/" in inval[0]["key"]
    probes = [r for r in _decisions(j1, "nd_impl")
              if r["source"] == "probe"]
    assert len(probes) == 2
    # an unrelated program's drift evicts nothing
    assert tuning.note_hlo_drift("some_other_program") == 0


def test_observatory_drift_triggers_tuning_eviction(tmp_path):
    """End-to-end invalidation: the cost observatory seeing the same
    (label, signature) recompile to a different HLO must evict the
    tuning entries recorded against that program label."""
    tuner = tuning.enable()
    tuner.record("gp_mode", (64,), "scan",
                 timings={"scan": 0.001}, probe_s=0.1,
                 program="gp_interpreter")
    assert any("/gp_mode/" in k for k in _entries())
    x = jnp.ones(4, jnp.float32)
    lo1 = jax.jit(lambda v: v + 1).lower(x)
    lo2 = jax.jit(lambda v: v * 3 - v).lower(x)
    with ProgramObservatory() as obs:
        obs.record("gp_interpreter", lo1, lo1.compile(), 0.0,
                   signature=("sig",))
        obs.record("gp_interpreter", lo2, lo2.compile(), 0.0,
                   signature=("sig",))
    assert obs.drifts, "observatory did not flag the recompile"
    assert not any("/gp_mode/" in k for k in _entries())


# --------------------------------------- per-decision-point identity ----

def test_compaction_probe_matches_forced(tmp_path):
    tuning.enable()
    choice = resolve_compaction("auto", 512)
    assert choice in ("host", "device")
    entry = _entries().get(
        tuning.DispatchTuner().key_for("compaction", ()))
    assert entry is not None and entry["winner"] == choice
    assert entry["identity"] == "bitwise"
    assert set(entry["timings"]) == {"host", "device"}


def test_eigh_auto_probes_with_tolerance_check(tmp_path):
    tuning.enable()
    auto = Strategy(np.zeros(8, np.float32), sigma=0.5,
                    eigh_impl="auto")
    assert auto.eigh_impl in ("lapack", "jacobi")
    entry = _entries().get(
        tuning.DispatchTuner().key_for("eigh_impl", (8,)))
    assert entry is not None and entry["winner"] == auto.eigh_impl
    # the two solvers are NOT bitwise-equal: the probe must have used
    # the reconstruction-residual tolerance check instead
    assert entry["identity"] == "tolerance"
    forced = Strategy(np.zeros(8, np.float32), sigma=0.5,
                      eigh_impl=auto.eigh_impl)
    ga = auto.generate(jax.random.key(5), auto.initial_state())
    gf = forced.generate(jax.random.key(5), forced.initial_state())
    np.testing.assert_array_equal(np.asarray(ga), np.asarray(gf))


def test_fused_variation_tuned_equals_unfused(tmp_path):
    tuning.enable()
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    pop = evaluate_invalid(
        init_population(jax.random.key(1), 64,
                        ops.bernoulli_genome(23), FitnessSpec((1.0,))),
        lambda g: g.sum(-1).astype(jnp.float32))
    key = jax.random.key(7)
    jpath = tmp_path / "run.jsonl"
    with RunJournal(str(jpath)):
        tuned = var_and(key, pop, tb, 0.5, 0.2)  # fused='auto'
    unfused = var_and(key, pop, tb, 0.5, 0.2, fused=False)
    for a, b in zip(jax.tree_util.tree_leaves(tuned),
                    jax.tree_util.tree_leaves(unfused)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    rows = _decisions(jpath, "fused")
    assert len(rows) == 1 and rows[0]["source"] == "probe"
    assert rows[0]["identity"] == "bitwise"
    assert rows[0]["winner"] in ("unfused", "fused_xla")


def test_gp_mode_auto_loop_bit_identity(tmp_path):
    """make_symbreg_loop(mode='auto') under a tuner: the mode probe
    races the interpreters, and the resulting loop is bit-identical to
    the same loop built with the winner forced."""
    tuning.enable(reps=1)
    pset = math_set(n_args=1)
    X = np.linspace(-1, 1, P).reshape(P, 1).astype(np.float32)
    y = (X[:, 0] ** 2 + X[:, 0]).astype(np.float32)
    jpath = tmp_path / "run.jsonl"
    with RunJournal(str(jpath)):
        run_auto = make_symbreg_loop(pset, ML, X, y, mode="auto")
    rows = _decisions(jpath, "gp_mode")
    assert len(rows) == 1 and rows[0]["source"] == "probe"
    winner = rows[0]["winner"]
    assert winner in ("scan", "sweep", "grouped")
    run_forced = make_symbreg_loop(pset, ML, X, y, mode=winner)
    gen = make_generator(pset, ML, 1, 3, "full")
    genomes = jax.vmap(gen)(jax.random.split(jax.random.key(3), N))
    res_a = run_auto(jax.random.key(11), genomes, 2)
    res_f = run_forced(jax.random.key(11), genomes, 2)
    for k in ("genomes", "fitness", "best_genome"):
        for a, b in zip(jax.tree_util.tree_leaves(res_a[k]),
                        jax.tree_util.tree_leaves(res_f[k])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=k)
    assert res_a["best_fitness"] == res_f["best_fitness"]


# ------------------------------------------------- scheduler admission ----

def _gp_job(pset, X, y, tenant="t0", seed=2, ngen=4):
    gen = make_generator(pset, ML, 1, 3, "full")
    founders = jax.vmap(gen)(jax.random.split(jax.random.key(seed), N))
    return Job(tenant_id=tenant, family="gp", toolbox=None,
               key=jax.random.key(seed), init=founders, ngen=ngen,
               hyper={"cxpb": 0.5, "mutpb": 0.2},
               spec=GpJobSpec(pset=pset, max_len=ML, X=X, y=y))


def test_scheduler_admission_probe_and_solo_routing(tmp_path):
    """The headline consumer: a cached 'solo' winner routes the bucket
    through max_lanes=1 (the autoscaler's actuator), and a live probe
    measures + persists a winner at first bucket creation."""
    pset = math_set(n_args=1)
    X = np.linspace(-1, 1, P).reshape(P, 1).astype(np.float32)
    y = (X[:, 0] ** 2 + X[:, 0]).astype(np.float32)

    # (a) cache-driven routing, no probe cost: pre-seed winner 'solo'
    tuner = tuning.enable()
    job = _gp_job(pset, X, y)
    bkey = bucket_key(job)
    tuner.record("gp_batch",
                 (str(bkey[0]), str(bkey[1])[:16], 4, 3), "solo",
                 timings={"solo": 0.001, "batched": 0.005},
                 probe_s=0.1, program="seeded")
    # fresh session over the same cache dir, so the scheduler's
    # decision walks the (journaled) cache rung, not the session memo
    tuning.tuner._reset_for_tests()
    tuning.enable()
    sched = Scheduler(str(tmp_path / "solo"), max_lanes=4,
                      segment_len=3, telemetry=False, metrics=False)
    sched.submit(job)
    bucket = sched.buckets[bkey]
    assert bucket.max_lanes == 1
    results = sched.run()
    assert set(results) == {"t0"}
    rows = read_journal(os.path.join(str(tmp_path / "solo"),
                                     "journal.jsonl"))
    routed = [r for r in rows if r.get("kind") == "tuned_admission"]
    assert routed and routed[0]["max_lanes"] == 1
    cached = [r for r in rows if r.get("kind") == "tuning_decision"
              and r.get("knob") == "gp_batch"]
    assert cached and cached[0]["source"] == "cache"

    # (b) live probe on a fresh key: different segment_len → new
    # bucket coordinate → the probe actually runs and persists
    tuning.tuner._reset_for_tests()
    tuning.enable(reps=1)
    sched2 = Scheduler(str(tmp_path / "probe"), max_lanes=4,
                       segment_len=2, telemetry=False, metrics=False)
    sched2.submit(_gp_job(pset, X, y, tenant="t1"))
    rows2 = read_journal(os.path.join(str(tmp_path / "probe"),
                                      "journal.jsonl"))
    probed = [r for r in rows2 if r.get("kind") == "tuning_decision"
              and r.get("knob") == "gp_batch"]
    assert len(probed) == 1 and probed[0]["source"] == "probe"
    assert probed[0]["identity"] == "bitwise"
    assert set(probed[0]["timings"]) == {"batched", "solo"}
    bucket2 = sched2.buckets[bucket_key(_gp_job(pset, X, y))]
    expect = 1 if probed[0]["winner"] == "solo" else 4
    assert bucket2.max_lanes == expect
    assert set(sched2.run()) == {"t1"}


def test_scheduler_no_tuner_no_probe(tmp_path):
    """Tuner off: admission must not journal, probe, or touch lanes."""
    pset = math_set(n_args=1)
    X = np.linspace(-1, 1, P).reshape(P, 1).astype(np.float32)
    y = (X[:, 0] ** 2 + X[:, 0]).astype(np.float32)
    sched = Scheduler(str(tmp_path / "off"), max_lanes=4,
                      segment_len=3, telemetry=False, metrics=False)
    sched.submit(_gp_job(pset, X, y))
    rows = read_journal(os.path.join(str(tmp_path / "off"),
                                     "journal.jsonl"))
    assert not [r for r in rows
                if r.get("kind") in ("tuning_decision",
                                      "tuned_admission")]
    assert next(iter(sched.buckets.values())).max_lanes == 4


# ------------------------------------------------------- health ledger ----

def test_health_report_renders_tuning_ledger(tmp_path):
    jpath = str(tmp_path / "run.jsonl")
    with RunJournal(jpath) as j:
        j.event("tuning_decision", knob="nd_impl", bucket="3/1024",
                source="probe", winner="dc", default="matrix",
                cache_hit=False, probe_s=0.21, identity="bitwise",
                timings={"dc": 0.001, "matrix": 0.004},
                program="nd_rank")
        j.event("tuning_decision", knob="nd_impl", bucket="3/1024",
                source="cache", winner="dc", default="matrix",
                cache_hit=True, program="nd_rank")
        j.event("tuning_decision", knob="fused", bucket="var_and/64",
                source="static", winner="unfused", default="fused_xla",
                cache_hit=False, identity="failed", reason="identity",
                program="var_and")
        j.event("tuning_invalidation", key="cpu/cpu/gp_mode/64",
                program="gp_interpreter", reason="hlo_drift")
    code = (
        "import sys, runpy\n"
        f"sys.argv = ['bench_report.py', '--health', {jpath!r}]\n"
        f"runpy.run_path({os.path.join(REPO, 'bench_report.py')!r}, "
        "run_name='__main__')\n"
        "assert 'jax' not in sys.modules, 'ledger imported jax'\n")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    out = r.stdout
    assert "Tuning ledger" in out
    assert "nd_impl" in out and "dc" in out
    assert "identity check" in out  # the failed-identity warning
    assert "drift eviction" in out and "gp_mode" in out
