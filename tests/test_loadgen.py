"""Load observatory — traffic models, SLO curves, attribution.

The acceptance bar of ``deap_tpu/serving/loadgen.py`` +
``deap_tpu/telemetry/slo.py`` (ISSUE 17): schedules are byte-identical
functions of (model, seed); journal replay reconstructs a recorded
arrival process (speed-scaled, ``ngen`` preserved); windowed curves
compute exact per-window rates/percentiles with ``None`` for empty
windows; gates journal ``slo_gate`` rows and trip on the worst window;
regression attribution names the phase that actually regressed. Plus
the live pins: a real loopback loadgen run whose non-abandoned digests
match the in-process Scheduler, record→replay pacing fidelity, an
injected ``segment``-seam stall attributed to the ``segment`` phase,
the ``SLO_JOURNAL_KINDS`` doc drift gate, and the no-jax standalone
loads of ``slo.py``/``loadgen.py``.
"""

import json
import os
import subprocess
import sys
import tempfile

import pytest

from deap_tpu.serving import (
    DiurnalTraffic,
    EvolutionService,
    ParetoMixTraffic,
    PoissonTraffic,
    Scheduler,
    Schedule,
    ServiceClient,
    ThunderingHerd,
    run_schedule,
    schedule_from_journal,
)
from deap_tpu.serving.loadgen import replay_fidelity
from deap_tpu.serving.wire import result_digest
from deap_tpu.telemetry.metrics import MetricsRegistry
from deap_tpu.telemetry.slo import (
    DEFAULT_SLOS,
    SLO_JOURNAL_KINDS,
    SloSpec,
    attribute_regression,
    evaluate_gates,
    exact_quantile,
    windowed_curve,
)

from test_service import PROBLEMS

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------ schedule contract ----

def test_schedule_same_seed_byte_identical():
    model = PoissonTraffic(rate_per_s=5.0, problem="onemax",
                           params={"ngen": 4}, n=25,
                           abandon_frac=0.3, abandon_range=(0.1, 0.5))
    a = model.schedule(seed=11).to_jsonl()
    b = model.schedule(seed=11).to_jsonl()
    assert a == b                       # bytes, not just semantics
    c = model.schedule(seed=12).to_jsonl()
    assert a != c
    # and the text round-trips losslessly
    sched = Schedule.from_jsonl(a)
    assert sched.to_jsonl() == a
    assert sched.seed == 11 and len(sched.arrivals) == 25


def test_traffic_model_shapes():
    # Poisson: monotone offsets, mean inter-arrival ~ 1/rate
    po = PoissonTraffic(rate_per_s=100.0, problem="p",
                        n=400).schedule(seed=0)
    ts = [a.t for a in po.arrivals]
    assert ts == sorted(ts)
    mean_gap = ts[-1] / len(ts)
    assert 0.005 < mean_gap < 0.02      # ~0.01s at 100/s

    # diurnal: arrivals cluster at the sinusoid's crest (mid-period),
    # thin out at the trough (period boundaries)
    di = DiurnalTraffic(base_rate=2.0, peak_rate=60.0, period_s=1.0,
                        problem="p", n=300).schedule(seed=3)
    phases = [a.t % 1.0 for a in di.arrivals]
    crest = sum(1 for p in phases if 0.25 <= p < 0.75)
    assert crest > 2 * (len(phases) - crest)

    # pareto mix: ngen in [min, cap], heavy tail actually present,
    # families drawn from the mix
    mix = [("ea", "onemax", {"pop": 8}, 3.0),
           ("cma", "sphere", {"sigma": 0.5}, 1.0)]
    pa = ParetoMixTraffic(rate_per_s=50.0, mix=mix, alpha=1.1,
                          ngen_min=4, ngen_cap=64,
                          n=300).schedule(seed=5)
    ngens = [a.params["ngen"] for a in pa.arrivals]
    assert all(4 <= g <= 64 for g in ngens)
    assert max(ngens) == 64             # the whale hit the cap
    fams = {a.family for a in pa.arrivals}
    assert fams == {"ea", "cma"}
    probs = {a.problem for a in pa.arrivals}
    assert probs == {"onemax", "sphere"}

    # herd: one jittered burst, every arrival storm-flagged
    he = ThunderingHerd(at_s=1.0, jitter_s=0.1, problem="p",
                        n=50).schedule(seed=7)
    assert all(a.storm for a in he.arrivals)
    assert all(1.0 <= a.t <= 1.1 for a in he.arrivals)

    # abandonment draws land inside the configured range
    ab = PoissonTraffic(rate_per_s=10.0, problem="p", n=200,
                        abandon_frac=0.5,
                        abandon_range=(0.2, 0.4)).schedule(seed=9)
    drawn = [a.abandon_after_s for a in ab.arrivals
             if a.abandon_after_s is not None]
    assert 40 < len(drawn) < 160        # ~half at frac=0.5
    assert all(0.2 <= d <= 0.4 for d in drawn)


def test_schedule_from_journal_speed_and_ngen():
    rows = [
        {"t": 10.0, "kind": "job_submitted", "tenant_id": "a",
         "family": "ea_simple", "ngen": 6},
        {"t": 12.0, "kind": "other", "tenant_id": "x"},
        {"t": 14.0, "kind": "job_submitted", "tenant_id": "b",
         "family": "ea_simple", "ngen": 40},
    ]
    sched = schedule_from_journal(rows, "onemax",
                                  params={"pop": 8}, speed=2.0)
    assert sched.model == "replay"
    assert [a.t for a in sched.arrivals] == [0.0, 2.0]  # 4s gap / 2
    assert [a.tenant_id for a in sched.arrivals] == ["rp-a", "rp-b"]
    assert [a.params["ngen"] for a in sched.arrivals] == [6, 40]
    assert all(a.params["pop"] == 8 for a in sched.arrivals)
    assert schedule_from_journal([], "onemax").arrivals == ()


# ------------------------------------------------------- SLO curves ----

def test_exact_quantile_nearest_rank():
    xs = list(range(1, 101))
    assert exact_quantile(xs, 0.5) == 50
    assert exact_quantile(xs, 0.99) == 99
    assert exact_quantile(xs, 1.0) == 100
    assert exact_quantile([7.0], 0.99) == 7.0
    assert exact_quantile([], 0.99) is None


def test_windowed_curve_rates_and_percentiles():
    rows = [
        # window 0: 2 arrivals, 1 shed (2 jobs), one 0.5s admission
        {"t": 0.1, "kind": "job_submitted", "tenant_id": "a"},
        {"t": 0.2, "kind": "job_submitted", "tenant_id": "b"},
        {"t": 0.3, "kind": "load_shed", "new": 2},
        {"t": 0.4, "kind": "tenant_admitted", "wait_s": 0.5},
        # window 1: empty
        # window 2: a resume wait, a segment, a deadline miss
        {"t": 2.1, "kind": "tenant_resumed", "wait_s": 2.0},
        {"t": 2.2, "kind": "slo", "segment_s": 0.25},
        {"t": 2.3, "kind": "deadline_exceeded", "tenant_id": "c"},
    ]
    curve = windowed_curve(rows, window_s=1.0)
    assert len(curve) == 3
    w0, w1, w2 = curve
    assert w0["arrivals"] == 2 and w0["sheds"] == 2
    assert w0["arrival_rate"] == 2.0
    assert w0["shed_rate"] == 0.5       # 2 shed of 4 offered
    assert w0["admission_p99"] == 0.5
    assert w0["queue_wait_p99"] == 0.5
    assert w0["segment_p99"] is None    # no data ≠ 0 seconds
    assert w1["arrivals"] == 0 and w1["admission_p99"] is None
    assert w2["admission_p99"] is None  # resumes aren't admissions
    assert w2["queue_wait_p99"] == 2.0  # but they are queue waits
    assert w2["segment_p99"] == 0.25
    assert w2["deadline_misses"] == 1
    with pytest.raises(ValueError):
        windowed_curve(rows, window_s=0.0)
    assert windowed_curve([]) == []


def test_slo_spec_gates_and_journaling(tmp_path):
    from deap_tpu.telemetry.journal import RunJournal, read_journal

    curve = [{"segment_p99": None}, {"segment_p99": 0.2},
             {"segment_p99": 5.0}]
    spec = SloSpec("seg", "segment_p99", 1.0)
    gate = spec.check(curve)
    assert gate["worst"] == 5.0 and gate["ok"] is False
    assert SloSpec("seg", "segment_p99", 6.0).check(curve)["ok"]
    # all-empty windows: absence of evidence passes the gate
    assert spec.check([{"segment_p99": None}])["ok"] is True
    with pytest.raises(ValueError):
        SloSpec("bad", "not_a_metric", 1.0)

    jpath = tmp_path / "j.jsonl"
    journal = RunJournal(str(jpath))
    gates = evaluate_gates(curve, (spec,), journal=journal,
                           model="poisson")
    journal.close()
    assert len(gates) == 1 and gates[0]["ok"] is False
    rows = [r for r in read_journal(str(jpath))
            if r.get("kind") == "slo_gate"]
    assert len(rows) == 1
    assert rows[0]["slo"] == "seg" and rows[0]["model"] == "poisson"
    assert rows[0]["ok"] is False
    assert len(DEFAULT_SLOS) == 5       # the committed default set


def test_attribute_regression_names_the_phase():
    def spans(phase_s):
        rows = []
        for tid in range(10):
            rows.append({"t": float(tid), "kind": "job_submitted",
                         "tenant_id": f"t{tid}"})
            for name, phase, dur in phase_s:
                rows.append({"kind": "trace_span", "name": name,
                             "phase": phase, "dur_s": dur,
                             "tenant_id": f"t{tid}"})
            rows.append({"t": tid + 1.0 + phase_s[-1][2],
                         "kind": "tenant_finished",
                         "tenant_id": f"t{tid}"})
        return rows

    base = spans([("request", "frontend", 0.01),
                  ("segment", "device", 0.1)])
    probe = spans([("request", "frontend", 0.01),
                   ("segment", "device", 1.1)])
    att = attribute_regression(base, probe)
    assert att["top_phase"] == "segment"
    assert abs(att["top_delta_s"] - 1.0) < 1e-6
    assert abs(att["end_to_end_delta"] - 1.0) < 1e-6
    by_phase = {r["phase"]: r for r in att["phases"]}
    assert by_phase["frontend"]["delta_s"] == 0.0
    assert by_phase["segment"]["n_base"] == 10
    # nothing regressed → no culprit named, not a tiny-noise winner
    att0 = attribute_regression(base, base)
    assert att0["top_phase"] is None


# ---------------------------------------------------- doc drift gate ----

def test_slo_journal_kinds_documented():
    """Same drift gate as SERVICE_JOURNAL_KINDS: every kind the SLO
    plane writes must appear as `kind` in the telemetry doc."""
    doc = os.path.join(REPO, "docs", "advanced", "telemetry.md")
    with open(doc) as fh:
        text = fh.read()
    assert SLO_JOURNAL_KINDS            # the gate must gate something
    for kind in SLO_JOURNAL_KINDS:
        assert f"`{kind}`" in text, (
            f"journal kind {kind!r} undocumented in "
            "docs/advanced/telemetry.md")


def test_slo_and_loadgen_import_without_jax():
    """Curve math and schedule generation must run on a no-jax box
    (laptop triage, CI scrapers) — both modules load standalone with
    jax never entering sys.modules."""
    slo_py = os.path.join(REPO, "deap_tpu", "telemetry", "slo.py")
    lg_py = os.path.join(REPO, "deap_tpu", "serving", "loadgen.py")
    code = (
        "import importlib.util, sys\n"
        "def load(name, path):\n"
        "    spec = importlib.util.spec_from_file_location(name, path)\n"
        "    mod = importlib.util.module_from_spec(spec)\n"
        "    sys.modules[name] = mod\n"
        "    spec.loader.exec_module(mod)\n"
        "    return mod\n"
        f"slo = load('slo_sa', {slo_py!r})\n"
        f"lg = load('loadgen_sa', {lg_py!r})\n"
        "sched = lg.PoissonTraffic(rate_per_s=10.0, problem='p',"
        " n=5).schedule(seed=1)\n"
        "assert len(sched.arrivals) == 5\n"
        "curve = slo.windowed_curve([{'t': 0.1, 'kind':"
        " 'job_submitted', 'tenant_id': 'a'}])\n"
        "assert curve[0]['arrivals'] == 1\n"
        "assert 'jax' not in sys.modules, 'jax leaked in'\n"
        "print('OK')\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


# ------------------------------------------------------- live (slow) ----

def _live_service(root, **kw):
    kw.setdefault("max_lanes", 4)
    kw.setdefault("segment_len", 2)
    kw.setdefault("metrics", MetricsRegistry())
    kw.setdefault("max_poll_s", 2.0)
    return EvolutionService(str(root), PROBLEMS, **kw)


def test_loadgen_live_run_digests_and_replay(tmp_path):
    """The end-to-end pin: an open-loop Poisson run with abandonment
    against a real loopback service — non-abandoned results
    bit-identical to the Scheduler in-process, abandonments surface
    without wedging anything, the run journals ``loadgen_run``, and
    the journal replays with faithful pacing."""
    model = PoissonTraffic(rate_per_s=8.0, problem="onemax",
                           params={"ngen": 6}, n=10,
                           abandon_frac=0.2,
                           abandon_range=(0.1, 0.4))
    sched = model.schedule(seed=7)
    n_abandoners = sum(1 for a in sched.arrivals
                       if a.abandon_after_s is not None)
    assert 0 < n_abandoners < len(sched.arrivals)

    with _live_service(tmp_path / "svc") as svc:
        jpath = svc.journal.path
        rep = run_schedule(sched, svc.url,
                           max_workers=len(sched.arrivals),
                           poll_timeout_s=120.0, journal=svc.journal)
    counts = rep.counts
    assert counts.get("abandoned") == n_abandoners
    assert counts.get("finished") == len(sched.arrivals) - n_abandoners

    # bit-identity over the non-abandoned overlap set
    with Scheduler(str(tmp_path / "ref"), max_lanes=4,
                   segment_len=2) as s:
        for a in sched.arrivals:
            s.submit(PROBLEMS[a.problem](a.tenant_id, a.params))
        ref = {tid: result_digest(r) for tid, r in s.run().items()}
    got = rep.digests()
    assert got and all(ref[tid] == d for tid, d in got.items())

    rows = [json.loads(ln) for ln in open(jpath) if ln.strip()]
    lg = [r for r in rows if r.get("kind") == "loadgen_run"]
    assert len(lg) == 1
    assert lg[0]["model"] == "poisson"
    assert lg[0]["n_arrivals"] == len(sched.arrivals)

    # the journal's arrival process replays: reconstruct + re-run at
    # 2x on a fresh service; pacing error bounded, recorded ngen kept
    rsched = schedule_from_journal(jpath, "onemax",
                                   params={"ngen": 6}, speed=2.0)
    assert len(rsched.arrivals) == len(sched.arrivals)
    with _live_service(tmp_path / "svc2") as svc2:
        rrep = run_schedule(rsched, svc2.url,
                            max_workers=len(rsched.arrivals),
                            poll_timeout_s=120.0)
    fid = replay_fidelity(rsched, rrep.results)
    assert fid["n"] == len(rsched.arrivals)
    assert fid["max_abs_err_s"] <= 0.5
    assert rrep.counts.get("finished") == len(rsched.arrivals)


def test_loadgen_restart_drill(tmp_path):
    """ISSUE 18: a :class:`RestartPlan` kills-and-restarts the service
    mid-schedule. Workers that die into the outage park on the ready
    event and re-offer once (tenant id = idempotency key), every job
    still lands, and the report carries the restart marks +
    time-to-first-result-after-restart — the client-observed mirror of
    the service's own ``first_result`` startup phase."""
    from deap_tpu.serving.loadgen import RestartPlan

    model = PoissonTraffic(rate_per_s=50.0, problem="onemax",
                           params={"ngen": 6}, n=6)
    sched = model.schedule(seed=11)
    root = tmp_path / "svc"
    svc1 = _live_service(root)
    later = []

    def _restart() -> str:
        svc1.close()
        svc2 = _live_service(root)   # same root: WAL + checkpoints
        later.append(svc2)
        return svc2.url

    class _J:
        rows: list = []

        def event(self, kind, **kw):
            self.rows.append({"kind": kind, **kw})

    j = _J()
    try:
        rep = run_schedule(sched, svc1.url,
                           max_workers=len(sched.arrivals),
                           poll_timeout_s=120.0,
                           restart=RestartPlan(at_s=1.0,
                                               restart=_restart),
                           journal=j)
    finally:
        svc1.close()
        for s in later:
            s.close()
    assert later, "restart never fired"
    assert rep.counts.get("finished") == len(sched.arrivals), rep.counts
    assert rep.restart_t is not None
    assert rep.restart_ready_t is not None
    assert rep.restart_ready_t >= rep.restart_t
    assert rep.time_to_first_result_after_restart_s is not None
    assert rep.time_to_first_result_after_restart_s >= 0.0
    lg = [r for r in j.rows if r["kind"] == "loadgen_run"]
    assert len(lg) == 1
    assert lg[0]["restart_t"] == rep.restart_t
    assert lg[0]["time_to_first_result_after_restart_s"] == \
        rep.time_to_first_result_after_restart_s


def test_loadgen_live_segment_attribution(tmp_path):
    """An injected in-segment stall (the ``segment`` fault seam) must
    come out of :func:`attribute_regression` named ``segment`` — the
    observatory's 'checkpoint phase +1.8s at p99' demo, live."""
    from deap_tpu.resilience.faultinject import DelaySegment, FaultPlan
    from deap_tpu.telemetry.journal import read_journal

    # The test must isolate the injected stall from two *real* (but
    # here unwanted) signals: the first segment of a fresh service
    # carries the jit compile inside its span (so each arm runs a
    # warmup tenant whose rows are filtered out — ngen=6 → 3 driver
    # steps, the stall is scheduled at step 5, after warmup), and a
    # tenant queued behind the wedged driver inherits the whole delay
    # as queue_wait (so every tenant gets its own lane). Burst all
    # arrivals up front so submits land before the stall, else
    # cmd.queue spans absorb it too.
    model = PoissonTraffic(rate_per_s=100.0, problem="onemax",
                           params={"ngen": 6}, n=6)
    sched = model.schedule(seed=3)

    def arm(root, faults=None):
        with _live_service(root, trace_sample=1.0, max_lanes=6,
                           fault_plan=faults) as svc:
            c = ServiceClient(svc.url)
            c.submit("onemax", params={"ngen": 6},
                     tenant_id="warmup")
            c.result("warmup", wait=True, timeout=120)
            run_schedule(sched, svc.url,
                         max_workers=len(sched.arrivals),
                         poll_timeout_s=120.0)
            rows = list(read_journal(svc.journal.path))
        return [r for r in rows if r.get("tenant_id") != "warmup"]

    base = arm(tmp_path / "base")
    probe = arm(tmp_path / "probe",
                faults=FaultPlan([DelaySegment(5, 5.0,
                                               event="segment")]))
    att = attribute_regression(base, probe)
    assert att["top_phase"] == "segment", att["phases"]
    assert att["top_delta_s"] >= 2.5
