"""Algorithm integration tests — quality-threshold style, the reference's
signature pattern (deap/tests/test_algorithms.py; SURVEY.md §4.1)."""

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.support import hof_best
from deap_tpu.support.stats import fitness_stats


def onemax_toolbox(length=60):
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.05)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def test_ea_simple_solves_onemax():
    # reference config: README.md:74-104 (pop=300, cxpb=.5, mutpb=.2, ngen=40)
    length = 60
    tb = onemax_toolbox(length)
    key = jax.random.key(64)
    pop = init_population(
        jax.random.key(1), 300, ops.bernoulli_genome(length), FitnessSpec((1.0,)))
    stats = fitness_stats()
    pop, logbook, hof = algorithms.ea_simple(
        key, pop, tb, cxpb=0.5, mutpb=0.2, ngen=40, stats=stats,
        halloffame_size=3)
    best_g, best_f = hof_best(hof)
    assert float(best_f[0]) >= 0.95 * length
    assert float(best_f[0]) == float(np.asarray(best_g).sum())
    # logbook sanity: gen 0..40, nevals full at gen 0
    assert len(logbook) == 41
    assert logbook[0]["nevals"] == 300
    gens = logbook.select("gen")
    assert gens == list(range(41))
    maxes = logbook.select("max")
    assert maxes[-1] >= maxes[0]
    text = logbook.stream
    assert "gen" in text.splitlines()[0] and len(text.splitlines()) == 42


def test_ea_simple_nevals_counts_touched_only():
    tb = onemax_toolbox(20)
    pop = init_population(
        jax.random.key(2), 100, ops.bernoulli_genome(20), FitnessSpec((1.0,)))
    _, logbook, _ = algorithms.ea_simple(
        jax.random.key(0), pop, tb, cxpb=0.0, mutpb=0.0, ngen=3)
    # no variation → nothing ever re-evaluated after gen 0
    assert logbook.select("nevals")[1:] == [0, 0, 0]


def test_ea_mu_plus_lambda_monotone_best():
    # elitist (mu+lambda) never loses the best individual
    length = 40
    tb = onemax_toolbox(length)
    pop = init_population(
        jax.random.key(3), 100, ops.bernoulli_genome(length), FitnessSpec((1.0,)))
    stats = fitness_stats()
    pop, logbook, _ = algorithms.ea_mu_plus_lambda(
        jax.random.key(4), pop, tb, mu=100, lambda_=200, cxpb=0.4, mutpb=0.4,
        ngen=25, stats=stats)
    maxes = logbook.select("max")
    assert all(b >= a - 1e-6 for a, b in zip(maxes, maxes[1:]))
    assert maxes[-1] >= 0.9 * length


def test_ea_mu_comma_lambda_runs():
    length = 30
    tb = onemax_toolbox(length)
    pop = init_population(
        jax.random.key(5), 50, ops.bernoulli_genome(length), FitnessSpec((1.0,)))
    pop, logbook, hof = algorithms.ea_mu_comma_lambda(
        jax.random.key(6), pop, tb, mu=50, lambda_=100, cxpb=0.3, mutpb=0.5,
        ngen=15, halloffame_size=1)
    _, best_f = hof_best(hof)
    assert float(best_f[0]) >= 0.8 * length
    assert pop.size == 50


def test_var_or_reproduction_keeps_fitness():
    tb = onemax_toolbox(16)
    pop = init_population(
        jax.random.key(7), 64, ops.bernoulli_genome(16), FitnessSpec((1.0,)))
    pop = algorithms.evaluate_invalid(pop, tb.evaluate)
    # all reproduction: children must carry valid parent fitness
    off = algorithms.var_or(jax.random.key(8), pop, tb, 64, cxpb=0.0, mutpb=0.0)
    assert bool(off.valid.all())
    # all crossover: every child invalid
    off = algorithms.var_or(jax.random.key(9), pop, tb, 64, cxpb=1.0, mutpb=0.0)
    assert not bool(off.valid.any())


def test_var_and_invalidates_touched():
    tb = onemax_toolbox(16)
    pop = init_population(
        jax.random.key(10), 64, ops.bernoulli_genome(16), FitnessSpec((1.0,)))
    pop = algorithms.evaluate_invalid(pop, tb.evaluate)
    off = algorithms.var_and(jax.random.key(11), pop, tb, cxpb=1.0, mutpb=0.0)
    assert not bool(off.valid.any())
    off = algorithms.var_and(jax.random.key(12), pop, tb, cxpb=0.0, mutpb=0.0)
    assert bool(off.valid.all())


def test_ea_generate_update_ask_tell():
    # toy strategy: state = mean vector; generate = mean + noise;
    # update = mean of top half (a (mu/2, lambda) ES on sphere)
    spec = FitnessSpec((-1.0,))
    dim, lam = 8, 64

    def generate(key, state):
        return state[None, :] + 0.3 * jax.random.normal(key, (lam, dim))

    def update(state, genomes, values):
        order = jnp.argsort(values[:, 0])
        return genomes[order[: lam // 8]].mean(0)

    tb = Toolbox()
    tb.register("generate", generate)
    tb.register("update", update)
    tb.register("evaluate", lambda g: (g ** 2).sum(-1))

    state = jnp.full((dim,), 5.0)
    state, logbook, hof = algorithms.ea_generate_update(
        jax.random.key(13), state, tb, ngen=60, spec=spec, halloffame_size=1)
    assert float((state ** 2).sum()) < 0.5
    _, best = hof_best(hof)
    assert float(best[0]) < 0.5
    assert logbook.select("nevals")[0] == lam
