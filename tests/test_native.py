"""Native hypervolume extension: build, parity with the Python fallback,
and contribution semantics (the reference's graceful-fallback pattern,
deap/tools/indicator.py:3-8)."""

import numpy as np
import pytest

from deap_tpu.native.pyhv import hypervolume as py_hv

native = pytest.importorskip("deap_tpu.native.hv_binding")


@pytest.mark.parametrize("d", [2, 3, 4, 5])
def test_native_matches_python(d):
    rng = np.random.default_rng(d)
    pts = rng.uniform(0.0, 1.0, size=(24, d))
    ref = np.full(d, 1.1)
    assert native.hypervolume(pts, ref) == pytest.approx(
        py_hv(pts, ref), rel=1e-12)


def test_dominated_and_out_of_range_points_ignored():
    pts = np.array([[0.5, 0.5], [0.6, 0.6], [2.0, 0.1]])  # dominated + outside
    ref = np.array([1.0, 1.0])
    assert native.hypervolume(pts, ref) == pytest.approx(0.25)


def test_known_2d_value():
    # two staircase points: total = 0.5*0.5 + (1-0.8)*(0.5-0.2) rotated
    pts = np.array([[0.2, 0.8], [0.8, 0.2]])
    ref = np.array([1.0, 1.0])
    expected = (1 - 0.2) * (1 - 0.8) + (1 - 0.8) * (0.8 - 0.2)
    assert native.hypervolume(pts, ref) == pytest.approx(expected)


@pytest.mark.parametrize("d", [3, 4, 5])
def test_degenerate_fronts_match_python(d):
    """Duplicates, dominated rows, and tied coordinates exercise every
    equality branch of the staircase sweeps and the slicing recursion
    (the d<=3 paths skip the non-domination prefilter entirely)."""
    rng = np.random.default_rng(d + 100)
    base = rng.uniform(0.0, 1.0, size=(12, d))
    quant = np.round(base * 4) / 4          # heavy coordinate ties
    pts = np.concatenate([base, base[:5], quant])  # + exact duplicates
    ref = np.full(d, 1.1)
    assert native.hypervolume(pts, ref) == pytest.approx(
        py_hv(pts, ref), rel=1e-12)


@pytest.mark.parametrize("d", [3, 4])
def test_contributions_match_leave_one_out(d):
    """The direct clipped-box contribution formula must agree with
    literal leave-one-out recomputation, including zero rows for
    dominated and duplicated points."""
    rng = np.random.default_rng(d)
    pts = rng.uniform(0.0, 1.0, size=(20, d))
    pts = np.concatenate([pts, pts[:3]])    # duplicates -> 0 contrib
    ref = np.full(d, 1.1)
    contrib = native.hv_contributions(pts, ref)
    total = native.hypervolume(pts, ref)
    for i in range(len(pts)):
        excl = total - native.hypervolume(np.delete(pts, i, 0), ref)
        assert contrib[i] == pytest.approx(excl, rel=1e-9, abs=1e-12)
    assert np.allclose(contrib[20:], 0.0)


def test_contributions_sum_and_positivity():
    rng = np.random.default_rng(0)
    x = np.sort(rng.uniform(0, 1, 10))
    pts = np.stack([x, 1 - x], axis=1)  # non-dominated line
    ref = np.array([2.0, 2.0])
    contrib = native.hv_contributions(pts, ref)
    assert (contrib > 0).all()
    total = native.hypervolume(pts, ref)
    for i in range(10):
        excl = total - native.hypervolume(np.delete(pts, i, 0), ref)
        assert contrib[i] == pytest.approx(excl, rel=1e-12)


def test_d4_unfiltered_entry_parity_on_adversarial_fronts():
    """d=4 skips the O(n^2) non-domination prefilter since r5 (WFG's
    exclusive-volume chain telescopes dominated points to zero, and
    the fused sweep's pruned live set absorbs them) — so the
    dominance-rich, duplicate-heavy, and tie-grid cases must still
    match the Python fallback exactly."""
    rng = np.random.default_rng(9)
    ref = np.full(4, 1.1)
    cases = [
        rng.uniform(0.0, 1.0, size=(300, 4)),          # ~half dominated
        np.repeat(rng.uniform(0, 1, (50, 4)), 3, 0),   # heavy duplicates
        rng.integers(0, 4, (200, 4)) / 4.0,            # tie grid
        np.tile(rng.uniform(0, 1, (1, 4)), (20, 1)),   # all identical
    ]
    for pts in cases:
        assert native.hypervolume(pts, ref) == pytest.approx(
            py_hv(pts, ref), rel=1e-12)
