"""Serving SLO metrics registry + Prometheus exposition + /metrics.

Three contracts:

1. **Golden exposition format** — ``metrics_text()`` emits exactly the
   Prometheus text format (HELP/TYPE lines, label escaping, cumulative
   histogram buckets with the implicit ``+Inf``, ``_sum``/``_count``).
2. **Stdlib-only discipline** — ``telemetry/metrics.py`` must be
   loadable (and serve a scrape) without jax in ``sys.modules``, the
   same pin ``telemetry/report.py`` enforces: scraping a box must
   never initialise an XLA backend.
3. **The scheduler's SLO surface** — a real multi-tenant scheduler run
   exports queue depth, lane occupancy and per-tenant gens/s, and a
   live HTTP fetch of ``/metrics`` mid-run returns valid exposition
   text covering them (the ISSUE 9 acceptance pin).
"""

import os
import subprocess
import sys
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from deap_tpu import ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.telemetry.metrics import (DEFAULT_BUCKETS, MetricsRegistry,
                                        get_registry, metrics_text,
                                        resolve_registry, serve_metrics)

HERE = os.path.dirname(os.path.abspath(__file__))
METRICS_PATH = os.path.join(os.path.dirname(HERE), "deap_tpu",
                            "telemetry", "metrics.py")


# ------------------------------------------------------ golden format ----

def test_counter_gauge_golden_format():
    reg = MetricsRegistry()
    c = reg.counter("jobs_total", "jobs seen", labels=("bucket",))
    c.inc(bucket="a")
    c.inc(2, bucket="b")
    g = reg.gauge("queue_depth", "waiting jobs")
    g.set(3)
    assert reg.metrics_text() == (
        "# HELP jobs_total jobs seen\n"
        "# TYPE jobs_total counter\n"
        'jobs_total{bucket="a"} 1\n'
        'jobs_total{bucket="b"} 2\n'
        "# HELP queue_depth waiting jobs\n"
        "# TYPE queue_depth gauge\n"
        "queue_depth 3\n")


def test_histogram_golden_format():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    assert reg.metrics_text() == (
        "# HELP lat_seconds latency\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="1"} 2\n'
        'lat_seconds_bucket{le="+Inf"} 3\n'
        "lat_seconds_sum 5.55\n"
        "lat_seconds_count 3\n")


def test_label_escaping_and_validation():
    reg = MetricsRegistry()
    c = reg.counter("c_total", labels=("name",))
    c.inc(name='he said "hi"\nback\\slash')
    text = reg.metrics_text()
    assert r'he said \"hi\"\nback\\slash' in text
    with pytest.raises(ValueError):
        reg.counter("0bad")
    with pytest.raises(ValueError):
        reg.counter("bad-name")
    with pytest.raises(ValueError):
        c.inc(wrong_label="x")
    with pytest.raises(ValueError):
        c.inc(-1, name="x")


def test_registry_create_or_get_and_mismatch():
    reg = MetricsRegistry()
    a = reg.counter("n_total", labels=("k",))
    assert reg.counter("n_total", labels=("k",)) is a
    with pytest.raises(ValueError):
        reg.gauge("n_total", labels=("k",))
    with pytest.raises(ValueError):
        reg.counter("n_total", labels=("other",))


def test_histogram_quantile_and_values():
    reg = MetricsRegistry()
    h = reg.histogram("h", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 1.6, 3.0):
        h.observe(v)
    assert h.quantile(0.5) == 2.0
    assert h.quantile(1.0) == 4.0
    h.observe(100.0)
    assert h.quantile(1.0) == float("inf")
    c = reg.counter("c_total")
    assert c.value() == 0.0
    c.inc(3)
    assert c.value() == 3.0


def test_histogram_snapshot_delta_windowed_quantile():
    """Cumulative counts cannot give windowed percentiles — the
    snapshot/delta pair can: a window's quantile comes from the delta
    of its edge snapshots, not the all-time counts."""
    reg = MetricsRegistry()
    h = reg.histogram("w", buckets=(0.1, 0.25, 1.0), labels=("b",))
    h.observe(0.05, b="x")
    h.observe(0.05, b="x")
    s0 = h.snapshot(b="x")
    h.observe(0.2, b="x")
    h.observe(0.2, b="x")
    s1 = h.snapshot(b="x")
    win = s1.delta(s0)
    assert win.n == 2
    assert win.quantile(0.5) == 0.25       # window: only the 0.2s
    assert s1.quantile(0.5) == 0.1         # cumulative disagrees
    assert abs(win.mean() - 0.2) < 1e-9
    # unobserved label set → all-zero snapshot, quantile None
    empty = h.snapshot(b="never")
    assert empty.n == 0 and empty.quantile(0.99) is None
    assert h.label_sets() == [{"b": "x"}]
    # bucket-shape mismatch between snapshots is a hard error
    other = reg.histogram("w2", buckets=(1.0, 2.0))
    other.observe(0.5)
    with pytest.raises(ValueError):
        other.snapshot().delta(s0)
    # past the top bucket the quantile saturates at +Inf
    h.observe(50.0, b="x")
    assert h.snapshot(b="x").quantile(1.0) == float("inf")


def test_serving_wait_buckets_resolve_long_observations():
    """The bucket-boundary audit (ISSUE 17): DEFAULT_BUCKETS top out
    at 10 s, so every longer queue wait collapsed into +Inf — the
    serving overrides must pin >10 s observations to a finite
    bucket."""
    from deap_tpu.telemetry.metrics import (SERVING_PHASE_BUCKETS,
                                            SERVING_SEGMENT_BUCKETS,
                                            SERVING_WAIT_BUCKETS)
    reg = MetricsRegistry()
    h = reg.histogram("wait_s", buckets=SERVING_WAIT_BUCKETS)
    h.observe(14.2)
    assert h.quantile(0.99) == 15.0        # finite, not +Inf
    assert h.quantile(0.99) != float("inf")
    for bs in (SERVING_WAIT_BUCKETS, SERVING_SEGMENT_BUCKETS,
               SERVING_PHASE_BUCKETS):
        assert list(bs) == sorted(bs)
        assert max(bs) >= 120.0


def test_histogram_redeclare_bucket_mismatch_raises():
    """Re-declaring a histogram with different buckets silently kept
    the first shape before the audit; now it is a hard error — two
    call sites disagreeing on boundaries is a bug, not a preference."""
    reg = MetricsRegistry()
    reg.histogram("lat_s", buckets=(0.1, 1.0))
    assert reg.histogram("lat_s", buckets=(1.0, 0.1)) is not None
    with pytest.raises(ValueError):
        reg.histogram("lat_s", buckets=(0.1, 2.0))


def test_resolve_registry_convention():
    reg = MetricsRegistry()
    assert resolve_registry(None) is None
    assert resolve_registry(False) is None
    assert resolve_registry(True) is get_registry()
    assert resolve_registry(reg) is reg
    with pytest.raises(TypeError):
        resolve_registry("nope")


def test_default_buckets_sorted():
    assert list(DEFAULT_BUCKETS) == sorted(DEFAULT_BUCKETS)


# ------------------------------------------------------- HTTP endpoint ----

def test_serve_metrics_http_roundtrip():
    reg = MetricsRegistry()
    reg.gauge("up", "server liveness").set(1)
    with serve_metrics(reg) as srv:
        req = urllib.request.urlopen(srv.url, timeout=5)
        body = req.read().decode()
        assert req.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")
        assert "up 1" in body
        # non-/metrics paths 404
        bad = srv.url.replace("/metrics", "/nope")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bad, timeout=5)


# -------------------------------------------------------- no-jax pin ----

def test_metrics_module_needs_no_jax():
    """metrics.py loaded standalone must serve a scrape with jax never
    imported — the report.py stdlib-only discipline."""
    code = (
        "import importlib.util, sys, urllib.request\n"
        f"spec = importlib.util.spec_from_file_location('m', "
        f"{METRICS_PATH!r})\n"
        "m = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(m)\n"
        "reg = m.MetricsRegistry()\n"
        "reg.counter('a_total').inc()\n"
        "srv = m.serve_metrics(reg)\n"
        "body = urllib.request.urlopen(srv.url, timeout=5)"
        ".read().decode()\n"
        "srv.close()\n"
        "assert 'a_total 1' in body, body\n"
        "assert 'jax' not in sys.modules, 'metrics imported jax'\n"
        "print('OK')\n")
    out = subprocess.run([sys.executable, "-c", code],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


# ------------------------------------------- scheduler SLO acceptance ----

def _toolbox():
    tb = Toolbox()
    tb.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=0.1)
    tb.register("select", ops.sel_tournament, tournsize=3)
    return tb


def test_scheduler_exports_slo_metrics(tmp_path):
    """The acceptance pin: during a contended scheduler run a
    curl-equivalent fetch of /metrics returns valid Prometheus text
    covering queue depth, lane occupancy and per-tenant gens/s; the
    journal carries one `slo` sample per boundary."""
    from deap_tpu.serving import Job, Scheduler
    from deap_tpu.telemetry import read_journal

    tb = _toolbox()
    jobs = []
    for i in range(4):
        pop = init_population(jax.random.key(i), 16,
                              ops.bernoulli_genome(12),
                              FitnessSpec((1.0,)))
        jobs.append(Job(tenant_id=f"t{i}", family="ea_simple",
                        toolbox=tb, key=jax.random.key(100 + i),
                        init=pop, ngen=6,
                        hyper={"cxpb": 0.5, "mutpb": 0.2},
                        program="onemax"))

    reg = MetricsRegistry()
    with Scheduler(str(tmp_path), max_lanes=2, segment_len=3,
                   fair_quantum=1, metrics=reg) as sched:
        srv = sched.serve_metrics()
        for j in jobs:
            sched.submit(j)
        # mid-run scrape: contention (4 tenants, 2 lanes) is live
        sched.step()
        sched.step()
        body = urllib.request.urlopen(srv.url, timeout=5).read().decode()
        results = sched.run()

    assert set(results) == {j.tenant_id for j in jobs}
    for family, needle in (
            ("gauge", "deap_serving_queue_depth{bucket="),
            ("gauge", "deap_serving_lane_occupancy{bucket="),
            ("gauge", "deap_serving_tenant_gens_per_sec{tenant_id="),
            ("histogram", "deap_serving_queue_wait_seconds_bucket"),
            ("histogram", "deap_serving_segment_seconds_sum"),
            ("counter", "deap_serving_admissions_total")):
        assert needle in body, (family, needle, body)
    # exposition sanity: every non-comment line is "name[{labels}] value"
    for ln in body.splitlines():
        if not ln or ln.startswith("#"):
            continue
        name_part, _, value = ln.rpartition(" ")
        assert name_part and value
        float(value)  # parses

    # eviction pressure showed up in the counters (quantum=1, 4>2)
    assert reg.counter("deap_serving_evictions_total",
                       labels=("bucket",)).value(
        bucket="ea_simple:onemax") > 0
    # per-boundary SLO samples landed in the journal
    slos = [e for e in read_journal(str(tmp_path / "journal.jsonl"))
            if e.get("kind") == "slo"]
    assert slos
    for e in slos:
        assert {"bucket", "queue_depth", "occupancy", "residents",
                "lanes", "gens_advanced"} <= set(e)
        assert "segment_s" in e and "gens_per_sec" in e


def test_scheduler_metrics_disabled(tmp_path):
    """metrics=None runs clean with no instruments and refuses to
    serve."""
    from deap_tpu.serving import Job, Scheduler

    tb = _toolbox()
    pop = init_population(jax.random.key(0), 16,
                          ops.bernoulli_genome(12), FitnessSpec((1.0,)))
    job = Job(tenant_id="t0", family="ea_simple", toolbox=tb,
              key=jax.random.key(1), init=pop, ngen=4,
              hyper={"cxpb": 0.5, "mutpb": 0.2}, program="onemax")
    with Scheduler(str(tmp_path), max_lanes=2, segment_len=2,
                   metrics=None) as sched:
        sched.submit(job)
        results = sched.run()
        assert set(results) == {"t0"}
        with pytest.raises(ValueError):
            sched.serve_metrics()
