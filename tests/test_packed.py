"""Bit-packed genome ops (ops.packed): pack/unpack round trip, word-mask
crossover, per-bit-exact mutation, SWAR popcount, and the fused packed
kernel's invariants (Pallas interpreter on the CPU test platform)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu.ops import packed as pk


def test_pack_unpack_roundtrip():
    for L in (1, 31, 32, 33, 100, 256):
        bits = jax.random.bernoulli(jax.random.key(L), 0.5, (7, L))
        p = pk.pack_genomes(bits)
        assert p.dtype == jnp.uint32 and p.shape == (7, -(-L // 32))
        np.testing.assert_array_equal(
            np.asarray(pk.unpack_genomes(p, L)), np.asarray(bits))


def test_popcount_and_fitness():
    bits = jax.random.bernoulli(jax.random.key(0), 0.3, (50, 100))
    p = pk.pack_genomes(bits)
    np.testing.assert_array_equal(
        np.asarray(pk.packed_fitness(p)),
        np.asarray(bits.sum(-1).astype(jnp.float32)))


def test_segment_mask_words():
    W, L = 4, 100
    m = pk.segment_mask_words(jnp.int32(10), jnp.int32(70), W)
    bits = np.asarray(pk.unpack_genomes(m[None, :], W * 32))[0]
    want = (np.arange(W * 32) >= 10) & (np.arange(W * 32) < 70)
    np.testing.assert_array_equal(bits, want)
    # degenerate empty segment
    m0 = pk.segment_mask_words(jnp.int32(5), jnp.int32(5), W)
    assert not np.asarray(pk.unpack_genomes(m0[None, :], W * 32)).any()


def test_cx_two_point_packed_matches_unpacked_structure():
    L = 100
    b1 = jax.random.bernoulli(jax.random.key(1), 0.5, (L,))
    b2 = jax.random.bernoulli(jax.random.key(2), 0.5, (L,))
    g1, g2 = pk.pack_genomes(b1[None])[0], pk.pack_genomes(b2[None])[0]
    c1, c2 = pk.cx_two_point_packed(jax.random.key(3), g1, g2, L)
    u1 = np.asarray(pk.unpack_genomes(c1[None], L))[0]
    u2 = np.asarray(pk.unpack_genomes(c2[None], L))[0]
    a, b = np.asarray(b1), np.asarray(b2)
    d = u1 != a
    assert (np.where(d, b, a) == u1).all()
    assert (np.where(d, a, b) == u2).all()
    # swapped genes form one contiguous segment among differing columns
    diff = np.flatnonzero((a != b) & d)
    if diff.size:
        lo, hi = diff[0], diff[-1]
        assert (d[lo : hi + 1] == (a != b)[lo : hi + 1]).all()


def test_mut_flip_bit_packed_rate_and_tail():
    L, n = 100, 2048
    g = jnp.zeros((n, pk.words_for(L)), jnp.uint32)
    flipped = jax.vmap(
        lambda k, row: pk.mut_flip_bit_packed(k, row, 0.05, L)
    )(jax.random.split(jax.random.key(4), n), g)
    bits = np.asarray(pk.unpack_genomes(flipped, L))
    rate = bits.mean()
    assert 0.04 < rate < 0.06
    # tail bits beyond L stay zero (pack invariant preserved)
    full = np.asarray(flipped)
    tail_mask = ~np.asarray(pk.pack_genomes(jnp.ones((1, L)))[0])
    assert (full & tail_mask).sum() == 0


def _fused(key, packed, L, cxpb, mutpb, indpb):
    return pk.fused_variation_eval_packed(
        key, packed, L, cxpb=cxpb, mutpb=mutpb, indpb=indpb,
        prng="input", block_i=64)


def test_fused_packed_identity_and_fitness():
    L = 100
    bits = jax.random.bernoulli(jax.random.key(5), 0.5, (130, L))
    g = pk.pack_genomes(bits)
    c, f = _fused(jax.random.key(0), g, L, 0.0, 0.0, 0.05)
    np.testing.assert_array_equal(np.asarray(c), np.asarray(g))
    np.testing.assert_allclose(np.asarray(f), np.asarray(bits.sum(-1)))


def test_fused_packed_crossover_structure():
    L = 100
    bits = jax.random.bernoulli(jax.random.key(6), 0.5, (128, L))
    g = pk.pack_genomes(bits)
    c, f = _fused(jax.random.key(1), g, L, 1.0, 0.0, 0.0)
    u = np.asarray(pk.unpack_genomes(c, L))
    gb = np.asarray(bits)
    some_swap = False
    for p in range(64):
        a, b = gb[2 * p], gb[2 * p + 1]
        ca, cb = u[2 * p], u[2 * p + 1]
        d = ca != a
        assert (np.where(d, b, a) == ca).all()
        assert (np.where(d, a, b) == cb).all()
        diff = np.flatnonzero((a != b) & d)
        if diff.size:
            some_swap = True
            lo, hi = diff[0], diff[-1]
            assert (d[lo : hi + 1] == (a != b)[lo : hi + 1]).all()
    assert some_swap
    np.testing.assert_allclose(np.asarray(f), u.sum(-1))


def test_fused_packed_full_flip_and_odd_row():
    L = 100
    bits = jax.random.bernoulli(jax.random.key(7), 0.5, (129, L))
    g = pk.pack_genomes(bits)
    c, _ = _fused(jax.random.key(2), g, L, 0.0, 1.0, 1.0)
    np.testing.assert_array_equal(
        np.asarray(pk.unpack_genomes(c, L)), ~np.asarray(bits))
    # odd last row never mates
    c2, _ = _fused(jax.random.key(3), g, L, 1.0, 0.0, 0.0)
    np.testing.assert_array_equal(np.asarray(c2[128]), np.asarray(g[128]))


def test_fused_packed_flip_rate():
    L = 128
    g = jnp.zeros((1024, pk.words_for(L)), jnp.uint32)
    c, _ = _fused(jax.random.key(4), g, L, 0.0, 1.0, 0.05)
    rate = np.asarray(pk.unpack_genomes(c, L)).mean()
    assert 0.04 < rate < 0.06


def test_fused_packed_hw_rejected_off_tpu():
    g = jnp.zeros((8, 4), jnp.uint32)
    with pytest.raises(ValueError, match="hw"):
        pk.fused_variation_eval_packed(
            jax.random.key(0), g, 100, cxpb=0.5, mutpb=0.2, indpb=0.05,
            prng="hw", interpret=True)


def test_selgather_exact_vs_numpy():
    """Bits-path selection+gather reproduces the tournament exactly:
    explicit draw stream, winners recomputed in numpy (first-drawn wins
    ties, like the reference's max())."""
    n, L = 37, 70
    bits = jax.random.bernoulli(jax.random.key(11), 0.5, (n, L))
    g = pk.pack_genomes(bits)
    fit = pk.packed_fitness(g)
    key = jax.random.key(5)
    parents = pk.sel_tournament_gather_packed(
        key, g, fit, tournsize=3, prng="input", interpret=True)
    assert parents.shape == g.shape and parents.dtype == jnp.uint32

    ni = -(-n // 128) * 128
    draws = np.asarray(jax.random.bits(key, (3, ni), jnp.uint32))
    fit_np = np.asarray(fit)
    g_np = np.asarray(g)
    for i in range(n):
        aspirants = (draws[:, i] % np.uint32(n)).astype(np.int64)
        best = aspirants[0]
        for a in aspirants[1:]:
            if fit_np[a] > fit_np[best]:
                best = a
        np.testing.assert_array_equal(np.asarray(parents[i]), g_np[best],
                                      err_msg=f"row {i}")


def test_selgather_selection_pressure_and_membership():
    n, L = 300, 100
    bits = jax.random.bernoulli(jax.random.key(3), 0.5, (n, L))
    g = pk.pack_genomes(bits)
    fit = pk.packed_fitness(g)
    parents = pk.sel_tournament_gather_packed(
        jax.random.key(9), g, fit, tournsize=3, prng="input",
        interpret=True)
    # every output row is a population member
    pop_set = {bytes(np.asarray(r).tobytes()) for r in np.asarray(g)}
    for r in np.asarray(parents):
        assert bytes(r.tobytes()) in pop_set
    # min-of-3 tournament raises mean fitness
    assert float(pk.packed_fitness(parents).mean()) > float(fit.mean())


def test_selgather_hw_rejected_off_tpu():
    g = jnp.zeros((8, 4), jnp.uint32)
    with pytest.raises(ValueError, match="hw"):
        pk.sel_tournament_gather_packed(
            jax.random.key(0), g, jnp.zeros(8), prng="hw", interpret=True)


# ------------------------------------------------ whole-GA mega-kernel ----

def test_evolve_packed_selection_only_membership():
    """cxpb=0, mutpb=0: each generation's children are EXACT copies of
    tournament winners, so after G generations every row is a member of
    the original population and mean fitness is non-decreasing."""
    n, L = 256, 100
    bits = jax.random.bernoulli(jax.random.key(0), 0.5, (n, L))
    g = pk.pack_genomes(bits)
    fit = pk.packed_fitness(g)
    pop2, fit2 = pk.evolve_packed(
        jax.random.key(1), g, fit, L, 3, cxpb=0.0, mutpb=0.0,
        indpb=0.05, prng="input", chunk=128, interpret=True)
    pop_set = {bytes(np.asarray(r).tobytes()) for r in np.asarray(g)}
    for r in np.asarray(pop2):
        assert bytes(r.tobytes()) in pop_set
    np.testing.assert_array_equal(np.asarray(pk.packed_fitness(pop2)),
                                  np.asarray(fit2))
    assert float(fit2.mean()) >= float(fit.mean())


def test_evolve_packed_crossover_conserves_pair_totals():
    """cxpb=1, mutpb=0, tournsize=1 from a half-zeros/half-ones
    population: two-point swap conserves each pair's total gene count
    (every pair mixes one all-zeros with one all-ones parent only when
    the tournament draws them; totals must stay in [0, 2L] and equal
    the parents' sum per pair)."""
    n, L = 256, 100
    W = pk.words_for(L)
    ones_row = pk.pack_genomes(jnp.ones((1, L)))[0]
    g = jnp.where((jnp.arange(n) % 2 == 0)[:, None],
                  jnp.zeros((W,), jnp.uint32), ones_row)
    fit = pk.packed_fitness(g)
    pop2, fit2 = pk.evolve_packed(
        jax.random.key(2), g, fit, L, 1, tournsize=1, cxpb=1.0,
        mutpb=0.0, indpb=0.05, prng="input", chunk=128, interpret=True)
    f = np.asarray(fit2)
    assert ((f >= 0) & (f <= L)).all()
    # two-point swap conserves each adjacent pair's combined popcount;
    # with tournsize=1 parents are uniform draws of 0- or L-rows, so
    # every pair total must be 0, L, or 2L
    tot = f[0::2] + f[1::2]
    assert set(np.unique(tot)).issubset({0.0, float(L), float(2 * L)})


def test_evolve_packed_flip_rate():
    """cxpb=0, mutpb=1, tournsize=1 over an all-zeros population: the
    per-gene flip rate over one generation is Bernoulli(indpb)."""
    n, L, indpb = 512, 100, 0.05
    W = pk.words_for(L)
    g = jnp.zeros((n, W), jnp.uint32)
    fit = pk.packed_fitness(g)
    _, fit2 = pk.evolve_packed(
        jax.random.key(3), g, fit, L, 1, tournsize=1, cxpb=0.0,
        mutpb=1.0, indpb=indpb, prng="input", chunk=128, interpret=True)
    rate = float(np.asarray(fit2).sum()) / (n * L)
    sigma = (indpb * (1 - indpb) / (n * L)) ** 0.5
    assert abs(rate - indpb) < 4 * sigma, rate


def test_evolve_packed_improves_onemax():
    """Full GA config over several generations climbs OneMax and the
    returned fitness column matches the returned population."""
    n, L = 512, 100
    bits = jax.random.bernoulli(jax.random.key(4), 0.5, (n, L))
    g = pk.pack_genomes(bits)
    fit = pk.packed_fitness(g)
    pop2, fit2 = pk.evolve_packed(
        jax.random.key(5), g, fit, L, 6, cxpb=0.5, mutpb=0.2,
        indpb=0.05, prng="input", chunk=128, interpret=True)
    assert float(fit2.mean()) > float(fit.mean()) + 3.0
    np.testing.assert_array_equal(np.asarray(pk.packed_fitness(pop2)),
                                  np.asarray(fit2))


def test_evolve_packed_pad_lanes_inert():
    """n not a multiple of the lane chunk: padding lanes must never be
    selected into the real population (draws are % n)."""
    n, L = 200, 64  # pads to 256 with chunk=128
    bits = jax.random.bernoulli(jax.random.key(6), 0.5, (n, L))
    g = pk.pack_genomes(bits)
    fit = pk.packed_fitness(g)
    pop2, fit2 = pk.evolve_packed(
        jax.random.key(7), g, fit, L, 2, cxpb=0.0, mutpb=0.0,
        indpb=0.05, prng="input", chunk=128, interpret=True)
    pop_set = {bytes(np.asarray(r).tobytes()) for r in np.asarray(g)}
    for r in np.asarray(pop2):
        assert bytes(r.tobytes()) in pop_set


def test_evolve_packed_hw_rejected_off_tpu():
    g = jnp.zeros((8, 4), jnp.uint32)
    with pytest.raises(ValueError, match="hw"):
        pk.evolve_packed(jax.random.key(0), g, jnp.zeros(8), 100, 1,
                         cxpb=0.5, mutpb=0.2, indpb=0.05, prng="hw",
                         interpret=True)


def test_evolve_packed_bits_vmem_guard():
    # off-interpreter, the 'input' path materialises (ngen, 32W, N)
    # draws as VMEM-resident inputs — must fail fast with a clear
    # message instead of an opaque Mosaic allocation error
    g = jnp.zeros((4096, 4), jnp.uint32)
    with pytest.raises(ValueError, match="VMEM-resident"):
        pk.evolve_packed(jax.random.key(0), g, jnp.zeros(4096), 128,
                         200, cxpb=0.5, mutpb=0.2, indpb=0.05,
                         prng="input", interpret=False)
