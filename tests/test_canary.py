"""Known-answer canary tenants — the bit-identity contract live.

The acceptance bar of ``deap_tpu/serving/canary.py`` (ISSUE 19): a
fixed-seed canary rides the REAL front end (auth, WAL, command queue,
scheduler, wire encode) at a boundary cadence, an idle service
bootstraps its own first canary from the driver's idle loop, a clean
run journals ``canary_ok`` rows and ZERO alert transitions, and an
injected silent wrong answer (``CorruptResult`` — the failure class
nothing else can see, since the corrupted job still journals success
and returns HTTP 200) is detected within two segment boundaries:
``canary_failed`` row, ``canary`` HealthMonitor alarm,
``deap_alarms_total``/``deap_alert_state`` on ``/metrics``, a firing
``canary_failure`` alert at ``/v1/alerts`` and ``/healthz`` flipping
to ``degraded`` (503) with the new detail body."""

import json
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.resilience.faultinject import (CorruptResult, FaultPlan,
                                             InjectedCorruption,
                                             corrupt_pytree)
from deap_tpu.serving.canary import (CANARY_JOURNAL_KINDS,
                                     CanaryRunner, CanarySpec)
from deap_tpu.serving.service import (SERVICE_JOURNAL_KINDS,
                                      EvolutionService)
from deap_tpu.serving.tenant import Job
from deap_tpu.telemetry.journal import read_journal
from deap_tpu.telemetry.metrics import MetricsRegistry
from deap_tpu.telemetry.probes import HealthMonitor

_TB = Toolbox()
_TB.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
_TB.register("mate", ops.cx_two_point)
_TB.register("mutate", ops.mut_flip_bit, indpb=0.1)
_TB.register("select", ops.sel_tournament, tournsize=3)


def _onemax_job(tid, params):
    seed = int(params.get("seed", 0))
    pop = init_population(jax.random.key(seed), 16,
                          ops.bernoulli_genome(12), FitnessSpec((1.0,)))
    return Job(tenant_id=tid, family="ea_simple", toolbox=_TB,
               key=jax.random.key(1000 + seed), init=pop,
               ngen=int(params.get("ngen", 4)),
               hyper={"cxpb": 0.5, "mutpb": 0.2}, program="onemax")


PROBLEMS = {"onemax": _onemax_job}
SPEC = dict(problem="onemax", params={"seed": 7, "ngen": 4})


def _get(url):
    try:
        with urllib.request.urlopen(url) as r:
            return r.status, json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode())


def _wait(pred, timeout=120.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ------------------------------------------------------------ units ----

def test_journal_kinds_registered():
    # the drift gate over docs/advanced/telemetry.md covers these via
    # SERVICE_JOURNAL_KINDS — canary kinds must ride it
    for kind in CANARY_JOURNAL_KINDS:
        assert kind in SERVICE_JOURNAL_KINDS
    assert "alert" in SERVICE_JOURNAL_KINDS
    assert "canary" in HealthMonitor.ALARM_KINDS


def test_corrupt_result_fault_targets_tenant_substring():
    f = CorruptResult(tenant_substr="canary-2", times=1)
    f.fire("result", tenant_id="canary-1")       # wrong tenant
    f.fire("submit", tenant_id="canary-2")       # wrong event
    with pytest.raises(InjectedCorruption):
        f.fire("result", tenant_id="canary-2")
    f.fire("result", tenant_id="canary-2")       # budget spent


def test_corrupt_pytree_changes_bytes_once():
    tree = {"a": np.array([np.nan, np.inf, 1.5]),
            "b": np.arange(4, dtype=np.int8)}
    out = corrupt_pytree(tree)
    # exactly one leaf damaged, and damaged even though it leads with
    # NaN (byte-flip, not arithmetic)
    assert out["a"].tobytes() != tree["a"].tobytes()
    assert out["b"].tobytes() == tree["b"].tobytes()
    assert out["a"].dtype == tree["a"].dtype
    assert out["a"].shape == tree["a"].shape
    # nothing corruptible → unchanged
    empty = {"s": "text", "n": None}
    assert corrupt_pytree(empty) == empty


def test_spec_defaults_and_runner_snapshot():
    spec = CanarySpec("onemax")
    assert spec.cadence_boundaries == 20 and spec.max_in_flight == 1
    assert CanarySpec("x", cadence_boundaries=0).cadence_boundaries == 1
    r = CanaryRunner(CanarySpec("onemax", expected_digest="abc"))
    assert r.reference == "abc"
    snap = r.snapshot()
    assert snap == {"submitted": 0, "ok": 0, "failed": 0, "shed": 0,
                    "in_flight": 0, "reference": "abc"}


# ------------------------------------------------------- e2e: clean ----

def test_clean_run_idle_bootstrap_zero_alerts(tmp_path):
    """An idle service (no client traffic at all) primes its own
    first canary from the driver loop; the canary chain then
    self-sustains at the boundary cadence; TOFU learns the reference
    and every later canary matches — zero alert rows, zero failures,
    /healthz stays ok and carries the new detail body."""
    reg = MetricsRegistry()
    with EvolutionService(str(tmp_path), PROBLEMS, port=0,
                          segment_len=2, metrics=reg,
                          canary=CanarySpec(**SPEC,
                                            cadence_boundaries=1)
                          ) as svc:
        assert _wait(lambda: svc.canary.ok >= 3), svc.canary.snapshot()
        assert svc.canary.failed == 0
        assert svc.canary.reference        # learned trust-on-first-use
        assert svc.alerts.firing() == []

        code, body = _get(svc.url + "/healthz")
        assert code == 200 and body["status"] == "ok"
        # the detail body contract (satellite b) — the old status
        # string stays, everything else is additive
        assert set(body) >= {"status", "jobs", "problems", "watchdog",
                             "warming", "startup_phases",
                             "seconds_since_boundary", "steps",
                             "firing_alerts", "canary"}
        assert body["watchdog"] == {"enabled": False, "budget_s": None,
                                    "stalled": False}
        assert body["warming"]["active"] is False
        assert body["seconds_since_boundary"] is not None
        assert body["firing_alerts"] == []
        assert body["canary"]["ok"] >= 3
        assert body["canary"]["reference"] == svc.canary.reference

        code, body = _get(svc.url + "/v1/alerts")
        assert code == 200
        assert body["firing"] == []
        states = {a["name"]: a["state"] for a in body["alerts"]}
        assert states["canary_failure"] == "inactive"

    rows = read_journal(str(tmp_path / "journal.jsonl"))
    oks = [r for r in rows if r.get("kind") == "canary_ok"]
    assert len(oks) >= 3
    assert oks[0].get("learned") is True       # auditable TOFU
    assert all("digest" in r and "request_id" in r for r in oks)
    assert not [r for r in rows if r.get("kind") == "canary_failed"]
    # the determinism headline: ZERO alert transitions on a clean run
    assert not [r for r in rows if r.get("kind") == "alert"]


# -------------------------------------------------- e2e: corruption ----

def test_injected_corruption_detected_within_two_boundaries(tmp_path):
    """CorruptResult on the second canary (the first learns the clean
    TOFU reference): the full detection chain within two segment
    boundaries of the corrupted canary completing."""
    reg = MetricsRegistry()
    health = HealthMonitor()
    plan = FaultPlan([CorruptResult(tenant_substr="canary-2")])
    with EvolutionService(str(tmp_path), PROBLEMS, port=0,
                          segment_len=2, metrics=reg, health=health,
                          fault_plan=plan,
                          canary=CanarySpec(**SPEC,
                                            cadence_boundaries=1)
                          ) as svc:
        assert _wait(lambda: svc.canary.failed >= 1), \
            svc.canary.snapshot()
        # later canaries keep passing — corruption was one-shot
        before = svc.canary.ok
        assert _wait(lambda: svc.canary.ok >= before + 1)

        # the alarm fired
        kinds = [a["alarm"] for a in health.alarms]
        assert "canary" in kinds
        alarm = next(a for a in health.alarms
                     if a["alarm"] == "canary")
        assert alarm["tenant_id"] == "canary-2"
        assert alarm["reason"] == "digest_mismatch"
        assert alarm["expected"] != alarm["got"]

        # the alert is firing at /v1/alerts
        code, body = _get(svc.url + "/v1/alerts")
        assert code == 200
        assert "canary_failure" in body["firing"]

        # /healthz degrades (503) but keeps the status-string contract
        code, body = _get(svc.url + "/healthz")
        assert code == 503 and body["status"] == "degraded"
        assert body["firing_alerts"] == ["canary_failure"]
        assert body["canary"]["failed"] == 1

        # both new metric families are scrapeable
        with urllib.request.urlopen(svc.url + "/metrics") as r:
            text = r.read().decode()
        assert 'deap_alarms_total{kind="canary"} 1' in text
        assert 'deap_alert_state{name="canary_failure"} 2' in text

    rows = read_journal(str(tmp_path / "journal.jsonl"))
    fails = [r for r in rows if r.get("kind") == "canary_failed"]
    assert len(fails) == 1
    fail = fails[0]
    assert fail["tenant_id"] == "canary-2"
    assert fail["reason"] == "digest_mismatch"
    assert fail["expected"] != fail["got"]

    # detection latency: the firing alert row lands within two
    # boundary (`slo`) rows of the canary_failed row — the bench's
    # ≤ 2 boundary gate, pinned structurally
    idx_fail = rows.index(fail)
    firing = [i for i, r in enumerate(rows)
              if r.get("kind") == "alert" and r.get("state") == "firing"
              and r.get("name") == "canary_failure"]
    assert firing, "canary_failure never fired in the journal"
    between = [r for r in rows[idx_fail:firing[0]]
               if r.get("kind") == "slo"]
    assert len(between) <= 2, (idx_fail, firing, between)


def test_canary_rejected_submission_counts_as_shed(tmp_path):
    """A front end that refuses the canary (unknown problem → 404) is
    a shed beat, not a failure — an overloaded or misconfigured
    service must not page through the bit-identity alarm."""
    with EvolutionService(str(tmp_path), PROBLEMS, port=0,
                          segment_len=2,
                          canary=CanarySpec("no-such-problem",
                                            cadence_boundaries=1)
                          ) as svc:
        assert _wait(lambda: svc.canary.shed >= 1, timeout=30), \
            svc.canary.snapshot()
        assert svc.canary.failed == 0
        assert svc.canary.ok == 0
    rows = read_journal(str(tmp_path / "journal.jsonl"))
    assert not [r for r in rows
                if r.get("kind") in CANARY_JOURNAL_KINDS]
