"""Penalty-decorator tests (reference: deap/tools/constraint.py,
tutorial doc/tutorials/advanced/constraints.rst)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu import benchmarks
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.ops.constraint import closest_valid_penalty, delta_penalty


SPEC = FitnessSpec((-1.0,))


def feasible(g):
    # feasible region: all coordinates within [-1, 1]
    return jnp.all(jnp.abs(g) <= 1.0, axis=-1)


def project(g):
    return jnp.clip(g, -1.0, 1.0)


def distance(g):
    return jnp.sum((g - project(g)) ** 2, axis=-1)


def test_delta_penalty_valid_rows_untouched():
    evaluate = delta_penalty(feasible, 1e4, spec=SPEC)(
        jax.vmap(benchmarks.sphere))
    g = jnp.array([[0.5, 0.5], [3.0, 0.0]])
    vals = evaluate(g)
    assert vals[0, 0] == pytest.approx(0.5)
    assert vals[1, 0] == pytest.approx(1e4)


def test_delta_penalty_distance_grows_with_violation():
    evaluate = delta_penalty(feasible, 1e4, distance, spec=SPEC)(
        jax.vmap(benchmarks.sphere))
    g = jnp.array([[2.0, 0.0], [4.0, 0.0]])
    vals = evaluate(g)
    # minimisation: penalty = delta + distance (Δ_i − w_i·d, w = −1)
    assert vals[0, 0] == pytest.approx(1e4 + 1.0)
    assert vals[1, 0] == pytest.approx(1e4 + 9.0)
    assert vals[1, 0] > vals[0, 0]


def test_closest_valid_penalty():
    evaluate = closest_valid_penalty(
        feasible, project, alpha=2.0,
        distance=lambda v, g: jnp.sum((v - g) ** 2, -1), spec=SPEC)(
        jax.vmap(benchmarks.sphere))
    g = jnp.array([[0.25, 0.25], [3.0, 0.0]])
    vals = evaluate(g)
    assert vals[0, 0] == pytest.approx(0.125)
    # projected (1,0): f=1; + alpha*d = 2*(2^2) = 8 → 9
    assert vals[1, 0] == pytest.approx(1.0 + 2.0 * 4.0)


def test_decorate_seam_on_toolbox():
    """The Toolbox.decorate composition seam (base.py:100-122) applies
    penalties exactly like the reference tutorial."""
    tb = Toolbox()
    tb.register("evaluate", jax.vmap(benchmarks.sphere))
    tb.decorate("evaluate", delta_penalty(feasible, 7.0, spec=SPEC))
    vals = tb.evaluate(jnp.array([[0.1, 0.1], [5.0, 5.0]]))
    assert vals[0, 0] == pytest.approx(0.02)
    assert vals[1, 0] == pytest.approx(7.0)
