"""Fault-tolerant service plane — WAL, idempotency, deadlines, load
shedding, watchdog, and the kill -9 chaos pin (ISSUE 12).

Two tiers in one module:

- **fast**: the admission-WAL unit surface (CRC framing, torn-tail
  self-heal), the overload pin (bounded queues shed with 429 +
  Retry-After — never hang, never 500 — and a retrying client
  converges), the deadline pin (expired commands are dropped with 504
  and never reach the scheduler), the watchdog (stall detected,
  journaled with a stack, alarmed, ``/healthz`` 503, re-armed; opt-in
  exit escalation), dropped-response idempotent retries, WAL replay on
  restart, request-id tracing and the long-poll ``timeout=`` hardening.
- **chaos** (``-m chaos``, slow tier): a real ``kill -9`` of a service
  subprocess mid-run under live concurrent retrying client load,
  restart over the same root, and the acceptance pin — zero lost jobs,
  every tenant's wire digest bit-identical to an uninterrupted
  in-process run; plus checkpoint-corruption fallback during a
  service-restart resume.
"""

import http.client
import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from deap_tpu import ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.resilience import (
    DelaySegment,
    DropResponse,
    FaultPlan,
    RetryPolicy,
    TornWAL,
    corrupt_file,
)
from deap_tpu.serving import (
    AdmissionWAL,
    EvolutionService,
    Job,
    Scheduler,
    ServiceClient,
    ServiceError,
    scan_wal,
)
from deap_tpu.serving import migration
from deap_tpu.serving.wire import result_digest
from deap_tpu.support.checkpoint import Checkpointer
from deap_tpu.telemetry import read_journal
from deap_tpu.telemetry.metrics import MetricsRegistry
from deap_tpu.telemetry.probes import HealthMonitor

_TB = Toolbox()
_TB.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
_TB.register("mate", ops.cx_two_point)
_TB.register("mutate", ops.mut_flip_bit, indpb=0.1)
_TB.register("select", ops.sel_tournament, tournsize=3)


def _onemax_job(tid, params):
    seed = int(params.get("seed", 0))
    pop = init_population(jax.random.key(seed), 16,
                          ops.bernoulli_genome(12), FitnessSpec((1.0,)))
    return Job(tenant_id=tid, family="ea_simple", toolbox=_TB,
               key=jax.random.key(3000 + seed), init=pop,
               ngen=int(params.get("ngen", 4)),
               hyper={"cxpb": 0.5, "mutpb": 0.2}, program="onemax")


PROBLEMS = {"onemax": _onemax_job}


def _svc_kwargs():
    return dict(max_lanes=2, segment_len=2, metrics=MetricsRegistry())


def _inprocess_digests(root, jobs):
    with Scheduler(str(root), max_lanes=2, segment_len=2) as sched:
        for j in jobs:
            sched.submit(j)
        results = sched.run()
    return {tid: result_digest(res) for tid, res in results.items()}


def _journal(root):
    return read_journal(os.path.join(str(root), "journal.jsonl"))


# ------------------------------------------------ WAL unit surface ----

def test_wal_append_replay_roundtrip(tmp_path):
    path = str(tmp_path / "a.wal")
    with AdmissionWAL(path) as w:
        w.append("accept", tenant_id="t0", problem="p", params={"s": 1},
                 idempotency_key="k0")
        w.append("accept", tenant_id="t1", problem="p", params={"s": 2},
                 idempotency_key="k1")
        w.append("done", tenant_id="t0", status="finished")
    st = AdmissionWAL(path).replay()
    assert st.tear_offset is None and len(st) == 3
    # done cancels replay; idempotency survives the terminal state (a
    # late retry of a finished job must still map to it)
    assert set(st.pending) == {"t1"}
    assert st.idempotency == {"k0": "t0", "k1": "t1"}
    assert st.pending["t1"]["params"] == {"s": 2}


def test_wal_torn_tail_self_heals(tmp_path):
    path = str(tmp_path / "a.wal")
    with AdmissionWAL(path) as w:
        w.append("accept", tenant_id="t0", problem="p", params={})
        w.append("done", tenant_id="t0", status="finished")
        w.append("accept", tenant_id="t1", problem="p", params={},
                 idempotency_key="k1")
        w.append("accept", tenant_id="t2", problem="p", params={})
    # a power cut mid-append: the final record loses its tail
    corrupt_file(path, mode="truncate", offset=-7)
    w2 = AdmissionWAL(path)
    st = w2.replay()
    # the torn record was never ACKed — dropping it loses nothing;
    # everything before it survives intact
    assert st.tear_offset is not None
    assert set(st.pending) == {"t1"}
    assert st.idempotency == {"k1": "t1"}
    # the tear was truncated away at open: appends land on a clean
    # line boundary and the log parses clean again
    w2.append("accept", tenant_id="t3", problem="p", params={})
    w2.close()
    st3 = AdmissionWAL(path).replay()
    assert st3.tear_offset is None
    assert set(st3.pending) == {"t1", "t3"}


def test_wal_interior_damage_skipped_not_fatal(tmp_path):
    path = str(tmp_path / "a.wal")
    with AdmissionWAL(path) as w:
        w.append("accept", tenant_id="t0", problem="p", params={})
        w.append("accept", tenant_id="t1", problem="p", params={})
    # flip bytes INSIDE the first record (newline-terminated): CRC
    # rejects it, the rest of the log still replays
    corrupt_file(path, mode="flip", nbytes=4, offset=12)
    st = AdmissionWAL(path).replay()
    assert set(st.pending) == {"t1"}
    assert st.tear_offset is None


# ------------------------------------------------- overload pin ----

def test_overload_sheds_429_with_retry_after_then_converges(tmp_path):
    """Acceptance: with bounded queues saturated, new submits get 429 +
    Retry-After (never hang, never 500), journaled ``load_shed``; a
    retrying client honouring Retry-After converges once load drains."""
    with EvolutionService(str(tmp_path), PROBLEMS, max_pending=2,
                          retry_after_s=1.0, **_svc_kwargs()) as svc:
        c = ServiceClient(svc.url)
        c.submit("onemax", params={"seed": 1, "ngen": 20},
                 tenant_id="o1")
        c.submit("onemax", params={"seed": 2, "ngen": 20},
                 tenant_id="o2")
        # saturated: the third submit is shed — an explicit 429 with
        # the server's Retry-After, not a hang and not a 500
        with pytest.raises(ServiceError) as ei:
            c.submit("onemax", params={"seed": 3, "ngen": 4})
        assert ei.value.code == 429
        assert ei.value.retry_after == 1.0
        # a retrying client converges: backoff honours Retry-After
        sleeps = []

        def sleep(s):
            sleeps.append(s)
            time.sleep(min(s, 0.2))

        retry = RetryPolicy(max_retries=200, backoff_s=0.05,
                            max_backoff_s=0.5, jitter=0.5, sleep=sleep)
        rc = ServiceClient(svc.url, retry=retry)
        t3 = rc.submit("onemax", params={"seed": 3, "ngen": 4},
                       idempotency_key="k3")
        for tid in ("o1", "o2", t3):
            res = c.result(tid, wait=True, timeout=300)
            assert res["status"] == "finished", res
        assert sleeps and max(sleeps) >= 1.0  # Retry-After respected
    rows = _journal(tmp_path)
    sheds = [r for r in rows if r.get("kind") == "load_shed"]
    assert sheds and all(r.get("max_pending") == 2 for r in sheds
                         if "max_pending" in r)


# ------------------------------------------------- deadline pin ----

def test_deadline_expired_at_frontend_is_504(tmp_path):
    with EvolutionService(str(tmp_path), PROBLEMS,
                          **_svc_kwargs()) as svc:
        c = ServiceClient(svc.url)
        with pytest.raises(ServiceError) as ei:
            c.submit("onemax", params={"seed": 1, "ngen": 4},
                     tenant_id="dead0", deadline_s=0.0)
        assert ei.value.code == 504
    rows = _journal(tmp_path)
    assert any(r.get("kind") == "deadline_exceeded"
               and r.get("stage") == "frontend" for r in rows)
    # it never existed scheduler-side
    assert not any(r.get("kind") == "job_submitted"
                   and r.get("tenant_id") == "dead0" for r in rows)


def test_deadline_expired_in_queue_dropped_before_scheduler(tmp_path):
    """Acceptance: a command whose deadline expires while queued is
    dropped by the driver — journaled ``deadline_exceeded``, result
    polls return 504, and the job never reaches the scheduler."""
    entered, release = threading.Event(), threading.Event()

    def hook(step):
        if step == 1:
            entered.set()
            release.wait(30)

    svc = EvolutionService(str(tmp_path), PROBLEMS, step_hook=hook,
                           **_svc_kwargs())
    try:
        c = ServiceClient(svc.url)
        c.submit("onemax", params={"seed": 1, "ngen": 8},
                 tenant_id="busy")
        assert entered.wait(120)
        # the driver is wedged in the hook: this command queues behind
        # it and its deadline expires in the queue
        c.submit("onemax", params={"seed": 2, "ngen": 4},
                 tenant_id="late", deadline_s=0.15)
        time.sleep(0.4)
        release.set()
        with pytest.raises(ServiceError) as ei:
            c.result("late", wait=True, timeout=120)
        assert ei.value.code == 504
        res = c.result("busy", wait=True, timeout=300)
        assert res["status"] == "finished"
    finally:
        release.set()
        svc.close()
    rows = _journal(tmp_path)
    drops = [r for r in rows if r.get("kind") == "deadline_exceeded"]
    assert any(r.get("tenant_id") == "late"
               and r.get("stage") == "driver" for r in drops)
    assert not any(r.get("kind") == "job_submitted"
                   and r.get("tenant_id") == "late" for r in rows)


# --------------------------------------------------- watchdog ----

def test_watchdog_detects_stall_and_rearms(tmp_path):
    hm = HealthMonitor()
    plan = FaultPlan([DelaySegment(1, 1.5)])
    with EvolutionService(str(tmp_path), PROBLEMS, watchdog_s=0.4,
                          health=hm, fault_plan=plan,
                          **_svc_kwargs()) as svc:
        c = ServiceClient(svc.url)
        tid = c.submit("onemax", params={"seed": 1, "ngen": 8})
        deadline = time.monotonic() + 30
        while not svc.stalled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert svc.stalled, "watchdog never fired during the stall"
        assert c.healthz()["status"] == "stalled"  # /healthz -> 503
        res = c.result(tid, wait=True, timeout=300)
        assert res["status"] == "finished"
        # once the driver recovers, the watchdog re-arms (the tick is
        # up to watchdog_s/4 behind the heartbeat — poll, don't race)
        while svc.stalled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not svc.stalled, "watchdog never re-armed"
        assert c.healthz()["status"] == "ok"
    rows = _journal(tmp_path)
    stalls = [r for r in rows if r.get("kind") == "driver_stall"]
    fired = [r for r in stalls if "stack" in r]
    assert fired and all(r["stalled_s"] >= 0.4 for r in fired)
    # the injected wedge's stall dump names the culprit frame (a slow
    # first compile may legitimately trip an additional stall first)
    assert any("faultinject" in r["stack"] for r in fired)
    assert any(r.get("recovered") for r in stalls)
    assert any(a["alarm"] == "driver_stall" for a in hm.alarms)


def test_watchdog_exit_escalation_is_opt_in(tmp_path):
    plan = FaultPlan([DelaySegment(1, 1.2)])
    exits = []
    svc = EvolutionService(str(tmp_path), PROBLEMS, watchdog_s=0.3,
                           watchdog_exit=True, fault_plan=plan,
                           **_svc_kwargs())
    svc._exit_fn = exits.append  # capture instead of killing pytest
    try:
        ServiceClient(svc.url).submit("onemax",
                                      params={"seed": 1, "ngen": 8})
        deadline = time.monotonic() + 30
        while not exits and time.monotonic() < deadline:
            time.sleep(0.02)
        assert exits == [70]
    finally:
        svc.close()
    rows = _journal(tmp_path)
    assert any(r.get("kind") == "driver_stall" and r.get("escalate")
               for r in rows)


# --------------------------------- idempotency & dropped responses ----

def test_dropped_response_retry_is_idempotent(tmp_path):
    """The network eats the submit ACK after the job was durably
    accepted: the retry (same idempotency key) maps back to the same
    tenant — one admission, no twin."""
    plan = FaultPlan([DropResponse("/v1/jobs", times=1)])
    with EvolutionService(str(tmp_path), PROBLEMS, fault_plan=plan,
                          **_svc_kwargs()) as svc:
        retry = RetryPolicy(max_retries=4, backoff_s=0.05)
        c = ServiceClient(svc.url, retry=retry)
        tid = c.submit("onemax", params={"seed": 9, "ngen": 4},
                       tenant_id="drop0", idempotency_key="kd")
        assert tid == "drop0"
        res = c.result(tid, wait=True, timeout=300)
        assert res["status"] == "finished"
    rows = _journal(tmp_path)
    submits = [r for r in rows if r.get("kind") == "job_submitted"
               and r.get("tenant_id") == "drop0"]
    assert len(submits) == 1  # exactly one admission
    assert any(r.get("kind") == "idempotent_replay"
               and r.get("tenant_id") == "drop0" for r in rows)


def test_wal_replay_recovers_unacked_jobs_and_dedups_keys(tmp_path):
    """A forged crash artifact: accept records whose process died
    before admission. A fresh service over the root replays them —
    and a concurrent fresh submit for the same key (the client that
    never saw its ACK, retrying into the restart) maps to the
    recovered tenant instead of admitting a twin."""
    root = tmp_path / "svc"
    os.makedirs(root)
    specs = [("w0", {"seed": 5, "ngen": 4}, "kw0"),
             ("w1", {"seed": 6, "ngen": 4}, "kw1")]
    wal = AdmissionWAL(os.path.join(root, "admission.wal"))
    for tid, params, key in specs:
        wal.append("accept", tenant_id=tid, problem="onemax",
                   params=params, idempotency_key=key,
                   request_id="r-crashed", token="")
    wal.close()
    ref = _inprocess_digests(
        tmp_path / "ref",
        [_onemax_job(tid, p) for tid, p, _ in specs])

    with EvolutionService(str(root), PROBLEMS, **_svc_kwargs()) as svc:
        c = ServiceClient(svc.url)
        # the replay-vs-fresh-submit race for the same key: the key
        # map is rebuilt before the HTTP server exists, so this must
        # resolve to the recovered tenant
        assert c.submit("onemax", params={"seed": 5, "ngen": 4},
                        idempotency_key="kw0") == "w0"
        for tid, _, _ in specs:
            res = c.result(tid, wait=True, timeout=300)
            assert res["status"] == "finished", res
            assert res["result"]["digest"] == ref[tid]
    rows = _journal(root)
    replays = [r for r in rows if r.get("kind") == "wal_replay"]
    assert replays and replays[0]["replayed"] == ["w0", "w1"]
    assert any(r.get("kind") == "idempotent_replay"
               and r.get("tenant_id") == "w0" for r in rows)


# -------------------------------------- satellite: request tracing ----

def test_request_id_threads_through_journal(tmp_path):
    with EvolutionService(str(tmp_path), PROBLEMS,
                          **_svc_kwargs()) as svc:
        conn = http.client.HTTPConnection(svc.host, svc.port,
                                          timeout=60)
        conn.request("POST", "/v1/jobs",
                     body=json.dumps({
                         "problem": "onemax",
                         "params": {"seed": 2, "ngen": 4},
                         "tenant_id": "rid0"}),
                     headers={"Content-Type": "application/json",
                              "X-Request-Id": "trace-42"})
        resp = conn.getresponse()
        assert resp.getheader("X-Request-Id") == "trace-42"  # echoed
        assert resp.status == 200
        resp.read()
        conn.close()
        c = ServiceClient(svc.url)
        # requests without the header get a generated id back
        c2 = http.client.HTTPConnection(svc.host, svc.port, timeout=60)
        c2.request("GET", "/v1/jobs/rid0")
        r2 = c2.getresponse()
        assert r2.getheader("X-Request-Id", "").startswith("req-")
        r2.read()
        c2.close()
        assert c.result("rid0", wait=True,
                        timeout=300)["status"] == "finished"
    rows = _journal(tmp_path)
    traced = [r for r in rows if r.get("request_id") == "trace-42"]
    kinds = {r.get("kind") for r in traced}
    # one grep over the id reconstructs the request's full path
    assert {"service_request", "job_submitted", "tenant_admitted",
            "tenant_finished"} <= kinds


# --------------------------- satellite: long-poll param hardening ----

def test_timeout_param_malformed_is_400_and_clamped(tmp_path):
    with EvolutionService(str(tmp_path), PROBLEMS, max_poll_s=0.5,
                          **_svc_kwargs()) as svc:
        c = ServiceClient(svc.url)
        tid = c.submit("onemax", params={"seed": 1, "ngen": 200},
                       tenant_id="long0")
        # malformed timeout: 400, never an unhandled ValueError -> 500
        with pytest.raises(ServiceError) as ei:
            c.result(tid, wait=True, timeout="bogus")
        assert ei.value.code == 400
        with pytest.raises(ServiceError) as ei:
            c.results_many([tid], wait=True, timeout="1e")
        assert ei.value.code == 400
        # a huge client timeout cannot pin the request thread: the
        # server clamps the long-poll to max_poll_s
        t0 = time.monotonic()
        res = c.result(tid, wait=True, timeout=10_000)
        assert time.monotonic() - t0 < 30
        assert res["_status"] == 202  # still running, poll returned
        svc.drain(wait=True, timeout=120)
    rows = _journal(tmp_path)
    assert any(r.get("kind") == "service_drain" for r in rows)


# ----------------------------- satellite: scheduler idleness signal ----

def test_slo_snapshot_exposes_gens_since_interaction(tmp_path):
    sched = Scheduler(str(tmp_path), max_lanes=2, segment_len=2)
    sched.submit(_onemax_job("i0", {"seed": 1, "ngen": 8}))
    sched.step()
    snap = sched.slo_snapshot()
    idle = next(iter(snap.values()))["idle"]
    assert idle and all(len(t) == 3 for t in idle)
    tid, segments, gens_idle = idle[0]
    assert tid == "i0" and gens_idle == 2  # 2 gens, never polled
    sched.tenants["i0"].note_interaction()
    idle2 = next(iter(sched.slo_snapshot().values()))["idle"]
    assert idle2[0][2] == 0  # the interaction reset the idleness clock
    sched.run()
    sched.close()


# --------------------------------------------------- chaos tier ----

@pytest.mark.chaos
def test_kill9_restart_bit_identical_under_live_load(tmp_path):
    """THE acceptance pin: ``kill -9`` mid-run under concurrent
    retrying client load (idempotency keys), supervisor restart over
    the same root (WAL replay + checkpoint resume), zero lost jobs and
    every tenant's wire digest bit-identical to an uninterrupted
    in-process run."""
    from deap_tpu.serving import chaos

    specs = chaos.chaos_specs(8)
    ref = chaos.reference_digests(str(tmp_path / "ref"), specs,
                                  segment_len=2, max_lanes=8)
    out = chaos.run_chaos(str(tmp_path / "svc"), n_tenants=8,
                          kill_at_step=3, segment_len=2, max_lanes=8,
                          clients=4, converge_timeout_s=420)
    assert out["kill_rc"] == -9, out       # SIGKILL actually landed
    assert out["lost"] == [], out          # zero lost jobs
    assert out["digests"] == ref           # bit-identical, every tenant
    rows = _journal(tmp_path / "svc")      # the restarted journal
    assert any(r.get("kind") == "wal_replay" for r in rows)
    assert any(r.get("kind") in ("tenant_resumed", "tenant_admitted")
               for r in rows)


@pytest.mark.chaos
def test_restart_resume_falls_back_past_corrupt_checkpoint(tmp_path):
    """``CheckpointCorruptError`` during a service-restart resume: the
    newest checkpoint is damaged after a drain; the restart falls back
    to the previous verified-good step and still converges to the
    uninterrupted digest."""
    NGEN = 12
    root = str(tmp_path / "svc")
    ref = _inprocess_digests(
        tmp_path / "ref", [_onemax_job("tA", {"seed": 3,
                                              "ngen": NGEN})])["tA"]

    def drain_at(step):
        if step == 3:
            svc.drain(wait=False)

    svc = EvolutionService(root, PROBLEMS, step_hook=drain_at,
                           **_svc_kwargs())
    c = ServiceClient(svc.url)
    c.submit("onemax", params={"seed": 3, "ngen": NGEN},
             tenant_id="tA", idempotency_key="ka")
    assert svc._drained.wait(300)
    svc.close()

    ck = Checkpointer(os.path.join(root, "tenants", "tA", "ckpt"))
    steps = ck.steps()
    assert len(steps) >= 2, steps  # need an older step to fall back to
    corrupt_file(ck.path_for(steps[-1]), mode="flip")

    with EvolutionService(root, PROBLEMS, **_svc_kwargs()) as svc2:
        c2 = ServiceClient(svc2.url)
        # WAL replay already resubmitted tA — no client action needed
        res = c2.result("tA", wait=True, timeout=300)
        assert res["status"] == "finished"
        assert res["result"]["digest"] == ref
    rows = _journal(root)
    kinds = {r.get("kind") for r in rows}
    assert "wal_replay" in kinds
    assert {"checkpoint_corrupt", "checkpoint_fallback"} & kinds


@pytest.mark.chaos
def test_kill9_trace_stitches_across_restart(tmp_path):
    """Trace continuity through kill -9: the restarted service rotates
    the journal, WAL replay re-derives every trace id from the
    persisted request id, and a killed-then-resumed tenant's spans
    assemble into ONE trace across both journal generations — the
    resume span parented on the (deterministic) root, no orphans."""
    from deap_tpu.serving import chaos
    from deap_tpu.telemetry import tracing
    from deap_tpu.telemetry.journal import journal_generations

    root = str(tmp_path / "svc")
    out = chaos.run_chaos(root, n_tenants=8, kill_at_step=3,
                          segment_len=2, max_lanes=8, clients=4,
                          converge_timeout_s=420, trace_sample=1.0)
    assert out["kill_rc"] == -9, out
    assert out["lost"] == [], out

    path = os.path.join(root, "journal.jsonl")
    gens = journal_generations(path)
    assert len(gens) >= 2, gens       # pre-kill + post-restart
    groups, per_gen_spans = [], []
    for p in gens:
        rows = read_journal(p, strict=False)
        hdr = next((r for r in rows if r.get("kind") == "header"),
                   None)
        groups.append((hdr, rows))
        per_gen_spans.append([r for r in rows
                              if r.get("kind") == "trace_span"])

    # a tenant the restart replayed out of the WAL, whose spans exist
    # in BOTH generations (killed mid-flight, then resumed)
    replay_rows = [r for _, rows in groups for r in rows
                   if r.get("kind") == "wal_replay"]
    replayed = {t for r in replay_rows for t in r.get("replayed", [])}
    assert replayed
    pre = {s.get("tenant_id") for s in per_gen_spans[0]}
    post = {s.get("tenant_id") for s in per_gen_spans[-1]}
    both = sorted((replayed & pre & post) - {None})
    assert both, (replayed, pre, post)
    tid = both[0]

    # every row of that tenant carries the one WAL-persisted request
    # id → the one deterministic trace id
    rids = {s["request_id"] for g in per_gen_spans for s in g
            if s.get("tenant_id") == tid and s.get("request_id")}
    assert len(rids) == 1, rids
    rid = rids.pop()
    trace_id = tracing.trace_id_for(rid)
    tenant_traces = {s["trace_id"] for g in per_gen_spans for s in g
                     if s.get("tenant_id") == tid}
    assert tenant_traces == {trace_id}

    # the restarted journal carries the replay span, parented on the
    # deterministic root span id — no row from the old process needed
    replays = [s for s in per_gen_spans[-1]
               if s["name"] == "request.replay"
               and s.get("trace_id") == trace_id]
    assert replays
    assert replays[0]["parent_id"] == tracing.root_span_id(rid)

    # assembled across generations: one waterfall, no orphan spans,
    # spans from both sides of the kill
    trace = tracing.assemble_trace(groups, trace_id)
    assert trace["orphans"] == []
    names = {s["name"] for s in trace["spans"]}
    assert "request.replay" in names
    assert "segment" in names
    n_pre = sum(1 for s in per_gen_spans[0]
                if s.get("trace_id") == trace_id)
    n_post = sum(1 for s in per_gen_spans[-1]
                 if s.get("trace_id") == trace_id)
    assert n_pre >= 1 and n_post >= 1
    assert len(trace["spans"]) >= n_pre + n_post


# ------------------------------------ zero-downtime migration ----
# (ISSUE 20: WAL ownership transfer, orphan adoption, rolling
# upgrade. Fast tier = the transfer-record state machine and the
# in-process protocol seams; chaos tier = subprocess kill -9 at the
# exact handoff seams.)


def _wait_gen(client, tid, min_gen, timeout_s=60.0):
    """Poll until the tenant is mid-run (``gen >= min_gen``) — the
    migration tests move LIVE tenants, never gen-0 ones."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        got = client.result(tid, wait=False)
        if got.get("result") or int(got.get("gen") or 0) >= min_gen:
            return got
        time.sleep(0.02)
    raise AssertionError(f"{tid} never reached gen {min_gen}")


def test_wal_migration_record_fold(tmp_path):
    """offer keeps the tenant pending (the source still owns it until
    resolution), adopted folds like an accept on the target, and
    transferred closes the tenant on the source — while the
    idempotency mapping survives the transfer (a late client retry on
    the source must still resolve)."""
    src = str(tmp_path / "src.wal")
    with AdmissionWAL(src) as w:
        w.append("accept", tenant_id="t0", problem="p",
                 params={"s": 1}, idempotency_key="k0")
        w.append("offer", tenant_id="t0", offer_id="X",
                 target="http://peer", gen=3, problem="p",
                 params={"s": 1}, idempotency_key="k0")
    st = scan_wal(src)
    assert set(st.pending) == {"t0"}          # offer is NOT terminal
    assert st.offers["t0"]["offer_id"] == "X"

    tgt = str(tmp_path / "tgt.wal")
    with AdmissionWAL(tgt) as w:
        w.append("adopted", tenant_id="t0", offer_id="X",
                 source="http://peer", source_root=str(tmp_path),
                 gen=3, problem="p", params={"s": 1},
                 idempotency_key="k0")
    st2 = scan_wal(tgt)
    assert set(st2.pending) == {"t0"}         # adopted = an accept
    assert st2.pending["t0"]["kind"] == "adopted"
    assert st2.adoptions["X"]["tenant_id"] == "t0"
    assert st2.idempotency == {"k0": "t0"}

    with AdmissionWAL(src) as w:
        w.append("transferred", tenant_id="t0", offer_id="X",
                 target="http://peer")
    st3 = scan_wal(src)
    assert st3.pending == {} and st3.offers == {}
    assert st3.idempotency == {"k0": "t0"}

    # scan_wal is STRICTLY read-only: scanning a peer's torn log (the
    # adoption path reads logs of processes that died mid-append)
    # must never heal-truncate a file this process doesn't own
    corrupt_file(tgt, mode="truncate", offset=-7)
    size = os.path.getsize(tgt)
    st4 = scan_wal(tgt)
    assert st4.tear_offset is not None
    assert os.path.getsize(tgt) == size


def test_transfer_commit_race_single_winner(tmp_path):
    """Ownership arbitration is an O_EXCL create: N racing claimants
    for the same offer produce exactly one winner, and every loser
    reads back the SAME winning record."""
    src = str(tmp_path / "dead")
    os.makedirs(src)
    results = []
    lock = threading.Lock()

    def claim(i):
        own = str(tmp_path / f"peer{i}")
        won, rec = migration.try_commit(
            src, offer_id="orphan-tx", tenant_id="tx",
            owner_root=own, owner_wal=os.path.join(own, "a.wal"))
        with lock:
            results.append((won, rec))

    threads = [threading.Thread(target=claim, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [rec for won, rec in results if won]
    assert len(winners) == 1
    # losers converge on the winner's record, not their own attempt
    owner = winners[0]["owner_root"]
    assert all(rec["owner_root"] == owner for _, rec in results)
    assert len(migration.commits_for(src, "tx")) == 1
    # a foreign owner is a transfer; a self-owned commit is a closed
    # reclaim (the door shut on late adopters, nothing moved)
    assert migration._foreign_commit(src, "tx") is not None
    migration.try_commit(src, offer_id="orphan-ty", tenant_id="ty",
                         owner_root=src, owner_wal="w")
    assert migration._foreign_commit(src, "ty") is None


def test_live_migration_bit_exact_mid_run(tmp_path):
    """THE tentpole pin, in process: a tenant is migrated MID-RUN
    between two live services and its final wire digest is
    bit-identical to an unmigrated single-scheduler run. Source
    journals offered->transferred, target journals the adoption, and
    the commit file records the new owner."""
    NGEN = 400   # enough runway that the migrate lands MID-RUN
    ref = _inprocess_digests(
        tmp_path / "ref",
        [_onemax_job("tA", {"seed": 41, "ngen": NGEN})])["tA"]
    src_root = str(tmp_path / "srcsvc")
    dst_root = str(tmp_path / "dstsvc")
    with EvolutionService(src_root, PROBLEMS, **_svc_kwargs()) as src, \
            EvolutionService(dst_root, PROBLEMS,
                             **_svc_kwargs()) as dst:
        c = ServiceClient(src.url)
        c.submit("onemax", params={"seed": 41, "ngen": NGEN},
                 tenant_id="tA", idempotency_key="ka")
        _wait_gen(c, "tA", 2)
        out = src.migrate("tA", dst.url)
        assert out.get("migrated") is True, out
        # the source's view is terminal `migrated` — the client
        # re-offer signal, naming the live new home
        res_src = c.result("tA", wait=False)
        assert res_src["status"] == "migrated"
        # the target finishes the run bit-identically
        c2 = ServiceClient(dst.url)
        res = c2.result("tA", wait=True, timeout=300)
        assert res["status"] == "finished"
        assert res["result"]["digest"] == ref
        # idempotency rode the transfer: re-offering the same key to
        # the new owner maps onto the adopted tenant, no twin run
        again = c2.submit("onemax",
                          params={"seed": 41, "ngen": NGEN},
                          idempotency_key="ka")
        assert again == "tA"
    commit = migration._foreign_commit(src_root, "tA")
    assert commit is not None
    assert os.path.abspath(commit["owner_root"]) == \
        os.path.abspath(dst_root)
    src_rows = [r for r in _journal(src_root)
                if r.get("kind") == "migration_offer"]
    assert [r["phase"] for r in src_rows] == ["offered",
                                              "transferred"]
    assert any(r.get("kind") == "migration_adopted"
               for r in _journal(dst_root))
    # the ownership pause is bounded and recorded
    assert 0 < src_rows[-1]["pause_s"] < 30


def test_migrate_to_dead_target_reclaims(tmp_path):
    """An offer the target never ACKs resolves to the SOURCE: the
    self-owned commit shuts the door on a late adopter, the tenant
    resumes locally, and the run still converges bit-identically."""
    NGEN = 400   # enough runway that the migrate lands MID-RUN
    ref = _inprocess_digests(
        tmp_path / "ref",
        [_onemax_job("tA", {"seed": 42, "ngen": NGEN})])["tA"]
    root = str(tmp_path / "svc")
    # a port with no listener: connect is refused immediately
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    with EvolutionService(root, PROBLEMS, **_svc_kwargs()) as svc:
        c = ServiceClient(svc.url)
        c.submit("onemax", params={"seed": 42, "ngen": NGEN},
                 tenant_id="tA", idempotency_key="ka")
        _wait_gen(c, "tA", 2)
        out = svc.migrate("tA", f"http://127.0.0.1:{dead_port}")
        assert out.get("migrated") is False
        assert out.get("reclaimed") is True, out
        res = c.result("tA", wait=True, timeout=300)
        assert res["status"] == "finished"
        assert res["result"]["digest"] == ref
    # the commit is self-owned — a closed reclaim, not a transfer
    assert migration._foreign_commit(root, "tA") is None
    assert len(migration.commits_for(root, "tA")) == 1
    phases = [r["phase"] for r in _journal(root)
              if r.get("kind") == "migration_offer"]
    assert phases == ["offered", "reclaimed"]


def test_orphan_adoption_race_deterministic_loser(tmp_path):
    """Two live peers discover the same dead member. Deterministic
    offer ids (``orphan-<tenant>``) make them contend for the SAME
    commit file: the first claimant wins, the second voids its own
    durable adoption (``done adoption_lost``) so its restart can
    never resurrect a twin."""
    import subprocess
    import sys as _sys
    NGEN = 6
    ref = _inprocess_digests(
        tmp_path / "ref",
        [_onemax_job("tO", {"seed": 7, "ngen": NGEN})])["tO"]

    # the dead member: a WAL with an accepted-not-terminal tenant,
    # registered in the fleet root under a pid that is gone
    dead_root = str(tmp_path / "dead")
    os.makedirs(dead_root)
    with AdmissionWAL(os.path.join(dead_root, "admission.wal")) as w:
        w.append("accept", tenant_id="tO", problem="onemax",
                 params={"seed": 7, "ngen": NGEN},
                 idempotency_key="kO")
    gone = subprocess.Popen([_sys.executable, "-c", "pass"])
    gone.wait()
    fleet = tmp_path / "fleet"
    member = fleet / "member-dead"
    member.mkdir(parents=True)
    (member / "meta.json").write_text(json.dumps({
        "process_id": "member-dead", "pid": gone.pid,
        "serving_root": dead_root, "url": "http://127.0.0.1:9"}))

    spec = dict(tenant_id="tO", offer_id="orphan-tO",
                source="member-dead", source_root=dead_root, gen=0,
                problem="onemax",
                params={"seed": 7, "ngen": NGEN},
                idempotency_key="kO")
    root_a, root_b = str(tmp_path / "a"), str(tmp_path / "b")
    with EvolutionService(root_a, PROBLEMS, **_svc_kwargs()) as a, \
            EvolutionService(root_b, PROBLEMS,
                             **_svc_kwargs()) as b:
        assert a.adopt_orphans(str(fleet)) == ["tO"]
        # the scan pre-check: a committed transfer is skipped
        assert b.adopt_orphans(str(fleet)) == []
        # the RACE: b passed the pre-check concurrently and reached
        # the claim — it must lose the O_EXCL create and stand down
        code, out = migration.adopt_tenant(b, spec, orphan=True)
        assert code == 409, (code, out)
        assert out.get("adopted") is False
        res = ServiceClient(a.url).result("tO", wait=True,
                                          timeout=300)
        assert res["status"] == "finished"
        assert res["result"]["digest"] == ref
    # b's durable claim is voided: its restart replays NO tenant
    assert "tO" not in scan_wal(
        os.path.join(root_b, "admission.wal")).pending
    lost_rows = [r for r in _journal(root_b)
                 if r.get("kind") == "orphan_adopted"
                 and r.get("lost")]
    assert lost_rows and lost_rows[0]["tenant_id"] == "tO"


def test_resolve_replay_acked_but_source_died(tmp_path):
    """The source dies AFTER the target ACKed adoption (commit on
    disk) but BEFORE appending ``transferred``: the restart must
    resolve the offer to the target — append the missing record, not
    resubmit, and journal the resolution."""
    root = str(tmp_path / "svc")
    os.makedirs(root)
    with AdmissionWAL(os.path.join(root, "admission.wal")) as w:
        w.append("accept", tenant_id="tA", problem="onemax",
                 params={"seed": 5, "ngen": 6},
                 idempotency_key="ka")
        w.append("offer", tenant_id="tA", offer_id="X",
                 target="http://peer", gen=2, problem="onemax",
                 params={"seed": 5, "ngen": 6},
                 idempotency_key="ka")
    peer_root = str(tmp_path / "peer")
    won, _ = migration.try_commit(
        root, offer_id="X", tenant_id="tA", owner_root=peer_root,
        owner_wal=os.path.join(peer_root, "admission.wal"))
    assert won
    with EvolutionService(root, PROBLEMS, **_svc_kwargs()) as svc:
        with pytest.raises(ServiceError) as ei:
            ServiceClient(svc.url).result("tA", wait=False)
        assert ei.value.code == 404      # not resubmitted: not ours
    st = scan_wal(os.path.join(root, "admission.wal"))
    assert "tA" not in st.pending        # transferred was appended
    rows = [r for r in _journal(root)
            if r.get("kind") == "migration_offer"]
    assert rows and rows[-1]["phase"] == "resolved"
    assert rows[-1]["owner"] == "target"


def test_resolve_replay_unresolved_offer_commits_to_self(tmp_path):
    """The source dies right after the durable offer, before any byte
    reached the target: the restart commits the offer to ITSELF
    (shutting the door on a late adopter) and replays the tenant
    locally to the uninterrupted digest."""
    NGEN = 6
    ref = _inprocess_digests(
        tmp_path / "ref",
        [_onemax_job("tA", {"seed": 5, "ngen": NGEN})])["tA"]
    root = str(tmp_path / "svc")
    os.makedirs(root)
    with AdmissionWAL(os.path.join(root, "admission.wal")) as w:
        w.append("accept", tenant_id="tA", problem="onemax",
                 params={"seed": 5, "ngen": NGEN},
                 idempotency_key="ka")
        w.append("offer", tenant_id="tA", offer_id="X",
                 target="http://peer", gen=2, problem="onemax",
                 params={"seed": 5, "ngen": NGEN},
                 idempotency_key="ka")
    with EvolutionService(root, PROBLEMS, **_svc_kwargs()) as svc:
        res = ServiceClient(svc.url).result("tA", wait=True,
                                            timeout=300)
        assert res["status"] == "finished"
        assert res["result"]["digest"] == ref
    commit = migration.read_commit(root, "X")
    assert commit is not None
    assert os.path.abspath(commit["owner_root"]) == \
        os.path.abspath(root)
    rows = [r for r in _journal(root)
            if r.get("kind") == "migration_offer"
            and r.get("phase") == "resolved"]
    assert rows and rows[0]["owner"] == "source"


def test_torn_transfer_record_is_no_offer(tmp_path):
    """A power cut mid-append of the OFFER record: the torn record
    never became durable, so after restart the offer simply never
    happened — the tenant replays locally, exactly once, to the
    uninterrupted digest. (Seq 2 = the offer: the accept was seq 1.)"""
    NGEN = 400   # enough runway that the migrate lands MID-RUN
    ref = _inprocess_digests(
        tmp_path / "ref",
        [_onemax_job("tA", {"seed": 6, "ngen": NGEN})])["tA"]
    root = str(tmp_path / "svc")
    plan = FaultPlan([TornWAL(seq=2, nbytes=7, then_crash=True)])
    svc = EvolutionService(root, PROBLEMS, fault_plan=plan,
                           **_svc_kwargs())
    c = ServiceClient(svc.url)
    c.submit("onemax", params={"seed": 6, "ngen": NGEN},
             tenant_id="tA", idempotency_key="ka")
    _wait_gen(c, "tA", 2)
    out = svc.migrate("tA", "http://127.0.0.1:9")
    assert out.get("migrated") is False
    assert "InjectedCrash" in out.get("error", ""), out
    svc.close()
    # the log still carries the tear; the offer never folded
    st = scan_wal(os.path.join(root, "admission.wal"))
    assert st.tear_offset is not None
    assert st.offers == {} and set(st.pending) == {"tA"}
    assert migration.commits_for(root, "tA") == []
    with EvolutionService(root, PROBLEMS, **_svc_kwargs()) as svc2:
        res = ServiceClient(svc2.url).result("tA", wait=True,
                                             timeout=300)
        assert res["status"] == "finished"
        assert res["result"]["digest"] == ref


@pytest.mark.chaos
@pytest.mark.parametrize("seam", ["after_offer", "before_adopted",
                                  "before_transferred"])
def test_migration_seam_kill_bit_identical(tmp_path, seam):
    """kill -9 at each ownership-transfer seam, under a supervisor
    that restarts the dead side: zero lost jobs and every digest
    bit-identical to the uninterrupted reference — wherever the
    commit files say each tenant ended up."""
    from deap_tpu.serving import chaos

    NGEN = 30   # jobs must still be mid-run when the drain lands
    specs = chaos.chaos_specs(6, ngen=NGEN)
    ref = chaos.reference_digests(str(tmp_path / "ref"), specs,
                                  segment_len=2, max_lanes=8)
    out = chaos.run_migration_chaos(str(tmp_path / "mig"), seam,
                                    n_tenants=6, ngen=NGEN)
    assert out["kill_rc"] == -9, out
    assert out["lost"] == [], out
    assert out["digests"] == ref
    if seam == "before_transferred":
        # the target ACKed before the source died: the ACKed
        # adoption STANDS — resolution must follow the commit file
        assert out["adopted_by_target"], out
    if seam == "after_offer":
        # the source died before any byte reached the target: no
        # claim can exist, the restart resolves every offer to self
        assert out["adopted_by_target"] == [], out


@pytest.mark.chaos
def test_orphan_adoption_drill(tmp_path):
    """A fleet member is kill -9ed and NEVER restarted: a live peer
    discovers the death through the federation metadata and adopts
    every accepted-not-terminal tenant, bit-identically."""
    from deap_tpu.serving import chaos

    NGEN = 30
    specs = chaos.chaos_specs(6, ngen=NGEN)
    ref = chaos.reference_digests(str(tmp_path / "ref"), specs,
                                  segment_len=2, max_lanes=8)
    out = chaos.run_orphan_drill(str(tmp_path / "orph"),
                                 n_tenants=6, ngen=NGEN)
    assert out["kill_rc"] == -9, out
    assert out["lost"] == [], out
    assert out["digests"] == ref
    assert out["peer_kinds"].get("orphan_adopted", 0) >= 1, out


@pytest.mark.chaos
def test_rolling_upgrade_drill(tmp_path):
    """The ISSUE 20 acceptance drill: drain an old-version service
    into a warm new-version one under live load. Zero lost jobs,
    every digest bit-identical, the cross-version resumes journaled
    under the explicit compat gate, canaries green on both sides,
    and every per-tenant ownership pause bounded."""
    from deap_tpu.serving import chaos

    NGEN = 30
    specs = chaos.chaos_specs(6, ngen=NGEN)
    ref = chaos.reference_digests(str(tmp_path / "ref"), specs,
                                  segment_len=2, max_lanes=8)
    out = chaos.run_upgrade_drill(str(tmp_path / "up"),
                                  n_tenants=6, ngen=NGEN)
    assert out["old_rc"] == 0, out           # a DRAIN, not a crash
    assert out["lost"] == [], out
    assert out["digests"] == ref
    assert out["new_kinds"].get("migration_adopted", 0) >= 1, out
    assert out["new_kinds"].get("compat_restore", 0) >= 1, out
    assert out["old_kinds"].get("canary_failed", 0) == 0
    assert out["new_kinds"].get("canary_failed", 0) == 0
    assert out["migration_pauses_s"], out
    assert max(out["migration_pauses_s"]) < 30
