"""Pallas kernel tests (interpreter on the CPU test platform).

The hardware-PRNG path of fused_variation_eval exists only on real TPU
cores and is exercised by bench.py / the TPU smoke script; everything
else — tiling, masking, pairing, the two-point/flip-bit semantics, and
dominance counting — is validated here against the XLA formulations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deap_tpu.core.fitness import dominates
from deap_tpu.mo.emo import nd_rank
from deap_tpu.ops.kernels import (
    dominated_counts,
    dominated_weight_maxes,
    fused_variation_eval,
    nd_rank_tiled,
)


# ---------------------------------------------------- dominance counting ----

@pytest.mark.parametrize("n,m", [(37, 2), (300, 3), (513, 4)])
def test_dominated_counts_matches_matrix(n, m):
    w = jax.random.normal(jax.random.key(n), (n, m))
    # duplicate some rows to exercise the equal-fitness (no-domination) case
    w = w.at[: n // 4].set(w[n // 4 : 2 * (n // 4)])
    rem = jax.random.bernoulli(jax.random.key(1), 0.7, (n,))
    got = dominated_counts(w, rem, block_i=128, block_j=128)
    dom = dominates(w[None, :, :], w[:, None, :])  # [i, j]: j dominates i
    want = (dom & rem[None, :]).sum(1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("n,nq,m", [(37, 37, 3), (300, 120, 4)])
def test_dominated_weight_maxes_matches_matrix(n, nq, m):
    w = jax.random.normal(jax.random.key(n), (n, m))
    w = w.at[: n // 4].set(w[n // 4 : 2 * (n // 4)])  # exact ties
    q = w[:nq] if nq < n else w
    wts = jax.random.uniform(jax.random.key(2), (n,), minval=1.0,
                             maxval=9.0)
    got = dominated_weight_maxes(w, wts, queries=q,
                                 block_i=128, block_j=128)
    dom = dominates(w[None, :, :], q[:, None, :])  # [i, j]: j dom q_i
    want = jnp.max(jnp.where(dom, wts[None, :], 0.0), axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want))


def test_dominated_weight_maxes_default_queries_is_self():
    w = jax.random.normal(jax.random.key(9), (65, 3))
    a = dominated_weight_maxes(w, jnp.ones(65), block_i=64, block_j=64)
    b = dominated_weight_maxes(w, jnp.ones(65), queries=w,
                               block_i=64, block_j=64)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_nd_rank_tiled_matches_matrix_path():
    w = jax.random.normal(jax.random.key(7), (257, 3))
    np.testing.assert_array_equal(
        np.asarray(nd_rank_tiled(w, block_i=128, block_j=128)),
        np.asarray(nd_rank(w)))


def test_nd_rank_tiled_known_fronts():
    # three hand-made fronts on a 2-objective max problem
    f0 = jnp.array([[3.0, 0.0], [2.0, 2.0], [0.0, 3.0]])
    f1 = jnp.array([[2.0, 0.0], [1.0, 1.0], [0.0, 2.0]])
    f2 = jnp.array([[0.5, 0.5]])
    w = jnp.concatenate([f1, f2, f0])  # shuffled order
    ranks = nd_rank_tiled(w, block_i=128, block_j=128)
    np.testing.assert_array_equal(
        np.asarray(ranks), [1, 1, 1, 2, 0, 0, 0])


# ------------------------------------------------------- fused variation ----

def _fused(key, g, cxpb, mutpb, indpb):
    return fused_variation_eval(
        key, g, cxpb=cxpb, mutpb=mutpb, indpb=indpb, prng="input",
        block_i=64)


def test_fused_identity_and_fitness():
    g = jax.random.bernoulli(jax.random.key(5), 0.5, (130, 100))
    c, f = _fused(jax.random.key(0), g, 0.0, 0.0, 0.05)
    assert bool((c == g).all())
    np.testing.assert_allclose(np.asarray(f), np.asarray(g.sum(1)))


def test_fused_crossover_is_two_point_segment_swap():
    g = jax.random.bernoulli(jax.random.key(6), 0.5, (128, 100))
    c, f = _fused(jax.random.key(1), g, 1.0, 0.0, 0.0)
    g_np, c_np = np.asarray(g), np.asarray(c)
    some_swap = False
    for p in range(64):
        a, b = g_np[2 * p], g_np[2 * p + 1]
        ca, cb = c_np[2 * p], c_np[2 * p + 1]
        d = ca != a  # columns taken from the partner
        assert (np.where(d, b, a) == ca).all()
        assert (np.where(d, a, b) == cb).all()
        # the swapped region is one contiguous segment of differing genes
        diff_cols = np.flatnonzero((a != b) & d)
        if diff_cols.size:
            some_swap = True
            lo, hi = diff_cols[0], diff_cols[-1]
            inside = (a != b)[lo : hi + 1]
            assert (d[lo : hi + 1] == inside).all()
    assert some_swap
    np.testing.assert_allclose(np.asarray(f), c_np.sum(1))


def test_fused_full_flip():
    g = jax.random.bernoulli(jax.random.key(8), 0.5, (64, 100))
    c, _ = _fused(jax.random.key(2), g, 0.0, 1.0, 1.0)
    assert bool((c == ~g).all())


def test_fused_flip_rate():
    g = jnp.zeros((2048, 128), jnp.bool_)
    c, _ = _fused(jax.random.key(3), g, 0.0, 1.0, 0.05)
    rate = float(c.mean())
    assert 0.04 < rate < 0.06


def test_fused_odd_last_row_never_mates():
    g = jax.random.bernoulli(jax.random.key(9), 0.5, (129, 100))
    c, _ = _fused(jax.random.key(4), g, 1.0, 0.0, 0.0)
    assert bool((c[128] == g[128]).all())


def test_fused_uint8_genomes_and_padding_tail():
    # non-multiple population size and integer storage
    g = jax.random.bernoulli(jax.random.key(10), 0.5, (70, 33)).astype(
        jnp.uint8)
    c, f = _fused(jax.random.key(5), g, 0.6, 0.3, 0.1)
    assert c.shape == g.shape and c.dtype == g.dtype
    assert set(np.unique(np.asarray(c))) <= {0, 1}
    np.testing.assert_allclose(np.asarray(f),
                               np.asarray(c.astype(jnp.float32).sum(1)))


def test_dominated_counts_non_dividing_blocks():
    # block sizes that do not divide each other must still cover all
    # dominator columns (pad-to-lcm regression test)
    n = 512
    w = jax.random.normal(jax.random.key(11), (n, 3))
    rem = jnp.ones(n, bool)
    got = dominated_counts(w, rem, block_i=512, block_j=384)
    dom = dominates(w[None, :, :], w[:, None, :])
    np.testing.assert_array_equal(np.asarray(got), np.asarray(dom.sum(1)))


def test_fused_hw_prng_rejected_off_tpu():
    g = jnp.zeros((8, 16), jnp.bool_)
    with pytest.raises(ValueError, match="hw"):
        fused_variation_eval(jax.random.key(0), g, cxpb=0.5, mutpb=0.2,
                             indpb=0.05, prng="hw", interpret=True)


def test_strengths_and_weighted_sums_match_dense_spea2():
    # streaming strength/raw == the dense SPEA2 quantities
    from deap_tpu.mo.emo import dominance_matrix, spea2_fitness_stream

    w = jax.random.normal(jax.random.key(21), (157, 3))
    strength, raw = spea2_fitness_stream(
        w, block_i=128, block_j=128)
    dom = dominance_matrix(w)                     # dom[i, j]: j dominates i
    want_strength = dom.sum(axis=0).astype(jnp.float32)
    want_raw = jnp.where(dom, want_strength[None, :], 0).sum(1)
    np.testing.assert_allclose(np.asarray(strength),
                               np.asarray(want_strength))
    np.testing.assert_allclose(np.asarray(raw), np.asarray(want_raw))


def test_sel_spea2_stream_prefers_nondominated():
    from deap_tpu.mo.emo import sel_spea2_stream

    # clear 2-objective fronts: the k chosen must all be non-dominated
    front = jnp.stack([jnp.linspace(0, 1, 10),
                       1.0 - jnp.linspace(0, 1, 10)], 1)
    dominated = front * 0.5
    w = jnp.concatenate([dominated, front])
    idx = np.asarray(sel_spea2_stream(jax.random.key(0), w, 8,
                                      block_i=128, block_j=128))
    assert (idx >= 10).all()


def test_sel_spea2_stream_small_candidate_set():
    from deap_tpu.mo.emo import sel_spea2_stream

    w = jax.random.normal(jax.random.key(22), (300, 2))
    # candidates below k must still return k distinct indices
    idx = np.asarray(sel_spea2_stream(jax.random.key(0), w, 40,
                                      candidates=10,
                                      block_i=128, block_j=128))
    assert idx.shape == (40,) and len(set(idx.tolist())) == 40
    # tiny candidate pools must not degenerate density to zero
    idx2 = np.asarray(sel_spea2_stream(jax.random.key(0), w, 3,
                                       candidates=3,
                                       block_i=128, block_j=128))
    assert idx2.shape == (3,)


def test_sel_spea2_stream_tie_break_unbiased():
    from deap_tpu.mo.emo import sel_spea2_stream

    # all rows mutually non-dominated (raw == 0 everywhere): candidate
    # truncation must not systematically keep the lowest indices
    t = jnp.linspace(0, 1, 400)
    w = jnp.stack([t, 1.0 - t], 1)
    idx = np.asarray(sel_spea2_stream(jax.random.key(3), w, 20,
                                      candidates=50,
                                      block_i=128, block_j=128))
    assert idx.max() > 100  # stable-sort bias would cap indices at 49


# ----------------------------------------- fused variation-plane kernel ----
#
# ops.kernels.fused_variation (the Pallas apply of the fused variation
# plane) pinned bit-identical to ops.variation.apply_variation — the
# XLA formulation that is itself pinned bit-identical to the unfused
# var_and/var_or composition in tests/test_fused_variation.py. Odd
# shapes are the satellite contract: pop sizes off the block lattice,
# pop=1/2 degenerate tournaments/pairings, zero-probability cx/mut.

from deap_tpu.ops import variation as _variation
from deap_tpu.ops.crossover import cx_one_point, cx_two_point
from deap_tpu.ops.kernels import fused_variation
from deap_tpu.ops.mutation import mut_flip_bit, mut_gaussian


def _flip_plan(indpb=0.1, mate=cx_two_point):
    kind, draw = mut_flip_bit.fused_plan(indpb)
    return _variation.VariationPlan(mate.fused_segment_draw,
                                    mate.__name__, kind, draw,
                                    "mut_flip_bit")


def _kernel_vs_xla(g, plan, cxpb, mutpb, block_i, key, src=None):
    n = g.shape[0] if src is None else src.shape[0]
    masks = _variation.var_and_masks(key, n, g.shape[1], cxpb, mutpb,
                                     plan, g.dtype)
    cx_row, lo, hi, do_mut, mask, arg = masks
    pos = _variation.pair_partner_positions(n)
    partner = pos if src is None else jnp.take(src, pos)
    want = _variation.apply_variation(g, src, partner, cx_row, lo, hi,
                                      do_mut, mask, arg, plan.mut_kind)
    s = jnp.arange(n, dtype=jnp.int32) if src is None else src
    got = fused_variation(g, s, partner, cx_row, lo, hi, do_mut, mask,
                          arg, mut_kind=plan.mut_kind, block_i=block_i,
                          interpret=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("n,block_i", [(70, 64), (65, 64), (127, 32),
                                       (256, 256)])
def test_fused_variation_kernel_off_lattice_pops(n, block_i):
    """Pop sizes that are not a multiple of the block size: the padded
    tail must never leak into the returned rows."""
    g = jax.random.bernoulli(jax.random.key(n), 0.5, (n, 33))
    _kernel_vs_xla(g, _flip_plan(), 0.7, 0.4, block_i,
                   jax.random.key(n + 1))


@pytest.mark.parametrize("n", [1, 2])
def test_fused_variation_kernel_degenerate_pops(n):
    """pop=1 (no pair at all) and pop=2 (one pair): the adjacent-pair
    clamp and the odd-tail no-mate rule, at the smallest sizes."""
    g = jax.random.bernoulli(jax.random.key(n), 0.5, (n, 17))
    _kernel_vs_xla(g, _flip_plan(), 1.0, 1.0, 8, jax.random.key(5))


@pytest.mark.parametrize("cxpb,mutpb", [(0.0, 0.5), (0.5, 0.0),
                                        (0.0, 0.0)])
def test_fused_variation_kernel_zero_probabilities(cxpb, mutpb):
    g = jax.random.bernoulli(jax.random.key(3), 0.5, (48, 21))
    _kernel_vs_xla(g, _flip_plan(), cxpb, mutpb, 16, jax.random.key(6))
    if cxpb == mutpb == 0.0:
        # and the all-zero case is the identity on the population
        plan = _flip_plan()
        masks = _variation.var_and_masks(jax.random.key(6), 48, 21,
                                         0.0, 0.0, plan, g.dtype)
        cx_row, lo, hi, do_mut, mask, arg = masks
        pos = _variation.pair_partner_positions(48)
        out = fused_variation(g, jnp.arange(48, dtype=jnp.int32), pos,
                              cx_row, lo, hi, do_mut, mask, None,
                              mut_kind="flip", block_i=16,
                              interpret=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(g))


def test_fused_variation_kernel_composed_selection():
    """src_idx composes the selection gather into the kernel: parity
    against the XLA apply given the same winners."""
    n = 90
    g = jax.random.bernoulli(jax.random.key(8), 0.5, (n, 40))
    src = jax.random.randint(jax.random.key(9), (n,), 0, n)
    _kernel_vs_xla(g, _flip_plan(mate=cx_one_point), 0.6, 0.3, 32,
                   jax.random.key(10), src=src)


def test_fused_variation_kernel_add_kind_f32():
    n, L = 50, 24
    kind, draw = mut_gaussian.fused_plan(mu=0.0, sigma=0.5, indpb=0.3)
    plan = _variation.VariationPlan(cx_two_point.fused_segment_draw,
                                    "cx_two_point", kind, draw,
                                    "mut_gaussian")
    g = jax.random.uniform(jax.random.key(11), (n, L))
    _kernel_vs_xla(g, plan, 0.5, 0.6, 16, jax.random.key(12))


def test_fused_variation_kernel_rejects_bad_kind():
    g = jnp.zeros((8, 8), jnp.float32)
    z = jnp.zeros(8, jnp.int32)
    with pytest.raises(ValueError, match="mut_kind"):
        fused_variation(g, z, z, z.astype(bool), z, z, z.astype(bool),
                        jnp.zeros((8, 8), bool), mut_kind="nope",
                        interpret=True)
    with pytest.raises(ValueError, match="mut_arg"):
        fused_variation(g, z, z, z.astype(bool), z, z, z.astype(bool),
                        jnp.zeros((8, 8), bool), mut_kind="add",
                        interpret=True)


# ---------------------------------------------------- real-valued kernel ----

def test_real_fused_eval_exact_and_noop():
    from deap_tpu import benchmarks
    from deap_tpu.ops.kernels_real import fused_variation_eval_real

    g = jax.random.uniform(jax.random.key(5), (96, 30),
                           minval=-5.12, maxval=5.12)
    ch, fit = fused_variation_eval_real(
        jax.random.key(6), g, cxpb=0.0, mutpb=0.0, indpb=0.1,
        sigma=0.3, evaluate="rastrigin", prng="input", interpret=True)
    ref = jax.vmap(benchmarks.rastrigin)(g)[:, 0]
    assert np.allclose(ch, g)
    assert np.allclose(fit, ref, rtol=1e-5)


def test_real_fused_blend_pair_sum_invariant():
    from deap_tpu.ops.kernels_real import fused_variation_eval_real

    g = jax.random.uniform(jax.random.key(7), (128, 16))
    ch, _ = fused_variation_eval_real(
        jax.random.key(8), g, cxpb=1.0, mutpb=0.0, indpb=0.0,
        alpha=0.5, evaluate="sphere", prng="input", interpret=True)
    # shared per-gene gammas: c1+c2 == p1+p2 exactly (crossover.py:256-258)
    assert np.allclose(np.asarray(ch[0::2] + ch[1::2]),
                       np.asarray(g[0::2] + g[1::2]), atol=1e-4)
    assert not np.allclose(ch, g)


def test_real_fused_gaussian_moments():
    from deap_tpu.ops.kernels_real import fused_variation_eval_real

    g = jnp.zeros((512, 32))
    ch, _ = fused_variation_eval_real(
        jax.random.key(9), g, cxpb=0.0, mutpb=1.0, indpb=0.3, mu=2.0,
        sigma=0.5, evaluate="sphere", prng="input", interpret=True)
    d = np.asarray(ch)
    frac = (d != 0).mean()
    steps = d[d != 0]
    assert abs(frac - 0.3) < 0.03
    assert abs(steps.mean() - 2.0) < 0.06
    assert abs(steps.std() - 0.5) < 0.06


def test_real_fused_odd_row_and_custom_eval():
    from deap_tpu.ops.kernels_real import fused_variation_eval_real

    g = jax.random.uniform(jax.random.key(10), (95, 8))

    def neg_sum(child, valid_col):
        return -jnp.sum(jnp.where(valid_col, child, 0.0), axis=1,
                        keepdims=True)

    ch, fit = fused_variation_eval_real(
        jax.random.key(11), g, cxpb=1.0, mutpb=0.0, indpb=0.0,
        evaluate=neg_sum, prng="input", interpret=True)
    assert np.allclose(ch[-1], g[-1])  # odd last row never mates
    assert np.allclose(fit, -np.asarray(ch).sum(1), atol=1e-4)
