"""Test configuration: force an 8-virtual-device CPU platform.

This is the TPU-native analog of the reference's "test multi-node without
a cluster" strategy (pickle round-trips, SURVEY.md §4.3): all sharding /
island / multi-host-shaped tests run against
``--xla_force_host_platform_device_count=8`` so CI needs no TPU.

Note: the environment's TPU plugin pins ``jax_platforms`` to
``axon,cpu``, overriding the JAX_PLATFORMS env var — so CPU must be
forced through ``jax.config`` after import, while XLA_FLAGS still must
be set *before* backend initialisation.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _measured_slow_ids():
    """Node ids measured >= 3s on one core (tests/slow_tests.txt) —
    the data-driven part of the slow tier; explicit markers also work."""
    path = os.path.join(os.path.dirname(__file__), "slow_tests.txt")
    try:
        with open(path) as fh:
            return {ln.strip() for ln in fh
                    if ln.strip() and not ln.startswith("#")}
    except OSError:
        return set()


def pytest_collection_modifyitems(config, items):
    """Two-tier suite: everything not marked ``slow`` is ``fast``, so
    both ``-m fast`` and ``-m "not slow"`` select the quick tier
    (target: ~2 minutes on one CPU core; the full suite is dominated by
    XLA compiles and the reference's 100+-generation quality gates).
    Slow = explicit ``@pytest.mark.slow`` plus the measured manifest in
    ``tests/slow_tests.txt``."""
    slow_ids = _measured_slow_ids()
    for item in items:
        if item.nodeid in slow_ids and "slow" not in item.keywords:
            item.add_marker(pytest.mark.slow)
        if "chaos" in item.keywords and "slow" not in item.keywords:
            # chaos (fault-injection) tests ride the slow tier: they
            # re-run whole evolutions per fault plan. `-m chaos`
            # still selects exactly them.
            item.add_marker(pytest.mark.slow)
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.fast)


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """Drop compiled executables between test modules.

    A full-suite run accumulates hundreds of CPU XLA executables in one
    process; past a threshold that once produced segfaults during
    *tracing* of later complex programs (observed in the multiswarm
    change-recovery test, round 1). Root-cause attempt 2026-07-30: a
    complete suite run with clearing disabled (287 tests, jax 0.9.0,
    ``DEAP_TPU_NO_CACHE_CLEAR=1``) passed cleanly, so the crash is not
    currently reproducible — likely fixed upstream or dependent on a
    state pattern the suite no longer produces. The per-module clear is
    kept anyway: it bounds peak process state for a few re-traces'
    cost, and the env toggle preserves the repro path if it returns.
    """
    yield
    if not os.environ.get("DEAP_TPU_NO_CACHE_CLEAR"):
        jax.clear_caches()
